"""Benchmark harness: flagship train-step throughput on the real chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: images/sec/chip for the full BD-BNN training step (forward +
backward + optimizer + kurtosis regularization) on binary ResNet-18 at
224×224 — the workload of BASELINE config 3 ("ResNet-18 BD-BNN,
ImageNet, single-chip, kurtosis reg only").

vs_baseline normalizes against the reference's GPU throughput for the
same step. The reference repo publishes no numbers (SURVEY.md §6), so
the anchor is an estimate pinned here: ~900 images/sec on a modern
training GPU for ReActNet-style binary ResNet-18 with FP32 master
weights (binary nets run at FP speed on GPUs — cuDNN has no 1-bit
path, matching the reference's stock-PyTorch convs). The BASELINE.json
north star asks for ≥1.5× chip-normalized.
"""

from __future__ import annotations

import json
import time

BASELINE_IMAGES_PER_SEC_PER_CHIP = 900.0


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bdbnn_tpu.models import conv_weight_paths, create_model
    from bdbnn_tpu.train import (
        StepConfig,
        TrainState,
        make_optimizer,
        make_train_step,
    )

    batch = 64
    model = create_model("resnet18", "imagenet")
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(batch, 224, 224, 3)),
        jnp.float32,
    )
    y = jnp.asarray(np.random.default_rng(1).integers(0, 1000, size=(batch,)))

    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 224, 224, 3)), train=True
    )
    paths = conv_weight_paths(variables["params"])
    hooked = tuple(paths[1:])
    cfg = StepConfig(
        w_kurtosis=True,
        kurt_paths=hooked,
        kurt_targets=(1.8,) * len(hooked),
        kurtosis_mode="avg",
        w_lambda_kurtosis=1.0,
    )
    tx = make_optimizer(
        variables["params"], dataset="imagenet", lr=1e-3,
        epochs=90, steps_per_epoch=1000,
    )
    state = TrainState.create(variables, tx)
    step = jax.jit(make_train_step(model, tx, cfg), donate_argnums=(0,))

    tk = (jnp.float32(1.0), jnp.float32(1.0))
    gate = jnp.float32(1.0)

    # warmup / compile
    state, metrics = step(state, (x, y), tk, gate)
    jax.block_until_ready(metrics["loss"])

    iters = 20
    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, (x, y), tk, gate)
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0

    images_per_sec = batch * iters / dt
    n_chips = max(jax.device_count(), 1)
    per_chip = images_per_sec / n_chips

    print(
        json.dumps(
            {
                "metric": "train_step_images_per_sec_per_chip",
                "value": round(per_chip, 2),
                "unit": "images/sec/chip",
                "vs_baseline": round(
                    per_chip / BASELINE_IMAGES_PER_SEC_PER_CHIP, 3
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
