"""Benchmark harness: flagship train-step throughput on the real chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Metric: images/sec/chip for the full BD-BNN training step (forward +
backward + optimizer + kurtosis regularization) on binary ResNet-18 at
224x224 in bf16 — the workload of BASELINE config 3 ("ResNet-18 BD-BNN,
ImageNet, single-chip, kurtosis reg only"). Reference anchor for the
loop being benchmarked: ``/root/reference/train.py:441-554``.

Measurement methodology (round 4 — defensibility fixes):

* **Fenced windows.** Async dispatch through remote PJRT tunnels can
  return from ``block_until_ready`` before execution completes, which
  inflated round 3's headline ~13x (95,975 img/s ≈ 1.05 PFLOP/s —
  above the bf16 peak of any TPU through v6e). Each timing window now
  ends with a device-to-host transfer of the final loss (a true fence);
  the headline is the median over several windows.
* **Analytic FLOPs + MFU.** The compiled step's FLOPs come from XLA's
  own ``compiled.cost_analysis()``; MFU is computed against the chip's
  published bf16 peak (table below). ``timing_suspect`` is set when
  MFU exceeds 100% — such a number must not be trusted.
* **Profiler trace.** When ``BDBNN_BENCH_PROFILE_DIR`` is set (or
  ``--profile-dir`` passed), a ``jax.profiler`` trace of 5 steps is
  captured and the median on-device ``jit_train_step`` duration is
  reported as ``device_ms_per_step`` (the tunnel-latency-free number).

Robustness: the measurement runs in a SUBPROCESS with a hard timeout —
a hung or unavailable TPU backend is killed and retried with backoff,
and each heavy attempt is preceded by a cheap reachability probe (the
remote PJRT tunnel flaps for hours; when down, backend init hangs).
CONTRACT NOTE for consumers: on total measurement failure the contract
keys are ``value: 0.0`` + ``vs_baseline: 0.0`` + ``error`` — a consumer
reading only {metric, value, unit, vs_baseline} can never mistake a
dead-tunnel round for a live one. Prior committed on-chip evidence
(profiles/r04/PROFILE_r04.json), when present, rides along under
``prior_value`` / ``prior_vs_baseline`` / ``evidence`` keys with
``fresh_run: false``.

Baseline provenance: the reference repo publishes no throughput numbers
(SURVEY.md §6) and this container has no network egress, so
``vs_baseline`` normalizes against a pinned engineering estimate of the
reference's per-GPU rate for this exact step: ~900 images/sec — binary
ResNet-18 with FP latent weights trains at FP32 ResNet-18 speed on GPUs
(stock cuDNN convs, no 1-bit path; reference ``train.py:9-19``), and
FP32 ResNet-18 ImageNet training sits in the 700–1100 img/s range on
A100/H100-class parts. Override with env BDBNN_BENCH_BASELINE when a
measured anchor exists. The north star (BASELINE.json) is ≥1.5x
chip-normalized.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

from bdbnn_tpu.obs.trace import (  # stdlib-importable (no jax init)
    BF16_PEAK_TFLOPS,
    find_trace_file,
    jit_step_ms,
)

BASELINE_IMAGES_PER_SEC_PER_CHIP = float(
    os.environ.get("BDBNN_BENCH_BASELINE", "900.0")
)
METRIC = "train_step_images_per_sec_per_chip"
UNIT = "images/sec/chip"
# steps traced by _profile_device_ms; consumers dividing aggregate
# trace durations into per-step numbers (profile_r05.py) must use THIS
PROFILE_TRACE_STEPS = 5


def _build_step(dtype: str, batch: int):
    """The flagship jitted train step + inputs (BASELINE config 3)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bdbnn_tpu.models import conv_weight_paths, create_model
    from bdbnn_tpu.train import (
        StepConfig,
        TrainState,
        make_optimizer,
        make_train_step,
    )

    model = create_model("resnet18", "imagenet", dtype=dtype)
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(batch, 224, 224, 3)),
        jnp.float32,
    )
    y = jnp.asarray(np.random.default_rng(1).integers(0, 1000, size=(batch,)))

    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 224, 224, 3)), train=True
    )
    paths = conv_weight_paths(variables["params"])
    hooked = tuple(paths[1:])
    cfg = StepConfig(
        w_kurtosis=True,
        kurt_paths=hooked,
        kurt_targets=(1.8,) * len(hooked),
        kurtosis_mode="avg",
        w_lambda_kurtosis=1.0,
    )
    tx = make_optimizer(
        variables["params"], dataset="imagenet", lr=1e-3,
        epochs=90, steps_per_epoch=1000,
    )
    state = TrainState.create(variables, tx)
    step = jax.jit(make_train_step(model, tx, cfg), donate_argnums=(0,))
    tk = (jnp.float32(1.0), jnp.float32(1.0))
    gate = jnp.float32(1.0)
    return step, state, (x, y), tk, gate


def _log(msg: str) -> None:
    print(f"[bench +{time.perf_counter() - _T0:7.1f}s] {msg}", file=sys.stderr,
          flush=True)


_T0 = time.perf_counter()


def _compile_step(dtype: str, batch: int):
    """AOT-compile the flagship step ONCE (jit dispatch would compile a
    second cache entry; compiles are the slow part over a remote
    tunnel). Returns (compiled, state, args..., flops)."""
    _log(f"building step dtype={dtype}")
    step, state, batch_xy, tk, gate = _build_step(dtype, batch)
    _log("lowering + compiling")
    compiled = step.lower(state, batch_xy, tk, gate).compile()
    _log("compiled")
    try:
        flops = float(compiled.cost_analysis().get("flops", 0.0))
    except Exception:
        flops = 0.0
    _log(f"cost_analysis flops={flops:.3e}")
    return compiled, state, batch_xy, tk, gate, flops


def _measure_compiled(compiled, state, batch_xy, tk, gate, batch: int,
                      iters: int, windows: int = 5):
    """Median fenced-window images/sec for a compiled step.

    Every window of ``iters`` chained steps ends with a device-to-host
    transfer of the loss — the only fence observed to be reliable over
    remote PJRT tunnels (``block_until_ready`` alone returned early and
    inflated round-3 numbers ~13x).
    """
    metrics = None
    for _ in range(3):
        state, metrics = compiled(state, batch_xy, tk, gate)
    loss = float(metrics["loss"])  # fence

    rates = []
    for _ in range(windows):
        t0 = time.perf_counter()
        s, m = state, metrics
        for _ in range(iters):
            s, m = compiled(s, batch_xy, tk, gate)
        loss = float(m["loss"])  # fence: true device-to-host transfer
        dt = time.perf_counter() - t0
        rates.append(iters * batch / dt)
        state = s
    import math

    assert math.isfinite(loss), f"non-finite loss in bench: {loss}"
    rates.sort()
    return rates[len(rates) // 2], state


def _profile_device_ms(compiled, state, batch_xy, tk, gate, batch: int,
                       profile_dir: str):
    """Trace 5 steps of the already-compiled step; return median
    on-device jit_train_step ms (parsed by the shared semantic-trace
    module, obs/trace.py)."""
    import jax

    os.makedirs(profile_dir, exist_ok=True)
    with jax.profiler.trace(profile_dir):
        s, m = state, None
        for _ in range(PROFILE_TRACE_STEPS):
            s, m = compiled(s, batch_xy, tk, gate)
        _ = float(m["loss"])

    trace_path = find_trace_file(profile_dir)
    if trace_path is None:
        return None, None, s
    return jit_step_ms(trace_path, prefix="jit_train_step"), trace_path, s


def worker_main(args) -> None:
    import jax

    # explicit JAX_PLATFORMS must win over a PJRT-plugin sitecustomize's
    # jax.config.update (same guard as the CLI)
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from bdbnn_tpu.nn.kernels import default_impl

    n_chips = max(jax.device_count(), 1)
    dev = jax.devices()[0]
    device_kind = dev.device_kind
    peak_tflops = BF16_PEAK_TFLOPS.get(device_kind)
    print(f"[bench] devices: {jax.devices()}", file=sys.stderr)

    # the shared structured event channel (obs/events.py): bench rounds
    # land in <profile_dir>/events.jsonl with the same envelope fit()
    # uses, so `summarize`-grade tooling can read bench history too.
    # Telemetry must never break a measurement — any writer failure
    # (read-only dir, etc.) downgrades to events=None.
    events = None
    if args.profile_dir:
        try:
            from bdbnn_tpu.obs import EventWriter

            events = EventWriter(args.profile_dir)
        except Exception as e:
            print(f"[bench] event channel disabled: {e}", file=sys.stderr)

    # Staged measurement, emitting a cumulative JSON line after every
    # stage: if the driver's timeout kills us mid-way, the parent still
    # scavenges the last complete line.
    rates = {}
    extras = {
        "batch": args.batch,
        "n_chips": n_chips,
        "platform": dev.platform,
        "device_kind": device_kind,
        "bf16_peak_tflops": peak_tflops,
        "fencing": "device-to-host loss transfer per window, median of windows",
    }

    flops_by_impl = {}

    def emit():
        best = max(rates, key=rates.get)
        out = {
            "metric": METRIC,
            "value": round(rates[best], 2),
            "unit": UNIT,
            "vs_baseline": round(
                rates[best] / BASELINE_IMAGES_PER_SEC_PER_CHIP, 3
            ),
            "dtype": "bfloat16",
            "conv_impl": best,
            "impl_rates": {k: round(v, 2) for k, v in rates.items()},
            **extras,
        }
        # MFU must pair the winning impl's rate with ITS OWN compiled
        # step's FLOPs — impls lower differently
        if peak_tflops and flops_by_impl.get(best):
            per_image = flops_by_impl[best] / args.batch
            achieved = per_image * rates[best]
            out["achieved_tflops"] = round(achieved / 1e12, 2)
            out["mfu"] = round(achieved / (peak_tflops * 1e12), 4)
            out["timing_suspect"] = bool(out["mfu"] > 1.0)
        print(json.dumps(out), flush=True)
        if events is not None:
            events.emit("bench_result", **out)

    with default_impl("dot"):
        compiled, state, batch_xy, tk, gate, flops = _compile_step(
            "bfloat16", args.batch
        )
        rate, state = _measure_compiled(
            compiled, state, batch_xy, tk, gate, args.batch, args.iters
        )
        rates["dot"] = rate / n_chips
        flops_by_impl["dot"] = flops
        extras["flops_per_step"] = flops
        extras["gflops_per_image"] = round(flops / args.batch / 1e9, 3)
    emit()

    if args.profile_dir:
        try:
            dev_ms, trace_path, state = _profile_device_ms(
                compiled, state, batch_xy, tk, gate, args.batch,
                args.profile_dir,
            )
            if dev_ms:
                extras["device_ms_per_step"] = round(dev_ms, 3)
                extras["device_images_per_sec"] = round(
                    args.batch / (dev_ms / 1e3), 2
                )
                if peak_tflops and extras.get("flops_per_step"):
                    extras["device_mfu"] = round(
                        extras["flops_per_step"]
                        / (dev_ms / 1e3)
                        / (peak_tflops * 1e12),
                        4,
                    )
            if trace_path:
                extras["profile_trace"] = trace_path
            emit()
        except Exception as e:
            print(f"[bench] profiling failed: {e}", file=sys.stderr)

    if args.compare:
        with default_impl("dot"):
            c2, s2, bxy2, tk2, g2, _ = _compile_step("float32", args.batch)
            f32, _ = _measure_compiled(
                c2, s2, bxy2, tk2, g2, args.batch, args.iters
            )
        f32 /= n_chips
        extras["f32_images_per_sec_per_chip"] = round(f32, 2)
        extras["bf16_speedup_vs_f32"] = round(rates["dot"] / f32, 3)
        emit()

    # the int8 / pallas impl stages were retired in round 4: xla_int8
    # measured ~14x slower on-chip and pallas never survived Mosaic
    # lowering — see the decision record in nn/kernels/binary_conv.py
    # and KERNELS_r04.json. "dot" is the only implementation.


def _probe_backend(timeout_s: float):
    """Cheap TPU-reachability probe → (ok, failure_detail). Can a fresh
    process enumerate devices and fence one tiny computation within
    ``timeout_s``?

    The attached chip arrives over a remote PJRT tunnel that flaps for
    hours at a time; when it is down, backend init HANGS rather than
    erroring. Probing first costs ~20s when healthy and saves a full
    540s worker timeout per dead attempt."""
    code = (
        "import os, jax, jax.numpy as jnp;"
        # same guard as the CLI/worker: an explicit JAX_PLATFORMS must
        # win over a PJRT-plugin sitecustomize's config update
        "os.environ.get('JAX_PLATFORMS') and "
        "jax.config.update('jax_platforms', os.environ['JAX_PLATFORMS']);"
        "d = jax.devices()[0];"
        # a dead tunnel can also ERROR (not hang), making jax silently
        # fall back to the CPU backend — that must fail the probe,
        # unless the caller explicitly asked for cpu via JAX_PLATFORMS
        "assert d.platform != 'cpu' or "
        "os.environ.get('JAX_PLATFORMS', '').lower().startswith('cpu'), "
        "f'fell back to {d.platform}';"
        "x = jnp.ones((128, 128));"
        "print('PROBE_OK', float(jnp.sum(x)), d.device_kind)"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return False, f"no reachable device within {timeout_s:.0f}s (hang)"
    if "PROBE_OK" in (proc.stdout or ""):
        return True, ""
    return False, (
        f"probe exited rc={proc.returncode}: "
        + (proc.stderr or proc.stdout or "")[-300:].strip()
    )


def _stale_evidence_fallback(err: str):
    """When every fresh attempt failed (dead tunnel), report FAILURE in
    the contract keys (``value``/``vs_baseline`` = 0.0 — a consumer
    reading only the pinned contract must never mistake this for a live
    run; ADVICE r4 medium) and attach the committed on-chip evidence
    (profiles/r04/PROFILE_r04.json) under ``prior_*`` keys. The
    conservative HOST-FENCED median is the prior, not the device-trace
    number."""
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "profiles", "r04", "PROFILE_r04.json",
    )
    try:
        with open(path) as f:
            prof = json.load(f)
        rate = float(prof["host_fenced_median_img_per_sec"])
    except Exception:
        return None
    return {
        "metric": METRIC,
        "value": 0.0,
        "unit": UNIT,
        "vs_baseline": 0.0,
        "dtype": "bfloat16",
        "fresh_run": False,
        "prior_value": rate,
        "prior_vs_baseline": round(
            rate / BASELINE_IMAGES_PER_SEC_PER_CHIP, 3
        ),
        "evidence": path,
        "evidence_captured": prof.get("captured"),
        "device_kind": prof.get("device_kind"),
        "device_ms_per_step": prof.get("device_ms_per_step_median"),
        "device_images_per_sec": prof.get("device_images_per_sec"),
        "device_mfu": prof.get("device_mfu"),
        "host_fenced_mfu": prof.get("host_fenced_mfu"),
        "error": (
            "fresh measurement failed (remote PJRT tunnel unreachable): "
            + err
        ),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--attempts", type=int, default=3)
    ap.add_argument("--timeout", type=float, default=540.0)
    ap.add_argument("--probe-timeout", type=float, default=150.0)
    ap.add_argument(
        "--profile-dir",
        default=os.environ.get("BDBNN_BENCH_PROFILE_DIR", "profiles/bench"),
        help="capture a jax.profiler trace here ('' = skip); the trace "
        "backs the reported device_ms_per_step / device_mfu",
    )
    ap.add_argument("--no-compare", dest="compare", action="store_false",
                    help="skip the f32 comparison run")
    # accepted-and-ignored for compatibility with older drivers: the
    # int8/pallas stages were retired with measurement in round 4
    ap.add_argument("--no-int8", dest="try_int8", action="store_false",
                    help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.worker:
        worker_main(args)
        return

    err_tail = ""
    for attempt in range(args.attempts):
        if args.probe_timeout > 0:
            ok, detail = _probe_backend(args.probe_timeout)
            if not ok:
                err_tail = f"attempt {attempt + 1}: backend probe failed: {detail}"
                print(f"[bench] {err_tail}", file=sys.stderr)
                if attempt < args.attempts - 1:
                    time.sleep(min(120.0, 30.0 * (attempt + 1)))
                continue
        cmd = [
            sys.executable, os.path.abspath(__file__), "--worker",
            "--batch", str(args.batch), "--iters", str(args.iters),
        ]
        if args.profile_dir:
            cmd += ["--profile-dir", args.profile_dir]
        if not args.compare:
            cmd.append("--no-compare")
        try:
            proc = subprocess.run(
                cmd, capture_output=True, text=True, timeout=args.timeout,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
        except subprocess.TimeoutExpired as e:
            # the worker emits a cumulative JSON line per stage — a
            # timeout mid-stage still leaves a usable last line
            partial = e.stdout or b""
            if isinstance(partial, bytes):
                partial = partial.decode(errors="replace")
            for line in reversed(partial.splitlines()):
                line = line.strip()
                if line.startswith("{") and line.endswith("}"):
                    print(line)
                    return
            err_tail = f"attempt {attempt + 1}: timeout after {args.timeout}s"
            print(f"[bench] {err_tail}", file=sys.stderr)
            if attempt < args.attempts - 1:
                time.sleep(min(30.0, 5.0 * (attempt + 1)))
            continue
        for line in reversed(proc.stdout.splitlines()):
            line = line.strip()
            if line.startswith("{") and line.endswith("}"):
                print(line)
                return
        err_tail = (proc.stderr or proc.stdout or "")[-800:]
        print(
            f"[bench] attempt {attempt + 1} failed rc={proc.returncode}",
            file=sys.stderr,
        )
        if attempt < args.attempts - 1:
            time.sleep(min(30.0, 5.0 * (attempt + 1)))

    err = f"all {args.attempts} attempts failed: {err_tail}"
    fallback = _stale_evidence_fallback(err)
    if fallback is not None:
        print(json.dumps(fallback))
        return
    print(
        json.dumps(
            {
                "metric": METRIC,
                "value": 0.0,
                "unit": UNIT,
                "vs_baseline": 0.0,
                "error": err,
            }
        )
    )


if __name__ == "__main__":
    main()
