"""Benchmark harness: flagship train-step throughput on the real chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Metric: images/sec/chip for the full BD-BNN training step (forward +
backward + optimizer + kurtosis regularization) on binary ResNet-18 at
224×224 in bf16 — the workload of BASELINE config 3 ("ResNet-18 BD-BNN,
ImageNet, single-chip, kurtosis reg only"). The f32 rate is reported
alongside so the bf16 speedup is visible.

Robustness: the measurement runs in a SUBPROCESS with a hard timeout —
a hung or unavailable TPU backend (remote PJRT plugins can block in
backend init) is killed and retried with backoff; after the final
attempt a parseable JSON error line is printed instead of a traceback.

Baseline provenance: the reference repo publishes no throughput numbers
(SURVEY.md §6) and this container has no network egress, so
``vs_baseline`` normalizes against a pinned engineering estimate of the
reference's per-GPU rate for this exact step: ~900 images/sec — binary
ResNet-18 with FP latent weights trains at FP32 ResNet-18 speed on
GPUs (stock cuDNN convs, no 1-bit path; reference ``train.py:9-19``),
and FP32 ResNet-18 ImageNet training sits in the 700–1100 img/s range
on A100/H100-class parts. Override with env BDBNN_BENCH_BASELINE when a
measured anchor exists. The north star (BASELINE.json) is ≥1.5×
chip-normalized.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

BASELINE_IMAGES_PER_SEC_PER_CHIP = float(
    os.environ.get("BDBNN_BENCH_BASELINE", "900.0")
)
METRIC = "train_step_images_per_sec_per_chip"
UNIT = "images/sec/chip"


def _measure(dtype: str, batch: int, iters: int) -> float:
    """Images/sec for the jitted flagship train step at ``dtype``."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bdbnn_tpu.models import conv_weight_paths, create_model
    from bdbnn_tpu.train import (
        StepConfig,
        TrainState,
        make_optimizer,
        make_train_step,
    )

    model = create_model("resnet18", "imagenet", dtype=dtype)
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(batch, 224, 224, 3)),
        jnp.float32,
    )
    y = jnp.asarray(np.random.default_rng(1).integers(0, 1000, size=(batch,)))

    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 224, 224, 3)), train=True
    )
    paths = conv_weight_paths(variables["params"])
    hooked = tuple(paths[1:])
    cfg = StepConfig(
        w_kurtosis=True,
        kurt_paths=hooked,
        kurt_targets=(1.8,) * len(hooked),
        kurtosis_mode="avg",
        w_lambda_kurtosis=1.0,
    )
    tx = make_optimizer(
        variables["params"], dataset="imagenet", lr=1e-3,
        epochs=90, steps_per_epoch=1000,
    )
    state = TrainState.create(variables, tx)
    step = jax.jit(make_train_step(model, tx, cfg), donate_argnums=(0,))

    tk = (jnp.float32(1.0), jnp.float32(1.0))
    gate = jnp.float32(1.0)

    # warmup / compile + 2 steady steps
    for _ in range(3):
        state, metrics = step(state, (x, y), tk, gate)
    jax.block_until_ready(metrics["loss"])
    print(f"[bench] {dtype}: compiled, timing {iters} steps", file=sys.stderr)

    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, (x, y), tk, gate)
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0
    assert bool(jnp.isfinite(metrics["loss"])), "non-finite loss in bench"
    return batch * iters / dt


def worker_main(args) -> None:
    import jax

    # explicit JAX_PLATFORMS must win over a PJRT-plugin sitecustomize's
    # jax.config.update (same guard as the CLI)
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from bdbnn_tpu.nn.kernels import default_impl

    n_chips = max(jax.device_count(), 1)
    print(f"[bench] devices: {jax.devices()}", file=sys.stderr)

    # Staged measurement, emitting a cumulative JSON line after every
    # stage: if the driver's timeout kills us mid-way, the parent still
    # scavenges the last complete line. Stage 1 (bf16 + stock XLA conv)
    # is the safe headline; the f32 comparison and the int8 MXU paths
    # (see nn/kernels/binary_conv.py) enrich it — the best successful
    # rate becomes the headline and "conv_impl" records the winner.
    rates = {}
    extras = {"batch": args.batch, "n_chips": n_chips,
              "platform": jax.devices()[0].platform}

    def emit():
        best = max(rates, key=rates.get)
        out = {
            "metric": METRIC,
            "value": round(rates[best], 2),
            "unit": UNIT,
            "vs_baseline": round(
                rates[best] / BASELINE_IMAGES_PER_SEC_PER_CHIP, 3
            ),
            "dtype": "bfloat16",
            "conv_impl": best,
            "impl_rates": {k: round(v, 2) for k, v in rates.items()},
            **extras,
        }
        print(json.dumps(out), flush=True)

    with default_impl("dot"):
        rates["dot"] = _measure("bfloat16", args.batch, args.iters) / n_chips
    emit()

    if args.compare:
        with default_impl("dot"):
            f32 = _measure("float32", args.batch, args.iters) / n_chips
        extras["f32_images_per_sec_per_chip"] = round(f32, 2)
        extras["bf16_speedup_vs_f32"] = round(rates["dot"] / f32, 3)
        emit()

    for impl in ("xla_int8", "pallas") if args.try_int8 else ():
        try:
            with default_impl(impl):
                rates[impl] = (
                    _measure("bfloat16", args.batch, args.iters) / n_chips
                )
            emit()
        except Exception as e:
            print(f"[bench] impl {impl} failed: {e}", file=sys.stderr)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--attempts", type=int, default=2)
    ap.add_argument("--timeout", type=float, default=540.0)
    ap.add_argument("--no-compare", dest="compare", action="store_false",
                    help="skip the f32 comparison run")
    ap.add_argument("--no-int8", dest="try_int8", action="store_false",
                    help="skip the int8 conv implementations")
    args = ap.parse_args()

    if args.worker:
        worker_main(args)
        return

    err_tail = ""
    for attempt in range(args.attempts):
        cmd = [
            sys.executable, os.path.abspath(__file__), "--worker",
            "--batch", str(args.batch), "--iters", str(args.iters),
        ]
        if not args.compare:
            cmd.append("--no-compare")
        if not args.try_int8:
            cmd.append("--no-int8")
        try:
            proc = subprocess.run(
                cmd, capture_output=True, text=True, timeout=args.timeout,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
        except subprocess.TimeoutExpired as e:
            # the worker emits a cumulative JSON line per stage — a
            # timeout mid-stage still leaves a usable last line
            partial = e.stdout or b""
            if isinstance(partial, bytes):
                partial = partial.decode(errors="replace")
            for line in reversed(partial.splitlines()):
                line = line.strip()
                if line.startswith("{") and line.endswith("}"):
                    print(line)
                    return
            err_tail = f"attempt {attempt + 1}: timeout after {args.timeout}s"
            print(f"[bench] {err_tail}", file=sys.stderr)
            time.sleep(min(30.0, 5.0 * (attempt + 1)))
            continue
        for line in reversed(proc.stdout.splitlines()):
            line = line.strip()
            if line.startswith("{") and line.endswith("}"):
                print(line)
                return
        err_tail = (proc.stderr or proc.stdout or "")[-800:]
        print(
            f"[bench] attempt {attempt + 1} failed rc={proc.returncode}",
            file=sys.stderr,
        )
        time.sleep(min(30.0, 5.0 * (attempt + 1)))

    print(
        json.dumps(
            {
                "metric": METRIC,
                "value": 0.0,
                "unit": UNIT,
                "vs_baseline": 0.0,
                "error": f"all {args.attempts} attempts failed: {err_tail}",
            }
        )
    )


if __name__ == "__main__":
    main()
