import sys; sys.path.insert(0, "/root/repo")
import os; os.environ["JAX_PLATFORMS"]="cpu"
import jax; jax.config.update("jax_platforms","cpu")
import tempfile, json, glob, numpy as np
from run_accuracy import make_digits_npz
from bdbnn_tpu.configs.config import RunConfig
from bdbnn_tpu.train.loop import fit
with tempfile.TemporaryDirectory() as tmp:
    make_digits_npz(tmp)
    cfg = RunConfig(data=tmp, dataset="cifar10", arch="resnet18", epochs=3,
                    batch_size=128, lr=0.1, opt_policy="adam-linear",
                    w_kurtosis=True, diffkurt=True, kurtepoch=1,
                    seed=0, print_freq=5, log_path=os.path.join(tmp,"log"))
    res = fit(cfg)
    scal=[json.loads(l) for p in glob.glob(os.path.join(tmp,"log","**","scalars.jsonl"),recursive=True) for l in open(p)]
    kurt=[s["value"] for s in scal if s["tag"]=="Train loss_kurt"]
    print("diffkurt e2e:", res, "kurt per epoch:", [round(k,4) for k in kurt])
    assert all(np.isfinite(k) for k in kurt)
    assert kurt[0] == 0.0  # kurtepoch=1 gates epoch 0 off
    assert kurt[1] > 0.0
    print("DIFFKURT+KURTEPOCH E2E OK")
