#!/bin/bash
# Serialize CPU-bound evidence runs on this 1-core host: when the VGG
# KD run (run_kd.py pid given as $1) exits, launch the 150-epoch EDE
# companion (tests the round-5 "schedule-budget" verdict: EDE anneals t
# over the full epoch budget, so a longer budget stretches the anneal).
cd /root/repo || exit 1
while kill -0 "$1" 2>/dev/null; do sleep 60; done
echo "$(date -u +%FT%TZ) KD run done; launching 150-epoch EDE companion" \
  >> runs_r05/queue.log
python run_accuracy.py --epochs 150 --ede --platform cpu \
  --out ACCURACY_r05_ede150.json \
  > runs_r05/ede150.out 2>&1
echo "$(date -u +%FT%TZ) EDE-150 done rc=$?" >> runs_r05/queue.log
