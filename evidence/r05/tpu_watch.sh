#!/bin/bash
# Round-5 TPU tunnel watcher (re-armed in the continuation session).
# Probes the axon PJRT tunnel; on a live window captures, in order:
#   1. profile_r05.py       -> profiles/r05/PROFILE_r05.json
#   2. remat_ceiling.py     -> profiles/r05/REMAT_CEILING_r05.json
#   3. bench.py             -> runs_r05/bench_fresh.json (one JSON line)
# Each capture gets a generous timeout; a partial window still yields
# whatever completed. Log: runs_r05/tpu_watch.log
cd /root/repo || exit 1
LOG=runs_r05/tpu_watch.log
STAMP() { date -u +%Y-%m-%dT%H:%M:%SZ; }
echo "$(STAMP) watcher (re)armed pid $$" >> "$LOG"

while true; do
  if [ -f runs_r05/capture_done ]; then
    echo "$(STAMP) all captures done; watcher exiting" >> "$LOG"
    exit 0
  fi
  if timeout 150 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "$(STAMP) tunnel UP — starting capture sequence" >> "$LOG"
    if [ ! -f profiles/r05/PROFILE_r05.json ]; then
      echo "$(STAMP) capture 1/3: profile_r05.py" >> "$LOG"
      timeout 2400 python profile_r05.py \
        > runs_r05/profile_r05.out 2>&1
      echo "$(STAMP) profile_r05 exit=$? (json: $(ls profiles/r05/PROFILE_r05.json 2>/dev/null || echo MISSING))" >> "$LOG"
    fi
    if [ -f profiles/r05/PROFILE_r05.json ] && [ ! -f profiles/r05/REMAT_CEILING_r05.json ]; then
      echo "$(STAMP) capture 2/3: remat_ceiling.py" >> "$LOG"
      timeout 3000 python remat_ceiling.py \
        > runs_r05/remat_ceiling.out 2>&1
      echo "$(STAMP) remat_ceiling exit=$? (json: $(ls profiles/r05/REMAT_CEILING_r05.json 2>/dev/null || echo MISSING))" >> "$LOG"
    fi
    if [ -f profiles/r05/PROFILE_r05.json ] && [ ! -f runs_r05/bench_fresh.json ]; then
      echo "$(STAMP) capture 3/3: bench.py" >> "$LOG"
      timeout 2400 python bench.py > runs_r05/bench_fresh.json 2> runs_r05/bench_fresh.err
      rc=$?
      echo "$(STAMP) bench exit=$rc" >> "$LOG"
      # keep only a real fresh run; a dead-tunnel fallback prints value 0.0
      if ! grep -q '"fresh_run": true' runs_r05/bench_fresh.json 2>/dev/null; then
        mv runs_r05/bench_fresh.json runs_r05/bench_attempt_$(date +%s).json 2>/dev/null
      fi
    fi
    if [ -f profiles/r05/PROFILE_r05.json ] && [ -f profiles/r05/REMAT_CEILING_r05.json ] && [ -f runs_r05/bench_fresh.json ]; then
      touch runs_r05/capture_done
      echo "$(STAMP) ALL CAPTURES COMPLETE" >> "$LOG"
      exit 0
    fi
  else
    echo "$(STAMP) tunnel down" >> "$LOG"
  fi
  sleep 300
done
