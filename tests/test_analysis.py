"""Tier-1 gate for the project-native static analyzer
(bdbnn_tpu/analysis/): framework units, the seeded-bad fixture corpus
(per-detector discipline — each fixture fires EXACTLY its own
checker), and the self-run gate: the analyzer must be CLEAN on the
repo itself, with every baseline suppression justified and live.

The self-run gate is also the standing regression pin for the races
this PR fixed in serve/pool.py (unguarded ``restarts`` increment, the
drain-path ``state`` write, the ``_shadow_stats`` reset): those sites
are annotated guarded, so reintroducing any unguarded touch fails
here with a ``file:line:lock-discipline:...`` record.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from bdbnn_tpu.analysis import (
    BASELINE_NAME,
    CHECKER_IDS,
    load_baseline,
    render_report,
    run_check,
)
from bdbnn_tpu.analysis.core import Finding, discover_files
from bdbnn_tpu.analysis.eventschema import check_event_schema, scan_events
from bdbnn_tpu.analysis.jitpure import check_jit_purity
from bdbnn_tpu.analysis.lockcheck import check_lock_discipline
from bdbnn_tpu.analysis.verdictcheck import check_verdict_coherence

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "analysis")


def _write(tmp_path, name, source):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    return str(p)


def _lock(tmp_path, source):
    path = _write(tmp_path, "mod.py", source)
    return check_lock_discipline(str(tmp_path), [path])


class TestFinding:
    def test_record_format_and_order(self):
        f = Finding("a/b.py", 7, "lock-discipline", "boom")
        assert f.record == "a/b.py:7:lock-discipline:boom"
        fs = sorted([
            Finding("b.py", 1, "x", "m"),
            Finding("a.py", 9, "x", "m"),
            Finding("a.py", 2, "x", "m"),
        ])
        assert [(f.file, f.line) for f in fs] == [
            ("a.py", 2), ("a.py", 9), ("b.py", 1),
        ]


class TestLockChecker:
    def test_write_outside_lock_fires(self, tmp_path):
        findings = _lock(tmp_path, """
            import threading
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0  # guarded-by: _lock
                def bad(self):
                    self.n = 5
        """)
        assert len(findings) == 1
        assert "self.n" in findings[0].message
        assert findings[0].checker == "lock-discipline"

    def test_write_under_lock_clean(self, tmp_path):
        assert _lock(tmp_path, """
            import threading
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0  # guarded-by: _lock
                def good(self):
                    with self._lock:
                        self.n += 1
        """) == []

    def test_condition_aliases_its_lock(self, tmp_path):
        assert _lock(tmp_path, """
            import threading
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._cv = threading.Condition(self._lock)
                    self.q = []  # guarded-by: _lock
                def good(self):
                    with self._cv:
                        self.q.append(1)
        """) == []

    def test_container_mutation_fires(self, tmp_path):
        findings = _lock(tmp_path, """
            import threading
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.q = []  # guarded-by: _lock
                def bad(self):
                    self.q.append(1)
        """)
        assert len(findings) == 1
        assert "append() mutation" in findings[0].message

    def test_plain_read_not_flagged(self, tmp_path):
        assert _lock(tmp_path, """
            import threading
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.state = "ready"  # guarded-by: _lock
                def advisory(self):
                    return self.state == "ready"
        """) == []

    def test_requires_lock_helper_escape(self, tmp_path):
        findings = _lock(tmp_path, """
            import threading
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.q = []  # guarded-by: _lock
                def _pop(self):  # requires-lock: _lock
                    return self.q.pop()
                def good(self):
                    with self._lock:
                        return self._pop()
                def bad(self):
                    return self._pop()
        """)
        assert len(findings) == 1
        assert "_pop()" in findings[0].message
        assert "requires" in findings[0].message

    def test_requires_lock_only_file_still_analyzed(self, tmp_path):
        # a file whose only annotation is `# requires-lock:` (no
        # guarded-by anywhere) must not skip the fast path — the
        # helper-escape class would otherwise pass unseen
        findings = _lock(tmp_path, """
            import threading
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.items = []
                def _evict(self):  # requires-lock: _lock
                    return self.items.pop()
                def bad(self):
                    return self._evict()
        """)
        assert len(findings) == 1
        assert "_evict()" in findings[0].message

    def test_cross_object_access_checked(self, tmp_path):
        findings = _lock(tmp_path, """
            import threading
            class Replica:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.restarts = 0  # guarded-by: _lock
            class Pool:
                def __init__(self):
                    self.replicas = []
                def good(self, r):
                    with r._lock:
                        r.restarts += 1
                def bad(self, r):
                    r.restarts += 1
        """)
        assert len(findings) == 1
        assert "r.restarts" in findings[0].message

    def test_nested_function_gets_fresh_context(self, tmp_path):
        # a closure defined under `with` runs LATER, without the lock
        findings = _lock(tmp_path, """
            import threading
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0  # guarded-by: _lock
                def bad(self):
                    with self._lock:
                        def cb():
                            self.n += 1
                        return cb
        """)
        assert len(findings) == 1

    def test_init_exempt(self, tmp_path):
        assert _lock(tmp_path, """
            import threading
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0  # guarded-by: _lock
                    self.n += 1
        """) == []

    def test_subscripted_container_mutation_fires(self, tmp_path):
        # self._qs[p].append(x) mutates the guarded container through
        # an element subscript — the MicroBatcher/RequestTracer shape
        findings = _lock(tmp_path, """
            import threading
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._qs = [[], []]  # guarded-by: _lock
                def good(self, p, x):
                    with self._lock:
                        self._qs[p].append(x)
                def bad(self, p, x):
                    self._qs[p].append(x)
        """)
        assert len(findings) == 1
        assert "append() mutation" in findings[0].message

    def test_nested_subscript_mutation_fires(self, tmp_path):
        # self._counts[t]["k"] += 1 — the per-cohort/per-tenant
        # counter shape (pool._cohort_counts, admission._counts)
        findings = _lock(tmp_path, """
            import threading
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._counts = {}  # guarded-by: _lock
                def good(self, t):
                    with self._lock:
                        self._counts[t]["shed"] += 1
                def bad_augassign(self, t):
                    self._counts[t]["shed"] += 1
                def bad_append(self, t, x):
                    self._counts[t]["events"].append(x)
        """)
        assert len(findings) == 2
        assert all("self._counts" in f.message for f in findings)

    def test_free_function_heap_mutation_fires(self, tmp_path):
        findings = _lock(tmp_path, """
            import heapq, threading
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._tail = {}  # guarded-by: _lock
                def bad(self, p, item):
                    heapq.heappush(self._tail[p], item)
        """)
        assert len(findings) == 1
        assert "heappush() mutation" in findings[0].message

    def test_docstring_quoted_annotation_registers_nothing(self, tmp_path):
        # design.md §15 teaches the comment forms; quoting them in a
        # docstring or string literal must not create guards
        assert _lock(tmp_path, '''
            import threading
            class C:
                """Document the form: ``# guarded-by: _lock: foo``."""
                def __init__(self):
                    self._lock = threading.Lock()
                    self.foo = 0
                    self.spec = "# guarded-by: _lock: foo"
                def fine(self):
                    self.foo = 5
        ''') == []

    def test_unbound_annotation_is_a_finding(self, tmp_path):
        # a trailing guarded-by on a line with no self.<attr> (e.g. a
        # multi-line assignment's closing paren) must not silently
        # register nothing
        findings = _lock(tmp_path, """
            import threading
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.count = (
                        0
                    )  # guarded-by: _lock
                def racy(self):
                    self.count += 1
        """)
        assert len(findings) == 1
        assert "binds to nothing" in findings[0].message

    def test_requires_lock_off_signature_is_a_finding(self, tmp_path):
        # mid-body (after the first statement) or module level: the
        # annotation can bind to no def and must be flagged, not
        # silently dropped
        findings = _lock(tmp_path, """
            import threading
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                def helper(self):
                    x = 1
                    # requires-lock: _lock
                    return x
        """)
        assert len(findings) == 1
        assert "binds to nothing" in findings[0].message

    def test_same_method_name_two_locks_accepts_either(self, tmp_path):
        # two classes share a helper name with different locks; a call
        # holding the CORRECT lock must not be flagged
        findings = _lock(tmp_path, """
            import threading
            class A:
                def __init__(self):
                    self._lock_a = threading.Lock()
                    self.x = 0  # guarded-by: _lock_a
                def _reset(self):  # requires-lock: _lock_a
                    self.x = 0
            class B:
                def __init__(self):
                    self._lock_b = threading.Lock()
                    self.y = 0  # guarded-by: _lock_b
                def _reset(self):  # requires-lock: _lock_b
                    self.y = 0
            class Driver:
                def __init__(self):
                    pass
                def fine(self, b):
                    with b._lock_b:
                        b._reset()
                def bad(self, b):
                    b._reset()
        """)
        assert len(findings) == 1
        assert findings[0].message.startswith("call to b._reset()")

    def test_bulk_annotation_form(self, tmp_path):
        findings = _lock(tmp_path, """
            import threading
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    # guarded-by: _lock: a, b
                    self.a = 0
                    self.b = 0
                def bad(self):
                    self.b = 2
        """)
        assert len(findings) == 1
        assert "self.b" in findings[0].message


class TestJitPurity:
    def _run(self, tmp_path, source):
        path = _write(tmp_path, "mod.py", source)
        return check_jit_purity(str(tmp_path), [path])

    def test_direct_root_banned_call(self, tmp_path):
        findings = self._run(tmp_path, """
            import jax, time
            @jax.jit
            def step(x):
                time.sleep(1)
                return x
        """)
        assert len(findings) == 1
        assert "time.sleep()" in findings[0].message

    def test_closure_through_helper(self, tmp_path):
        findings = self._run(tmp_path, """
            import jax, random
            def helper(x):
                return x * random.random()
            def step(x):
                return helper(x)
            fast = jax.jit(step)
        """)
        assert len(findings) == 1
        assert "random.random()" in findings[0].message

    def test_factory_argument_root(self, tmp_path):
        findings = self._run(tmp_path, """
            import jax
            import numpy as np
            def make_step(cfg):
                def step(x):
                    return x + np.random.rand()
                return step
            fast = jax.jit(make_step(None))
        """)
        assert len(findings) == 1
        assert "np.random.rand()" in findings[0].message

    def test_flax_module_call_is_root(self, tmp_path):
        findings = self._run(tmp_path, """
            import flax.linen as nn
            class Net(nn.Module):
                def __call__(self, x):
                    print("tracing", x)
                    return x
        """)
        assert len(findings) == 1
        assert "print()" in findings[0].message

    def test_higher_order_wrapper_param(self, tmp_path):
        findings = self._run(tmp_path, """
            import jax
            def wrap(step_fn):
                return jax.jit(step_fn, donate_argnums=(0,))
            def my_step(s):
                return s.params.mean().item()
            fast = wrap(my_step)
        """)
        assert len(findings) == 1
        assert ".item()" in findings[0].message

    def test_host_code_not_flagged(self, tmp_path):
        assert self._run(tmp_path, """
            import jax, time
            @jax.jit
            def step(x):
                return x + 1
            def bench(x):
                t0 = time.perf_counter()
                step(x)
                return time.perf_counter() - t0
        """) == []


class TestEventSchemaChecker:
    def test_unregistered_kind_fires(self, tmp_path):
        path = _write(tmp_path, "ev.py", '''
            """Registry. ``good`` is documented."""
            KNOWN_KINDS = frozenset({"good"})
            class W:
                def emit(self, kind, **f): pass
            def run(w):
                w.emit("good")
                w.emit("bad_kind")
        ''')
        findings = check_event_schema(str(tmp_path), [path])
        assert len(findings) == 1
        assert "bad_kind" in findings[0].message

    def test_undocumented_and_dead_kinds_fire(self, tmp_path):
        path = _write(tmp_path, "ev.py", '''
            """Registry. ``good`` is documented."""
            KNOWN_KINDS = frozenset({"good", "ghost"})
            def run(w):
                w.emit("good")
        ''')
        findings = check_event_schema(str(tmp_path), [path])
        msgs = "\n".join(f.message for f in findings)
        assert "not documented" in msgs and "no emit call site" in msgs
        assert all("ghost" in f.message for f in findings)


class TestVerdictChecker:
    def test_produced_but_unjudged_fires(self, tmp_path):
        path = _write(tmp_path, "cmp.py", """
            METRIC_SPECS = (("serve_p99_ms", "lower", "rel"),)
            def _serve_metrics(verdict):
                out = {}
                out["serve_p99_ms"] = verdict.get("p99_ms")
                out["serve_new_thing"] = verdict.get("new_thing")
                return out
        """)
        findings = check_verdict_coherence(str(tmp_path), [path])
        assert len(findings) == 1
        assert "serve_new_thing" in findings[0].message
        assert "never judges" in findings[0].message


class TestBaseline:
    def test_justified_entry_suppresses(self, tmp_path):
        mod = _write(tmp_path, "mod.py", """
            import threading
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0  # guarded-by: _lock
                def bad(self):
                    self.n = 5
        """)
        rec = check_lock_discipline(str(tmp_path), [mod])[0].record
        base = tmp_path / BASELINE_NAME
        base.write_text(f"# why: deliberate for the test\n{rec}\n")
        rep = run_check(str(tmp_path), files=[mod])
        assert rep["verdict"] == "clean"
        assert rep["counts"]["suppressed"] == 1

    def test_unjustified_entry_is_a_finding(self, tmp_path):
        mod = _write(tmp_path, "mod.py", """
            import threading
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0  # guarded-by: _lock
                def bad(self):
                    self.n = 5
        """)
        rec = check_lock_discipline(str(tmp_path), [mod])[0].record
        (tmp_path / BASELINE_NAME).write_text(f"{rec}\n")
        rep = run_check(str(tmp_path), files=[mod])
        assert rep["verdict"] == "findings"
        msgs = [f["message"] for f in rep["findings"]]
        assert any("justification" in m for m in msgs)
        # the suppression itself still applies; only the hygiene fails
        assert rep["counts"]["suppressed"] == 1

    def test_stale_entry_is_a_finding(self, tmp_path):
        (tmp_path / BASELINE_NAME).write_text(
            "# why: excuse for nothing\n"
            "gone.py:1:lock-discipline:ancient history\n"
        )
        rep = run_check(str(tmp_path), files=[])
        assert rep["verdict"] == "findings"
        assert any(
            "stale suppression" in f["message"] for f in rep["findings"]
        )

    def test_line_number_is_advisory_for_matching(self, tmp_path):
        # an edit above the suppressed site shifts its line; the
        # suppression must keep matching on (file, checker, message)
        mod = _write(tmp_path, "mod.py", """
            import threading
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0  # guarded-by: _lock
                def bad(self):
                    self.n = 5
        """)
        f = check_lock_discipline(str(tmp_path), [mod])[0]
        shifted = f"{f.file}:{f.line + 40}:{f.checker}:{f.message}"
        (tmp_path / BASELINE_NAME).write_text(
            f"# why: line drifted, identity did not\n{shifted}\n"
        )
        rep = run_check(str(tmp_path), files=[mod])
        assert rep["verdict"] == "clean"
        assert rep["counts"]["suppressed"] == 1

    def test_entry_consumes_at_most_one_finding(self, tmp_path):
        # a second, NEW site producing the same message must stay open
        # — the baseline excuses one understood occurrence, never a
        # class of them
        mod = _write(tmp_path, "mod.py", """
            import threading
            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0  # guarded-by: _lock
                def old_known(self):
                    self.n = 5
                def brand_new(self):
                    self.n = 6
        """)
        findings = check_lock_discipline(str(tmp_path), [mod])
        assert len(findings) == 2
        assert findings[0].message == findings[1].message
        (tmp_path / BASELINE_NAME).write_text(
            f"# why: the old site is understood\n{findings[0].record}\n"
        )
        rep = run_check(str(tmp_path), files=[mod])
        assert rep["verdict"] == "findings"
        assert rep["counts"]["suppressed"] == 1
        # the entry consumed the CLOSEST finding; the new site is open
        open_lines = [
            f["line"] for f in rep["findings"]
            if f["checker"] == "lock-discipline"
        ]
        assert open_lines == [findings[1].line]

    def test_duplicate_modulo_line_is_flagged(self, tmp_path):
        _entries, problems = load_baseline(
            _write(
                tmp_path, BASELINE_NAME,
                """
                # why: once
                a.py:1:lock-discipline:m
                # why: same suppression, different advisory line
                a.py:9:lock-discipline:m
                """,
            )
        )
        assert any("duplicate" in p.message for p in problems)

    def test_baseline_checker_id_entry_not_suppressible(self, tmp_path):
        # hygiene findings bypass the suppression set by design; an
        # entry naming the `baseline` checker is inert and flagged
        (tmp_path / BASELINE_NAME).write_text(
            "# why: trying to silence a hygiene finding\n"
            "analysis-baseline.txt:5:baseline:stale suppression (x)\n"
        )
        rep = run_check(str(tmp_path), files=[])
        assert rep["verdict"] == "findings"
        assert any(
            "cannot be suppressed" in f["message"]
            for f in rep["findings"]
        )

    def test_unknown_checker_id_entry_is_a_finding(self, tmp_path):
        # a typo'd checker id can never match a finding and must not
        # become a permanently inert suppression
        (tmp_path / BASELINE_NAME).write_text(
            "# why: typo in the checker id\n"
            "pool.py:181:lock-dicipline:write of guarded attribute\n"
        )
        rep = run_check(str(tmp_path), files=[])
        assert rep["verdict"] == "findings"
        assert any(
            "unknown checker id" in f["message"]
            for f in rep["findings"]
        )

    def test_numeric_line_order_is_sorted(self, tmp_path):
        # records pasted from the analyzer's own output order (file,
        # NUMERIC line) must pass the sortedness check: 181 < 1283
        # numerically though not lexicographically
        _entries, problems = load_baseline(
            _write(
                tmp_path, BASELINE_NAME,
                """
                # why: first
                pool.py:181:lock-discipline:write of a
                # why: second
                pool.py:1283:lock-discipline:write of b
                """,
            )
        )
        assert problems == []

    def test_unsorted_and_duplicate_fire(self, tmp_path):
        entries, problems = load_baseline(
            _write(
                tmp_path, BASELINE_NAME,
                """
                # why: b first
                b.py:1:x:m
                # why: a second (unsorted)
                a.py:1:x:m
                # why: a again (duplicate)
                a.py:1:x:m
                """,
            )
        )
        assert len(entries) == 3
        msgs = [p.message for p in problems]
        assert any("not sorted" in m for m in msgs)
        assert any("duplicate" in m for m in msgs)


class TestFixtureCorpus:
    """Per-detector discipline (the tests/test_health.py pattern):
    each seeded-bad snippet fires EXACTLY its own checker, exactly
    once, under the full checker battery."""

    CASES = [
        ("bad_lock_discipline.py", "lock-discipline"),
        ("bad_jit_purity.py", "jit-purity"),
        ("bad_event_schema.py", "event-schema"),
        ("bad_verdict_coherence.py", "verdict-coherence"),
    ]

    @pytest.mark.parametrize("name,expected", CASES)
    def test_fixture_fires_exactly_its_checker(self, name, expected):
        rep = run_check(
            FIXTURES,
            files=[os.path.join(FIXTURES, name)],
            baseline_path=os.path.join(FIXTURES, "no-baseline"),
        )
        fired = sorted({f["checker"] for f in rep["findings"]})
        assert fired == [expected], rep["findings"]
        assert len(rep["findings"]) == 1

    def test_corpus_covers_every_checker(self):
        assert sorted(c for _, c in self.CASES) == sorted(CHECKER_IDS)


class TestSelfRun:
    """THE gate: the analyzer is clean on the repo at head. Any
    unguarded touch of an annotated attribute, impure jitted call,
    unregistered event kind or verdict-key drift lands here as a
    file:line:checker:message record."""

    def test_repo_is_clean(self):
        rep = run_check(REPO)
        assert rep["verdict"] == "clean", "\n".join(
            f["record"] for f in rep["findings"]
        )

    def test_baseline_entries_all_justified_and_live(self):
        entries, problems = load_baseline(
            os.path.join(REPO, BASELINE_NAME)
        )
        assert problems == []
        assert all(e["justified"] for e in entries)

    def test_scan_set_nontrivial(self):
        files = discover_files(REPO)
        assert len(files) > 50
        _findings, found = scan_events(REPO, files)
        assert "analysis" in found  # the check CLI's own emit site

    def test_jit_purity_actually_traverses(self):
        """Vacuity floor: a refactor that silently empties the jit
        root set (renamed factories, moved domain files) must fail
        here, not pass as zero findings."""
        from bdbnn_tpu.analysis.jitpure import analyze_jit_purity

        _f, roots, reachable = analyze_jit_purity(
            REPO, discover_files(REPO)
        )
        # the engine AOT root, the step factories, the flax forwards
        assert "_apply" in roots
        assert "make_train_step" in roots
        assert "__call__" in roots
        assert len(reachable) >= 20

    def test_verdict_coherence_actually_sees_compare(self):
        """Vacuity floor: _serve_metrics renamed or METRIC_SPECS made
        non-literal would silently skip obs/compare.py — pin that the
        checker's extraction still resolves both."""
        import ast as _ast

        from bdbnn_tpu.analysis.verdictcheck import (
            FLATTENER,
            SPECS_NAME,
            _module_literal,
            _produced_keys,
        )

        tree = _ast.parse(
            open(os.path.join(REPO, "bdbnn_tpu/obs/compare.py")).read()
        )
        fn = next(
            n for n in tree.body
            if isinstance(n, _ast.FunctionDef) and n.name == FLATTENER
        )
        specs = _module_literal(tree, SPECS_NAME)
        assert isinstance(specs, tuple) and len(specs) >= 10
        produced, table_fields = _produced_keys(fn, tree)
        assert len({k for k in produced if k.startswith("serve_")}) >= 15
        assert {"p99_ms", "throughput_rps", "shed_rate"} <= table_fields

    def test_syntax_error_reported_even_unannotated(self, tmp_path):
        """An unparseable file with NO annotations must still surface
        (lock-discipline owns this; the other checkers skip
        SyntaxError citing it)."""
        bad = tmp_path / "broken.py"
        bad.write_text("def f(:\n")
        rep = run_check(str(tmp_path), files=[str(bad)])
        assert rep["verdict"] == "findings"
        assert any(
            "unparseable" in f["message"] for f in rep["findings"]
        )

    def test_report_renders_and_is_deterministic(self):
        rep1 = run_check(REPO)
        rep2 = run_check(REPO)
        assert rep1 == rep2
        text = render_report(rep1)
        assert "Static analysis" in text and "CLEAN" in text
        assert json.loads(
            json.dumps(rep1), parse_constant=pytest.fail
        ) == rep1


class TestRegressionPins:
    """The three pool.py true positives the checkers surfaced, pinned
    individually: serve/pool.py must stay lock-clean (restarts
    increment, drain-path state write, _shadow_stats reset) and the
    annotated batching/rtrace/canary/admission classes with it."""

    @pytest.mark.parametrize("rel", [
        "bdbnn_tpu/serve/pool.py",
        "bdbnn_tpu/serve/batching.py",
        "bdbnn_tpu/serve/canary.py",
        "bdbnn_tpu/serve/admission.py",
        "bdbnn_tpu/obs/rtrace.py",
    ])
    def test_file_lock_clean_modulo_baseline(self, rel):
        findings = check_lock_discipline(
            REPO, [os.path.join(REPO, rel)]
        )
        entries, _ = load_baseline(os.path.join(REPO, BASELINE_NAME))
        # advisory-line matching, same as run_check: (file, checker,
        # message) — an exact-record filter here would reintroduce the
        # unrelated-line-churn red gate the baseline design prevents
        suppressed = set()
        for e in entries:
            parts = e["record"].split(":", 3)
            if len(parts) == 4:
                suppressed.add((parts[0], parts[2], parts[3]))
        open_findings = [
            f for f in findings if f.match_key not in suppressed
        ]
        assert open_findings == []

    def test_pool_annotations_present(self):
        # the fixes are only pinned while the attributes stay declared
        src = open(os.path.join(REPO, "bdbnn_tpu/serve/pool.py")).read()
        for attr in ("restarts", "_shadow_stats", "state"):
            assert attr in src
        assert src.count("guarded-by:") >= 10


class TestCheckerSelection:
    def test_unknown_checker_rejected(self):
        with pytest.raises(ValueError):
            run_check(REPO, checkers=["nope"])

    def test_checker_ids_derived_from_registry(self):
        from bdbnn_tpu.analysis.core import _checkers

        assert tuple(_checkers()) == CHECKER_IDS

    def test_single_checker_runs(self):
        rep = run_check(REPO, checkers=["event-schema"])
        assert rep["checkers"] == ["event-schema"]
        assert rep["verdict"] == "clean"
