"""Teacher-checkpoint ingestion tests.

Round-1 advice found the float-twin forward was NOT torchvision
BasicBlock semantics, so ingested teachers computed wrong logits while
key/shape checks passed. These tests pin FORWARD parity against a torch
oracle implementing exact torchvision BasicBlock semantics (the
reference builds teachers from torchvision models, ``train.py:253-258``),
plus the strict-overlay guarantees (shape mismatch and unconsumed /
missing keys raise — torch ``load_state_dict`` is strict by default).
"""

import numpy as np
import pytest
import torch
import torch.nn as tnn

import jax
import jax.numpy as jnp

from bdbnn_tpu.models.resnet import BiResNet
from bdbnn_tpu.models.torch_import import convert_torch_state_dict
from bdbnn_tpu.train.loop import _overlay


class TorchBasicBlock(tnn.Module):
    """torchvision.models.resnet.BasicBlock, verbatim semantics."""

    def __init__(self, cin, cout, stride=1):
        super().__init__()
        self.conv1 = tnn.Conv2d(cin, cout, 3, stride, 1, bias=False)
        self.bn1 = tnn.BatchNorm2d(cout)
        self.relu = tnn.ReLU(inplace=True)
        self.conv2 = tnn.Conv2d(cout, cout, 3, 1, 1, bias=False)
        self.bn2 = tnn.BatchNorm2d(cout)
        self.downsample = None
        if stride != 1 or cin != cout:
            self.downsample = tnn.Sequential(
                tnn.Conv2d(cin, cout, 1, stride, bias=False),
                tnn.BatchNorm2d(cout),
            )

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class TorchBottleneck(tnn.Module):
    """torchvision.models.resnet.Bottleneck, verbatim semantics
    (expansion 4)."""

    def __init__(self, cin, planes, stride=1):
        super().__init__()
        self.conv1 = tnn.Conv2d(cin, planes, 1, 1, 0, bias=False)
        self.bn1 = tnn.BatchNorm2d(planes)
        self.conv2 = tnn.Conv2d(planes, planes, 3, stride, 1, bias=False)
        self.bn2 = tnn.BatchNorm2d(planes)
        self.conv3 = tnn.Conv2d(planes, 4 * planes, 1, 1, 0, bias=False)
        self.bn3 = tnn.BatchNorm2d(4 * planes)
        self.relu = tnn.ReLU(inplace=True)
        self.downsample = None
        if stride != 1 or cin != 4 * planes:
            self.downsample = tnn.Sequential(
                tnn.Conv2d(cin, 4 * planes, 1, stride, bias=False),
                tnn.BatchNorm2d(4 * planes),
            )

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class TorchMiniBottleneckNet(tnn.Module):
    """CIFAR-stem bottleneck ResNet matching BiResNet(stage_sizes=(1, 1),
    width=8, stem='cifar', variant='float', block='bottleneck') with
    torchvision parameter naming."""

    def __init__(self, width=8, num_classes=4):
        super().__init__()
        self.conv1 = tnn.Conv2d(3, width, 3, 1, 1, bias=False)
        self.bn1 = tnn.BatchNorm2d(width)
        self.relu = tnn.ReLU(inplace=True)
        self.layer1 = tnn.Sequential(TorchBottleneck(width, width, 1))
        self.layer2 = tnn.Sequential(TorchBottleneck(4 * width, 2 * width, 2))
        self.fc = tnn.Linear(8 * width, num_classes)

    def forward(self, x):
        x = self.relu(self.bn1(self.conv1(x)))
        x = self.layer1(x)
        x = self.layer2(x)
        x = x.mean(dim=(2, 3))
        return self.fc(x)


class TorchMiniResNet(tnn.Module):
    """CIFAR-stem BasicBlock ResNet matching
    BiResNet(stage_sizes=(1, 1), width=8, stem='cifar', variant='float')
    with torchvision parameter naming."""

    def __init__(self, width=8, num_classes=4):
        super().__init__()
        self.conv1 = tnn.Conv2d(3, width, 3, 1, 1, bias=False)
        self.bn1 = tnn.BatchNorm2d(width)
        self.relu = tnn.ReLU(inplace=True)
        self.layer1 = tnn.Sequential(TorchBasicBlock(width, width, 1))
        self.layer2 = tnn.Sequential(TorchBasicBlock(width, 2 * width, 2))
        self.fc = tnn.Linear(2 * width, num_classes)

    def forward(self, x):
        x = self.relu(self.bn1(self.conv1(x)))
        x = self.layer1(x)
        x = self.layer2(x)
        x = x.mean(dim=(2, 3))
        return self.fc(x)


def _randomized_oracle(seed=0):
    torch.manual_seed(seed)
    net = TorchMiniResNet()
    # randomize BN affine + running stats so parity is non-trivial
    with torch.no_grad():
        for m in net.modules():
            if isinstance(m, tnn.BatchNorm2d):
                m.weight.uniform_(0.5, 1.5)
                m.bias.uniform_(-0.3, 0.3)
                m.running_mean.uniform_(-0.2, 0.2)
                m.running_var.uniform_(0.5, 1.5)
    net.eval()
    return net


def _float_twin():
    return BiResNet(
        stage_sizes=(1, 1), num_classes=4, width=8,
        stem="cifar", variant="float", act="identity",
    )


class TestFloatTeacherParity:
    def test_forward_matches_torch_oracle(self):
        net = _randomized_oracle()
        # translate layerN.M keys: mini-net uses layer1/layer2 Sequentials
        sd = {k: v for k, v in net.state_dict().items()}
        converted = convert_torch_state_dict(sd)

        model = _float_twin()
        template = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3)), train=False
        )
        variables = {
            "params": _overlay(
                template["params"], converted["params"],
                scope="t", allow_missing=False,
            ),
            "batch_stats": _overlay(
                template["batch_stats"], converted["batch_stats"],
                scope="t", allow_missing=False,
            ),
        }

        x = np.random.default_rng(1).normal(size=(4, 16, 16, 3)).astype(
            np.float32
        )
        with torch.no_grad():
            ref = net(torch.tensor(x.transpose(0, 3, 1, 2))).numpy()
        out = np.asarray(model.apply(variables, jnp.asarray(x), train=False))
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=1e-4)

    def test_bottleneck_forward_matches_torch_oracle(self):
        """Bottleneck-family teachers (torchvision resnet50/101; the
        reference names any torchvision ctor, train.py:44-48) ingest and
        compute the same logits."""
        torch.manual_seed(7)
        net = TorchMiniBottleneckNet()
        with torch.no_grad():
            for m in net.modules():
                if isinstance(m, tnn.BatchNorm2d):
                    m.weight.uniform_(0.5, 1.5)
                    m.bias.uniform_(-0.3, 0.3)
                    m.running_mean.uniform_(-0.2, 0.2)
                    m.running_var.uniform_(0.5, 1.5)
        net.eval()
        converted = convert_torch_state_dict(dict(net.state_dict()))

        model = BiResNet(
            stage_sizes=(1, 1), num_classes=4, width=8,
            stem="cifar", variant="float", act="identity",
            block="bottleneck",
        )
        template = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3)), train=False
        )
        variables = {
            "params": _overlay(
                template["params"], converted["params"],
                scope="t", allow_missing=False,
            ),
            "batch_stats": _overlay(
                template["batch_stats"], converted["batch_stats"],
                scope="t", allow_missing=False,
            ),
        }

        x = np.random.default_rng(5).normal(size=(4, 16, 16, 3)).astype(
            np.float32
        )
        with torch.no_grad():
            ref = net(torch.tensor(x.transpose(0, 3, 1, 2))).numpy()
        out = np.asarray(model.apply(variables, jnp.asarray(x), train=False))
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=1e-4)

    def test_dataparallel_module_prefix(self):
        """``module.``-prefixed keys (DataParallel teachers, reference
        ``train.py:258, 269``) convert identically."""
        net = _randomized_oracle(seed=3)
        sd = {f"module.{k}": v for k, v in net.state_dict().items()}
        converted = convert_torch_state_dict(sd)
        assert "conv1" in converted["params"]
        assert "layer2_0" in converted["params"]


class TestOverlayStrictness:
    def _template(self):
        model = _float_twin()
        return model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3)), train=False
        )

    def test_shape_mismatch_raises(self):
        tmpl = self._template()["params"]
        bad = {"conv1": {"weight": np.zeros((3, 3, 3, 99), np.float32)}}
        with pytest.raises(ValueError, match="shape mismatch"):
            _overlay(tmpl, bad, scope="t", allow_missing=True)

    def test_unconsumed_keys_raise(self):
        tmpl = self._template()["params"]
        bad = {"nonexistent_layer": {"weight": np.zeros((1,), np.float32)}}
        with pytest.raises(ValueError, match="not consumed"):
            _overlay(tmpl, bad, scope="t", allow_missing=True)

    def test_missing_leaves_raise_when_strict(self):
        tmpl = self._template()["params"]
        partial = {
            "conv1": {
                "weight": np.zeros((3, 3, 3, 8), np.float32)
            }
        }
        with pytest.raises(ValueError, match="missing from checkpoint"):
            _overlay(tmpl, partial, scope="t", allow_missing=False)
        # and succeeds when partial init is explicitly allowed
        merged = _overlay(tmpl, partial, scope="t", allow_missing=True)
        assert merged["conv1"]["weight"].shape == (3, 3, 3, 8)

    def test_float_weight_alias(self):
        """FP checkpoint 'weight' lands on binary latent 'float_weight'
        (the QAT-name fallback, reference train.py:404)."""
        student = BiResNet(
            stage_sizes=(1, 1), num_classes=4, width=8,
            stem="cifar", variant="cifar", act="hardtanh",
        )
        tmpl = student.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3)), train=False
        )["params"]
        w = np.full((3, 3, 8, 8), 0.5, np.float32)
        loaded = {"layer1_0": {"conv1": {"weight": w}}}
        merged = _overlay(
            tmpl, loaded, scope="t", allow_missing=True,
            alias_float_weight=True,
        )
        np.testing.assert_array_equal(
            np.asarray(merged["layer1_0"]["conv1"]["float_weight"]), w
        )


class TestTeacherBuildGuards:
    def test_ts_without_teacher_ckpt_raises(self):
        from bdbnn_tpu.configs.config import RunConfig
        from bdbnn_tpu.train.loop import build_teacher

        cfg = RunConfig(
            dataset="cifar10",
            arch_teacher="resnet20_float",
            imagenet_setting_step_2_ts=True,
        )
        with pytest.raises(ValueError, match="random-init"):
            build_teacher(cfg, 32)

    def test_ts_smoke_escape_hatch(self):
        from bdbnn_tpu.configs.config import RunConfig
        from bdbnn_tpu.train.loop import build_teacher

        cfg = RunConfig(
            dataset="cifar10",
            arch_teacher="resnet20_float",
            imagenet_setting_step_2_ts=True,
            allow_random_teacher=True,
        )
        teacher, variables = build_teacher(cfg, 32)
        assert "params" in variables
