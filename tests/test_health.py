"""Online health monitor tests (obs/health.py) + the auto-forensics
acceptance path.

Unit level: each detector's warmup/debounce/hysteresis state machine
against synthetic pathological signal streams — injected flip collapse,
flip explosion, kurtosis divergence, loss spike, loss plateau,
throughput cliff, HBM creep — each firing EXACTLY its own alert and
nothing else, plus a healthy-stream false-positive guard.

End to end: a real synthetic fit() with an injected flip-rate collapse
must produce an ``alert`` event, an auto-forensics checkpoint under
``<run_dir>/forensics/``, and a trace window on disk (the acceptance
criterion); a healthy seed fit must produce zero alerts.
"""

import glob
import os

import pytest

from bdbnn_tpu.configs.config import RunConfig
from bdbnn_tpu.obs.events import EventWriter, read_events
from bdbnn_tpu.obs.health import (
    SEVERITIES,
    HealthConfig,
    HealthMonitor,
    _DetectorState,
    apply_overrides,
)

# unit-stream config: short warmup so streams stay readable; the
# PRODUCTION default warmup (10) is pinned separately below
UCFG = HealthConfig(warmup_intervals=3, debounce=2)


def _monitor(tmp_path, cfg=UCFG, epochs=10, kurt_target=None):
    ev = EventWriter(str(tmp_path))
    return HealthMonitor(cfg, ev, epochs=epochs, kurt_target=kurt_target), ev


def _feed(mon, signals, epochs_at=0):
    """Drive observe_interval over a list of signal dicts; returns the
    list of (index, detector) firings. The default loss DECAYS — a
    constant default would itself be a plateau."""
    fired = []
    for i, sig in enumerate(signals):
        alerts = mon.observe_interval(
            epoch=sig.get("epoch", epochs_at), step=i,
            loss=sig.get("loss", 2.3 - 0.05 * i),
            img_per_s=sig.get("img_per_s", 100.0),
            flip_rate=sig.get("flip_rate", {"a": 1e-3}),
            kurtosis=sig.get("kurtosis", {"a": 2.5}),
        )
        fired += [(i, a["detector"]) for a in alerts]
    return fired


class TestDetectorState:
    def test_warmup_swallows_early_breaches(self):
        st = _DetectorState(warmup=3, debounce=1)
        assert [st.update(True) for _ in range(3)] == [False] * 3
        assert st.update(True) is True  # first post-warmup breach

    def test_debounce_needs_consecutive_breaches(self):
        st = _DetectorState(warmup=0, debounce=3)
        assert not st.update(True)
        assert not st.update(True)
        assert not st.update(False)  # streak reset
        assert not st.update(True)
        assert not st.update(True)
        assert st.update(True)  # 3 consecutive

    def test_hysteresis_latches_until_recovery(self):
        st = _DetectorState(warmup=0, debounce=1)
        assert st.update(True)
        # still breaching: latched, no second alert
        assert not st.update(True)
        assert not st.update(True)
        # recovery re-arms; next sustained breach fires again
        assert not st.update(False, recovered=True)
        assert st.update(True)
        assert st.fired == 2


class TestOverrides:
    def test_apply_and_types(self):
        cfg = apply_overrides(
            HealthConfig(), ["loss_spike_factor=5.5", "loss_window=4"]
        )
        assert cfg.loss_spike_factor == 5.5
        assert cfg.loss_window == 4 and isinstance(cfg.loss_window, int)

    def test_unknown_name_rejected(self):
        with pytest.raises(ValueError, match="health-threshold"):
            apply_overrides(HealthConfig(), ["not_a_knob=1"])
        with pytest.raises(ValueError, match="health-threshold"):
            apply_overrides(HealthConfig(), ["loss_window=soon"])

    def test_config_validate_rejects_bad_threshold(self):
        cfg = RunConfig(synthetic=True, health_thresholds=("nope=1",))
        with pytest.raises(ValueError, match="health-threshold"):
            cfg.validate()

    def test_production_warmup_default(self):
        # smoke-scale fits (< ~10 drains) must end before any flip/kurt
        # detector becomes eligible — that is the false-positive guard
        # for the whole existing test suite
        assert HealthConfig().warmup_intervals == 10


class TestDetectorStreams:
    """Each injected pathology fires exactly its own detector."""

    def test_flip_collapse_only(self, tmp_path):
        mon, ev = _monitor(tmp_path)
        fired = _feed(mon, [{"flip_rate": {"a": 0.0}}] * 8)
        # warmup 3 + debounce 2 -> fires at the 5th observation, once
        assert fired == [(4, "flip_collapse")]
        ev.close()
        recs = read_events(str(tmp_path), "alert")
        assert len(recs) == 1 and recs[0]["severity"] == "critical"

    def test_flip_collapse_not_near_run_end(self, tmp_path):
        # a run at 95% of its epoch budget is ALLOWED to freeze: that
        # is convergence, not collapse
        mon, _ = _monitor(tmp_path, epochs=100)
        fired = _feed(mon, [{"flip_rate": {"a": 0.0}, "epoch": 95}] * 8)
        assert fired == []

    def test_flip_explosion_only(self, tmp_path):
        mon, _ = _monitor(tmp_path)
        fired = _feed(mon, [{"flip_rate": {"a": 0.4}}] * 8)
        assert fired == [(4, "flip_explosion")]

    def test_hysteresis_one_alert_for_hovering_signal(self, tmp_path):
        mon, _ = _monitor(tmp_path)
        # collapse for 10 drains, recover (> 2x threshold), collapse again
        stream = (
            [{"flip_rate": {"a": 0.0}}] * 10
            + [{"flip_rate": {"a": 1e-3}}] * 2
            + [{"flip_rate": {"a": 0.0}}] * 3
        )
        fired = _feed(mon, stream)
        # re-fires after recovery: debounce 2 over indices 12-13
        assert fired == [(4, "flip_collapse"), (13, "flip_collapse")]

    def test_kurt_divergence_needs_target(self, tmp_path):
        stream = [{"kurtosis": {"a": 50.0}}] * 8
        mon, _ = _monitor(tmp_path)  # kurtosis loss off -> disarmed
        assert _feed(mon, stream) == []
        mon, _ = _monitor(tmp_path / "t", kurt_target=1.8)
        assert _feed(mon, stream) == [(4, "kurt_divergence")]

    def test_loss_spike_only(self, tmp_path):
        mon, _ = _monitor(tmp_path)
        # jittered baseline (so it is not ALSO a plateau), one 4.5x spike
        base = [{"loss": 2.0 + (0.1 if i % 2 else -0.1)} for i in range(6)]
        fired = _feed(mon, base + [{"loss": 9.0}] + base[:3])
        assert fired == [(6, "loss_spike")]

    def test_loss_plateau_only_at_high_loss(self, tmp_path):
        mon, _ = _monitor(tmp_path)
        fired = _feed(mon, [{"loss": 2.3}] * 8)
        # plateau_window 6 -> fires as soon as 6 flat high-loss drains
        # exist (early in training: epoch 0 of 10)
        assert fired == [(5, "loss_plateau")]
        # a plateau at ~zero loss is convergence, not pathology
        mon, _ = _monitor(tmp_path / "low")
        assert _feed(mon, [{"loss": 0.01}] * 8) == []

    def test_throughput_cliff_only(self, tmp_path):
        mon, _ = _monitor(tmp_path)
        stream = [{"img_per_s": 1000.0}] * 9 + [{"img_per_s": 200.0}] * 2
        fired = _feed(mon, stream)
        # needs 8 history + debounce 2 -> second cliff interval fires
        assert fired == [(10, "throughput_regression")]

    def test_hbm_creep_fires_once(self, tmp_path):
        mon, ev = _monitor(tmp_path)
        assert mon.observe_memory({"peak_bytes": 10 * 2**30}) == []  # baseline
        assert mon.observe_memory({"peak_bytes": 10 * 2**30}) == []
        out = mon.observe_memory({"peak_bytes": 12 * 2**30, "epoch": 3})
        assert [a["detector"] for a in out] == ["hbm_creep"]
        # latched: further creep does not re-alert
        assert mon.observe_memory({"peak_bytes": 14 * 2**30}) == []
        assert mon.observe_memory({"available": False, "peak_bytes": None}) == []

    def test_healthy_stream_no_alerts(self, tmp_path):
        """False-positive guard: a healthy run's signals — decaying
        loss, settling (but nonzero) flips, near-target kurtosis,
        steady throughput with realistic jitter — fire nothing."""
        mon, ev = _monitor(tmp_path, kurt_target=1.8)
        stream = [
            {
                "loss": 2.3 * (0.97 ** i),
                "img_per_s": 1000.0 + (-30.0 if i % 3 else 40.0),
                "flip_rate": {"a": 1e-2 / (1 + i), "b": 5e-3},
                "kurtosis": {"a": 2.8 - 0.05 * i, "b": 2.2},
                "epoch": i // 4,
            }
            for i in range(24)
        ]
        assert _feed(mon, stream) == []
        mon.observe_memory({"peak_bytes": 8 * 2**30})
        assert mon.observe_memory({"peak_bytes": 8 * 2**30 + 2**20}) == []
        summary = mon.emit_summary()
        assert summary["alerts_total"] == 0
        ev.close()
        assert read_events(str(tmp_path), "alert") == []

    def test_summary_event_counts(self, tmp_path):
        mon, ev = _monitor(tmp_path)
        _feed(mon, [{"flip_rate": {"a": 0.0}}] * 6)
        rec = mon.emit_summary()
        assert rec["kind"] == "health"
        assert rec["alerts_total"] == 1
        assert rec["alerts_critical"] == 1
        assert rec["by_detector"] == {"flip_collapse": 1}
        ev.close()
        assert read_events(str(tmp_path), "health") == [rec]

    def test_severity_table_covers_all_detectors(self):
        assert set(SEVERITIES.values()) <= {"critical", "warning"}
        for det in ("flip_collapse", "flip_explosion", "loss_spike"):
            assert SEVERITIES[det] == "critical"


def _find_run_dir(root):
    hits = glob.glob(os.path.join(str(root), "**", "events.jsonl"),
                     recursive=True)
    assert hits, f"no events.jsonl under {root}"
    return os.path.dirname(sorted(hits)[-1])


@pytest.fixture(scope="module")
def collapsed_run(tmp_path_factory):
    """ONE synthetic fit with an injected flip-rate collapse (the probe
    drain is patched to report zero flips), health on, forensics on:
    the acceptance-criterion run shared by the assertions below.
    Throughput detection is disabled via threshold override — the
    forensics trace capture itself slows the traced steps, which is
    exactly the kind of measurement perturbation that must not turn
    into a second alert inside this test."""
    import bdbnn_tpu.train.loop as loop_mod
    from bdbnn_tpu.train.loop import fit

    tmp = tmp_path_factory.mktemp("healthrun")
    orig = loop_mod.drain_probe_report
    loop_mod.drain_probe_report = (
        lambda sums, sizes, steps: ({"layer": 0.0}, {"layer": 2.5})
    )
    try:
        res = fit(RunConfig(
            dataset="cifar10",
            synthetic=True,
            synthetic_train_size=1024,  # 16 steps
            synthetic_val_size=64,
            arch="resnet8_tiny",
            epochs=1,
            batch_size=64,
            lr=0.05,
            print_freq=1,
            log_path=str(tmp / "log"),
            seed=0,
            workers=2,
            health_forensics_steps=3,
            health_thresholds=("throughput_window=999",),
        ))
    finally:
        loop_mod.drain_probe_report = orig
    return {"res": res, "run_dir": _find_run_dir(tmp)}


class TestFitHealthEndToEnd:
    def test_alert_event_fired(self, collapsed_run):
        alerts = read_events(collapsed_run["run_dir"], "alert")
        assert alerts, "injected flip collapse fired no alert"
        assert {a["detector"] for a in alerts} == {"flip_collapse"}
        a = alerts[0]
        assert a["severity"] == "critical"
        # warmup 10 + debounce 2 -> the 12th drain (step index 11)
        assert a["step"] == 11
        assert a["value"] == 0.0 and a["threshold"] == pytest.approx(1e-5)

    def test_forensics_checkpoint_on_disk(self, collapsed_run):
        run_dir = collapsed_run["run_dir"]
        ck = [e for e in read_events(run_dir, "checkpoint")
              if e.get("reason") == "forensics"]
        assert len(ck) == 1
        assert ck[0]["detector"] == "flip_collapse"
        assert os.path.isdir(ck[0]["path"])
        assert ck[0]["path"].startswith(os.path.join(run_dir, "forensics"))
        # a real, restorable checkpoint: payload + integrity + sidecar
        for name in ("INTEGRITY.json", "resume.json"):
            assert os.path.exists(os.path.join(ck[0]["path"], name))

    def test_forensics_trace_window_on_disk(self, collapsed_run):
        from bdbnn_tpu.obs import find_trace_file

        run_dir = collapsed_run["run_dir"]
        prof = read_events(run_dir, "profile")
        assert len(prof) == 1
        # scheduled at the alert's resume cursor (step 12), 3 steps
        assert prof[0]["epoch"] == 0 and prof[0]["start_step"] == 12
        assert prof[0]["steps"] == 3
        assert find_trace_file(run_dir), "no forensics trace on disk"

    def test_health_summary_event(self, collapsed_run):
        health = read_events(collapsed_run["run_dir"], "health")
        assert len(health) == 1
        assert health[0]["alerts_critical"] == 1
        assert health[0]["by_detector"] == {"flip_collapse": 1}

    def test_summarize_renders_health_and_strict_gates(self, collapsed_run):
        from bdbnn_tpu.obs import summarize_run

        report, summary = summarize_run(collapsed_run["run_dir"])
        assert summary["health"]["alerts_critical"] == 1
        assert summary["health"]["by_detector"] == {"flip_collapse": 1}
        assert "health:" in report and "flip_collapse" in report
        assert "!! flip_collapse" in report

    def test_watch_highlights_alerts(self, collapsed_run):
        from bdbnn_tpu.obs.manifest import read_manifest
        from bdbnn_tpu.obs.watch import render_status

        run_dir = collapsed_run["run_dir"]
        out = render_status(read_events(run_dir), read_manifest(run_dir))
        assert "!! alerts: 1 (flip_collapse x1)" in out
        assert "critical flip_collapse" in out


class TestForensicsAtEpochEnd:
    def test_alert_on_final_drain_skips_empty_trace(self, tmp_path):
        """An alert at the epoch's LAST drain must not open a trace
        window the loop can never feed: an empty capture's `profile`
        event would poison summarize/compare attribution (they key on
        the newest trace). The checkpoint still lands; the trace is
        skipped when no steps remain in the run."""
        import bdbnn_tpu.train.loop as loop_mod
        from bdbnn_tpu.obs import find_trace_file
        from bdbnn_tpu.train.loop import fit

        orig = loop_mod.drain_probe_report
        loop_mod.drain_probe_report = (
            lambda sums, sizes, steps: ({"layer": 0.0}, {"layer": 2.5})
        )
        try:
            fit(RunConfig(
                dataset="cifar10",
                synthetic=True,
                synthetic_train_size=768,  # 12 steps: warmup 10 +
                synthetic_val_size=64,     # debounce 2 fire on the last
                arch="resnet8_tiny",
                epochs=1,
                batch_size=64,
                lr=0.05,
                print_freq=1,
                log_path=str(tmp_path / "log"),
                seed=0,
                workers=2,
                health_thresholds=("throughput_window=999",),
            ))
        finally:
            loop_mod.drain_probe_report = orig
        run_dir = _find_run_dir(tmp_path)
        alerts = read_events(run_dir, "alert")
        assert [a["detector"] for a in alerts] == ["flip_collapse"]
        assert alerts[0]["step"] == 11  # the epoch's final drain
        # forensics checkpoint still lands...
        ck = [e for e in read_events(run_dir, "checkpoint")
              if e.get("reason") == "forensics"]
        assert len(ck) == 1 and os.path.isdir(ck[0]["path"])
        # ...but no empty capture: no profile event, no trace file
        assert read_events(run_dir, "profile") == []
        assert find_trace_file(run_dir) is None


class TestHealthyFitNoAlerts:
    @pytest.mark.slow
    def test_healthy_seed_run_fires_nothing(self, tmp_path):
        """End-to-end false-positive guard: a healthy (default-config)
        synthetic fit with real probes emits zero alerts and a clean
        health roll-up.

        tier-1 budget (PR 10 rebalance): the broad fit()-smoke variant
        of the guard rides slow; the per-detector healthy-STREAM
        false-positive guard (test_healthy_stream_no_alerts) keeps the
        denser tier-1 coverage over the same detector set."""
        from bdbnn_tpu.train.loop import fit

        fit(RunConfig(
            dataset="cifar10",
            synthetic=True,
            synthetic_train_size=512,  # 8 steps
            synthetic_val_size=64,
            arch="resnet8_tiny",
            epochs=1,
            batch_size=64,
            lr=0.05,
            print_freq=2,
            log_path=str(tmp_path / "log"),
            seed=0,
            workers=2,
        ))
        run_dir = _find_run_dir(tmp_path)
        assert read_events(run_dir, "alert") == []
        health = read_events(run_dir, "health")
        assert len(health) == 1 and health[0]["alerts_total"] == 0
        # no forensics artifacts for a clean run
        assert not os.path.isdir(os.path.join(run_dir, "forensics"))

    def test_no_health_flag_disables_monitor(self, tmp_path):
        from bdbnn_tpu.train.loop import fit

        fit(RunConfig(
            dataset="cifar10",
            synthetic=True,
            synthetic_train_size=128,
            synthetic_val_size=64,
            arch="resnet8_tiny",
            epochs=1,
            batch_size=64,
            print_freq=2,
            log_path=str(tmp_path / "log"),
            seed=0,
            workers=2,
            health=False,
        ))
        run_dir = _find_run_dir(tmp_path)
        assert read_events(run_dir, "health") == []
        assert read_events(run_dir, "alert") == []
