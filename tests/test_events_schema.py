"""CI guard for the structured event channel (obs/events.py).

Two invariants that keep ``events.jsonl`` machine-readable forever:

1. **Registered kinds.** Every ``*.emit(...)`` call site in the package
   (plus the bench/profile harnesses) passes a LITERAL kind string that
   is registered in ``events.KNOWN_KINDS`` — a new event kind added
   without registration fails here, so the docs/registry can't drift
   from the code.

2. **Strict RFC 8259.** Whatever a call site passes — NaN/Inf floats,
   numpy scalars, nested dicts of them — the emitted line round-trips
   through ``json.loads`` with ``parse_constant`` raising, i.e. no bare
   ``NaN``/``Infinity`` tokens and no repr-string smuggling of numeric
   values. This is what keeps jq / non-Python consumers working on a
   warn-policy run's telemetry.
"""

import ast
import glob
import json
import os

import numpy as np
import pytest

from bdbnn_tpu.obs.events import KNOWN_KINDS, EventWriter, jsonsafe

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# everything that writes events: the package, plus the root-level
# harnesses that share the channel
SCANNED = sorted(
    glob.glob(os.path.join(REPO, "bdbnn_tpu", "**", "*.py"), recursive=True)
) + [os.path.join(REPO, "bench.py"), os.path.join(REPO, "profile_r05.py")]


def _emit_calls(path):
    """(lineno, first-arg AST node) for every ``<obj>.emit(...)`` call.

    ``EventWriter.emit``'s own definition isn't a call; dict ``.items``
    etc. don't match the attribute name."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    out = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "emit"
        ):
            # ProgressLog.emit(step, parts) takes an int first — only
            # event emits pass a string literal or anything else; the
            # literal-kind assertion below separates them
            out.append((node.lineno, node.args[0] if node.args else None))
    return out


class TestEmitCallSites:
    def test_every_emit_kind_is_registered(self):
        """Every event-channel emit passes a literal, registered kind."""
        unregistered = []
        found = set()
        for path in SCANNED:
            for lineno, arg in _emit_calls(path):
                if not isinstance(arg, ast.Constant) or not isinstance(
                    arg.value, str
                ):
                    # not the event channel (ProgressLog.emit's first
                    # arg is a step index; **info-style relays are
                    # covered by the registry test on their kind field)
                    continue
                found.add(arg.value)
                if arg.value not in KNOWN_KINDS:
                    unregistered.append(
                        f"{os.path.relpath(path, REPO)}:{lineno}: "
                        f"emit({arg.value!r})"
                    )
        assert not unregistered, (
            "event kinds missing from obs.events.KNOWN_KINDS:\n"
            + "\n".join(unregistered)
        )
        # the scan actually saw the package's core kinds (guards
        # against the AST walk silently matching nothing) — including
        # the four resilience kinds, which must keep real call sites
        assert {"run_start", "compile", "train_interval", "eval",
                "memory", "profile", "run_end",
                "checkpoint", "restore", "preempt", "data_error"} <= found

    def test_registry_matches_docs(self):
        """KNOWN_KINDS and the events.py module docstring stay in sync."""
        import bdbnn_tpu.obs.events as ev

        for kind in KNOWN_KINDS:
            assert f"``{kind}``" in ev.__doc__, (
                f"event kind {kind!r} not documented in obs/events.py"
            )


class TestStrictRfc8259:
    def _strict(self, line):
        def no_constants(s):
            raise AssertionError(f"bare {s} token in events.jsonl")

        return json.loads(line, parse_constant=no_constants)

    def test_adversarial_payload_roundtrips(self, tmp_path):
        """NaN/Inf, numpy scalars (float32 is NOT a Python float and
        used to leak through as a repr string), 0-d arrays, nesting."""
        ev = EventWriter(str(tmp_path))
        ev.emit(
            "train_interval",
            loss=float("nan"),
            neg=float("-inf"),
            np32=np.float32(1.5),
            np32_nan=np.float32("nan"),
            np64=np.float64(2.5),
            npint=np.int64(7),
            npbool=np.bool_(True),
            zerod=np.asarray(3.25),
            nested={"k": {"deep": np.float32("inf")}},
            arr=[np.float32(0.5), float("inf"), 2],
        )
        ev.close()
        with open(ev.path) as f:
            rec = self._strict(f.read().strip())
        assert rec["loss"] is None and rec["neg"] is None
        assert rec["np32"] == 1.5 and isinstance(rec["np32"], float)
        assert rec["np32_nan"] is None
        assert rec["np64"] == 2.5
        assert rec["npint"] == 7 and isinstance(rec["npint"], int)
        assert rec["npbool"] is True
        assert rec["zerod"] == 3.25
        assert rec["nested"]["k"]["deep"] is None
        assert rec["arr"] == [0.5, None, 2]

    def test_every_known_kind_emits_strict(self, tmp_path):
        """One adversarial record per registered kind: whatever fields
        a future call site adds, the envelope machinery keeps the line
        parseable."""
        ev = EventWriter(str(tmp_path))
        for kind in sorted(KNOWN_KINDS):
            ev.emit(kind, value=float("nan"),
                    per_layer={"l1": np.float32("-inf")})
        ev.close()
        with open(ev.path) as f:
            lines = [l for l in f if l.strip()]
        assert len(lines) == len(KNOWN_KINDS)
        for line in lines:
            rec = self._strict(line)
            assert rec["kind"] in KNOWN_KINDS
            assert rec["value"] is None
            assert rec["per_layer"]["l1"] is None

    def test_jsonsafe_bool_and_int_untouched(self):
        assert jsonsafe(True) is True
        assert jsonsafe(0) == 0 and jsonsafe(0) is not False
        assert jsonsafe("NaN") == "NaN"  # strings pass through
