"""CI guard for the structured event channel (obs/events.py).

Two invariants that keep ``events.jsonl`` machine-readable forever:

1. **Registered kinds.** Every ``*.emit(...)`` call site in the package
   (plus the bench/profile harnesses) passes a LITERAL kind string that
   is registered in ``events.KNOWN_KINDS``, every registered kind is
   documented in the module docstring, and every registered kind keeps
   a live call site. The AST scan that enforces this was born here and
   now lives in the static-analysis package
   (``bdbnn_tpu/analysis/eventschema.py``, the ``event-schema``
   checker) — this test is the thin tier-1 wrapper over it.

2. **Strict RFC 8259.** Whatever a call site passes — NaN/Inf floats,
   numpy scalars, nested dicts of them — the emitted line round-trips
   through ``json.loads`` with ``parse_constant`` raising, i.e. no bare
   ``NaN``/``Infinity`` tokens and no repr-string smuggling of numeric
   values. This is what keeps jq / non-Python consumers working on a
   warn-policy run's telemetry.
"""

import json
import os

import numpy as np
import pytest

from bdbnn_tpu.analysis.core import discover_files
from bdbnn_tpu.analysis.eventschema import scan_events
from bdbnn_tpu.obs.events import (
    KNOWN_KINDS,
    EventWriter,
    jsonsafe,
    load_events,
    read_events,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# everything that writes events: the package, plus the root-level
# harnesses that share the channel (the analysis package's default
# scan set is exactly this)
SCANNED = discover_files(REPO)


class TestEmitCallSites:
    """Thin wrapper over the ``event-schema`` checker: the scan logic
    lives in bdbnn_tpu/analysis/eventschema.py (where the ``check``
    CLI also runs it); this test keeps it a named tier-1 gate and pins
    the historical found-set floor."""

    def test_event_schema_checker_clean(self):
        """No unregistered emit kinds, no undocumented registered
        kinds, no dead registry entries — over the package + the
        bench/profile harnesses. ``_emit`` relay wrappers
        (serve/pool.py, serve/canary.py) are scanned exactly like
        direct emits."""
        findings, _found = scan_events(REPO, SCANNED)
        assert findings == [], "\n".join(f.record for f in findings)

    def test_found_set_floor(self):
        """The scan actually saw the package's core kinds (guards
        against the AST walk silently matching nothing) — the training
        kinds, the four resilience kinds, the health-monitor kinds,
        the serving/front-end/replica-pool kinds, the request-tracing
        and canary kinds, the fleet router's ``fleet`` kind
        (serve/fleet.py), and the static analyzer's own ``analysis``
        kind (the `check --events-into` emit in cli.py), and the
        recipe-search harness's ``search``/``trial`` kinds
        (bdbnn_tpu/search/harness.py), and the performance
        observatory's ``perf`` kind (bdbnn_tpu/obs/roofline.py), and
        the capacity observatory's ``capacity`` kind (obs/capacity.py
        heartbeats + burn-rate breach/recovery transitions emitted by
        the serve-http stats pump)."""
        _findings, found = scan_events(REPO, SCANNED)
        assert {"run_start", "compile", "train_interval", "eval",
                "memory", "profile", "run_end",
                "checkpoint", "restore", "preempt", "data_error",
                "alert", "health", "export", "serve",
                "http", "admission", "replica", "swap", "fleet",
                "rtrace", "canary", "shadow", "search", "trial",
                "analysis", "perf", "capacity"} <= found

    def test_registry_matches_docs(self):
        """KNOWN_KINDS and the events.py module docstring stay in sync
        (also enforced by the checker; kept as a direct assertion so a
        failure names the kind)."""
        import bdbnn_tpu.obs.events as ev

        for kind in KNOWN_KINDS:
            assert f"``{kind}``" in ev.__doc__, (
                f"event kind {kind!r} not documented in obs/events.py"
            )


class TestStrictRfc8259:
    def _strict(self, line):
        def no_constants(s):
            raise AssertionError(f"bare {s} token in events.jsonl")

        return json.loads(line, parse_constant=no_constants)

    def test_adversarial_payload_roundtrips(self, tmp_path):
        """NaN/Inf, numpy scalars (float32 is NOT a Python float and
        used to leak through as a repr string), 0-d arrays, nesting."""
        ev = EventWriter(str(tmp_path))
        ev.emit(
            "train_interval",
            loss=float("nan"),
            neg=float("-inf"),
            np32=np.float32(1.5),
            np32_nan=np.float32("nan"),
            np64=np.float64(2.5),
            npint=np.int64(7),
            npbool=np.bool_(True),
            zerod=np.asarray(3.25),
            nested={"k": {"deep": np.float32("inf")}},
            arr=[np.float32(0.5), float("inf"), 2],
        )
        ev.close()
        with open(ev.path) as f:
            rec = self._strict(f.read().strip())
        assert rec["loss"] is None and rec["neg"] is None
        assert rec["np32"] == 1.5 and isinstance(rec["np32"], float)
        assert rec["np32_nan"] is None
        assert rec["np64"] == 2.5
        assert rec["npint"] == 7 and isinstance(rec["npint"], int)
        assert rec["npbool"] is True
        assert rec["zerod"] == 3.25
        assert rec["nested"]["k"]["deep"] is None
        assert rec["arr"] == [0.5, None, 2]

    def test_perf_payload_roundtrips(self, tmp_path):
        """The perf observatory's worst-case payload: a roofline
        efficiency that divided by a zero measurement (NaN), numpy
        scalars from the trace join, and the nested per-layer map —
        the ledger line and the ``perf`` verdict event must both stay
        strict RFC 8259."""
        ev = EventWriter(str(tmp_path))
        ev.emit(
            "perf",
            phase="verdict",
            verdict={
                "summary": {
                    "step_ms_best": np.float32(4.358),
                    "efficiency_mean": float("nan"),
                    "mfu_best": np.float64("inf"),
                    "bucket": np.int64(8),
                },
                "perf_layers": {
                    "conv1|b8|unpack": np.float32(0.25),
                    "fc|b8|unpack": float("nan"),
                },
            },
        )
        ev.close()
        with open(ev.path) as f:
            rec = self._strict(f.read().strip())
        s = rec["verdict"]["summary"]
        assert s["step_ms_best"] == pytest.approx(4.358)
        assert isinstance(s["step_ms_best"], float)
        assert s["efficiency_mean"] is None  # NaN -> null
        assert s["mfu_best"] is None  # inf -> null
        assert s["bucket"] == 8 and isinstance(s["bucket"], int)
        layers = rec["verdict"]["perf_layers"]
        assert layers["conv1|b8|unpack"] == pytest.approx(0.25)
        assert layers["fc|b8|unpack"] is None

    def test_capacity_payload_roundtrips(self, tmp_path):
        """The capacity observatory's worst-case payload: a burn rate
        that divided by a zero-measurement window (NaN), numpy demand
        counters from a future call site, and the nested
        per-model / per-tenant / per-host tables a fleet-merged
        ``capacity`` stats event carries — all must stay strict
        RFC 8259."""
        ev = EventWriter(str(tmp_path))
        ev.emit(
            "capacity",
            phase="stats",
            offered_rps=np.float32(120.5),
            in_flight=np.int64(3),
            demand_shed_ratio_max=float("nan"),
            headroom={
                "capacity_rps_est": np.float64(200.0),
                "headroom_rps": np.float32("-inf"),
                "seconds_to_saturation": float("nan"),
            },
            detectors={
                "p2:shed": {
                    "burn_rate_fast": float("nan"),
                    "burn_rate_slow": np.float32(4.2),
                    "breach": np.bool_(True),
                },
            },
            demand={
                "by_model": {"default": np.int64(41)},
                "by_tenant": {"bulk": np.float32(0.25)},
            },
            hosts={
                "h0": {"burn_rate_max": float("inf"),
                       "offered_rps": np.float64(60.25)},
            },
        )
        ev.close()
        with open(ev.path) as f:
            rec = self._strict(f.read().strip())
        assert rec["offered_rps"] == pytest.approx(120.5)
        assert isinstance(rec["offered_rps"], float)
        assert rec["in_flight"] == 3 and isinstance(rec["in_flight"], int)
        assert rec["demand_shed_ratio_max"] is None  # NaN -> null
        hr = rec["headroom"]
        assert hr["capacity_rps_est"] == 200.0
        assert hr["headroom_rps"] is None  # -inf -> null
        assert hr["seconds_to_saturation"] is None
        det = rec["detectors"]["p2:shed"]
        assert det["burn_rate_fast"] is None
        assert det["burn_rate_slow"] == pytest.approx(4.2)
        assert det["breach"] is True
        assert rec["demand"]["by_model"]["default"] == 41
        assert rec["demand"]["by_tenant"]["bulk"] == pytest.approx(0.25)
        assert rec["hosts"]["h0"]["burn_rate_max"] is None
        assert rec["hosts"]["h0"]["offered_rps"] == pytest.approx(60.25)

    def test_every_known_kind_emits_strict(self, tmp_path):
        """One adversarial record per registered kind: whatever fields
        a future call site adds, the envelope machinery keeps the line
        parseable."""
        ev = EventWriter(str(tmp_path))
        for kind in sorted(KNOWN_KINDS):
            ev.emit(kind, value=float("nan"),
                    per_layer={"l1": np.float32("-inf")})
        ev.close()
        with open(ev.path) as f:
            lines = [l for l in f if l.strip()]
        assert len(lines) == len(KNOWN_KINDS)
        for line in lines:
            rec = self._strict(line)
            assert rec["kind"] in KNOWN_KINDS
            assert rec["value"] is None
            assert rec["per_layer"]["l1"] is None

    def test_jsonsafe_bool_and_int_untouched(self):
        assert jsonsafe(True) is True
        assert jsonsafe(0) == 0 and jsonsafe(0) is not False
        assert jsonsafe("NaN") == "NaN"  # strings pass through

    def test_serve_kind_payloads_roundtrip(self, tmp_path):
        """The real export/serve payload shapes (serve/export.py,
        serve/loadgen.py) with adversarial values in the numeric slots:
        a NaN latency percentile must land as null, numpy counters must
        unwrap, and the nested warmup/bucket structures must survive."""
        ev = EventWriter(str(tmp_path))
        x = ev.emit(
            "export",
            artifact="/tmp/art",
            arch="resnet8_tiny",
            checkpoint="/tmp/run/model_best",
            integrity="ok",
            binarized_convs=np.int64(5),
            compression_ratio=np.float32(7.1),
            checkpoint_acc1=float("nan"),
        )
        s = ev.emit(
            "serve",
            phase="verdict",
            p50_ms=np.float32(4.25),
            p99_ms=float("inf"),
            throughput_rps=np.float64(450.5),
            shed_rate=0.0,
            mean_batch_occupancy=np.float32("nan"),
            warmup_compile_s={"1": np.float32(0.5), "8": 1.25},
            buckets=[np.int64(1), 8],
            preempted=np.bool_(False),
            drained_clean=True,
        )
        ev.close()
        with open(ev.path) as f:
            lines = [self._strict(l) for l in f if l.strip()]
        assert lines[0]["kind"] == "export"
        assert lines[0]["binarized_convs"] == 5
        assert isinstance(lines[0]["binarized_convs"], int)
        assert lines[0]["checkpoint_acc1"] is None  # NaN -> null
        assert lines[1]["kind"] == "serve"
        assert lines[1]["p99_ms"] is None  # Inf -> null, never a token
        assert lines[1]["warmup_compile_s"]["1"] == 0.5
        assert lines[1]["buckets"] == [1, 8]
        assert lines[1]["preempted"] is False
        assert x["checkpoint_acc1"] is None and s["p50_ms"] == 4.25

    def test_http_admission_kind_payloads_roundtrip(self, tmp_path):
        """The real network-front-end payload shapes (serve/http.py)
        with adversarial values in the numeric slots: NaN latencies in
        the nested per-priority verdict blocks must land as null,
        numpy counters must unwrap, and the per-tenant admission dicts
        must survive strict parsing."""
        ev = EventWriter(str(tmp_path))
        h = ev.emit(
            "http",
            phase="stats",
            state="ready",
            inflight=np.int64(3),
            requests_seen=1200,
            queue_depth_by_priority=[np.int64(0), 2, np.int64(7)],
            completed_by_priority=[100, np.int64(300), 800],
            shed_by_priority=[0, 0, np.int64(41)],
            tenants={
                "tenant-a": {"admitted": np.int64(900),
                             "over_quota": 0, "shed": np.int64(12)},
                "tenant-b": {"admitted": 300, "over_quota": np.int64(41),
                             "shed": 0},
            },
        )
        d = ev.emit(
            "http", phase="drain", signum=np.int64(15),
            preempted=np.bool_(True),
        )
        a = ev.emit(
            "admission",
            phase="summary",
            draining=np.bool_(True),
            default_rate=np.float32(100.0),
            default_burst=200.0,
            tenants={
                "tenant-a": {
                    "admitted": np.int64(900), "over_quota": 0,
                    "shed": 12, "completed": np.int64(888),
                    "failed": 0, "shed_rate": np.float32("nan"),
                    "quota_rate": float("inf"), "quota_burst": 200.0,
                },
            },
        )
        s = ev.emit(
            "serve",
            phase="verdict",
            per_priority={
                "0": {"submitted": np.int64(100), "completed": 100,
                      "shed": 0, "p99_ms": np.float32(12.5)},
                "2": {"submitted": 800, "completed": np.int64(759),
                      "shed": 41, "p99_ms": float("nan")},
            },
            per_tenant={
                "tenant-b": {"submitted": 341, "completed": np.int64(300),
                             "shed_rate": np.float32(0.12)},
            },
            fairness_ratio=np.float32(1.33),
        )
        ev.close()
        with open(ev.path) as f:
            lines = [self._strict(l) for l in f if l.strip()]
        assert lines[0]["kind"] == "http"
        assert lines[0]["queue_depth_by_priority"] == [0, 2, 7]
        assert isinstance(lines[0]["inflight"], int)
        assert lines[0]["tenants"]["tenant-b"]["over_quota"] == 41
        assert lines[1]["signum"] == 15 and lines[1]["preempted"] is True
        assert lines[2]["kind"] == "admission"
        assert lines[2]["tenants"]["tenant-a"]["shed_rate"] is None  # NaN
        assert lines[2]["tenants"]["tenant-a"]["quota_rate"] is None  # Inf
        assert lines[2]["draining"] is True
        assert lines[3]["per_priority"]["0"]["p99_ms"] == 12.5
        assert lines[3]["per_priority"]["2"]["p99_ms"] is None
        assert lines[3]["fairness_ratio"] == pytest.approx(1.33, abs=1e-3)
        # the emit() return values match what was written
        assert h["inflight"] == 3 and d["signum"] == 15
        assert a["tenants"]["tenant-a"]["shed_rate"] is None
        assert s["per_priority"]["2"]["p99_ms"] is None

    def test_replica_swap_kind_payloads_roundtrip(self, tmp_path):
        """The replica-pool payload shapes (serve/pool.py emitted via
        serve/http.py + serve/loadgen.py) with adversarial values in
        the numeric slots: a NaN busy-seconds lands as null, numpy
        counters unwrap, and the nested per-replica table / swap
        status / completed-by-version ledger survive strict parsing."""
        ev = EventWriter(str(tmp_path))
        u = ev.emit(
            "replica",
            phase="unhealthy",
            replica=np.int64(2),
            device="TFRT_CPU_2",
            version="v0001",
            reason="wedged",
            busy_s=float("nan"),
        )
        r = ev.emit(
            "replica",
            phase="stats",
            version="v0002",
            completed=np.int64(1200),
            restarts=np.int64(1),
            completed_by_version={
                "v0001": np.int64(800), "v0002": 400,
            },
            swap={"state": "shifting",
                  "replicas_shifted": np.int64(3),
                  "replicas_total": 8},
            replicas=[
                {"replica": np.int64(0), "device": "TFRT_CPU_0",
                 "version": "v0002", "state": "ready",
                 "queue_depth": np.int64(2), "completed": 600},
                {"replica": 1, "device": "TFRT_CPU_1",
                 "version": "v0001", "state": "shifting",
                 "queue_depth": 0, "completed": np.int64(600)},
            ],
        )
        s = ev.emit(
            "swap",
            phase="done",
            version_from="v0001",
            version_to="v0002",
            seconds=np.float32("inf"),
            replicas_shifted=np.int64(8),
        )
        t = ev.emit(
            "swap",
            phase="failed",
            version_to="v0002",
            error="corrupt artifact",
        )
        ev.close()
        with open(ev.path) as f:
            lines = [self._strict(l) for l in f if l.strip()]
        assert lines[0]["kind"] == "replica"
        assert lines[0]["busy_s"] is None  # NaN -> null, never a token
        assert isinstance(lines[0]["replica"], int)
        assert lines[1]["completed_by_version"] == {
            "v0001": 800, "v0002": 400,
        }
        assert lines[1]["swap"]["replicas_shifted"] == 3
        assert lines[1]["replicas"][0]["queue_depth"] == 2
        assert lines[1]["replicas"][1]["state"] == "shifting"
        assert lines[2]["kind"] == "swap"
        assert lines[2]["seconds"] is None  # Inf -> null
        assert lines[2]["replicas_shifted"] == 8
        assert lines[3]["error"] == "corrupt artifact"
        # the emit() return values match what was written
        assert u["busy_s"] is None and r["restarts"] == 1
        assert s["seconds"] is None and t["phase"] == "failed"

    def test_rtrace_kind_payloads_roundtrip(self, tmp_path):
        """The request-path tracing payload shapes (obs/rtrace.py via
        serve/http.py + serve/loadgen.py) with adversarial values in
        the numeric slots: a NaN stage ms in a waterfall must land as
        null, numpy counters must unwrap, and the nested stage-p99 /
        per-priority / waterfall structures must survive strict
        parsing."""
        ev = EventWriter(str(tmp_path))
        w = ev.emit(
            "rtrace",
            phase="request",
            seq=np.int64(123),
            priority=np.int64(0),
            tenant="tenant-a",
            total_ms=np.float32(14.25),
            stages={
                "read": np.float32(0.5),
                "admit": 0.01,
                "queue": float("nan"),
                "coalesce": np.float32(1.0),
                "compute": np.float64(11.5),
                "respond": float("inf"),
            },
        )
        s = ev.emit(
            "rtrace",
            phase="stats",
            requests=np.int64(1200),
            aborted=0,
            sampled=np.int64(75),
            stage_p99_ms={
                "read": np.float32(0.4),
                "queue": float("nan"),
                "dispatch": None,
                "compute": np.float64(12.5),
            },
            e2e_p99_ms_by_priority={
                "0": np.float32(13.0), "2": float("inf"),
            },
            queue_share=np.float32(0.31),
        )
        ev.close()
        with open(ev.path) as f:
            lines = [self._strict(l) for l in f if l.strip()]
        assert lines[0]["kind"] == "rtrace"
        assert lines[0]["seq"] == 123
        assert isinstance(lines[0]["seq"], int)
        assert lines[0]["stages"]["queue"] is None  # NaN -> null
        assert lines[0]["stages"]["respond"] is None  # Inf -> null
        assert lines[0]["stages"]["compute"] == 11.5
        assert lines[1]["stage_p99_ms"]["queue"] is None
        assert lines[1]["stage_p99_ms"]["dispatch"] is None
        assert lines[1]["e2e_p99_ms_by_priority"]["2"] is None
        assert lines[1]["queue_share"] == pytest.approx(0.31, abs=1e-3)
        # the emit() return values match what was written
        assert w["stages"]["queue"] is None and s["requests"] == 1200

    def test_canary_shadow_kind_payloads_roundtrip(self, tmp_path):
        """The canary-rollout payload shapes (serve/canary.py via
        serve/pool.py) with adversarial values in the numeric slots: a
        NaN drift must land as null (never a bare token), numpy
        counters must unwrap, and the nested per-detector evidence
        table must survive strict parsing."""
        ev = EventWriter(str(tmp_path))
        e = ev.emit(
            "canary",
            phase="evaluate",
            evaluation=np.int64(7),
            decision="observe",
            trigger=None,
            clean_streak=np.int64(2),
            canary_served=np.int64(40),
            incumbent_served=120,
            detectors={
                "p99_p0": {
                    "value": np.float32(1.25), "threshold": 2.0,
                    "breach": np.bool_(False), "fired": False,
                    "eligible": np.bool_(True),
                    "canary_p99_ms": np.float32(12.5),
                    "incumbent_p99_ms": float("nan"),
                    "canary_n": np.int64(40), "incumbent_n": 120,
                },
                "logit_drift": {
                    "value": float("inf"), "threshold": 0.0,
                    "breach": True, "fired": np.bool_(True),
                    "eligible": True, "compared": np.int64(9),
                },
            },
        )
        d = ev.emit(
            "canary",
            phase="decision",
            decision="rollback",
            trigger="logit_drift",
            reason="timeout",
            evaluations=np.int64(11),
        )
        s = ev.emit(
            "shadow",
            phase="mirror",
            seq=np.int64(42),
            drift=float("nan"),
            version_from="v0001",
            version_to="v0002",
        )
        s2 = ev.emit(
            "shadow", phase="mirror", seq=43, drift=np.float32(0.25),
            version_from="v0001", version_to="v0002",
        )
        ev.close()
        with open(ev.path) as f:
            lines = [self._strict(l) for l in f if l.strip()]
        assert lines[0]["kind"] == "canary"
        assert lines[0]["evaluation"] == 7
        assert isinstance(lines[0]["evaluation"], int)
        dets = lines[0]["detectors"]
        # NaN/Inf evidence -> null; numpy bools/ints unwrap; the
        # nested per-detector table survives strict parsing intact
        assert dets["p99_p0"]["incumbent_p99_ms"] is None
        assert dets["p99_p0"]["eligible"] is True
        assert dets["p99_p0"]["canary_n"] == 40
        assert dets["logit_drift"]["value"] is None  # Inf -> null
        assert dets["logit_drift"]["fired"] is True
        assert lines[1]["trigger"] == "logit_drift"
        assert lines[1]["evaluations"] == 11
        assert lines[2]["kind"] == "shadow"
        assert lines[2]["drift"] is None  # NaN -> null, never a token
        assert lines[3]["drift"] == 0.25
        # the emit() return values match what was written
        assert e["detectors"]["logit_drift"]["value"] is None
        assert d["evaluations"] == 11
        assert s["drift"] is None and s2["seq"] == 43

    def test_fleet_kind_payloads_roundtrip(self, tmp_path):
        """The fleet router's payload shapes (serve/fleet.py) with
        adversarial values in the numeric slots: a NaN per-host p99 in
        the stats table must land as null (never a bare token), numpy
        counters must unwrap, and the nested per-host ledger /
        retries-by-cause / swap structures must survive strict-RFC-8259
        parsing."""
        ev = EventWriter(str(tmp_path))
        s = ev.emit(
            "fleet",
            phase="stats",
            role="fleet-router",
            draining=np.bool_(False),
            hosts_total=np.int64(2),
            hosts_ready=1,
            inflight=np.int64(3),
            unrouteable=0,
            router_shed_draining=np.int64(0),
            hosts={
                "h0": {
                    "host": "127.0.0.1", "port": np.int64(8100),
                    "state": "dead", "server_id": "h0",
                    "inflight": 0, "proxied": np.int64(420),
                    "completed": 400,
                    "relayed_429": np.int64(3), "relayed_503": 17,
                    "relayed_other": 0,
                    "retries": {"connect": np.int64(5),
                                "timeout": 0, "reset": np.int64(2)},
                    "retried_away": np.int64(7),
                    "probes": 120, "probe_transitions": np.int64(2),
                    "p99_ms": float("nan"),
                },
                "h1": {
                    "host": "127.0.0.1", "port": 8101,
                    "state": "ready", "server_id": "h1",
                    "inflight": np.int64(3), "proxied": 600,
                    "completed": np.int64(580),
                    "relayed_429": 0, "relayed_503": np.int64(20),
                    "relayed_other": 0,
                    "retries": {"connect": 0, "timeout": 0,
                                "reset": 0},
                    "retried_away": 0,
                    "probes": np.int64(120), "probe_transitions": 0,
                    "p99_ms": np.float32(41.5),
                },
            },
            swap=None,
        )
        p = ev.emit(
            "fleet",
            phase="probe",
            host="h0",
            state_from="ready",
            state_to="dead",
        )
        x = ev.emit(
            "fleet",
            phase="proxy",
            host="h0",
            cause="reset",
            attempt=np.int64(1),
        )
        w = ev.emit(
            "fleet",
            phase="swap",
            state="done",
            seconds=float("inf"),
            hosts_shifted=np.int64(2),
        )
        ev.close()
        with open(ev.path) as f:
            lines = [self._strict(l) for l in f if l.strip()]
        assert lines[0]["kind"] == "fleet"
        h0 = lines[0]["hosts"]["h0"]
        assert h0["p99_ms"] is None  # NaN -> null, never a token
        assert h0["retries"] == {"connect": 5, "timeout": 0,
                                 "reset": 2}
        assert isinstance(h0["proxied"], int)
        assert lines[0]["hosts"]["h1"]["p99_ms"] == 41.5
        assert lines[0]["draining"] is False
        assert lines[1]["state_to"] == "dead"
        assert lines[2]["cause"] == "reset"
        assert isinstance(lines[2]["attempt"], int)
        assert lines[3]["seconds"] is None  # Inf -> null
        assert lines[3]["hosts_shifted"] == 2
        # the emit() return values match what was written
        assert s["hosts"]["h0"]["p99_ms"] is None
        assert p["host"] == "h0" and x["attempt"] == 1
        assert w["seconds"] is None

    def test_fleet_trace_plane_payloads_roundtrip(self, tmp_path):
        """The fleet tracing/metrics-plane payload shapes (v7): the
        ``rtrace`` + ``host_windows`` blocks riding the fleet
        phase=stats event, and the cross-host waterfall riding the
        rtrace phase=request event — with adversarial values in the
        numeric slots. A NaN stage p99 in the merged window must land
        as null (never a bare token), numpy counters must unwrap, and
        the nested per-host / per-stage / per-attempt structures must
        survive strict-RFC-8259 parsing."""
        ev = EventWriter(str(tmp_path))
        s = ev.emit(
            "fleet",
            phase="stats",
            role="fleet-router",
            hosts_total=np.int64(2),
            hosts_ready=2,
            rtrace={
                "requests": np.int64(96),
                "stitched": np.int64(90),
                "unstitched": 6,
                "retry_hop_share": np.float32(0.083),
                "stages": {
                    "probe_wait": {"p99_ms": np.float32(0.2),
                                   "n": np.int64(96)},
                    "retry_hop": {"p99_ms": float("nan"), "n": 8},
                    "network": {"p99_ms": np.float64(3.5),
                                "n": np.int64(90)},
                },
                "backend_stages": {
                    "queue": {"p99_ms": np.float32(4.0), "n": 90},
                    "compute": {"p99_ms": float("inf"),
                                "n": np.int64(90)},
                },
                "reconciliation": {
                    "violations": np.int64(0),
                    "mean_abs_err_pct": np.float32(0.6),
                    "ok": np.bool_(True),
                },
            },
            host_windows={
                "hosts_fresh": np.int64(1),
                "hosts_stale": 1,
                "hosts": {
                    "h0": {
                        "stale": np.bool_(False),
                        "failures": np.int64(0),
                        "stage_p99_ms": {
                            "queue": np.float32(4.1),
                            "compute": float("nan"),
                            "respond": None,
                        },
                        "queue_share": np.float32(0.3),
                    },
                    "h1": {
                        "stale": np.bool_(True),
                        "failures": np.int64(3),
                        "stage_p99_ms": {"queue": None,
                                         "compute": None},
                        "queue_share": None,
                    },
                },
                "merged": {
                    "stage_p99_ms": {"queue": np.float64(4.1),
                                     "compute": float("nan")},
                },
            },
        )
        w = ev.emit(
            "rtrace",
            phase="request",
            trace="0123456789abcdef",
            host="h1",
            priority=np.int64(0),
            attempts=np.int64(2),
            total_ms=np.float32(22.5),
            stages={
                "probe_wait": np.float32(0.1),
                "pick": 0.02,
                "connect": np.float32(0.4),
                "retry_hop": np.float64(10.0),
                "network": float("nan"),
            },
            backend_total_ms=np.float32(11.0),
            backend={
                "queue": np.float32(3.0),
                "compute": np.float64(7.5),
                "respond": float("inf"),
            },
            slowest_stage="retry_hop",
        )
        ev.close()
        with open(ev.path) as f:
            lines = [self._strict(l) for l in f if l.strip()]
        rt = lines[0]["rtrace"]
        assert rt["stages"]["retry_hop"]["p99_ms"] is None  # NaN
        assert rt["backend_stages"]["compute"]["p99_ms"] is None
        assert rt["stages"]["network"]["p99_ms"] == 3.5
        assert isinstance(rt["requests"], int) and rt["requests"] == 96
        assert rt["reconciliation"]["ok"] is True
        hw = lines[0]["host_windows"]
        assert hw["hosts"]["h0"]["stage_p99_ms"]["compute"] is None
        assert hw["hosts"]["h0"]["stage_p99_ms"]["queue"] == (
            pytest.approx(4.1, abs=1e-3)
        )
        assert hw["hosts"]["h1"]["stale"] is True
        assert isinstance(hw["hosts"]["h1"]["failures"], int)
        assert hw["merged"]["stage_p99_ms"]["compute"] is None
        wf = lines[1]
        assert wf["trace"] == "0123456789abcdef"
        assert wf["stages"]["network"] is None  # NaN -> null
        assert wf["stages"]["retry_hop"] == 10.0
        assert wf["backend"]["respond"] is None  # Inf -> null
        assert isinstance(wf["attempts"], int) and wf["attempts"] == 2
        assert wf["slowest_stage"] == "retry_hop"
        # the emit() return values match what was written
        assert s["rtrace"]["stages"]["retry_hop"]["p99_ms"] is None
        assert w["stages"]["network"] is None

    def test_resilience_kind_payloads_roundtrip(self, tmp_path):
        """The extended pod-resilience payload shapes (train/loop.py):
        coordinated checkpoint/preempt records and an elastic-resume
        restore with its topology_from/topology_to/resharded lineage —
        with adversarial values in the numeric slots. A NaN schedule
        scalar must land as null, numpy bools/ints must unwrap, and the
        nested topology dicts must survive strict parsing."""
        ev = EventWriter(str(tmp_path))
        c = ev.emit(
            "checkpoint",
            reason="preempt",
            epoch=np.int64(1),
            step_in_epoch=3,
            lr_step=np.int64(7),
            ede_t=np.float32(0.01),
            ede_k=float("nan"),
            kurt_gate=0.0,
            coordinated=np.bool_(True),
            path="/runs/a/checkpoint",
            seconds=np.float32(0.4),
        )
        p = ev.emit(
            "preempt",
            signum=np.int64(15),
            epoch=1,
            step_in_epoch=np.int64(3),
            saved=True,
            coordinated=np.bool_(True),
            coordination_step=np.int64(3),
        )
        r = ev.emit(
            "restore",
            source="/runs/a/checkpoint",
            format="orbax",
            fallback=False,
            integrity="ok",
            epoch=0,
            step_in_epoch=3,
            lr_step=3,
            ede_t=np.float32("inf"),
            ede_k=100.0,
            kurt_gate=0.0,
            topology_from={
                "processes": np.int64(2),
                "devices": np.int64(4),
                "mesh": {"data": np.int64(4), "model": 1},
            },
            topology_to={"processes": 1, "devices": 8,
                         "mesh": {"data": 8, "model": 1}},
            resharded=np.bool_(True),
            restored=["params", "batch_stats"],
            not_restored=[],
        )
        ev.close()
        with open(ev.path) as f:
            lines = [self._strict(l) for l in f if l.strip()]
        assert lines[0]["kind"] == "checkpoint"
        assert lines[0]["coordinated"] is True
        assert lines[0]["ede_k"] is None  # NaN -> null, never a token
        assert isinstance(lines[0]["lr_step"], int)
        assert lines[1]["kind"] == "preempt"
        assert lines[1]["signum"] == 15
        assert lines[1]["coordination_step"] == 3
        assert lines[1]["coordinated"] is True
        assert lines[2]["kind"] == "restore"
        assert lines[2]["ede_t"] is None  # Inf -> null
        assert lines[2]["resharded"] is True
        assert lines[2]["topology_from"] == {
            "processes": 2, "devices": 4, "mesh": {"data": 4, "model": 1},
        }
        assert isinstance(
            lines[2]["topology_from"]["mesh"]["data"], int
        )
        # the emit() return values match what was written
        assert c["ede_k"] is None and p["signum"] == 15
        assert r["topology_to"]["devices"] == 8

    def test_analysis_kind_payload_roundtrips(self, tmp_path):
        """The static analyzer's ``analysis`` payload shape (cli.py
        ``check --events-into``) with adversarial values in the numeric
        slots: numpy counters must unwrap, a NaN smuggled into a count
        must land as null, and the by_checker dict + finding-record
        list must survive strict parsing."""
        ev = EventWriter(str(tmp_path))
        a = ev.emit(
            "analysis",
            verdict="findings",
            checkers=["lock-discipline", "jit-purity",
                      "event-schema", "verdict-coherence"],
            files_scanned=np.int64(65),
            findings=np.int64(2),
            suppressed=1,
            by_checker={
                "lock-discipline": np.int64(2),
                "jit-purity": 0,
                "event-schema": np.int64(0),
                "verdict-coherence": float("nan"),
            },
            records=[
                "bdbnn_tpu/serve/pool.py:181:lock-discipline:write of "
                "guarded attribute self._thread outside "
                "'with self._lock'",
            ],
        )
        ev.close()
        with open(ev.path) as f:
            rec = self._strict(f.read().strip())
        assert rec["kind"] == "analysis"
        assert rec["files_scanned"] == 65
        assert isinstance(rec["files_scanned"], int)
        assert rec["by_checker"]["lock-discipline"] == 2
        assert rec["by_checker"]["verdict-coherence"] is None  # NaN
        assert rec["records"][0].endswith("'with self._lock'")
        # the emit() return value matches what was written
        assert a["findings"] == 2 and a["suppressed"] == 1

    def test_search_trial_kind_payloads_roundtrip(self, tmp_path):
        """The recipe-search payload shapes (bdbnn_tpu/search/
        harness.py) with adversarial values in the numeric slots: a
        NaN best_top1 must land as null, numpy counters must unwrap,
        and the nested leaderboard structures (ranking rows, winner
        block, per-trial table) must survive strict parsing."""
        ev = EventWriter(str(tmp_path))
        ev.emit(
            "search",
            phase="start",
            trials_total=np.int64(3),
            completed=0,
            families=["ste", "proximal:delta1=0.25", "stochastic"],
            workers=np.int64(2),
            config_hash="abc123",
        )
        ev.emit(
            "trial",
            phase="done",
            trial="t000_ste_lr0.1",
            family="ste",
            lr=np.float64(0.1),
            best_top1=float("nan"),
            final_top1=np.float32(12.5),
            wall_s=np.float64(3.0),
            run_dir="/tmp/sweep/trials/t000",
        )
        ev.emit(
            "trial",
            phase="failed",
            trial="t001_ede_lr0.1",
            family="ede",
            lr=0.1,
            rc=np.int64(-9),
            run_dir=None,
        )
        ev.emit(
            "search",
            phase="verdict",
            search_verdict=1,
            trials_total=3,
            completed=np.int64(2),
            failed=1,
            common_acc_level=np.float32(12.5),
            ranking=[
                {"rank": 1, "trial": "t000", "family": "ste",
                 "lr": np.float64(0.1),
                 "best_top1": np.float32(12.5),
                 "final_top1": float("inf")},
            ],
            winner={
                "trial": "t000", "family": "ste", "lr": 0.1,
                "best_top1": 12.5,
                "time_to_common_acc_s": float("nan"),
                "run_dir": "/tmp/sweep/trials/t000",
            },
            trials={
                "t000": {"status": "done",
                         "attempts": np.int64(2),
                         "resumed": np.bool_(True),
                         "alerts_critical": 0},
            },
        )
        ev.close()
        with open(ev.path) as f:
            recs = [self._strict(l) for l in f if l.strip()]
        start, done, failed, verdict = recs
        assert start["workers"] == 2 and isinstance(start["workers"], int)
        assert done["best_top1"] is None  # NaN -> null
        assert done["final_top1"] == 12.5
        assert isinstance(done["final_top1"], float)
        assert failed["rc"] == -9 and isinstance(failed["rc"], int)
        assert verdict["ranking"][0]["final_top1"] is None  # Inf -> null
        assert verdict["ranking"][0]["best_top1"] == 12.5
        assert verdict["winner"]["time_to_common_acc_s"] is None
        assert verdict["trials"]["t000"]["resumed"] is True
        assert verdict["trials"]["t000"]["attempts"] == 2
        assert verdict["completed"] == 2

    def test_health_kind_payloads_roundtrip(self, tmp_path):
        """The real alert/health payload shapes the monitor emits
        (obs/health.py), with adversarial values in the numeric slots:
        a NaN detector value must land as null, and the by_detector
        dict must survive numpy counts."""
        ev = EventWriter(str(tmp_path))
        a = ev.emit(
            "alert",
            detector="flip_collapse",
            severity="critical",
            epoch=np.int64(3),
            step=40,
            value=float("nan"),
            threshold=np.float32(1e-5),
            message="mean sign-flip rate nan/step < 1e-05",
        )
        h = ev.emit(
            "health",
            intervals=100,
            alerts_total=np.int64(2),
            alerts_critical=1,
            by_detector={"flip_collapse": np.int64(1),
                         "loss_spike": 1},
        )
        ev.close()
        with open(ev.path) as f:
            lines = [self._strict(l) for l in f if l.strip()]
        assert lines[0]["kind"] == "alert"
        assert lines[0]["value"] is None  # NaN -> null, never a token
        assert lines[0]["threshold"] == pytest.approx(1e-5)
        assert isinstance(lines[0]["epoch"], int)
        assert lines[1]["by_detector"] == {"flip_collapse": 1,
                                           "loss_spike": 1}
        # the emit() return values match what was written
        assert a["value"] is None and h["alerts_total"] == 2


class TestRotation:
    """Size-aware rotation (events.jsonl -> events.<N>.jsonl): a
    multi-day run's channel is bounded per segment, and every reader
    sees one continuous timeline through the rotation-transparent
    loader."""

    def test_writer_rotates_and_reader_reassembles(self, tmp_path):
        w = EventWriter(str(tmp_path), max_bytes=400)
        for i in range(30):
            w.emit("train_interval", step=i, filler="x" * 64)
        w.close()
        names = sorted(os.listdir(tmp_path))
        assert "events.jsonl" in names
        rotated = [n for n in names if n not in ("events.jsonl",)]
        assert rotated, "cap crossed but nothing rotated"
        assert all(n.startswith("events.") and n.endswith(".jsonl")
                   for n in rotated)
        # one continuous, ordered timeline across segments
        recs = read_events(str(tmp_path))
        assert [r["step"] for r in recs] == list(range(30))
        # load_events is the same rotation-transparent loader
        assert load_events(str(tmp_path)) == recs
        # kind filter still applies across segments
        assert len(read_events(str(tmp_path), "train_interval")) == 30

    def test_rotation_numeric_order_past_ten(self, tmp_path):
        """Segment 10 must sort after segment 2 (numeric, not
        lexicographic)."""
        w = EventWriter(str(tmp_path), max_bytes=1)  # rotate every emit
        for i in range(12):
            w.emit("epoch", epoch=i)
        w.close()
        recs = read_events(str(tmp_path))
        assert [r["epoch"] for r in recs] == list(range(12))

    def test_unbounded_by_default(self, tmp_path):
        w = EventWriter(str(tmp_path))
        for i in range(50):
            w.emit("epoch", epoch=i, filler="y" * 256)
        w.close()
        assert sorted(os.listdir(tmp_path)) == ["events.jsonl"]

    def test_reopen_appends_to_live_segment(self, tmp_path):
        """A resumed run (new EventWriter on the same dir) continues
        the live segment and the rotation index sequence."""
        w = EventWriter(str(tmp_path), max_bytes=300)
        for i in range(10):
            w.emit("epoch", epoch=i, filler="z" * 64)
        w.close()
        w2 = EventWriter(str(tmp_path), max_bytes=300)
        for i in range(10, 20):
            w2.emit("epoch", epoch=i, filler="z" * 64)
        w2.close()
        recs = read_events(str(tmp_path))
        assert [r["epoch"] for r in recs] == list(range(20))
