"""Model-family tests: conv inventory, shapes, gradient flow.

The reference has no tests (SURVEY.md §4); the conv-count assertions
here pin the behavioral constraints recovered from its call sites —
a ResNet-18 with 20 convs whose ``all_convs[1:]`` selector yields the
19 kurtosis-hooked layers matching the hard-coded ``--diffkurt``
tables (reference ``train.py:390-393, 467-475``).
"""

import jax
import jax.numpy as jnp
import pytest

from bdbnn_tpu.models import (
    conv_weight_paths,
    create_model,
    get_by_path,
    list_models,
    module_path_str,
)


def _init(model, hw, train=False):
    x = jnp.zeros((1, hw, hw, 3))
    return model.init(jax.random.PRNGKey(0), x, train=train)


class TestConvInventory:
    def test_resnet18_has_20_convs_19_hooked(self):
        # flagship constraint: 20 convs, all_convs[1:] == 19 hooked
        m = create_model("resnet18", "cifar10")
        v = _init(m, 32)
        paths = conv_weight_paths(v["params"])
        assert len(paths) == 20
        hooked = paths[1:]
        assert len(hooked) == 19
        # stem is first and is a full-precision 'weight' (not binarized)
        assert paths[0][-1] == "weight"
        # all hooked convs carry latent FP master weights, QAT-named
        assert all(p[-1] == "float_weight" for p in hooked)

    def test_conv_ordering_matches_torch_named_parameters(self):
        m = create_model("resnet18", "imagenet")
        v = _init(m, 64)
        names = [module_path_str(p) for p in conv_weight_paths(v["params"])]
        assert names[0] == "conv1"
        # within a downsampling block: conv1 < conv2 < downsample_conv
        i = names.index("layer2_0.conv1")
        assert names[i : i + 3] == [
            "layer2_0.conv1",
            "layer2_0.conv2",
            "layer2_0.downsample_conv",
        ]
        # per-stage conv counts reproduce the 19-entry diffkurt grouping:
        # layer1: 4, layers 2-4: 5 each (SURVEY.md §0.2)
        counts = {}
        for n in names[1:]:
            counts[n.split("_")[0]] = counts.get(n.split("_")[0], 0) + 1
        assert counts == {"layer1": 4, "layer2": 5, "layer3": 5, "layer4": 5}

    def test_teacher_student_paths_align(self):
        ms = create_model("resnet18", "cifar10")
        mt = create_model("resnet18_float", "cifar10")
        vs = _init(ms, 32)
        vt = _init(mt, 32)
        sp = [module_path_str(p) for p in conv_weight_paths(vs["params"])]
        tp = [module_path_str(p) for p in conv_weight_paths(vt["params"])]
        assert sp == tp  # name-equal pairing (↔ KD_loss name matching)

    def test_matched_shapes(self):
        ms = create_model("resnet18", "cifar10")
        mt = create_model("resnet18_float", "cifar10")
        vs, vt = _init(ms, 32), _init(mt, 32)
        for p_s, p_t in zip(
            conv_weight_paths(vs["params"]), conv_weight_paths(vt["params"])
        ):
            ws = get_by_path(vs["params"], p_s)
            wt = get_by_path(vt["params"], p_t)
            assert ws.shape == wt.shape, (p_s, p_t)


class TestForward:
    @pytest.mark.parametrize(
        "arch,dataset,hw,classes",
        [
            ("resnet20", "cifar10", 32, 10),
            ("resnet18", "cifar10", 32, 10),
            ("resnet20_react", "cifar10", 32, 10),
            ("resnet20", "cifar100", 32, 100),
            ("vgg_small", "cifar10", 32, 10),
            ("resnet18", "imagenet", 64, 1000),
            ("resnet18_step2", "imagenet", 64, 1000),
        ],
    )
    def test_output_shape(self, arch, dataset, hw, classes):
        m = create_model(arch, dataset)
        v = _init(m, hw)
        out = m.apply(v, jnp.ones((2, hw, hw, 3)), train=False)
        assert out.shape == (2, classes)
        assert jnp.all(jnp.isfinite(out))

    def test_train_mode_updates_batch_stats(self):
        m = create_model("resnet20", "cifar10")
        v = _init(m, 32, train=True)
        _, upd = m.apply(
            v, jnp.ones((2, 32, 32, 3)), train=True, mutable=["batch_stats"]
        )
        leaves = jax.tree_util.tree_leaves(upd["batch_stats"])
        assert leaves
        # running stats moved off their init values
        assert any(float(jnp.abs(l).sum()) > 0 for l in leaves)

    def test_outputs_depend_on_binarized_weights_sign_only(self):
        """Scaling a latent weight by a positive constant rescales only
        via the magnitude term; flipping signs changes the output — the
        ±alpha algebra of binarized convs."""
        m = create_model("resnet20", "cifar10")
        v = _init(m, 32)
        x = jax.random.normal(jax.random.PRNGKey(1), (1, 32, 32, 3))
        out0 = m.apply(v, x, train=False)
        flipped = jax.tree_util.tree_map(lambda w: w, v["params"])
        w = get_by_path(flipped, ("layer1_0", "conv1", "float_weight"))
        flipped["layer1_0"]["conv1"]["float_weight"] = -w
        out1 = m.apply({**v, "params": flipped}, x, train=False)
        assert not jnp.allclose(out0, out1)


class TestGradFlow:
    def test_grads_reach_latent_weights(self):
        m = create_model("resnet20", "cifar10")
        v = _init(m, 32, train=True)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 32, 32, 3))
        y = jnp.array([0, 1])

        def loss_fn(params):
            logits, _ = m.apply(
                {**v, "params": params},
                x,
                train=True,
                mutable=["batch_stats"],
            )
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=-1))

        grads = jax.grad(loss_fn)(v["params"])
        for p in conv_weight_paths(v["params"]):
            g = get_by_path(grads, p)
            assert float(jnp.abs(g).sum()) > 0, f"zero grad at {p}"

    def test_ede_tk_changes_grads_not_forward(self):
        m = create_model("resnet20", "cifar10")
        v = _init(m, 32)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 32, 32, 3))

        def out_sum(params, tk):
            return jnp.sum(m.apply({**v, "params": params}, x, train=False, tk=tk))

        tk_soft = (jnp.float32(0.01), jnp.float32(100.0))
        tk_sharp = (jnp.float32(10.0), jnp.float32(1.0))
        assert jnp.allclose(
            out_sum(v["params"], tk_soft), out_sum(v["params"], tk_sharp)
        )
        g_soft = jax.grad(out_sum)(v["params"], tk_soft)
        g_sharp = jax.grad(out_sum)(v["params"], tk_sharp)
        ga = get_by_path(g_soft, ("layer1_0", "conv1", "float_weight"))
        gb = get_by_path(g_sharp, ("layer1_0", "conv1", "float_weight"))
        assert not jnp.allclose(ga, gb)


class TestMixedPrecision:
    def test_bf16_params_stay_f32_logits_f32(self):
        m = create_model("resnet20", "cifar10", dtype="bfloat16")
        v = _init(m, 32)
        # master params stay f32 (mixed-precision contract)
        for leaf in jax.tree_util.tree_leaves(v["params"]):
            assert leaf.dtype == jnp.float32, leaf.dtype
        out = m.apply(v, jnp.ones((2, 32, 32, 3)), train=False)
        assert out.dtype == jnp.float32  # logits upcast for stable CE
        assert jnp.all(jnp.isfinite(out))

    def test_bf16_close_to_f32_on_float_twin(self):
        # closeness is asserted on the CONTINUOUS float variant: in the
        # binary variants any activation within bf16-epsilon of 0 flips
        # its sign() between precisions (same chaos as cross-sharding
        # comparisons, see test_parallel._float_model)
        m32 = create_model("resnet20_float", "cifar10")
        m16 = create_model("resnet20_float", "cifar10", dtype="bfloat16")
        v = _init(m32, 32)
        x = jax.random.normal(jax.random.PRNGKey(2), (4, 32, 32, 3))
        o32 = m32.apply(v, x, train=False)
        o16 = m16.apply(v, x, train=False)  # same params, bf16 compute
        # bf16 carries ~8 mantissa bits; through 20 layers the logit
        # error is O(0.1) — this is an order-of-magnitude sanity bound
        assert float(jnp.max(jnp.abs(o32 - o16))) < 0.3

    # tier-1 budget (PR 7 rebalance): the memorization e2e trains bf16
    # END TO END in tier-1 and asserts it actually learns (>0.85 top-1,
    # test_memorize.py) — strictly stronger than finite-and-updates —
    # and the f32-master-param contract keeps its own cheap pin above,
    # so this one-step smoke rides the slow tier
    @pytest.mark.slow
    def test_bf16_train_step_finite_and_updates(self):
        from bdbnn_tpu.train import (
            StepConfig,
            TrainState,
            make_optimizer,
            make_train_step,
        )

        m = create_model("resnet20", "cifar10", dtype="bfloat16")
        v = _init(m, 32, train=True)
        tx = make_optimizer(
            v["params"], dataset="cifar10", lr=0.05,
            epochs=10, steps_per_epoch=100,
        )
        state = TrainState.create(v, tx)
        step = jax.jit(make_train_step(m, tx, StepConfig()))
        x = jax.random.normal(jax.random.PRNGKey(0), (8, 32, 32, 3))
        y = jnp.arange(8) % 10
        tk = (jnp.float32(1.0), jnp.float32(1.0))
        state2, metrics = step(state, (x, y), tk, jnp.float32(0.0))
        assert jnp.isfinite(metrics["loss"])
        # grads flowed and params (still f32) moved
        moved = any(
            not jnp.allclose(a, b)
            for a, b in zip(
                jax.tree_util.tree_leaves(state.params),
                jax.tree_util.tree_leaves(state2.params),
            )
        )
        assert moved
        for leaf in jax.tree_util.tree_leaves(state2.params):
            assert leaf.dtype == jnp.float32


def test_registry_lists_and_rejects():
    assert "resnet18" in list_models("cifar10")
    assert "resnet34_react" in list_models("imagenet")
    with pytest.raises(ValueError):
        create_model("resnet999", "cifar10")
    with pytest.raises(ValueError):
        create_model("resnet18", "mnist")


class TestTwoBlock:
    """--twoblock (ref train.py:143-144): odd blocks swap to the partner
    binary variant — react blocks carry RPReLU/shift params, step2
    blocks don't."""

    def _block_kinds(self, params):
        kinds = {}
        for name, sub in params.items():
            if not name.startswith("layer"):
                continue
            has_react = any("act1" in k or "shift" in k for k in sub)
            kinds[name] = "react" if has_react else "plain"
        return kinds

    def test_alternates_block_types(self):
        model = create_model("resnet18", "imagenet", twoblock=True)
        variables = _init(model, 32, train=False)
        kinds = self._block_kinds(variables["params"])
        # 8 blocks: even positions react (imagenet default), odd step2
        order = sorted(kinds, key=lambda n: (int(n[5]), int(n[7:])))
        expected = ["react" if i % 2 == 0 else "plain" for i in range(8)]
        assert [kinds[n] for n in order] == expected, kinds

    def test_same_conv_inventory_and_forward(self):
        model = create_model("resnet18", "imagenet", twoblock=True)
        variables = _init(model, 64, train=False)
        # the 20-conv / 19-hooked flagship constraint is variant-blind
        assert len(conv_weight_paths(variables["params"])) == 20
        out = model.apply(variables, jnp.zeros((2, 64, 64, 3)), train=False)
        assert out.shape == (2, 1000)

    def test_float_twin_ignores_twoblock(self):
        a = create_model("resnet18_float", "cifar10")
        b = create_model("resnet18_float", "cifar10", twoblock=True)
        va, vb = _init(a, 32), _init(b, 32)
        assert jax.tree_util.tree_structure(va) == jax.tree_util.tree_structure(vb)

    def test_vgg_rejects_twoblock(self):
        with pytest.raises(ValueError):
            create_model("vgg_small", "cifar10", twoblock=True)


class TestRemat:
    """--remat (jax.checkpoint over residual blocks): must be a
    numerical IDENTITY up to float32 recompute reassociation (the
    checkpointed backward re-executes blocks under different fusion, so
    last-ulp differences accumulate; observed max rel diff ~1e-5 over
    20 layers) — while storing O(depth) fewer activations."""

    def test_remat_is_identity_for_loss_and_grads(self):
        import numpy as np

        x = jnp.asarray(
            np.random.default_rng(0).normal(size=(4, 32, 32, 3)),
            jnp.float32,
        )
        tk = (jnp.float32(1.2), jnp.float32(3.0))
        plain = create_model("resnet20", "cifar10")
        rem = create_model("resnet20", "cifar10", remat=True)
        v = plain.init(jax.random.PRNGKey(0), x[:1], train=True)

        def loss_fn(model, params):
            out, upd = model.apply(
                {"params": params, "batch_stats": v["batch_stats"]},
                x, train=True, tk=tk, mutable=["batch_stats"],
            )
            return jnp.mean(out**2), upd

        (l0, u0), g0 = jax.value_and_grad(
            lambda p: loss_fn(plain, p), has_aux=True
        )(v["params"])
        (l1, u1), g1 = jax.value_and_grad(
            lambda p: loss_fn(rem, p), has_aux=True
        )(v["params"])
        assert jnp.allclose(l0, l1, rtol=1e-6)

        def close(a, b):
            # per-leaf scale-relative tolerance: recompute reassociation
            # leaves small elements of a leaf with unbounded RELATIVE
            # error when siblings are 1000x larger (cancellation), so
            # atol keys on the leaf's own magnitude
            scale = float(np.max(np.abs(b))) or 1.0
            np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-4 * scale)

        jax.tree_util.tree_map(close, g0, g1)
        jax.tree_util.tree_map(close, u0, u1)

    def test_remat_param_structure_unchanged(self):
        """Checkpoints/teachers must load identically: remat cannot
        change module naming or shapes."""
        a = _init(create_model("resnet20", "cifar10"), 32)
        b = _init(create_model("resnet20", "cifar10", remat=True), 32)
        assert jax.tree_util.tree_structure(a) == jax.tree_util.tree_structure(b)

    def test_vgg_rejects_remat(self):
        with pytest.raises(ValueError, match="remat"):
            create_model("vgg_small", "cifar10", remat=True)


class TestRegistrySurface:
    """The documented deviation from the reference's open torchvision
    namespace (MIGRATION.md 'Deliberate deviations'; ref
    train.py:283-288): unknown arch names fail fast and the error
    names every valid arch so migration is one read."""

    def test_unknown_arch_error_lists_all_models(self):
        from bdbnn_tpu.models.registry import create_model, list_models

        with pytest.raises(ValueError) as ei:
            create_model("densenet121", "cifar10")
        msg = str(ei.value)
        assert "densenet121" in msg
        for name in list_models("cifar10"):
            assert name in msg

    def test_unknown_imagenet_arch_error_lists_all_models(self):
        from bdbnn_tpu.models.registry import create_model, list_models

        with pytest.raises(ValueError) as ei:
            create_model("mobilenet_v2", "imagenet")
        msg = str(ei.value)
        for name in list_models("imagenet"):
            assert name in msg

    @pytest.mark.slow
    def test_bottleneck_teachers_match_torchvision_param_counts(self):
        """resnet50_float / resnet101_float are exact structural twins
        of torchvision resnet50/101 (param-for-param), so their
        checkpoints ingest strictly.

        tier-1 budget (PR 10 rebalance): initializing both bottleneck
        giants costs ~15s of pure construction; the ingest contract
        keeps denser tier-1 coverage via the torch-import strict-load
        tests and the bottleneck-is-float-only pin below, and the
        bottleneck archs' BN-fold cases already ride slow (PR 5)."""
        expected = {"resnet50_float": 25_557_032,
                    "resnet101_float": 44_549_160}
        for arch, want in expected.items():
            m = create_model(arch, "imagenet")
            v = m.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), train=False
            )
            n = sum(
                x.size for x in jax.tree_util.tree_leaves(v["params"])
            )
            assert n == want, (arch, n, want)

    def test_bottleneck_is_float_only(self):
        from bdbnn_tpu.models.resnet import BiResNet

        model = BiResNet(
            stage_sizes=(1, 1), num_classes=4, width=8, stem="cifar",
            variant="react", act="rprelu", block="bottleneck",
        )
        with pytest.raises(ValueError, match="float-teacher only"):
            model.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 8, 8, 3)), train=False
            )

    def test_every_baseline_config_arch_resolves(self):
        """BASELINE.json's five acceptance configs name these archs."""
        from bdbnn_tpu.models.registry import create_model

        for arch, dataset in (
            ("resnet20", "cifar10"),       # config 1
            ("resnet18", "cifar10"),       # config 2 student
            ("resnet18_float", "cifar10"), # config 2 teacher
            ("resnet18", "imagenet"),      # configs 3/5
            ("resnet34", "imagenet"),      # config 4 student
            ("resnet34_float", "imagenet") # config 4 teacher
        ):
            assert create_model(arch, dataset) is not None
