"""Request-path tracing tests (obs/rtrace.py + its threading through
serve/batching.py, serve/pool.py, serve/admission.py, serve/http.py
and the v4 verdict):

- trace arithmetic (stamp/add/sync) and the waterfall payload shape
- deterministic seeded sampling + always-kept slowest-K tail exemplars
- the empty-stage-window -> null pin (the hardened None-propagating
  percentile helpers from serve/loadgen.py, never a TypeError)
- THE reconciliation identity: per-request stage sums match the
  server-side end-to-end latency within tolerance, on both the
  single-engine (sync) and replica-pool (async dispatch/compute
  split) paths — no mixed-clock arithmetic anywhere in a request
- the full socket-to-socket waterfall over a real HTTP front end,
  /statsz live histograms included
- compare's stage-share gates: an injected queue-wait regression
  flips the verdict to regression (exit 3) even when the aggregate
  p99 is flat
- a `slow`-marked overhead benchmark pinning sampled tracing under
  the 2% budget on a pacing-dominated load
"""

import json
import time

import pytest

from bdbnn_tpu.obs.rtrace import (
    RECON_TOL_PCT,
    STAGES,
    RequestTracer,
    pop_future_timing,
    set_future_timing,
)
from bdbnn_tpu.serve.batching import MicroBatcher
from bdbnn_tpu.serve.loadgen import LoadGenerator, slo_verdict


class TestTraceArithmetic:
    def test_stamp_charges_gap_and_advances(self):
        tracer = RequestTracer(seed=0)
        tr = tracer.begin(1, "tenant-x")
        time.sleep(0.005)
        tr.stamp("read")
        assert tr.stages["read"] >= 4.0
        t_after_read = tr._last
        tr.stamp("admit")
        # the admit stamp only charged its own (tiny) gap
        assert tr.stages["admit"] < tr.stages["read"]
        assert tr._last >= t_after_read

    def test_add_does_not_advance_cursor(self):
        tracer = RequestTracer(seed=0)
        tr = tracer.begin(0)
        cursor = tr._last
        tr.add("dispatch", 3.5)
        tr.add("compute", 7.25)
        assert tr._last == cursor
        assert tr.stages["dispatch"] == 3.5
        assert tr.stages["compute"] == 7.25
        tr.sync()
        assert tr._last > cursor

    def test_waterfall_shape_and_stage_order(self):
        tracer = RequestTracer(seed=0)
        tr = tracer.begin(2, "t")
        tr.stamp("read")
        tr.add("compute", 1.0)
        wf = tr.waterfall()
        assert wf["priority"] == 2 and wf["tenant"] == "t"
        assert set(wf["stages"]) == {"read", "compute"}
        # stages render in canonical taxonomy order
        assert list(wf["stages"]) == [
            s for s in STAGES if s in wf["stages"]
        ]

    def test_begin_seq_is_unique_and_monotone(self):
        tracer = RequestTracer(seed=0)
        seqs = [tracer.begin(0).seq for _ in range(10)]
        assert seqs == list(range(10))


def _finish_exact(tracer, priority, stages_ms):
    """Feed one synthetic request whose end-to-end total EXACTLY
    equals its stage sum (the cursor is pinned to t0 + sum, so the
    reconciliation identity holds by construction — these tests are
    about the rollups, not the clock)."""
    tr = tracer.begin(priority)
    for stage, ms in stages_ms.items():
        tr.add(stage, ms)
    tr._last = tr.t0 + sum(stages_ms.values()) / 1000.0
    tracer.finish(tr)
    return tr


class TestSamplingAndTail:
    def test_sampling_is_deterministic_per_seed(self):
        kept_a = [
            RequestTracer(seed=7, sample_every=4)._keep(i)
            for i in range(200)
        ]
        kept_b = [
            RequestTracer(seed=7, sample_every=4)._keep(i)
            for i in range(200)
        ]
        kept_c = [
            RequestTracer(seed=8, sample_every=4)._keep(i)
            for i in range(200)
        ]
        assert kept_a == kept_b  # same seed -> same exemplar set
        assert kept_a != kept_c  # a different seed picks differently
        # the rate is roughly 1/sample_every (hash, not stride)
        assert 20 <= sum(kept_a) <= 80

    def test_sample_every_one_keeps_everything(self):
        hits = []
        tracer = RequestTracer(
            seed=0, sample_every=1, on_sample=hits.append
        )
        for _ in range(5):
            _finish_exact(tracer, 0, {"queue": 1.0, "compute": 2.0})
        assert len(hits) == 5
        assert tracer.sampled == 5

    def test_tail_keeps_slowest_k_regardless_of_sampling(self):
        # sample_every huge: nothing sampled, the tail still fills
        tracer = RequestTracer(seed=0, sample_every=10**6, tail_k=3)
        totals = [5.0, 50.0, 1.0, 30.0, 2.0, 40.0, 3.0]
        for t in totals:
            _finish_exact(tracer, 0, {"compute": t})
        att = tracer.attribution()
        tail = att["tail"]["0"]
        assert [wf["total_ms"] for wf in tail] == [50.0, 40.0, 30.0]
        assert att["sampled"] == 0  # the tail is sampling-independent

    def test_aborted_requests_stay_out_of_histograms(self):
        tracer = RequestTracer(seed=0)
        tr = tracer.begin(0)
        tr.stamp("read")
        tracer.abort(tr)
        assert tracer.aborted == 1 and tracer.finished == 0
        att = tracer.attribution()
        assert att["stages"]["read"] is None  # a 503 is not a serve


class TestEmptyStageNull:
    def test_empty_stage_window_lands_null_never_typeerror(self):
        """THE satellite pin: the verdict's stage blocks reuse the
        hardened None-propagating percentile helpers — a stage nothing
        measured (dispatch on the single-engine path; everything on a
        zero-request run) is null in strict JSON, never a crash."""
        tracer = RequestTracer(seed=0)
        # zero requests: every block null, reconciliation unjudged
        att = tracer.attribution()
        assert all(att["stages"][s] is None for s in STAGES)
        assert att["reconciliation"]["ok"] is None
        assert att["queue_share"] is None
        json.dumps(att, allow_nan=False)
        # some requests, but never a dispatch span (no pool)
        _finish_exact(tracer, 0, {"queue": 1.0, "compute": 2.0})
        att = tracer.attribution()
        assert att["stages"]["dispatch"] is None
        assert att["stages"]["queue"]["p99_ms"] == 1.0
        v = slo_verdict(
            {"submitted": 1, "completed": 1, "shed": 0, "wall_s": 1.0,
             "latencies_ms": [3.0]},
            {}, mode="open", rate=1.0, seed=0, attribution=att,
        )
        line = json.dumps(v, allow_nan=False)
        parsed = json.loads(
            line, parse_constant=lambda s: pytest.fail(f"bare {s}")
        )
        assert parsed["attribution"]["stages"]["dispatch"] is None

    def test_stats_snapshot_is_strict_json_safe(self):
        tracer = RequestTracer(seed=0)
        s = tracer.stats()
        assert s["queue_share"] is None
        json.dumps(s, allow_nan=False)


class TestReconciliationBatcher:
    """The identity over the REAL micro-batcher: stage sums match the
    measured end-to-end latency within tolerance on both runner
    shapes. All spans ride one perf_counter timeline — there is no
    cross-clock subtraction anywhere in a request."""

    def test_sync_runner_path(self):
        tracer = RequestTracer(seed=0, sample_every=1, tail_k=5)

        def runner(batch):
            time.sleep(0.02)
            return list(batch)

        b = MicroBatcher(
            runner, max_batch=8, max_queue=64, max_delay_ms=2.0
        )
        gen = LoadGenerator(
            tracer.bind(b.submit), lambda i: i,
            mode="open", requests=40, rate=400.0, seed=0,
        )
        raw = gen.run()
        assert b.drain(timeout=30.0)
        assert raw["completed"] == 40
        att = tracer.attribution()
        recon = att["reconciliation"]
        assert recon["requests"] == 40
        assert recon["ok"] is True, recon
        assert recon["mean_abs_err_pct"] <= RECON_TOL_PCT
        # the waterfall stages a batcher-only path can populate
        assert att["stages"]["queue"] is not None
        assert att["stages"]["coalesce"] is not None
        assert att["stages"]["compute"] is not None
        assert att["stages"]["dispatch"] is None  # no pool, no hop
        # per-request identity on the kept tail exemplars
        for wf in att["tail"]["0"]:
            stage_sum = sum(wf["stages"].values())
            assert stage_sum == pytest.approx(
                wf["total_ms"], rel=RECON_TOL_PCT / 100.0, abs=0.5,
            )

    def test_pool_async_path_splits_dispatch_and_compute(self):
        from bdbnn_tpu.serve.pool import ReplicaPool

        tracer = RequestTracer(seed=0, sample_every=1, tail_k=5)

        def factory(ref, dev):
            def r(payloads):
                time.sleep(0.01)
                return list(payloads)

            return r

        pool = ReplicaPool(
            factory, ["d0", "d1"], max_queue_batches=4
        )
        b = MicroBatcher(
            pool.submit, max_batch=4, max_queue=64, max_delay_ms=1.0,
            max_pending_batches=4,
        )
        gen = LoadGenerator(
            tracer.bind(b.submit), lambda i: i,
            mode="open", requests=40, rate=600.0, seed=1,
        )
        raw = gen.run()
        assert b.drain(timeout=30.0)
        assert pool.drain(timeout=30.0)
        assert raw["completed"] == 40
        att = tracer.attribution()
        # the pool path measures the dispatch hop the sync path lacks
        assert att["stages"]["dispatch"] is not None
        assert att["stages"]["compute"] is not None
        recon = att["reconciliation"]
        assert recon["ok"] is True, recon

    def test_future_timing_handoff_is_consumed_once(self):
        from concurrent.futures import Future

        fut = Future()
        set_future_timing(fut, 1.5, 2.5)
        assert pop_future_timing(fut) == (1.5, 2.5)
        assert pop_future_timing(fut) is None  # consumed, not sticky


class TestHttpWaterfall:
    """The full socket-to-socket lifecycle over a REAL front end:
    read/admit/queue/coalesce/compute/respond all populated, /statsz
    mirrors the live histograms, the per-priority decomposition
    reconciles with the server-side end-to-end latency."""

    def _drive(self, fe, n, priorities=(0, 1)):
        import http.client

        conn = http.client.HTTPConnection(
            fe.host, fe.port, timeout=30
        )
        for i in range(n):
            conn.request(
                "POST", "/v1/predict",
                body=json.dumps([i]).encode(),
                headers={
                    "x-priority": str(priorities[i % len(priorities)]),
                    "x-tenant": "tenant-a",
                },
            )
            r = conn.getresponse()
            assert r.status == 200, r.read()
            r.read()
        conn.request("GET", "/statsz")
        statsz = json.loads(conn.getresponse().read())
        conn.close()
        return statsz

    def test_full_waterfall_and_statsz(self, http_frontend):
        samples = []
        tracer = RequestTracer(
            seed=0, sample_every=1, tail_k=3, on_sample=samples.append
        )

        def runner(batch):
            time.sleep(0.01)
            return list(batch)

        fe = http_frontend(runner, tracer=tracer, max_delay_ms=2.0)
        statsz = self._drive(fe, 14)
        # /statsz mirrors the live stage histograms
        rt = statsz["rtrace"]
        assert rt["requests"] == 14
        for stage in ("read", "admit", "queue", "coalesce",
                      "compute", "respond"):
            assert rt["stage_p99_ms"][stage] is not None, stage
        assert rt["stage_p99_ms"]["dispatch"] is None  # no pool
        assert set(rt["e2e_p99_ms_by_priority"]) == {"0", "1"}
        assert len(samples) == 14  # sample_every=1: every waterfall
        att = tracer.attribution()
        # the acceptance identity: per-priority stage decomposition
        # reconciles with the measured server-side latency within 5%
        recon = att["reconciliation"]
        assert recon["requests"] == 14
        assert recon["ok"] is True, recon
        for p in ("0", "1"):
            blocks = att["per_priority"][p]["stages"]
            for stage in ("read", "admit", "queue", "coalesce",
                          "compute", "respond"):
                assert blocks[stage] is not None, (p, stage)
        for wf in att["tail"]["0"] + att["tail"]["1"]:
            stage_sum = sum(wf["stages"].values())
            assert stage_sum == pytest.approx(
                wf["total_ms"], rel=RECON_TOL_PCT / 100.0, abs=0.5,
            )
        # both clock bases are documented in the verdict block
        assert "perf_counter" in att["clocks"]["server"]
        assert "SCHEDULED" in att["clocks"]["client"]

    def test_shed_and_rejected_requests_abort_not_pollute(
        self, http_frontend
    ):
        import http.client

        tracer = RequestTracer(seed=0, sample_every=1)
        fe = http_frontend(
            lambda batch: list(batch),
            tracer=tracer,
            default_rate=0.0, default_burst=1.0,  # 1 request, no refill
        )
        conn = http.client.HTTPConnection(fe.host, fe.port, timeout=30)
        statuses = []
        for i in range(3):
            conn.request(
                "POST", "/v1/predict", body=b"[1]",
                headers={"x-priority": "0"},
            )
            r = conn.getresponse()
            statuses.append(r.status)
            r.read()
        conn.close()
        assert statuses == [200, 429, 429]
        assert tracer.finished == 1
        assert tracer.aborted == 2  # over-quota 429s never enter stats
        att = tracer.attribution()
        assert att["per_priority"]["0"]["e2e"]["n"] == 1


def _attributed_verdict(tmp_path, name, *, queue_ms, compute_ms,
                        lat_p99=30.0, n=60):
    """A v4 verdict file whose aggregate latency is FIXED while the
    stage decomposition varies — the 'p99 flat, decomposition moved'
    construction the stage-share gates exist for."""
    tracer = RequestTracer(seed=0, sample_every=16, tail_k=3)
    for _ in range(n):
        _finish_exact(
            tracer, 0, {"queue": queue_ms, "compute": compute_ms}
        )
    lats = sorted([lat_p99 * 0.5] * (n - 1) + [lat_p99])
    v = slo_verdict(
        {"submitted": n, "completed": n, "shed": 0, "wall_s": 1.0,
         "latencies_ms": lats},
        {"mean_occupancy": 0.5, "batches": 8,
         "max_queue_depth_seen": 4, "max_queue": 64},
        mode="open", rate=100.0, seed=0,
        provenance={"recipe": {"arch": "resnet8_tiny",
                               "dataset": "cifar10"}},
        attribution=tracer.attribution(),
    )
    path = tmp_path / name
    path.write_text(json.dumps(v))
    return str(path)


class TestCompareStageGates:
    def test_queue_wait_regression_flips_exit_even_with_flat_p99(
        self, tmp_path
    ):
        """THE acceptance gate: an injected queue-wait regression (the
        decomposition moved from device-bound to queue-bound — a
        wedged worker or a shrunk replica queue looks exactly like
        this) flips compare to regression while the aggregate p99 and
        throughput hold."""
        from bdbnn_tpu.obs.compare import compare_runs

        base = _attributed_verdict(
            tmp_path, "base.json", queue_ms=2.0, compute_ms=25.0,
        )
        cand = _attributed_verdict(
            tmp_path, "cand.json", queue_ms=25.0, compute_ms=2.0,
        )
        result = compare_runs([base, cand])
        rows = {
            m["metric"]: m
            for m in result["comparisons"][0]["metrics"]
        }
        # the aggregate SLO is identical on both sides...
        assert rows["serve_p99_ms"]["verdict"] == "ok"
        assert rows["serve_throughput_rps"]["verdict"] == "ok"
        # ...but the stage decomposition regressed: exit 3
        assert rows["serve_p99_queue_ms"]["verdict"] == "regression"
        assert rows["serve_queue_share"]["verdict"] == "regression"
        assert result["verdict"] == "regression"
        # and the mirror image improves, never regresses
        back = compare_runs([cand, base])
        rows = {
            m["metric"]: m
            for m in back["comparisons"][0]["metrics"]
        }
        assert rows["serve_p99_queue_ms"]["verdict"] == "improvement"
        # (the mirror's OVERALL verdict still flags the compute-stage
        # increase — the gates are symmetric, each stage judged on its
        # own axis)
        assert rows["serve_p99_compute_ms"]["verdict"] == "regression"
        # a self-compare is clean on every stage metric
        self_cmp = compare_runs([base, base])
        assert self_cmp["verdict"] == "pass"

    def test_pre_v4_verdicts_skip_stage_metrics_cleanly(self, tmp_path):
        """v1-v3 verdicts (and traced-off v4 runs) carry no
        attribution block: the stage metrics land None on both sides
        -> no row, never a phantom verdict (pinned per the satellite)."""
        from bdbnn_tpu.obs.compare import _serve_metrics, compare_runs

        old = {
            "serve_verdict": 3,
            "p99_ms": 10.0, "throughput_rps": 100.0, "shed_rate": 0.0,
            "provenance": {"recipe": {"arch": "resnet8_tiny",
                                      "dataset": "cifar10"}},
        }
        m = _serve_metrics(old)
        assert m["serve_p99_queue_ms"] is None
        assert m["serve_p99_compute_ms"] is None
        assert m["serve_queue_share"] is None
        a = tmp_path / "old_a.json"
        b = tmp_path / "old_b.json"
        a.write_text(json.dumps(old))
        b.write_text(json.dumps(old))
        result = compare_runs([str(a), str(b)])
        judged = {
            m["metric"]
            for m in result["comparisons"][0]["metrics"]
        }
        assert "serve_p99_queue_ms" not in judged
        assert "serve_queue_share" not in judged
        assert result["verdict"] == "pass"

    def test_v4_against_v3_baseline_skips_not_crashes(self, tmp_path):
        from bdbnn_tpu.obs.compare import compare_runs

        v3 = tmp_path / "v3.json"
        v3.write_text(json.dumps({
            "serve_verdict": 3,
            "p99_ms": 30.0, "throughput_rps": 60.0, "shed_rate": 0.0,
            "provenance": {"recipe": {"arch": "resnet8_tiny",
                                      "dataset": "cifar10"}},
        }))
        v4 = _attributed_verdict(
            tmp_path, "v4.json", queue_ms=25.0, compute_ms=2.0,
        )
        result = compare_runs([str(v3), v4])
        judged = {
            m["metric"]
            for m in result["comparisons"][0]["metrics"]
        }
        # one side unknown -> the stage metrics are skipped
        assert "serve_p99_queue_ms" not in judged


class TestConsumersRenderAttribution:
    def _run_dir(self, tmp_path):
        """A serve-shaped run dir whose events carry rtrace stats and
        a v4 verdict — what watch/summarize consume."""
        from bdbnn_tpu.obs.events import EventWriter

        tracer = RequestTracer(seed=0, sample_every=1, tail_k=2)
        for _ in range(10):
            _finish_exact(
                tracer, 0, {"queue": 3.0, "compute": 9.0}
            )
        run_dir = tmp_path / "run"
        ev = EventWriter(str(run_dir))
        ev.emit("serve", phase="start", mode="open",
                arch="resnet8_tiny", buckets=[1, 8],
                queue_depth=64, requests=10)
        ev.emit("rtrace", phase="stats", **tracer.stats())
        v = slo_verdict(
            {"submitted": 10, "completed": 10, "shed": 0,
             "wall_s": 1.0, "latencies_ms": [12.0] * 10},
            {"mean_occupancy": 0.5, "batches": 2},
            mode="open", rate=10.0, seed=0,
            attribution=tracer.attribution(),
        )
        ev.emit("serve", phase="verdict", **v)
        ev.close()
        return str(run_dir)

    def test_watch_renders_live_and_final_waterfall(self, tmp_path):
        from bdbnn_tpu.obs.events import read_events
        from bdbnn_tpu.obs.watch import render_status

        run_dir = self._run_dir(tmp_path)
        events = read_events(run_dir)
        # live view (pre-verdict): the stats heartbeat waterfall
        live = render_status(
            [e for e in events
             if not (e.get("kind") == "serve"
                     and e.get("phase") == "verdict")]
        )
        assert "trace: p99/stage ms" in live
        assert "queue" in live and "compute" in live
        # final view: the verdict's attribution waterfall + slowest
        final = render_status(events)
        assert "trace: p99/stage ms" in final
        assert "slowest p0" in final
        assert "RECONCILIATION BROKEN" not in final

    def test_summarize_attribution_section(self, tmp_path):
        from bdbnn_tpu.obs.summarize import summarize_run

        run_dir = self._run_dir(tmp_path)
        text, summary = summarize_run(run_dir)
        att = summary["serving"]["verdict"]["attribution"]
        assert att["requests"] == 10
        assert att["reconciliation"]["ok"] is True
        assert "trace: 10 requests traced" in text
        assert "slowest p0" in text
        json.dumps(summary, allow_nan=False)


@pytest.mark.slow
class TestTracingOverhead:
    def test_sampled_tracing_overhead_under_budget(self):
        """The acceptance budget: sampled tracing costs < 2% of the
        serve-bench throughput verdict. End-to-end A/B throughput on a
        micro-batcher is dominated by batch-formation timing noise
        (one extra 5ms batch moves the wall more than the recorder
        ever could — measured both directions run to run), so this
        pins the budget the honest way: the recorder's measured
        per-request lifecycle cost (begin + every stage stamp + the
        finish rollup, amortized over the sampling rate) against the
        bench's measured per-request wall at the serve-bench DEFAULT
        load shape (open-loop Poisson at 100 req/s — the throughput
        verdict the budget is stated against)."""
        # 1. the bench's per-request wall at the default load shape
        def runner(batch):
            time.sleep(0.005)
            return list(batch)

        b = MicroBatcher(
            runner, max_batch=16, max_queue=256, max_delay_ms=2.0
        )
        gen = LoadGenerator(
            b.submit, lambda i: i,
            mode="open", requests=120, rate=100.0, seed=0,
        )
        raw = gen.run()
        assert b.drain(timeout=60.0)
        assert raw["completed"] == 120
        per_request_s = raw["wall_s"] / raw["completed"]

        # 2. the recorder's own per-request cost at the default
        # sampling config (the full serve-http stamp sequence)
        tracer = RequestTracer(seed=0, sample_every=16, tail_k=5)
        n = 5000
        t0 = time.perf_counter()
        for _ in range(n):
            tr = tracer.begin(0, "tenant-a")
            tr.stamp("read")
            tr.stamp("admit")
            tr.stamp("queue")
            tr.stamp("coalesce")
            tr.add("dispatch", 0.1)
            tr.add("compute", 1.0)
            tr.sync()
            tr.stamp("respond")
            tracer.finish(tr)
        cost_s = (time.perf_counter() - t0) / n
        overhead = cost_s / per_request_s
        assert overhead < 0.02, (
            f"tracing cost {cost_s * 1e6:.1f}us/request is "
            f"{overhead:.2%} of the {per_request_s * 1e3:.2f}ms "
            "bench per-request wall — over the 2% budget"
        )


# ---------------------------------------------------------------------------
# Fleet tracing (PR 16): wire format, cross-host stitching, the fleet
# metrics plane and the v7 fleet_attribution consumers
# ---------------------------------------------------------------------------


from bdbnn_tpu.obs.rtrace import (  # noqa: E402
    FLEET_STAGES,
    FleetTracer,
    HostStatsWindows,
    encode_stage_header,
    encode_trace_context,
    mint_trace_id,
    parse_stage_header,
    parse_trace_context,
)


class TestFleetWireFormat:
    def test_trace_context_round_trip(self):
        ctx = encode_trace_context("0123456789abcdef", 42, 2, "tenant-a")
        parsed = parse_trace_context(ctx)
        assert parsed == {
            "id": "0123456789abcdef", "seq": 42,
            "priority": 2, "tenant": "tenant-a",
        }

    def test_trace_context_round_trip_without_tenant(self):
        ctx = encode_trace_context("f" * 16, 0, 0, None)
        assert ";tn=" not in ctx
        parsed = parse_trace_context(ctx)
        assert parsed["tenant"] is None

    def test_encode_omits_non_token_tenant(self):
        # a tenant name that is not a safe header token is DROPPED at
        # encode time, never smuggled onto the wire
        ctx = encode_trace_context("a" * 16, 1, 1, "bad tenant;x=1")
        assert ";tn=" not in ctx
        assert parse_trace_context(ctx) is not None

    @pytest.mark.parametrize("bad", [
        None,
        "",
        "garbage",
        "v=2;id=0123456789abcdef;seq=0;p=0",        # wrong version
        "id=0123456789abcdef;seq=0;p=0",            # no version
        "v=1;id=0123456789ABCDEF;seq=0;p=0",        # uppercase hex
        "v=1;id=0123;seq=0;p=0",                    # short id
        "v=1;id=0123456789abcdef;seq=-1;p=0",       # negative seq
        "v=1;id=0123456789abcdef;seq=x;p=0",        # non-int seq
        "v=1;id=0123456789abcdef;seq=0;p=64",       # priority too big
        "v=1;id=0123456789abcdef;seq=0;p=0;tn=a b",  # bad tenant
        "v=1;id=0123456789abcdef;id=0123456789abcdef;seq=0;p=0",  # dup
        "v=1;;id=0123456789abcdef;seq=0;p=0",       # empty field
        "v=1;id=0123456789abcdef;seq=0;p=0;" + "x" * 300,  # oversized
    ])
    def test_malformed_trace_context_is_rejected(self, bad):
        assert parse_trace_context(bad) is None

    def test_stage_header_round_trip(self):
        hdr = encode_stage_header(
            "0123456789abcdef", 12.5,
            {"read": 0.25, "compute": 10.0, "respond": 2.25},
        )
        parsed = parse_stage_header(hdr)
        assert parsed["id"] == "0123456789abcdef"
        assert parsed["total_ms"] == 12.5
        assert parsed["stages"] == {
            "read": 0.25, "compute": 10.0, "respond": 2.25,
        }

    def test_stage_header_encode_drops_nonfinite_and_negative(self):
        hdr = encode_stage_header(
            "a" * 16, 5.0,
            {"read": float("nan"), "compute": 5.0, "respond": -1.0},
        )
        parsed = parse_stage_header(hdr)
        assert parsed["stages"] == {"compute": 5.0}

    @pytest.mark.parametrize("bad", [
        None,
        "",
        "garbage",
        "v=1;id=0123456789abcdef;total=nan;read=1.0",
        "v=1;id=0123456789abcdef;total=-1.0;read=1.0",
        "v=1;id=0123456789abcdef;total=5.0;bogus_stage=1.0",
        "v=1;id=0123456789abcdef;total=5.0;read=inf",
        "v=1;id=0123456789abcdef;total=5.0;read=-1.0",
        "v=1;id=zzzz;total=5.0;read=1.0",
        "v=1;id=0123456789abcdef;total=5.0;read=1.0;" + "y" * 1100,
    ])
    def test_malformed_stage_header_is_rejected(self, bad):
        assert parse_stage_header(bad) is None

    def test_mint_trace_id_is_deterministic_and_distinct(self):
        a = [mint_trace_id(7, i) for i in range(64)]
        b = [mint_trace_id(7, i) for i in range(64)]
        assert a == b
        assert len(set(a)) == 64
        assert all(len(t) == 16 for t in a)
        assert parse_trace_context(
            encode_trace_context(a[0], 0, 0, None)
        ) is not None
        assert mint_trace_id(8, 0) != mint_trace_id(7, 0)


def _fleet_finish_exact(
    tracer, priority, router_ms, backend_ms, *,
    host="h0", network_ms=1.0, attempts=1, stitch=True,
):
    """One synthetic proxied request whose cursor is pinned so the
    cross-hop identity holds EXACTLY: router stages + network +
    backend stage sum == e2e (these tests are about the rollups and
    the stitch bookkeeping, not the clock)."""
    tr = tracer.begin(priority)
    for stage, ms in router_ms.items():
        tr.add(stage, ms)
    backend_total = sum(backend_ms.values())
    hdr = encode_stage_header(
        tr.trace_id if stitch else "0" * 16, backend_total, backend_ms,
    )
    tr.attempts = attempts
    tracer.stitch(tr, backend_total + network_ms, hdr, host)
    total = sum(tr.stages.values()) + (
        backend_total if tr.backend is not None else 0.0
    )
    tr._last = tr.t0 + total / 1000.0
    tracer.finish(tr)
    return tr


class TestFleetTracerStitching:
    BACKEND = {"read": 0.5, "queue": 1.0, "compute": 6.0, "respond": 0.5}

    def test_matching_header_stitches_and_network_is_residual(self):
        tracer = FleetTracer(seed=0, sample_every=1)
        tr = _fleet_finish_exact(
            tracer, 0, {"probe_wait": 0.2, "pick": 0.1, "connect": 0.7},
            self.BACKEND, network_ms=2.5,
        )
        assert tr.backend == self.BACKEND
        assert tr.backend_total_ms == sum(self.BACKEND.values())
        # network = exchange wall - the backend's self-reported span:
        # two DURATIONS, no cross-clock subtraction anywhere
        assert tr.stages["network"] == pytest.approx(2.5, abs=1e-6)
        st = tracer.stats()
        assert st["stitched"] == 1 and st["unstitched"] == 0

    def test_mismatched_id_falls_back_to_unstitched(self):
        tracer = FleetTracer(seed=0, sample_every=1)
        tr = _fleet_finish_exact(
            tracer, 0, {"pick": 0.1}, self.BACKEND,
            network_ms=2.5, stitch=False,
        )
        assert tr.backend is None
        # the WHOLE exchange is charged to network — honest "we don't
        # know where the time went inside the host"
        assert tr.stages["network"] == pytest.approx(
            sum(self.BACKEND.values()) + 2.5, abs=1e-6,
        )
        st = tracer.stats()
        assert st["stitched"] == 0 and st["unstitched"] == 1

    def test_reconciliation_holds_and_counts_violations(self):
        tracer = FleetTracer(seed=0, sample_every=16)
        for _ in range(20):
            _fleet_finish_exact(
                tracer, 0, {"pick": 0.1, "connect": 0.5}, self.BACKEND,
            )
        att = tracer.attribution()
        recon = att["reconciliation"]
        assert recon["requests"] == 20
        assert recon["violations"] == 0
        assert recon["ok"] is True
        # now a torn request: 20ms of stage claims against a 1ms e2e
        tr = tracer.begin(0)
        tr.add("network", 20.0)
        tr._last = tr.t0 + 0.001
        tracer.finish(tr)
        recon = tracer.attribution()["reconciliation"]
        assert recon["violations"] == 1
        assert recon["ok"] is False

    def test_retry_hop_share_is_cumulative_over_e2e(self):
        tracer = FleetTracer(seed=0, sample_every=16)
        # 10 clean requests of 10ms, then 10 that burned a 10ms retry
        # hop on top of the same backend work: share = 100/300
        for _ in range(10):
            _fleet_finish_exact(
                tracer, 0, {"pick": 1.0}, {"compute": 8.0},
                network_ms=1.0,
            )
        for _ in range(10):
            _fleet_finish_exact(
                tracer, 0, {"pick": 1.0, "retry_hop": 10.0},
                {"compute": 8.0}, network_ms=1.0, attempts=2,
            )
        st = tracer.stats()
        assert st["retry_hop_share"] == pytest.approx(0.3333, abs=1e-3)
        att = tracer.attribution()
        assert att["retry_hop_share"] == pytest.approx(
            0.3333, abs=1e-3,
        )
        assert att["per_priority"]["0"]["retry_hop_share"] == (
            att["retry_hop_share"]
        )

    def test_clean_run_share_is_zero_not_none(self):
        # THE compare-gate precondition: a clean baseline publishes
        # 0.0 (a measured zero), so ANY wedged increase is a
        # regression under rel tolerance — never a silent None-skip
        tracer = FleetTracer(seed=0, sample_every=16)
        _fleet_finish_exact(tracer, 0, {"pick": 1.0}, {"compute": 8.0})
        assert tracer.stats()["retry_hop_share"] == 0.0
        assert tracer.attribution()["retry_hop_share"] == 0.0

    def test_host_stage_spread_needs_two_hosts(self):
        tracer = FleetTracer(seed=0, sample_every=16)
        for _ in range(5):
            _fleet_finish_exact(
                tracer, 0, {"pick": 0.1}, {"compute": 5.0}, host="h0",
            )
        att = tracer.attribution()
        assert att["host_stage_spread_max"] is None
        for _ in range(5):
            _fleet_finish_exact(
                tracer, 0, {"pick": 0.1}, {"compute": 10.0}, host="h1",
            )
        att = tracer.attribution()
        assert att["host_stage_spread"]["compute"] == pytest.approx(
            2.0, abs=0.01,
        )
        assert att["host_stage_spread_max"] == pytest.approx(
            2.0, abs=0.01,
        )
        assert att["per_host"]["h0"]["requests"] == 5
        assert att["per_host"]["h1"]["requests"] == 5

    def test_tail_exemplars_name_host_and_stage(self):
        tracer = FleetTracer(seed=0, sample_every=10**9, tail_k=2)
        _fleet_finish_exact(
            tracer, 0, {"pick": 0.1}, {"compute": 50.0}, host="h1",
        )
        att = tracer.attribution()
        wf = att["tail"]["0"][0]
        assert wf["host"] == "h1"
        assert wf["slowest_stage"] == "backend.compute"
        assert wf["trace"] == wf["trace"].lower()
        assert len(wf["trace"]) == 16
        assert list(wf["stages"]) == [
            s for s in FLEET_STAGES if s in wf["stages"]
        ]

    def test_stats_and_attribution_are_strict_json_safe(self):
        from bdbnn_tpu.obs.events import jsonsafe

        tracer = FleetTracer(seed=0, sample_every=1)
        _fleet_finish_exact(tracer, 1, {"pick": 0.1}, {"compute": 5.0})
        json.dumps(jsonsafe(tracer.stats()), allow_nan=False)
        json.dumps(jsonsafe(tracer.attribution()), allow_nan=False)


class TestHostStatsWindows:
    def _block(self, compute_p99=5.0):
        return {
            "stage_p99_ms": {"compute": compute_p99, "queue": 1.0},
            "e2e_p99_ms_by_priority": {"0": compute_p99 + 1.0},
            "queue_share": 0.2,
        }

    def test_record_rolls_windows_and_merges(self):
        w = HostStatsWindows(window=8, stale_after=3)
        w.record("h0", self._block(5.0))
        w.record("h1", self._block(9.0))
        snap = w.snapshot()
        assert snap["hosts_fresh"] == 2 and snap["hosts_stale"] == 0
        assert snap["hosts"]["h0"]["stage_p99_ms"]["compute"] == 5.0
        assert snap["merged"]["stage_p99_ms"]["compute"] == 9.0
        assert snap["merged"]["e2e_p99_ms_by_priority"]["0"] == 10.0

    def test_stale_after_consecutive_failures_and_excluded(self):
        w = HostStatsWindows(window=8, stale_after=2)
        w.record("h0", self._block(5.0))
        w.record("wedged", self._block(50.0))
        w.record_failure("wedged")
        assert w.snapshot()["hosts"]["wedged"]["stale"] is False
        w.record_failure("wedged")
        snap = w.snapshot()
        assert snap["hosts"]["wedged"]["stale"] is True
        assert snap["hosts"]["wedged"]["fail_streak"] == 2
        assert snap["hosts_stale"] == 1
        # the wedged host's FROZEN window is out of the merged view —
        # an autoscaler reading `merged` never acts on its numbers
        assert snap["merged"]["stage_p99_ms"]["compute"] == 5.0

    def test_success_resets_the_streak(self):
        w = HostStatsWindows(window=8, stale_after=2)
        w.record_failure("h0")
        w.record("h0", self._block())
        w.record_failure("h0")
        snap = w.snapshot()
        assert snap["hosts"]["h0"]["stale"] is False
        assert snap["hosts"]["h0"]["fail_streak"] == 1
        assert snap["hosts"]["h0"]["failures"] == 2
        assert snap["hosts"]["h0"]["scrapes"] == 1

    def test_malformed_scrape_payload_is_ignored_not_fatal(self):
        w = HostStatsWindows(window=8, stale_after=3)
        w.record("h0", {"stage_p99_ms": {"compute": float("nan"),
                                         "queue": "bogus"},
                        "e2e_p99_ms_by_priority": None})
        snap = w.snapshot()
        # nothing numeric survived: every stage window is still empty
        assert all(
            v is None
            for v in snap["hosts"]["h0"]["stage_p99_ms"].values()
        )
        json.dumps(snap, allow_nan=False)


class TestConsumersRenderFleetAttribution:
    def _fleet_run_dir(self, tmp_path, *, wedged=False):
        """A serve-fleet-shaped run dir: fleet start/stats events
        carrying the metrics plane (router windows + scraped host
        windows, one stale when wedged) and a v7 verdict with the
        fleet_attribution block."""
        from bdbnn_tpu.obs.events import EventWriter
        from bdbnn_tpu.serve.fleet import fleet_slo_verdict

        tracer = FleetTracer(seed=0, sample_every=1, tail_k=2)
        for i in range(10):
            _fleet_finish_exact(
                tracer, 0,
                {"probe_wait": 0.1, "pick": 0.1, "connect": 0.4,
                 **({"retry_hop": 30.0} if wedged and i % 2 else {})},
                {"read": 0.5, "queue": 1.0, "compute": 6.0,
                 "respond": 0.5},
                host="h%d" % (i % 2), network_ms=1.5,
                attempts=2 if wedged and i % 2 else 1,
            )
        scrape = HostStatsWindows(window=8, stale_after=2)
        scrape.record("h0", {"stage_p99_ms": {"compute": 6.0},
                             "e2e_p99_ms_by_priority": {"0": 8.0}})
        if wedged:
            scrape.record_failure("h1")
            scrape.record_failure("h1")
        else:
            scrape.record("h1", {"stage_p99_ms": {"compute": 6.5},
                                 "e2e_p99_ms_by_priority": {"0": 8.5}})
        run_dir = tmp_path / ("wedged" if wedged else "clean")
        ev = EventWriter(str(run_dir))
        ev.emit("fleet", phase="start", host="127.0.0.1", port=9000,
                hosts=["127.0.0.1:9100", "127.0.0.1:9101"],
                priorities=1, scenario="steady")
        ev.emit("fleet", phase="stats", role="fleet-router",
                draining=False, hosts_total=2, hosts_ready=2,
                inflight=0, unrouteable=0, router_shed_draining=0,
                hosts={}, swap=None, rtrace=tracer.stats(),
                host_windows=scrape.snapshot())
        lats = sorted(
            tr_ms for tr_ms in
            [10.0] * 5 + ([40.0] * 5 if wedged else [10.0] * 5)
        )
        counts = {
            "submitted": 10, "completed": 10, "failed": 0,
            "rejected": 0, "shed_draining": 0, "shed_over_quota": 0,
            "shed_queue_full": 0, "shed_unavailable": 0,
        }
        v = fleet_slo_verdict(
            {"wall_s": 1.0, "latencies_ms_by_priority": [lats],
             "counts_by_priority": [counts]},
            {"n_hosts": 2, "hosts": {}, "submitted": 10,
             "completed_total": 10, "relayed_total": 0,
             "router_unrouteable": 0, "router_shed_draining": 0,
             "retries_total": 5 if wedged else 0,
             "retry_rate": 0.5 if wedged else 0.0,
             "host_p99_spread": 1.0, "dropped": 0,
             "ledger_consistent": True, "swap": None},
            scenario="steady", rate=100.0, seed=0,
            fleet_attribution=tracer.attribution(),
        )
        ev.emit("serve", phase="verdict", **v)
        ev.close()
        return str(run_dir), v

    def test_watch_renders_fleet_waterfall_and_stale_host(
        self, tmp_path
    ):
        from bdbnn_tpu.obs.events import read_events
        from bdbnn_tpu.obs.watch import render_status

        run_dir, _ = self._fleet_run_dir(tmp_path, wedged=True)
        events = read_events(run_dir)
        live = render_status(
            [e for e in events
             if not (e.get("kind") == "serve"
                     and e.get("phase") == "verdict")]
        )
        # the live fleet waterfall + the scraped per-host table, with
        # the wedged host loudly STALE (never rendered as live data)
        assert "trace: fleet p99/stage ms" in live
        assert "retry_hop" in live
        assert "scrape: 1 fresh / 1 stale" in live
        assert "STALE" in live
        final = render_status(events)
        assert "fleet trace: p99/stage ms" in final
        assert "slowest p0" in final
        assert "CROSS-HOP RECONCILIATION BROKEN" not in final

    def test_summarize_fleet_attribution_section(self, tmp_path):
        from bdbnn_tpu.obs.summarize import summarize_run

        run_dir, v = self._fleet_run_dir(tmp_path, wedged=False)
        assert v["serve_verdict"] == 8
        text, summary = summarize_run(run_dir)
        fat = summary["serving"]["verdict"]["fleet_attribution"]
        assert fat["requests"] == 10
        assert fat["reconciliation"]["ok"] is True
        assert "fleet trace: 10 requests traced" in text
        assert "router p99/stage ms" in text
        assert "backend p99/stage ms" in text
        assert "per-host backend stage p99" in text
        assert "slowest p0" in text
        json.dumps(summary, allow_nan=False)
