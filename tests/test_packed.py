"""True 1-bit inference tests: packed weights resident on device with
fused on-the-fly unpack (nn/packed.py, serve/engine.py packed mode,
serve/pool.py ResidentModelCache, the serve-bench packed-vs-dense A/B
and the serve-http x-model multi-model path).

The load-bearing contract everywhere: packed-mode logits are BITWISE
equal to dense-mode logits — the unpack (``unpackbits -> ±1 -> *alpha``)
is exact in f32 and feeds the identical binarize+conv subgraph, and the
popcount dot computes the same exact small integers the f32 conv does.
"""

import json
import os
import threading

import numpy as np
import pytest

from bdbnn_tpu.serve.export import _file_sha256, _pack_sign, unpack_sign


# ---------------------------------------------------------------------------
# packbits round trip at odd sizes (remainder bits) — host and device
# ---------------------------------------------------------------------------


class TestPackBits:
    @pytest.mark.parametrize(
        "shape",
        [(3, 3, 3, 3), (1, 1, 5, 5), (3, 3, 8, 8), (2, 2, 1, 1)],
        ids=["81w", "25w", "576w", "4w"],
    )
    def test_host_round_trip_any_remainder(self, shape, rng):
        """packbits pads the final byte with zero bits; unpack must
        strip exactly the remainder — a flattened weight count that is
        NOT a multiple of 8 (81, 25) reconstructs bitwise."""
        w = rng.normal(size=shape).astype(np.float32)
        sign = unpack_sign(_pack_sign(w), shape)
        expect = np.where(w >= 0, 1.0, -1.0).astype(np.float32)
        np.testing.assert_array_equal(sign, expect)

    @pytest.mark.parametrize("shape", [(3, 3, 3, 3), (1, 1, 5, 5)])
    def test_device_unpack_bitwise_matches_host(self, shape, rng):
        """The jnp twin (the thing fused into the jitted forward)
        reconstructs bitwise what the host loader reconstructs — at
        odd weight counts where the remainder-bit slice matters."""
        import jax

        from bdbnn_tpu.nn.packed import (
            packed_dense_weight,
            unpack_sign_device,
        )

        w = rng.normal(size=shape).astype(np.float32)
        packed = _pack_sign(w)
        alpha = np.mean(np.abs(w), axis=(0, 1, 2)).astype(np.float32)
        host_sign = unpack_sign(packed, shape)
        dev_sign = np.asarray(
            jax.jit(lambda p: unpack_sign_device(p, shape))(packed)
        )
        np.testing.assert_array_equal(dev_sign, host_sign)
        dev_w = np.asarray(
            jax.jit(lambda p, a: packed_dense_weight(p, a, shape))(
                packed, alpha
            )
        )
        np.testing.assert_array_equal(dev_w, host_sign * alpha)


# ---------------------------------------------------------------------------
# export <-> engine round trip at odd channel counts: a hand-built
# artifact whose binary conv has 81 weights (7 remainder bits in the
# final byte) must reconstruct bitwise through BOTH loaders
# ---------------------------------------------------------------------------


def _write_mini_artifact(out_dir, tensors):
    """A minimal artifact dir in the exact export format: weights.npz
    with sign:/alpha:/dense: keys + artifact.json carrying the tensor
    index, bn_folded and the weights digest (what the loaders read)."""
    os.makedirs(out_dir, exist_ok=True)
    arrays = {}
    index = []
    for path, leaf in tensors:
        leaf = np.asarray(leaf, np.float32)
        if path.endswith("float_weight"):
            base = path.rsplit("/", 1)[0]
            arrays[f"sign:{base}"] = _pack_sign(leaf)
            arrays[f"alpha:{base}"] = np.mean(
                np.abs(leaf), axis=tuple(range(leaf.ndim - 1))
            ).astype(np.float32)
            index.append({
                "path": base,
                "kind": "binary",
                "shape": list(leaf.shape),
                "dtype": "1bit+f32alpha",
            })
        else:
            arrays[f"dense:{path}"] = leaf
            index.append({
                "path": path,
                "kind": "dense",
                "shape": list(leaf.shape),
                "dtype": "float32",
            })
    wpath = os.path.join(out_dir, "weights.npz")
    with open(wpath, "wb") as f:
        np.savez(f, **arrays)
    artifact = {
        "schema": 1,
        "tensors": index,
        "bn_folded": [],
        "weights_sha256": _file_sha256(wpath),
    }
    with open(os.path.join(out_dir, "artifact.json"), "w") as f:
        json.dump(artifact, f)
    return out_dir


class TestOddChannelRoundTrip:
    def test_loaders_reconstruct_bitwise(self, tmp_path, rng):
        """81- and 25-weight binary convs (flattened counts not
        divisible by 8) round-trip export-format -> dense loader AND
        export-format -> packed loader -> device unpack, all bitwise
        equal to sign*alpha of the original latent weights."""
        import jax

        from bdbnn_tpu.nn.packed import packed_dense_weight
        from bdbnn_tpu.serve.export import (
            load_artifact_packed,
            load_artifact_variables,
        )

        w_a = rng.normal(size=(3, 3, 3, 3)).astype(np.float32)
        w_b = rng.normal(size=(1, 1, 5, 5)).astype(np.float32)
        dense = rng.normal(size=(7,)).astype(np.float32)
        art = _write_mini_artifact(
            str(tmp_path / "art"),
            [
                ("blk/conv_odd/float_weight", w_a),
                ("blk/conv_tiny/float_weight", w_b),
                ("head/bias", dense),
            ],
        )
        expected = {
            "conv_odd": (
                np.where(w_a >= 0, 1.0, -1.0).astype(np.float32)
                * np.mean(np.abs(w_a), axis=(0, 1, 2)).astype(np.float32)
            ),
            "conv_tiny": (
                np.where(w_b >= 0, 1.0, -1.0).astype(np.float32)
                * np.mean(np.abs(w_b), axis=(0, 1, 2)).astype(np.float32)
            ),
        }

        # dense loader
        variables = load_artifact_variables(art)
        for name, want in expected.items():
            np.testing.assert_array_equal(
                variables["params"]["blk"][name]["float_weight"], want
            )
        np.testing.assert_array_equal(
            variables["params"]["head"]["bias"], dense
        )

        # packed loader + device reconstruction
        packed_vars, spec = load_artifact_packed(art)
        assert "float_weight" not in str(packed_vars["params"])
        assert {b["path"] for b in spec["binary"]} == {
            "blk/conv_odd", "blk/conv_tiny",
        }
        for name, want in expected.items():
            node = packed_vars["packed"]["blk"][name]
            got = np.asarray(
                jax.jit(
                    lambda p, a, s=want.shape: packed_dense_weight(
                        p, a, s
                    )
                )(node["sign"], node["alpha"])
            )
            np.testing.assert_array_equal(got, want)
        # the squeeze is real even at odd sizes: 81 f32 weights -> 11
        # packed bytes + 3 alphas
        row = next(
            b for b in spec["binary"] if b["path"] == "blk/conv_odd"
        )
        assert row["packed_bytes"] == 11 + 3 * 4
        assert row["dense_bytes"] == 81 * 4

    def test_torn_weights_fail_packed_loader_too(self, tmp_path, rng):
        from bdbnn_tpu.serve.export import load_artifact_packed

        art = _write_mini_artifact(
            str(tmp_path / "art"),
            [("blk/c/float_weight", rng.normal(size=(3, 3, 3, 3)))],
        )
        with open(os.path.join(art, "weights.npz"), "ab") as f:
            f.write(b"\0" * 8)
        with pytest.raises(RuntimeError, match="sha256"):
            load_artifact_packed(art)


# ---------------------------------------------------------------------------
# packed-apply bitwise equality across the registry (the acceptance
# matrix): eval_shape-seeded params, folded BN, host-packed binary
# convs — jitted packed apply must equal jitted dense apply BITWISE
# ---------------------------------------------------------------------------

# tier-1 keeps one member of every equivalence class (cifar/imagenet
# stem, plain/react/step2 variants, vgg topology); the depth/duplicate
# tail runs under `slow`, mirroring the fold-matrix split
_PACKED_CASES = [
    ("cifar10", "resnet8_tiny", []),
    ("cifar10", "resnet18_react", []),
    ("cifar10", "vgg_small", []),
    ("imagenet", "resnet18_react", []),
    ("imagenet", "resnet18_step2", []),
    ("cifar10", "resnet20", [pytest.mark.slow]),
    ("cifar10", "resnet18", [pytest.mark.slow]),
    ("cifar10", "resnet20_react", [pytest.mark.slow]),
    ("cifar10", "resnet34", [pytest.mark.slow]),
    ("imagenet", "resnet18", [pytest.mark.slow]),
    ("imagenet", "resnet34_react", [pytest.mark.slow]),
    ("imagenet", "resnet34_step2", [pytest.mark.slow]),
]


def _packed_variables(dataset, arch, seed=2):
    """(model, dense_variables, packed_variables, n_binary): fold BN,
    then pack every binary conv to the artifact representation — dense
    variables carry the reconstructed sign*alpha float_weight, packed
    variables carry the 1-bit payload in the `packed` collection and
    NO float_weight param."""
    import jax
    import jax.numpy as jnp

    from bdbnn_tpu.models.registry import create_model
    from bdbnn_tpu.models.resnet import fold_batch_norm

    model = create_model(arch, dataset)
    shapes = jax.eval_shape(
        lambda rng: model.init(
            rng, jnp.zeros((1, 16, 16, 3)), train=False
        ),
        jax.random.PRNGKey(0),
    )
    prng = np.random.default_rng(seed)
    params = jax.tree_util.tree_map(
        lambda sd: prng.normal(0, 0.1, sd.shape).astype(sd.dtype),
        shapes["params"],
    )
    stats = jax.tree_util.tree_map(
        lambda sd: np.zeros(sd.shape, sd.dtype),
        shapes.get("batch_stats", {}),
    )
    variables = fold_batch_norm({"params": params, "batch_stats": stats})

    def set_path(tree, path, leaf):
        node = tree
        for k in path[:-1]:
            node = node.setdefault(k, {})
        node[path[-1]] = leaf

    dense_params, packed_params, packed = {}, {}, {}
    n_binary = 0

    def walk(node, prefix=()):
        if isinstance(node, dict):
            for k in sorted(node):
                walk(node[k], prefix + (k,))
            return
        nonlocal n_binary
        leaf = np.asarray(node)
        if prefix[-1] == "float_weight" and leaf.ndim == 4:
            alpha = np.mean(
                np.abs(leaf.astype(np.float32)), axis=(0, 1, 2)
            ).astype(np.float32)
            pk = _pack_sign(leaf)
            sign = unpack_sign(pk, leaf.shape)
            set_path(dense_params, prefix, sign * alpha)
            set_path(packed, prefix[:-1] + ("sign",), pk)
            set_path(packed, prefix[:-1] + ("alpha",), alpha)
            n_binary += 1
        else:
            set_path(dense_params, prefix, leaf)
            set_path(packed_params, prefix, leaf)

    walk(variables["params"])
    dense_vars = {
        "params": dense_params, "batch_stats": variables["batch_stats"],
    }
    packed_vars = {
        "params": packed_params,
        "batch_stats": variables["batch_stats"],
        "packed": packed,
    }
    return model, dense_vars, packed_vars, n_binary


class TestPackedApplyBitwise:
    @pytest.mark.parametrize(
        "dataset,arch",
        [
            pytest.param(d, a, marks=marks)
            for d, a, marks in _PACKED_CASES
        ],
        ids=[f"{d}-{a}" for d, a, _ in _PACKED_CASES],
    )
    def test_packed_equals_dense_bitwise(self, dataset, arch):
        """THE acceptance pin: for every registry arch, the jitted
        packed-apply forward (1-bit resident, transient unpack) yields
        logits bitwise-equal to the jitted dense forward."""
        import jax

        model, dense_vars, packed_vars, n_binary = _packed_variables(
            dataset, arch
        )
        assert n_binary > 0, "matrix case has no binary convs"
        x = np.random.default_rng(0).normal(
            size=(2, 16, 16, 3)
        ).astype(np.float32)
        apply = lambda v, x: model.apply(v, x, train=False)
        ref = np.asarray(jax.jit(apply)(dense_vars, x))
        got = np.asarray(jax.jit(apply)(packed_vars, x))
        np.testing.assert_array_equal(got, ref)

    def test_float_arch_packed_collection_is_noop(self):
        """A float twin has no binary convs: an empty packed collection
        must change nothing (and the packed loader path stays total)."""
        import jax

        from bdbnn_tpu.models.registry import create_model
        from bdbnn_tpu.models.resnet import fold_batch_norm

        model = create_model("resnet20_float", "cifar10")
        import jax.numpy as jnp

        shapes = jax.eval_shape(
            lambda rng: model.init(
                rng, jnp.zeros((1, 16, 16, 3)), train=False
            ),
            jax.random.PRNGKey(0),
        )
        prng = np.random.default_rng(3)
        params = jax.tree_util.tree_map(
            lambda sd: prng.normal(0, 0.1, sd.shape).astype(sd.dtype),
            shapes["params"],
        )
        stats = jax.tree_util.tree_map(
            lambda sd: np.zeros(sd.shape, sd.dtype),
            shapes.get("batch_stats", {}),
        )
        variables = fold_batch_norm(
            {"params": params, "batch_stats": stats}
        )
        x = np.random.default_rng(0).normal(
            size=(1, 16, 16, 3)
        ).astype(np.float32)
        apply = lambda v, x: model.apply(v, x, train=False)
        ref = np.asarray(jax.jit(apply)(variables, x))
        got = np.asarray(
            jax.jit(apply)({**variables, "packed": {}}, x)
        )
        np.testing.assert_array_equal(got, ref)


class TestPopcountImpl:
    @pytest.mark.parametrize(
        "shape,strides",
        [
            ((3, 3, 5, 4), (1, 1)),   # odd K = 45: remainder lanes
            ((3, 3, 8, 8), (2, 2)),   # strided
            ((1, 1, 7, 3), (1, 1)),   # 1x1, odd channels
        ],
        ids=["k45", "strided", "1x1-k7"],
    )
    def test_popcount_matches_xla_conv_bitwise(self, shape, strides, rng):
        """The XNOR-popcount dot computes the exact integers the f32
        conv on ±1 operands accumulates — masked correctly through the
        zero-padding lanes — so the two paths agree BITWISE."""
        import jax

        from bdbnn_tpu.nn.kernels import binary_conv2d_mxu
        from bdbnn_tpu.nn.packed import popcount_binary_conv

        xb = np.where(
            rng.normal(size=(2, 9, 9, shape[2])) >= 0, 1.0, -1.0
        ).astype(np.float32)
        wb = np.where(
            rng.normal(size=shape) >= 0, 1.0, -1.0
        ).astype(np.float32)
        alpha = rng.uniform(0.1, 2.0, shape[-1]).astype(np.float32)
        ref = np.asarray(
            jax.jit(
                lambda x, w, a: binary_conv2d_mxu(
                    x, w, a, strides=strides
                )
            )(xb, wb, alpha)
        )
        got = np.asarray(
            jax.jit(
                lambda x, w, a: popcount_binary_conv(
                    x, w, a, strides=strides
                )
            )(xb, wb, alpha)
        )
        np.testing.assert_array_equal(got, ref)

    def test_full_model_popcount_bitwise(self):
        """resnet8_tiny end-to-end with the popcount impl bound at
        trace time: logits bitwise-equal to the dense forward."""
        import jax

        from bdbnn_tpu.nn.packed import packed_impl

        model, dense_vars, packed_vars, _ = _packed_variables(
            "cifar10", "resnet8_tiny"
        )
        x = np.random.default_rng(1).normal(
            size=(2, 16, 16, 3)
        ).astype(np.float32)
        apply = lambda v, x: model.apply(v, x, train=False)
        ref = np.asarray(jax.jit(apply)(dense_vars, x))
        with packed_impl("popcount"):
            got = np.asarray(jax.jit(apply)(packed_vars, x))
        np.testing.assert_array_equal(got, ref)

    def test_bf16_rejected(self):
        from bdbnn_tpu.nn.packed import popcount_binary_conv
        import jax.numpy as jnp

        xb = jnp.ones((1, 4, 4, 8), jnp.bfloat16)
        wb = jnp.ones((3, 3, 8, 4), jnp.bfloat16)
        with pytest.raises(ValueError, match="float32"):
            popcount_binary_conv(xb, wb, jnp.ones((4,)))

    def test_unknown_impl_rejected(self):
        from bdbnn_tpu.nn.packed import set_packed_impl

        with pytest.raises(ValueError, match="unpack"):
            set_packed_impl("int8")


# ---------------------------------------------------------------------------
# engine packed mode over the REAL exported artifact (session fixture)
# ---------------------------------------------------------------------------


class TestPackedEngine:
    def test_packed_logits_bitwise_and_residency(self, exported_artifact):
        """The engine-level round trip: a packed engine answers every
        request size (padding + chunk seam included) with logits
        bitwise-equal to the dense engine, while its resident weight
        bytes shrink >= 4x vs the dense-equivalent footprint."""
        from bdbnn_tpu.serve.engine import InferenceEngine

        art_dir, _ = exported_artifact
        dense = InferenceEngine(art_dir, buckets=(1, 4))
        packed = InferenceEngine(art_dir, buckets=(1, 4), packed=True)
        rng = np.random.default_rng(11)
        for n in (1, 3, 4, 5, 11):
            x = rng.normal(size=(n, 32, 32, 3)).astype(np.float32)
            np.testing.assert_array_equal(
                packed.predict_logits(x), dense.predict_logits(x)
            )
        r = packed.residency()
        assert r["packed"] is True
        assert r["resident_bytes"] < r["dense_equiv_bytes"]
        # the acceptance gate: >= 4x artifact-level squeeze on the
        # resident set (resnet8_tiny measures ~7x)
        assert r["ratio"] >= 4.0
        # the dense engine agrees about the counterfactual
        rd = dense.residency()
        assert rd["packed"] is False
        assert rd["resident_bytes"] == r["dense_equiv_bytes"]
        assert rd["packed_equiv_bytes"] == r["resident_bytes"]
        assert packed.time_step(bucket=4, iters=2) > 0.0

    def test_popcount_engine_bitwise(self, exported_artifact):
        from bdbnn_tpu.serve.engine import InferenceEngine

        art_dir, _ = exported_artifact
        dense = InferenceEngine(art_dir, buckets=(4,))
        pop = InferenceEngine(
            art_dir, buckets=(4,), packed=True, packed_impl="popcount"
        )
        x = np.random.default_rng(13).normal(
            size=(4, 32, 32, 3)
        ).astype(np.float32)
        np.testing.assert_array_equal(
            pop.predict_logits(x), dense.predict_logits(x)
        )

    def test_bad_packed_impl_rejected(self, exported_artifact):
        from bdbnn_tpu.serve.engine import InferenceEngine

        art_dir, _ = exported_artifact
        with pytest.raises(ValueError, match="packed_impl"):
            InferenceEngine(art_dir, buckets=(1,), packed_impl="int8")


# ---------------------------------------------------------------------------
# ResidentModelCache (no JAX: stub engines)
# ---------------------------------------------------------------------------


class _StubEngine:
    def __init__(self, key, nbytes=100):
        self.key = key
        self._nbytes = nbytes

    def residency(self):
        return {
            "resident_bytes": self._nbytes,
            "dense_equiv_bytes": self._nbytes * 8,
        }

    def predict_logits(self, batch):
        return np.full((len(batch), 2), hash(self.key) % 97, np.float32)


class TestResidentModelCache:
    def _cache(self, capacity=2, events=None):
        from bdbnn_tpu.serve.pool import ResidentModelCache

        built = []

        def loader(key):
            built.append(key)
            return _StubEngine(key)

        cache = ResidentModelCache(
            loader,
            capacity=capacity,
            device="cpu:0",
            on_event=(
                (lambda kind, **f: events.append((kind, f)))
                if events is not None else None
            ),
        )
        return cache, built

    def test_lru_eviction_order_and_accounting(self):
        events = []
        cache, built = self._cache(capacity=2, events=events)
        cache.get("a")
        cache.get("b")
        cache.get("a")          # refreshes a: LRU order is now b, a
        cache.get("c")          # evicts b (least recently used)
        assert built == ["a", "b", "c"]
        assert cache.resident_keys() == ["a", "c"]
        s = cache.stats()
        assert s["evictions"] == 1
        assert s["misses"] == 3 and s["hits"] == 1
        # byte accounting tracks what is resident NOW: the evicted
        # model's row left with its engine
        assert s["resident_bytes"] == {"a": 100, "c": 100}
        assert s["dense_equiv_bytes"]["a"] == 800
        assert "b" not in s["dense_equiv_bytes"]
        kinds = [f.get("model") for k, f in events if k == "replica"]
        assert "b" in kinds  # the eviction event names the victim
        # a reload after eviction is a miss + fresh load
        cache.get("b")
        assert built == ["a", "b", "c", "b"]
        assert cache.resident_keys() == ["c", "b"]

    def test_capacity_one_thrashes_honestly(self):
        cache, built = self._cache(capacity=1)
        cache.get("a")
        cache.get("b")
        cache.get("a")
        assert built == ["a", "b", "a"]
        assert cache.stats()["evictions"] == 2

    def test_capacity_validated(self):
        from bdbnn_tpu.serve.pool import ResidentModelCache

        with pytest.raises(ValueError, match="capacity"):
            ResidentModelCache(lambda k: None, capacity=0)

    def test_concurrent_gets_never_lose_accounting(self):
        cache, _ = self._cache(capacity=4)
        errs = []

        def worker(key):
            try:
                for _ in range(50):
                    assert cache.get(key).key == key
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [
            threading.Thread(target=worker, args=(k,))
            for k in ("a", "b", "c", "d")
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        s = cache.stats()
        assert s["hits"] + s["misses"] == 200
        assert sorted(cache.resident_keys()) == ["a", "b", "c", "d"]

    def test_resident_block_aggregation(self):
        from bdbnn_tpu.serve.pool import resident_block

        c1, _ = self._cache(capacity=2)
        c2, _ = self._cache(capacity=2)
        c1.get("default")
        c2.get("default")
        c1.get("v0002")
        block = resident_block(
            [c1, c2], completed_by_model={"default": 7, "v0002": 3}
        )
        assert block["replicas"] == 2
        assert block["models"]["default"]["completed"] == 7
        assert block["models"]["v0002"]["resident_bytes"] == 100
        assert block["models"]["default"]["dense_equiv_bytes"] == 800
        assert block["bytes_per_model_max"] == 100
        assert resident_block([]) is None


class TestRunnerFactoryMultiModel:
    def test_runner_groups_by_model_and_preserves_order(
        self, exported_artifact, tmp_path
    ):
        """The pooled runner contract for x-model routing: a mixed
        coalesced batch is answered per co-resident model and
        reassembled in arrival order — bitwise what each engine
        answers alone."""
        import shutil

        from bdbnn_tpu.serve.engine import InferenceEngine
        from bdbnn_tpu.serve.pool import make_engine_runner_factory

        art_dir, _ = exported_artifact
        art2 = str(tmp_path / "art2")
        shutil.copytree(art_dir, art2)
        factory = make_engine_runner_factory(
            (4,),
            packed=True,
            resident_models=2,
            model_dirs={"v0002": art2},
        )
        runner = factory(art_dir, None)
        assert len(factory.caches) == 1
        rng = np.random.default_rng(5)
        imgs = [
            rng.normal(size=(32, 32, 3)).astype(np.float32)
            for _ in range(5)
        ]
        keys = [None, "v0002", None, "v0002", None]
        results = runner(list(zip(keys, imgs)))
        ref = InferenceEngine(art_dir, buckets=(4,), packed=True)
        for i, img in enumerate(imgs):
            np.testing.assert_array_equal(
                results[i], ref.predict_logits(img[None])[0]
            )
        s = factory.caches[0].stats()
        assert sorted(s["resident"]) == ["default", "v0002"]

    def test_unknown_model_key_raises(self, exported_artifact):
        from bdbnn_tpu.serve.pool import make_engine_runner_factory

        art_dir, _ = exported_artifact
        factory = make_engine_runner_factory(
            (4,), packed=True, resident_models=2, model_dirs={}
        )
        runner = factory(art_dir, None)
        img = np.zeros((32, 32, 3), np.float32)
        with pytest.raises(KeyError, match="nope"):
            runner([("nope", img)])

    def test_swap_replaces_not_accumulates_device_cache(
        self, monkeypatch
    ):
        """A blue/green swap calls the factory again per device; the
        retired runner's cache must LEAVE factory.caches with it.
        Accumulating would pin the old version's engines (device
        weights never freed) and aggregate dead caches' bytes/counters
        into the verdict's resident block."""
        import bdbnn_tpu.serve.engine as engine_mod
        from bdbnn_tpu.serve.pool import (
            make_engine_runner_factory,
            resident_block,
        )

        class _FakeEngine:
            def __init__(self, path, **kw):
                self.compile_seconds = {}

            def residency(self):
                return {
                    "resident_bytes": 100,
                    "dense_equiv_bytes": 700,
                }

        monkeypatch.setattr(engine_mod, "InferenceEngine", _FakeEngine)
        factory = make_engine_runner_factory(
            (4,), packed=True, resident_models=2, model_dirs={}
        )
        # pool construction: one runner per device
        factory("artA", "dev0")
        factory("artA", "dev1")
        assert len(factory.caches) == 2
        # swap: the factory is re-invoked for the same devices with
        # the new artifact — per-device replacement, no accumulation
        factory("artB", "dev0")
        factory("artB", "dev1")
        assert len(factory.caches) == 2
        assert sorted(c.device for c in factory.caches) == [
            "dev0", "dev1",
        ]
        block = resident_block(factory.caches)
        assert block["replicas"] == 2
        # only the LIVE caches' counters ride into the verdict: each
        # post-swap cache has loaded exactly its own default engine
        assert block["loads"] == 2
        assert block["models"]["default"]["resident_bytes"] == 100


# ---------------------------------------------------------------------------
# serve-bench packed-vs-dense A/B (the memory-squeeze verdict)
# ---------------------------------------------------------------------------


class TestServeBenchPackedAB:
    def test_ab_verdict_memory_events_and_compare_metrics(
        self, exported_artifact, tmp_path
    ):
        """THE A/B acceptance: one serve-bench run drives the SAME load
        dense-then-packed; the verdict's `packed` block records a >= 4x
        resident squeeze and a measured step time on BOTH sides, the
        run dir carries before/after `memory` events, and the compare
        flattener exposes the new metric keys."""
        from bdbnn_tpu.configs.config import ServeBenchConfig
        from bdbnn_tpu.obs.compare import _serve_metrics
        from bdbnn_tpu.obs.events import read_events
        from bdbnn_tpu.serve.loadgen import run_serve_bench

        art_dir, _ = exported_artifact
        cfg = ServeBenchConfig(
            artifact=art_dir,
            log_path=str(tmp_path / "log"),
            mode="open",
            rate=400.0,
            requests=40,
            buckets=(4,),
            queue_depth=64,
            seed=0,
            packed_weights="ab",
        )
        result = run_serve_bench(cfg)
        v = result["verdict"]
        assert v["requests_failed"] == 0
        pb = v["packed"]
        assert pb["mode"] == "ab" and pb["impl"] == "unpack"
        assert pb["dense"]["step_ms"] > 0
        assert pb["packed"]["step_ms"] > 0
        assert pb["step_ms_delta_pct"] is not None
        assert (
            pb["packed"]["resident_bytes"]
            < pb["dense"]["resident_bytes"]
        )
        assert pb["resident_ratio"] >= 4.0
        # primary aggregates come from the packed pass; its resident
        # footprint is the per-model figure
        res = v["resident"]
        assert (
            res["bytes_per_model_max"] == pb["packed"]["resident_bytes"]
        )
        assert res["models"]["default"]["completed"] == 40
        # before/after memory events on one timeline
        mems = [
            e for e in read_events(result["run_dir"], "memory")
            if e.get("phase") == "serve_resident"
        ]
        assert [m["weights_mode"] for m in mems] == ["dense", "packed"]
        assert (
            mems[0]["resident_bytes"] > mems[1]["resident_bytes"]
        )
        assert mems[1]["ratio"] >= 4.0
        # the compare flattener reads both new metrics off the verdict
        flat = _serve_metrics(v)
        assert (
            flat["serve_resident_bytes_per_model"]
            == pb["packed"]["resident_bytes"]
        )
        assert flat["serve_packed_step_ms"] == pb["packed"]["step_ms"]

    def test_ab_rejects_pooled_and_paced(self, tmp_path):
        from bdbnn_tpu.configs.config import ServeBenchConfig

        with pytest.raises(ValueError, match="single-engine"):
            ServeBenchConfig(
                artifact="a", packed_weights="ab", replicas=(1, 2)
            ).validate()
        with pytest.raises(ValueError, match="single-engine"):
            ServeBenchConfig(
                artifact="a", packed_weights="ab", pace_ms=5.0
            ).validate()


# ---------------------------------------------------------------------------
# compare judges the packed metrics; older verdicts skip cleanly
# ---------------------------------------------------------------------------


def _packed_verdict_file(
    path, *, resident_bytes=None, packed_step_ms=None, schema=3
):
    """A minimal serve verdict artifact with (or without) the packed
    blocks, recipe-aligned so compare judges it."""
    v = {
        "serve_verdict": schema,
        "mode": "open",
        "p99_ms": 10.0,
        "throughput_rps": 100.0,
        "shed_rate": 0.0,
        "provenance": {
            "recipe": {"arch": "resnet8_tiny", "dataset": "cifar10"},
            "config_hash": None,
        },
    }
    if resident_bytes is not None:
        v["resident"] = {
            "capacity": 1,
            "replicas": 1,
            "models": {
                "default": {
                    "resident_bytes": resident_bytes, "completed": 10,
                }
            },
            "bytes_per_model_max": resident_bytes,
        }
    if packed_step_ms is not None:
        v["packed"] = {
            "mode": "on",
            "impl": "unpack",
            "dense": {"resident_bytes": resident_bytes, "step_ms": None},
            "packed": {
                "resident_bytes": resident_bytes,
                "step_ms": packed_step_ms,
            },
            "resident_ratio": 7.0,
            "step_ms_delta_pct": 1.0,
        }
    with open(path, "w") as f:
        json.dump(v, f)
    return str(path)


class TestComparePackedMetrics:
    def test_resident_bytes_regression_caught(self, tmp_path):
        """A change that silently re-densifies the resident set (bytes
        per model up >tol) is a regression even when latency holds."""
        from bdbnn_tpu.obs.compare import compare_runs

        base = _packed_verdict_file(
            tmp_path / "base.json",
            resident_bytes=100_000, packed_step_ms=5.0,
        )
        cand = _packed_verdict_file(
            tmp_path / "cand.json",
            resident_bytes=700_000, packed_step_ms=5.0,
        )
        result = compare_runs([base, cand])
        rows = {
            m["metric"]: m
            for m in result["comparisons"][0]["metrics"]
        }
        assert (
            rows["serve_resident_bytes_per_model"]["verdict"]
            == "regression"
        )
        assert result["verdict"] == "regression"

    def test_packed_step_ms_regression_caught(self, tmp_path):
        from bdbnn_tpu.obs.compare import compare_runs

        base = _packed_verdict_file(
            tmp_path / "base.json",
            resident_bytes=100_000, packed_step_ms=5.0,
        )
        cand = _packed_verdict_file(
            tmp_path / "cand.json",
            resident_bytes=100_000, packed_step_ms=9.0,
        )
        result = compare_runs([base, cand])
        rows = {
            m["metric"]: m
            for m in result["comparisons"][0]["metrics"]
        }
        assert rows["serve_packed_step_ms"]["verdict"] == "regression"

    def test_verdicts_without_packed_blocks_skip_cleanly(self, tmp_path):
        """v1/v2/v3-without-packed verdicts carry no resident/packed
        blocks: the new metrics must be ABSENT from the judged rows
        (skipped), never a crash or a phantom regression — pinned for
        old-vs-old and old-vs-new alike."""
        from bdbnn_tpu.obs.compare import compare_runs, extract_run

        old_a = _packed_verdict_file(tmp_path / "a.json", schema=1)
        old_b = _packed_verdict_file(tmp_path / "b.json", schema=2)
        new = _packed_verdict_file(
            tmp_path / "new.json",
            resident_bytes=100_000, packed_step_ms=5.0,
        )
        ex = extract_run(old_a)
        assert ex["metrics"]["serve_resident_bytes_per_model"] is None
        assert ex["metrics"]["serve_packed_step_ms"] is None
        for pair in ([old_a, old_b], [old_a, new]):
            result = compare_runs(pair)
            judged = {
                m["metric"]
                for m in result["comparisons"][0]["metrics"]
            }
            assert "serve_resident_bytes_per_model" not in judged
            assert "serve_packed_step_ms" not in judged
            # the aggregates still compared — skipping must not mean
            # "compared nothing"
            assert result["comparisons"][0]["verdict"] == "pass"


# ---------------------------------------------------------------------------
# THE acceptance e2e: two co-resident packed models behind a 2-replica
# serve-http, routed by x-model over real sockets, zero dropped
# ---------------------------------------------------------------------------


class TestServeHttpCoResidentModels:
    def test_two_models_routed_by_x_model_zero_dropped(
        self, exported_artifact, tmp_path
    ):
        from bdbnn_tpu.configs.config import ServeHttpConfig
        from bdbnn_tpu.serve.http import run_serve_http
        from bdbnn_tpu.serve.registry import ArtifactRegistry

        art_dir, _ = exported_artifact
        reg_root = str(tmp_path / "registry")
        reg = ArtifactRegistry(reg_root)
        assert reg.publish(art_dir)["version"] == 1
        assert reg.publish(art_dir)["version"] == 2

        cfg = ServeHttpConfig(
            artifact="v0001",
            registry=reg_root,
            log_path=str(tmp_path / "log"),
            replicas=2,
            resident_models=2,
            packed_weights=True,
            scenario="poisson",
            rate=300.0,
            requests=40,
            concurrency=8,
            buckets=(4,),
            queue_depth=64,
            models=("v0001", "v0002"),
            seed=3,
        )
        result = run_serve_http(cfg)
        v = result["verdict"]
        # the drain contract's cross-check: every request got SOME
        # response — zero dropped connections
        assert v["client"]["dropped"] == 0
        assert v["requests_failed"] == 0
        assert v["requests_completed"] == 40
        # both models served, co-resident (v0001 IS the default —
        # routed without a second copy; v0002 is the second resident)
        res = v["resident"]
        assert res["models"]["default"]["completed"] > 0
        assert res["models"]["v0002"]["completed"] > 0
        assert (
            res["models"]["default"]["completed"]
            + res["models"]["v0002"]["completed"]
            == v["requests_completed"]
        )
        # packed residency held end to end: >= 4x squeeze per model
        assert v["packed"]["resident_ratio"] >= 4.0
        # no model was ever evicted/reloaded mid-run: both stayed
        # resident on every replica (the whole point of the cache)
        assert res["evictions"] == 0
        assert res["replicas"] == 2

    def test_x_model_rejected_without_multi_model(
        self, http_frontend
    ):
        """A server not configured for multi-model must 404 an x-model
        request (ledgered as rejected), never silently answer from the
        wrong model."""
        import socket

        fe = http_frontend()
        sock = socket.create_connection((fe.host, fe.port), timeout=5)
        body = b"[1, 2]"
        sock.sendall(
            b"POST /v1/predict HTTP/1.1\r\n"
            b"host: x\r\nx-model: v0002\r\n"
            b"content-type: application/json\r\n"
            + f"content-length: {len(body)}\r\n\r\n".encode() + body
        )
        resp = sock.recv(4096).decode()
        sock.close()
        assert resp.startswith("HTTP/1.1 404")
        assert "multi-model routing disabled" in resp
        counts = fe.accounting()["counts_by_priority"]
        assert sum(c["rejected"] for c in counts) == 1
