"""Distributed tests on the 8-device CPU-simulated mesh — the
JAX-native analogue of a mock-NCCL DDP test (SURVEY.md §4).

Key property: a DP-sharded train step must be numerically equivalent to
the same step on one device with the same global batch (DDP's gradient
all-reduce == jit's psum insertion)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bdbnn_tpu.models.resnet import BiResNet
from bdbnn_tpu.parallel import (
    DATA_AXIS,
    MODEL_AXIS,
    batch_sharding,
    broadcast_host_int,
    coordinate_flags,
    create_sharded_state,
    jit_train_step,
    make_mesh,
    params_shardings,
    shard_batch,
    shard_variables,
    topology,
)
from bdbnn_tpu.train import StepConfig, TrainState, make_optimizer, make_train_step


def _model():
    return BiResNet(
        stage_sizes=(1, 1), num_classes=4, width=8,
        stem="cifar", variant="cifar", act="hardtanh",
    )


def _float_model():
    # Continuous (no sign()) twin for numerical-equivalence assertions:
    # binary nets are chaotic under reduction-order noise (any activation
    # or latent weight within float-eps of 0 flips its sign() between
    # two valid computation orders), so DP≡single-device can only be
    # asserted bitwise-tight on the float variant. The property under
    # test — GSPMD psum == full-batch gradient — is the same either way.
    return BiResNet(
        stage_sizes=(1, 1), num_classes=4, width=8,
        stem="cifar", variant="float", act="hardtanh",
    )


def _batch(n=16, hw=8, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, hw, hw, 3)).astype(np.float32)
    y = rng.integers(0, 4, size=(n,)).astype(np.int64)
    return x, y


def test_eight_cpu_devices_available():
    assert jax.device_count() == 8


class TestMesh:
    def test_pure_dp_mesh_shape(self):
        mesh = make_mesh()
        assert mesh.shape[DATA_AXIS] == 8
        assert mesh.shape[MODEL_AXIS] == 1

    def test_2d_mesh(self):
        mesh = make_mesh(model_parallel=2)
        assert mesh.shape[DATA_AXIS] == 4
        assert mesh.shape[MODEL_AXIS] == 2

    def test_indivisible_raises(self):
        with pytest.raises(ValueError):
            make_mesh(model_parallel=3)

    def test_param_shardings_pure_dp_replicated(self):
        mesh = make_mesh()
        model = _model()
        v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8, 8, 3)), train=False)
        sh = params_shardings(mesh, v["params"])
        for s in jax.tree_util.tree_leaves(
            sh, is_leaf=lambda x: hasattr(x, "spec")
        ):
            assert all(a is None for a in s.spec)

    def test_model_axis_shards_large_kernels(self):
        mesh = make_mesh(model_parallel=2)
        params = {
            "big": {"float_weight": jnp.zeros((3, 3, 256, 512))},
            "small": {"float_weight": jnp.zeros((3, 3, 8, 8))},
            "bn": {"scale": jnp.zeros((512,))},
        }
        sh = params_shardings(mesh, params)
        assert sh["big"]["float_weight"].spec[-1] == MODEL_AXIS
        assert all(a is None for a in sh["small"]["float_weight"].spec)
        assert all(a is None for a in sh["bn"]["scale"].spec)


class TestDPEquivalence:
    # Equivalence is asserted on the FLOAT model (see _float_model) over
    # two steps — the DDP-allreduce contract of reference
    # train.py:292-314, validated the GSPMD way.
    def _run_single(self, model, variables, batch, steps=2):
        tx = make_optimizer(
            variables["params"], dataset="cifar10", lr=0.05,
            epochs=10, steps_per_epoch=100,
        )
        state = TrainState.create(variables, tx)
        step = jax.jit(make_train_step(model, tx, StepConfig()))
        tk = jnp.float32(1.0), jnp.float32(1.0)
        x, y = batch
        metrics = None
        for _ in range(steps):
            state, metrics = step(
                state, (jnp.asarray(x), jnp.asarray(y)), tk, jnp.float32(0.0)
            )
        return state, metrics

    def _run_sharded(self, model, variables, batch, steps=2, model_parallel=1):
        mesh = make_mesh(model_parallel=model_parallel)
        tx = make_optimizer(
            variables["params"], dataset="cifar10", lr=0.05,
            epochs=10, steps_per_epoch=100,
        )
        state = create_sharded_state(mesh, variables, tx, TrainState)
        step = jit_train_step(make_train_step(model, tx, StepConfig()))
        tk = jnp.float32(1.0), jnp.float32(1.0)
        x, y = batch
        metrics = None
        for _ in range(steps):
            gx, gy = shard_batch(mesh, x, y)
            state, metrics = step(state, (gx, gy), tk, jnp.float32(0.0))
        return state, metrics

    def test_dp_equals_single_device(self):
        model = _float_model()
        batch = _batch(n=16)
        variables = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 8, 8, 3)), train=True
        )
        s_single, m_single = self._run_single(model, variables, batch)
        s_dp, m_dp = self._run_sharded(model, variables, batch)
        assert float(m_single["loss"]) == pytest.approx(
            float(m_dp["loss"]), rel=1e-5
        )
        for a, b in zip(
            jax.tree_util.tree_leaves(s_single.params),
            jax.tree_util.tree_leaves(s_dp.params),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5
            )

    def test_dp_plus_tp_equals_single_device(self):
        model = _float_model()
        batch = _batch(n=16, seed=4)
        variables = model.init(
            jax.random.PRNGKey(1), jnp.zeros((1, 8, 8, 3)), train=True
        )
        s_single, m_single = self._run_single(model, variables, batch)
        s_tp, m_tp = self._run_sharded(model, variables, batch, model_parallel=2)
        assert float(m_single["loss"]) == pytest.approx(
            float(m_tp["loss"]), rel=1e-5
        )
        for a, b in zip(
            jax.tree_util.tree_leaves(s_single.params),
            jax.tree_util.tree_leaves(s_tp.params),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5
            )

    def test_binary_model_trains_on_mesh(self):
        # The binary net itself can't be compared bitwise across
        # shardings (sign() chaos, see _float_model) — assert it runs
        # sharded with finite loss and updated params instead.
        model = _model()
        batch = _batch(n=16)
        variables = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 8, 8, 3)), train=True
        )
        # snapshot before running: jit donation may reuse these buffers
        before = [
            np.asarray(a)
            for a in jax.tree_util.tree_leaves(variables["params"])
        ]
        s_dp, m_dp = self._run_sharded(model, variables, batch)
        assert np.isfinite(float(m_dp["loss"]))
        after = jax.tree_util.tree_leaves(s_dp.params)
        assert any(
            not np.allclose(np.asarray(a), np.asarray(b))
            for a, b in zip(before, after)
        )

    def test_batch_is_actually_sharded(self):
        mesh = make_mesh()
        x, y = _batch(n=16)
        gx, gy = shard_batch(mesh, x, y)
        assert gx.sharding.is_equivalent_to(batch_sharding(mesh, 4), 4)
        # each device holds 1/8 of the batch
        assert gx.addressable_shards[0].data.shape[0] == 2

    def test_sharded_variables_replicated(self):
        mesh = make_mesh()
        model = _model()
        v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8, 8, 3)), train=True)
        placed = shard_variables(mesh, v)
        leaf = jax.tree_util.tree_leaves(placed["params"])[0]
        assert len(leaf.sharding.device_set) == 8


class TestCoordinationPrimitives:
    """Single-process semantics of the pod coordination layer — the
    collective (gloo) path is exercised by tests/test_pod_faults.py;
    here the contract is that one process IS its own agreement."""

    def test_coordinate_flags_identity_single_process(self):
        out = coordinate_flags((15.0, 0.0, 3.0))
        np.testing.assert_array_equal(out, np.asarray([15.0, 0.0, 3.0],
                                                      np.float32))
        assert out.dtype == np.float32

    def test_broadcast_host_int_identity_single_process(self):
        assert broadcast_host_int(1785735886) == 1785735886

    def test_topology_records_mesh_shape(self):
        topo = topology(make_mesh())
        assert topo == {
            "processes": 1,
            "devices": 8,
            "mesh": {"data": 8, "model": 1},
        }
        # without a mesh: process/device layout only (manifest extras)
        assert topology() == {"processes": 1, "devices": 8}


class TestCheckpointPolicyLeadership:
    """CheckpointPolicy's wallclock split: only the clock leader's
    wallclock may decide (process 0 on pods); the step cadence is
    deterministic and needs no leader."""

    def test_wallclock_decision_is_leader_only(self):
        from bdbnn_tpu.train.resilience import CheckpointPolicy

        now = [0.0]
        pol = CheckpointPolicy(every_mins=1.0, clock=lambda: now[0])
        pol.tick()
        now[0] = 61.0
        assert pol.due(clock_leader=True) is True
        assert pol.due(clock_leader=False) is False

    def test_step_cadence_needs_no_leader(self):
        from bdbnn_tpu.train.resilience import CheckpointPolicy

        pol = CheckpointPolicy(every_steps=2)
        pol.tick()
        assert pol.due(clock_leader=False) is False
        pol.tick()
        assert pol.due(clock_leader=False) is True
        pol.note_saved()
        assert pol.due(clock_leader=False) is False

    def test_step_wrapper_back_compat(self):
        from bdbnn_tpu.train.resilience import CheckpointPolicy

        pol = CheckpointPolicy(every_steps=3)
        assert [pol.step() for _ in range(3)] == [False, False, True]
