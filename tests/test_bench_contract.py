"""The driver-facing bench.py contract: one JSON line with
{metric, value, unit, vs_baseline} — including the dead-tunnel fallback
path, which must stay parseable and clearly labeled."""

import importlib.util
import json
import os

import pytest

_spec = importlib.util.spec_from_file_location(
    "bench", os.path.join(os.path.dirname(__file__), "..", "bench.py")
)
bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench)


class TestStaleEvidenceFallback:
    def test_fallback_carries_contract_keys_and_provenance(self):
        out = bench._stale_evidence_fallback("synthetic-error")
        assert out is not None, "profiles/r04 evidence missing"
        # the driver's parse contract
        for key in ("metric", "value", "unit", "vs_baseline"):
            assert key in out
        assert out["metric"] == bench.METRIC
        # ADVICE r4 (medium): a consumer reading ONLY the pinned
        # {metric, value, unit, vs_baseline} contract must see failure
        assert out["value"] == 0.0
        assert out["vs_baseline"] == 0.0
        assert out["fresh_run"] is False
        assert "synthetic-error" in out["error"]
        assert os.path.exists(out["evidence"])
        # the prior measurement rides along under non-contract keys
        assert out["prior_value"] > 0
        # JSON-serializable end to end
        json.loads(json.dumps(out))

    def test_fallback_prior_is_the_conservative_host_fenced_number(self):
        out = bench._stale_evidence_fallback("e")
        with open(out["evidence"]) as f:
            prof = json.load(f)
        assert out["prior_value"] == prof["host_fenced_median_img_per_sec"]
        assert out["prior_value"] <= prof["device_images_per_sec"]


class TestProbe:
    def test_probe_ok_on_explicit_cpu(self, monkeypatch):
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        ok, detail = bench._probe_backend(120.0)
        assert ok, detail
        assert detail == ""

    @pytest.mark.skipif(
        os.environ.get("BDBNN_TEST_PROBE_FAIL") != "1",
        reason="needs an environment where the default backend is dead",
    )
    def test_probe_fail_reports_detail(self):
        ok, detail = bench._probe_backend(5.0)
        assert not ok and detail
