"""Self-driving rollouts: canary stage + live-verdict auto-rollback
(bdbnn_tpu/serve/canary.py + ReplicaPool.canary_swap).

Four tiers, mirroring the serve/pool test strategy:

- **monitor tier** (pure unit, no JAX, no threads): CanaryConfig
  overrides, the seeded cohort assignment, and each detector firing
  EXACTLY its own alert on a synthetic pathological stream — plus the
  promote streak, hysteresis latching, and the inconclusive-timeout
  conservative rollback.
- **stub-pool tier**: the canary state machine over stub runners —
  cohort routing by seeded assignment, shadow mirrors excluded from
  every ledger, logit-drift detection → rollback restoring vN,
  healthy canary → promote completing the full shift, drain-mid-canary
  abort, one-rollout-at-a-time.
- **degradation tier** (satellite): the make_engine_runner_factory
  fault-injection hook — latency/error/logit-perturbation each
  observable in isolation through a real pool, and the no-injection
  zero-cost pin (disabled = the plain runner object, bitwise logits).
- **acceptance tier** (real sockets, real AOT engines): flash-crowd
  against a pooled vN, canary to a fault-injected vN+1 whose
  degradation hits ONLY priority 0 → auto-rollback from the
  per-priority window with zero client drops and ledger identity
  intact; the sibling healthy-canary run through the REAL serve-http
  orchestration auto-promotes with swap.shed == 0 and the shadow
  logit-drift probe pinned bitwise-zero between a packed vN and a
  republished-identical vN+1; injected logit perturbation flips the
  probe nonzero → rollback; and `compare` exits 3 on a doctored run
  whose canary rolled back while the aggregate p99 is unchanged.
"""

import copy
import json
import os
import shutil
import threading
import time

import numpy as np
import pytest

from bdbnn_tpu.serve.canary import (
    CANARY,
    INCONCLUSIVE,
    INCUMBENT,
    OBSERVE,
    PROMOTE,
    ROLLBACK,
    CanaryConfig,
    CanaryMonitor,
    apply_canary_overrides,
    assign_canary,
)
from bdbnn_tpu.serve.pool import (
    SWAP_DONE,
    SWAP_FAILED,
    SWAP_ROLLED_BACK,
    PoolAdmin,
    ReplicaPool,
    make_engine_runner_factory,
)


def _cfg(**kw):
    base = dict(
        eval_interval_s=0.01,
        healthy_evals=2,
        max_wait_s=5.0,
        min_samples=5,
        debounce=2,
        p99_ratio=2.0,
        p99_floor_ms=5.0,
    )
    base.update(kw)
    return CanaryConfig(**base)


def _armed(cfg=None, priorities=2, on_event=None):
    mon = CanaryMonitor(
        cfg or _cfg(), priorities=priorities, on_event=on_event
    )
    mon.arm(
        version_from="v0001", version_to="v0002", fraction=0.3,
        replicas=[1],
    )
    return mon


def _feed(mon, cohort, priority, lats):
    version = "v0002" if cohort == CANARY else "v0001"
    for lat in lats:
        mon.record_served(priority, lat, version)


# ---------------------------------------------------------------------------
# monitor tier
# ---------------------------------------------------------------------------


class TestConfigOverrides:
    def test_overrides_applied_and_typed(self):
        cfg = apply_canary_overrides(
            CanaryConfig(),
            ("p99_ratio=3.5", "min_samples=7", "debounce=1"),
        )
        assert cfg.p99_ratio == 3.5
        assert cfg.min_samples == 7 and isinstance(cfg.min_samples, int)
        assert cfg.debounce == 1

    def test_unknown_name_and_bad_value_fail_at_config_time(self):
        with pytest.raises(ValueError, match="bad --canary-threshold"):
            apply_canary_overrides(CanaryConfig(), ("nope=1",))
        with pytest.raises(ValueError, match="bad --canary-threshold"):
            apply_canary_overrides(CanaryConfig(), ("p99_ratio=abc",))
        with pytest.raises(ValueError, match="NAME=VALUE"):
            apply_canary_overrides(CanaryConfig(), ("p99_ratio",))

    def test_empty_specs_identity(self):
        cfg = CanaryConfig()
        assert apply_canary_overrides(cfg, ()) is cfg


class TestAssignment:
    def test_deterministic_and_fraction_honored(self):
        picks = [assign_canary(11, i, 0.3) for i in range(4000)]
        again = [assign_canary(11, i, 0.3) for i in range(4000)]
        assert picks == again  # pure function of (seed, seq)
        rate = sum(picks) / len(picks)
        assert 0.25 < rate < 0.35

    def test_zero_fraction_never_canary(self):
        assert not any(assign_canary(0, i, 0.0) for i in range(100))

    def test_seed_changes_assignment(self):
        a = [assign_canary(1, i, 0.5) for i in range(256)]
        b = [assign_canary(2, i, 0.5) for i in range(256)]
        assert a != b


class TestMonitorDetectors:
    def test_p99_regression_fires_exactly_p99_p0(self):
        mon = _armed()
        _feed(mon, INCUMBENT, 0, [10.0] * 20)
        _feed(mon, CANARY, 0, [100.0] * 20)
        # healthy p1 on both sides so fairness stays ineligible-or-ok
        r1 = mon.evaluate()
        assert r1["decision"] == OBSERVE  # debounce 2: first breach
        assert r1["detectors"]["p99_p0"]["breach"] is True
        r2 = mon.evaluate()
        assert r2["decision"] == ROLLBACK
        assert r2["trigger"] == "p99_p0"
        fired = [
            n for n, d in r2["detectors"].items() if d.get("fired")
        ]
        assert fired == ["p99_p0"]

    def test_absolute_floor_gates_sub_ms_noise(self):
        mon = _armed()
        _feed(mon, INCUMBENT, 0, [0.1] * 10)
        _feed(mon, CANARY, 0, [0.5] * 10)  # ratio 5, gap 0.4ms < floor
        for _ in range(4):
            res = mon.evaluate()
        assert res["decision"] in (OBSERVE, PROMOTE)
        assert res["detectors"]["p99_p0"]["breach"] is False

    def test_healthy_canary_promotes_after_clean_streak(self):
        mon = _armed()
        _feed(mon, INCUMBENT, 0, [10.0] * 10)
        _feed(mon, CANARY, 0, [11.0] * 10)
        assert mon.evaluate()["decision"] == OBSERVE
        assert mon.evaluate()["decision"] == PROMOTE  # healthy_evals=2
        # the decision latches
        assert mon.evaluate()["decision"] == PROMOTE

    def test_promote_needs_min_canary_samples(self):
        mon = _armed()
        _feed(mon, INCUMBENT, 0, [10.0] * 10)
        _feed(mon, CANARY, 0, [11.0] * 3)  # < min_samples
        for _ in range(5):
            res = mon.evaluate()
        assert res["decision"] == OBSERVE

    def test_logit_drift_zero_tolerance_no_debounce(self):
        mon = _armed()
        mon.record_drift(0.0)
        assert mon.evaluate()["decision"] == OBSERVE  # exact zero is ok
        mon.record_drift(1e-6)
        res = mon.evaluate()
        assert res["decision"] == ROLLBACK  # one sample, no debounce
        assert res["trigger"] == "logit_drift"
        assert res["detectors"]["logit_drift"]["value"] == 1e-6

    def test_incomparable_drift_is_not_a_measurement(self):
        mon = _armed()
        mon.record_drift(None)
        res = mon.evaluate()
        assert res["detectors"]["logit_drift"]["eligible"] is False

    def test_unabsorbed_from_pool_counters(self):
        mon = _armed()
        counters = {
            CANARY: {
                "assigned_batches": 20, "sheds": 6, "fallbacks": 8,
                "failed_requests": 0,
            },
            INCUMBENT: {"assigned_batches": 50, "failed_requests": 0},
        }
        assert mon.evaluate(counters)["decision"] == OBSERVE
        res = mon.evaluate(counters)
        assert res["decision"] == ROLLBACK
        assert res["trigger"] == "unabsorbed"
        assert res["detectors"]["unabsorbed"]["value"] == 0.7

    def test_error_rate_vs_incumbent(self):
        mon = _armed()
        _feed(mon, INCUMBENT, 0, [10.0] * 50)
        _feed(mon, CANARY, 0, [10.0] * 8)
        counters = {
            CANARY: {"assigned_batches": 2, "failed_requests": 4},
            INCUMBENT: {"assigned_batches": 50, "failed_requests": 0},
        }
        mon.evaluate(counters)
        res = mon.evaluate(counters)
        assert res["trigger"] == "error_rate"
        assert res["detectors"]["error_rate"]["canary_fail_rate"] == (
            pytest.approx(4 / 12)
        )

    def test_fairness_fires_on_uneven_degradation(self):
        # p0 ratio 1.9 (under p99_ratio 2 -> p99_p0 silent), p1 ratio
        # 0.5 -> max/min = 3.8 > 3: the canary reshuffles who suffers
        mon = _armed(_cfg(fairness_ratio_max=3.0))
        _feed(mon, INCUMBENT, 0, [10.0] * 10)
        _feed(mon, CANARY, 0, [19.0] * 10)
        _feed(mon, INCUMBENT, 1, [10.0] * 10)
        _feed(mon, CANARY, 1, [5.0] * 10)
        mon.evaluate()
        res = mon.evaluate()
        assert res["trigger"] == "fairness"
        assert res["detectors"]["fairness"]["value"] == pytest.approx(
            3.8
        )
        assert res["detectors"]["p99_p0"]["fired"] is False

    def test_queue_share_from_batch_splits(self):
        mon = _armed()
        for _ in range(10):
            mon.record_batch("v0001", 5.0, 95.0)   # share 0.05
            mon.record_batch("v0002", 50.0, 50.0)  # share 0.50
        mon.evaluate()
        res = mon.evaluate()
        assert res["trigger"] == "queue_share"
        assert res["detectors"]["queue_share"]["value"] == (
            pytest.approx(0.45)
        )

    def test_ineligible_everything_stays_observing(self):
        mon = _armed()
        _feed(mon, CANARY, 0, [10.0] * 2)
        res = mon.evaluate()
        assert res["decision"] == OBSERVE
        assert not any(
            d["eligible"] for d in res["detectors"].values()
        )

    def test_conclude_timeout_inconclusive_rolls_back(self):
        mon = _armed()
        res = mon.conclude("timeout")
        assert res["decision"] == ROLLBACK
        assert res["trigger"] == INCONCLUSIVE

    def test_conclude_timeout_promotes_only_with_evidence(self):
        mon = _armed()
        _feed(mon, INCUMBENT, 0, [10.0] * 10)
        _feed(mon, CANARY, 0, [11.0] * 10)
        mon.evaluate()  # one clean eligible evaluation
        res = mon.conclude("timeout")
        assert res["decision"] == PROMOTE

    def test_raw_breach_resets_promote_streak(self):
        mon = _armed()
        _feed(mon, INCUMBENT, 0, [10.0] * 10)
        _feed(mon, CANARY, 0, [11.0] * 10)
        mon.evaluate()  # clean streak 1
        _feed(mon, CANARY, 0, [500.0] * 10)  # now breaching
        assert mon.evaluate()["decision"] == OBSERVE  # streak reset
        # recovery: back to healthy needs a fresh streak
        _feed(mon, CANARY, 0, [11.0] * 512)  # flush the window
        assert mon.evaluate()["decision"] == OBSERVE
        assert mon.evaluate()["decision"] == PROMOTE

    def test_served_feed_keys_on_who_answered(self):
        mon = _armed()
        mon.record_served(0, 10.0, "v0001")
        mon.record_served(0, 10.0, "v0002")
        mon.record_served(0, 10.0, None)  # unlabeled: ignored
        assert mon.served == {INCUMBENT: 1, CANARY: 1}

    def test_report_shape_and_events(self):
        events = []
        mon = _armed(
            on_event=lambda kind, **f: events.append((kind, f))
        )
        _feed(mon, INCUMBENT, 0, [10.0] * 20)
        _feed(mon, CANARY, 0, [100.0] * 20)
        mon.evaluate()
        mon.evaluate()
        rep = mon.report({"mirrored": 3, "skipped": 1, "failed": 0})
        assert rep["decision"] == ROLLBACK
        assert rep["rollbacks"] == 1
        assert rep["trigger"] == "p99_p0"
        assert rep["fraction"] == 0.3
        assert rep["shadow"]["mirrored"] == 3
        assert rep["shadow"]["max_abs_drift"] is None
        assert rep["detectors"]["p99_p0"]["fired"] is True
        kinds = [(k, f.get("phase")) for k, f in events]
        assert ("canary", "evaluate") in kinds
        # the live /statsz view is None once disarmed
        assert mon.live() is not None
        mon.disarm()
        assert mon.live() is None


# ---------------------------------------------------------------------------
# stub-pool tier
# ---------------------------------------------------------------------------


def _num_factory(calls=None, eps_by_ref=None, pace_by_ref=None):
    """Stub runner factory answering NUMERIC payloads (so the shadow
    comparator has real arrays to diff): result rows are
    float32([payload]) + eps(ref), optionally paced per ref."""

    def factory(ref, device):
        if calls is not None:
            calls.append((str(ref), str(device)))
        eps = float((eps_by_ref or {}).get(ref, 0.0))
        pace = float((pace_by_ref or {}).get(ref, 0.0))

        def runner(payloads):
            if pace:
                time.sleep(pace)
            return [
                np.asarray([float(p)], np.float32) + eps
                for p in payloads
            ]

        return runner

    return factory


def _drive(pool, stop, answered, period=0.002):
    """Background submit loop; answered collects (payload, version)."""
    from bdbnn_tpu.obs.rtrace import pop_future_answered_by

    i = 0
    while not stop.is_set():
        try:
            fut = pool.submit([float(i)])

            def _done(f, i=i):
                if not f.cancelled() and f.exception() is None:
                    answered.append((i, pop_future_answered_by(f)))

            fut.add_done_callback(_done)
        except Exception:
            pass
        i += 1
        time.sleep(period)


class TestPoolCanaryStub:
    def test_promote_routes_cohorts_and_completes_full_shift(self):
        events = []
        calls = []
        pool = ReplicaPool(
            _num_factory(calls),
            ["d0", "d1", "d2"],
            artifact_ref="art1",
            version="v0001",
            on_event=lambda kind, **f: events.append((kind, f)),
        )
        mon = CanaryMonitor(
            _cfg(min_samples=5, healthy_evals=2, eval_interval_s=0.02),
            priorities=1,
            on_event=lambda kind, **f: events.append((kind, f)),
        )
        answered = []
        stop = threading.Event()
        t = threading.Thread(
            target=_drive, args=(pool, stop, answered), daemon=True
        )
        t.start()
        try:
            # the pool feed alone has no served-latency source (that
            # is the HTTP front end's job) — feed the monitor from the
            # pool's answered-by labels like the front end would
            feeder_stop = threading.Event()

            def feeder():
                seen = 0
                while not feeder_stop.is_set():
                    while seen < len(answered):
                        _, v = answered[seen]
                        mon.record_served(0, 1.0, v)
                        seen += 1
                    time.sleep(0.01)

            ft = threading.Thread(target=feeder, daemon=True)
            ft.start()
            status = pool.canary_swap(
                "art2", "v0002", mon, fraction=0.5,
                canary_replicas=1, shadow_every=4, seed=7,
            )
            feeder_stop.set()
            ft.join(2)
        finally:
            stop.set()
            t.join(2)
        assert status["state"] == SWAP_DONE
        can = status["canary"]
        assert can["decision"] == PROMOTE
        assert can["rollbacks"] == 0
        assert can["promote_s"] > 0
        # both cohorts actually answered traffic during observation
        versions = {v for _, v in answered if v is not None}
        assert versions == {"v0001", "v0002"}
        # the full shift completed: pool retired vN
        assert pool.version == "v0002"
        stats = pool.stats()
        assert all(
            r["version"] == "v0002" and not r["canary"]
            for r in stats["replicas"]
        )
        # shadow duplicates are excluded from the serving ledger:
        # completed_by_version counts exactly the client submissions
        assert sum(stats["completed_by_version"].values()) == len(
            answered
        )
        # identical stub outputs -> the probe measured EXACTLY zero
        assert can["shadow"]["compared"] > 0
        assert can["shadow"]["max_abs_drift"] == 0.0
        phases = [f.get("phase") for k, f in events if k == "canary"]
        for expected in ("start", "observing", "evaluate", "promote"):
            assert expected in phases, phases
        assert pool.drain(10)

    def test_logit_drift_detected_rolls_back_and_restores_vn(self):
        events = []
        calls = []
        pool = ReplicaPool(
            _num_factory(calls, eps_by_ref={"art2": 0.25}),
            ["d0", "d1"],
            artifact_ref="art1",
            version="v0001",
            on_event=lambda kind, **f: events.append((kind, f)),
        )
        mon = CanaryMonitor(
            _cfg(min_samples=5, healthy_evals=50, eval_interval_s=0.02),
            priorities=1,
        )
        answered = []
        stop = threading.Event()
        t = threading.Thread(
            target=_drive, args=(pool, stop, answered), daemon=True
        )
        t.start()
        try:
            status = pool.canary_swap(
                "art2", "v0002", mon, fraction=0.3,
                canary_replicas=1, shadow_every=1, seed=3,
            )
        finally:
            stop.set()
            t.join(2)
        assert status["state"] == SWAP_ROLLED_BACK
        can = status["canary"]
        assert can["decision"] == ROLLBACK
        assert can["trigger"] == "logit_drift"
        assert can["rollbacks"] == 1
        assert can["promote_s"] is None
        # the drift is the injected perturbation, measured exactly
        assert can["shadow"]["max_abs_drift"] == pytest.approx(
            0.25, abs=1e-6
        )
        # vN restored: version unchanged, no canary flags, and the
        # factory was re-invoked with the OLD ref for the canary device
        assert pool.version == "v0001"
        stats = pool.stats()
        assert all(
            r["version"] == "v0001" and not r["canary"]
            for r in stats["replicas"]
        )
        assert ("art1", "d1") in calls[2:]  # the rollback rebuild
        phases = [f.get("phase") for k, f in events if k == "canary"]
        assert "rollback" in phases
        swap_phases = [f.get("phase") for k, f in events if k == "swap"]
        assert "rolled_back" in swap_phases
        # post-rollback traffic answers from vN with clean outputs
        fut = pool.submit([5.0])
        assert fut.result(5)[0][0] == pytest.approx(5.0)
        assert pool.drain(10)

    def test_inconclusive_timeout_rolls_back(self):
        pool = ReplicaPool(
            _num_factory(), ["d0", "d1"],
            artifact_ref="art1", version="v0001",
        )
        mon = CanaryMonitor(
            _cfg(max_wait_s=0.3, eval_interval_s=0.05), priorities=1
        )
        # no traffic at all: nothing to judge -> conservative rollback
        status = pool.canary_swap(
            "art2", "v0002", mon, fraction=0.5, canary_replicas=1
        )
        assert status["state"] == SWAP_ROLLED_BACK
        assert status["canary"]["trigger"] == INCONCLUSIVE
        assert pool.version == "v0001"
        assert pool.drain(10)

    def test_drain_mid_canary_aborts_honestly(self):
        pool = ReplicaPool(
            _num_factory(), ["d0", "d1"],
            artifact_ref="art1", version="v0001",
        )
        mon = CanaryMonitor(
            _cfg(max_wait_s=30.0, eval_interval_s=0.05), priorities=1
        )
        out = {}

        def run():
            out["status"] = pool.canary_swap(
                "art2", "v0002", mon, fraction=0.5, canary_replicas=1
            )

        t = threading.Thread(target=run, daemon=True)
        t.start()
        time.sleep(0.3)  # observing by now
        assert pool.drain(10)
        t.join(5)
        assert out["status"]["state"] == SWAP_FAILED
        assert "drained mid-canary" in out["status"]["error"]

    def test_canary_needs_an_incumbent_replica(self):
        pool = ReplicaPool(
            _num_factory(), ["d0", "d1"],
            artifact_ref="art1", version="v0001",
        )
        mon = CanaryMonitor(_cfg(), priorities=1)
        with pytest.raises(ValueError, match="incumbent replica"):
            pool.canary_swap(
                "art2", "v0002", mon, fraction=0.5, canary_replicas=2
            )
        assert pool.drain(10)

    def test_failed_canary_standby_keeps_vn_serving(self):
        def factory(ref, device):
            if ref == "bad":
                raise RuntimeError("corrupt artifact")
            return _num_factory()(ref, device)

        pool = ReplicaPool(
            factory, ["d0", "d1"], artifact_ref="art1", version="v0001"
        )
        mon = CanaryMonitor(_cfg(), priorities=1)
        with pytest.raises(RuntimeError, match="corrupt artifact"):
            pool.canary_swap(
                "bad", "v0002", mon, fraction=0.5, canary_replicas=1
            )
        assert pool.swap_status()["state"] == SWAP_FAILED
        assert pool.version == "v0001"
        fut = pool.submit([1.0])
        assert fut.result(5)[0][0] == pytest.approx(1.0)
        assert pool.drain(10)

    def test_admin_routes_rollout_through_canary(self, tmp_path):
        art = tmp_path / "art_dir"
        art.mkdir()
        pool = ReplicaPool(
            _num_factory(eps_by_ref={str(art): 0.5}),
            ["d0", "d1"],
            artifact_ref="art1",
            version="v0001",
        )
        mon = CanaryMonitor(
            _cfg(min_samples=3, healthy_evals=50, eval_interval_s=0.02),
            priorities=1,
        )
        admin = PoolAdmin(
            pool,
            canary={
                "monitor": mon, "fraction": 0.4, "replicas": 1,
                "shadow_every": 1, "seed": 1,
            },
        )
        status_code, payload = admin.start_swap({"artifact": str(art)})
        assert status_code == 202
        stop = threading.Event()
        answered = []
        t = threading.Thread(
            target=_drive, args=(pool, stop, answered), daemon=True
        )
        t.start()
        try:
            assert admin.wait(20)
        finally:
            stop.set()
            t.join(2)
        report = admin.swap_report()
        assert report["performed"] is False
        assert report["state"] == SWAP_ROLLED_BACK
        can = admin.canary_report()
        assert can is not None and can["trigger"] == "logit_drift"
        assert pool.version == "v0001"
        assert pool.drain(10)


# ---------------------------------------------------------------------------
# degradation-hook tier (satellite)
# ---------------------------------------------------------------------------


class TestDegradationHook:
    def test_disabled_hook_is_zero_cost_plain_runner(self):
        factory = make_engine_runner_factory((4,), pace_ms=1.0)
        runner = factory("art", "d0")
        assert not hasattr(runner, "degraded")
        # a spec targeting a DIFFERENT artifact also stays unwrapped
        factory2 = make_engine_runner_factory(
            (4,), pace_ms=1.0,
            degrade={"artifact": "other", "latency_ms": 100},
        )
        assert not hasattr(factory2("art", "d0"), "degraded")
        # an all-zero spec is a no-op, not a wrapper
        factory3 = make_engine_runner_factory(
            (4,), pace_ms=1.0, degrade={"latency_ms": 0},
        )
        assert not hasattr(factory3("art", "d0"), "degraded")

    def test_latency_injection_observable_through_a_real_pool(self):
        factory = make_engine_runner_factory(
            (4,), pace_ms=1.0,
            degrade={"artifact": "art", "latency_ms": 80},
        )
        pool = ReplicaPool(
            factory, ["paced:0"], artifact_ref="art", version="v0001"
        )
        t0 = time.monotonic()
        pool.submit([1.0]).result(10)
        assert time.monotonic() - t0 >= 0.08
        assert pool.drain(10)

    def test_error_injection_ledgers_as_failed(self):
        factory = make_engine_runner_factory(
            (4,), pace_ms=1.0,
            degrade={"artifact": "art", "error_rate": 1.0},
        )
        pool = ReplicaPool(
            factory, ["paced:0"], artifact_ref="art", version="v0001"
        )
        fut = pool.submit([1.0, 2.0])
        with pytest.raises(RuntimeError, match="injected engine"):
            fut.result(10)
        assert pool.stats()["failed_by_version"] == {"v0001": 2}
        assert pool.drain(10)

    def test_logit_perturbation_exact_and_per_payload(
        self, exported_artifact
    ):
        art_dir, _ = exported_artifact
        rng = np.random.default_rng(0)
        imgs = [
            rng.standard_normal((32, 32, 3)).astype(np.float32)
            for _ in range(3)
        ]
        imgs[1][0, 0, 0] = 99.0  # the marked payload

        def marked(p):
            return float(np.asarray(p)[0, 0, 0]) > 50.0

        plain = make_engine_runner_factory((4,))(art_dir, None)
        degraded = make_engine_runner_factory(
            (4,),
            degrade={
                "artifact": art_dir, "logit_eps": 0.25,
                "match": marked,
            },
        )(art_dir, None)
        assert degraded.degraded is True
        a = np.asarray(plain(imgs))
        b = np.stack(degraded(imgs))
        # only the marked row is perturbed, by EXACTLY eps
        assert np.array_equal(a[0], b[0]) and np.array_equal(a[2], b[2])
        assert np.array_equal(a[1] + np.float32(0.25), b[1])

    def test_no_injection_pin_bitwise_logits(self, exported_artifact):
        """degrade=None produces BITWISE the plain engine's logits —
        the hook costs nothing when disabled."""
        from bdbnn_tpu.serve.engine import InferenceEngine

        art_dir, _ = exported_artifact
        rng = np.random.default_rng(1)
        imgs = [
            rng.standard_normal((32, 32, 3)).astype(np.float32)
            for _ in range(2)
        ]
        runner = make_engine_runner_factory((4,))(art_dir, None)
        engine = InferenceEngine(art_dir, buckets=(4,))
        assert np.array_equal(
            np.asarray(runner(list(imgs))),
            engine.predict_logits(np.stack(imgs)),
        )


# ---------------------------------------------------------------------------
# compare gates (satellite): v1-v4 skip pins both directions + the
# zero-tolerance rollback/drift regressions over doctored verdicts
# ---------------------------------------------------------------------------


def _verdict_file(path, name, *, canary=None, p99=12.0):
    v = {
        "serve_verdict": 5,
        "mode": "http",
        "rate_rps": 100.0,
        "seed": 0,
        "scenario": "poisson",
        "requests_submitted": 100,
        "requests_completed": 100,
        "requests_shed": 0,
        "requests_failed": 0,
        "requests_rejected": 0,
        "shed_rate": 0.0,
        "p50_ms": 5.0,
        "p95_ms": 10.0,
        "p99_ms": p99,
        "throughput_rps": 90.0,
        "wall_s": 1.0,
        "provenance": {
            "config_hash": None,
            "recipe": {"arch": "resnet8_tiny", "dataset": "cifar10"},
        },
        "canary": canary,
    }
    out = os.path.join(str(path), name)
    with open(out, "w") as f:
        json.dump(v, f)
    return out


def _canary_block(rollbacks=0, drift=0.0, promote_s=2.5):
    return {
        "fraction": 0.25,
        "replicas_canary": [1],
        "version_from": "v0001",
        "version_to": "v0002",
        "decision": "rollback" if rollbacks else "promote",
        "trigger": "p99_p0" if rollbacks else None,
        "rollbacks": rollbacks,
        "evaluations": 5,
        "observe_s": 1.5,
        "promote_s": None if rollbacks else promote_s,
        "served": {"incumbent": 80, "canary": 20},
        "detectors": {},
        "shadow": {
            "mirrored": 8, "compared": 8, "skipped": 0, "failed": 0,
            "max_abs_drift": drift,
        },
    }


class TestCompareCanaryGates:
    def test_v4_verdicts_skip_cleanly_both_directions(self, tmp_path):
        from bdbnn_tpu.obs.compare import compare_runs, extract_run

        old = _verdict_file(tmp_path, "old.json", canary=None)
        new = _verdict_file(
            tmp_path, "new.json", canary=_canary_block()
        )
        # a canary-less verdict knows none of the canary metrics
        m = extract_run(old)["metrics"]
        assert m["serve_canary_rollbacks"] is None
        assert m["serve_shadow_logit_drift_max"] is None
        assert m["serve_canary_promote_s"] is None
        for base, cand in ((old, new), (new, old)):
            rows = {
                r["metric"]
                for r in compare_runs([base, cand])["comparisons"][0][
                    "metrics"
                ]
            }
            assert "serve_canary_rollbacks" not in rows
            assert "serve_shadow_logit_drift_max" not in rows
            assert "serve_canary_promote_s" not in rows

    def test_rollback_is_zero_tolerance_even_with_flat_p99(
        self, tmp_path
    ):
        """THE doctored-run gate: the candidate's canary rolled back
        while its aggregate p99 is UNCHANGED from the baseline —
        compare must exit 3 anyway (the per-priority blindness the
        canary stage exists to catch)."""
        from bdbnn_tpu.cli import compare_main

        base = _verdict_file(
            tmp_path, "base.json", canary=_canary_block(rollbacks=0)
        )
        cand = _verdict_file(
            tmp_path, "cand.json", canary=_canary_block(rollbacks=1)
        )
        rc = compare_main([base, cand, "--json"])
        assert rc == 3
        from bdbnn_tpu.obs.compare import compare_runs

        result = compare_runs([base, cand])
        rows = {
            m["metric"]: m
            for m in result["comparisons"][0]["metrics"]
        }
        assert rows["serve_canary_rollbacks"]["verdict"] == "regression"
        # the aggregate p99 row is identical — flat, and NOT the gate
        assert rows["serve_p99_ms"]["delta"] == 0.0

    def test_shadow_drift_is_zero_tolerance(self, tmp_path):
        from bdbnn_tpu.obs.compare import compare_runs

        base = _verdict_file(
            tmp_path, "b.json", canary=_canary_block(drift=0.0)
        )
        cand = _verdict_file(
            tmp_path, "c.json", canary=_canary_block(drift=1e-4)
        )
        result = compare_runs([base, cand])
        rows = {
            m["metric"]: m
            for m in result["comparisons"][0]["metrics"]
        }
        assert (
            rows["serve_shadow_logit_drift_max"]["verdict"]
            == "regression"
        )
        assert result["verdict"] == "regression"

    def test_promote_seconds_judged_under_tol_rel(self, tmp_path):
        from bdbnn_tpu.obs.compare import compare_runs

        base = _verdict_file(
            tmp_path, "b.json", canary=_canary_block(promote_s=2.0)
        )
        cand = _verdict_file(
            tmp_path, "c.json", canary=_canary_block(promote_s=5.0)
        )
        rows = {
            m["metric"]: m
            for m in compare_runs([base, cand], tol_rel=0.10)[
                "comparisons"
            ][0]["metrics"]
        }
        assert rows["serve_canary_promote_s"]["verdict"] == "regression"


class TestWatchSummarizeRendering:
    def _events(self):
        return [
            {"t": 100.0, "kind": "http", "phase": "start",
             "host": "h", "port": 1, "arch": "resnet8_tiny",
             "priorities": 3, "queue_depth": 64, "buckets": [1]},
            {"t": 101.0, "kind": "canary", "phase": "start",
             "version_from": "v0001", "version_to": "v0002",
             "fraction": 0.25, "replicas_canary": [1],
             "shadow_every": 8},
            {"t": 101.5, "kind": "canary", "phase": "evaluate",
             "evaluation": 3, "decision": "observe", "trigger": None,
             "clean_streak": 1, "canary_served": 12,
             "incumbent_served": 40,
             "detectors": {
                 "p99_p0": {"value": 1.1, "threshold": 2.0,
                            "breach": False, "fired": False,
                            "eligible": True},
                 "logit_drift": {"value": None, "threshold": 0.0,
                                 "breach": False, "fired": False,
                                 "eligible": False},
             }},
        ]

    def test_watch_live_canary_banner(self):
        from bdbnn_tpu.obs.watch import render_status

        status = render_status(self._events(), None)
        assert ">> CANARY v0001 -> v0002: observing" in status
        assert "fraction 0.25" in status
        assert "p99_p0:ok" in status
        assert "logit_drift:warming" in status

    def test_watch_rollback_banner(self):
        from bdbnn_tpu.obs.watch import render_status

        events = self._events() + [
            {"t": 102.0, "kind": "swap", "phase": "rolled_back",
             "version_from": "v0001", "version_to": "v0002",
             "trigger": "p99_p0", "seconds": 2.5},
        ]
        status = render_status(events, None)
        assert "!! CANARY ROLLBACK" in status
        assert "trigger p99_p0" in status
        assert "registry untouched" in status


# ---------------------------------------------------------------------------
# acceptance tier — real sockets, real AOT engines
# ---------------------------------------------------------------------------


def _raw_decode(image_size):
    shape = (image_size, image_size, 3)
    nbytes = int(np.prod(shape)) * 4

    def decode(body, content_type):
        if len(body) != nbytes:
            raise ValueError(f"want {nbytes} bytes, got {len(body)}")
        return np.frombuffer(body, np.float32).reshape(shape).copy()

    return decode


class TestCanaryRollbackEndToEnd:
    """THE acceptance e2e: flash-crowd over real sockets against a
    2-replica pool of real AOT engines, canary to a fault-injected
    vN+1 whose latency degradation hits ONLY priority-0 requests
    (marked bodies + the degradation hook's payload matcher) →
    CanaryMonitor auto-rollback from the per-priority window, zero
    client drops, ledger identity intact across versions, and the
    rollback episode consumed by watch/summarize/compare."""

    INJECT_MS = 150.0

    @pytest.fixture(scope="class")
    def rollback_run(
        self, exported_artifact, tmp_path_factory, port_allocator
    ):
        from bdbnn_tpu.obs.events import EventWriter
        from bdbnn_tpu.parallel.mesh import replica_devices
        from bdbnn_tpu.serve.admission import AdmissionController
        from bdbnn_tpu.serve.batching import MicroBatcher
        from bdbnn_tpu.serve.http import HttpFrontEnd
        from bdbnn_tpu.serve.loadgen import (
            HttpLoadGenerator,
            _pool_replicas_block,
            build_schedule,
            http_slo_verdict,
            write_verdict_files,
        )

        art_dir, artifact = exported_artifact
        tmp = tmp_path_factory.mktemp("canary_rollback_e2e")
        # vN+1 is a COPY of the same artifact so the degradation hook
        # can target it by path while vN stays clean
        art2 = str(tmp / "v0002")
        shutil.copytree(art_dir, art2)
        run_dir = str(tmp / "run")
        os.makedirs(run_dir)
        events = EventWriter(run_dir)
        emit = lambda kind, **f: events.emit(kind, **f)  # noqa: E731

        def marked(p):
            return float(np.asarray(p).flat[0]) > 50.0

        factory = make_engine_runner_factory(
            (1,),
            on_event=emit,
            degrade={
                "artifact": art2,
                "latency_ms": self.INJECT_MS,
                "match": marked,
            },
        )
        pool = ReplicaPool(
            factory,
            list(replica_devices(2)),
            artifact_ref=art_dir,
            version="v0001",
            on_event=emit,
        )
        mon = CanaryMonitor(
            apply_canary_overrides(
                CanaryConfig(),
                (
                    "min_samples=4", "debounce=2",
                    "eval_interval_s=0.15", "max_wait_s=25",
                    "healthy_evals=1000",  # this canary must not pass
                    "p99_ratio=2.0", "p99_floor_ms=20",
                    # the OTHER detectors stand down so the rollback
                    # provably fires from the per-priority p99 window
                    "unabsorbed_rate=2.0", "fairness_ratio_max=1000",
                    "queue_share_abs=5.0", "error_rate_abs=1.1",
                ),
            ),
            priorities=3,
            on_event=emit,
        )
        batcher = MicroBatcher(
            pool.submit,
            max_batch=1,
            max_queue=256,
            max_delay_ms=1.0,
            priorities=3,
            max_pending_batches=4,
        )
        admission = AdmissionController(
            default_rate=1e9, default_burst=1e9
        )
        admin = PoolAdmin(
            pool,
            shed_counter=lambda: (
                batcher.stats()["shed"]
                + pool.stats()["shed_requests"]
            ),
            canary={
                "monitor": mon, "fraction": 0.45, "replicas": 1,
                "shadow_every": 6, "seed": 5,
            },
        )
        front = HttpFrontEnd(
            batcher,
            admission,
            decode=_raw_decode(artifact["image_size"]),
            encode=lambda logits: {
                "pred": int(np.argmax(logits)),
            },
            port=port_allocator(),
            admin=admin,
            canary=mon,
        )
        host, port = front.start()
        # premium-heavy mix on purpose: priority 0 must reach detector
        # eligibility FIRST, so the trigger provably comes from the
        # premium window (head-of-line blocking on the degraded canary
        # replica can contaminate the other classes' tails later)
        schedule = build_schedule(
            "flash_crowd",
            requests=280,
            rate=40.0,
            seed=13,
            priorities=3,
            priority_weights=[0.5, 0.2, 0.3],
            flash_factor=2.0,
        )
        rng = np.random.default_rng(13)
        size = artifact["image_size"]
        base_img = rng.standard_normal((size, size, 3)).astype(
            np.float32
        )
        marked_img = base_img.copy()
        marked_img[0, 0, 0] = 99.0  # the matcher's marker
        bodies = {
            True: np.ascontiguousarray(marked_img).tobytes(),
            False: np.ascontiguousarray(base_img).tobytes(),
        }

        def body_fn(i):
            # ONLY priority-0 requests carry the marker: the injected
            # degradation hits exactly the premium class
            return bodies[schedule[i].priority == 0]

        threshold = max(int(0.15 * len(schedule)), 1)
        fired = []

        def on_arrival(i):
            if not fired and i + 1 >= threshold:
                fired.append(True)

                def _fire():
                    status, payload = admin.start_swap(
                        {"artifact": art2}
                    )
                    events.emit(
                        "swap", phase="trigger", at_request=i + 1,
                        of=len(schedule), status=status, **payload,
                    )

                threading.Thread(target=_fire, daemon=True).start()

        gen = HttpLoadGenerator(
            host, port, schedule,
            body_fn=body_fn,
            concurrency=8,
            on_arrival=on_arrival,
        )
        client_raw = gen.run()
        front.drain(timeout=60.0)
        admin.wait(timeout=40.0)
        pool_stats = pool.stats()
        pool.drain(timeout=30.0)
        verdict = http_slo_verdict(
            front.accounting(),
            batcher.stats(),
            admission.stats(),
            scenario="flash_crowd",
            rate=40.0,
            seed=13,
            client=client_raw,
            replicas=_pool_replicas_block(pool_stats),
            swap=admin.swap_report(),
            canary=admin.canary_report(),
        )
        events.emit("serve", phase="verdict", **verdict)
        events.close()
        write_verdict_files(verdict, run_dir)
        return {
            "verdict": verdict,
            "run_dir": run_dir,
            "pool_stats": pool_stats,
        }

    def test_rollback_fired_from_the_per_priority_window(
        self, rollback_run
    ):
        can = rollback_run["verdict"]["canary"]
        assert can is not None
        assert can["decision"] == "rollback"
        assert can["rollbacks"] == 1
        assert can["promote_s"] is None
        # the trigger is a PER-PRIORITY p99 detector, and the premium
        # class's window shows the breach — the injected degradation
        # hit only priority 0, which no aggregate percentile isolates
        assert can["trigger"].startswith("p99_p")
        p0 = can["detectors"]["p99_p0"]
        assert p0["breach"] or p0["fired"]
        assert p0["canary_p99_ms"] >= self.INJECT_MS
        swap = rollback_run["verdict"]["swap"]
        assert swap["state"] == "rolled_back"
        assert swap["performed"] is False

    def test_aggregate_stays_blind_to_the_premium_regression(
        self, rollback_run
    ):
        v = rollback_run["verdict"]
        # the bulk of traffic never saw the injection: the median is
        # flat while priority 0's own p99 carries the full injected
        # latency — the exact blindness the per-priority windows (and
        # PR 10's attribution) exist to expose
        assert v["p50_ms"] < self.INJECT_MS
        assert v["per_priority"]["0"]["p99_ms"] >= self.INJECT_MS

    def test_zero_client_drops_and_ledger_identity(self, rollback_run):
        v = rollback_run["verdict"]
        assert v["client"]["dropped"] == 0
        assert v["client"]["responses"] == v["client"]["submitted"]
        assert (
            v["requests_completed"] + v["requests_shed"]
            + v["requests_failed"] + v["requests_rejected"]
            == v["requests_submitted"]
        )
        assert v["requests_failed"] == 0
        # every completed request was answered by exactly one version;
        # the canary DID serve traffic before the rollback
        by = v["swap"]["answered_by"]
        assert sum(by.values()) == v["requests_completed"]
        assert by.get("v0002", 0) > 0
        assert v["serve_verdict"] == 8

    def test_pool_restored_to_vn(self, rollback_run):
        ps = rollback_run["pool_stats"]
        assert ps["version"] == "v0001"
        assert all(
            r["version"] == "v0001" and not r["canary"]
            for r in ps["replicas"]
        )
        assert ps["canary_active"] is False

    def test_watch_summarize_compare_consume_the_episode(
        self, rollback_run
    ):
        from bdbnn_tpu.obs.compare import compare_runs, extract_run
        from bdbnn_tpu.obs.events import read_events
        from bdbnn_tpu.obs.summarize import summarize_run
        from bdbnn_tpu.obs.watch import render_status

        run_dir = rollback_run["run_dir"]
        events = read_events(run_dir)
        canary_phases = [
            e.get("phase") for e in events if e.get("kind") == "canary"
        ]
        for expected in (
            "start", "observing", "evaluate", "rollback",
        ):
            assert expected in canary_phases, canary_phases
        assert any(
            e.get("phase") == "rolled_back"
            for e in events
            if e.get("kind") == "swap"
        )
        # watch: the live banner pre-verdict, the canary line post
        pre_verdict = [
            e for e in events
            if not (
                e.get("kind") == "serve"
                and e.get("phase") == "verdict"
            )
        ]
        assert "CANARY ROLLBACK" in render_status(pre_verdict, None)
        status = render_status(events, None)
        assert "ROLLED BACK (trigger p99_p" in status
        # summarize: the canary-episode section with the evidence table
        report, summary = summarize_run(run_dir)
        assert "ROLLED BACK (trigger p99_p" in report
        assert "p99_p0" in report
        assert "shadow:" in report
        sv = summary["serving"]["verdict"]["canary"]
        assert sv["rollbacks"] == 1
        # compare: the run dir extracts the rollback count and
        # self-compares clean (same count both sides)
        rec = extract_run(run_dir)
        assert rec["metrics"]["serve_canary_rollbacks"] == 1
        assert compare_runs([run_dir, run_dir])["verdict"] == "pass"


class TestCanaryPromoteEndToEnd:
    """The sibling acceptance e2e through the REAL serve-http
    orchestration: a healthy vN+1 (a republished-identical artifact,
    PACKED on both sides) canaries under a poisson scenario, the
    monitor auto-promotes, the full replica-by-replica shift completes
    with swap.shed == 0 — and the shadow logit-drift probe is pinned
    BITWISE-ZERO, the quality gate packed determinism makes free."""

    @pytest.fixture(scope="class")
    def promote_run(self, exported_artifact, tmp_path_factory):
        from bdbnn_tpu.configs.config import ServeHttpConfig
        from bdbnn_tpu.serve.http import run_serve_http
        from bdbnn_tpu.serve.registry import ArtifactRegistry

        art_dir, _ = exported_artifact
        tmp = tmp_path_factory.mktemp("canary_promote_e2e")
        reg_root = str(tmp / "registry")
        reg = ArtifactRegistry(reg_root)
        reg.publish(art_dir)  # v0001 — the incumbent
        reg.publish(art_dir)  # v0002 — byte-identical republish
        cfg = ServeHttpConfig(
            artifact="v0001",
            registry=reg_root,
            log_path=str(tmp / "http"),
            replicas=2,
            packed_weights=True,
            buckets=(4,),
            queue_depth=128,
            max_delay_ms=2.0,
            priorities=3,
            default_quota="100000:100000",
            scenario="poisson",
            rate=40.0,
            requests=240,
            concurrency=8,
            seed=7,
            swap_to="v0002",
            swap_at=0.2,
            canary_fraction=0.3,
            canary_replicas=1,
            shadow_every=2,
            canary_thresholds=(
                "min_samples=10", "healthy_evals=2",
                "eval_interval_s=0.2", "max_wait_s=25",
            ),
            stats_interval_s=0.25,
        )
        return run_serve_http(cfg)

    def test_promoted_with_zero_swap_shed(self, promote_run):
        v = promote_run["verdict"]
        swap = v["swap"]
        assert swap["performed"] is True
        assert swap["state"] == SWAP_DONE
        assert swap["version_from"] == "v0001"
        assert swap["version_to"] == "v0002"
        assert swap["replicas_shifted"] == 2
        assert swap["shed"] == 0
        can = v["canary"]
        assert can["decision"] == "promote"
        assert can["rollbacks"] == 0
        assert can["promote_s"] > 0
        assert can["fraction"] == 0.3
        # the whole pool ended on vN+1
        assert all(
            r["version"] == "v0002"
            for r in v["replicas"]["per_replica"]
        )

    def test_shadow_drift_bitwise_zero_packed_vs_republished(
        self, promote_run
    ):
        """THE exactness pin: packed inference is deterministic and
        bitwise-exact, so a packed vN mirrored against a republished-
        identical packed vN+1 measures max-abs logit drift of EXACTLY
        0.0 — not approximately."""
        shadow = promote_run["verdict"]["canary"]["shadow"]
        assert shadow["compared"] > 0
        assert shadow["max_abs_drift"] == 0.0

    def test_zero_dropped_and_ledger_identity(self, promote_run):
        v = promote_run["verdict"]
        assert v["client"]["dropped"] == 0
        assert (
            v["requests_completed"] + v["requests_shed"]
            + v["requests_failed"] + v["requests_rejected"]
            == v["requests_submitted"]
        )
        by = v["swap"]["answered_by"]
        assert set(by) == {"v0001", "v0002"}
        assert sum(by.values()) == v["requests_completed"]
        assert v["serve_verdict"] == 8

    def test_episode_consumed_by_watch_summarize_compare(
        self, promote_run
    ):
        from bdbnn_tpu.obs.compare import extract_run
        from bdbnn_tpu.obs.events import read_events
        from bdbnn_tpu.obs.summarize import summarize_run
        from bdbnn_tpu.obs.watch import render_status

        run_dir = promote_run["run_dir"]
        events = read_events(run_dir)
        canary_phases = [
            e.get("phase") for e in events if e.get("kind") == "canary"
        ]
        for expected in (
            "start", "observing", "evaluate", "promote",
        ):
            assert expected in canary_phases, canary_phases
        mirrors = [
            e for e in events
            if e.get("kind") == "shadow" and e.get("phase") == "mirror"
        ]
        assert mirrors and all(e["drift"] == 0.0 for e in mirrors)
        status = render_status(events, None)
        assert "canary: fraction 0.3" in status
        assert "promoted in" in status
        report, summary = summarize_run(run_dir)
        assert "PROMOTED in" in report
        assert "bitwise-exact" in report
        rec = extract_run(run_dir)
        assert rec["metrics"]["serve_canary_rollbacks"] == 0
        assert rec["metrics"]["serve_shadow_logit_drift_max"] == 0.0
        assert rec["metrics"]["serve_canary_promote_s"] > 0

    def test_compare_exits_3_on_doctored_rollback_with_flat_p99(
        self, promote_run, tmp_path
    ):
        """THE acceptance gate: doctor the clean run's verdict so its
        canary ROLLED BACK while every latency number — the aggregate
        p99 included — is byte-identical to the baseline; compare must
        exit 3 on the rollback alone."""
        from bdbnn_tpu.cli import compare_main

        orig = os.path.join(promote_run["run_dir"], "verdict.json")
        with open(orig) as f:
            doctored = json.load(f)
        doctored["canary"] = copy.deepcopy(doctored["canary"])
        doctored["canary"]["decision"] = "rollback"
        doctored["canary"]["trigger"] = "p99_p0"
        doctored["canary"]["rollbacks"] = 1
        doctored["canary"]["promote_s"] = None
        doctored_path = str(tmp_path / "doctored_verdict.json")
        with open(doctored_path, "w") as f:
            json.dump(doctored, f)
        assert compare_main([orig, doctored_path, "--json"]) == 3
        # and the aggregate p99 row really is flat — the rollback is
        # the ONLY regression
        from bdbnn_tpu.obs.compare import compare_runs

        rows = {
            m["metric"]: m
            for m in compare_runs([orig, doctored_path])[
                "comparisons"
            ][0]["metrics"]
        }
        assert rows["serve_p99_ms"]["delta"] == 0.0
        assert rows["serve_canary_rollbacks"]["verdict"] == "regression"
        assert compare_main([orig, orig]) == 0


class TestCanaryDriftRollbackEndToEnd:
    """Injected logit perturbation on vN+1 through the REAL serve-http
    orchestration: the shadow probe measures a NONZERO drift and the
    canary auto-rolls-back — the detected half of the bitwise-zero
    pin above."""

    @pytest.fixture(scope="class")
    def drift_run(self, exported_artifact, tmp_path_factory):
        from bdbnn_tpu.configs.config import ServeHttpConfig
        from bdbnn_tpu.serve.http import run_serve_http
        from bdbnn_tpu.serve.registry import ArtifactRegistry

        art_dir, _ = exported_artifact
        tmp = tmp_path_factory.mktemp("canary_drift_e2e")
        reg_root = str(tmp / "registry")
        reg = ArtifactRegistry(reg_root)
        reg.publish(art_dir)
        reg.publish(art_dir)
        v2_dir = reg.resolve(2)
        cfg = ServeHttpConfig(
            artifact="v0001",
            registry=reg_root,
            log_path=str(tmp / "http"),
            replicas=2,
            buckets=(4,),
            queue_depth=128,
            max_delay_ms=2.0,
            priorities=3,
            default_quota="100000:100000",
            scenario="poisson",
            rate=50.0,
            requests=180,
            concurrency=8,
            seed=17,
            swap_to="v0002",
            swap_at=0.2,
            canary_fraction=0.35,
            canary_replicas=1,
            shadow_every=1,
            canary_thresholds=(
                "min_samples=4", "eval_interval_s=0.15",
                "max_wait_s=20", "healthy_evals=1000",
                # only the drift probe may decide this episode
                "p99_ratio=1000", "p99_floor_ms=100000",
                "unabsorbed_rate=2.0", "fairness_ratio_max=1000",
                "queue_share_abs=5.0", "error_rate_abs=1.1",
            ),
            stats_interval_s=0.25,
        )
        return run_serve_http(
            cfg,
            # perturb ONLY the republished version's runners: the
            # mirrored incumbent batches diff clean-vs-perturbed
            degrade={"artifact": v2_dir, "logit_eps": 0.01},
        )

    def test_drift_detected_and_rolled_back(self, drift_run):
        v = drift_run["verdict"]
        can = v["canary"]
        assert can["decision"] == "rollback"
        assert can["trigger"] == "logit_drift"
        assert can["rollbacks"] == 1
        shadow = can["shadow"]
        assert shadow["compared"] > 0
        # the measured drift IS the injected perturbation (float32
        # addition of a representable eps: exact)
        assert shadow["max_abs_drift"] == pytest.approx(
            0.01, rel=1e-5
        )
        assert v["swap"]["state"] == SWAP_ROLLED_BACK
        assert v["swap"]["performed"] is False
        # the pool ended back on vN
        assert all(
            r["version"] == "v0001"
            for r in v["replicas"]["per_replica"]
        )
        assert v["client"]["dropped"] == 0

    def test_nonzero_drift_lands_in_events_and_compare(
        self, drift_run
    ):
        from bdbnn_tpu.obs.compare import extract_run
        from bdbnn_tpu.obs.events import read_events

        run_dir = drift_run["run_dir"]
        mirrors = [
            e for e in read_events(run_dir)
            if e.get("kind") == "shadow" and e.get("phase") == "mirror"
        ]
        assert any(e["drift"] > 0 for e in mirrors)
        rec = extract_run(run_dir)
        assert rec["metrics"]["serve_shadow_logit_drift_max"] > 0
        assert rec["metrics"]["serve_canary_rollbacks"] == 1


class TestReviewHardening:
    """Pins for the post-review fixes: shadow work in the restart
    requeue path, shift-window fallbacks polluting the unabsorbed
    detector, and promote requiring at least one eligible
    comparison."""

    def test_restart_requeue_drops_shadow_work_without_shed(self):
        from bdbnn_tpu.serve.batching import LoadShedError
        from bdbnn_tpu.serve.pool import _Work

        gate = threading.Event()

        def factory(ref, device):
            def runner(payloads):
                if payloads and payloads[0] == "block":
                    gate.wait(5)
                return [
                    np.asarray([0.0], np.float32) for _ in payloads
                ]

            return runner

        pool = ReplicaPool(
            factory, ["d0", "d1"], artifact_ref="a", version="v0001"
        )
        r = pool.replicas[1]
        blocker = _Work(["block"])
        assert r.try_enqueue(blocker)
        time.sleep(0.1)  # the worker picks it up and parks on the gate
        shadow = _Work([1.0], shadow=True)
        normal = _Work([2.0])
        assert r.try_enqueue(shadow)
        assert r.try_enqueue(normal)
        pool._restart_replica(r, "test")
        # the shadow duplicate was DROPPED, not shed-counted and not
        # requeued cohort-less onto an incumbent: no client sent it,
        # and a vN-executed mirror would fake a drift measurement
        assert pool.stats()["shed_requests"] == 0
        with pytest.raises(LoadShedError):
            shadow.future.result(1)
        # the real client batch still moved to a healthy peer
        assert normal.future.result(5)[0][0] == 0.0
        gate.set()
        assert pool.drain(10)

    def test_shift_window_fallbacks_are_not_health_evidence(self):
        """Cohort routing goes live BEFORE the canary subset shifts
        (no unbounded vN+1 leakage), so the shift window mechanically
        falls back every canary-assigned batch. Those fallbacks are
        drain physics: the cohort counters reset at observation start,
        and a healthy canary behind a slow subset drain must PROMOTE,
        never roll back as `unabsorbed`."""
        from bdbnn_tpu.serve.pool import _Work

        gate = threading.Event()

        def factory(ref, device):
            def runner(payloads):
                if payloads and payloads[0] == "block":
                    gate.wait(5)
                return [
                    np.asarray(
                        [float(p) if not isinstance(p, str) else 0.0],
                        np.float32,
                    )
                    for p in payloads
                ]

            return runner

        pool = ReplicaPool(
            factory, ["d0", "d1"], artifact_ref="a", version="v0001"
        )
        # wedge the future canary replica: its shift drain stalls on
        # the gate while routing is already live, piling up fallbacks
        blocker = _Work(["block"])
        assert pool.replicas[1].try_enqueue(blocker)
        time.sleep(0.05)
        mon = CanaryMonitor(
            _cfg(
                min_samples=5, healthy_evals=2, eval_interval_s=0.05,
            ),
            priorities=1,
        )
        answered = []
        stop = threading.Event()
        t = threading.Thread(
            target=_drive, args=(pool, stop, answered), daemon=True
        )
        t.start()
        feeder_stop = threading.Event()

        def feeder():
            seen = 0
            while not feeder_stop.is_set():
                while seen < len(answered):
                    _, v = answered[seen]
                    mon.record_served(0, 1.0, v)
                    seen += 1
                time.sleep(0.01)

        ft = threading.Thread(target=feeder, daemon=True)
        ft.start()
        threading.Timer(0.8, gate.set).start()
        try:
            status = pool.canary_swap(
                "a2", "v0002", mon, fraction=0.5,
                canary_replicas=1, shadow_every=0, seed=2,
            )
        finally:
            feeder_stop.set()
            stop.set()
            t.join(2)
            ft.join(2)
        assert status["state"] == SWAP_DONE, status
        det = status["canary"]["detectors"]["unabsorbed"]
        assert det["fired"] is False
        assert pool.drain(10)

    def test_no_eligible_comparison_never_promotes(self):
        """Promote requires at least one detector to have actually
        COMPARED the cohorts: a canary with plenty of samples against
        an incumbent window below min_samples has proven nothing, and
        the timeout conclusion stays a conservative rollback."""
        mon = _armed()
        _feed(mon, CANARY, 0, [10.0] * 50)
        _feed(mon, INCUMBENT, 0, [10.0] * 2)  # too thin to compare
        for _ in range(10):
            res = mon.evaluate()
        assert res["decision"] == OBSERVE
        assert not any(
            d["eligible"] for d in res["detectors"].values()
        )
        concluded = mon.conclude("timeout")
        assert concluded["decision"] == ROLLBACK
        assert concluded["trigger"] == INCONCLUSIVE
