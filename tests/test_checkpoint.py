"""Checkpoint round-trip tests on the 8-device CPU mesh.

Round-1 gaps: restore dropped the GSPMD shardings (resume re-placed
params by jit default) and save rmtree'd the old checkpoint before the
new one existed. These tests pin: (a) save → restore → step on a mesh
bit-matches uninterrupted training, (b) restored leaves carry the
template's shardings, (c) a crash that leaves only ``checkpoint.old``
still resumes. (↔ reference resume, ``train.py:345-366``.)
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bdbnn_tpu.models.resnet import BiResNet
from bdbnn_tpu.parallel import (
    create_sharded_state,
    jit_train_step,
    make_mesh,
    shard_batch,
)
from bdbnn_tpu.train import StepConfig, TrainState, make_optimizer, make_train_step
from bdbnn_tpu.utils.checkpoint import (
    CKPT_NAME,
    load_checkpoint,
    save_checkpoint,
)


def _setup(model_parallel=1):
    model = BiResNet(
        stage_sizes=(1, 1), num_classes=4, width=8,
        stem="cifar", variant="cifar", act="hardtanh",
    )
    mesh = make_mesh(model_parallel=model_parallel)
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8, 8, 3)), train=True
    )
    tx = make_optimizer(
        variables["params"], dataset="cifar10", lr=0.05,
        epochs=10, steps_per_epoch=100,
    )
    state = create_sharded_state(mesh, variables, tx, TrainState)
    step = jit_train_step(make_train_step(model, tx, StepConfig()))
    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 8, 8, 3)).astype(np.float32)
    y = rng.integers(0, 4, size=(16,))
    tk = (jnp.float32(1.0), jnp.float32(1.0))

    def run(state, n=1):
        for _ in range(n):
            gx, gy = shard_batch(mesh, x, y)
            state, m = step(state, (gx, gy), tk, jnp.float32(0.0))
        return state, m

    def fresh_template():
        v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8, 8, 3)), train=True)
        return create_sharded_state(mesh, v, tx, TrainState)

    return run, fresh_template


def _leaves(tree):
    return jax.tree_util.tree_leaves(tree)


class TestMeshRoundTrip:
    def test_resume_bitmatches_uninterrupted(self, tmp_path):
        run, fresh_template = _setup()
        state, _ = run(fresh_template(), n=2)
        save_checkpoint(
            str(tmp_path), state, epoch=1, arch="tiny", best_acc1=11.0,
            is_best=True,
        )
        # uninterrupted: 2 more steps from the live state
        cont, m_cont = run(state, n=2)

        restored = load_checkpoint(str(tmp_path), fresh_template())
        assert restored["epoch"] == 2
        assert restored["best_acc1"] == pytest.approx(11.0)
        resumed, m_res = run(restored["state"], n=2)

        for a, b in zip(_leaves(cont.params), _leaves(resumed.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert float(m_cont["loss"]) == pytest.approx(
            float(m_res["loss"]), rel=1e-6
        )

    def test_restored_leaves_keep_template_sharding(self, tmp_path):
        run, fresh_template = _setup(model_parallel=2)
        state, _ = run(fresh_template(), n=1)
        save_checkpoint(
            str(tmp_path), state, epoch=0, arch="tiny", best_acc1=0.0,
            is_best=False,
        )
        template = fresh_template()
        restored = load_checkpoint(str(tmp_path), template)["state"]
        for t, r in zip(_leaves(template), _leaves(restored)):
            if hasattr(t, "sharding"):
                assert t.sharding.is_equivalent_to(r.sharding, t.ndim), (
                    t.sharding, r.sharding
                )

    def test_distributed_path_tp_roundtrip(self, tmp_path):
        """The collective checkpoint path (used for TP-over-hosts /
        multi-process runs, VERDICT r3 #6-missing): sharded jax.Arrays
        go to Orbax directly (no host materialization) and restore lands
        each leaf in the template's sharding via construct_restore_args.
        Forced on here since tests are single-process."""
        run, fresh_template = _setup(model_parallel=2)
        state, _ = run(fresh_template(), n=2)
        save_checkpoint(
            str(tmp_path), state, epoch=1, arch="tiny", best_acc1=7.0,
            is_best=False, distributed=True,
        )
        template = fresh_template()
        restored = load_checkpoint(str(tmp_path), template, distributed=True)
        assert restored["epoch"] == 2
        assert restored["best_acc1"] == pytest.approx(7.0)
        for t, r in zip(_leaves(template), _leaves(restored["state"])):
            if hasattr(t, "sharding"):
                assert t.sharding.is_equivalent_to(r.sharding, t.ndim)
        for a, b in zip(_leaves(state.params), _leaves(restored["state"].params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # resumed training bit-matches uninterrupted
        cont, m_cont = run(state, n=1)
        resumed, m_res = run(restored["state"], n=1)
        assert float(m_cont["loss"]) == pytest.approx(
            float(m_res["loss"]), rel=1e-6
        )

    def test_reset_resume_keeps_weights_only(self, tmp_path):
        run, fresh_template = _setup()
        state, _ = run(fresh_template(), n=2)
        save_checkpoint(
            str(tmp_path), state, epoch=5, arch="tiny", best_acc1=50.0,
            is_best=False,
        )
        restored = load_checkpoint(
            str(tmp_path), fresh_template(), reset_resume=True
        )
        assert restored["epoch"] == 0
        assert restored["best_acc1"] == 0.0
        # weights taken from ckpt
        for a, b in zip(
            _leaves(state.params), _leaves(restored["state"].params)
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # optimizer state re-initialized (step counter back to 0)
        assert int(restored["state"].step) == 0


class TestCrashSafety:
    def test_crash_at_every_commit_phase_recovers(self, tmp_path, monkeypatch):
        """Simulate a crash between EVERY pair of filesystem operations
        in the save/commit path (rename + rmtree fault injection) and
        assert load_checkpoint ALWAYS returns a usable checkpoint, and
        that the NEXT save succeeds despite the stale tmp/old debris."""
        import os as _os
        import shutil as _shutil

        from bdbnn_tpu.utils import checkpoint as ckpt_mod

        run, fresh_template = _setup()
        s1, _ = run(fresh_template(), n=1)
        # seed a committed checkpoint so every later phase has a
        # predecessor to displace
        save_checkpoint(
            str(tmp_path), s1, epoch=0, arch="tiny", best_acc1=1.0,
            is_best=False,
        )
        state, _ = run(s1, n=1)

        class Crash(RuntimeError):
            pass

        real_rename, real_rmtree = _os.rename, _shutil.rmtree

        def crashing_save(crash_after_n_ops):
            ops = {"n": 0}

            def counted(real):
                def op(*a, **kw):
                    if ops["n"] >= crash_after_n_ops:
                        raise Crash(f"injected crash at fs op {ops['n']}")
                    ops["n"] += 1
                    return real(*a, **kw)

                return op

            # patch the commit-path indirection points (NOT os/shutil
            # globally — Orbax's own internal I/O must stay real)
            monkeypatch.setattr(ckpt_mod, "_rename", counted(real_rename))
            monkeypatch.setattr(ckpt_mod, "_rmtree", counted(real_rmtree))
            try:
                save_checkpoint(
                    str(tmp_path), state, epoch=1, arch="tiny",
                    best_acc1=2.0, is_best=False,
                )
                return False  # save completed: no op at that index
            except Crash:
                return True
            finally:
                monkeypatch.setattr(ckpt_mod, "_rename", real_rename)
                monkeypatch.setattr(ckpt_mod, "_rmtree", real_rmtree)

        phase = 0
        crashed_any = False
        while True:
            crashed = crashing_save(phase)
            crashed_any |= crashed
            # invariant: WHATEVER the crash point, a usable checkpoint
            # loads (epoch 1 survivor or epoch 2 committed)
            restored = load_checkpoint(str(tmp_path), fresh_template())
            assert restored["epoch"] in (1, 2), restored["epoch"]
            # and the next (uninjected) save always succeeds over the
            # debris, landing the new checkpoint
            save_checkpoint(
                str(tmp_path), state, epoch=1, arch="tiny", best_acc1=2.0,
                is_best=False,
            )
            assert load_checkpoint(
                str(tmp_path), fresh_template()
            )["epoch"] == 2
            if not crashed:
                break  # every fs op index has been exercised
            # reset to the seeded predecessor layout for the next phase
            _shutil.rmtree(str(tmp_path))
            save_checkpoint(
                str(tmp_path), s1, epoch=0, arch="tiny", best_acc1=1.0,
                is_best=False,
            )
            phase += 1
        assert crashed_any and phase >= 2  # the matrix actually ran

    def test_old_checkpoint_survives_until_commit(self, tmp_path):
        run, fresh_template = _setup()
        state, _ = run(fresh_template(), n=1)
        save_checkpoint(
            str(tmp_path), state, epoch=0, arch="tiny", best_acc1=1.0,
            is_best=False,
        )
        state2, _ = run(state, n=1)
        save_checkpoint(
            str(tmp_path), state2, epoch=1, arch="tiny", best_acc1=2.0,
            is_best=False,
        )
        restored = load_checkpoint(str(tmp_path), fresh_template())
        assert restored["epoch"] == 2  # saved epoch+1

    def test_fallback_to_old_after_simulated_crash(self, tmp_path):
        import os

        run, fresh_template = _setup()
        state, _ = run(fresh_template(), n=1)
        save_checkpoint(
            str(tmp_path), state, epoch=3, arch="tiny", best_acc1=7.0,
            is_best=False,
        )
        # simulate a crash mid-commit: committed dir renamed to .old,
        # replacement never landed
        target = os.path.join(str(tmp_path), CKPT_NAME)
        os.rename(target, target + ".old")
        restored = load_checkpoint(str(tmp_path), fresh_template())
        assert restored["epoch"] == 4
        assert restored["best_acc1"] == pytest.approx(7.0)


class TestIntegrityAndResumeState:
    """The survivable-I/O layer: per-checkpoint digests, corrupt-dir
    fallback to ``checkpoint.old``, the ``resume.json`` cursor sidecar,
    and bounded-backoff retry on transient FS errors."""

    def test_integrity_ok_and_sidecar_roundtrip(self, tmp_path):
        from bdbnn_tpu.utils.checkpoint import (
            INTEGRITY_NAME,
            read_resume_state,
            verify_integrity,
        )

        run, fresh_template = _setup()
        state, _ = run(fresh_template(), n=1)
        save_checkpoint(
            str(tmp_path), state, epoch=2, arch="tiny", best_acc1=5.0,
            is_best=False, step_in_epoch=3,
            resume_state={"best_epoch": 1, "lr_step": 11,
                          "host_rng": {"name": "MT19937"}},
        )
        import os

        ckpt = os.path.join(str(tmp_path), CKPT_NAME)
        assert os.path.exists(os.path.join(ckpt, INTEGRITY_NAME))
        assert verify_integrity(ckpt) == "ok"
        side = read_resume_state(ckpt)
        # mid-epoch encoding: payload epoch == the epoch to re-enter
        assert side["epoch"] == 2 and side["step_in_epoch"] == 3
        assert side["best_epoch"] == 1 and side["lr_step"] == 11

        restored = load_checkpoint(str(tmp_path), fresh_template())
        assert restored["epoch"] == 2
        assert restored["step_in_epoch"] == 3
        assert restored["best_epoch"] == 1
        assert restored["host_rng"] == {"name": "MT19937"}
        assert restored["integrity"] == "ok"
        assert restored["fallback"] is False

    def test_corrupt_checkpoint_falls_back_to_old(self, tmp_path):
        """Flip bytes in the COMMITTED checkpoint: the digest catches
        it and restore comes from checkpoint.old instead of crashing —
        the acceptance-criteria corruption injection."""
        import glob
        import os

        from bdbnn_tpu.utils.checkpoint import INTEGRITY_NAME

        run, fresh_template = _setup()
        s1, _ = run(fresh_template(), n=1)
        save_checkpoint(
            str(tmp_path), s1, epoch=0, arch="tiny", best_acc1=1.0,
            is_best=False,
        )
        s2, _ = run(s1, n=1)
        save_checkpoint(
            str(tmp_path), s2, epoch=1, arch="tiny", best_acc1=2.0,
            is_best=False,
        )
        ckpt = os.path.join(str(tmp_path), CKPT_NAME)
        assert os.path.isdir(ckpt + ".old")  # retained for fallback
        # corrupt some payload file (not the digest itself)
        victims = [
            p for p in glob.glob(os.path.join(ckpt, "**"), recursive=True)
            if os.path.isfile(p) and not p.endswith(INTEGRITY_NAME)
        ]
        with open(victims[0], "r+b") as f:
            f.write(b"\xde\xad\xbe\xef")
        restored = load_checkpoint(str(tmp_path), fresh_template())
        assert restored["fallback"] is True
        assert restored["source"] == ckpt + ".old"
        assert restored["epoch"] == 1  # the older save's epoch+1
        assert restored["best_acc1"] == pytest.approx(1.0)

    def test_truncated_checkpoint_falls_back_to_old(self, tmp_path):
        """A SIGKILL mid-write leaves a short file: size change ->
        digest mismatch -> fallback."""
        import glob
        import os

        from bdbnn_tpu.utils.checkpoint import INTEGRITY_NAME

        run, fresh_template = _setup()
        s1, _ = run(fresh_template(), n=1)
        save_checkpoint(
            str(tmp_path), s1, epoch=3, arch="tiny", best_acc1=1.0,
            is_best=False,
        )
        s2, _ = run(s1, n=1)
        save_checkpoint(
            str(tmp_path), s2, epoch=4, arch="tiny", best_acc1=2.0,
            is_best=False,
        )
        ckpt = os.path.join(str(tmp_path), CKPT_NAME)
        victims = sorted(
            p for p in glob.glob(os.path.join(ckpt, "**"), recursive=True)
            if os.path.isfile(p) and not p.endswith(INTEGRITY_NAME)
            and os.path.getsize(p) > 8
        )
        with open(victims[-1], "r+b") as f:
            f.truncate(4)
        restored = load_checkpoint(str(tmp_path), fresh_template())
        assert restored["fallback"] is True
        assert restored["epoch"] == 4

    def test_all_candidates_corrupt_raises_with_reasons(self, tmp_path):
        import os

        run, fresh_template = _setup()
        s1, _ = run(fresh_template(), n=1)
        save_checkpoint(
            str(tmp_path), s1, epoch=0, arch="tiny", best_acc1=1.0,
            is_best=False,
        )
        ckpt = os.path.join(str(tmp_path), CKPT_NAME)
        # corrupt the only candidate
        from bdbnn_tpu.utils.checkpoint import INTEGRITY_NAME

        with open(os.path.join(ckpt, INTEGRITY_NAME), "w") as f:
            f.write('{"algo": "sha256", "digest": "beef"}')
        with pytest.raises(RuntimeError, match="integrity digest mismatch"):
            load_checkpoint(str(tmp_path), fresh_template())

    def test_missing_digest_is_trusted_backward_compat(self, tmp_path):
        """Pre-resilience checkpoints (no INTEGRITY.json) keep loading."""
        import os

        from bdbnn_tpu.utils.checkpoint import INTEGRITY_NAME, RESUME_NAME

        run, fresh_template = _setup()
        state, _ = run(fresh_template(), n=1)
        save_checkpoint(
            str(tmp_path), state, epoch=5, arch="tiny", best_acc1=9.0,
            is_best=False,
        )
        ckpt = os.path.join(str(tmp_path), CKPT_NAME)
        os.remove(os.path.join(ckpt, INTEGRITY_NAME))
        os.remove(os.path.join(ckpt, RESUME_NAME))
        restored = load_checkpoint(str(tmp_path), fresh_template())
        assert restored["epoch"] == 6
        assert restored["integrity"] == "missing"
        assert restored["step_in_epoch"] == 0 and restored["host_rng"] is None

    def test_stale_tmp_from_crashed_save_is_cleaned(self, tmp_path):
        """A crashed save's leftover checkpoint.tmp must not collide
        with (Orbax would refuse to overwrite it) or survive the next
        save."""
        import os

        run, fresh_template = _setup()
        state, _ = run(fresh_template(), n=1)
        stale = os.path.join(str(tmp_path), CKPT_NAME + ".tmp")
        os.makedirs(stale)
        with open(os.path.join(stale, "junk"), "w") as f:
            f.write("torn save debris")
        save_checkpoint(
            str(tmp_path), state, epoch=0, arch="tiny", best_acc1=1.0,
            is_best=True,
        )
        assert not os.path.exists(stale)
        assert load_checkpoint(str(tmp_path), fresh_template())["epoch"] == 1

    def test_retry_io_backs_off_then_succeeds(self):
        from bdbnn_tpu.utils.checkpoint import retry_io

        calls = {"n": 0}
        sleeps = []

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError("transient FS blip")
            return "ok"

        assert retry_io(flaky, sleep=sleeps.append) == "ok"
        assert calls["n"] == 3
        assert sleeps == [0.05, 0.1]  # bounded exponential backoff

    def test_retry_io_gives_up_and_raises(self):
        from bdbnn_tpu.utils.checkpoint import retry_io

        sleeps = []

        def always_fails():
            raise OSError("permanent")

        with pytest.raises(OSError, match="permanent"):
            retry_io(always_fails, attempts=3, sleep=sleeps.append)
        assert len(sleeps) == 2  # no sleep after the final attempt

    def test_retry_io_does_not_catch_non_io_errors(self):
        from bdbnn_tpu.utils.checkpoint import retry_io

        with pytest.raises(ValueError):
            retry_io(
                lambda: (_ for _ in ()).throw(ValueError("logic bug")),
                sleep=lambda s: pytest.fail("must not retry"),
            )


class TestLoadVariables:
    """``load_variables`` — the template-free weights-only restore that
    backs native (Orbax) KD teachers (``--resume-teacher <run dir>``,
    build_teacher in train/loop.py; ↔ the reference's torch-teacher
    load, train.py:258-277)."""

    def test_roundtrip_params_and_batch_stats(self, tmp_path):
        from bdbnn_tpu.utils.checkpoint import load_variables

        run, fresh_template = _setup()
        state, _ = run(fresh_template(), n=2)
        save_checkpoint(
            str(tmp_path), state, epoch=0, arch="tiny", best_acc1=1.0,
            is_best=False,
        )
        loaded = load_variables(str(tmp_path))
        assert set(loaded) == {"params", "batch_stats"}
        want = jax.device_get(state.params)
        got = loaded["params"]
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            want,
            got,
        )
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            jax.device_get(state.batch_stats),
            loaded["batch_stats"],
        )

    def test_prefers_model_best_over_checkpoint(self, tmp_path):
        from bdbnn_tpu.utils.checkpoint import load_variables

        run, fresh_template = _setup()
        s1, _ = run(fresh_template(), n=1)
        save_checkpoint(
            str(tmp_path), s1, epoch=0, arch="tiny", best_acc1=1.0,
            is_best=True,  # model_best = s1
        )
        # fetch BEFORE stepping again: the jitted step donates the
        # input state, deleting s1's buffers
        best_leaf = np.asarray(_leaves(jax.device_get(s1.params))[0])
        s2, _ = run(s1, n=1)
        save_checkpoint(
            str(tmp_path), s2, epoch=1, arch="tiny", best_acc1=1.0,
            is_best=False,  # checkpoint = s2, model_best stays s1
        )
        loaded = load_variables(str(tmp_path))
        got_leaf = np.asarray(_leaves(loaded["params"])[0])
        np.testing.assert_array_equal(got_leaf, best_leaf)

    def test_rejects_non_checkpoint(self, tmp_path):
        from bdbnn_tpu.utils.checkpoint import load_variables

        with pytest.raises(Exception):
            load_variables(str(tmp_path / "nope"))
