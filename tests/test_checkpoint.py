"""Checkpoint round-trip tests on the 8-device CPU mesh.

Round-1 gaps: restore dropped the GSPMD shardings (resume re-placed
params by jit default) and save rmtree'd the old checkpoint before the
new one existed. These tests pin: (a) save → restore → step on a mesh
bit-matches uninterrupted training, (b) restored leaves carry the
template's shardings, (c) a crash that leaves only ``checkpoint.old``
still resumes. (↔ reference resume, ``train.py:345-366``.)
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bdbnn_tpu.models.resnet import BiResNet
from bdbnn_tpu.parallel import (
    create_sharded_state,
    jit_train_step,
    make_mesh,
    shard_batch,
)
from bdbnn_tpu.train import StepConfig, TrainState, make_optimizer, make_train_step
from bdbnn_tpu.utils.checkpoint import (
    CKPT_NAME,
    load_checkpoint,
    save_checkpoint,
)


def _setup(model_parallel=1):
    model = BiResNet(
        stage_sizes=(1, 1), num_classes=4, width=8,
        stem="cifar", variant="cifar", act="hardtanh",
    )
    mesh = make_mesh(model_parallel=model_parallel)
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8, 8, 3)), train=True
    )
    tx = make_optimizer(
        variables["params"], dataset="cifar10", lr=0.05,
        epochs=10, steps_per_epoch=100,
    )
    state = create_sharded_state(mesh, variables, tx, TrainState)
    step = jit_train_step(make_train_step(model, tx, StepConfig()))
    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 8, 8, 3)).astype(np.float32)
    y = rng.integers(0, 4, size=(16,))
    tk = (jnp.float32(1.0), jnp.float32(1.0))

    def run(state, n=1):
        for _ in range(n):
            gx, gy = shard_batch(mesh, x, y)
            state, m = step(state, (gx, gy), tk, jnp.float32(0.0))
        return state, m

    def fresh_template():
        v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8, 8, 3)), train=True)
        return create_sharded_state(mesh, v, tx, TrainState)

    return run, fresh_template


def _leaves(tree):
    return jax.tree_util.tree_leaves(tree)


class TestMeshRoundTrip:
    def test_resume_bitmatches_uninterrupted(self, tmp_path):
        run, fresh_template = _setup()
        state, _ = run(fresh_template(), n=2)
        save_checkpoint(
            str(tmp_path), state, epoch=1, arch="tiny", best_acc1=11.0,
            is_best=True,
        )
        # uninterrupted: 2 more steps from the live state
        cont, m_cont = run(state, n=2)

        restored = load_checkpoint(str(tmp_path), fresh_template())
        assert restored["epoch"] == 2
        assert restored["best_acc1"] == pytest.approx(11.0)
        resumed, m_res = run(restored["state"], n=2)

        for a, b in zip(_leaves(cont.params), _leaves(resumed.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert float(m_cont["loss"]) == pytest.approx(
            float(m_res["loss"]), rel=1e-6
        )

    def test_restored_leaves_keep_template_sharding(self, tmp_path):
        run, fresh_template = _setup(model_parallel=2)
        state, _ = run(fresh_template(), n=1)
        save_checkpoint(
            str(tmp_path), state, epoch=0, arch="tiny", best_acc1=0.0,
            is_best=False,
        )
        template = fresh_template()
        restored = load_checkpoint(str(tmp_path), template)["state"]
        for t, r in zip(_leaves(template), _leaves(restored)):
            if hasattr(t, "sharding"):
                assert t.sharding.is_equivalent_to(r.sharding, t.ndim), (
                    t.sharding, r.sharding
                )

    def test_distributed_path_tp_roundtrip(self, tmp_path):
        """The collective checkpoint path (used for TP-over-hosts /
        multi-process runs, VERDICT r3 #6-missing): sharded jax.Arrays
        go to Orbax directly (no host materialization) and restore lands
        each leaf in the template's sharding via construct_restore_args.
        Forced on here since tests are single-process."""
        run, fresh_template = _setup(model_parallel=2)
        state, _ = run(fresh_template(), n=2)
        save_checkpoint(
            str(tmp_path), state, epoch=1, arch="tiny", best_acc1=7.0,
            is_best=False, distributed=True,
        )
        template = fresh_template()
        restored = load_checkpoint(str(tmp_path), template, distributed=True)
        assert restored["epoch"] == 2
        assert restored["best_acc1"] == pytest.approx(7.0)
        for t, r in zip(_leaves(template), _leaves(restored["state"])):
            if hasattr(t, "sharding"):
                assert t.sharding.is_equivalent_to(r.sharding, t.ndim)
        for a, b in zip(_leaves(state.params), _leaves(restored["state"].params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # resumed training bit-matches uninterrupted
        cont, m_cont = run(state, n=1)
        resumed, m_res = run(restored["state"], n=1)
        assert float(m_cont["loss"]) == pytest.approx(
            float(m_res["loss"]), rel=1e-6
        )

    def test_reset_resume_keeps_weights_only(self, tmp_path):
        run, fresh_template = _setup()
        state, _ = run(fresh_template(), n=2)
        save_checkpoint(
            str(tmp_path), state, epoch=5, arch="tiny", best_acc1=50.0,
            is_best=False,
        )
        restored = load_checkpoint(
            str(tmp_path), fresh_template(), reset_resume=True
        )
        assert restored["epoch"] == 0
        assert restored["best_acc1"] == 0.0
        # weights taken from ckpt
        for a, b in zip(
            _leaves(state.params), _leaves(restored["state"].params)
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # optimizer state re-initialized (step counter back to 0)
        assert int(restored["state"].step) == 0


class TestCrashSafety:
    def test_old_checkpoint_survives_until_commit(self, tmp_path):
        run, fresh_template = _setup()
        state, _ = run(fresh_template(), n=1)
        save_checkpoint(
            str(tmp_path), state, epoch=0, arch="tiny", best_acc1=1.0,
            is_best=False,
        )
        state2, _ = run(state, n=1)
        save_checkpoint(
            str(tmp_path), state2, epoch=1, arch="tiny", best_acc1=2.0,
            is_best=False,
        )
        restored = load_checkpoint(str(tmp_path), fresh_template())
        assert restored["epoch"] == 2  # saved epoch+1

    def test_fallback_to_old_after_simulated_crash(self, tmp_path):
        import os

        run, fresh_template = _setup()
        state, _ = run(fresh_template(), n=1)
        save_checkpoint(
            str(tmp_path), state, epoch=3, arch="tiny", best_acc1=7.0,
            is_best=False,
        )
        # simulate a crash mid-commit: committed dir renamed to .old,
        # replacement never landed
        target = os.path.join(str(tmp_path), CKPT_NAME)
        os.rename(target, target + ".old")
        restored = load_checkpoint(str(tmp_path), fresh_template())
        assert restored["epoch"] == 4
        assert restored["best_acc1"] == pytest.approx(7.0)


class TestLoadVariables:
    """``load_variables`` — the template-free weights-only restore that
    backs native (Orbax) KD teachers (``--resume-teacher <run dir>``,
    build_teacher in train/loop.py; ↔ the reference's torch-teacher
    load, train.py:258-277)."""

    def test_roundtrip_params_and_batch_stats(self, tmp_path):
        from bdbnn_tpu.utils.checkpoint import load_variables

        run, fresh_template = _setup()
        state, _ = run(fresh_template(), n=2)
        save_checkpoint(
            str(tmp_path), state, epoch=0, arch="tiny", best_acc1=1.0,
            is_best=False,
        )
        loaded = load_variables(str(tmp_path))
        assert set(loaded) == {"params", "batch_stats"}
        want = jax.device_get(state.params)
        got = loaded["params"]
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            want,
            got,
        )
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            jax.device_get(state.batch_stats),
            loaded["batch_stats"],
        )

    def test_prefers_model_best_over_checkpoint(self, tmp_path):
        from bdbnn_tpu.utils.checkpoint import load_variables

        run, fresh_template = _setup()
        s1, _ = run(fresh_template(), n=1)
        save_checkpoint(
            str(tmp_path), s1, epoch=0, arch="tiny", best_acc1=1.0,
            is_best=True,  # model_best = s1
        )
        # fetch BEFORE stepping again: the jitted step donates the
        # input state, deleting s1's buffers
        best_leaf = np.asarray(_leaves(jax.device_get(s1.params))[0])
        s2, _ = run(s1, n=1)
        save_checkpoint(
            str(tmp_path), s2, epoch=1, arch="tiny", best_acc1=1.0,
            is_best=False,  # checkpoint = s2, model_best stays s1
        )
        loaded = load_variables(str(tmp_path))
        got_leaf = np.asarray(_leaves(loaded["params"])[0])
        np.testing.assert_array_equal(got_leaf, best_leaf)

    def test_rejects_non_checkpoint(self, tmp_path):
        from bdbnn_tpu.utils.checkpoint import load_variables

        with pytest.raises(Exception):
            load_variables(str(tmp_path / "nope"))
