"""Binary-conv hot-spot tests: exactness + gradient correctness.

The single surviving implementation is the stock XLA conv on ±1
operands behind a ``custom_vjp`` (the int8/Pallas candidates were
deleted with measurement — decision record in
``bdbnn_tpu/nn/kernels/binary_conv.py``). These tests pin:

- the wrapper is transparent (identical to the plain float conv);
- the custom backward equals the float conv's VJP — the whole
  training path depends on it;
- deleted impl names are rejected loudly, not silently aliased.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bdbnn_tpu.nn.binarize import ste_sign
from bdbnn_tpu.nn.kernels import binary_conv2d_mxu, default_impl
from bdbnn_tpu.nn.layers import conv2d


def _pm1(rng, shape):
    return np.sign(rng.normal(size=shape) + 1e-9).astype(np.float32)


def _alpha(rng, o):
    return rng.uniform(0.1, 2.0, size=(o,)).astype(np.float32)


CASES = [
    # (N, H, W, C, O, k, stride)
    (2, 8, 8, 16, 32, 3, 1),
    (2, 9, 9, 8, 16, 3, 1),   # odd spatial
    (2, 8, 8, 16, 32, 3, 2),  # strided
    (1, 8, 8, 16, 32, 1, 1),  # 1x1 (downsample path)
]


def _ref(xb, wb, alpha, stride):
    y = conv2d(xb, wb, strides=(stride, stride))
    return y * alpha.reshape(1, 1, 1, -1)


class TestExactness:
    @pytest.mark.parametrize("case", CASES)
    def test_matches_float_conv_exactly(self, case):
        n, h, w, c, o, k, stride = case
        rng = np.random.default_rng(0)
        xb = jnp.asarray(_pm1(rng, (n, h, w, c)))
        wb = jnp.asarray(_pm1(rng, (k, k, c, o)))
        alpha = jnp.asarray(_alpha(rng, o))
        ref = _ref(xb, wb, alpha, stride)
        out = binary_conv2d_mxu(xb, wb, alpha, strides=(stride, stride))
        assert out.shape == ref.shape
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_default_impl_context(self):
        rng = np.random.default_rng(1)
        xb = jnp.asarray(_pm1(rng, (1, 8, 8, 8)))
        wb = jnp.asarray(_pm1(rng, (3, 3, 8, 8)))
        alpha = jnp.asarray(_alpha(rng, 8))
        with default_impl("dot"):
            out = binary_conv2d_mxu(xb, wb, alpha)
        np.testing.assert_array_equal(
            np.asarray(out), np.asarray(_ref(xb, wb, alpha, 1))
        )

    def test_deleted_impls_rejected(self):
        rng = np.random.default_rng(5)
        xb = jnp.asarray(_pm1(rng, (1, 8, 8, 8)))
        wb = jnp.asarray(_pm1(rng, (3, 3, 8, 8)))
        alpha = jnp.asarray(_alpha(rng, 8))
        for dead in ("xla_int8", "pallas"):
            with pytest.raises(ValueError):
                binary_conv2d_mxu(xb, wb, alpha, impl=dead)
            with pytest.raises(ValueError):
                with default_impl(dead):
                    pass


class TestGradients:
    def test_custom_vjp_matches_float_conv_grads(self):
        """The wrapper's backward must equal the float conv's VJP —
        the whole training path depends on it."""
        rng = np.random.default_rng(2)
        n, h, w, c, o = 2, 8, 8, 8, 16
        x = jnp.asarray(rng.normal(size=(n, h, w, c)).astype(np.float32))
        lat = jnp.asarray(
            rng.normal(size=(3, 3, c, o)).astype(np.float32)
        )
        alpha = jnp.asarray(_alpha(rng, o))

        def loss_wrapped(x, lat):
            xb = ste_sign(x)
            wb = ste_sign(lat)
            y = binary_conv2d_mxu(xb, wb, alpha)
            return jnp.sum(y * y)

        def loss_ref(x, lat):
            xb = ste_sign(x)
            wb = ste_sign(lat) * alpha.reshape(1, 1, 1, -1)
            y = conv2d(xb, wb)
            return jnp.sum(y * y)

        gx_f, gl_f = jax.grad(loss_wrapped, argnums=(0, 1))(x, lat)
        gx_r, gl_r = jax.grad(loss_ref, argnums=(0, 1))(x, lat)
        # forward is bit-exact; grads differ only by f32 reduction order
        # in the two conv formulations (~1e-4 relative)
        np.testing.assert_allclose(
            np.asarray(gx_f), np.asarray(gx_r), rtol=1e-3, atol=1e-3
        )
        np.testing.assert_allclose(
            np.asarray(gl_f), np.asarray(gl_r), rtol=1e-3, atol=1e-3
        )


class TestLayerIntegration:
    def test_layer_routes_through_wrapper(self):
        """The conv layers route through binary_conv2d_mxu — output
        must equal the layer's math done by hand."""
        from bdbnn_tpu.nn.layers import BinaryConvCifar

        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.normal(size=(2, 8, 8, 8)).astype(np.float32))
        layer = BinaryConvCifar(features=16)
        v = layer.init(jax.random.PRNGKey(0), x)
        with default_impl("dot"):
            y_dot = layer.apply(v, x)
        y_auto = layer.apply(v, x)
        np.testing.assert_array_equal(np.asarray(y_dot), np.asarray(y_auto))

    def test_bf16_inputs(self):
        rng = np.random.default_rng(4)
        xb = jnp.asarray(_pm1(rng, (1, 8, 8, 8))).astype(jnp.bfloat16)
        wb = jnp.asarray(_pm1(rng, (3, 3, 8, 8)))
        alpha = jnp.asarray(_alpha(rng, 8))
        out = binary_conv2d_mxu(xb, wb, alpha)
        assert out.dtype == jnp.bfloat16
        ref = _ref(xb.astype(jnp.float32), wb, alpha, 1)
        np.testing.assert_allclose(
            np.asarray(out, dtype=np.float32), np.asarray(ref),
            rtol=2e-2, atol=1e-2,  # bf16 rounding of alpha product only
        )
