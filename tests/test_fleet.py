"""Cross-host serving fleet tests (serve/fleet.py, registry.pull).

Three tiers:

1. **Router failure taxonomy in isolation** — scriptable stub backends
   (raw threaded HTTP servers, no JAX) pin the per-host backoff
   schedule, retry-never-duplicates (idempotent proxy accounting),
   the relayed-vs-retried 429/503 split, the draining-host bleed, the
   probe state machine (warmup→debounce→hysteresis via the shared
   DetectorState), and the host-by-host fleet-swap serialization.
2. **Registry replication** — digest-verified ``pull`` between two
   on-disk registries, including the torn-remote case that must leave
   the local registry untouched.
3. **The fleet acceptance e2e** — 2 REAL serve-http host subprocesses
   (the tests/pod_worker.py recipe: each pinned to its own simulated
   device count, real sockets, the real CLI) behind the router,
   flash-crowd load, SIGTERM one host mid-burst → zero client-visible
   drops, the drained host's accepted requests answered by peers,
   per-host ledgers summing to the client totals in the v6 ``fleet``
   verdict block, and the episode consumed by watch/summarize/compare.
   The SIGKILL variant is ``slow``-marked.

Host ports in the e2e are kernel-assigned (``--port 0``) and
discovered from each host's ``http`` start event — no cross-process
port race at all; the conftest allocator's bind-and-hold handoff
covers the ports tests DO pre-allocate in-process. Cluster formation
is quarantined behind ``conftest.retry_once_flaky`` (tracking note in
the fixture) for the documented subprocess bring-up transient.
"""

import glob
import json
import os
import re
import signal
import socket
import socketserver
import subprocess
import sys
import threading
import time

import pytest

from bdbnn_tpu.configs.config import ServeFleetConfig
from bdbnn_tpu.obs.events import read_jsonl
from bdbnn_tpu.serve.fleet import (
    HOST_DEAD,
    HOST_DRAINING,
    HOST_READY,
    FleetRouter,
    backoff_s,
    fleet_slo_verdict,
    parse_hosts,
    run_serve_fleet,
)
from bdbnn_tpu.serve.loadgen import recv_response

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(TESTS_DIR)


# ---------------------------------------------------------------------------
# helpers: a scriptable stub backend + a raw one-shot HTTP client
# ---------------------------------------------------------------------------


class StubBackend:
    """A minimal threaded HTTP backend whose behavior per route is
    scripted by the test: the router sees a real socket peer without
    any JAX/engine machinery. ``predict`` returns ``(status, obj)`` or
    the string ``"die"`` to tear the connection without a response
    (the SIGKILL-shaped transport failure)."""

    def __init__(self, server_id, predict=None, admin=None):
        self.server_id = server_id
        self.predict = predict or (
            lambda headers, body: (200, {"result": 1})
        )
        self.admin = admin
        self.ready_state = "ready"
        self.predict_seen = 0
        self._lock = threading.Lock()
        backend = self

        class Handler(socketserver.StreamRequestHandler):
            timeout = 10.0

            def handle(self):
                from bdbnn_tpu.serve.fleet import _read_request

                while True:
                    try:
                        req = _read_request(self.rfile, 2**20)
                    except (ValueError, OSError):
                        return
                    if req is None:
                        return
                    method, path, headers, body = req
                    out = backend._route(method, path, headers, body)
                    if out == "die":
                        return  # close without a response
                    status, obj = out
                    payload = json.dumps(obj).encode()
                    head = (
                        f"HTTP/1.1 {status} X\r\n"
                        "content-type: application/json\r\n"
                        f"content-length: {len(payload)}\r\n"
                    )
                    if status in (429, 503):
                        head += "retry-after: 1\r\n"
                    try:
                        self.wfile.write(
                            head.encode() + b"\r\n" + payload
                        )
                        self.wfile.flush()
                    except OSError:
                        return
                    if headers.get("connection", "") == "close":
                        return

        class Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._srv = Server(("127.0.0.1", 0), Handler)
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True,
            kwargs={"poll_interval": 0.05},
        )
        self._thread.start()

    def _route(self, method, path, headers, body):
        if path == "/readyz":
            state = self.ready_state
            return (
                (200, {"state": state})
                if state == "ready"
                else (503, {"state": state})
            )
        if path == "/statsz":
            return 200, {
                "state": self.ready_state,
                "inflight": 0,
                "server_id": self.server_id,
            }
        if path.startswith("/admin/swap") and self.admin is not None:
            return self.admin(method, body)
        if path == "/v1/predict":
            with self._lock:
                self.predict_seen += 1
            return self.predict(headers, body)
        return 404, {"error": "no route"}

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()
        self._thread.join(timeout=5.0)


def _predict(host, port, body=b"[1]", priority=0, timeout=10.0):
    """One raw predict against a router — (status, headers, obj)."""
    s = socket.create_connection((host, port), timeout=timeout)
    try:
        s.sendall(
            (
                f"POST /v1/predict HTTP/1.1\r\nhost: x\r\n"
                f"x-priority: {priority}\r\n"
                "content-type: application/octet-stream\r\n"
                f"content-length: {len(body)}\r\n"
                "connection: close\r\n\r\n"
            ).encode()
            + body
        )
        status, headers, raw = recv_response(s.makefile("rb"))
        return status, headers, json.loads(raw) if raw else None
    finally:
        s.close()


def _router_over(backends, **kw):
    kw.setdefault("probe_interval_s", 0.05)
    kw.setdefault("probe_timeout_s", 1.0)
    kw.setdefault("health_debounce", 2)
    kw.setdefault("backoff_base_s", 0.001)
    kw.setdefault("backoff_cap_s", 0.01)
    router = FleetRouter(
        [("127.0.0.1", b.port) for b in backends], **kw
    )
    router.start()
    assert router.wait_ready(10.0), "no backend probed ready"
    return router


# ---------------------------------------------------------------------------
# 1. router failure taxonomy in isolation
# ---------------------------------------------------------------------------


class TestBackoffSchedule:
    def test_schedule_pins(self):
        """The per-host retry backoff schedule, pinned value by value:
        base*2^attempt, hard-capped — a refactor cannot silently turn
        bounded backoff into a hot retry loop or an unbounded sleep."""
        assert backoff_s(0, 0.025, 0.25) == pytest.approx(0.025)
        assert backoff_s(1, 0.025, 0.25) == pytest.approx(0.05)
        assert backoff_s(2, 0.025, 0.25) == pytest.approx(0.1)
        assert backoff_s(3, 0.025, 0.25) == pytest.approx(0.2)
        assert backoff_s(4, 0.025, 0.25) == pytest.approx(0.25)  # cap
        assert backoff_s(50, 0.025, 0.25) == pytest.approx(0.25)
        assert backoff_s(-1, 0.025, 0.25) == pytest.approx(0.025)

    def test_parse_hosts(self):
        assert parse_hosts(("127.0.0.1:81", "h:9")) == [
            ("127.0.0.1", 81), ("h", 9),
        ]


class TestFleetConfigValidation:
    def test_needs_hosts(self):
        with pytest.raises(ValueError, match="at least one backend"):
            ServeFleetConfig(hosts=()).validate()

    def test_bad_host_spec(self):
        with pytest.raises(ValueError, match="HOST:PORT"):
            ServeFleetConfig(hosts=("nope",)).validate()
        with pytest.raises(ValueError, match="duplicate"):
            ServeFleetConfig(
                hosts=("a:1", "a:1")
            ).validate()

    def test_scenario_needs_artifact(self):
        with pytest.raises(ValueError, match="ARTIFACT"):
            ServeFleetConfig(
                hosts=("a:1",), scenario="poisson"
            ).validate()

    def test_swap_version_needs_registry(self):
        with pytest.raises(ValueError, match="registry"):
            ServeFleetConfig(
                hosts=("a:1",), swap_to="v0002"
            ).validate()

    def test_host_registries_arity(self):
        with pytest.raises(ValueError, match="one registry root per"):
            ServeFleetConfig(
                hosts=("a:1", "b:2"), host_registries=("r1",)
            ).validate()

    def test_swap_at_needs_scenario_and_target(self):
        with pytest.raises(ValueError, match="swap-to"):
            ServeFleetConfig(hosts=("a:1",), swap_at=0.5).validate()


class TestRouterTaxonomy:
    def test_spreads_by_occupancy_and_health(self):
        a, b = StubBackend("a"), StubBackend("b")
        router = _router_over([a, b])
        try:
            for _ in range(12):
                status, headers, obj = _predict(
                    "127.0.0.1", router.port
                )
                assert status == 200
                assert headers.get("x-served-by") in ("h0", "h1")
            stats = router.stats()
            # both hosts took load; identity advertised via /statsz
            assert stats["hosts"]["h0"]["completed"] > 0
            assert stats["hosts"]["h1"]["completed"] > 0
            assert stats["hosts"]["h0"]["server_id"] == "a"
            assert stats["hosts"]["h1"]["server_id"] == "b"
            assert (
                stats["hosts"]["h0"]["completed"]
                + stats["hosts"]["h1"]["completed"]
                == 12
            )
        finally:
            router.drain(5.0)
            a.stop()
            b.stop()

    def test_retry_never_duplicates(self):
        """A host tearing every predict connection (reset, no
        response) burns retries — ledgered per host and per cause —
        while the peer answers each request EXACTLY once: idempotent
        proxy accounting, client sees only 200s."""
        a = StubBackend("a", predict=lambda h, b: "die")
        b = StubBackend("b")
        router = _router_over([a, b], max_attempts=3)
        try:
            n = 10
            for _ in range(n):
                status, _h, _o = _predict("127.0.0.1", router.port)
                assert status == 200
            stats = router.stats()
            h0, h1 = stats["hosts"]["h0"], stats["hosts"]["h1"]
            # the peer answered every request once — never a duplicate
            # completion anywhere in the ledger
            assert h1["completed"] == b.predict_seen
            assert h0["completed"] == 0
            assert h1["completed"] + h0["completed"] == n
            # every torn attempt ledgered on the torn host, by cause
            assert h0["retried_away"] == h0["retries"]["reset"]
            assert h0["retried_away"] > 0
            assert h0["retried_away"] == a.predict_seen
            assert sum(h1["retries"].values()) == 0
        finally:
            router.drain(5.0)
            a.stop()
            b.stop()

    def test_connect_refused_retries_on_peer(self):
        """A host that dies between probe-ready and dispatch (the
        SIGKILL window): connect refused -> retried on the peer, cause
        'connect' ledgered, zero client-visible failures."""
        a, b = StubBackend("a"), StubBackend("b")
        router = _router_over([a, b], probe_interval_s=5.0)
        try:
            # probes have seen both hosts ready; now kill a's listener
            # — the prober (5s interval) cannot save the router, only
            # the per-request retry can
            a.stop()
            completed = 0
            for _ in range(8):
                status, _h, _o = _predict("127.0.0.1", router.port)
                assert status == 200
                completed += 1
            stats = router.stats()
            assert stats["hosts"]["h1"]["completed"] == completed
            h0 = stats["hosts"]["h0"]
            assert h0["retries"]["connect"] + h0["retries"]["reset"] > 0
            assert h0["completed"] == 0
        finally:
            router.drain(5.0)
            b.stop()

    def test_relayed_429_503_not_retried(self):
        """A well-formed backend shed is RELAYED with its taxonomy
        (and retry-after) intact — never retried into a duplicate on
        the healthy peer."""
        a = StubBackend(
            "a", predict=lambda h, b: (503, {"error": "queue full"})
        )
        router = _router_over([a])
        try:
            status, headers, obj = _predict("127.0.0.1", router.port)
            assert status == 503
            assert obj["error"] == "queue full"
            assert headers.get("retry-after") == "1"
            status, _h, obj = _predict(
                "127.0.0.1", router.port, priority=1
            )
            assert status == 503
            a.predict = lambda h, b: (429, {"error": "over_quota"})
            status, headers, obj = _predict("127.0.0.1", router.port)
            assert status == 429 and obj["error"] == "over_quota"
            stats = router.stats()
            h0 = stats["hosts"]["h0"]
            assert h0["relayed_503"] == 2
            assert h0["relayed_429"] == 1
            assert sum(h0["retries"].values()) == 0
            # the per-priority ledger files each relay under the
            # backend's own reason
            acct = router.accounting()
            assert acct["counts_by_priority"][0][
                "shed_queue_full"] == 1
            assert acct["counts_by_priority"][1][
                "shed_queue_full"] == 1
            assert acct["counts_by_priority"][0][
                "shed_over_quota"] == 1
        finally:
            router.drain(5.0)
            a.stop()

    def test_draining_host_bleeds_and_leaves_dispatch(self):
        """A host flipping /readyz to draining leaves the dispatch set
        on the next probe WITHOUT burning the failure detector; its
        in-flight work completes (the bleed); with no host left the
        router's own shed is explicit — never a dropped connection."""
        gate = threading.Event()

        def slow_predict(headers, body):
            gate.wait(5.0)
            return 200, {"result": "slow"}

        a = StubBackend("a", predict=slow_predict)
        router = _router_over([a])
        try:
            results = []
            t = threading.Thread(
                target=lambda: results.append(
                    _predict("127.0.0.1", router.port)
                )
            )
            t.start()
            time.sleep(0.2)  # request is in flight on a
            a.ready_state = "draining"
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                with router._lock:
                    if router.hosts[0].state == HOST_DRAINING:
                        break
                time.sleep(0.02)
            with router._lock:
                assert router.hosts[0].state == HOST_DRAINING
                assert router.hosts[0].detector.fired == 0
            gate.set()  # the bleed: the accepted request completes
            t.join(5.0)
            assert results and results[0][0] == 200
            # new traffic: no dispatchable host -> explicit 503
            status, headers, obj = _predict("127.0.0.1", router.port)
            assert status == 503
            assert obj["error"] == "no host available"
            assert headers.get("retry-after")
            acct = router.accounting()
            assert acct["counts_by_priority"][0][
                "shed_unavailable"] == 1
        finally:
            gate.set()
            router.drain(5.0)
            a.stop()

    def test_probe_state_machine_debounce_and_recovery(self):
        """warmup→debounce→hysteresis, probed DETERMINISTICALLY (the
        probe loop parked on a long interval; the test drives
        _probe_host by hand): two failed probes are not death under
        debounce 3; the third fires exactly once; a dead host re-arms
        on the first successful probe."""
        a = StubBackend("a")
        port = a.port
        router = FleetRouter(
            [("127.0.0.1", port)],
            probe_interval_s=60.0,  # park the loop: manual probes only
            probe_timeout_s=0.5,
            health_debounce=3,
        )
        router.start()
        h = router.hosts[0]
        try:
            router._probe_host(h)
            with router._lock:
                assert h.state == HOST_READY
            a.stop()  # connect refused from here on
            router._probe_host(h)
            router._probe_host(h)
            with router._lock:
                # two consecutive breaches: below debounce, the last
                # known state holds — one blip is not an eviction
                assert h.state == HOST_READY
                assert h.detector.fired == 0
            router._probe_host(h)
            with router._lock:
                assert h.state == HOST_DEAD
                assert h.detector.fired == 1
            router._probe_host(h)  # still dead, no double-fire
            with router._lock:
                assert h.state == HOST_DEAD
                assert h.detector.fired == 1
            # resurrection on the SAME port: hysteresis re-arms on the
            # first good probe and the host returns to dispatch
            b = StubBackend("a2")
            b._srv.server_close()
            srv = type(b._srv)(
                ("127.0.0.1", port), b._srv.RequestHandlerClass
            )
            b._srv = srv
            threading.Thread(
                target=srv.serve_forever, daemon=True,
                kwargs={"poll_interval": 0.05},
            ).start()
            router._probe_host(h)
            with router._lock:
                assert h.state == HOST_READY
                assert h.transitions >= 2
            srv.shutdown()
            srv.server_close()
        finally:
            router.drain(5.0)

    def test_statsz_failure_never_feeds_the_detector(self):
        """/statsz is enrichment only: a host that ANSWERS /readyz is
        alive even when its stats route tears every connection — the
        failure detector must never fire off the enrichment fetch
        (review-hardening pin)."""
        a = StubBackend("a")
        orig_route = a._route

        def route(method, path, headers, body):
            if path == "/statsz":
                return "die"  # torn connection on the stats fetch
            return orig_route(method, path, headers, body)

        a._route = route
        router = FleetRouter(
            [("127.0.0.1", a.port)],
            probe_interval_s=60.0,
            probe_timeout_s=0.5,
            health_debounce=2,
        )
        router.start()
        h = router.hosts[0]
        try:
            for _ in range(5):  # well past debounce
                router._probe_host(h)
            with router._lock:
                assert h.state == HOST_READY
                assert h.detector.fired == 0
                assert h.last_statsz is None  # stale, not fatal
            status, _h, _o = _predict("127.0.0.1", router.port)
            assert status == 200
        finally:
            router.drain(5.0)
            a.stop()

    def test_fleet_swap_host_by_host(self):
        """The fleet rollout shifts hosts SERIALLY: at no instant are
        two hosts' swap machines active, and the router polls each to
        a terminal state before touching the next."""
        active = []
        max_active = [0]
        lock = threading.Lock()

        def make_admin(label):
            state = {"state": "idle"}

            def admin(method, body):
                if method == "POST":
                    with lock:
                        active.append(label)
                        max_active[0] = max(
                            max_active[0], len(active)
                        )
                    state["state"] = "shifting"

                    def finish():
                        time.sleep(0.15)
                        state["state"] = "done"
                        with lock:
                            active.remove(label)

                    threading.Thread(
                        target=finish, daemon=True
                    ).start()
                    return 202, {"accepted": label}
                return 200, {"current": dict(state), "last": None}

            return admin

        a = StubBackend("a", admin=make_admin("a"))
        b = StubBackend("b", admin=make_admin("b"))
        router = _router_over([a, b], swap_host_timeout_s=10.0)
        try:
            status, payload = router.start_fleet_swap(
                {"artifact": "/tmp/whatever"}
            )
            assert status == 202
            # a second trigger while rolling is refused
            status2, _p = router.start_fleet_swap(
                {"artifact": "/tmp/other"}
            )
            assert status2 == 409
            deadline = time.monotonic() + 15.0
            while time.monotonic() < deadline:
                with router._lock:
                    swap = dict(router._swap)
                if swap["state"] in ("done", "failed"):
                    break
                time.sleep(0.05)
            assert swap["state"] == "done", swap
            assert swap["hosts_shifted"] == ["h0", "h1"]
            assert swap["hosts_unshifted"] == []
            assert max_active[0] == 1, (
                "two hosts were mid-shift at once"
            )
        finally:
            router.drain(10.0)
            a.stop()
            b.stop()

    def test_router_endpoints(self):
        a = StubBackend("a")
        router = _router_over([a])
        try:
            for path, want in (
                ("/healthz", 200), ("/readyz", 200),
                ("/statsz", 200), ("/fleet/hosts", 200),
                ("/fleet/swap", 200), ("/nope", 404),
            ):
                s = socket.create_connection(
                    ("127.0.0.1", router.port), timeout=5
                )
                s.sendall(
                    f"GET {path} HTTP/1.1\r\nhost: x\r\n"
                    "connection: close\r\n\r\n".encode()
                )
                status, _h, body = recv_response(s.makefile("rb"))
                s.close()
                assert status == want, path
            # bad x-priority -> 400, never proxied
            status, _h, obj = _predict(
                "127.0.0.1", router.port, priority=9
            )
            assert status == 400 and "x-priority" in obj["error"]
        finally:
            router.drain(5.0)
            a.stop()


class TestFleetVerdict:
    def test_v6_fleet_block_and_compare_gates(self, tmp_path):
        """The verdict pipeline end to end over stub hosts: v6 schema,
        ledger consistency computed against the client observation,
        the compare flattener's fleet keys pinned BOTH directions
        (v5-shaped verdicts skip; fleet verdicts judge), and a
        doctored fleet-dropped regression exiting 3 through the real
        compare CLI."""
        from bdbnn_tpu.obs.compare import _serve_metrics
        from bdbnn_tpu.serve.loadgen import (
            HttpLoadGenerator,
            build_schedule,
        )

        a, b = StubBackend("a"), StubBackend("b")
        router = _router_over([a, b])
        try:
            schedule = build_schedule(
                "poisson", requests=40, rate=400.0, seed=0
            )
            gen = HttpLoadGenerator(
                "127.0.0.1", router.port, schedule,
                body_fn=lambda i: b"[1]", concurrency=4,
            )
            client = gen.run()
            assert client["dropped"] == 0
            router.drain(5.0)
            fleet = router.fleet_block(client=client)
            verdict = fleet_slo_verdict(
                router.accounting(), fleet,
                scenario="poisson", rate=400.0, seed=0,
                client=client,
            )
        finally:
            a.stop()
            b.stop()
        assert verdict["serve_verdict"] == 8
        assert verdict["mode"] == "fleet"
        flt = verdict["fleet"]
        assert flt["dropped"] == 0
        assert flt["ledger_consistent"] is True
        assert flt["completed_total"] == verdict["requests_completed"]
        assert flt["completed_total"] == client["by_status"]["200"]
        assert flt["retry_rate"] == 0.0
        assert flt["host_p99_spread"] is not None  # both hosts served
        # per-priority skeleton matches the http verdict's shape
        assert set(verdict["per_priority"]) <= {"0", "1", "2"}

        # the flattener, pinned both directions
        m = _serve_metrics(verdict)
        assert m["serve_fleet_dropped"] == 0
        assert m["serve_fleet_retry_rate"] == 0.0
        assert m["serve_fleet_host_p99_spread"] == flt[
            "host_p99_spread"
        ]
        old = _serve_metrics({"p99_ms": 1.0})  # v1-v5: no fleet block
        assert old["serve_fleet_dropped"] is None
        assert old["serve_fleet_retry_rate"] is None
        assert old["serve_fleet_host_p99_spread"] is None

        # compare exits 3 on a doctored fleet-dropped regression
        from bdbnn_tpu.cli import main as cli_main

        base = tmp_path / "verdict.json"
        base.write_text(json.dumps(verdict))
        doctored = dict(verdict)
        doctored["fleet"] = {**flt, "dropped": 3}
        cand = tmp_path / "doctored.json"
        cand.write_text(json.dumps(doctored))
        assert cli_main(
            ["compare", str(base), str(base), "--json"]
        ) == 0
        assert cli_main(
            ["compare", str(base), str(cand), "--json"]
        ) == 3


# ---------------------------------------------------------------------------
# 2. registry replication: digest-verified pull
# ---------------------------------------------------------------------------


def _fake_artifact(d, payload=b"fake-weights-bytes"):
    """A minimal on-disk export artifact (manifest + weights blob with
    a true digest chain) — the registry hashes bytes, it never loads
    weights, so no numpy/JAX is needed."""
    from bdbnn_tpu.serve.export import WEIGHTS_NAME, _file_sha256

    os.makedirs(d, exist_ok=True)
    wpath = os.path.join(d, WEIGHTS_NAME)
    with open(wpath, "wb") as f:
        f.write(payload)
    manifest = {
        "arch": "resnet8_tiny",
        "dataset": "cifar10",
        "image_size": 32,
        "num_classes": 10,
        "weights_sha256": _file_sha256(wpath),
        "provenance": {"config_hash": "cafe", "recipe": {}},
        "eval": {"checkpoint_acc1": 50.0},
    }
    with open(os.path.join(d, "artifact.json"), "w") as f:
        json.dump(manifest, f)
    return d


class TestRegistryPull:
    def test_pull_replicates_with_verified_digests(self, tmp_path):
        from bdbnn_tpu.serve.registry import ArtifactRegistry

        art = _fake_artifact(str(tmp_path / "art"))
        primary = ArtifactRegistry(str(tmp_path / "primary"))
        e1 = primary.publish(art)
        local = ArtifactRegistry(str(tmp_path / "hostA"))
        pulled = local.pull(primary.root)
        assert [p["version"] for p in pulled] == [e1["version"]]
        # same version number, same digests, provenance preserved,
        # pull lineage recorded
        got = local.get(e1["version"])
        assert got["weights_sha256"] == e1["weights_sha256"]
        assert got["artifact_sha256"] == e1["artifact_sha256"]
        assert got["pulled_from"] == os.path.abspath(primary.root)
        # the local resolve chain verifies end to end
        assert os.path.isdir(local.resolve(e1["version"]))
        # idempotent re-pull: nothing new
        assert local.pull(primary.root) == []

    def test_pull_single_version_and_unknown(self, tmp_path):
        from bdbnn_tpu.serve.registry import ArtifactRegistry

        art1 = _fake_artifact(str(tmp_path / "a1"), b"one")
        art2 = _fake_artifact(str(tmp_path / "a2"), b"two")
        primary = ArtifactRegistry(str(tmp_path / "primary"))
        primary.publish(art1)
        e2 = primary.publish(art2)
        local = ArtifactRegistry(str(tmp_path / "host"))
        pulled = local.pull(primary.root, version=e2["version"])
        assert [p["version"] for p in pulled] == [e2["version"]]
        assert local.get(1) is None  # only the asked-for version
        with pytest.raises(KeyError, match="no version 99"):
            local.pull(primary.root, version=99)

    def test_torn_remote_pull_fails_verified_registry_untouched(
        self, tmp_path
    ):
        """The acceptance case: a remote version torn AFTER publish
        (bytes no longer match the published digests) must fail the
        pull loudly and leave the LOCAL registry with no entry and no
        version dir — a torn replica can never become servable."""
        from bdbnn_tpu.serve.registry import (
            REGISTRY_NAME,
            ArtifactRegistry,
        )

        art = _fake_artifact(str(tmp_path / "art"))
        primary = ArtifactRegistry(str(tmp_path / "primary"))
        e1 = primary.publish(art)
        # tear the remote replica: truncate the published weights
        with open(
            os.path.join(
                primary.root, e1["path"], "weights.npz"
            ),
            "wb",
        ) as f:
            f.write(b"torn")
        local_root = str(tmp_path / "host")
        local = ArtifactRegistry(local_root)
        with pytest.raises(RuntimeError, match="digest|match"):
            local.pull(primary.root)
        # untouched: no index, no version dirs, no staging debris
        assert not os.path.exists(
            os.path.join(local_root, REGISTRY_NAME)
        )
        leftovers = (
            os.listdir(local_root)
            if os.path.isdir(local_root) else []
        )
        assert [n for n in leftovers if not n.startswith(".")] == []
        assert local.entries() == []

    def test_forked_registries_refuse(self, tmp_path):
        from bdbnn_tpu.serve.registry import ArtifactRegistry

        a1 = _fake_artifact(str(tmp_path / "a1"), b"one")
        a2 = _fake_artifact(str(tmp_path / "a2"), b"two")
        primary = ArtifactRegistry(str(tmp_path / "primary"))
        primary.publish(a1)
        local = ArtifactRegistry(str(tmp_path / "host"))
        local.publish(a2)  # local v0001 differs from remote v0001
        with pytest.raises(RuntimeError, match="forked"):
            local.pull(primary.root)


# ---------------------------------------------------------------------------
# 3. the fleet acceptance e2e: real serve-http subprocesses
# ---------------------------------------------------------------------------


def _host_env(devices=2):
    """The tests/pod_worker.py env recipe: a fresh process pinned to
    its own simulated device count."""
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+",
        "",
        os.environ.get("XLA_FLAGS", ""),
    )
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={devices}"
    ).strip()
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get(
        "PYTHONPATH", ""
    )
    return env


def _spawn_host(art_dir, root, server_id):
    """One fleet host: the REAL serve-http CLI in serve mode (no
    scenario — it answers until SIGTERM), port 0 (kernel-assigned,
    discovered from the http start event: no cross-process port race
    at all)."""
    argv = [
        sys.executable, "-m", "bdbnn_tpu.cli", "serve-http", art_dir,
        "--log-path", str(root),
        "--port", "0",
        "--buckets", "1", "8",
        "--queue-depth", "8",
        "--max-delay-ms", "2",
        "--default-quota", "100000:100000",
        "--server-id", server_id,
        "--rtrace-sample-every", "64",
    ]
    return subprocess.Popen(
        argv,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env=_host_env(devices=2),
        cwd=REPO_ROOT,
    )


def _host_events(root):
    hits = glob.glob(
        os.path.join(str(root), "**", "events.jsonl"), recursive=True
    )
    events = []
    for h in sorted(hits):
        events += read_jsonl(h)
    return events


def _wait_host_ready(root, proc, timeout=240.0):
    """Poll the host's run dir until its http start AND ready events
    land; returns the bound port. Raises AssertionError (the
    retry-once boundary) if the host died or timed out instead."""
    deadline = time.time() + timeout
    port = None
    while time.time() < deadline:
        events = _host_events(root)
        for e in events:
            if e.get("kind") == "http" and e.get("phase") == "start":
                port = e.get("port")
        if port is not None and any(
            e.get("kind") == "http" and e.get("phase") == "ready"
            for e in events
        ):
            return port
        if proc.poll() is not None:
            out, err = proc.communicate(timeout=10)
            raise AssertionError(
                f"fleet host died during bring-up rc={proc.returncode}"
                f"\nstdout:{out[-1200:]}\nstderr:{err[-2500:]}"
            )
        time.sleep(0.2)
    raise AssertionError("fleet host never reached http ready")


def _reap_hosts(procs, timeout=60):
    outs = []
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    for p in procs:
        try:
            out, err = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            out, err = p.communicate()
        outs.append((p.returncode, out, err))
    return outs


def _form_fleet(art_dir, roots):
    """Bring up one host subprocess per root; AssertionError when the
    cluster never forms (the retry_once_flaky boundary)."""
    procs = []
    try:
        for i, root in enumerate(roots):
            procs.append(_spawn_host(art_dir, root, f"h{i}"))
        ports = [
            _wait_host_ready(root, proc)
            for root, proc in zip(roots, procs)
        ]
    except BaseException:
        _reap_hosts(procs, timeout=10)
        raise
    return procs, ports


class TestFleetEndToEnd:
    """THE fleet acceptance: 2 real serve-http hosts (2 simulated
    devices each) over real sockets, flash-crowd load through the
    router, SIGTERM one host mid-burst."""

    @pytest.fixture(scope="class")
    def fleet(self, exported_artifact, tmp_path_factory):
        """Cluster formation quarantined behind
        conftest.retry_once_flaky (the ONE bounded retry-once policy)
        for the documented transient: a serve-http subprocess dying or
        timing out during jax-import/AOT bring-up on a contended box
        (the pod_worker GRPC precedent, PR 7/8/9 notes). Every
        post-formation contract is asserted by the tests and fails
        deterministically."""
        from conftest import retry_once_flaky

        art_dir, _ = exported_artifact

        def attempt(i):
            tag = "fleet" if i == 0 else "fleet_retry"
            roots = [
                tmp_path_factory.mktemp(f"{tag}_h{j}")
                for j in range(2)
            ]
            procs, ports = _form_fleet(art_dir, roots)
            return {
                "art": art_dir,
                "procs": procs,
                "ports": ports,
                "roots": roots,
            }

        fleet = retry_once_flaky(
            attempt,
            note=(
                "fleet host cluster attempt 1 never formed "
                "(serve-http subprocess bring-up transient on "
                "contended boxes — jax import + AOT warmup racing "
                "the formation timeout; pod_worker precedent)"
            ),
        )
        yield fleet
        _reap_hosts(fleet["procs"], timeout=30)

    def test_sigterm_one_host_mid_flash_crowd(self, fleet, tmp_path):
        """SIGTERM host 0 inside the flash-crowd burst: the fleet
        keeps serving, the dead host's accepted requests are answered
        (zero client drops), per-host ledgers sum to the client
        totals in the v6 fleet block, and the episode is consumable
        by watch, summarize and compare."""
        from bdbnn_tpu.obs.events import read_events
        from bdbnn_tpu.obs.summarize import summarize_run
        from bdbnn_tpu.obs.watch import render_status

        cfg = ServeFleetConfig(
            hosts=tuple(
                f"127.0.0.1:{p}" for p in fleet["ports"]
            ),
            artifact=fleet["art"],
            log_path=str(tmp_path / "fleet_run"),
            scenario="flash_crowd",
            rate=120.0,
            requests=700,
            concurrency=12,
            flash_factor=8.0,
            seed=0,
            probe_interval_s=0.1,
            health_debounce=2,
            max_attempts=3,
            proxy_timeout_s=30.0,
            ready_timeout_s=60.0,
            stats_interval_s=0.2,
        )
        killed = []

        def on_arrival(i):
            # the flash burst occupies the middle sixth of the nominal
            # run; arrival ~300 of 700 sits inside it
            if not killed and i >= 300:
                killed.append(True)
                fleet["procs"][0].send_signal(signal.SIGTERM)

        res = run_serve_fleet(cfg, on_arrival=on_arrival)
        v = res["verdict"]
        assert killed, "the kill hook never fired"
        assert v["serve_verdict"] == 8
        # zero client-visible drops across the host death: every
        # request got SOME response — 200 or an explicit shed
        assert v["client"]["dropped"] == 0
        assert v["client"]["responses"] == v["client"]["submitted"]
        flt = v["fleet"]
        assert flt["dropped"] == 0
        # per-host ledgers sum to the client totals — computed inside
        # the verdict AND re-derived here
        assert flt["ledger_consistent"] is True
        assert flt["completed_total"] == (
            v["client"]["by_status"].get("200", 0)
        )
        assert flt["completed_total"] == sum(
            h["completed"] for h in flt["hosts"].values()
        )
        assert flt["completed_total"] == v["requests_completed"]
        # both hosts served before the kill; the survivor carried the
        # fleet after it
        assert flt["hosts"]["h0"]["completed"] > 0
        assert flt["hosts"]["h1"]["completed"] > 0
        assert flt["hosts"]["h0"]["state"] in (
            HOST_DRAINING, HOST_DEAD
        )
        # identity cross-check: the hosts advertised who they are
        assert flt["hosts"]["h0"]["server_id"] == "h0"
        assert flt["hosts"]["h1"]["server_id"] == "h1"
        assert v["requests_failed"] == 0
        assert v["drained_clean"] is True
        # run-dir artifacts: verdict.json matches, fleet events flow
        with open(os.path.join(res["run_dir"], "verdict.json")) as f:
            assert json.load(f) == v
        events = read_events(res["run_dir"])
        kinds = {e["kind"] for e in events}
        assert "fleet" in kinds and "serve" in kinds
        fleet_phases = [
            e["phase"] for e in events if e["kind"] == "fleet"
        ]
        assert fleet_phases[0] == "start"
        assert "ready" in fleet_phases and "stats" in fleet_phases
        assert "probe" in fleet_phases  # h0's state transitions
        assert fleet_phases[-1] == "stop"
        # watch renders the fleet banner; summarize carries the block
        status = render_status(events, None)
        assert "fleet:" in status
        report, summary = summarize_run(res["run_dir"])
        assert summary["serving"]["fleet"] is not None
        assert summary["serving"]["verdict"]["fleet"][
            "ledger_consistent"] is True
        assert "fleet" in report
        # the SIGTERMed host exited cleanly after ITS drain: rc 0 and
        # its own run dir shows the drain latch
        p0 = fleet["procs"][0]
        try:
            p0.wait(timeout=60)
        except subprocess.TimeoutExpired:
            pytest.fail("SIGTERMed host never exited")
        assert p0.returncode == 0
        host0_events = _host_events(fleet["roots"][0])
        assert any(
            e.get("kind") == "http" and e.get("phase") == "drain"
            for e in host0_events
        )
        # compare: a doctored fleet-dropped regression exits 3
        from bdbnn_tpu.cli import main as cli_main

        doctored = dict(v)
        doctored["fleet"] = {**flt, "dropped": 3}
        cand = tmp_path / "doctored.json"
        cand.write_text(json.dumps(doctored))
        verdict_path = os.path.join(res["run_dir"], "verdict.json")
        assert cli_main(
            ["compare", verdict_path, str(cand), "--json"]
        ) == 3


class TestFleetTraceAcceptance:
    """THE fleet tracing acceptance (v7): the SAME 2 real serve-http
    hosts serve a clean run and a wedged run (SIGSTOP one host
    mid-run — its kernel keeps accepting connections but nothing ever
    answers, so every exchange parked on it times out and retry-hops
    to the peer). The v7 verdict must attribute the wedged client
    tail to retry_hop/network while the backend stage p99s stay
    flat, cross-hop reconciliation must hold on every traced
    request, the stats pump must mark the wedged host's window
    stale, and ``compare`` clean-vs-wedged must exit 3 on
    serve_fleet_retry_hop_share even with --tol-rel wide open."""

    @pytest.fixture(scope="class")
    def fleet(self, exported_artifact, tmp_path_factory):
        """Same formation quarantine as TestFleetEndToEnd (its fleet
        is not reusable here: that test SIGTERMs h0)."""
        from conftest import retry_once_flaky

        art_dir, _ = exported_artifact

        def attempt(i):
            tag = "tracefleet" if i == 0 else "tracefleet_retry"
            roots = [
                tmp_path_factory.mktemp(f"{tag}_h{j}")
                for j in range(2)
            ]
            procs, ports = _form_fleet(art_dir, roots)
            return {
                "art": art_dir,
                "procs": procs,
                "ports": ports,
                "roots": roots,
            }

        fleet = retry_once_flaky(
            attempt,
            note=(
                "fleet host cluster attempt 1 never formed "
                "(serve-http subprocess bring-up transient on "
                "contended boxes; pod_worker precedent)"
            ),
        )
        yield fleet
        _reap_hosts(fleet["procs"], timeout=30)

    def _cfg(self, fleet, run_dir, **kw):
        base = dict(
            hosts=tuple(
                f"127.0.0.1:{p}" for p in fleet["ports"]
            ),
            artifact=fleet["art"],
            log_path=run_dir,
            scenario="poisson",
            rate=60.0,
            requests=50,
            concurrency=8,
            seed=0,
            probe_interval_s=0.1,
            health_debounce=2,
            max_attempts=3,
            proxy_timeout_s=30.0,
            ready_timeout_s=60.0,
            stats_interval_s=0.2,
            rtrace_sample_every=1,
            scrape_timeout_s=0.2,
            scrape_stale_after=2,
        )
        base.update(kw)
        return ServeFleetConfig(**base)

    def test_clean_then_wedged_attribution_and_compare_gate(
        self, fleet, tmp_path
    ):
        from bdbnn_tpu.cli import main as cli_main
        from bdbnn_tpu.obs.compare import compare_runs
        from bdbnn_tpu.obs.events import read_events
        from bdbnn_tpu.obs.summarize import summarize_run

        # ---- clean pass: both hosts healthy -----------------------
        clean = run_serve_fleet(
            self._cfg(fleet, str(tmp_path / "clean"))
        )
        cv = clean["verdict"]
        assert cv["serve_verdict"] == 8
        assert cv["client"]["dropped"] == 0
        assert cv["requests_failed"] == 0
        cfa = cv["fleet_attribution"]
        assert cfa is not None
        # every relayed request is traced AND stitched: the backends
        # adopted the router's x-rtrace and echoed their stage header
        assert cfa["requests"] > 0
        assert cfa["stitched"] == cfa["requests"]
        assert cfa["unstitched"] == 0
        # clean fleet: the retry-hop share is a MEASURED zero (never
        # None) — that is what leaves zero relative headroom below
        assert cfa["retry_hop_share"] == 0.0
        assert cfa["stages"]["network"]["p99_ms"] > 0.0
        assert cfa["backend_stages"]["compute"]["p99_ms"] > 0.0
        # two-clock discipline: cross-hop reconciliation holds on
        # every traced request (router stages + backend sum == e2e)
        crec = cfa["reconciliation"]
        assert crec["ok"] is True
        assert crec["violations"] == 0
        assert crec["stitched"] == cfa["requests"]
        # both hosts served -> the per-stage host spread is judgeable
        assert cfa["host_stage_spread_max"] is not None
        clean_vp = os.path.join(clean["run_dir"], "verdict.json")

        # ---- wedged pass: SIGSTOP h0 mid-run ----------------------
        wedged_at = []

        def on_arrival(i):
            if not wedged_at and i >= 10:
                wedged_at.append(i)
                fleet["procs"][0].send_signal(signal.SIGSTOP)

        try:
            wedged = run_serve_fleet(
                self._cfg(
                    fleet,
                    str(tmp_path / "wedged"),
                    requests=60,
                    proxy_timeout_s=0.75,
                ),
                on_arrival=on_arrival,
            )
        finally:
            fleet["procs"][0].send_signal(signal.SIGCONT)
        wv = wedged["verdict"]
        assert wedged_at, "the wedge hook never fired"
        assert wv["serve_verdict"] == 8
        # the wedged host never DROPS a client: every parked exchange
        # times out at the router and retry-hops to the peer
        assert wv["client"]["dropped"] == 0
        assert wv["fleet"]["hosts"]["h0"]["retries"]["timeout"] > 0
        wfa = wv["fleet_attribution"]
        # the client tail is attributed to retry_hop: wedged attempts
        # charge their wall + backoff to the hop stage...
        assert wfa["retry_hop_share"] > 0.0
        rh = wfa["stages"]["retry_hop"]
        assert rh is not None and rh["p99_ms"] > 0.0
        # ...while the backend stage p99s stay flat — the surviving
        # host's self-reported decomposition is untouched by the
        # router-side stall (proxy_timeout_s dominates every backend
        # stage by construction)
        backend_p99s = [
            blk["p99_ms"]
            for blk in (wfa["backend_stages"] or {}).values()
            if blk is not None and blk.get("p99_ms") is not None
        ]
        assert backend_p99s
        assert max(backend_p99s) < rh["p99_ms"]
        # reconciliation still holds on every traced request — the
        # timed-out attempts are charged to retry_hop, not smeared
        # into an unexplained residual
        wrec = wfa["reconciliation"]
        assert wrec["ok"] is True
        assert wrec["violations"] == 0
        # the sampled waterfalls carry the hop: some traced request
        # took >= 2 attempts and names retry_hop its slowest stage
        events = read_events(wedged["run_dir"])
        waterfalls = [
            e for e in events
            if e["kind"] == "rtrace" and e.get("phase") == "request"
        ]
        assert waterfalls
        assert any(w.get("attempts", 0) >= 2 for w in waterfalls)
        assert any(
            w.get("slowest_stage") == "retry_hop"
            for w in waterfalls
        )
        # the stats pump marked the wedged host's window stale (its
        # bounded-timeout scrape kept failing) without stalling the
        # pump — the fleet stats events carry the staleness live
        windows = [
            e.get("host_windows")
            for e in events
            if e["kind"] == "fleet" and e.get("phase") == "stats"
            and e.get("host_windows") is not None
        ]
        assert windows
        h0_rows = [
            w["hosts"]["h0"] for w in windows
            if "h0" in (w.get("hosts") or {})
        ]
        assert any(r["failures"] > 0 for r in h0_rows)
        assert any(r["stale"] for r in h0_rows)
        # summarize renders the fleet-trace section from the run dir
        report, summary = summarize_run(wedged["run_dir"])
        assert "fleet trace:" in report
        assert summary["serving"]["verdict"]["fleet_attribution"][
            "retry_hop_share"] > 0.0
        wedged_vp = os.path.join(wedged["run_dir"], "verdict.json")

        # ---- the compare gate -------------------------------------
        # the clean baseline measured share 0.0, so ANY retry-hop
        # time regresses regardless of how wide --tol-rel is opened
        result = compare_runs(
            [clean_vp, wedged_vp], tol_rel=5.0
        )
        rows = {
            m["metric"]: m
            for m in result["comparisons"][0]["metrics"]
        }
        assert rows["serve_fleet_retry_hop_share"]["verdict"] == (
            "regression"
        )
        assert result["verdict"] == "regression"
        assert cli_main(
            ["compare", clean_vp, wedged_vp,
             "--tol-rel", "5.0", "--json"]
        ) == 3


@pytest.mark.slow
class TestFleetSigkill:
    """The SIGKILL variant: no drain on the victim — its in-flight
    proxied requests die mid-exchange and MUST be answered by the
    peer through the retry path."""

    def test_sigkill_one_host_mid_flash_crowd(
        self, exported_artifact, tmp_path_factory, tmp_path
    ):
        from conftest import retry_once_flaky

        art_dir, _ = exported_artifact

        def attempt(i):
            tag = "fleet_kill" if i == 0 else "fleet_kill_retry"
            roots = [
                tmp_path_factory.mktemp(f"{tag}_h{j}")
                for j in range(2)
            ]
            return _form_fleet(art_dir, roots)

        procs, ports = retry_once_flaky(
            attempt,
            note=(
                "fleet host cluster attempt 1 never formed "
                "(serve-http subprocess bring-up transient — see "
                "TestFleetEndToEnd.fleet)"
            ),
        )
        try:
            cfg = ServeFleetConfig(
                hosts=tuple(f"127.0.0.1:{p}" for p in ports),
                artifact=art_dir,
                log_path=str(tmp_path / "fleet_run"),
                scenario="flash_crowd",
                rate=120.0,
                requests=700,
                concurrency=12,
                seed=0,
                probe_interval_s=0.1,
                health_debounce=2,
                max_attempts=3,
                proxy_timeout_s=30.0,
                stats_interval_s=0.2,
            )
            killed = []

            def on_arrival(i):
                if not killed and i >= 300:
                    killed.append(True)
                    procs[0].kill()  # SIGKILL: no drain, no goodbye

            res = run_serve_fleet(cfg, on_arrival=on_arrival)
            v = res["verdict"]
            flt = v["fleet"]
            assert v["client"]["dropped"] == 0
            assert flt["dropped"] == 0
            assert flt["ledger_consistent"] is True
            # the kill produced real transport failures that were
            # retried onto the peer — that is the whole point
            h0 = flt["hosts"]["h0"]
            assert (
                h0["retries"]["reset"] + h0["retries"]["connect"]
                + h0["retries"]["timeout"] > 0
            )
            assert h0["state"] == HOST_DEAD
            assert flt["hosts"]["h1"]["completed"] > 0
            assert v["requests_failed"] == 0
        finally:
            _reap_hosts(procs, timeout=30)


# ---------------------------------------------------------------------------
# 8. the router's capacity plane: scrape merge + measured offered rate
# ---------------------------------------------------------------------------


class TestRouterCapacityPlane:
    def test_scrape_merges_capacity_and_marks_pre_v8_host_stale(self):
        """One backend serves a ``capacity`` block in /statsz, the
        other (a pre-v8 host) serves none: the scrape folds the first
        into the fleet merge and walks the second to capacity-stale —
        its absence is a recorded scrape failure, never fabricated
        zeros in the merged view."""
        cap_block = {
            "demand": {
                "offered_rps": 40.0, "demand_shed_ratio_max": 0.2,
            },
            "headroom": {
                "headroom_rps": 60.0, "capacity_rps_est": 100.0,
            },
            "slo_budget": {
                "detectors": {
                    "p2:shed": {
                        "burn_rate_fast": 3.0, "burn_rate_slow": 2.0,
                    },
                },
            },
        }
        a = StubBackend("cap-a")
        orig_route = a._route

        def route(method, path, headers, body):
            out = orig_route(method, path, headers, body)
            if path == "/statsz" and out != "die":
                status, obj = out
                return status, dict(obj, capacity=cap_block)
            return out

        a._route = route
        b = StubBackend("plain-b")
        router = _router_over([a, b], scrape_stale_after=2)
        try:
            for _ in range(3):
                router.scrape_host_stats()
            snap = router.stats()["capacity"]
            h0, h1 = snap["hosts"]["h0"], snap["hosts"]["h1"]
            assert h0["stale"] is False
            assert h0["offered_rps"] == pytest.approx(40.0)
            assert h0["burn_rate_max"] == pytest.approx(3.0)
            assert h1["stale"] is True
            assert h1["failures"] >= 2
            assert snap["hosts_fresh"] == 1 and snap["hosts_stale"] == 1
            merged = snap["merged"]
            assert merged["offered_rps"] == pytest.approx(40.0)
            assert merged["headroom_rps"] == pytest.approx(60.0)
            assert merged["burn_rate_max"] == pytest.approx(3.0)
            assert merged["demand_shed_ratio_max"] == pytest.approx(0.2)
            # the fleet verdict block carries the three flat gates at
            # the top level — the same contract as a host's block
            block = router.capacity_block()
            assert block["burn_rate_max"] == pytest.approx(3.0)
            assert block["headroom_rps"] == pytest.approx(60.0)
            assert block["demand_shed_ratio_max"] == pytest.approx(0.2)
            assert block["fleet"]["hosts_stale"] == 1
        finally:
            router.drain(5.0)
            a.stop()
            b.stop()

    def test_accounting_measures_offered_rate_from_arrivals(self):
        """The router's verdict rate is MEASURED from arrival stamps:
        None until two requests have been observed (never fabricated),
        then the observed inter-arrival rate — what actually hit the
        router, not a config knob."""
        a = StubBackend("b0")
        router = _router_over([a])
        try:
            assert router.accounting()["measured_rate_rps"] is None
            _predict("127.0.0.1", router.port)
            assert router.accounting()["measured_rate_rps"] is None
            for _ in range(4):
                _predict("127.0.0.1", router.port)
                time.sleep(0.01)
            rate = router.accounting()["measured_rate_rps"]
            assert rate is not None and 0.5 < rate < 5000.0
        finally:
            router.drain(5.0)
            a.stop()
