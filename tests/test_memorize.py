"""The recipe LEARNS: synthetic-memorization to >90% train accuracy.

VERDICT r3 task 5 — the strongest in-suite convergence evidence so far
was "loss decreases over a few steps"; this pins the full BD-BNN recipe
(binary convs + STE/EDE + kurtosis regularization, reference
``train.py:441-554`` + ``utils/utils.py:6-14``) actually fitting data,
and that bf16 training tracks f32 within tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bdbnn_tpu.models import conv_weight_paths
from bdbnn_tpu.models.resnet import BiResNet
from bdbnn_tpu.train import (
    StepConfig,
    TrainState,
    cpt_tk,
    make_optimizer,
    make_train_step,
)

N, HW, CLASSES = 32, 8, 4
STEPS = 300
EPOCHS_FAKE = 12  # EDE schedule length; one "epoch" per 25 steps


def _data():
    rng = np.random.default_rng(7)
    x = rng.normal(size=(N, HW, HW, 3)).astype(np.float32)
    y = rng.integers(0, CLASSES, size=(N,))
    return jnp.asarray(x), jnp.asarray(y)


def _train(dtype):
    model = BiResNet(
        stage_sizes=(1, 1), num_classes=CLASSES, width=16,
        stem="cifar", variant="cifar", act="hardtanh",
        dtype=jnp.bfloat16 if dtype == "bfloat16" else None,
    )
    x, y = _data()
    variables = model.init(jax.random.PRNGKey(0), x, train=True)
    paths = conv_weight_paths(variables["params"])
    hooked = tuple(paths[1:])
    cfg = StepConfig(
        w_kurtosis=True,
        kurt_paths=hooked,
        kurt_targets=(1.8,) * len(hooked),
        kurtosis_mode="avg",
        w_lambda_kurtosis=0.1,
        ede=True,
    )
    tx = make_optimizer(
        variables["params"], dataset="cifar10", lr=0.05,
        epochs=EPOCHS_FAKE, steps_per_epoch=STEPS // EPOCHS_FAKE,
    )
    state = TrainState.create(variables, tx)
    step = jax.jit(make_train_step(model, tx, cfg), donate_argnums=(0,))

    accs = []
    for i in range(STEPS):
        epoch = i // (STEPS // EPOCHS_FAKE)
        t, k = cpt_tk(epoch, EPOCHS_FAKE)
        tk = (jnp.float32(t), jnp.float32(k))
        state, m = step(state, (x, y), tk, jnp.float32(1.0))
        accs.append(float(m["top1"]) / N)
    assert np.isfinite(float(m["loss"]))
    return accs


class TestMemorization:
    def test_recipe_memorizes_to_90pct_and_bf16_tracks_f32(self):
        acc_f32 = _train("float32")
        assert max(acc_f32[-20:]) > 0.90, (
            f"f32 failed to memorize: last-20 accs {acc_f32[-20:]}"
        )
        acc_bf16 = _train("bfloat16")
        assert max(acc_bf16[-20:]) > 0.85, (
            f"bf16 failed to track f32 ({max(acc_f32[-20:]):.2f}): "
            f"last-20 accs {acc_bf16[-20:]}"
        )
