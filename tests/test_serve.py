"""Serving subsystem tests (bdbnn_tpu/serve/).

- BN-folding correctness matrix: for EVERY arch in models/registry.py,
  the folded eval forward matches the unfolded eval forward within fp32
  tolerance on random inputs WITH randomized running stats (identity
  stats would make folding trivially correct).
- Export fidelity: a real (smoke-scale) training run exports to an
  artifact that contains NO training-only state, and offline inference
  over the same val split reproduces the checkpoint's recorded eval
  top-1 EXACTLY.
- Micro-batcher: bounded queue (sheds, never grows), deadline
  coalescing, latched-flag drain with every accepted request answered.
- serve-bench end-to-end: SLO verdict invariants, queue bound held,
  SIGTERM drains cleanly mid-run; `watch`/`summarize`/`compare` consume
  the serving telemetry.
"""

import json
import os
import signal
import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

from bdbnn_tpu.serve.batching import LoadShedError, MicroBatcher
from bdbnn_tpu.serve.loadgen import (
    LoadGenerator,
    percentile,
    slo_verdict,
)

# ---------------------------------------------------------------------------
# BN folding: every registry arch
# ---------------------------------------------------------------------------


def _randomize_stats(tree, rng):
    """Random running stats (mean ~ N(0, .5), var ~ U(.5, 2)): folding
    must be exercised on NON-identity stats or the test proves nothing."""
    if isinstance(tree, dict):
        out = {}
        for k, v in tree.items():
            if isinstance(v, dict) and set(v) == {"mean", "var"}:
                out[k] = {
                    "mean": rng.normal(0, 0.5, np.shape(v["mean"])).astype(
                        np.float32
                    ),
                    "var": rng.uniform(0.5, 2.0, np.shape(v["var"])).astype(
                        np.float32
                    ),
                }
            else:
                out[k] = _randomize_stats(v, rng)
        return out
    return tree


# Heavy tail of the per-arch fold matrix, run under the `slow` marker:
# every equivalence class these archs belong to (imagenet stem, react /
# step2 / float variants, bottleneck blocks, depth) is still covered in
# tier-1 by a cheaper family member — the tier-1 budget satellite asks
# exactly this split (like the Poisson soak).
_SLOW_FOLD = {
    ("cifar10", "resnet34"),
    ("cifar10", "resnet34_float"),
    ("imagenet", "resnet34"),
    ("imagenet", "resnet34_react"),
    ("imagenet", "resnet34_step2"),
    ("imagenet", "resnet34_float"),
    ("imagenet", "resnet101_float"),
}


def _fold_cases():
    from bdbnn_tpu.models.registry import list_models

    for dataset in ("cifar10", "imagenet"):
        for arch in list_models(dataset):
            yield dataset, arch


class TestFoldBatchNorm:
    @pytest.mark.parametrize(
        "dataset,arch",
        [
            pytest.param(
                d, a,
                marks=[pytest.mark.slow] if (d, a) in _SLOW_FOLD else [],
            )
            for d, a in _fold_cases()
        ],
        ids=[f"{d}-{a}" for d, a in _fold_cases()],
    )
    def test_folded_matches_unfolded_eval(self, dataset, arch):
        """fold_batch_norm is a numerics-preserving transform of the
        eval forward for every registered arch (16x16 inputs: both stems
        accept them and the matrix stays inside the tier-1 budget —
        folding is per-channel, so spatial size proves nothing extra)."""
        import jax
        import jax.numpy as jnp

        from bdbnn_tpu.models.registry import create_model
        from bdbnn_tpu.models.resnet import fold_batch_norm

        model = create_model(arch, dataset)
        # shapes only (eval_shape traces without executing), then random
        # params: a real init would run the whole forward per arch and
        # triple the matrix's cost for no extra coverage
        shapes = jax.eval_shape(
            lambda rng: model.init(
                rng, jnp.zeros((1, 16, 16, 3)), train=False
            ),
            jax.random.PRNGKey(0),
        )
        prng = np.random.default_rng(2)
        params = jax.tree_util.tree_map(
            lambda sd: prng.normal(0, 0.1, sd.shape).astype(sd.dtype),
            shapes["params"],
        )
        variables = {
            "params": params,
            "batch_stats": _randomize_stats(
                jax.tree_util.tree_map(
                    lambda sd: np.zeros(sd.shape, sd.dtype),
                    shapes.get("batch_stats", {}),
                ),
                np.random.default_rng(1),
            ),
        }
        x = np.random.default_rng(0).normal(size=(1, 16, 16, 3)).astype(
            np.float32
        )
        ref = np.asarray(model.apply(variables, x, train=False))
        got = np.asarray(
            model.apply(fold_batch_norm(variables), x, train=False)
        )
        # fp32 tolerance scaled to the logit magnitude: deep float twins
        # with random affine stats push logits to O(10^3), and the
        # reassociated per-channel affine rounds differently by design
        scale = max(1.0, float(np.max(np.abs(ref))))
        np.testing.assert_allclose(got / scale, ref / scale, atol=1e-5)

    def test_identity_var_is_exact(self):
        """The folded running stats make flax's in-graph rsqrt(var+eps)
        exactly 1.0 — the fold introduces ONE rounding (the precomputed
        scale'), not two."""
        import jax.numpy as jnp
        from jax import lax

        from bdbnn_tpu.models.resnet import BN_EPS, bn_identity_stats

        stats = bn_identity_stats(4)
        r = np.asarray(
            lax.rsqrt(jnp.asarray(stats["var"]) + jnp.float32(BN_EPS))
        )
        assert (r == 1.0).all()


# ---------------------------------------------------------------------------
# Micro-batcher (no JAX: stub runners)
# ---------------------------------------------------------------------------


class TestMicroBatcher:
    def test_coalesces_under_deadline(self):
        seen = []

        def runner(batch):
            seen.append(len(batch))
            return batch

        b = MicroBatcher(runner, max_batch=8, max_queue=32, max_delay_ms=50)
        futs = [b.submit(i) for i in range(8)]
        assert [f.result(timeout=5) for f in futs] == list(range(8))
        assert b.drain(timeout=5)
        # 8 requests submitted back-to-back within one 50ms deadline
        # coalesce into few batches (the first may dispatch solo)
        assert sum(seen) == 8 and len(seen) <= 3
        assert b.stats()["completed"] == 8

    def test_bounded_queue_sheds_never_grows(self):
        release = threading.Event()

        def runner(batch):
            release.wait(10)
            return batch

        b = MicroBatcher(
            runner, max_batch=2, max_queue=4, max_delay_ms=0.0
        )
        futs = []
        shed = 0
        # the worker takes up to max_batch into flight; everything past
        # the 4-slot queue must be REJECTED, not buffered
        for i in range(20):
            try:
                futs.append(b.submit(i))
            except LoadShedError:
                shed += 1
        assert shed > 0
        assert b.stats()["max_queue_depth_seen"] <= 4
        release.set()
        assert b.drain(timeout=5)
        for f in futs:
            assert f.done() and f.exception() is None
        s = b.stats()
        assert s["completed"] == len(futs)
        assert s["shed"] == shed
        assert s["completed"] + s["shed"] == 20

    def test_drain_answers_all_inflight(self):
        def runner(batch):
            time.sleep(0.01)
            return batch

        b = MicroBatcher(runner, max_batch=4, max_queue=64, max_delay_ms=1)
        futs = [b.submit(i) for i in range(32)]
        assert b.drain(timeout=10)  # latched flag; worker finishes queue
        assert all(f.done() for f in futs)
        assert [f.result() for f in futs] == list(range(32))
        # the latch is sticky: post-drain submits are shed explicitly
        with pytest.raises(LoadShedError, match="draining"):
            b.submit(99)

    def test_cancelled_future_does_not_kill_worker(self):
        release = threading.Event()

        def runner(batch):
            release.wait(5)
            return batch

        b = MicroBatcher(runner, max_batch=2, max_queue=8, max_delay_ms=0.0)
        f1 = b.submit(1)  # in flight, blocked in the runner
        time.sleep(0.05)
        f2 = b.submit(2)  # pending -> cancellable
        assert f2.cancel()
        release.set()
        assert f1.result(timeout=5) == 1
        # the worker survived resolving a batch with a cancelled Future
        f3 = b.submit(3)
        assert f3.result(timeout=5) == 3
        assert b.drain(timeout=5)

    def test_runner_exception_fails_futures_not_worker(self):
        calls = []

        def runner(batch):
            calls.append(len(batch))
            if len(calls) == 1:
                raise RuntimeError("boom")
            return batch

        b = MicroBatcher(runner, max_batch=4, max_queue=8, max_delay_ms=1)
        f1 = b.submit(1)
        with pytest.raises(RuntimeError, match="boom"):
            f1.result(timeout=5)
        f2 = b.submit(2)  # the worker survived the batch failure
        assert f2.result(timeout=5) == 2
        assert b.drain(timeout=5)

    def test_strict_priority_dequeue_and_per_class_bounds(self):
        """Priority 0 overtakes a queued priority-1 backlog, each class
        sheds against its OWN bound, and stats()['per_priority'] is the
        one aggregate the verdict/watch read."""
        release = threading.Event()
        executed = []

        def runner(batch):
            release.wait(10)
            executed.extend(batch)
            return batch

        b = MicroBatcher(
            runner, max_batch=1, max_queue=2, max_delay_ms=0.0,
            priorities=2,
        )
        futs = [b.submit("wedge", priority=1)]  # pulled into the runner
        time.sleep(0.05)
        futs += [b.submit(f"low{i}", priority=1) for i in range(2)]
        # class 1 is now full: its third submit sheds...
        with pytest.raises(LoadShedError, match="queue full"):
            b.submit("low-overflow", priority=1)
        # ...while class 0 still has its own 2 slots
        futs.append(b.submit("hi", priority=0))
        with pytest.raises(ValueError, match="priority"):
            b.submit("bad", priority=2)
        release.set()
        for f in futs:
            f.result(timeout=5)
        assert b.drain(timeout=5)
        assert executed[0] == "wedge" and executed[1] == "hi"
        s = b.stats()
        assert [p["shed"] for p in s["per_priority"]] == [0, 1]
        assert [p["completed"] for p in s["per_priority"]] == [1, 3]
        assert s["shed"] == 1 and s["completed"] == 4
        assert s["per_priority"][1]["max_queue_depth_seen"] == 2

    def test_single_priority_stats_backwards_compatible(self):
        b = MicroBatcher(lambda batch: batch, max_batch=4, max_queue=8)
        futs = [b.submit(i) for i in range(6)]
        for f in futs:
            f.result(timeout=5)
        assert b.drain(timeout=5)
        s = b.stats()
        assert s["priorities"] == 1
        assert len(s["per_priority"]) == 1
        assert s["per_priority"][0]["completed"] == s["completed"] == 6


# ---------------------------------------------------------------------------
# Load generator + SLO verdict (no JAX)
# ---------------------------------------------------------------------------


class TestLoadGen:
    def test_percentile_nearest_rank(self):
        vals = [1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 7.0, 8.0, 9.0, 10.0]
        assert percentile(vals, 50) == 5.0
        assert percentile(vals, 99) == 10.0
        assert percentile(vals, 100) == 10.0
        assert percentile([7.0], 99) == 7.0
        assert percentile([], 99) is None

    def _instant_submit(self, payload):
        f = Future()
        f.set_result(payload)
        return f

    def test_closed_loop_accounting(self):
        gen = LoadGenerator(
            self._instant_submit, lambda i: i, mode="closed",
            requests=23, concurrency=4, seed=0,
        )
        raw = gen.run()
        assert raw["submitted"] == 23
        assert raw["completed"] == 23 and raw["shed"] == 0

    def test_closed_loop_ids_cover_range_without_overlap(self):
        """Worker id ranges partition 0..requests-1 exactly, including
        when requests % concurrency != 0 (each worker's base must skip
        the +1 requests handed to earlier workers)."""
        seen = []
        lock = threading.Lock()

        def sample(i):
            with lock:
                seen.append(i)
            return i

        gen = LoadGenerator(
            self._instant_submit, sample, mode="closed",
            requests=10, concurrency=4, seed=0,
        )
        raw = gen.run()
        assert raw["submitted"] == 10
        assert sorted(seen) == list(range(10))

    def test_open_loop_sheds_are_counted(self):
        def always_shed(payload):
            raise LoadShedError("queue full")

        gen = LoadGenerator(
            always_shed, lambda i: i, mode="open", requests=20,
            rate=10000.0, seed=0,
        )
        raw = gen.run()
        assert raw["submitted"] == 20
        assert raw["shed"] == 20 and raw["completed"] == 0

    def test_verdict_is_strict_json_and_deterministic_schema(self):
        raw = {
            "submitted": 10, "completed": 8, "shed": 2,
            "wall_s": 0.5,
            "latencies_ms": sorted([1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 7.0,
                                    float("nan")])[:8],
        }
        stats = {"mean_occupancy": 0.5, "batches": 4,
                 "max_queue_depth_seen": 3, "max_queue": 8}
        v = slo_verdict(
            raw, stats, mode="open", rate=100.0, seed=7,
            provenance={"arch": "resnet8_tiny"},
        )
        # strict RFC 8259: no NaN tokens survive into the verdict
        line = json.dumps(v, allow_nan=False, sort_keys=True)
        parsed = json.loads(
            line, parse_constant=lambda s: pytest.fail(f"bare {s}")
        )
        assert parsed["shed_rate"] == 0.2
        assert parsed["requests_completed"] == 8
        assert parsed["serve_verdict"] == 8
        # v1 consumers: the v2 blocks exist but are null on a plain
        # serve-bench verdict
        assert parsed["per_priority"] is None
        assert parsed["fairness_ratio"] is None
        for k in ("p50_ms", "p95_ms", "p99_ms", "throughput_rps",
                  "mean_batch_occupancy", "drained_clean", "preempted"):
            assert k in parsed


# ---------------------------------------------------------------------------
# Export + engine over a REAL trained run (session fixture)
# ---------------------------------------------------------------------------


class TestExportArtifact:
    def test_artifact_layout_and_strict_json(self, exported_artifact):
        art_dir, artifact = exported_artifact
        assert os.path.exists(os.path.join(art_dir, "artifact.json"))
        assert os.path.exists(os.path.join(art_dir, "weights.npz"))
        with open(os.path.join(art_dir, "artifact.json")) as f:
            parsed = json.loads(
                f.read(),
                parse_constant=lambda s: pytest.fail(f"bare {s}"),
            )
        assert parsed["arch"] == "resnet8_tiny"
        assert parsed["stats"]["binarized_convs"] == 5
        assert parsed["stats"]["compression_ratio"] > 1.0
        assert parsed["checkpoint"]["integrity"] == "ok"
        assert parsed["provenance"]["config_hash"]
        assert len(parsed["weights_sha256"]) == 64

    def test_torn_weights_detected_at_load(
        self, exported_artifact, tmp_path
    ):
        """A mixed/torn re-export (weights not matching the manifest's
        recorded sha256) must fail loudly at load, never serve."""
        import shutil

        from bdbnn_tpu.serve.export import load_artifact_variables

        art_dir, _ = exported_artifact
        torn = str(tmp_path / "torn")
        shutil.copytree(art_dir, torn)
        with open(os.path.join(torn, "weights.npz"), "ab") as f:
            f.write(b"\0" * 16)
        with pytest.raises(RuntimeError, match="sha256"):
            load_artifact_variables(torn)

    def test_no_training_state_in_artifact(self, exported_artifact):
        """The acceptance assertion: no EDE/optimizer/latent-float
        state survives the export — neither in the tensor index nor in
        the weights payload itself."""
        art_dir, artifact = exported_artifact
        from bdbnn_tpu.serve.export import FORBIDDEN_STATE

        paths = [t["path"].lower() for t in artifact["tensors"]]
        npz_keys = [
            k.lower()
            for k in np.load(os.path.join(art_dir, "weights.npz")).keys()
        ]
        for needle in FORBIDDEN_STATE:
            assert not any(needle in p for p in paths), needle
            assert not any(needle in k for k in npz_keys), needle
        # every binary conv ships packed sign bits, not dense latents
        binary = [t for t in artifact["tensors"] if t["kind"] == "binary"]
        assert len(binary) == 5
        for t in binary:
            base = t["path"]
            assert f"sign:{base}".lower() in npz_keys
            assert f"alpha:{base}".lower() in npz_keys

    def test_export_refuses_dir_without_checkpoint(self, tmp_path):
        from bdbnn_tpu.serve.export import export_artifact

        with pytest.raises(RuntimeError, match="no exportable checkpoint"):
            export_artifact(str(tmp_path), str(tmp_path / "a"))

    def test_bare_checkpoint_dir_requires_explicit_dataset(
        self, tiny_trained_run_dir, tmp_path
    ):
        """A checkpoint dir with no run manifest records no dataset; a
        silent default would bake the wrong num_classes/image_size into
        the artifact — export must refuse instead."""
        import shutil

        from bdbnn_tpu.serve.export import export_artifact

        src = str(tmp_path / "ckpt")
        shutil.copytree(
            os.path.join(tiny_trained_run_dir, "model_best"), src
        )
        with pytest.raises(ValueError, match="--dataset"):
            export_artifact(src, str(tmp_path / "a"))
        art = export_artifact(src, str(tmp_path / "a"), dataset="cifar10")
        assert art["arch"] == "resnet8_tiny"  # from the orbax payload
        assert art["provenance"]["config_hash"] is None
        # a non-model_best export must not CLAIM an accuracy its
        # weights never produced — best-seen is context, not a claim
        assert art["eval"]["source"] == "checkpoint"
        assert art["eval"]["checkpoint_acc1"] is None
        assert art["eval"]["best_seen_acc1"] is not None

    def test_export_event_on_run_timeline(
        self, exported_artifact, tiny_trained_run_dir
    ):
        from bdbnn_tpu.obs.events import read_events

        art_dir, artifact = exported_artifact
        exports = read_events(tiny_trained_run_dir, "export")
        assert exports, "export left no event on the source run"
        # several tests export from the shared session run dir (the
        # CLI subprocess smoke among them), so match THIS export's
        # event by its artifact path instead of assuming it was last —
        # in-suite ordering must not decide which event is newest
        e = next(
            (
                e for e in exports
                if e["artifact"] == os.path.abspath(art_dir)
            ),
            None,
        )
        assert e is not None, [x["artifact"] for x in exports]
        assert e["integrity"] == "ok"
        assert e["checkpoint_acc1"] == artifact["eval"]["checkpoint_acc1"]

    def test_reconstruction_is_binarizer_fixed_point(
        self, exported_artifact
    ):
        """Reconstructed float_weight = sign * alpha re-binarizes to
        itself: sign() returns the stored sign, per-channel mean|W|
        returns the stored alpha."""
        from bdbnn_tpu.serve.export import (
            load_artifact_variables,
            unpack_sign,
        )

        art_dir, artifact = exported_artifact
        z = np.load(os.path.join(art_dir, "weights.npz"))
        variables = load_artifact_variables(art_dir)
        t = next(t for t in artifact["tensors"] if t["kind"] == "binary")
        node = variables["params"]
        for k in t["path"].split("/"):
            node = node[k]
        w = node["float_weight"]
        sign = unpack_sign(z[f"sign:{t['path']}"], t["shape"])
        alpha = z[f"alpha:{t['path']}"]
        resigned = np.where(w >= 0, 1.0, -1.0).astype(np.float32)
        np.testing.assert_array_equal(resigned, sign)
        np.testing.assert_allclose(
            np.mean(np.abs(w), axis=(0, 1, 2)), alpha, rtol=1e-6
        )


class TestEngineFidelity:
    def test_predict_reproduces_recorded_eval_top1_exactly(
        self, exported_artifact, tiny_trained_run_dir
    ):
        """The acceptance criterion: export → predict on the training
        run's own val split reproduces the exported checkpoint's
        recorded eval top-1 EXACTLY (same 100*correct/count
        arithmetic)."""
        from bdbnn_tpu.obs.manifest import read_manifest
        from bdbnn_tpu.configs.config import RunConfig
        from bdbnn_tpu.serve.engine import InferenceEngine, evaluate_split
        from bdbnn_tpu.train.loop import build_datasets

        art_dir, artifact = exported_artifact
        cfg_dict = read_manifest(tiny_trained_run_dir)["config"]
        fields = {
            f.name for f in __import__("dataclasses").fields(RunConfig)
        }
        cfg = RunConfig(**{
            k: tuple(v) if isinstance(v, list) else v
            for k, v in cfg_dict.items()
            if k in fields
        })
        _, val_pipe, _ = build_datasets(cfg)
        engine = InferenceEngine(art_dir, buckets=(val_pipe.batch_size,))
        result = evaluate_split(engine, val_pipe)
        assert result["count"] == 64
        assert result["top1"] == artifact["eval"]["checkpoint_acc1"]

    def test_bucket_padding_and_chunking(self, exported_artifact):
        """Any request size maps onto the AOT bucket ladder: short
        batches pad up (logits for the real rows unchanged), oversize
        batches chunk through the largest bucket."""
        from bdbnn_tpu.serve.engine import InferenceEngine

        art_dir, _ = exported_artifact
        engine = InferenceEngine(art_dir, buckets=(1, 4))
        x = np.random.default_rng(3).normal(size=(11, 32, 32, 3)).astype(
            np.float32
        )
        # no shape ever traces at call time: only the AOT buckets exist
        chunked = engine.predict_logits(x)  # 4+4+3->pad(4)
        assert chunked.shape == (11, 10)
        singles = np.concatenate(
            [engine.predict_logits(x[i : i + 1]) for i in range(11)]
        )
        np.testing.assert_allclose(chunked, singles, atol=1e-5)
        assert sorted(engine.compile_seconds) == [1, 4]

    def test_chunk_boundary_logit_equality(self, exported_artifact):
        """THE oversize-chunk seam pin: the single-loop dispatch (no
        recursive re-entry for the final short chunk) yields logits
        BITWISE equal to per-row prediction at exactly the boundary
        sizes — n = big+1 (one full chunk + a pad-to-1 tail) and
        n = 2*big+3 (two full chunks + a padded tail) — so the packed
        path inherits a clean seam."""
        from bdbnn_tpu.serve.engine import InferenceEngine

        art_dir, _ = exported_artifact
        engine = InferenceEngine(art_dir, buckets=(1, 4))
        big = engine.buckets[-1]
        rng = np.random.default_rng(7)
        for n in (big + 1, 2 * big + 3):
            x = rng.normal(size=(n, 32, 32, 3)).astype(np.float32)
            got = engine.predict_logits(x)
            assert got.shape == (n, 10)
            # bitwise vs standalone big-sized slices: the loop's chunk
            # boundaries land at multiples of `big`, and the final
            # short chunk pads exactly like a standalone short batch —
            # no re-entry, no double padding, no seam drift
            by_slice = np.concatenate([
                engine.predict_logits(x[i : i + big])
                for i in range(0, n, big)
            ])
            np.testing.assert_array_equal(got, by_slice)
            # and numerically vs per-row prediction (bucket-1 vs
            # bucket-4 executables may round differently in the last
            # ulp — same tolerance as the padding test above)
            rows = np.concatenate(
                [engine.predict_logits(x[i : i + 1]) for i in range(n)]
            )
            np.testing.assert_allclose(got, rows, atol=1e-5)


# ---------------------------------------------------------------------------
# serve-bench end-to-end
# ---------------------------------------------------------------------------


def _bench_cfg(art_dir, tmp_path, **kw):
    from bdbnn_tpu.configs.config import ServeBenchConfig

    base = dict(
        artifact=art_dir,
        log_path=str(tmp_path / "serve"),
        mode="closed",
        requests=24,
        concurrency=4,
        buckets=(1, 4),
        queue_depth=16,
        max_delay_ms=2.0,
        seed=0,
    )
    base.update(kw)
    return ServeBenchConfig(**base)


class TestServeBench:
    def test_verdict_invariants_and_telemetry(
        self, exported_artifact, tmp_path
    ):
        from bdbnn_tpu.obs.events import read_events
        from bdbnn_tpu.obs.summarize import summarize_run
        from bdbnn_tpu.obs.watch import render_status
        from bdbnn_tpu.serve.loadgen import run_serve_bench

        art_dir, _ = exported_artifact
        res = run_serve_bench(_bench_cfg(art_dir, tmp_path))
        v = res["verdict"]
        # every request is accounted for: answered or explicitly shed
        assert (
            v["requests_completed"] + v["requests_shed"]
            == v["requests_submitted"]
            == 24
        )
        # the queue bound held (sheds instead of growth)
        assert v["max_queue_depth_seen"] <= v["max_queue"] == 16
        assert v["drained_clean"] and not v["preempted"]
        assert v["p99_ms"] is not None and v["p99_ms"] > 0
        assert v["warmup_compile_s"] and set(v["warmup_compile_s"]) == {
            "1", "4",
        }
        # verdict.json on disk equals the emitted verdict event payload
        with open(os.path.join(res["run_dir"], "verdict.json")) as f:
            assert json.load(f) == v
        serves = read_events(res["run_dir"], "serve")
        phases = [e.get("phase") for e in serves]
        assert phases[0] == "start" and phases[-1] == "verdict"
        # watch renders the serving view from the same timeline
        status = render_status(read_events(res["run_dir"]), None)
        assert "SLO:" in status and "serve:" in status
        # summarize grows the serving section
        report, summary = summarize_run(res["run_dir"])
        assert summary["serving"]["verdict"]["p99_ms"] == v["p99_ms"]
        assert "SLO: p50" in report

    def test_sigterm_drains_cleanly_with_all_inflight_answered(
        self, exported_artifact, tmp_path
    ):
        """The acceptance criterion: SIGTERM mid-run latches the flag
        (resilience-style), load stops, the batcher drains, and the
        verdict reports every accepted request answered."""
        from bdbnn_tpu.serve.loadgen import run_serve_bench

        art_dir, _ = exported_artifact
        cfg = _bench_cfg(
            art_dir, tmp_path, mode="open", rate=50.0, requests=10_000,
            seed=1, buckets=(4,),
        )
        pid = os.getpid()
        killer = threading.Timer(
            1.5, lambda: os.kill(pid, signal.SIGTERM)
        )
        killer.start()
        try:
            res = run_serve_bench(cfg)
        finally:
            killer.cancel()
        v = res["verdict"]
        assert v["preempted"] is True
        assert v["drained_clean"] is True
        assert (
            v["requests_completed"] + v["requests_shed"]
            == v["requests_submitted"]
        )
        # the run was actually cut short, not completed
        assert v["requests_submitted"] < 10_000

    @pytest.mark.slow
    def test_poisson_soak(self, exported_artifact, tmp_path):
        """Open-loop Poisson soak at sustained offered load: the queue
        bound holds for thousands of arrivals and the accounting
        identity survives sheds under real overload."""
        from bdbnn_tpu.serve.loadgen import run_serve_bench

        art_dir, _ = exported_artifact
        res = run_serve_bench(_bench_cfg(
            art_dir, tmp_path, mode="open", rate=500.0, requests=4000,
            queue_depth=32, seed=3,
        ))
        v = res["verdict"]
        assert v["requests_submitted"] == 4000
        assert (
            v["requests_completed"] + v["requests_shed"]
            == v["requests_submitted"]
        )
        assert v["max_queue_depth_seen"] <= 32
        assert v["drained_clean"]


# ---------------------------------------------------------------------------
# compare: serving verdicts as first-class artifacts
# ---------------------------------------------------------------------------


def _verdict_file(
    tmp_path, name, p99, thr, shed_rate, recipe=None,
    per_priority=None, per_tenant=None, fairness=None,
):
    v = {
        "serve_verdict": 3,
        "mode": "open",
        "p50_ms": p99 / 3, "p95_ms": p99 / 1.5, "p99_ms": p99,
        "throughput_rps": thr,
        "shed_rate": shed_rate,
        "requests_submitted": 100,
        "requests_completed": int(100 * (1 - shed_rate)),
        "requests_shed": int(100 * shed_rate),
        "mean_batch_occupancy": 0.5,
        "per_priority": per_priority,
        "per_tenant": per_tenant,
        "fairness_ratio": fairness,
        "provenance": {
            "config_hash": "cafe",
            "recipe": recipe
            or {"arch": "resnet8_tiny", "dataset": "cifar10",
                "dtype": "float32"},
        },
    }
    path = str(tmp_path / name)
    with open(path, "w") as f:
        json.dump(v, f)
    return path


def _per_priority(p99s):
    return {
        str(p): {"submitted": 100, "completed": 100, "shed": 0,
                 "p99_ms": v}
        for p, v in enumerate(p99s)
    }


class TestCompareServeVerdicts:
    def test_p99_regression_beyond_tol(self, tmp_path):
        from bdbnn_tpu.obs.compare import compare_runs

        base = _verdict_file(tmp_path, "base.json", 10.0, 1000.0, 0.0)
        cand = _verdict_file(tmp_path, "cand.json", 20.0, 1000.0, 0.0)
        r = compare_runs([base, cand], tol_rel=0.10)
        assert r["verdict"] == "regression"
        bad = [
            m
            for c in r["comparisons"]
            for m in c["metrics"]
            if m["verdict"] == "regression"
        ]
        assert [m["metric"] for m in bad] == ["serve_p99_ms"]

    def test_within_tolerance_passes(self, tmp_path):
        from bdbnn_tpu.obs.compare import compare_runs

        base = _verdict_file(tmp_path, "base.json", 10.0, 1000.0, 0.0)
        cand = _verdict_file(tmp_path, "cand.json", 10.5, 980.0, 0.0)
        r = compare_runs([base, cand], tol_rel=0.10)
        assert r["verdict"] == "pass"

    def test_shed_increase_vs_zero_baseline_regresses(self, tmp_path):
        from bdbnn_tpu.obs.compare import compare_runs

        base = _verdict_file(tmp_path, "base.json", 10.0, 1000.0, 0.0)
        cand = _verdict_file(tmp_path, "cand.json", 10.0, 1000.0, 0.05)
        r = compare_runs([base, cand], tol_rel=0.10)
        assert r["verdict"] == "regression"

    def test_per_priority_p99_regression_caught(self, tmp_path):
        """The aggregate p99 can look flat while ONE class regresses
        (a flood of cheap low-priority traffic hides a priority-0
        collapse in the mix) — the per-priority metrics catch exactly
        that, and a regression there is exit-3 class."""
        from bdbnn_tpu.obs.compare import compare_runs

        base = _verdict_file(
            tmp_path, "base.json", 10.0, 1000.0, 0.0,
            per_priority=_per_priority([5.0, 8.0, 12.0]),
        )
        cand = _verdict_file(
            tmp_path, "cand.json", 10.0, 1000.0, 0.0,
            per_priority=_per_priority([20.0, 8.0, 12.0]),
        )
        r = compare_runs([base, cand], tol_rel=0.10)
        assert r["verdict"] == "regression"
        bad = [
            m["metric"]
            for c in r["comparisons"]
            for m in c["metrics"]
            if m["verdict"] == "regression"
        ]
        assert bad == ["serve_p99_ms_p0"]

    def test_fairness_and_tenant_shed_metrics_judged(self, tmp_path):
        from bdbnn_tpu.obs.compare import compare_runs

        tenants_ok = {
            "a": {"submitted": 50, "completed": 50, "shed_rate": 0.0},
            "b": {"submitted": 50, "completed": 48, "shed_rate": 0.04},
        }
        tenants_bad = {
            "a": {"submitted": 50, "completed": 50, "shed_rate": 0.0},
            "b": {"submitted": 50, "completed": 25, "shed_rate": 0.5},
        }
        base = _verdict_file(
            tmp_path, "base.json", 10.0, 1000.0, 0.0,
            per_tenant=tenants_ok, fairness=1.04,
        )
        cand = _verdict_file(
            tmp_path, "cand.json", 10.0, 1000.0, 0.0,
            per_tenant=tenants_bad, fairness=2.0,
        )
        r = compare_runs([base, cand], tol_rel=0.10)
        assert r["verdict"] == "regression"
        rows = {
            m["metric"]: m["verdict"]
            for c in r["comparisons"]
            for m in c["metrics"]
        }
        assert rows["serve_fairness_ratio"] == "regression"
        assert rows["serve_tenant_shed_rate_max"] == "regression"

    def test_v1_verdict_still_compares_on_aggregates(self, tmp_path):
        """A pre-PR7 verdict (no per_priority/per_tenant blocks) still
        aligns and judges on the v1 aggregate metrics — None rows are
        skipped, never phantom-judged."""
        from bdbnn_tpu.obs.compare import compare_runs

        base = _verdict_file(tmp_path, "base.json", 10.0, 1000.0, 0.0)
        cand = _verdict_file(
            tmp_path, "cand.json", 10.5, 990.0, 0.0,
            per_priority=_per_priority([5.0, 8.0, 12.0]),
        )
        r = compare_runs([base, cand], tol_rel=0.10)
        assert r["verdict"] == "pass"
        judged = {
            m["metric"]
            for c in r["comparisons"]
            for m in c["metrics"]
        }
        assert "serve_p99_ms" in judged
        assert "serve_p99_ms_p0" not in judged  # baseline side is None

    def test_export_provenance_mismatch_refused(self, tmp_path):
        from bdbnn_tpu.obs.compare import compare_runs

        base = _verdict_file(tmp_path, "base.json", 10.0, 1000.0, 0.0)
        cand = _verdict_file(
            tmp_path, "cand.json", 10.0, 1000.0, 0.0,
            recipe={"arch": "resnet18", "dataset": "cifar10",
                    "dtype": "float32"},
        )
        r = compare_runs([base, cand])
        assert r["verdict"] == "incomparable"
        r = compare_runs([base, cand], allow_mismatch=True)
        assert r["verdict"] == "pass"


# ---------------------------------------------------------------------------
# watch: serving mode over synthetic events (no processes)
# ---------------------------------------------------------------------------


class TestWatchServeMode:
    def test_live_stats_line(self):
        from bdbnn_tpu.obs.watch import render_status

        events = [
            {"t": 1.0, "kind": "serve", "phase": "start",
             "mode": "open", "arch": "resnet8_tiny", "buckets": [1, 8],
             "queue_depth": 64, "requests": 500},
            {"t": 2.0, "kind": "serve", "phase": "stats",
             "batch_size": 6, "occupancy": 0.75, "queue_depth": 3,
             "rolling_p99_ms": 12.5, "completed": 120, "shed": 2},
        ]
        out = render_status(events, None)
        assert "serve: open load on resnet8_tiny" in out
        assert "queue 3" in out and "rolling p99 12.5 ms" in out
        assert "shed 2" in out and "occupancy 75%" in out

    def test_verdict_line_replaces_live_stats(self):
        from bdbnn_tpu.obs.watch import render_status

        events = [
            {"t": 1.0, "kind": "serve", "phase": "start",
             "mode": "open", "arch": "resnet8_tiny", "buckets": [1, 8],
             "queue_depth": 64, "requests": 500},
            {"t": 2.0, "kind": "serve", "phase": "stats",
             "occupancy": 0.75, "queue_depth": 3,
             "rolling_p99_ms": 12.5, "completed": 120, "shed": 0},
            {"t": 3.0, "kind": "serve", "phase": "verdict",
             "p50_ms": 4.0, "p95_ms": 9.0, "p99_ms": 14.0,
             "throughput_rps": 450.0, "mean_batch_occupancy": 0.7,
             "shed_rate": 0.01, "preempted": False},
        ]
        out = render_status(events, None)
        assert "SLO:" in out and "p95 9.0" in out and "shed 1.0%" in out
        assert "rolling p99" not in out  # live line yields to the verdict

    def test_export_handoff_line_on_training_run(self):
        from bdbnn_tpu.obs.watch import render_status

        events = [
            {"t": 1.0, "kind": "run_start", "epochs": 1,
             "steps_per_epoch": 4, "config_hash": "abc"},
            {"t": 9.0, "kind": "export", "artifact": "/tmp/a",
             "arch": "resnet8_tiny", "binarized_convs": 5,
             "compression_ratio": 7.1, "checkpoint_acc1": 12.5},
        ]
        out = render_status(events, None)
        assert "export: /tmp/a" in out and "7.1x smaller" in out


# ---------------------------------------------------------------------------
# ServeBenchConfig validation
# ---------------------------------------------------------------------------


class TestServeBenchConfig:
    def test_validate_rejects_bad_knobs(self):
        from bdbnn_tpu.configs.config import ServeBenchConfig

        ok = ServeBenchConfig(artifact="a").validate()
        assert ok.mode == "open" and ok.buckets == (1, 8, 32)
        with pytest.raises(ValueError, match="load mode"):
            ServeBenchConfig(artifact="a", mode="swarm").validate()
        with pytest.raises(ValueError, match="buckets"):
            ServeBenchConfig(artifact="a", buckets=(0,)).validate()
        with pytest.raises(ValueError, match="queue-depth"):
            ServeBenchConfig(artifact="a", queue_depth=0).validate()
        with pytest.raises(ValueError, match="rate"):
            ServeBenchConfig(artifact="a", rate=0.0).validate()
        with pytest.raises(ValueError, match="artifact"):
            ServeBenchConfig(artifact="").validate()
        # replica-pool knobs fail at config time too
        with pytest.raises(ValueError, match="replicas"):
            ServeBenchConfig(artifact="a", replicas=(0,)).validate()
        with pytest.raises(ValueError, match="replicas"):
            ServeBenchConfig(artifact="a", replicas=()).validate()
        with pytest.raises(ValueError, match="pace-ms"):
            ServeBenchConfig(artifact="a", pace_ms=-1.0).validate()
        with pytest.raises(ValueError, match="replica-queue-batches"):
            ServeBenchConfig(
                artifact="a", replica_queue_batches=0
            ).validate()
        with pytest.raises(ValueError, match="wedge-timeout"):
            ServeBenchConfig(artifact="a", wedge_timeout_s=0).validate()
