"""Fault-injection harness: kill training at arbitrary points, resume,
and prove the result matches an uninterrupted run.

The preemption claim this repo makes (docs/design.md §7) is *exact
resume*: a run killed at ANY step and restarted from its last
checkpoint reaches the same final state as if it had never been killed
— including the schedule state BNN dynamics are sensitive to (EDE
(t, k), the step-indexed LR position, the kurtosis epoch gate: a
resume that fast-forwards those wrong corrupts the bimodal-distribution
training the paper depends on, and sign-flip sensitivity turns small
drift into large flip-rate artifacts).

Three tiers:

- **SIGTERM (graceful preemption)** — delivered to an in-process
  ``cli.main`` run mid-epoch; asserts the preemption protocol: flag
  checked at a step boundary, mid-epoch checkpoint committed,
  ``preempt`` + ``checkpoint`` events, exit code 75 (EX_TEMPFAIL),
  then resume → final state matches the uninterrupted baseline.
- **SIGKILL (hard kill, subprocess)** — no cleanup possible, so
  survival rests entirely on the durable-commit protocol: the victim
  subprocess is SIGKILLed right after its first mid-epoch interval
  checkpoint commits; resume matches the baseline and the resume
  point's schedule state is BITWISE-identical to what the victim
  recorded at save time.
- **randomized kill matrix** (``slow``) — SIGKILL at random offsets.
- **resharded restore** (``TestReshardedResume``) — the mid-epoch
  checkpoint restores onto SMALLER simulated topologies (4-device
  tier-1, 2-device ``slow``) in fresh subprocesses: bitwise-equal
  global params at restore, reshard lineage in the ``restore`` event,
  baseline-equal final metrics. The multi-PROCESS (pod) fault matrix
  lives in tests/test_pod_faults.py.

Cost control (tier-1 budget): everything runs the 2-stage width-8
``resnet8_tiny`` on 4-step synthetic epochs; the baseline fit is a
module fixture shared by every comparison; only the SIGKILL victim is
a real subprocess (SIGTERM is exercised in-process, which covers the
identical handler/save/raise path without a second interpreter+compile
bill).
"""

import glob
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax

from conftest import (
    FAULT_EPOCHS as EPOCHS,
    FAULT_STEPS_PER_EPOCH as STEPS_PER_EPOCH,
    fault_cfg as _cfg,
    fault_cli_args as _cli_args,
)
from bdbnn_tpu.train.loop import fit
from bdbnn_tpu.train.resilience import PREEMPT_EXIT_CODE
from bdbnn_tpu.utils.checkpoint import CKPT_NAME, load_variables


def _run_dir(root):
    hits = glob.glob(os.path.join(str(root), "**", "events.jsonl"),
                     recursive=True)
    assert hits, f"no events.jsonl under {root}"
    return os.path.dirname(sorted(hits)[-1])


def _events(run_dir, kind=None):
    out = []
    path = os.path.join(run_dir, "events.jsonl")
    if not os.path.exists(path):
        return out
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail of a killed writer
            if kind is None or rec.get("kind") == kind:
                out.append(rec)
    return out


def _wait_for_event(root, predicate, timeout=120.0, poll=0.05):
    """Poll the newest run dir under ``root`` until an event matches."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        hits = glob.glob(os.path.join(str(root), "**", "events.jsonl"),
                         recursive=True)
        if hits:
            run_dir = os.path.dirname(sorted(hits)[-1])
            for e in _events(run_dir):
                if predicate(e):
                    return run_dir, e
        time.sleep(poll)
    return None, None


def _final_params(run_dir):
    """Params of the run's FINAL committed checkpoint (not model_best —
    the equality claim is about where training ended up)."""
    return load_variables(os.path.join(run_dir, CKPT_NAME))


def _assert_params_equal(a, b):
    la = jax.tree_util.tree_leaves(a["params"])
    lb = jax.tree_util.tree_leaves(b["params"])
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), rtol=0, atol=1e-6
        )


SCHED_KEYS = ("epoch", "step_in_epoch", "lr_step", "ede_t", "ede_k",
              "kurt_gate")


def _assert_schedule_bitwise(saved_ckpt_event, restore_event):
    """The resumed run must re-enter with EXACTLY the schedule state the
    interrupted run froze — bitwise, no tolerance: these scalars are
    pure functions of (epoch, step) and any drift is a resume bug."""
    for key in SCHED_KEYS:
        assert restore_event[key] == saved_ckpt_event[key], (
            key, saved_ckpt_event, restore_event,
        )


@pytest.fixture(scope="module")
def baseline(fault_baseline):
    """ONE uninterrupted run (session-scoped, shared with the pod
    matrix in test_pod_faults.py); every kill/resume result compares
    to it."""
    return fault_baseline


@pytest.fixture(scope="module")
def preempted(tmp_path_factory):
    """An in-process CLI run SIGTERMed mid-epoch — shared by the
    graceful-preemption assertions AND the resharded-restore tests
    (its mid-epoch checkpoint is the reshard source)."""
    from bdbnn_tpu.cli import main

    root = tmp_path_factory.mktemp("sigterm")

    def _assassin():
        # SIGTERM once training is demonstrably mid-epoch (a step
        # beyond the first has completed and a checkpoint exists to
        # resume from if the flag lands before the next save)
        _wait_for_event(
            root,
            lambda e: e.get("kind") == "train_interval"
            and e.get("step", 0) >= 1,
        )
        os.kill(os.getpid(), signal.SIGTERM)

    t = threading.Thread(target=_assassin, daemon=True)
    t.start()
    rc = main(_cli_args(root))
    t.join(timeout=5)
    return {"rc": rc, "run_dir": _run_dir(root)}


class TestSigtermPreemption:
    """Graceful preemption through the real CLI entry point."""

    def test_exit_code_is_preempt(self, preempted):
        assert preempted["rc"] == PREEMPT_EXIT_CODE == 75

    def test_preempt_protocol_events(self, preempted):
        run_dir = preempted["run_dir"]
        preempts = _events(run_dir, "preempt")
        assert len(preempts) == 1
        p = preempts[0]
        assert p["signum"] == signal.SIGTERM
        ckpts = _events(run_dir, "checkpoint")
        assert ckpts, "no checkpoint events from the preempted run"
        last = ckpts[-1]
        # the final checkpoint is the preemption save (or, if the flag
        # landed at an epoch boundary, the epoch-end save) and its
        # cursor matches the preempt event's
        assert last["epoch"] == p["epoch"]
        assert last["step_in_epoch"] == p["step_in_epoch"]
        assert any(c["reason"] == "preempt" for c in ckpts) or (
            p["step_in_epoch"] == 0
        )
        # run_end never fired — the run was cut short
        assert not _events(run_dir, "run_end")

    def test_resume_matches_uninterrupted(
        self, preempted, baseline, tmp_path
    ):
        victim_dir = preempted["run_dir"]
        res = fit(_cfg(tmp_path / "resumed", resume=victim_dir))
        run_dir = _run_dir(tmp_path / "resumed")

        restore = _events(run_dir, "restore")[0]
        saved = _events(victim_dir, "checkpoint")[-1]
        _assert_schedule_bitwise(saved, restore)
        assert restore["integrity"] == "ok"
        assert restore["fallback"] is False

        assert res["best_acc1"] == pytest.approx(
            baseline["res"]["best_acc1"], abs=1e-3
        )
        _assert_params_equal(_final_params(run_dir), baseline["params"])

        # restart lineage recorded for the summarize/watch surfaces
        with open(os.path.join(run_dir, "manifest.json")) as f:
            man = json.load(f)
        assert man["resumed_from"] == os.path.abspath(victim_dir)
        assert man["restart_lineage"] == [os.path.abspath(victim_dir)]


@pytest.mark.slow
class TestSigkillResume:
    """Hard kill: no handler, no cleanup — only the committed mid-epoch
    checkpoint survives. The acceptance-criteria test.

    tier-1 budget (PR 10 rebalance): rides the slow tier with the
    randomized SIGKILL matrix and the pod-SIGKILL variant it fronts —
    hard-kill survivability keeps denser tier-1 coverage via the
    deterministic crash-at-every-commit-phase matrix
    (test_checkpoint), the in-process SIGTERM preempt->resume e2e
    (TestSigtermPreemption) and the coordinated pod preemption e2e
    (test_pod_faults)."""

    @pytest.fixture(scope="class")
    def killed(self, tmp_path_factory):
        root = tmp_path_factory.mktemp("sigkill")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, "-m", "bdbnn_tpu.cli", *_cli_args(root)],
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        try:
            # first mid-epoch interval checkpoint committed -> SIGKILL.
            # ~6 steps + eval remain (seconds), so the kill always lands
            # before the run can finish.
            run_dir, _ = _wait_for_event(
                root,
                lambda e: e.get("kind") == "checkpoint"
                and e.get("step_in_epoch", 0) > 0,
                timeout=300.0,
            )
            assert run_dir is not None, "victim never checkpointed"
            proc.kill()
            rc = proc.wait(timeout=60)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=60)
        assert rc == -signal.SIGKILL
        return {"run_dir": run_dir}

    def test_resume_matches_uninterrupted(self, killed, baseline, tmp_path):
        victim_dir = killed["run_dir"]
        saved = _events(victim_dir, "checkpoint")[-1]
        assert saved["step_in_epoch"] > 0  # genuinely mid-epoch
        assert not _events(victim_dir, "run_end")

        res = fit(_cfg(tmp_path / "resumed", resume=victim_dir))
        run_dir = _run_dir(tmp_path / "resumed")

        restore = _events(run_dir, "restore")[0]
        _assert_schedule_bitwise(saved, restore)
        assert restore["integrity"] == "ok"

        assert res["best_acc1"] == pytest.approx(
            baseline["res"]["best_acc1"], abs=1e-3
        )
        _assert_params_equal(_final_params(run_dir), baseline["params"])


class TestReshardedResume:
    """Elastic resume across DEVICE-topology changes: the 8-device
    session's mid-epoch preemption checkpoint restores onto smaller
    simulated topologies (fresh subprocesses pinned to their own
    ``--xla_force_host_platform_device_count``). Asserts the elastic
    contract end to end: bitwise-equal global params at restore (the
    reshard changes placement, never values — checked in the worker
    against the template-free host read), bitwise-identical schedule
    state, reshard lineage in the ``restore`` event, globally-complete
    sharded eval, and baseline-equal final metrics."""

    def _reshard(
        self, devices, preempted, baseline, tmp_path, sim_device_subprocess
    ):
        victim_dir = preempted["run_dir"]
        saved = _events(victim_dir, "checkpoint")[-1]
        worker = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "reshard_worker.py"
        )
        root = tmp_path / "resumed"
        # the shared simulated-device harness (conftest): the worker
        # pins its own device count from argv, so pin_env=False — the
        # harness still strips the parent's XLA_FLAGS and sets
        # PYTHONPATH/cwd
        proc = sim_device_subprocess(
            [
                worker, str(devices), victim_dir,
                *_cli_args(root), "--resume", victim_dir,
            ],
            devices=devices, timeout=540, pin_env=False,
        )
        assert proc.returncode == 0, (
            f"rc={proc.returncode}\nstdout:{proc.stdout[-1500:]}\n"
            f"stderr:{proc.stderr[-3000:]}"
        )
        # restored values identical to what was saved, on the new mesh
        assert "RESHARD_PARAMS_BITWISE_OK" in proc.stdout

        run_dir = _run_dir(root)
        restore = _events(run_dir, "restore")[0]
        _assert_schedule_bitwise(saved, restore)
        assert restore["integrity"] == "ok"
        assert restore["resharded"] is True
        assert restore["topology_from"]["devices"] == 8
        assert restore["topology_from"]["processes"] == 1
        assert restore["topology_to"]["devices"] == devices
        # sharded eval still counted the FULL val split on the new mesh
        evals = _events(run_dir, "eval")
        assert evals and all(e["count"] == 64 for e in evals)
        # same final eval metrics as the uninterrupted baseline
        end = _events(run_dir, "run_end")[-1]
        assert end["best_acc1"] == pytest.approx(
            baseline["res"]["best_acc1"], abs=1e-3
        )

    def test_restore_onto_4_devices(
        self, preempted, baseline, tmp_path, sim_device_subprocess
    ):
        self._reshard(
            4, preempted, baseline, tmp_path, sim_device_subprocess
        )

    @pytest.mark.slow
    def test_restore_onto_2_devices(
        self, preempted, baseline, tmp_path, sim_device_subprocess
    ):
        self._reshard(
            2, preempted, baseline, tmp_path, sim_device_subprocess
        )


@pytest.mark.slow
class TestKillMatrix:
    """SIGKILL at randomized offsets — the broad sweep of the same
    invariant. Excluded from tier-1 (`-m 'not slow'`); run explicitly
    when touching the checkpoint/resume machinery."""

    @pytest.mark.parametrize("trial", range(3))
    def test_random_offset_kill_then_resume(
        self, trial, baseline, tmp_path
    ):
        rng = np.random.default_rng(trial)
        root = tmp_path / "victim"
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, "-m", "bdbnn_tpu.cli", *_cli_args(root)],
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        try:
            run_dir, _ = _wait_for_event(
                root,
                lambda e: e.get("kind") == "train_interval",
                timeout=300.0,
            )
            assert run_dir is not None
            time.sleep(float(rng.uniform(0.0, 2.0)))
            proc.kill()
            proc.wait(timeout=60)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=60)
        if not os.path.isdir(os.path.join(run_dir, CKPT_NAME)) and not (
            os.path.isdir(os.path.join(run_dir, CKPT_NAME + ".old"))
        ):
            pytest.skip("killed before any checkpoint committed")
        res = fit(_cfg(tmp_path / "resumed", resume=run_dir))
        assert res["best_acc1"] == pytest.approx(
            baseline["res"]["best_acc1"], abs=1e-3
        )
        _assert_params_equal(
            _final_params(_run_dir(tmp_path / "resumed")),
            baseline["params"],
        )
