"""Simulated-POD fault-injection matrix (the acceptance e2e).

Everything in tests/test_faults.py kills ONE process. This module forms
a real 2-process jax.distributed cluster over gloo CPU collectives
(2 virtual devices per host — the proven tests/multihost_worker.py
bring-up) running the REAL CLI entry point, and injects faults into
individual pod hosts:

- **SIGTERM to ONE host** — the coordinated-preemption acceptance
  test: the signal latches on host 0 only, the step-boundary
  coordination all-reduce (parallel/mesh.py:coordinate_flags) spreads
  it, and BOTH hosts must exit 75 (EX_TEMPFAIL) after committing a
  SINGLE aligned collective checkpoint. The run also drives
  ``--save-every-mins`` (process-0 clock, broadcast) — the cadence
  that was BANNED on multi-process runs before the coordination layer
  — so the wallclock path produces coordinated mid-epoch saves on a
  pod in tier-1.
- **Elastic resume onto a smaller topology** — the victim's pod
  checkpoint (2 processes x 2 devices) resumes IN-PROCESS on this
  session's 1 process x 8 devices: bitwise-identical schedule state
  (epoch, step, lr_step, EDE t/k, kurt gate) between the victim's last
  ``checkpoint`` event and the resume's ``restore`` event, reshard
  lineage recorded, sharded eval counting the full val split, and the
  same final eval metrics as the uninterrupted baseline.
- **SIGKILL to ONE host** (``slow``) — no cleanup possible on the
  victim, and the survivor blocks in a collective against a dead peer:
  the parent reaps both, then proves the last COMMITTED coordinated
  interval checkpoint resumes to the baseline result. The pod-scope
  version of test_faults.py's SIGKILL tier.

Cost control: one pod (2 subprocesses) per scenario, smoke-scale
resnet8_tiny on 4-step synthetic epochs, and the resume/baseline
comparisons reuse the session-scoped ``fault_baseline`` fixture.
"""

import glob
import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

from conftest import fault_cfg as _cfg, fault_cli_args as _cli_args
from bdbnn_tpu.train.loop import fit
from bdbnn_tpu.train.resilience import PREEMPT_EXIT_CODE
from bdbnn_tpu.utils.checkpoint import CKPT_NAME, verify_integrity

from test_faults import (
    _assert_schedule_bitwise,
    _events,
    _run_dir,
)

pytestmark = pytest.mark.gloo

WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "pod_worker.py")
REPO_ROOT = os.path.dirname(os.path.dirname(WORKER))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_pod(root, num_procs=2, devices=2, extra=()):
    """Launch one simulated pod: ``num_procs`` worker subprocesses of
    ``devices`` virtual CPU chips each, all running the real CLI with
    the fault-harness recipe into a SHARED log root."""
    port = _free_port()
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    args = _cli_args(root, **dict(extra))
    return [
        subprocess.Popen(
            [
                sys.executable, WORKER, str(i), str(num_procs), str(port),
                str(devices), *args,
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
            cwd=REPO_ROOT,
        )
        for i in range(num_procs)
    ]


def _wait_for_pod_event(root, predicate, procs, timeout=300.0, poll=0.2):
    """Poll the shared run dir (process 0's events.jsonl) until an
    event matches; bail early if every worker already exited."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        hits = glob.glob(
            os.path.join(str(root), "**", "events.jsonl"), recursive=True
        )
        for h in sorted(hits, reverse=True):
            run_dir = os.path.dirname(h)
            for e in _events(run_dir):
                if predicate(e):
                    return run_dir, e
        if all(p.poll() is not None for p in procs):
            return None, None
        time.sleep(poll)
    return None, None


def _reap(procs, timeout=240):
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            out, err = p.communicate()
        outs.append((p.returncode, out, err))
    return outs


def _fail_debug(outs):
    return "\n".join(
        f"--- worker rc={rc}\nstdout:{out[-1200:]}\nstderr:{err[-2500:]}"
        for rc, out, err in outs
    )


class TestCoordinatedPreemption:
    """SIGTERM one host of a 2-process pod -> every host exits 75 with
    one aligned coordinated checkpoint; resume onto a smaller topology
    reproduces the uninterrupted run."""

    def _spawn_victim_attempt(self, root):
        """One attempt: bring the 2-process pod up to a running train
        step, SIGTERM host 0, reap. Raises AssertionError when the
        cluster never FORMED (a worker dying during GRPC coordinator
        bring-up) so the fixture can bound a retry; contract
        violations by a formed cluster are judged by the tests."""
        # --save-every-mins at a tiny interval: every boundary's
        # coordination carries process-0's (always-due) clock decision,
        # exercising the previously banned wallclock path on a pod.
        # --save-every-steps off to prove the saves came from the
        # wallclock cadence, not the step cadence.
        procs = _spawn_pod(
            root,
            extra={"--save-every-mins": "0.0005", "--save-every-steps": None},
        )
        try:
            run_dir, _ = _wait_for_pod_event(
                root,
                lambda e: e.get("kind") == "train_interval"
                and e.get("step", 0) >= 1,
                procs,
            )
            assert run_dir is not None, _fail_debug(_reap(procs, timeout=5))
            # deliver SIGTERM to host 0 ONLY — host 1 must learn about
            # it through the coordination all-reduce
            procs[0].send_signal(signal.SIGTERM)
            outs = _reap(procs)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        return {"run_dir": run_dir, "outs": outs}

    @pytest.fixture(scope="class")
    def pod_victim(self, tmp_path_factory):
        """Cluster formation quarantined behind
        conftest.retry_once_flaky (the ONE bounded retry-once policy),
        for the documented transient (PR 7/8/9 notes: a worker dying
        or timing out during GRPC coordinator bring-up on a contended
        box; in-suite ERRORs that never reproduce in isolation). Only
        the did-the-cluster-form assertion retries; every
        post-formation contract is asserted by the tests and fails
        deterministically."""
        from conftest import retry_once_flaky

        return retry_once_flaky(
            lambda i: self._spawn_victim_attempt(
                tmp_path_factory.mktemp(
                    "pod_sigterm" if i == 0 else "pod_sigterm_retry"
                )
            ),
            note=(
                "pod cluster attempt 1 never formed (GRPC coordinator "
                "bring-up transient on contended boxes, PR 7/8/9 "
                "notes)"
            ),
        )

    def test_every_host_exits_75(self, pod_victim):
        rcs = [rc for rc, _, _ in pod_victim["outs"]]
        assert rcs == [PREEMPT_EXIT_CODE, PREEMPT_EXIT_CODE], _fail_debug(
            pod_victim["outs"]
        )

    def test_single_aligned_coordinated_checkpoint(self, pod_victim):
        run_dir = pod_victim["run_dir"]
        preempts = _events(run_dir, "preempt")
        assert len(preempts) == 1
        p = preempts[0]
        assert p["signum"] == signal.SIGTERM
        assert p["coordinated"] is True
        assert p["coordination_step"] >= 1  # a real step boundary agreed
        ckpts = _events(run_dir, "checkpoint")
        assert ckpts, "no checkpoint events from the pod victim"
        # the wallclock cadence produced coordinated interval saves on
        # a multi-process run (the lifted --save-every-mins ban)
        assert any(c["reason"] == "interval" for c in ckpts)
        assert all(c["coordinated"] is True for c in ckpts)
        last = ckpts[-1]
        assert last["reason"] == "preempt" or p["step_in_epoch"] == 0
        assert last["epoch"] == p["epoch"]
        assert last["step_in_epoch"] == p["step_in_epoch"]
        # ONE committed checkpoint chain, integrity-verified — not one
        # per host, not mixed-step shards
        ckpt_dir = os.path.join(run_dir, CKPT_NAME)
        assert os.path.isdir(ckpt_dir)
        assert verify_integrity(ckpt_dir) == "ok"
        # host 1 wrote its telemetry to its own per-process channel in
        # the SAME shared run dir (process-0 timestamp broadcast)
        assert os.path.exists(os.path.join(run_dir, "events.p1.jsonl"))
        with open(os.path.join(run_dir, "events.p1.jsonl")) as f:
            p1 = [json.loads(l) for l in f if l.strip()]
        p1_pre = [e for e in p1 if e.get("kind") == "preempt"]
        assert len(p1_pre) == 1
        # both hosts agreed on the SAME preemption point
        assert p1_pre[0]["epoch"] == p["epoch"]
        assert p1_pre[0]["step_in_epoch"] == p["step_in_epoch"]
        assert not _events(run_dir, "run_end")

    def test_elastic_resume_onto_smaller_topology(
        self, pod_victim, fault_baseline, tmp_path
    ):
        victim_dir = pod_victim["run_dir"]
        saved = _events(victim_dir, "checkpoint")[-1]
        # resume IN-PROCESS: this session is 1 process x 8 devices —
        # fewer hosts than the 2-process pod that wrote the checkpoint
        res = fit(_cfg(tmp_path / "resumed", resume=victim_dir))
        run_dir = _run_dir(tmp_path / "resumed")

        restore = _events(run_dir, "restore")[0]
        _assert_schedule_bitwise(saved, restore)
        assert restore["integrity"] == "ok"
        assert restore["fallback"] is False
        assert restore["resharded"] is True
        assert restore["topology_from"] == {
            "processes": 2, "devices": 4, "mesh": {"data": 4, "model": 1},
        }
        assert restore["topology_to"]["processes"] == 1
        assert restore["topology_to"]["devices"] == 8

        # manifest topology lineage rides next to restart_lineage
        with open(os.path.join(run_dir, "manifest.json")) as f:
            man = json.load(f)
        assert man["resumed_from"] == os.path.abspath(victim_dir)
        assert man["topology_from"]["processes"] == 2
        assert man["topology_to"]["processes"] == 1

        # sharded eval counted the FULL split after the reshard
        evals = _events(run_dir, "eval")
        assert evals and all(e["count"] == 64 for e in evals)

        # same final eval metrics as the uninterrupted baseline
        assert res["best_acc1"] == pytest.approx(
            fault_baseline["res"]["best_acc1"], abs=1e-3
        )


@pytest.mark.slow
class TestPodSigkill:
    """SIGKILL one pod host right after the first coordinated interval
    checkpoint commits. The survivor blocks in a collective against a
    dead peer (reaped by the parent — that is what a pod scheduler
    does); durability rests entirely on the COMMITTED coordinated
    checkpoint, which must resume onto this session's topology to the
    baseline result."""

    def test_sigkill_one_host_then_resume(
        self, fault_baseline, tmp_path_factory, tmp_path
    ):
        root = tmp_path_factory.mktemp("pod_sigkill")
        procs = _spawn_pod(root)  # step cadence: --save-every-steps 2
        try:
            run_dir, ck = _wait_for_pod_event(
                root,
                lambda e: e.get("kind") == "checkpoint"
                and e.get("step_in_epoch", 0) > 0,
                procs,
            )
            assert run_dir is not None, _fail_debug(_reap(procs, timeout=5))
            procs[0].kill()
            # the survivor cannot make progress without its peer; give
            # it a moment to park in the collective, then reap it —
            # the pod scheduler's job, not the training system's
            time.sleep(2.0)
            procs[1].kill()
            _reap(procs, timeout=60)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        assert ck["coordinated"] is True and ck["reason"] == "interval"

        res = fit(_cfg(tmp_path / "resumed", resume=run_dir))
        resumed_dir = _run_dir(tmp_path / "resumed")
        restore = _events(resumed_dir, "restore")[0]
        saved = [
            e
            for e in _events(run_dir, "checkpoint")
            if e["step_in_epoch"] == restore["step_in_epoch"]
            and e["epoch"] == restore["epoch"]
        ][-1]
        _assert_schedule_bitwise(saved, restore)
        assert res["best_acc1"] == pytest.approx(
            fault_baseline["res"]["best_acc1"], abs=1e-3
        )
