"""Train-core tests: EDE schedule parity, optimizer parity vs torch,
train-step behavior (loss decreases, kurtosis gating, TS loss wiring)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bdbnn_tpu.losses.kd import softmax_cross_entropy
from bdbnn_tpu.models import conv_weight_paths, module_path_str
from bdbnn_tpu.models.resnet import BiResNet
from bdbnn_tpu.train import (
    StepConfig,
    TrainState,
    cpt_tk,
    make_eval_step,
    make_optimizer,
    make_train_step,
    make_ts_train_step,
)
from bdbnn_tpu.train.optim import conv_weight_mask


class TestEDESchedule:
    def test_matches_reference_formula(self):
        # oracle: utils/utils.py:6-14 computed with torch
        import torch

        for epoch, tot in [(0, 90), (45, 90), (89, 90), (10, 200)]:
            T_min, T_max = torch.tensor(1e-2).float(), torch.tensor(1e1).float()
            Tmin, Tmax = torch.log10(T_min), torch.log10(T_max)
            t_ref = torch.pow(
                torch.tensor(10.0), Tmin + (Tmax - Tmin) / tot * epoch
            ).item()
            k_ref = max(1.0 / t_ref, 1.0)
            t, k = cpt_tk(epoch, tot)
            assert t == pytest.approx(t_ref, rel=1e-5)
            assert k == pytest.approx(k_ref, rel=1e-5)

    def test_endpoints(self):
        t0, k0 = cpt_tk(0, 100)
        assert t0 == pytest.approx(1e-2)
        assert k0 == pytest.approx(100.0)
        t_end, k_end = cpt_tk(100, 100)
        assert t_end == pytest.approx(10.0)
        assert k_end == 1.0


def _tiny_model():
    return BiResNet(
        stage_sizes=(1, 1),
        num_classes=4,
        width=8,
        stem="cifar",
        variant="cifar",
        act="hardtanh",
    )


def _tiny_batch(rng, n=16, hw=8, classes=4):
    x = rng.normal(size=(n, hw, hw, 3)).astype(np.float32)
    y = rng.integers(0, classes, size=(n,))
    return jnp.asarray(x), jnp.asarray(y)


class TestOptimizerParity:
    def _torch_reference(self, params_np, grads_np, kind, steps, lr, wd, momentum):
        import torch

        tparams = [torch.nn.Parameter(torch.tensor(p)) for p in params_np]
        if kind == "sgd":
            opt = torch.optim.SGD(
                tparams, lr=lr, momentum=momentum, weight_decay=wd
            )
        else:
            opt = torch.optim.Adam(
                [
                    {"params": [tparams[0]]},  # no wd ("other")
                    {"params": [tparams[1]], "weight_decay": wd},
                ],
                lr=lr,
            )
        for _ in range(steps):
            for p, g in zip(tparams, grads_np):
                p.grad = torch.tensor(g)
            opt.step()
            opt.zero_grad()
        return [p.detach().numpy() for p in tparams]

    def test_sgd_matches_torch(self, rng):
        p0 = rng.normal(size=(3, 3)).astype(np.float32)
        p1 = rng.normal(size=(5,)).astype(np.float32)
        g0 = rng.normal(size=(3, 3)).astype(np.float32)
        g1 = rng.normal(size=(5,)).astype(np.float32)
        params = {"a": jnp.asarray(p0), "b": jnp.asarray(p1)}
        grads = {"a": jnp.asarray(g0), "b": jnp.asarray(g1)}
        tx = make_optimizer(
            params,
            dataset="cifar10",
            lr=0.1,
            epochs=10,
            steps_per_epoch=1000,  # stay in epoch 0 → constant-lr segment
            momentum=0.9,
            weight_decay=1e-4,
        )
        opt_state = tx.init(params)
        import optax

        for _ in range(3):
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
        # torch cosine epoch-0 multiplier is 1.0 → plain lr
        ref = self._torch_reference(
            [p0, p1], [g0, g1], "sgd", 3, 0.1, 1e-4, 0.9
        )
        np.testing.assert_allclose(np.asarray(params["a"]), ref[0], atol=1e-5)
        np.testing.assert_allclose(np.asarray(params["b"]), ref[1], atol=1e-5)

    def test_adam_masked_wd_matches_torch(self, rng):
        # param "other" (1-D, not conv) gets NO decay; 4-D conv gets decay
        p_other = rng.normal(size=(7,)).astype(np.float32)
        p_conv = rng.normal(size=(3, 3, 2, 4)).astype(np.float32)
        g_other = rng.normal(size=(7,)).astype(np.float32)
        g_conv = rng.normal(size=(3, 3, 2, 4)).astype(np.float32)
        params = {"bn": {"scale": jnp.asarray(p_other)},
                  "conv1": {"float_weight": jnp.asarray(p_conv)}}
        grads = {"bn": {"scale": jnp.asarray(g_other)},
                 "conv1": {"float_weight": jnp.asarray(g_conv)}}
        tx = make_optimizer(
            params,
            dataset="imagenet",
            lr=1e-3,
            epochs=10,
            steps_per_epoch=1000,
            weight_decay=1e-4,
        )
        opt_state = tx.init(params)
        import optax

        for _ in range(4):
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
        ref = self._torch_reference(
            [p_other, p_conv], [g_other, g_conv], "adam", 4, 1e-3, 1e-4, 0.0
        )
        np.testing.assert_allclose(
            np.asarray(params["bn"]["scale"]), ref[0], atol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(params["conv1"]["float_weight"]), ref[1], atol=1e-6
        )

    def test_mask_selects_4d_or_conv_named(self, rng):
        params = {
            "conv1": {"weight": jnp.zeros((3, 3, 2, 4))},
            "layer1_0": {
                "conv2": {"float_weight": jnp.zeros((3, 3, 4, 4))},
                "bn1": {"scale": jnp.zeros((4,))},
            },
            "fc": {"kernel": jnp.zeros((8, 4)), "bias": jnp.zeros((4,))},
        }
        mask = conv_weight_mask(params)
        assert mask["conv1"]["weight"] is True
        assert mask["layer1_0"]["conv2"]["float_weight"] is True
        # 'conv' appears in the bn's parent path? No — bn under layer1_0
        assert mask["layer1_0"]["bn1"]["scale"] is False
        assert mask["fc"]["kernel"] is False
        assert mask["fc"]["bias"] is False


class TestTrainStep:
    def _setup(self, cfg=None, seed=0):
        rng = np.random.default_rng(seed)
        model = _tiny_model()
        x, y = _tiny_batch(rng)
        variables = model.init(jax.random.PRNGKey(seed), x, train=True)
        tx = make_optimizer(
            variables["params"],
            dataset="cifar10",
            lr=0.05,
            epochs=10,
            steps_per_epoch=100,
        )
        state = TrainState.create(variables, tx)
        if cfg is None:
            cfg = StepConfig()
        step = jax.jit(make_train_step(model, tx, cfg))
        return model, state, step, (x, y)

    def test_loss_decreases(self):
        _, state, step, batch = self._setup()
        tk = jnp.float32(1.0), jnp.float32(1.0)
        losses = []
        for _ in range(15):
            state, metrics = step(state, batch, tk, jnp.float32(0.0))
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0] * 0.95, losses[::3]
        assert np.isfinite(losses).all()

    def test_kurtosis_gate_and_term(self):
        rng = np.random.default_rng(0)
        model = _tiny_model()
        x, y = _tiny_batch(rng)
        variables = model.init(jax.random.PRNGKey(0), x, train=True)
        paths = conv_weight_paths(variables["params"])
        hooked = tuple(paths[1:])
        cfg = StepConfig(
            w_kurtosis=True,
            kurt_paths=hooked,
            kurt_targets=(1.8,) * len(hooked),
            kurtosis_mode="avg",
            w_lambda_kurtosis=1.0,
        )
        tx = make_optimizer(
            variables["params"], dataset="cifar10", lr=0.05,
            epochs=10, steps_per_epoch=100,
        )
        state = TrainState.create(variables, tx)
        step = jax.jit(make_train_step(model, tx, cfg))
        tk = jnp.float32(1.0), jnp.float32(1.0)
        _, m_off = step(state, (x, y), tk, jnp.float32(0.0))
        _, m_on = step(state, (x, y), tk, jnp.float32(1.0))
        assert float(m_off["loss_kurt"]) == 0.0
        assert float(m_on["loss_kurt"]) > 0.0
        assert float(m_on["loss"]) == pytest.approx(
            float(m_on["loss_ce"]) + float(m_on["loss_kurt"]), rel=1e-5
        )

    def test_metrics_counts(self):
        _, state, step, batch = self._setup()
        tk = jnp.float32(1.0), jnp.float32(1.0)
        _, metrics = step(state, batch, tk, jnp.float32(0.0))
        assert int(metrics["count"]) == 16
        assert 0 <= int(metrics["top1"]) <= int(metrics["top5"]) <= 16

    def test_epoch_mean_is_example_weighted(self):
        """VERDICT r3 #6 regression: the step emits loss_sum = loss x
        count so interval/epoch means are exact example-weighted means
        even when drain intervals are unequal."""
        from bdbnn_tpu.utils import DeviceMetrics, Mean

        _, state, step, batch = self._setup()
        tk = jnp.float32(1.0), jnp.float32(1.0)
        per_step = []  # (loss, count)
        devmet = DeviceMetrics()
        mean = Mean("Loss")
        n_steps = 7
        for i in range(n_steps):
            state, metrics = step(state, batch, tk, jnp.float32(0.0))
            assert float(metrics["loss_sum"]) == pytest.approx(
                float(metrics["loss"]) * int(metrics["count"]), rel=1e-6
            )
            per_step.append((float(metrics["loss"]), int(metrics["count"])))
            devmet.add(metrics)
            # unequal intervals: drain after steps 0, 4, 6
            if i in (0, 4, n_steps - 1):
                sums = devmet.drain()
                n = max(sums["count"], 1.0)
                mean.add(sums["loss_sum"] / n, n)
        exact = sum(l * c for l, c in per_step) / sum(c for _, c in per_step)
        assert mean.mean == pytest.approx(exact, rel=1e-6)


class TestDeviceNormalize:
    """StepConfig.input_norm: uint8 batches normalized on device must
    reproduce the host-normalized float path exactly (same math, same
    order: (x/255 - mean)/std in f32)."""

    def _setup(self, input_norm=None, seed=0):
        rng = np.random.default_rng(seed)
        model = _tiny_model()
        x_u8 = rng.integers(0, 256, size=(16, 8, 8, 3), dtype=np.uint8)
        y = rng.integers(0, 4, size=(16,))
        variables = model.init(
            jax.random.PRNGKey(seed), jnp.zeros((1, 8, 8, 3)), train=True
        )
        cfg = StepConfig(input_norm=input_norm)
        tx = make_optimizer(
            variables["params"], dataset="cifar10", lr=0.05,
            epochs=10, steps_per_epoch=100,
        )
        state = TrainState.create(variables, tx)
        step = jax.jit(make_train_step(model, tx, cfg))
        return state, step, x_u8, y

    def test_train_step_equivalent_to_host_normalize(self):
        from bdbnn_tpu.data import CIFAR_MEAN, CIFAR_STD, normalize

        norm = (tuple(map(float, CIFAR_MEAN)), tuple(map(float, CIFAR_STD)))
        state_d, step_d, x_u8, y = self._setup(input_norm=norm)
        state_h, step_h, _, _ = self._setup(input_norm=None)
        tk = (jnp.float32(1.0), jnp.float32(1.0))

        x_host = normalize(x_u8, CIFAR_MEAN, CIFAR_STD)
        for _ in range(3):
            state_d, m_d = step_d(state_d, (jnp.asarray(x_u8), jnp.asarray(y)),
                                  tk, jnp.float32(0.0))
            state_h, m_h = step_h(state_h, (jnp.asarray(x_host), jnp.asarray(y)),
                                  tk, jnp.float32(0.0))
        assert float(m_d["loss"]) == pytest.approx(
            float(m_h["loss"]), rel=1e-5
        )
        for a, b in zip(
            jax.tree_util.tree_leaves(state_d.params),
            jax.tree_util.tree_leaves(state_h.params),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            )

    def test_eval_step_equivalent(self):
        from bdbnn_tpu.data import CIFAR_MEAN, CIFAR_STD, normalize

        rng = np.random.default_rng(1)
        model = _tiny_model()
        x_u8 = rng.integers(0, 256, size=(8, 8, 8, 3), dtype=np.uint8)
        y = jnp.asarray(rng.integers(0, 4, size=(8,)))
        valid = jnp.ones((8,), jnp.float32)
        variables = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 8, 8, 3)), train=True
        )
        tx = make_optimizer(
            variables["params"], dataset="cifar10", lr=0.05,
            epochs=10, steps_per_epoch=100,
        )
        state = TrainState.create(variables, tx)
        norm = (tuple(map(float, CIFAR_MEAN)), tuple(map(float, CIFAR_STD)))
        ev_d = jax.jit(make_eval_step(model, input_norm=norm))
        ev_h = jax.jit(make_eval_step(model))
        m_d = ev_d(state, (jnp.asarray(x_u8), y, valid))
        m_h = ev_h(
            state,
            (jnp.asarray(normalize(x_u8, CIFAR_MEAN, CIFAR_STD)), y, valid),
        )
        assert float(m_d["loss_sum"]) == pytest.approx(
            float(m_h["loss_sum"]), rel=1e-5
        )
        assert int(m_d["top1"]) == int(m_h["top1"])


class TestFastForwardCounts:
    """VERDICT r3 #9 / ADVICE r2: counts inside dict-based optax states
    (e.g. inject_hyperparams) must fast-forward on torch .pth resume."""

    def test_namedtuple_and_dict_counts(self):
        from bdbnn_tpu.train.loop import _fast_forward_counts

        import optax

        # real dict-carrying optax state
        tx = optax.inject_hyperparams(optax.adamw)(learning_rate=1e-3)
        params = {"w": jnp.ones((3, 3))}
        st = tx.init(params)
        ff = _fast_forward_counts(st, 123)

        counts = []

        def collect(node):
            if "count" in getattr(node, "_fields", ()):
                counts.append(int(node.count))
            if isinstance(node, tuple):
                for c in node:
                    collect(c)
            elif isinstance(node, dict):
                for k, v in node.items():
                    if k == "count" and not isinstance(v, (dict, tuple)):
                        counts.append(int(v))
                    else:
                        collect(v)

        collect(ff)
        assert counts and all(c == 123 for c in counts), counts

        # synthetic pure-dict state (the ADVICE scenario verbatim)
        st2 = {"inner": {"count": jnp.int32(0), "mu": jnp.zeros(2)}}
        ff2 = _fast_forward_counts(st2, 77)
        assert int(ff2["inner"]["count"]) == 77
        assert float(ff2["inner"]["mu"][0]) == 0.0


class TestTSStep:
    def test_react_vs_full_loss_wiring(self):
        rng = np.random.default_rng(1)
        student = _tiny_model()
        teacher = BiResNet(
            stage_sizes=(1, 1), num_classes=4, width=8,
            stem="cifar", variant="float", act="identity",
        )
        x, y = _tiny_batch(rng)
        sv = student.init(jax.random.PRNGKey(0), x, train=True)
        tv = teacher.init(jax.random.PRNGKey(1), x, train=False)
        s_paths = conv_weight_paths(sv["params"])
        t_paths = conv_weight_paths(tv["params"])
        # pair all non-stem convs (name-aligned by construction)
        pairs = tuple(
            (sp, tp)
            for sp, tp in zip(s_paths[1:], t_paths[1:])
            if "downsample" not in module_path_str(sp)
        )
        tx = make_optimizer(
            sv["params"], dataset="cifar10", lr=0.01,
            epochs=10, steps_per_epoch=100,
        )
        tk = jnp.float32(1.0), jnp.float32(1.0)

        full_cfg = StepConfig(
            teacher_student=True, react=False, alpha=0.9, beta=2.0,
            w_lambda_ce=1.0, kd_pairs=pairs,
        )
        state = TrainState.create(sv, tx)
        step_full = jax.jit(make_ts_train_step(student, teacher, tx, full_cfg))
        _, m_full = step_full(state, tv, (x, y), tk, jnp.float32(0.0))
        assert float(m_full["loss_kl"]) != 0.0
        assert float(m_full["loss_ce"]) != 0.0
        assert float(m_full["loss"]) == pytest.approx(
            float(m_full["loss_kl"])
            + float(m_full["loss_kl_c"])
            + float(m_full["loss_ce"]),
            rel=1e-4,
        )

        # react mode: beta = 0, CE weight = 0 (train.py:605-609)
        react_cfg = StepConfig(
            teacher_student=True, react=True, alpha=0.9, beta=2.0,
            w_lambda_ce=1.0, kd_pairs=pairs,
        )
        state2 = TrainState.create(sv, tx)
        step_react = jax.jit(
            make_ts_train_step(student, teacher, tx, react_cfg)
        )
        _, m_react = step_react(state2, tv, (x, y), tk, jnp.float32(0.0))
        assert float(m_react["loss_kl"]) == 0.0
        assert float(m_react["loss_ce"]) == 0.0
        assert float(m_react["loss"]) == pytest.approx(
            float(m_react["loss_kl_c"]), rel=1e-5
        )

    def test_teacher_frozen(self):
        """Gradients must not flow into teacher variables (↔ the
        reference's requires_grad=False freeze, train.py:275-277)."""
        rng = np.random.default_rng(2)
        student = _tiny_model()
        teacher = BiResNet(
            stage_sizes=(1, 1), num_classes=4, width=8,
            stem="cifar", variant="float", act="identity",
        )
        x, y = _tiny_batch(rng)
        sv = student.init(jax.random.PRNGKey(0), x, train=True)
        tv = teacher.init(jax.random.PRNGKey(1), x, train=False)
        cfg = StepConfig(teacher_student=True, alpha=1.0, beta=0.0)

        def loss_via_teacher(tparams):
            t_logits = teacher.apply(
                {"params": tparams, "batch_stats": tv["batch_stats"]},
                x, train=False,
            )
            logits = student.apply(sv, x, train=False)
            from bdbnn_tpu.losses.kd import distribution_loss

            return distribution_loss(logits, t_logits)

        g = jax.grad(loss_via_teacher)(tv["params"])
        total = sum(float(jnp.abs(l).sum()) for l in jax.tree_util.tree_leaves(g))
        assert total == 0.0


class TestEvalStep:
    def _state(self, model, variables):
        tx = make_optimizer(
            variables["params"], dataset="cifar10", lr=0.1,
            epochs=1, steps_per_epoch=1,
        )
        return TrainState.create(variables, tx)

    def test_eval_matches_manual_ce(self):
        rng = np.random.default_rng(3)
        model = _tiny_model()
        x, y = _tiny_batch(rng)
        variables = model.init(jax.random.PRNGKey(0), x, train=False)
        state = self._state(model, variables)
        ev = jax.jit(make_eval_step(model))
        valid = jnp.ones((x.shape[0],), jnp.float32)
        metrics = ev(state, (x, y, valid))
        logits = model.apply(variables, x, train=False)
        n = x.shape[0]
        assert float(metrics["loss_sum"]) / n == pytest.approx(
            float(softmax_cross_entropy(logits, y)), rel=1e-6
        )
        assert int(metrics["count"]) == n

    def test_eval_mask_ignores_padding(self):
        """Padded rows must not affect any metric — the contract the
        fixed-shape multi-host eval relies on."""
        rng = np.random.default_rng(4)
        model = _tiny_model()
        x, y = _tiny_batch(rng)
        variables = model.init(jax.random.PRNGKey(0), x, train=False)
        state = self._state(model, variables)
        ev = jax.jit(make_eval_step(model))
        n_real = 10
        valid = jnp.asarray(
            np.arange(x.shape[0]) < n_real, jnp.float32
        )
        # garbage in the padded tail — results must not change
        x_pad = jnp.asarray(np.asarray(x).copy())
        x_pad = x_pad.at[n_real:].set(7.7)
        m_masked = ev(state, (x_pad, y, valid))
        m_ref = ev(
            state,
            (
                x[:n_real],
                y[:n_real],
                jnp.ones((n_real,), jnp.float32),
            ),
        )
        assert int(m_masked["count"]) == n_real
        assert float(m_masked["loss_sum"]) == pytest.approx(
            float(m_ref["loss_sum"]), rel=1e-5
        )
        assert int(m_masked["top1"]) == int(m_ref["top1"])
        assert int(m_masked["top5"]) == int(m_ref["top5"])


class TestOptPolicyOverride:
    """opt_policy overrides the reference's dataset->optimizer keying
    with the OTHER reference policy (train.py:316-336)."""

    def test_override_matches_other_datasets_policy(self):
        rng = np.random.default_rng(0)
        model = _tiny_model()
        x, _ = _tiny_batch(rng)
        variables = model.init(jax.random.PRNGKey(0), x, train=True)
        p = variables["params"]
        grads = jax.tree_util.tree_map(jnp.ones_like, p)

        def first_update(tx):
            st = tx.init(p)
            up, _ = tx.update(grads, st, p)
            return up

        adam_by_ds = make_optimizer(
            p, dataset="imagenet", lr=0.1, epochs=5, steps_per_epoch=3
        )
        adam_by_policy = make_optimizer(
            p, dataset="cifar10", lr=0.1, epochs=5, steps_per_epoch=3,
            policy="adam-linear",
        )
        a, b = first_update(adam_by_ds), first_update(adam_by_policy)
        for la, lb in zip(
            jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
        ):
            np.testing.assert_allclose(np.asarray(la), np.asarray(lb))

    def test_rejects_unknown_policy(self):
        rng = np.random.default_rng(0)
        model = _tiny_model()
        x, _ = _tiny_batch(rng)
        p = model.init(jax.random.PRNGKey(0), x, train=True)["params"]
        with pytest.raises(ValueError):
            make_optimizer(
                p, dataset="cifar10", lr=0.1, epochs=5, steps_per_epoch=3,
                policy="rmsprop",
            )
