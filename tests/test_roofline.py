"""Kernel-grade performance observatory (obs/roofline.py + friends).

Tier-1 coverage for the roofline stack, from pure math to the full
``perf`` pipeline:

- roofline math pins: intensity, the ridge boundary, the
  max(compute, memory) roof identity;
- ceilings resolution: exact / substring / cpu-fallback / --ceilings
  overrides;
- the shared byte hooks (nn/packed.py) the cost model, export and
  ``residency()`` all price bytes through;
- synthetic resnet8_tiny layer-table pins — every row of the static
  cost model checked against hand-computed shapes/FLOPs/bytes;
- the compiled-HLO op->scope join (obs/trace.py) and per-layer trace
  attribution over a synthetic trace (longest-needle, module filter,
  trailing-index stripping);
- the engine's per-bucket activation working set (serve/engine.py);
- ``run_perf`` end to end over the session's REAL exported artifact:
  ledger, verdict, BENCH artifact, compare round trips, and the
  doctored per-layer regression the compare gate exists to catch.
"""

import json
import os

import pytest

from bdbnn_tpu.nn.packed import (
    dense_weight_bytes,
    packed_activation_bytes,
    packed_weight_bytes,
    popcount_word_bytes,
)
from bdbnn_tpu.obs.roofline import (
    BENCH_ARTIFACT_NAME,
    CEILINGS,
    IMPL_REGIME,
    PERF_LEDGER_NAME,
    PERF_VERDICT_NAME,
    arithmetic_intensity,
    classify_bound,
    layer_regimes,
    model_layer_table,
    resolve_ceilings,
    ridge_intensity,
    roof_ms,
    static_table,
)
from bdbnn_tpu.obs.trace import (
    attribute_trace_layers,
    hlo_module_name,
    hlo_op_scopes,
)


class TestRooflineMath:
    CPU = resolve_ceilings("cpu")

    def test_arithmetic_intensity(self):
        assert arithmetic_intensity(200.0, 100.0) == 2.0
        # zero bytes never divides by zero (floor of 1 byte)
        assert arithmetic_intensity(5.0, 0.0) == 5.0

    def test_cpu_ridge(self):
        # cpu fallback row: 2e11 FLOP/s over 50 GB/s -> ridge 4.0
        assert self.CPU["matched"] == "cpu"
        assert ridge_intensity(self.CPU) == pytest.approx(4.0)
        assert self.CPU["ridge_intensity"] == 4.0

    def test_ridge_boundary_classification(self):
        # AT the ridge is compute-bound (>=), just under is memory
        assert classify_bound(4.0, self.CPU) == "compute"
        assert classify_bound(3.999, self.CPU) == "memory"
        assert classify_bound(400.0, self.CPU) == "compute"

    def test_roof_is_max_of_compute_and_memory_time(self):
        # compute-dominated: 2e11 flops over 1 byte -> exactly 1s
        assert roof_ms(2.0e11, 1.0, self.CPU) == pytest.approx(1000.0)
        # memory-dominated: 50e9 bytes with 1 flop -> exactly 1s
        assert roof_ms(1.0, 50.0e9, self.CPU) == pytest.approx(1000.0)
        # the max identity, checked on a mixed point
        f, b = 1.0e9, 1.0e9
        t_c = f / self.CPU["peak_flops"] * 1e3
        t_m = b / (self.CPU["hbm_gbs"] * 1e9) * 1e3
        assert roof_ms(f, b, self.CPU) == pytest.approx(max(t_c, t_m))
        assert roof_ms(f, b, self.CPU) == pytest.approx(20.0)

    def test_impl_regime_covers_every_impl(self):
        assert set(IMPL_REGIME) == {"dense", "unpack", "popcount"}
        assert set(IMPL_REGIME.values()) == {
            "dense", "packed_weight", "packed_act",
        }


class TestCeilingsResolution:
    def test_exact_match(self):
        row = resolve_ceilings("TPU v5 lite")
        assert row["matched"] == "TPU v5 lite"
        assert row["peak_flops"] == pytest.approx(197e12)
        assert row["hbm_gbs"] == pytest.approx(819.0)

    def test_substring_match(self):
        row = resolve_ceilings("TPU v4 (podslice)")
        assert row["matched"] == "TPU v4"
        assert row["peak_flops"] == CEILINGS["TPU v4"]["peak_flops"]

    def test_unknown_kind_falls_back_to_cpu(self):
        row = resolve_ceilings("Radeon 9800 Pro")
        assert row["matched"] == "cpu"
        assert row["device_kind"] == "Radeon 9800 Pro"

    def test_override_single_row(self):
        row = resolve_ceilings(
            "cpu", {"peak_flops": 1.0e12, "hbm_gbs": 100.0}
        )
        assert row["source"] == "--ceilings"
        assert row["ridge_intensity"] == pytest.approx(10.0)

    def test_override_table_merge(self, tmp_path):
        p = tmp_path / "ceil.json"
        p.write_text(json.dumps(
            {"TPU v99": {"peak_flops": 9e14, "hbm_gbs": 9000.0}}
        ))
        row = resolve_ceilings("TPU v99", str(p))
        assert row["matched"] == "TPU v99"
        assert row["peak_flops"] == pytest.approx(9e14)
        # merged, not replaced: built-in rows still resolve
        assert resolve_ceilings("TPU v4", str(p))["matched"] == "TPU v4"


class TestByteHooks:
    """nn/packed.py's pure-int byte hooks — the ONE place the cost
    model, the export compression report and ``residency()`` price
    packing from."""

    def test_dense_weight_bytes(self):
        assert dense_weight_bytes((3, 3, 8, 8)) == 3 * 3 * 8 * 8 * 4

    def test_packed_weight_bytes_is_packbits_plus_alpha(self):
        # (576 signs + 7) // 8 = 72 bytes + 8 f32 alphas = 104
        assert packed_weight_bytes((3, 3, 8, 8)) == 104

    def test_packed_activation_bytes_ceil_div(self):
        assert packed_activation_bytes(8) == 1
        assert packed_activation_bytes(9) == 2

    def test_popcount_word_bytes(self):
        # 72 signs -> 3 u32 words, x2 operands, x4 bytes
        assert popcount_word_bytes(3, 3, 8) == 24

    def test_big_tensor_compression_approaches_32x(self):
        shape = (3, 3, 256, 256)
        ratio = dense_weight_bytes(shape) / packed_weight_bytes(shape)
        assert ratio > 7.0  # alpha overhead keeps it under 32


class TestLayerTable:
    """The static cost model over resnet8_tiny, pinned row by row
    against hand-computed shapes (cifar10 32x32, batch 8)."""

    @pytest.fixture(scope="class")
    def rows(self):
        return model_layer_table(
            "resnet8_tiny", "cifar10", 8, image_size=32
        )

    def _by_name(self, rows):
        return {r["name"]: r for r in rows}

    def test_exactly_the_seven_layers(self, rows):
        assert {r["name"] for r in rows} == {
            "conv1",
            "layer1_0.conv1", "layer1_0.conv2",
            "layer2_0.conv1", "layer2_0.conv2",
            "layer2_0.downsample_conv",
            "fc",
        }
        assert len(rows) == 7  # no duplicate recordings

    def test_conv1_is_float_and_pinned(self, rows):
        r = self._by_name(rows)["conv1"]
        assert r["kind"] == "float"
        assert r["kernel"] == [3, 3]
        assert r["in_shape"] == [8, 32, 32, 3]
        assert r["out_shape"] == [8, 32, 32, 8]
        # 2 * out elements * kernel volume * c_in
        assert r["flops"] == 2 * (8 * 32 * 32 * 8) * 9 * 3
        # float conv: packing does not apply
        assert r["weight_packed_bytes"] == r["weight_dense_bytes"]
        assert r["act_in_packed_bytes"] == r["act_in_bytes"]
        assert r["popcount_word_bytes"] is None
        assert r["act_in_bytes"] == 8 * 32 * 32 * 3 * 4

    def test_binary_conv_pinned(self, rows):
        r = self._by_name(rows)["layer1_0.conv1"]
        assert r["kind"] == "binary"
        assert r["scope"] == "layer1_0/conv1"
        assert r["weight_dense_bytes"] == 2304
        assert r["weight_packed_bytes"] == 104
        n_in = 8 * 32 * 32 * 8
        assert r["act_in_bytes"] == n_in * 4
        assert r["act_in_packed_bytes"] == (n_in + 7) // 8
        # out elems / c_out spatial positions x 24 bytes of words
        assert r["popcount_word_bytes"] == (n_in // 8) * 24

    def test_strided_downsample_block(self, rows):
        r = self._by_name(rows)["layer2_0.conv1"]
        assert r["strides"] == [2, 2]
        assert r["out_shape"] == [8, 16, 16, 16]
        assert r["flops"] == 2 * (8 * 16 * 16 * 16) * 9 * 8
        d = self._by_name(rows)["layer2_0.downsample_conv"]
        assert d["kind"] == "binary"
        assert d["kernel"] == [1, 1]

    def test_fc_row_pinned(self, rows):
        r = self._by_name(rows)["fc"]
        assert r["kind"] == "dense"
        assert r["flops"] == 2 * (8 * 10) * 16
        assert r["weight_packed_bytes"] == r["weight_dense_bytes"] == (
            16 * 10 * 4
        )

    def test_batch_scales_activations_not_weights(self, rows):
        rows1 = model_layer_table(
            "resnet8_tiny", "cifar10", 1, image_size=32
        )
        a8 = self._by_name(rows)["layer1_0.conv1"]
        a1 = self._by_name(rows1)["layer1_0.conv1"]
        assert a8["act_in_bytes"] == 8 * a1["act_in_bytes"]
        assert a8["flops"] == 8 * a1["flops"]
        assert a8["weight_packed_bytes"] == a1["weight_packed_bytes"]

    def test_bfloat16_halves_activation_bytes(self, rows):
        rows_bf = model_layer_table(
            "resnet8_tiny", "cifar10", 8, image_size=32,
            dtype="bfloat16",
        )
        f32 = self._by_name(rows)["conv1"]
        bf = self._by_name(rows_bf)["conv1"]
        assert bf["act_in_bytes"] * 2 == f32["act_in_bytes"]
        # weights stay priced f32 (the artifact stores f32 + packbits)
        assert bf["weight_dense_bytes"] == f32["weight_dense_bytes"]

    def test_regimes_monotone_for_binary_identical_for_float(self, rows):
        cpu = resolve_ceilings("cpu")
        by = self._by_name(rows)
        binary = layer_regimes(by["layer1_0.conv1"], cpu)
        assert binary["dense"]["bytes"] > binary["packed_weight"]["bytes"]
        assert (
            binary["packed_weight"]["bytes"]
            > binary["packed_act"]["bytes"]
        )
        # fewer bytes -> higher intensity -> no worse roof
        assert (
            binary["packed_act"]["intensity"]
            > binary["dense"]["intensity"]
        )
        assert (
            binary["packed_act"]["roof_ms"]
            <= binary["dense"]["roof_ms"]
        )
        flt = layer_regimes(by["conv1"], cpu)
        assert flt["dense"] == flt["packed_weight"] == flt["packed_act"]

    def test_static_table_attaches_regimes(self, rows):
        cpu = resolve_ceilings("cpu")
        table = static_table(rows, cpu)
        assert len(table) == len(rows)
        for r in table:
            for regime in ("dense", "packed_weight", "packed_act"):
                cell = r["regimes"][regime]
                assert cell["roof_ms"] > 0
                assert cell["bound"] in ("memory", "compute")


_HLO = """\
HloModule jit__apply, is_scheduled=true

ENTRY %main.42 {
  %convolution.12 = f32[8,32,32,8]{3,2,1,0} convolution(%p0, %p1), window={size=3x3}, metadata={op_name="jit(_apply)/jit(main)/BiResNet/conv1/conv_general_dilated" source_file="a.py" source_line=9}
  %convolution.19 = f32[8,32,32,8]{3,2,1,0} convolution(%a, %b), metadata={op_name="jit(_apply)/jit(main)/BiResNet/layer1_0/conv1/conv_general_dilated"}
  ROOT %dot.3 = f32[8,10]{1,0} dot(%c, %d), metadata={op_name="jit(_apply)/jit(main)/BiResNet/fc/dot_general"}
}
"""


def _op(name, dur_us, module="jit__apply", **extra_args):
    """A CPU-backend profiler op event: empty ``tf_op``, the
    instruction name in ``hlo_op`` — the shape the HLO join exists
    for."""
    args = {"hlo_op": name, "hlo_module": module}
    args.update(extra_args)
    return {
        "ph": "X", "name": name, "pid": 7, "tid": 1,
        "dur": dur_us, "args": args,
    }


class TestHloJoin:
    def test_hlo_op_scopes_parse(self):
        scopes = hlo_op_scopes(_HLO)
        assert len(scopes) == 3
        assert scopes["convolution.12"].endswith(
            "conv1/conv_general_dilated"
        )
        # ROOT-prefixed instructions parse too
        assert scopes["dot.3"].endswith("fc/dot_general")
        assert hlo_module_name(_HLO) == "jit__apply"
        assert hlo_op_scopes("") == {}
        assert hlo_module_name("") is None

    def test_synthetic_attribution(self):
        layers = {
            "conv1": "conv1",
            "layer1_0.conv1": "layer1_0/conv1",
            "fc": "fc",
        }
        scopes = hlo_op_scopes(_HLO)
        events = [
            _op("convolution.12", 1000),
            _op("convolution.19", 2000),
            _op("dot.3", 500),
            # no scope anywhere -> unattributed
            _op("transpose.5", 300),
            # another executable sharing the window -> dropped
            _op("convolution.88", 9000, module="jit_other"),
        ]
        att = attribute_trace_layers(
            events, 2, layers=layers, op_scopes=scopes,
            module="jit__apply",
        )
        assert att["n_steps"] == 2
        # longest needle wins: the layer1_0/conv1 op must NOT fall
        # into the bare "conv1" bucket
        assert att["layers"] == {
            "conv1": 0.5, "layer1_0.conv1": 1.0, "fc": 0.25,
        }
        assert att["unattributed"] == pytest.approx(0.15)
        assert att["total_ms"] == pytest.approx(1.9)

    def test_trailing_index_stripping_in_scope_segments(self):
        # XLA appends .N to repeated scope segments; the needle still
        # matches after the trailing [.digits] run is stripped
        att = attribute_trace_layers(
            [_op("dot.7", 800)],
            1,
            layers={"fc": "fc"},
            op_scopes={"dot.7": "jit(main)/Net/fc.3/dot_general"},
        )
        assert att["layers"] == {"fc": 0.8}
        # the strip eats the whole trailing digit run, so a stem can
        # never swallow an indexed sibling of a digit-suffixed layer:
        # "conv1.2" strips to "conv", which "conv1" does NOT match
        att = attribute_trace_layers(
            [_op("convolution.7", 800)],
            1,
            layers={"conv1": "conv1"},
            op_scopes={
                "convolution.7": "jit(main)/Net/conv1.2/conv",
            },
        )
        assert att["layers"] == {}
        assert att["unattributed"] == pytest.approx(0.8)

    def test_tpu_style_fallback_without_op_scopes(self):
        # no hlo join given: the event's own "/"-bearing string args
        # (tf_op on TPU) still attribute
        ev = _op("fusion.1", 600, tf_op="BiResNet/layer1_0/conv1/fused")
        att = attribute_trace_layers(
            [ev], 1, layers={"layer1_0.conv1": "layer1_0/conv1"},
        )
        assert att["layers"] == {"layer1_0.conv1": 0.6}


class TestEngineActivationWorkingSet:
    """serve/engine.py residency(): the per-bucket activation
    working-set estimate rides the same layer table."""

    def test_residency_reports_activations(self, exported_artifact):
        from bdbnn_tpu.serve.engine import InferenceEngine

        art_dir, _ = exported_artifact
        eng = InferenceEngine(art_dir, buckets=(1, 2))
        res = eng.residency()
        acts = res["activations"]
        assert set(acts) == {"1", "2"}
        one, two = acts["1"], acts["2"]
        assert one["per_conv"]["conv1"]["in"] == 1 * 32 * 32 * 3 * 4
        # doubling the bucket doubles every activation byte
        assert two["bytes_in"] == 2 * one["bytes_in"]
        assert two["bytes_out"] == 2 * one["bytes_out"]
        assert one["bytes_in"] == sum(
            v["in"] for v in one["per_conv"].values()
        )
        # the weight-residency contract is unchanged
        assert res["packed_equiv_bytes"] < res["dense_equiv_bytes"]


@pytest.mark.usefixtures("exported_artifact")
class TestPerfEndToEnd:
    """run_perf over the session's REAL trained+exported resnet8_tiny
    artifact on the CPU mesh — the PR's acceptance path: all three
    impls, per-layer attribution joined from the compiled HLO,
    reconciliation against the measured wall, and every persisted
    artifact (verdict, ledger, BENCH) closing the loop through
    ``compare``."""

    # ONE measured bucket: each (impl, bucket) cell costs a fresh
    # engine compile on the 1-core CI host, and bucket resolution is
    # already pinned statically (TestLayerTable batch scaling) and at
    # b1 through the CLI smoke (test_cli.py::TestPerfCliSmoke)
    BUCKETS = (8,)
    IMPLS = ("dense", "unpack", "popcount")

    @pytest.fixture(scope="class")
    def perf_run(self, exported_artifact, tmp_path_factory):
        from bdbnn_tpu.configs.config import PerfConfig
        from bdbnn_tpu.obs.roofline import run_perf

        art_dir, _ = exported_artifact
        log = str(tmp_path_factory.mktemp("perf") / "log")
        cfg = PerfConfig(
            artifact=art_dir,
            log_path=log,
            buckets=self.BUCKETS,
            impls=self.IMPLS,
            iters=3,
        ).validate()
        out = run_perf(cfg)
        return log, out["run_dir"], out["verdict"]

    def test_covers_every_impl_and_bucket(self, perf_run):
        _, _, v = perf_run
        assert v["perf_verdict"] == 1
        assert set(v["measured"]) == set(self.IMPLS)
        assert v["skipped"] == []  # f32 artifact: popcount runs
        for impl in self.IMPLS:
            for b in self.BUCKETS:
                cell = v["measured"][impl][str(b)]
                assert cell["traced"] is True
                assert cell["wall_ms"] > 0
                assert cell["layers"], (impl, b)

    def test_per_layer_attribution_and_rooflines(self, perf_run):
        _, _, v = perf_run
        # 7 layers x 1 bucket x 3 impls
        assert len(v["perf_layers"]) == 21
        cell = v["measured"]["unpack"]["8"]["layers"]
        for name in ("conv1", "layer1_0.conv1", "fc"):
            lay = cell[name]
            assert lay["ms"] > 0
            assert lay["roof_ms"] > 0
            assert lay["efficiency"] == pytest.approx(
                round(lay["roof_ms"] / lay["ms"], 4), abs=1e-4
            )
            assert lay["bound"] in ("memory", "compute")

    def test_reconciliation_within_tolerance(self, perf_run):
        _, _, v = perf_run
        big = str(max(self.BUCKETS))
        for impl in self.IMPLS:
            for b in self.BUCKETS:
                recon = v["measured"][impl][str(b)]["reconciliation"]
                assert recon is not None, (impl, b)
                assert recon["attributed_ms"] <= (
                    recon["device_total_ms"] + 1e-6
                )
                assert recon["abs_err_pct"] >= 0
            # small buckets are dispatch-overhead noisy on a shared
            # host; the gate is pinned where the work amortizes it
            assert v["measured"][impl][big]["reconciliation"]["ok"] is (
                True
            ), impl

    def test_summary_aggregates(self, perf_run):
        _, _, v = perf_run
        s = v["summary"]
        assert s["bucket"] == max(self.BUCKETS)
        assert s["step_ms_best"] > 0
        assert s["step_ms_dense"] > 0
        assert 0 < s["attributed_share"] <= 1
        assert s["efficiency_mean"] > 0

    def test_run_dir_artifacts_on_disk(self, perf_run):
        log, run_dir, v = perf_run
        assert os.path.isfile(os.path.join(run_dir, PERF_VERDICT_NAME))
        assert os.path.isfile(os.path.join(run_dir, BENCH_ARTIFACT_NAME))
        assert os.path.isfile(os.path.join(run_dir, "manifest.json"))
        with open(os.path.join(run_dir, PERF_VERDICT_NAME)) as f:
            on_disk = json.load(f)
        assert on_disk["perf_layers"] == v["perf_layers"]

    def test_ledger_line_is_strict_json(self, perf_run):
        log, run_dir, v = perf_run
        with open(os.path.join(log, PERF_LEDGER_NAME)) as f:
            lines = [l for l in f if l.strip()]
        assert len(lines) == 1
        rec = json.loads(
            lines[0],
            parse_constant=lambda s: pytest.fail(f"bare {s} in ledger"),
        )
        assert rec["schema"] == 1
        assert rec["run_dir"] == run_dir
        assert rec["arch"] == "resnet8_tiny"
        assert rec["perf_layers"] == v["perf_layers"]
        assert rec["summary"]["step_ms_best"] == (
            v["summary"]["step_ms_best"]
        )

    def test_events_trail(self, perf_run):
        from bdbnn_tpu.obs.events import read_events

        _, run_dir, _ = perf_run
        perf = [
            e for e in read_events(run_dir) if e.get("kind") == "perf"
        ]
        phases = [e.get("phase") for e in perf]
        assert phases[0] == "start"
        assert phases[-1] == "verdict"
        assert phases.count("bucket") == len(self.IMPLS) * len(
            self.BUCKETS
        )

    def test_watch_and_summarize_render_perf(self, perf_run):
        from bdbnn_tpu.obs.summarize import summarize_run
        from bdbnn_tpu.obs.watch import watch_run

        _, run_dir, _ = perf_run
        text, summary = summarize_run(run_dir)
        assert "perf observatory:" in text
        assert summary["perf"]["verdict"]["summary"]["step_ms_best"] > 0
        # the verdict event terminates the tail (no --once needed)
        out = []
        assert watch_run(run_dir, interval=0.05, out=out.append) == 0
        assert any("VERDICT: best" in s for s in out)

    def test_bench_artifact_round_trips_through_compare(self, perf_run):
        from bdbnn_tpu.obs.compare import extract_run

        _, run_dir, v = perf_run
        rec = extract_run(os.path.join(run_dir, BENCH_ARTIFACT_NAME))
        assert rec["format"] == "bench_artifact"
        assert rec["metrics"]["jit_step_ms"] == (
            v["summary"]["step_ms_best"]
        )
        assert rec["metrics"]["img_per_s"] > 0

    def test_compare_run_dir_and_verdict_formats(self, perf_run):
        from bdbnn_tpu.obs.compare import compare_runs, extract_run

        _, run_dir, _ = perf_run
        rec = extract_run(run_dir)
        assert rec["format"] == "perf_run_dir"
        assert rec["provenance"]["recipe"]["arch"] == "resnet8_tiny"
        vrec = extract_run(os.path.join(run_dir, PERF_VERDICT_NAME))
        assert vrec["format"] == "perf_verdict"
        # the perf metric surface is identical whichever door you
        # enter through (the run dir additionally scans alert events)
        perf_keys = [k for k in rec["metrics"] if k.startswith("perf_")]
        assert perf_keys
        for k in perf_keys:
            assert vrec["metrics"][k] == rec["metrics"][k], k
        out = compare_runs([run_dir, run_dir])
        assert out["verdict"] == "pass"
        per_layer_rows = [
            m for m in out["comparisons"][0]["metrics"]
            if m["metric"].startswith("perf_ms[")
        ]
        assert len(per_layer_rows) == 21

    def test_doctored_per_layer_regression_fires(
        self, perf_run, tmp_path
    ):
        """THE gate this PR exists for: one layer 2x slower while
        every aggregate is held byte-identical -> regression (exit 3
        at the CLI), and the aggregates all still judge ok."""
        from bdbnn_tpu.obs.compare import compare_runs

        _, run_dir, _ = perf_run
        base_path = os.path.join(run_dir, PERF_VERDICT_NAME)
        with open(base_path) as f:
            doctored = json.load(f)
        key = sorted(doctored["perf_layers"])[0]
        doctored["perf_layers"][key] *= 2.0
        cand = tmp_path / PERF_VERDICT_NAME
        cand.write_text(json.dumps(doctored))
        out = compare_runs([base_path, str(cand)])
        assert out["verdict"] == "regression"
        rows = {
            m["metric"]: m["verdict"]
            for m in out["comparisons"][0]["metrics"]
        }
        assert rows[f"perf_ms[{key}]"] == "regression"
        for agg in (
            "perf_step_ms_best", "perf_step_ms_dense",
            "perf_efficiency_mean", "perf_attributed_share",
        ):
            assert rows[agg] == "ok"

    def test_tol_rel_gates_the_delta(self, perf_run, tmp_path):
        from bdbnn_tpu.obs.compare import compare_runs

        _, run_dir, _ = perf_run
        base_path = os.path.join(run_dir, PERF_VERDICT_NAME)
        with open(base_path) as f:
            doctored = json.load(f)
        key = sorted(doctored["perf_layers"])[0]
        doctored["perf_layers"][key] *= 1.05  # +5%
        cand = tmp_path / "v.json"
        cand.write_text(json.dumps(doctored))
        # +5% passes the default 10% gate, fails a 1% gate
        assert compare_runs(
            [base_path, str(cand)]
        )["verdict"] == "pass"
        assert compare_runs(
            [base_path, str(cand)], tol_rel=0.01
        )["verdict"] == "regression"


class TestStaticOnly:
    """--static-only: the cost model with no engines, no compiles —
    runs anywhere, including hosts with no artifacts' arch deps."""

    def test_static_only_run(self, exported_artifact, tmp_path):
        from bdbnn_tpu.configs.config import PerfConfig
        from bdbnn_tpu.obs.roofline import render_perf, run_perf

        art_dir, _ = exported_artifact
        cfg = PerfConfig(
            artifact=art_dir,
            log_path=str(tmp_path / "log"),
            buckets=(4,),
            static_only=True,
        ).validate()
        out = run_perf(cfg)
        v = out["verdict"]
        assert v["measured"] == {}
        assert v["perf_layers"] == {}
        assert len(v["static"]["4"]) == 7
        assert v["summary"]["step_ms_best"] is None
        text = render_perf(v)
        assert "resnet8_tiny" in text
        assert "bound classes" in text
        # the ledger records static runs too
        assert os.path.isfile(
            os.path.join(cfg.log_path, PERF_LEDGER_NAME)
        )
