"""Kurtosis regularizer vs a torch oracle reproducing reference
``kurtosis.py`` semantics (incl. the Bessel-corrected std trap,
SURVEY.md Appendix B #10)."""

import jax.numpy as jnp
import numpy as np
import pytest
import torch

from bdbnn_tpu.losses.kurtosis import (
    DIFFKURT_TARGETS_CIFAR,
    DIFFKURT_TARGETS_IMAGENET,
    DIFFKURT_TARGETS_TS,
    kurtosis,
    kurtosis_loss,
    kurtosis_regularization,
    l2_regularization,
    resolve_targets,
    weight_to_pm1_regularization,
)


def torch_kurtosis(w):
    w = torch.tensor(w)
    mean = torch.mean(w)
    std = torch.std(w)  # Bessel-corrected, as reference kurtosis.py:25
    return torch.mean(((w - mean) / std) ** 4).item()


def test_kurtosis_matches_torch_oracle(rng):
    for shape in [(64,), (3, 3, 16, 32), (7, 11)]:
        w = rng.normal(size=shape).astype(np.float32)
        got = float(kurtosis(jnp.asarray(w)))
        want = torch_kurtosis(w)
        np.testing.assert_allclose(got, want, rtol=1e-5)


def test_kurtosis_loss_squared_error(rng):
    w = rng.normal(size=(128,)).astype(np.float32)
    got = float(kurtosis_loss(jnp.asarray(w), 1.8))
    want = (torch_kurtosis(w) - 1.8) ** 2
    np.testing.assert_allclose(got, want, rtol=1e-4)


def test_cross_layer_reduction_modes(rng):
    ws = [rng.normal(size=(32,)).astype(np.float32) for _ in range(3)]
    targets = [1.8, 1.4, 1.2]
    per_layer = np.array(
        [(torch_kurtosis(w) - t) ** 2 for w, t in zip(ws, targets)]
    )
    jws = [jnp.asarray(w) for w in ws]
    np.testing.assert_allclose(
        float(kurtosis_regularization(jws, targets, "sum")),
        per_layer.sum(),
        rtol=1e-4,
    )
    np.testing.assert_allclose(
        float(kurtosis_regularization(jws, targets, "avg")),
        per_layer.mean(),
        rtol=1e-4,
    )
    np.testing.assert_allclose(
        float(kurtosis_regularization(jws, targets, "max")),
        per_layer.max(),
        rtol=1e-4,
    )


def test_l2_and_pm1_regularizers(rng):
    ws = [rng.normal(size=(4, 5)).astype(np.float32) for _ in range(2)]
    jws = [jnp.asarray(w) for w in ws]
    np.testing.assert_allclose(
        float(l2_regularization(jws)),
        sum((w**2).sum() for w in ws),
        rtol=1e-5,
    )
    want = sum(
        torch.norm(torch.abs(torch.tensor(w)) - 1, p=2).item() for w in ws
    )
    np.testing.assert_allclose(
        float(weight_to_pm1_regularization(jws)), want, rtol=1e-5
    )


def test_diffkurt_tables_have_19_entries():
    # 19 binarized convs in the ResNet-18-shaped flagship (train.py:467-475)
    for t in (
        DIFFKURT_TARGETS_IMAGENET,
        DIFFKURT_TARGETS_CIFAR,
        DIFFKURT_TARGETS_TS,
    ):
        assert len(t) == 19


def test_resolve_targets():
    assert resolve_targets(5, scalar_target=1.8) == (1.8,) * 5
    assert (
        resolve_targets(19, diffkurt=True, dataset="imagenet")
        == DIFFKURT_TARGETS_IMAGENET
    )
    assert (
        resolve_targets(19, diffkurt=True, dataset="cifar10")
        == DIFFKURT_TARGETS_CIFAR
    )
    assert (
        resolve_targets(19, diffkurt=True, teacher_student=True)
        == DIFFKURT_TARGETS_TS
    )
    with pytest.raises(ValueError):
        resolve_targets(7, diffkurt=True)


def test_kurtosis_robust_to_mean_offset(rng):
    """Offset-robustness pin: blocks regressing kurtosis() to the
    rejected single-pass raw-moment form, which catastrophically
    cancels in f32 once |mean|/std >~ 40 (measured kurt -131 vs true
    3.05 at mean -8, std 0.05). The shipped two-pass centered form
    must stay exact for any offset."""
    for offset in (0.0, 0.5, -2.0):
        w = (rng.normal(size=(3, 3, 32, 32)) * 0.05 + offset).astype(
            np.float32
        )
        wt = torch.tensor(w.reshape(-1), dtype=torch.float64)
        z = (wt - wt.mean()) / wt.std()  # torch std = Bessel ddof=1
        want = float((z**4).mean())
        got = float(kurtosis(jnp.asarray(w)))
        assert abs(got - want) < 1e-3 * max(1.0, abs(want)), (
            offset, got, want
        )
