"""Unified telemetry tests (obs/): manifest round-trip, event-channel
contents of a real synthetic fit(), the non-finite fail-fast policy,
the summarize report engine, and the no-extra-syncs invariant (drain
count at ``print_freq`` granularity is UNCHANGED by telemetry — the
whole design rides the existing DeviceMetrics cadence)."""

import glob
import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from bdbnn_tpu.configs.config import RunConfig
from bdbnn_tpu.obs import (
    EventWriter,
    RunManifest,
    config_hash,
    read_events,
    read_manifest,
    summarize_run,
    write_manifest,
)
from bdbnn_tpu.obs.probes import NonFiniteLossError, drain_probe_report
from bdbnn_tpu.train.loop import fit

# the shared fit: 256 examples / batch 64 = 4 steps, print_freq 2
STEPS = 4
PRINT_FREQ = 2


def _cfg(tmp_path, **kw):
    base = dict(
        dataset="cifar10",
        synthetic=True,
        synthetic_train_size=256,
        synthetic_val_size=64,
        arch="resnet20",
        epochs=1,
        batch_size=64,
        lr=0.05,
        print_freq=PRINT_FREQ,
        log_path=str(tmp_path / "log"),
        seed=0,
        workers=2,
    )
    base.update(kw)
    return RunConfig(**base)


def _find_run_dir(root):
    hits = glob.glob(os.path.join(str(root), "**", "events.jsonl"),
                     recursive=True)
    assert hits, f"no events.jsonl under {root}"
    return os.path.dirname(sorted(hits)[-1])


@pytest.fixture(scope="module")
def telemetry_run(tmp_path_factory):
    """ONE 1-epoch synthetic fit, with DeviceMetrics.drain instrumented
    to count real host syncs, shared by every assertion below."""
    from bdbnn_tpu.utils.meters import DeviceMetrics

    tmp = tmp_path_factory.mktemp("obsrun")
    calls = {"drain": 0}
    orig = DeviceMetrics.drain

    def counted(self):
        calls["drain"] += 1
        return orig(self)

    DeviceMetrics.drain = counted
    try:
        res = fit(_cfg(tmp))
    finally:
        DeviceMetrics.drain = orig
    run_dir = _find_run_dir(tmp)
    return {"res": res, "run_dir": run_dir, "drains": calls["drain"]}


class TestManifest:
    def test_write_read_roundtrip(self, tmp_path):
        cfg = RunConfig(synthetic=True, epochs=3)
        written = write_manifest(str(tmp_path), cfg)
        loaded = read_manifest(str(tmp_path))
        assert loaded == written
        man = RunManifest.from_dict(loaded)
        assert man.config_hash == written["config_hash"]
        assert man.schema == 1
        # provenance the summarize report keys on
        for key in ("jax_version", "jaxlib_version", "backend",
                    "device_count", "process_count", "config"):
            assert loaded[key] is not None
        assert loaded["config"]["epochs"] == 3

    def test_missing_manifest_is_none(self, tmp_path):
        assert read_manifest(str(tmp_path)) is None

    def test_config_hash_stable_and_sensitive(self):
        a = RunConfig(lr=0.1)
        b = RunConfig(lr=0.1)
        c = RunConfig(lr=0.2)
        assert config_hash(a) == config_hash(b)
        assert config_hash(a) != config_hash(c)


class TestFitTelemetry:
    def test_files_written(self, telemetry_run):
        run_dir = telemetry_run["run_dir"]
        assert os.path.exists(os.path.join(run_dir, "manifest.json"))
        assert os.path.exists(os.path.join(run_dir, "events.jsonl"))
        man = read_manifest(run_dir)
        start = read_events(run_dir, "run_start")[0]
        assert start["config_hash"] == man["config_hash"]
        assert start["steps_per_epoch"] == STEPS

    def test_event_kinds(self, telemetry_run):
        kinds = {e["kind"] for e in read_events(telemetry_run["run_dir"])}
        assert {"run_start", "compile", "train_interval", "epoch",
                "eval", "run_end"} <= kinds

    def test_step_phase_timing_fields(self, telemetry_run):
        run_dir = telemetry_run["run_dir"]
        intervals = read_events(run_dir, "train_interval")
        assert intervals
        for ev in intervals:
            for key in ("data_wait_s", "dispatch_s", "drain_s",
                        "interval_s", "data_wait_share", "steps",
                        "loss", "grad_norm"):
                assert key in ev, f"{key} missing from train_interval"
            assert ev["data_wait_s"] >= 0 and ev["dispatch_s"] >= 0
        compile_ev = read_events(run_dir, "compile")[0]
        # first-step trace+compile is the big host block; sub-second
        # would mean we timed a cached dispatch instead
        assert compile_ev["seconds"] > 0.5
        # compile is backed OUT of the first interval's phase wall —
        # phase shares describe steady-state training, not compilation
        assert intervals[0]["interval_s"] < compile_ev["seconds"]

    def test_probe_fields(self, telemetry_run):
        intervals = read_events(telemetry_run["run_dir"], "train_interval")
        for ev in intervals:
            assert ev.get("flip_rate") and ev.get("kurtosis")
            for layer, rate in ev["flip_rate"].items():
                assert 0.0 <= rate <= 1.0, (layer, rate)
            for layer, k in ev["kurtosis"].items():
                assert np.isfinite(k) and k > 0.0, (layer, k)
        # the probed set is the non-stem convs of resnet20 (no kurtosis
        # hooks in this run -> the "all" convention)
        assert len(intervals[0]["flip_rate"]) == 20
        # per-epoch probe scalars landed too (summarize's trajectory)
        with open(os.path.join(telemetry_run["run_dir"],
                               "scalars.jsonl")) as f:
            tags = {json.loads(l)["tag"] for l in f if l.strip()}
        assert any(t.startswith("Probe flip ") for t in tags)
        assert any(t.startswith("Probe kurt ") for t in tags)

    def test_no_extra_host_syncs(self, telemetry_run):
        """THE invariant: telemetry must not add device syncs. Drains
        stay at print_freq granularity — one per interval plus the
        final partial — and every drain maps to exactly one
        train_interval event."""
        expected = len([i for i in range(STEPS) if i % PRINT_FREQ == 0])
        if (STEPS - 1) % PRINT_FREQ != 0:
            expected += 1  # trailing partial interval
        assert telemetry_run["drains"] == expected
        intervals = read_events(telemetry_run["run_dir"], "train_interval")
        assert len(intervals) == expected

    def test_summarize_real_run(self, telemetry_run):
        report, summary = summarize_run(telemetry_run["run_dir"])
        assert "compile" in report and "data-wait" in report
        assert "starvation verdict:" in report
        assert "layer1_0.conv1" in report
        assert summary["compile_s"] > 0
        assert summary["phases"]["interval_s"] > 0
        assert summary["starvation"]["verdict"]
        assert summary["best"]["acc1"] == pytest.approx(
            telemetry_run["res"]["best_acc1"], abs=1e-2
        )


class TestNonFinitePolicy:
    def test_injected_nan_fails_fast(self, tmp_path, monkeypatch):
        """End-to-end: a NaN CE loss inside the jitted step must stop
        the run at the next drain (policy 'raise', the default) — not
        silently poison best-acc tracking."""
        import bdbnn_tpu.train.step as step_mod

        monkeypatch.setattr(
            step_mod, "softmax_cross_entropy",
            lambda logits, labels: jnp.float32(jnp.nan),
        )
        with pytest.raises(NonFiniteLossError, match="non-finite"):
            fit(
                _cfg(
                    tmp_path,
                    synthetic_train_size=128,
                    probe_binarization=False,  # irrelevant here; compiles faster
                )
            )
        # the incident is on the record for post-hoc diagnosis
        nonfinite = read_events(_find_run_dir(tmp_path), "nonfinite")
        assert nonfinite and nonfinite[0]["policy"] == "raise"

    def test_eval_nan_loss_detected(self, tmp_path, monkeypatch):
        """The eval-side signal is the LOSS (accuracy is a ratio of
        boolean correct-counts — finite for any weights): a NaN
        validation loss must trip the policy even when every train
        interval was clean."""
        import bdbnn_tpu.train.loop as loop_mod

        orig = loop_mod.make_eval_step

        def nan_eval(model, input_norm=None):
            step = orig(model, input_norm=input_norm)

            def wrapped(state, batch):
                m = dict(step(state, batch))
                m["loss_sum"] = m["loss_sum"] + jnp.float32(jnp.nan)
                return m

            return wrapped

        monkeypatch.setattr(loop_mod, "make_eval_step", nan_eval)
        with pytest.raises(NonFiniteLossError, match="validation loss"):
            fit(_cfg(tmp_path, synthetic_train_size=64,
                     probe_binarization=False))
        ev = read_events(_find_run_dir(tmp_path), "nonfinite")
        assert ev and ev[0]["where"] == "eval"

    def test_policy_unit_semantics(self, tmp_path):
        import logging

        from bdbnn_tpu.train.loop import _apply_nonfinite_policy

        logger = logging.getLogger("test_obs_nonfinite")
        ev = EventWriter(str(tmp_path))
        # warn: records + continues
        _apply_nonfinite_policy("warn", logger, ev, "boom", epoch=0)
        # ignore: records + continues (detection upstream is what the
        # 'ignore' policy disables)
        _apply_nonfinite_policy("ignore", logger, ev, "boom", epoch=1)
        with pytest.raises(NonFiniteLossError):
            _apply_nonfinite_policy("raise", logger, ev, "boom", epoch=2)
        ev.close()
        assert len(read_events(str(tmp_path), "nonfinite")) == 3

    def test_ignore_policy_removes_detection(self):
        cfg = RunConfig(synthetic=True, nonfinite_policy="ignore")
        assert cfg.validate().nonfinite_policy == "ignore"
        with pytest.raises(ValueError, match="nonfinite_policy"):
            RunConfig(synthetic=True, nonfinite_policy="explode").validate()


class TestEventChannel:
    def test_nonfinite_values_serialize_as_null(self, tmp_path):
        """events.jsonl must stay strict RFC-8259 JSON even when a
        warn-policy run records NaN metrics: non-finite floats land as
        null, never bare NaN/Infinity tokens (which jq and most
        non-Python parsers reject)."""
        ev = EventWriter(str(tmp_path))
        ev.emit("train_interval", loss=float("nan"),
                kurtosis={"a": float("inf")}, ok=1.5)
        ev.close()
        with open(ev.path) as f:
            line = f.read().strip()

        def no_constants(s):
            raise AssertionError(f"bare {s} token in events.jsonl")

        rec = json.loads(line, parse_constant=no_constants)
        assert rec["loss"] is None
        assert rec["kurtosis"]["a"] is None
        assert rec["ok"] == 1.5


class TestProbeMath:
    def test_drain_probe_report_normalization(self):
        sums = {"flips/a": 30.0, "kurt/a": 7.5}
        flip, kurt = drain_probe_report(sums, {"a": 100}, 3)
        # 30 flips over 3 steps of a 100-weight layer = 0.1/step
        assert flip["a"] == pytest.approx(0.1)
        assert kurt["a"] == pytest.approx(2.5)


class TestSummarizeFixture:
    def test_report(self, fixture_run_dir):
        report, summary = summarize_run(fixture_run_dir)
        assert "compile: first-step trace+compile 5.00s" in report
        # fixture phase timing is half data-wait -> input-bound verdict
        assert summary["starvation"]["input_bound"] is True
        assert "INPUT-BOUND" in report
        assert "layer1_0.conv1" in report
        assert summary["best"]["acc1"] == pytest.approx(90.0)
        # flip rate decays across the fixture's epochs
        probes = summary["probes"]["layer1_0.conv1"]
        assert probes["flip_rate_first"] > probes["flip_rate_last"]
        assert summary["loss_components"]["loss_ce"][0] > (
            summary["loss_components"]["loss_ce"][-1]
        )

    def test_probe_fallback_is_chronological(self, fixture_run_dir):
        """Without scalars.jsonl the probe trajectories come from the
        per-interval events, whose `step` field resets each epoch —
        first/last must still be chronological (keyed on epoch+step)."""
        os.remove(os.path.join(fixture_run_dir, "scalars.jsonl"))
        _, summary = summarize_run(fixture_run_dir)
        probes = summary["probes"]["layer1_0.conv1"]
        # the fixture decays flip rate per epoch: 1e-3 -> 1e-3/3
        assert probes["flip_rate_first"] == pytest.approx(1e-3)
        assert probes["flip_rate_last"] == pytest.approx(1e-3 / 3, abs=1e-6)

    def test_probe_fallback_skips_null_values(self, fixture_run_dir):
        """A warn-policy run's NaN kurtosis lands as null in the event
        (jsonsafe); the fallback must skip it, not crash the report of
        exactly the broken run being post-mortemed."""
        os.remove(os.path.join(fixture_run_dir, "scalars.jsonl"))
        path = os.path.join(fixture_run_dir, "events.jsonl")
        with open(path) as f:
            lines = f.readlines()
        with open(path, "w") as f:
            for line in lines:
                rec = json.loads(line)
                if rec.get("kind") == "train_interval":
                    rec["kurtosis"] = {"layer1_0.conv1": None}
                f.write(json.dumps(rec) + "\n")
        report, summary = summarize_run(fixture_run_dir)
        probes = summary["probes"]["layer1_0.conv1"]
        assert "flip_rate_first" in probes
        assert "kurtosis_first" not in probes  # all nulls -> no curve
        assert "layer1_0.conv1" in report

    def test_resolves_from_log_root(self, fixture_run_dir):
        root = os.path.dirname(fixture_run_dir)
        _, summary = summarize_run(root)
        assert summary["run_dir"] == fixture_run_dir

    def test_missing_dir_is_hard_error(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            summarize_run(str(tmp_path / "empty"))
