"""Unified telemetry tests (obs/): manifest round-trip, event-channel
contents of a real synthetic fit(), the non-finite fail-fast policy,
the summarize report engine, and the no-extra-syncs invariant (drain
count at ``print_freq`` granularity is UNCHANGED by telemetry — the
whole design rides the existing DeviceMetrics cadence)."""

import glob
import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from bdbnn_tpu.configs.config import RunConfig
from bdbnn_tpu.obs import (
    EventWriter,
    RunManifest,
    TraceCapture,
    attribute_trace,
    config_hash,
    hlo_breakdown,
    jit_step_ms,
    parse_profile_at,
    read_events,
    read_manifest,
    summarize_run,
    write_manifest,
)
from bdbnn_tpu.obs.probes import NonFiniteLossError, drain_probe_report
from bdbnn_tpu.train.loop import fit
from conftest import write_synthetic_trace

# the shared fit: 256 examples / batch 64 = 4 steps, print_freq 2
STEPS = 4
PRINT_FREQ = 2


def _cfg(tmp_path, **kw):
    base = dict(
        dataset="cifar10",
        synthetic=True,
        synthetic_train_size=256,
        synthetic_val_size=64,
        arch="resnet20",
        epochs=1,
        batch_size=64,
        lr=0.05,
        print_freq=PRINT_FREQ,
        log_path=str(tmp_path / "log"),
        seed=0,
        workers=2,
    )
    base.update(kw)
    return RunConfig(**base)


def _find_run_dir(root):
    hits = glob.glob(os.path.join(str(root), "**", "events.jsonl"),
                     recursive=True)
    assert hits, f"no events.jsonl under {root}"
    return os.path.dirname(sorted(hits)[-1])


@pytest.fixture(scope="module")
def telemetry_run(tmp_path_factory):
    """ONE 1-epoch synthetic fit, with DeviceMetrics.drain instrumented
    to count real host syncs, shared by every assertion below."""
    from bdbnn_tpu.utils.meters import DeviceMetrics

    tmp = tmp_path_factory.mktemp("obsrun")
    calls = {"drain": 0}
    orig = DeviceMetrics.drain

    def counted(self):
        calls["drain"] += 1
        return orig(self)

    DeviceMetrics.drain = counted
    try:
        res = fit(_cfg(tmp))
    finally:
        DeviceMetrics.drain = orig
    run_dir = _find_run_dir(tmp)
    return {"res": res, "run_dir": run_dir, "drains": calls["drain"]}


class TestManifest:
    def test_write_read_roundtrip(self, tmp_path):
        cfg = RunConfig(synthetic=True, epochs=3)
        written = write_manifest(str(tmp_path), cfg)
        loaded = read_manifest(str(tmp_path))
        assert loaded == written
        man = RunManifest.from_dict(loaded)
        assert man.config_hash == written["config_hash"]
        assert man.schema == 1
        # provenance the summarize report keys on
        for key in ("jax_version", "jaxlib_version", "backend",
                    "device_count", "process_count", "config"):
            assert loaded[key] is not None
        assert loaded["config"]["epochs"] == 3

    def test_missing_manifest_is_none(self, tmp_path):
        assert read_manifest(str(tmp_path)) is None

    def test_config_hash_stable_and_sensitive(self):
        a = RunConfig(lr=0.1)
        b = RunConfig(lr=0.1)
        c = RunConfig(lr=0.2)
        assert config_hash(a) == config_hash(b)
        assert config_hash(a) != config_hash(c)


class TestFitTelemetry:
    def test_files_written(self, telemetry_run):
        run_dir = telemetry_run["run_dir"]
        assert os.path.exists(os.path.join(run_dir, "manifest.json"))
        assert os.path.exists(os.path.join(run_dir, "events.jsonl"))
        man = read_manifest(run_dir)
        start = read_events(run_dir, "run_start")[0]
        assert start["config_hash"] == man["config_hash"]
        assert start["steps_per_epoch"] == STEPS

    def test_event_kinds(self, telemetry_run):
        kinds = {e["kind"] for e in read_events(telemetry_run["run_dir"])}
        assert {"run_start", "compile", "train_interval", "epoch",
                "eval", "run_end"} <= kinds

    def test_step_phase_timing_fields(self, telemetry_run):
        run_dir = telemetry_run["run_dir"]
        intervals = read_events(run_dir, "train_interval")
        assert intervals
        for ev in intervals:
            for key in ("data_wait_s", "dispatch_s", "drain_s",
                        "interval_s", "data_wait_share", "steps",
                        "loss", "grad_norm"):
                assert key in ev, f"{key} missing from train_interval"
            assert ev["data_wait_s"] >= 0 and ev["dispatch_s"] >= 0
        compile_ev = read_events(run_dir, "compile")[0]
        # first-step trace+compile is the big host block; sub-second
        # would mean we timed a cached dispatch instead
        assert compile_ev["seconds"] > 0.5
        # compile is backed OUT of the first interval's phase wall —
        # phase shares describe steady-state training, not compilation
        assert intervals[0]["interval_s"] < compile_ev["seconds"]

    def test_probe_fields(self, telemetry_run):
        intervals = read_events(telemetry_run["run_dir"], "train_interval")
        for ev in intervals:
            assert ev.get("flip_rate") and ev.get("kurtosis")
            for layer, rate in ev["flip_rate"].items():
                assert 0.0 <= rate <= 1.0, (layer, rate)
            for layer, k in ev["kurtosis"].items():
                assert np.isfinite(k) and k > 0.0, (layer, k)
        # the probed set is the non-stem convs of resnet20 (no kurtosis
        # hooks in this run -> the "all" convention)
        assert len(intervals[0]["flip_rate"]) == 20
        # per-epoch probe scalars landed too (summarize's trajectory)
        with open(os.path.join(telemetry_run["run_dir"],
                               "scalars.jsonl")) as f:
            tags = {json.loads(l)["tag"] for l in f if l.strip()}
        assert any(t.startswith("Probe flip ") for t in tags)
        assert any(t.startswith("Probe kurt ") for t in tags)

    def test_no_extra_host_syncs(self, telemetry_run):
        """THE invariant: telemetry must not add device syncs. Drains
        stay at print_freq granularity — one per interval plus the
        final partial — and every drain maps to exactly one
        train_interval event."""
        expected = len([i for i in range(STEPS) if i % PRINT_FREQ == 0])
        if (STEPS - 1) % PRINT_FREQ != 0:
            expected += 1  # trailing partial interval
        assert telemetry_run["drains"] == expected
        intervals = read_events(telemetry_run["run_dir"], "train_interval")
        assert len(intervals) == expected

    def test_summarize_real_run(self, telemetry_run):
        report, summary = summarize_run(telemetry_run["run_dir"])
        assert "compile" in report and "data-wait" in report
        assert "starvation verdict:" in report
        assert "layer1_0.conv1" in report
        assert summary["compile_s"] > 0
        assert summary["phases"]["interval_s"] > 0
        assert summary["starvation"]["verdict"]
        assert summary["best"]["acc1"] == pytest.approx(
            telemetry_run["res"]["best_acc1"], abs=1e-2
        )


class TestNonFinitePolicy:
    def test_injected_nan_fails_fast(self, tmp_path, monkeypatch):
        """End-to-end: a NaN CE loss inside the jitted step must stop
        the run at the next drain (policy 'raise', the default) — not
        silently poison best-acc tracking."""
        import bdbnn_tpu.train.step as step_mod

        monkeypatch.setattr(
            step_mod, "softmax_cross_entropy",
            lambda logits, labels: jnp.float32(jnp.nan),
        )
        with pytest.raises(NonFiniteLossError, match="non-finite"):
            fit(
                _cfg(
                    tmp_path,
                    synthetic_train_size=128,
                    probe_binarization=False,  # irrelevant here; compiles faster
                )
            )
        # the incident is on the record for post-hoc diagnosis
        nonfinite = read_events(_find_run_dir(tmp_path), "nonfinite")
        assert nonfinite and nonfinite[0]["policy"] == "raise"

    def test_eval_nan_loss_detected(self, tmp_path, monkeypatch):
        """The eval-side signal is the LOSS (accuracy is a ratio of
        boolean correct-counts — finite for any weights): a NaN
        validation loss must trip the policy even when every train
        interval was clean."""
        import bdbnn_tpu.train.loop as loop_mod

        orig = loop_mod.make_eval_step

        def nan_eval(model, input_norm=None):
            step = orig(model, input_norm=input_norm)

            def wrapped(state, batch):
                m = dict(step(state, batch))
                m["loss_sum"] = m["loss_sum"] + jnp.float32(jnp.nan)
                return m

            return wrapped

        monkeypatch.setattr(loop_mod, "make_eval_step", nan_eval)
        with pytest.raises(NonFiniteLossError, match="validation loss"):
            fit(_cfg(tmp_path, synthetic_train_size=64,
                     probe_binarization=False))
        ev = read_events(_find_run_dir(tmp_path), "nonfinite")
        assert ev and ev[0]["where"] == "eval"

    def test_policy_unit_semantics(self, tmp_path):
        import logging

        from bdbnn_tpu.train.loop import _apply_nonfinite_policy

        logger = logging.getLogger("test_obs_nonfinite")
        ev = EventWriter(str(tmp_path))
        # warn: records + continues
        _apply_nonfinite_policy("warn", logger, ev, "boom", epoch=0)
        # ignore: records + continues (detection upstream is what the
        # 'ignore' policy disables)
        _apply_nonfinite_policy("ignore", logger, ev, "boom", epoch=1)
        with pytest.raises(NonFiniteLossError):
            _apply_nonfinite_policy("raise", logger, ev, "boom", epoch=2)
        ev.close()
        assert len(read_events(str(tmp_path), "nonfinite")) == 3

    def test_ignore_policy_removes_detection(self):
        cfg = RunConfig(synthetic=True, nonfinite_policy="ignore")
        assert cfg.validate().nonfinite_policy == "ignore"
        with pytest.raises(ValueError, match="nonfinite_policy"):
            RunConfig(synthetic=True, nonfinite_policy="explode").validate()


class TestEventChannel:
    def test_nonfinite_values_serialize_as_null(self, tmp_path):
        """events.jsonl must stay strict RFC-8259 JSON even when a
        warn-policy run records NaN metrics: non-finite floats land as
        null, never bare NaN/Infinity tokens (which jq and most
        non-Python parsers reject)."""
        ev = EventWriter(str(tmp_path))
        ev.emit("train_interval", loss=float("nan"),
                kurtosis={"a": float("inf")}, ok=1.5)
        ev.close()
        with open(ev.path) as f:
            line = f.read().strip()

        def no_constants(s):
            raise AssertionError(f"bare {s} token in events.jsonl")

        rec = json.loads(line, parse_constant=no_constants)
        assert rec["loss"] is None
        assert rec["kurtosis"]["a"] is None
        assert rec["ok"] == 1.5


class TestProbeMath:
    def test_drain_probe_report_normalization(self):
        sums = {"flips/a": 30.0, "kurt/a": 7.5}
        flip, kurt = drain_probe_report(sums, {"a": 100}, 3)
        # 30 flips over 3 steps of a 100-weight layer = 0.1/step
        assert flip["a"] == pytest.approx(0.1)
        assert kurt["a"] == pytest.approx(2.5)


class TestTraceParser:
    """The semantic-attribution parser against a hand-built trace
    (device + host tracks, named scopes, an unnamed HLO op) — pins the
    category aggregation and the ms/step math."""

    @pytest.fixture
    def trace_path(self, tmp_path):
        return write_synthetic_trace(
            str(tmp_path / "plugins" / "profile" / "x" / "t.trace.json.gz"),
            n_steps=5,
        )

    def test_category_aggregation_and_ms_math(self, trace_path):
        att = attribute_trace(trace_path, 5)
        cats = att["categories_ms_per_step"]
        assert cats["binarize"] == pytest.approx(1.0)
        assert cats["binary_conv"] == pytest.approx(4.0)
        assert cats["bn_act"] == pytest.approx(1.5)
        assert cats["kurtosis_loss"] == pytest.approx(2.0)
        assert cats["optimizer"] == pytest.approx(0.5)
        # the unnamed HLO op pools under "unattributed", never a span
        assert cats["unattributed"] == pytest.approx(1.0)
        # module-level jit_train_step events give the step total
        assert att["step_total_ms"] == pytest.approx(10.0)
        # categories render most-expensive first
        assert list(cats)[0] == "binary_conv"

    def test_host_phases_not_device_noise(self, trace_path):
        att = attribute_trace(trace_path, 5)
        host = att["host_phases_ms_per_step"]
        assert host["data_wait"] == pytest.approx(3.0)
        assert host["dispatch"] == pytest.approx(0.25)
        # the host-track PjitFunction umbrella span (11 ms/step) must
        # not leak into device categories — that would double-count
        # every op under it
        total_attr = sum(att["categories_ms_per_step"].values())
        assert total_attr == pytest.approx(10.0)

    def test_aux_device_tracks_not_double_counted(self, trace_path):
        """Real TPU traces re-describe device time on umbrella threads
        under the SAME device pid ("TensorFlow Name Scope" spans named
        after the scopes themselves, the "Steps" line). The fixture
        carries both; counting them would double binarize (1->2 ms)
        and kurtosis_loss (2->4 ms) and add a phantom 10 ms/step of
        unattributed Steps time."""
        att = attribute_trace(trace_path, 5)
        cats = att["categories_ms_per_step"]
        assert cats["binarize"] == pytest.approx(1.0)
        assert cats["kurtosis_loss"] == pytest.approx(2.0)
        assert cats["unattributed"] == pytest.approx(1.0)

    def test_mfu_estimate(self, trace_path):
        # 0.985e12 flops / 10 ms step / 197 TFLOP/s peak = 50% MFU
        att = attribute_trace(
            trace_path, 5, flops_per_step=0.985e12, peak_tflops=197.0
        )
        assert att["mfu"] == pytest.approx(0.5)
        # no peak -> no MFU, everything else intact
        att = attribute_trace(trace_path, 5, flops_per_step=0.985e12)
        assert att["mfu"] is None

    def test_hlo_breakdown_legacy_shape(self, trace_path):
        groups, step_total = hlo_breakdown(trace_path, 5)
        # trailing .N stripped, grouped, ms/step
        assert groups["convolution"] == pytest.approx(4.0)
        assert groups["fusion"] == pytest.approx(4.0)  # 1.0+1.5+0.5+1.0
        assert groups["reduce"] == pytest.approx(2.0)
        assert step_total == pytest.approx(10.0)

    def test_jit_step_ms_median(self, trace_path):
        assert jit_step_ms(trace_path) == pytest.approx(10.0)

    def test_profile_at_spec(self):
        assert parse_profile_at("12:40:8") == (12, 40, 8)
        assert parse_profile_at("0:5", default_steps=7) == (0, 5, 7)
        for bad in ("5", "1:2:3:4", "a:b", "1:-2", "1:2:0"):
            with pytest.raises(ValueError):
                parse_profile_at(bad)


class TestTraceCapture:
    """Exception safety: stop_trace runs exactly once on the failure
    path — a raised step between start and stop must neither leave the
    profiler running nor double-stop it."""

    @pytest.fixture
    def profiler_spy(self, monkeypatch):
        import jax.profiler

        calls = {"start": 0, "stop": 0}
        monkeypatch.setattr(
            jax.profiler, "start_trace",
            lambda d: calls.__setitem__("start", calls["start"] + 1),
        )
        monkeypatch.setattr(
            jax.profiler, "stop_trace",
            lambda: calls.__setitem__("stop", calls["stop"] + 1),
        )
        return calls

    def test_normal_window(self, tmp_path, profiler_spy):
        cap = TraceCapture(str(tmp_path / "tr"), [(1, 2, 3)])
        assert not cap.maybe_start(0, 2)  # wrong epoch
        assert not cap.maybe_start(1, 1)  # before the start step
        assert cap.maybe_start(1, 2)
        assert cap.active
        assert cap.maybe_stop(1, 3) is None  # budget is 3 steps
        info = cap.maybe_stop(1, 4)
        assert info == {
            "epoch": 1, "start_step": 2, "steps": 3,
            "trace_dir": str(tmp_path / "tr"),
        }
        assert profiler_spy == {"start": 1, "stop": 1}
        # idle finally-path call: no second stop
        assert cap.stop_if_active() is None
        assert profiler_spy["stop"] == 1

    def test_raise_between_start_and_stop(self, tmp_path, profiler_spy):
        cap = TraceCapture(str(tmp_path / "tr"), [(0, 0, 5)])
        assert cap.maybe_start(0, 0)
        # the step raised; the loop's finally flushes the window with a
        # short actual step count
        info = cap.stop_if_active(last_step=1)
        assert info["steps"] == 2  # trimmed to steps actually traced
        assert profiler_spy == {"start": 1, "stop": 1}
        assert cap.stop_if_active() is None  # exactly once
        assert profiler_spy["stop"] == 1

    def test_fence_failure_still_stops(self, tmp_path, profiler_spy):
        cap = TraceCapture(str(tmp_path / "tr"), [(0, 0, 5)])
        cap.maybe_start(0, 0)

        def bad_fence():
            raise RuntimeError("device died")

        with pytest.raises(RuntimeError, match="device died"):
            cap.maybe_stop(0, 4, fence=bad_fence)
        # the trace was still stopped, exactly once, and the capture
        # is inert afterwards
        assert profiler_spy == {"start": 1, "stop": 1}
        assert cap.active is None
        assert cap.stop_if_active() is None
        assert profiler_spy["stop"] == 1

    def test_late_start_fires_past_window_step(self, tmp_path, profiler_spy):
        # a start call that overshoots the requested step still opens
        # the window (>=), rather than never firing
        cap = TraceCapture(str(tmp_path / "tr"), [(0, 100, 2)])
        assert not cap.maybe_start(0, 99)
        assert cap.maybe_start(0, 100)

    def test_unreachable_windows_reported(self, tmp_path, profiler_spy):
        # a spec whose epoch is never visited (resume) or whose start
        # step exceeds the epoch length stays pending; unfired() is
        # what fit() warns from at run end
        cap = TraceCapture(str(tmp_path / "tr"), [(3, 0, 5), (0, 500, 5)])
        for step in range(10):  # a 10-step epoch 0; epoch 3 never runs
            assert not cap.maybe_start(0, step)
        assert sorted(cap.unfired()) == [(0, 500, 5), (3, 0, 5)]
        assert profiler_spy == {"start": 0, "stop": 0}


class TestMemoryEvents:
    def test_fit_emits_memory_events(self, telemetry_run):
        """The synthetic-fit harness emits the memory schema at both
        poll points (post-compile + epoch boundary); on backends
        without allocator stats (CPU) the event still lands with
        available=false so downstream tooling sees one schema."""
        mems = read_events(telemetry_run["run_dir"], "memory")
        phases = [m["phase"] for m in mems]
        assert "post_compile" in phases and "epoch" in phases
        for m in mems:
            assert set(m) >= {"t", "kind", "phase", "available",
                              "devices", "peak_bytes", "limit_bytes"}
            assert isinstance(m["available"], bool)
            assert isinstance(m["devices"], list)
            if not m["available"]:
                assert m["peak_bytes"] is None
            for row in m["devices"]:
                assert "device" in row and "peak_bytes_in_use" in row

    def test_emit_memory_event_with_stats(self, tmp_path):
        from bdbnn_tpu.obs.memory import emit_memory_event

        class FakeDev:
            def __init__(self, i, peak):
                self.id = i
                self._peak = peak

            def memory_stats(self):
                return {"bytes_in_use": 100, "peak_bytes_in_use": self._peak,
                        "bytes_limit": 1000}

        ev = EventWriter(str(tmp_path))
        rec = emit_memory_event(
            ev, "epoch", [FakeDev(0, 700), FakeDev(1, 800)], epoch=3
        )
        ev.close()
        assert rec["available"] is True
        assert rec["peak_bytes"] == 800  # max over devices
        assert rec["limit_bytes"] == 1000
        assert rec["epoch"] == 3
        assert len(rec["devices"]) == 2

    def test_hbm_watermark_fold(self):
        from bdbnn_tpu.obs.memory import hbm_watermark

        evs = [
            {"kind": "memory", "peak_bytes": 6 * 2**30,
             "limit_bytes": 16 * 2**30},
            {"kind": "memory", "peak_bytes": 8 * 2**30,
             "limit_bytes": 16 * 2**30},
            {"kind": "memory", "peak_bytes": None, "limit_bytes": None},
        ]
        wm = hbm_watermark(evs)
        assert wm["peak_gib"] == pytest.approx(8.0)
        assert wm["limit_gib"] == pytest.approx(16.0)
        assert wm["utilization"] == pytest.approx(0.5)
        assert hbm_watermark([{"kind": "memory", "peak_bytes": None}]) is None


class TestProfileAtEndToEnd:
    @pytest.mark.slow
    def test_profile_at_capture_and_summarize(self, tmp_path):
        """--profile-at on a real (CPU) synthetic fit: the window
        opens/closes exception-free mid-epoch, the trace lands under
        <run_dir>/profile, the profile event records the window, and
        `summarize` grows the attribution section."""
        fit(
            _cfg(
                tmp_path,
                synthetic_train_size=192,  # 3 steps
                profile_at=("0:1:2",),
                probe_binarization=False,
            )
        )
        run_dir = _find_run_dir(tmp_path)
        prof = read_events(run_dir, "profile")
        assert len(prof) == 1
        assert prof[0]["epoch"] == 0 and prof[0]["start_step"] == 1
        assert prof[0]["steps"] == 2
        from bdbnn_tpu.obs import find_trace_file

        assert find_trace_file(run_dir), "no trace file under run dir"

        report, summary = summarize_run(run_dir)
        att = summary["attribution"]
        assert att is not None
        assert att["captured"]["epoch"] == 0
        assert att["trace_file"]
        # the CPU backend strips scope metadata from its op events, so
        # categories may be all-unattributed here — the span-keyed math
        # is pinned by TestTraceParser on the synthetic device trace
        assert isinstance(att["categories_ms_per_step"], dict)
        assert att["step_total_ms"] is None or att["step_total_ms"] > 0
        # memory events fold in (CPU: available=false -> no hbm block)
        assert "hbm" in att or att.get("hbm") is None


class TestSummarizeFixture:
    def test_report(self, fixture_run_dir):
        report, summary = summarize_run(fixture_run_dir)
        assert "compile: first-step trace+compile 5.00s" in report
        # fixture phase timing is half data-wait -> input-bound verdict
        assert summary["starvation"]["input_bound"] is True
        assert "INPUT-BOUND" in report
        assert "layer1_0.conv1" in report
        assert summary["best"]["acc1"] == pytest.approx(90.0)
        # flip rate decays across the fixture's epochs
        probes = summary["probes"]["layer1_0.conv1"]
        assert probes["flip_rate_first"] > probes["flip_rate_last"]
        assert summary["loss_components"]["loss_ce"][0] > (
            summary["loss_components"]["loss_ce"][-1]
        )

    def test_attribution_section(self, fixture_run_dir):
        """The acceptance-criterion path: a run dir with a captured
        trace window + memory events reports per-category device
        ms/step keyed by the SEMANTIC span names (not raw HLO names),
        an MFU, and the HBM peak — in --json and in the report text."""
        report, summary = summarize_run(fixture_run_dir)
        att = summary["attribution"]
        cats = att["categories_ms_per_step"]
        assert cats["binary_conv"] == pytest.approx(4.0)
        assert cats["kurtosis_loss"] == pytest.approx(2.0)
        assert "fusion" not in cats  # semantic names, not HLO names
        assert att["step_total_ms"] == pytest.approx(10.0)
        assert att["mfu"] == pytest.approx(0.5)
        assert att["hbm"]["peak_gib"] == pytest.approx(8.0)
        assert att["hbm"]["utilization"] == pytest.approx(0.5)
        assert "device attribution" in report
        assert "binary_conv" in report
        assert "MFU 50.0%" in report
        assert "hbm: peak 8.00 GiB of 16.00 GiB (50%)" in report

    def test_probe_fallback_is_chronological(self, fixture_run_dir):
        """Without scalars.jsonl the probe trajectories come from the
        per-interval events, whose `step` field resets each epoch —
        first/last must still be chronological (keyed on epoch+step)."""
        os.remove(os.path.join(fixture_run_dir, "scalars.jsonl"))
        _, summary = summarize_run(fixture_run_dir)
        probes = summary["probes"]["layer1_0.conv1"]
        # the fixture decays flip rate per epoch: 1e-3 -> 1e-3/3
        assert probes["flip_rate_first"] == pytest.approx(1e-3)
        assert probes["flip_rate_last"] == pytest.approx(1e-3 / 3, abs=1e-6)

    def test_probe_fallback_skips_null_values(self, fixture_run_dir):
        """A warn-policy run's NaN kurtosis lands as null in the event
        (jsonsafe); the fallback must skip it, not crash the report of
        exactly the broken run being post-mortemed."""
        os.remove(os.path.join(fixture_run_dir, "scalars.jsonl"))
        path = os.path.join(fixture_run_dir, "events.jsonl")
        with open(path) as f:
            lines = f.readlines()
        with open(path, "w") as f:
            for line in lines:
                rec = json.loads(line)
                if rec.get("kind") == "train_interval":
                    rec["kurtosis"] = {"layer1_0.conv1": None}
                f.write(json.dumps(rec) + "\n")
        report, summary = summarize_run(fixture_run_dir)
        probes = summary["probes"]["layer1_0.conv1"]
        assert "flip_rate_first" in probes
        assert "kurtosis_first" not in probes  # all nulls -> no curve
        assert "layer1_0.conv1" in report

    def test_resolves_from_log_root(self, fixture_run_dir):
        root = os.path.dirname(fixture_run_dir)
        _, summary = summarize_run(root)
        assert summary["run_dir"] == fixture_run_dir

    def test_missing_dir_is_hard_error(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            summarize_run(str(tmp_path / "empty"))
