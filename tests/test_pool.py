"""Replica pool + artifact registry + blue/green hot swap
(bdbnn_tpu/serve/pool.py, serve/registry.py).

Three tiers, mirroring the serve/http test strategy:

- **stub tier** (no JAX): the dispatcher (least-loaded placement,
  per-replica bounded queues, strict-priority preserved through the
  async front batcher), the health monitor (wedged worker detected,
  routed around, restarted, queued work re-dispatched — and the stuck
  batch still ANSWERED when it unsticks), the swap state machine
  (standby warm -> replica-by-replica shift -> done; failed standby
  keeps vN serving; one swap at a time) and the ``/admin`` routes.
- **paced tier**: the ``serve-bench --replicas`` scaling sweep through
  the real orchestration with paced runners — on a CPU-simulated mesh
  every "device" shares one host's cores, so an unpaced sweep measures
  host contention, not the pool; a fixed sleep per batch parallelizes
  the way a per-chip engine does and isolates what the POOL adds. The
  sweep must be monotone with efficiency >= 0.7 at 8 replicas (the
  acceptance gate; the unpaced on-chip recipe is R05_NOTES.md's r06).
- **real-engine tier**: engines actually placed per mesh device
  (distinct devices, identical logits to a single engine), and THE
  acceptance e2e — flash-crowd over real sockets against a 2-replica
  pool of real AOT engines with a registry-resolved blue/green swap
  fired mid-schedule: zero dropped, zero shed-due-to-swap, every
  request answered by exactly one of vN/vN+1, ledger identity intact.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from bdbnn_tpu.serve.batching import LoadShedError, MicroBatcher
from bdbnn_tpu.serve.pool import (
    READY,
    SWAP_DONE,
    SWAP_FAILED,
    UNHEALTHY,
    PoolAdmin,
    ReplicaPool,
    make_engine_runner_factory,
)
from bdbnn_tpu.serve.registry import ArtifactRegistry

from test_http import _request


def tag_factory(pace_s=0.0, record=None):
    """A stub runner factory: results are (version_ref, device, payload)
    triples, optionally paced, optionally recording execution order."""

    def factory(ref, device):
        def runner(payloads):
            if pace_s:
                time.sleep(pace_s)
            if record is not None:
                record.append((device, list(payloads)))
            return [(ref, device, p) for p in payloads]

        return runner

    return factory


# ---------------------------------------------------------------------------
# artifact registry
# ---------------------------------------------------------------------------


class TestArtifactRegistry:
    def test_publish_list_resolve_roundtrip(
        self, exported_artifact, tmp_path
    ):
        art_dir, _ = exported_artifact
        reg = ArtifactRegistry(str(tmp_path / "reg"))
        e1 = reg.publish(art_dir)
        e2 = reg.publish(art_dir)
        assert (e1["version"], e2["version"]) == (1, 2)
        assert reg.label(2) == "v0002"
        assert [e["version"] for e in reg.entries()] == [1, 2]
        assert reg.latest()["version"] == 2
        # provenance copied from the artifact manifest at publish time
        assert e1["provenance"]["arch"] == "resnet8_tiny"
        assert e1["weights_sha256"] and len(e1["artifact_sha256"]) == 64
        resolved = reg.resolve(1)
        assert os.path.exists(os.path.join(resolved, "artifact.json"))
        assert resolved.endswith("v0001")
        # the index itself is strict JSON
        with open(os.path.join(str(tmp_path / "reg"), "registry.json")) as f:
            json.loads(
                f.read(),
                parse_constant=lambda s: pytest.fail(f"bare {s}"),
            )

    def test_resolve_detects_tamper(self, exported_artifact, tmp_path):
        art_dir, _ = exported_artifact
        reg = ArtifactRegistry(str(tmp_path / "reg"))
        v = reg.publish(art_dir)["version"]
        target = os.path.join(str(tmp_path / "reg"), "v0001")
        # edit artifact.json after publish -> outer digest link breaks
        with open(os.path.join(target, "artifact.json"), "a") as f:
            f.write("\n")
        with pytest.raises(RuntimeError, match="modified after publish"):
            reg.resolve(v)

    def test_resolve_detects_torn_weights(
        self, exported_artifact, tmp_path
    ):
        art_dir, _ = exported_artifact
        reg = ArtifactRegistry(str(tmp_path / "reg"))
        v = reg.publish(art_dir)["version"]
        wpath = os.path.join(str(tmp_path / "reg"), "v0001", "weights.npz")
        with open(wpath, "r+b") as f:
            f.seek(0)
            f.write(b"\x00\x01\x02\x03")
        with pytest.raises(RuntimeError, match="weights do not match"):
            reg.resolve(v)

    def test_publish_refuses_torn_artifact(
        self, exported_artifact, tmp_path
    ):
        import shutil

        art_dir, _ = exported_artifact
        torn = str(tmp_path / "torn")
        shutil.copytree(art_dir, torn)
        with open(os.path.join(torn, "weights.npz"), "r+b") as f:
            f.seek(0)
            f.write(b"\xff\xff\xff\xff")
        reg = ArtifactRegistry(str(tmp_path / "reg"))
        with pytest.raises(RuntimeError, match="refusing to publish"):
            reg.publish(torn)
        assert reg.entries() == []  # nothing half-published

    def test_orphan_version_dir_never_reused(
        self, exported_artifact, tmp_path
    ):
        """A crash between the version-dir rename and the index write
        leaves an orphan vNNNN dir with no entry; the next publish must
        skip its number (renaming onto a non-empty dir would fail) —
        the crash window leaves no trace OR a fully-published version,
        never a bricked registry."""
        art_dir, _ = exported_artifact
        root = str(tmp_path / "reg")
        os.makedirs(os.path.join(root, "v0001"))  # the orphan
        reg = ArtifactRegistry(root)
        e = reg.publish(art_dir)
        assert e["version"] == 2
        assert reg.resolve(2).endswith("v0002")

    def test_unknown_version_and_non_artifact(self, tmp_path):
        reg = ArtifactRegistry(str(tmp_path / "reg"))
        with pytest.raises(KeyError, match="no version 3"):
            reg.resolve(3)
        with pytest.raises(FileNotFoundError, match="not an export"):
            reg.publish(str(tmp_path))

    def test_concurrent_publishes_lose_no_entry(
        self, exported_artifact, tmp_path
    ):
        """publish is read-modify-write over the WHOLE index: without
        the publish lock, two concurrent publishers each copy a version
        dir correctly and then one overwrites the other's index entry —
        a fully-published version resolve() can never find. The lock
        serializes them: every publisher's entry survives."""
        art_dir, _ = exported_artifact
        reg = ArtifactRegistry(str(tmp_path / "reg"))
        errs = []

        def one():
            try:
                reg.publish(art_dir)
            except Exception as e:  # pragma: no cover - fails the test
                errs.append(e)

        threads = [threading.Thread(target=one) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert errs == []
        assert [e["version"] for e in reg.entries()] == [1, 2, 3, 4]
        for v in (1, 2, 3, 4):
            assert reg.resolve(v).endswith(f"v{v:04d}")

    def test_held_lock_times_out_and_stale_lock_is_stolen(
        self, exported_artifact, tmp_path
    ):
        art_dir, _ = exported_artifact
        root = str(tmp_path / "reg")
        reg = ArtifactRegistry(root)
        os.makedirs(root, exist_ok=True)
        lock = os.path.join(root, "registry.json.lock")
        with open(lock, "w") as f:
            f.write("12345")
        # a FRESH lock means another publish is live: bounded wait,
        # then a pointed error — never a silent lost update
        with pytest.raises(TimeoutError, match="publish lock"):
            reg.publish(art_dir, lock_timeout_s=0.2)
        # a crashed publisher's stale lock (old mtime) is stolen
        old = time.time() - 3600
        os.utime(lock, (old, old))
        assert reg.publish(art_dir, lock_timeout_s=0.2)["version"] == 1
        assert not os.path.exists(lock)  # released after publish


# ---------------------------------------------------------------------------
# dispatcher: least-loaded placement, bounded queues, priority, drain
# ---------------------------------------------------------------------------


class TestDispatch:
    def test_least_loaded_spreads_batches_across_replicas(self):
        record = []
        pool = ReplicaPool(
            tag_factory(pace_s=0.005, record=record),
            ["d0", "d1", "d2", "d3"],
            artifact_ref="v1",
            version="v0001",
        )
        futs = [pool.submit([i]) for i in range(32)]
        for f in futs:
            f.result(timeout=10)
        assert pool.drain(10)
        used = {dev for dev, _ in record}
        assert used == {"d0", "d1", "d2", "d3"}
        stats = pool.stats()
        assert stats["completed"] == 32
        assert stats["completed_by_version"] == {"v0001": 32}
        # no replica hogged the work while others idled
        shares = [r["batches"] for r in stats["replicas"]]
        assert min(shares) >= 1

    def test_replica_queue_bound_sheds_explicitly(self):
        release = threading.Event()

        def factory(ref, device):
            def runner(payloads):
                release.wait(timeout=10)
                return list(payloads)

            return runner

        pool = ReplicaPool(
            factory, ["d0"], max_queue_batches=2, wedge_timeout_s=60
        )
        held = [pool.submit([0])]
        # wait for the worker to pick the first batch up, so the bound
        # of 2 is measured on QUEUED work, deterministically
        deadline = time.monotonic() + 5.0
        while (
            pool.replicas[0].queue_depth() > 0
            and time.monotonic() < deadline
        ):
            time.sleep(0.005)
        held += [pool.submit([i]) for i in (1, 2)]  # 1 running + 2 queued
        with pytest.raises(LoadShedError, match="queue full"):
            pool.submit([99])
        assert pool.stats()["shed"] == 1
        release.set()
        for f in held:
            assert f.result(timeout=10)
        assert pool.drain(10)

    def test_no_healthy_replica_sheds_with_reason(self):
        pool = ReplicaPool(tag_factory(), ["d0"], wedge_timeout_s=60)
        pool.replicas[0].state = UNHEALTHY
        with pytest.raises(LoadShedError, match="no healthy replica"):
            pool.submit([1])
        pool.replicas[0].state = READY
        assert pool.drain(10)

    def test_strict_priority_preserved_through_async_dispatch(self):
        """The front batcher dequeues strict-priority and the async
        backpressure bound keeps waiting requests in ITS per-class
        queues (not FIFO'd into replica queues) — so a priority-0
        request submitted AFTER a backlog of priority-1 work overtakes
        every low request not already dispatched."""
        release = threading.Event()
        record = []

        def factory(ref, device):
            def runner(payloads):
                release.wait(timeout=10)
                record.append(list(payloads))
                return list(payloads)

            return runner

        pool = ReplicaPool(factory, ["d0"], wedge_timeout_s=60)
        batcher = MicroBatcher(
            pool.submit, max_batch=2, max_queue=16,
            max_delay_ms=1.0, priorities=2,
            max_pending_batches=2,  # the orchestration's 2x1-replica
        )
        # the first batches wedge the single replica and fill the
        # pending bound; everything after waits in the front's
        # per-class queues where priority still applies
        first = batcher.submit("warm", priority=1)
        time.sleep(0.1)
        lows = [batcher.submit(f"low{i}", priority=1) for i in range(4)]
        time.sleep(0.05)
        high = batcher.submit("HIGH", priority=0)
        time.sleep(0.05)
        release.set()
        assert high.result(timeout=10) == "HIGH"
        for f in [first, *lows]:
            f.result(timeout=10)
        assert batcher.drain(10) and pool.drain(10)
        flat_order = [p for b in record for p in b]
        # HIGH overtakes every low that was still behind the
        # backpressure bound when it arrived (low2, low3); inversion
        # is bounded to the <= 2 batches already dispatched
        assert flat_order.index("HIGH") < flat_order.index("low2")
        assert flat_order.index("HIGH") < flat_order.index("low3")

    def test_batcher_async_accounting_and_drain(self):
        pool = ReplicaPool(
            tag_factory(pace_s=0.002), ["d0", "d1"], version="vX"
        )
        batcher = MicroBatcher(
            pool.submit, max_batch=4, max_queue=64, max_delay_ms=1.0
        )
        futs = [batcher.submit(i) for i in range(20)]
        for f in futs:
            f.result(timeout=10)
        # async settlement still lands in the batcher's ledger
        assert batcher.drain(10)
        stats = batcher.stats()
        assert stats["completed"] == 20
        assert stats["shed"] == 0
        assert pool.drain(10)
        assert pool.stats()["completed"] == 20


# ---------------------------------------------------------------------------
# health: wedge detection, routing around, restart, answered-not-dropped
# ---------------------------------------------------------------------------


class TestReplicaHealth:
    def test_wedged_replica_detected_routed_around_restarted(self):
        wedge = threading.Event()
        events = []
        wedged_once = threading.Event()

        def factory(ref, device):
            def runner(payloads):
                # d0's FIRST batch wedges until released; everything
                # after (including post-restart traffic) is healthy
                if device == "d0" and not wedged_once.is_set():
                    wedged_once.set()
                    wedge.wait(timeout=30)
                return list(payloads)

            return runner

        pool = ReplicaPool(
            factory, ["d0", "d1"],
            wedge_timeout_s=0.3, health_interval_s=0.05,
            on_event=lambda kind, **f: events.append((kind, f)),
        )
        # d0 takes one batch and wedges; d1 keeps serving
        futs = [pool.submit([i]) for i in range(4)]
        deadline = time.monotonic() + 5.0
        while (
            not any(
                f.get("phase") == "restart"
                for kind, f in list(events) if kind == "replica"
            )
            and time.monotonic() < deadline
        ):
            time.sleep(0.05)
        stats = pool.stats()
        assert stats["restarts"] >= 1
        phases = [f.get("phase") for kind, f in events if kind == "replica"]
        assert "unhealthy" in phases and "restart" in phases
        unhealthy = next(
            f for kind, f in events
            if kind == "replica" and f.get("phase") == "unhealthy"
        )
        assert unhealthy["reason"] == "wedged"
        # fresh traffic flows (routed to the healthy replica even while
        # d0's restarted worker would wedge again)
        ok = pool.submit([100])
        assert ok.result(timeout=5) == [100]
        # the stuck batch is ANSWERED when the wedge clears — the
        # retiring worker's last act, never a dropped request
        wedge.set()
        for f in futs:
            assert f.result(timeout=10) is not None
        assert pool.drain(10)
        # exactly one restart once the heartbeat was re-armed (no
        # thrash-looping on the stale busy timestamp)
        assert pool.stats()["restarts"] == 1

    @pytest.mark.filterwarnings(
        "ignore::pytest.PytestUnhandledThreadExceptionWarning"
    )
    def test_dead_worker_detected_and_restarted(self):
        class Boom(BaseException):
            """Kills the worker thread (BaseException escapes the
            runner's Exception guard), simulating a crashed worker."""

        first = threading.Event()

        def factory(ref, device):
            def runner(payloads):
                if not first.is_set():
                    first.set()
                    raise Boom("worker dies")
                return list(payloads)

            return runner

        pool = ReplicaPool(
            factory, ["d0"],
            wedge_timeout_s=5.0, health_interval_s=0.05,
        )
        doomed = pool.submit([1])
        deadline = time.monotonic() + 5.0
        while (
            pool.stats()["restarts"] == 0
            or pool.replicas[0].state != READY
        ) and time.monotonic() < deadline:
            time.sleep(0.05)
        assert pool.stats()["restarts"] >= 1
        # the killed batch's future died with the worker — but later
        # traffic is served by the restarted one
        assert pool.submit([2]).result(timeout=5) == [2]
        with pytest.raises(BaseException):
            doomed.result(timeout=1)
        assert pool.drain(10)

    def test_drain_not_clean_while_a_retired_worker_holds_a_batch(self):
        """A restart rebinds the replica's worker thread; the
        superseded generation may still hold an accepted batch Future.
        drain() must NOT report clean until that Future resolves — a
        direct pool user trusting the True return would tear down with
        an accepted request forever unanswered."""
        wedge = threading.Event()
        wedged_once = threading.Event()

        def factory(ref, device):
            def runner(payloads):
                if not wedged_once.is_set():
                    wedged_once.set()
                    wedge.wait(timeout=30)
                return list(payloads)

            return runner

        pool = ReplicaPool(
            factory, ["d0"],
            wedge_timeout_s=0.2, health_interval_s=0.05,
        )
        stuck = pool.submit([1])
        deadline = time.monotonic() + 5.0
        while (
            pool.stats()["restarts"] == 0
            and time.monotonic() < deadline
        ):
            time.sleep(0.02)
        assert pool.stats()["restarts"] >= 1
        # the retired generation still holds the accepted batch
        assert pool.drain(0.5) is False
        assert not stuck.done()
        # ... which is answered the moment the wedge clears, and only
        # THEN does drain report clean
        wedge.set()
        assert stuck.result(timeout=10) == [1]
        assert pool.drain(10) is True


# ---------------------------------------------------------------------------
# blue/green swap (stub tier)
# ---------------------------------------------------------------------------


class TestBlueGreenSwap:
    def test_swap_under_load_answers_everything_by_exactly_one_version(
        self,
    ):
        pool = ReplicaPool(
            tag_factory(pace_s=0.003), ["d0", "d1", "d2", "d3"],
            artifact_ref="vN", version="v0001",
        )
        batcher = MicroBatcher(
            pool.submit, max_batch=4, max_queue=256, max_delay_ms=1.0
        )
        results, errors = [], []
        stop = threading.Event()

        def load():
            i = 0
            while not stop.is_set():
                try:
                    f = batcher.submit(i)
                    results.append(f.result(timeout=10))
                except LoadShedError as e:
                    errors.append(e)
                i += 1

        threads = [threading.Thread(target=load) for _ in range(8)]
        for t in threads:
            t.start()
        time.sleep(0.1)
        status = pool.swap("vN+1", "v0002")
        time.sleep(0.1)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert status["state"] == SWAP_DONE
        assert status["replicas_shifted"] == 4
        # zero shed caused by the swap, every request answered by
        # exactly one version, and both versions actually served
        assert errors == []
        versions = {r[0] for r in results}
        assert versions == {"vN", "vN+1"}
        assert all(r.version == "v0002" for r in pool.replicas)
        by = pool.stats()["completed_by_version"]
        assert set(by) == {"v0001", "v0002"}
        assert sum(by.values()) == len(results)
        assert batcher.drain(10) and pool.drain(10)

    def test_failed_standby_keeps_old_version_serving(self):
        calls = {"n": 0}

        def factory(ref, device):
            if ref == "bad":
                raise RuntimeError("corrupt artifact")
            calls["n"] += 1
            return lambda payloads: list(payloads)

        pool = ReplicaPool(factory, ["d0", "d1"], version="v0001")
        with pytest.raises(RuntimeError, match="corrupt artifact"):
            pool.swap("bad", "v0002")
        assert pool.swap_status()["state"] == SWAP_FAILED
        assert pool.version == "v0001"
        assert all(r.version == "v0001" for r in pool.replicas)
        # and it still serves
        assert pool.submit([7]).result(timeout=5) == [7]
        assert pool.drain(10)

    def test_one_swap_at_a_time(self):
        gate = threading.Event()

        def factory(ref, device):
            if ref == "slow":
                gate.wait(timeout=10)  # slow standby build
            return lambda payloads: list(payloads)

        pool = ReplicaPool(factory, ["d0"], version="v0001")
        t = threading.Thread(
            target=lambda: pool.swap("slow", "v0002"), daemon=True
        )
        t.start()
        time.sleep(0.1)
        with pytest.raises(RuntimeError, match="already in progress"):
            pool.swap("other", "v0003")
        gate.set()
        t.join(timeout=10)
        assert pool.version == "v0002"
        assert pool.drain(10)


# ---------------------------------------------------------------------------
# /admin routes (real sockets, stub pool — conftest http_frontend)
# ---------------------------------------------------------------------------


class TestAdminEndpoints:
    def test_no_pool_is_404(self, http_frontend):
        fe = http_frontend()
        status, _, payload = _request(fe, "GET", "/admin/replicas")
        assert status == 404 and "no replica pool" in payload["error"]

    def test_replicas_swap_status_and_trigger(
        self, http_frontend, tmp_path
    ):
        pool = ReplicaPool(
            tag_factory(), ["d0", "d1"], version="v0001"
        )
        admin = PoolAdmin(pool)
        fe = http_frontend(admin=admin)
        status, _, payload = _request(fe, "GET", "/admin/replicas")
        assert status == 200
        assert payload["n_replicas"] == 2
        assert [r["state"] for r in payload["replicas"]] == [
            READY, READY,
        ]
        status, _, payload = _request(fe, "GET", "/admin/swap")
        assert status == 200 and payload["current"]["state"] == "idle"
        # bad bodies fail explicitly
        status, _, payload = _request(
            fe, "POST", "/admin/swap", body=b"not json"
        )
        assert status == 400
        status, _, payload = _request(
            fe, "POST", "/admin/swap", body=json.dumps({"version": 1}).encode()
        )
        assert status == 400  # no registry configured
        status, _, payload = _request(
            fe, "POST", "/admin/swap",
            body=json.dumps({"artifact": str(tmp_path / "nope")}).encode(),
        )
        assert status == 404
        # a real target dir: 202, then the rollout completes
        target = tmp_path / "v0002"
        target.mkdir()
        status, _, payload = _request(
            fe, "POST", "/admin/swap",
            body=json.dumps({"artifact": str(target)}).encode(),
        )
        assert status == 202 and payload["accepted"] is True
        assert admin.wait(timeout=10)
        report = admin.swap_report()
        assert report["performed"] is True
        assert report["version_to"] == "v0002"
        assert report["shed"] == 0
        assert pool.version == "v0002"
        assert pool.drain(10)

    def test_concurrent_swap_is_409(self, http_frontend, tmp_path):
        gate = threading.Event()

        def factory(ref, device):
            if str(ref).endswith("slow"):
                gate.wait(timeout=10)
            return lambda payloads: list(payloads)

        pool = ReplicaPool(factory, ["d0", "d1"], version="v0001")
        admin = PoolAdmin(pool)
        fe = http_frontend(admin=admin)
        slow = tmp_path / "slow"
        slow.mkdir()
        other = tmp_path / "other"
        other.mkdir()
        status, _, _ = _request(
            fe, "POST", "/admin/swap",
            body=json.dumps({"artifact": str(slow)}).encode(),
        )
        assert status == 202
        time.sleep(0.1)
        status, _, payload = _request(
            fe, "POST", "/admin/swap",
            body=json.dumps({"artifact": str(other)}).encode(),
        )
        assert status == 409
        gate.set()
        assert admin.wait(timeout=10)
        assert pool.drain(10)


class TestRestartShiftRace:
    def test_restart_never_clobbers_a_completed_shift(self):
        """Interleave pinned: the health monitor restarts a replica the
        swap loop is shifting, and the SHIFT COMPLETES (runner swapped,
        state written READY) while the restart is still running. The
        restart's final state write must not resurrect SHIFTING — that
        replica would be healthy but excluded from dispatch forever
        (with one replica: every submit sheds 'no healthy replica')."""
        from bdbnn_tpu.serve.pool import SHIFTING

        pool = ReplicaPool(tag_factory(), ["d0"], version="v0001")
        try:
            r = pool.replicas[0]
            with r._lock:
                r.state = SHIFTING  # the swap loop owns the replica
            orig = r.start_worker

            def racing_start_worker():
                orig()
                # the swap loop finishes the shift mid-restart
                r.swap_runner(tag_factory()("v2", "d0"), "v0002")
                with r._lock:
                    r.state = READY

            r.start_worker = racing_start_worker
            pool._restart_replica(r, "wedged")
            assert r.state == READY
            # and the pool still dispatches to it
            assert pool.submit([1]).result(timeout=5)
        finally:
            assert pool.drain(10)

    def test_restart_mid_shift_leaves_the_swap_loop_owning_state(self):
        """The complementary case: the shift has NOT completed — the
        restart must hand the replica back SHIFTING (out of the
        dispatch set), because the swap loop owns its return to
        READY."""
        from bdbnn_tpu.serve.pool import SHIFTING

        pool = ReplicaPool(tag_factory(), ["d0"], version="v0001")
        try:
            r = pool.replicas[0]
            with r._lock:
                r.state = SHIFTING
            pool._restart_replica(r, "wedged")
            assert r.state == SHIFTING
        finally:
            assert pool.drain(10)


class TestShedUnits:
    def test_shed_counts_batches_and_requests(self):
        """`shed` counts rejected BATCHES, `shed_requests` the requests
        inside them — the swap report and verdict ledger read the
        latter so a nonzero swap.shed is never a mixed-unit
        undercount."""
        gate = threading.Event()

        def factory(ref, device):
            def runner(payloads):
                gate.wait(timeout=10)
                return list(payloads)

            return runner

        pool = ReplicaPool(factory, ["d0"], max_queue_batches=1)
        try:
            pool.submit([1])  # picked up by the worker, blocks on gate
            deadline = time.monotonic() + 5
            while pool.replicas[0].busy_since is None:
                assert time.monotonic() < deadline
                time.sleep(0.005)
            pool.submit([2])  # fills the one-batch queue
            with pytest.raises(LoadShedError, match="queue full"):
                pool.submit([3, 4, 5, 6, 7])
            s = pool.stats()
            assert s["shed"] == 1
            assert s["shed_requests"] == 5
        finally:
            gate.set()
            assert pool.drain(10)


class TestStartSwapIsTotal:
    """``start_swap`` must convert EVERY resolution failure into an
    HTTP error pair: an escaped exception would kill the scheduled
    swap-trigger thread before ``note_request_failed`` runs, nulling
    the verdict's swap block and silently skipping the zero-downtime
    gate (and on the admin route it would tear the client's
    connection instead of answering)."""

    class _TornRegistry:
        # index entry present, version dir torn after publish:
        # _file_sha256(artifact.json) raises FileNotFoundError
        def resolve(self, version):
            raise FileNotFoundError(
                f"v{version:04d}: artifact.json gone"
            )

        def label(self, version):
            return f"v{version:04d}"

    class _BrokenRegistry:
        def resolve(self, version):
            raise TypeError("unexpected resolution failure")

        def label(self, version):
            return f"v{version:04d}"

    def test_torn_version_is_404(self):
        pool = ReplicaPool(tag_factory(), ["d0"], version="v0001")
        try:
            admin = PoolAdmin(pool, registry=self._TornRegistry())
            status, payload = admin.start_swap({"version": 2})
            assert status == 404
            assert "artifact.json gone" in payload["error"]
        finally:
            assert pool.drain(10)

    def test_unexpected_resolution_failure_is_400(self):
        pool = ReplicaPool(tag_factory(), ["d0"], version="v0001")
        try:
            admin = PoolAdmin(pool, registry=self._BrokenRegistry())
            status, payload = admin.start_swap({"version": 2})
            assert status == 400
            assert "unexpected" in payload["error"]
        finally:
            assert pool.drain(10)

    def test_single_replica_swap_is_409(self, tmp_path):
        """The guard ServeHttpConfig.validate applies to --swap-at,
        applied to the admin route too: a blue/green shift with one
        replica has no peer to absorb traffic, so the 'zero-downtime'
        rollout is a guaranteed shed window. The operator gets told,
        not served an outage."""
        pool = ReplicaPool(tag_factory(), ["d0"], version="v0001")
        try:
            admin = PoolAdmin(pool)
            target = tmp_path / "v0002"
            target.mkdir()
            status, payload = admin.start_swap(
                {"artifact": str(target)}
            )
            assert status == 409
            assert "--replicas >= 2" in payload["error"]
            # the pool was never touched and still serves v0001
            assert pool.version == "v0001"
            assert pool.submit([3]).result(timeout=5)
        finally:
            assert pool.drain(10)


class TestFutureDeliveredShedReason:
    def test_queue_full_on_the_future_ledgers_as_queue_full(
        self, http_frontend
    ):
        """The pooled runner sheds INSIDE the batcher worker (every
        replica queue full / no healthy replica) — the LoadShedError
        arrives on the request future, AFTER submit succeeded. The
        per-priority ledger must record the real reason
        (shed_queue_full): a verdict blaming drain on a run that never
        drained points triage at the wrong layer."""

        def runner(batch):
            raise LoadShedError("queue full")

        fe = http_frontend(runner)
        status, _, payload = _request(
            fe, "POST", "/v1/predict", body=b"[1.0]",
            headers={"x-priority": "0"},
        )
        assert status == 503 and payload["error"] == "queue full"
        counts = fe.accounting()["counts_by_priority"][0]
        assert counts["shed_queue_full"] == 1
        assert counts["shed_draining"] == 0

    def test_no_healthy_replica_ledgers_as_unavailable(
        self, http_frontend
    ):
        """A total pool outage is not backpressure: 'no healthy
        replica' gets its own ledger column (shed_unavailable), so an
        operator triaging the incident reads 'zero healthy replicas',
        never 'overload'."""

        def runner(batch):
            raise LoadShedError("no healthy replica")

        fe = http_frontend(runner)
        status, _, payload = _request(
            fe, "POST", "/v1/predict", body=b"[1.0]",
            headers={"x-priority": "0"},
        )
        assert status == 503
        assert payload["error"] == "no healthy replica"
        counts = fe.accounting()["counts_by_priority"][0]
        assert counts["shed_unavailable"] == 1
        assert counts["shed_queue_full"] == 0
        assert counts["shed_draining"] == 0


# ---------------------------------------------------------------------------
# verdict v3 + compare judging
# ---------------------------------------------------------------------------


def _v3_verdict(tmp_path, name, *, efficiency, swap_shed=None,
                dropped=0, thr=1000.0, performed=True):
    from bdbnn_tpu.serve.loadgen import slo_verdict

    scaling = None
    if efficiency is not None:
        scaling = {
            "replicas": [1, 8],
            "throughput_rps": {"1": thr / 8 / efficiency, "8": thr},
            "efficiency": efficiency,
            "monotone": True,
            "paced_ms": None,
        }
    swap = None
    if swap_shed is not None:
        swap = {
            "performed": performed,
            "state": "done" if performed else "failed",
            "version_from": "v0001", "version_to": "v0002",
            "seconds": 1.0, "replicas_shifted": 8,
            "shed": swap_shed, "error": None,
            "answered_by": {"v0001": 10, "v0002": 10},
        }
    v = slo_verdict(
        {"submitted": 20, "completed": 20 - dropped, "shed": 0,
         "failed": 0, "wall_s": 1.0,
         "latencies_ms": [1.0, 2.0, 3.0]},
        {"mean_occupancy": 0.9, "batches": 4,
         "max_queue_depth_seen": 2, "max_queue": 64},
        mode="open", rate=100.0, seed=0,
        provenance={"recipe": {"arch": "resnet8_tiny"}},
        scaling=scaling, swap=swap,
        client={"dropped": dropped} if swap is not None else None,
    )
    path = tmp_path / name
    path.write_text(json.dumps(v))
    return str(path)


class TestVerdictV3Compare:
    def test_schema_version_and_null_blocks(self, tmp_path):
        from bdbnn_tpu.serve.loadgen import slo_verdict

        v = slo_verdict(
            {"submitted": 1, "completed": 1, "shed": 0, "wall_s": 1.0,
             "latencies_ms": [1.0]},
            {}, mode="open", rate=1.0, seed=0,
        )
        assert v["serve_verdict"] == 8
        # v1/v2 consumers: the v3 blocks exist but are null
        assert v["replicas"] is None
        assert v["scaling"] is None and v["swap"] is None
        # and the v4 attribution block is null when tracing is off
        assert v["attribution"] is None
        # ... and the v5 canary block is null when no canary stage ran
        assert v["canary"] is None

    def test_scaling_efficiency_regression_judged(self, tmp_path):
        from bdbnn_tpu.obs.compare import compare_runs

        base = _v3_verdict(tmp_path, "base.json", efficiency=0.9)
        cand = _v3_verdict(
            tmp_path, "cand.json", efficiency=0.5, thr=555.0
        )
        result = compare_runs([base, cand], tol_rel=0.10)
        rows = {
            m["metric"]: m
            for m in result["comparisons"][0]["metrics"]
        }
        assert rows["serve_scaling_efficiency"]["verdict"] == "regression"
        assert result["verdict"] == "regression"

    def test_swap_dropped_zero_tolerance(self, tmp_path):
        from bdbnn_tpu.obs.compare import compare_runs

        base = _v3_verdict(
            tmp_path, "base.json", efficiency=None, swap_shed=0
        )
        cand = _v3_verdict(
            tmp_path, "cand.json", efficiency=None, swap_shed=1,
        )
        result = compare_runs(
            [base, cand], tol_rel=10.0,  # huge rel tolerance
        )
        rows = {
            m["metric"]: m
            for m in result["comparisons"][0]["metrics"]
        }
        # one lost request can never be tolerated away
        assert rows["serve_swap_dropped"]["verdict"] == "regression"

    def test_unperformed_swap_scores_nonzero(self, tmp_path):
        """A rollout that never completed must not score 0 and slip
        past the zero-tolerance gate just because traffic stayed on
        vN (0 client drops, 0 sheds)."""
        from bdbnn_tpu.obs.compare import _serve_metrics, compare_runs

        with open(_v3_verdict(
            tmp_path, "failed.json", efficiency=None, swap_shed=0,
            performed=False,
        )) as f:
            v = json.load(f)
        assert _serve_metrics(v)["serve_swap_dropped"] == 1
        base = _v3_verdict(
            tmp_path, "base.json", efficiency=None, swap_shed=0
        )
        result = compare_runs([base, str(tmp_path / "failed.json")])
        rows = {
            m["metric"]: m
            for m in result["comparisons"][0]["metrics"]
        }
        assert rows["serve_swap_dropped"]["verdict"] == "regression"

    def test_client_drops_count_against_swap(self, tmp_path):
        from bdbnn_tpu.obs.compare import _serve_metrics

        with open(_v3_verdict(
            tmp_path, "v.json", efficiency=None, swap_shed=0, dropped=2,
        )) as f:
            v = json.load(f)
        assert _serve_metrics(v)["serve_swap_dropped"] == 2

    def test_v2_shape_leaves_v3_metrics_unjudged(self, tmp_path):
        from bdbnn_tpu.obs.compare import _serve_metrics

        assert _serve_metrics({"p99_ms": 5.0})[
            "serve_scaling_efficiency"] is None
        assert _serve_metrics({"p99_ms": 5.0})["serve_swap_dropped"] is None


# ---------------------------------------------------------------------------
# watch: live per-replica table + swap-progress banner
# ---------------------------------------------------------------------------


class TestWatchReplicaMode:
    def _base_events(self):
        return [
            {"t": 100.0, "kind": "http", "phase": "start",
             "host": "127.0.0.1", "port": 9, "arch": "resnet8_tiny",
             "priorities": 3, "queue_depth": 64, "buckets": [4]},
            {"t": 101.0, "kind": "replica", "phase": "stats",
             "version": "v0001", "completed": 120, "restarts": 1,
             "completed_by_version": {"v0001": 120},
             "swap": {"state": "shifting", "replicas_shifted": 1,
                      "replicas_total": 2},
             "replicas": [
                 {"replica": 0, "device": "TFRT_CPU_0",
                  "version": "v0002", "state": "ready",
                  "queue_depth": 1, "completed": 70},
                 {"replica": 1, "device": "TFRT_CPU_1",
                  "version": "v0001", "state": "shifting",
                  "queue_depth": 0, "completed": 50},
             ]},
        ]

    def test_live_table_and_swap_banner(self):
        from bdbnn_tpu.obs.watch import render_status

        events = self._base_events() + [
            {"t": 101.5, "kind": "swap", "phase": "shift",
             "replica": 0, "version_from": "v0001",
             "version_to": "v0002"},
        ]
        status = render_status(events, None)
        # one row per replica: version, health state, queue, completed
        assert "TFRT_CPU_0" in status and "TFRT_CPU_1" in status
        assert "shifting" in status and "ready" in status
        assert "SWAP in progress: v0001 -> v0002" in status
        assert "[1/2 shifted]" in status

    def test_failed_swap_banner(self):
        from bdbnn_tpu.obs.watch import render_status

        events = self._base_events() + [
            {"t": 102.0, "kind": "swap", "phase": "failed",
             "version_to": "v0002", "error": "corrupt artifact"},
        ]
        status = render_status(events, None)
        assert "swap to v0002 FAILED" in status
        assert "old version kept serving" in status

    def test_rejected_trigger_is_terminal_not_in_progress(self):
        """A scheduled trigger the admin REFUSED (torn version -> 404,
        bad spec -> 400) emits only phase='trigger' with the HTTP
        status — no start/failed event ever follows, so an in-progress
        banner would stick for the rest of the run."""
        from bdbnn_tpu.obs.watch import render_status

        events = self._base_events() + [
            {"t": 101.5, "kind": "swap", "phase": "trigger",
             "at_request": 250, "of": 1000, "status": 404,
             "error": "v0002: artifact.json gone"},
        ]
        status = render_status(events, None)
        assert "SWAP in progress" not in status
        assert "REJECTED (HTTP 404)" in status
        assert "artifact.json gone" in status
        # an ACCEPTED trigger still renders as in-progress
        events[-1] = {"t": 101.5, "kind": "swap", "phase": "trigger",
                      "at_request": 250, "of": 1000, "status": 202,
                      "accepted": True, "version_to": "v0002"}
        assert "SWAP in progress" in render_status(events, None)


# ---------------------------------------------------------------------------
# the scaling sweep through the real serve-bench orchestration (paced)
# ---------------------------------------------------------------------------


class TestScalingSweep:
    def test_paced_sweep_monotone_with_efficiency(
        self, exported_artifact, tmp_path
    ):
        """serve-bench --replicas 1 2 4 8 (in-process, paced): monotone
        throughput, efficiency >= 0.7 at 8 replicas, verdict + events
        + summarize/watch/compare all consume the v3 shape.

        Quarantined behind conftest.retry_once_flaky (the ONE bounded
        retry-once policy). TRACKING NOTE: PR 9 recorded ONE in-suite
        transient (efficiency 0.55 during a full tier-1 pass on a
        contended box; passes in isolation and on rerun) — the paced
        operating point measures wall-clock parallelism, which a
        loaded host cannot always deliver. A deterministic regression
        (broken dispatch, verdict schema, event shapes) fails BOTH
        attempts."""
        from conftest import retry_once_flaky

        retry_once_flaky(
            lambda i: self._paced_sweep_attempt(
                exported_artifact, tmp_path / f"a{i + 1}"
            ),
            note=(
                "paced scaling sweep attempt 1 failed "
                "(timing-sensitive transient on contended boxes, PR 9 "
                "note)"
            ),
        )

    def _paced_sweep_attempt(self, exported_artifact, tmp_path):
        from bdbnn_tpu.configs.config import ServeBenchConfig
        from bdbnn_tpu.obs.compare import compare_runs
        from bdbnn_tpu.obs.events import read_events
        from bdbnn_tpu.obs.summarize import summarize_run
        from bdbnn_tpu.serve.loadgen import run_serve_bench

        tmp_path.mkdir(parents=True, exist_ok=True)
        art_dir, _ = exported_artifact
        # operating point tuned for a GIL-shared host: service time
        # (40ms/batch) well above the serial batch-assembly cost, and
        # closed-loop concurrency at 2x the largest pool's in-flight
        # capacity (8 replicas x 4/batch) so the pool, not the client,
        # is the bottleneck being measured
        cfg = ServeBenchConfig(
            artifact=art_dir,
            log_path=str(tmp_path / "serve"),
            mode="closed",
            requests=240,
            concurrency=64,
            buckets=(4,),
            queue_depth=512,
            max_delay_ms=8.0,
            seed=0,
            replicas=(1, 2, 4, 8),
            pace_ms=40.0,
            out=str(tmp_path / "verdict.json"),
        )
        res = run_serve_bench(cfg)
        v = res["verdict"]
        assert v["serve_verdict"] == 8
        scaling = v["scaling"]
        assert scaling["replicas"] == [1, 2, 4, 8]
        assert scaling["monotone"] is True, scaling
        assert scaling["efficiency"] >= 0.7, scaling
        thr = scaling["throughput_rps"]
        assert thr["8"] > thr["1"]
        # the last (8-replica) pass's pool table rode into the verdict
        assert v["replicas"]["n"] == 8
        assert sum(
            r["completed"] for r in v["replicas"]["per_replica"]
        ) == 240
        assert v["requests_completed"] == 240 and v["requests_shed"] == 0
        # telemetry: one scaling event per N + replica lifecycle events
        serves = read_events(res["run_dir"], "serve")
        ns = [
            e["replicas_n"] for e in serves
            if e.get("phase") == "scaling"
        ]
        assert ns == [1, 2, 4, 8]
        assert len(read_events(res["run_dir"], "replica")) >= 15
        # summarize renders the scaling line; compare self-passes and
        # extracts the efficiency
        report, summary = summarize_run(res["run_dir"])
        assert "scaling:" in report and "efficiency" in report
        sv = summary["serving"]["verdict"]
        assert sv["scaling"]["efficiency"] == scaling["efficiency"]
        result = compare_runs(
            [str(tmp_path / "verdict.json"), str(tmp_path / "verdict.json")]
        )
        assert result["verdict"] == "pass"
        rows = {
            m["metric"]: m
            for m in result["comparisons"][0]["metrics"]
        }
        assert rows["serve_scaling_efficiency"]["baseline"] == (
            scaling["efficiency"]
        )

    @pytest.mark.slow
    def test_cli_sweep_subprocess_on_8_device_mesh(
        self, exported_artifact, tmp_path, sim_device_subprocess
    ):
        """The acceptance command line, end to end in a fresh 8-device
        subprocess: `serve-bench ART --replicas 1 2 4 8`."""
        art_dir, _ = exported_artifact
        out = str(tmp_path / "verdict.json")
        proc = sim_device_subprocess(
            [
                "-m", "bdbnn_tpu.cli", "serve-bench", art_dir,
                "--log-path", str(tmp_path / "serve"),
                "--mode", "closed",
                "--requests", "240", "--concurrency", "64",
                "--buckets", "4", "--queue-depth", "512",
                "--max-delay-ms", "8",
                "--replicas", "1", "2", "4", "8",
                "--pace-ms", "40",
                "--out", out,
            ],
            devices=8, timeout=540,
        )
        assert proc.returncode == 0, (
            f"rc={proc.returncode}\nstdout:{proc.stdout[-1500:]}\n"
            f"stderr:{proc.stderr[-3000:]}"
        )
        with open(out) as f:
            v = json.load(f)
        assert v["scaling"]["monotone"] is True
        assert v["scaling"]["efficiency"] >= 0.7


# ---------------------------------------------------------------------------
# real engines on real mesh devices
# ---------------------------------------------------------------------------


class TestPoolRealEngines:
    def test_engines_placed_per_device_match_single_engine(
        self, exported_artifact
    ):
        import jax

        from bdbnn_tpu.parallel.mesh import replica_devices
        from bdbnn_tpu.serve.engine import InferenceEngine

        art_dir, _ = exported_artifact
        devices = list(replica_devices(2))
        assert devices[0] != devices[1]
        factory = make_engine_runner_factory((4,))
        pool = ReplicaPool(
            factory, devices, artifact_ref=art_dir, version="v0001"
        )
        # device labels really are two different mesh devices
        labels = {r["device"] for r in pool.stats()["replicas"]}
        assert len(labels) == 2
        rng = np.random.default_rng(0)
        x = rng.standard_normal((4, 32, 32, 3)).astype(np.float32)
        want = InferenceEngine(art_dir, buckets=(4,)).predict_logits(x)
        futs = [pool.submit([x[i] for i in range(4)]) for _ in range(4)]
        for f in futs:
            got = np.asarray(f.result(timeout=60))
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
        assert pool.drain(30)
        # work executed on BOTH replicas
        assert all(
            r["batches"] >= 1 for r in pool.stats()["replicas"]
        )

    def test_replica_devices_contract(self):
        import jax

        from bdbnn_tpu.parallel.mesh import make_mesh, replica_devices

        n = jax.device_count()
        devs = replica_devices(n)
        assert len(set(devs)) == n
        with pytest.raises(ValueError, match="one engine per device"):
            replica_devices(n + 1)
        with pytest.raises(ValueError, match="n >= 1"):
            replica_devices(0)
        # mesh-aware order walks the data axis first
        mesh = make_mesh(model_parallel=2)
        first = replica_devices(n // 2, mesh)
        data_axis = [row[0] for row in np.asarray(mesh.devices)]
        assert list(first) == data_axis


# ---------------------------------------------------------------------------
# THE acceptance e2e: swap under flash crowd, real sockets, real engines
# ---------------------------------------------------------------------------


def _pool_http_cfg(artifact, registry, tmp_path, **kw):
    from bdbnn_tpu.configs.config import ServeHttpConfig

    # a ~10s flash-crowd schedule with the swap fired a quarter in: the
    # standby engines AOT-warm (seconds on CPU) while vN serves the
    # burst, the shift lands mid-schedule, and a meaningful tail of
    # traffic is answered by vN+1
    base = dict(
        artifact=artifact,
        registry=registry,
        log_path=str(tmp_path / "http"),
        replicas=2,
        buckets=(4,),
        queue_depth=128,
        max_delay_ms=2.0,
        priorities=3,
        default_quota="100000:100000",
        scenario="flash_crowd",
        rate=30.0,
        flash_factor=3.0,
        requests=300,
        concurrency=8,
        seed=11,
        swap_to="v0002",
        swap_at=0.25,
        stats_interval_s=0.25,
    )
    base.update(kw)
    return ServeHttpConfig(**base)


class TestSwapUnderFlashCrowdEndToEnd:
    @pytest.fixture(scope="class")
    def swap_run(self, exported_artifact, tmp_path_factory):
        """ONE flash-crowd run against a 2-replica pool of real AOT
        engines with a registry-resolved v0001 -> v0002 hot swap fired
        at 35% of the schedule — shared by the assertions below."""
        from bdbnn_tpu.serve.http import run_serve_http

        art_dir, _ = exported_artifact
        tmp_path = tmp_path_factory.mktemp("swap_e2e")
        reg_root = str(tmp_path / "registry")
        reg = ArtifactRegistry(reg_root)
        reg.publish(art_dir)  # v0001 — what we serve first
        reg.publish(art_dir)  # v0002 — the rollout target
        cfg = _pool_http_cfg("v0001", reg_root, tmp_path)
        res = run_serve_http(cfg)
        return res

    def test_zero_dropped_and_ledger_identity(self, swap_run):
        v = swap_run["verdict"]
        # the client-side cross-check: every offered request got SOME
        # response — none dropped, before, during or after the swap
        assert v["client"]["dropped"] == 0
        assert v["client"]["responses"] == v["client"]["submitted"] == 300
        # the server-side ledger identity survives the swap
        assert (
            v["requests_completed"] + v["requests_shed"]
            + v["requests_failed"] + v["requests_rejected"]
            == v["requests_submitted"]
        )
        assert v["requests_failed"] == 0 and v["requests_rejected"] == 0
        assert v["drained_clean"] is True

    def test_swap_performed_with_zero_shed_and_both_versions_serving(
        self, swap_run
    ):
        v = swap_run["verdict"]
        swap = v["swap"]
        assert swap["performed"] is True
        assert swap["version_from"] == "v0001"
        assert swap["version_to"] == "v0002"
        assert swap["replicas_shifted"] == 2
        # ZERO requests shed because of (or during) the rollout
        assert swap["shed"] == 0
        # every completed request was answered by exactly one version,
        # and BOTH versions actually served traffic
        by = swap["answered_by"]
        assert set(by) == {"v0001", "v0002"}
        assert all(n > 0 for n in by.values())
        assert sum(by.values()) == v["requests_completed"]
        # the final replica table shows the whole pool on v0002
        assert v["replicas"]["n"] == 2
        assert all(
            r["version"] == "v0002"
            for r in v["replicas"]["per_replica"]
        )
        assert v["serve_verdict"] == 8

    def test_events_watch_summarize_compare_consume_the_swap(
        self, swap_run, tmp_path
    ):
        from bdbnn_tpu.obs.compare import compare_runs, extract_run
        from bdbnn_tpu.obs.events import read_events
        from bdbnn_tpu.obs.summarize import summarize_run
        from bdbnn_tpu.obs.watch import render_status

        run_dir = swap_run["run_dir"]
        swaps = read_events(run_dir, "swap")
        phases = [e.get("phase") for e in swaps]
        for expected in ("trigger", "start", "warm", "shift", "done"):
            assert expected in phases, phases
        # replica lifecycle + live table events landed
        replicas = read_events(run_dir, "replica")
        assert sum(
            1 for e in replicas if e.get("phase") == "start"
        ) >= 2
        # watch renders the verdict's swap line
        status = render_status(read_events(run_dir), None)
        assert "swap: v0001 -> v0002 DONE" in status
        # summarize renders swap + replica lines and the ledger
        report, summary = summarize_run(run_dir)
        assert "swap: v0001 -> v0002 DONE" in report
        assert "answered by: v0001" in report
        assert summary["serving"]["verdict"]["swap"]["shed"] == 0
        # compare: the run dir extracts with serve_swap_dropped == 0
        # and self-compares clean
        rec = extract_run(run_dir)
        assert rec["metrics"]["serve_swap_dropped"] == 0
        assert compare_runs([run_dir, run_dir])["verdict"] == "pass"
