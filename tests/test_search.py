"""Recipe-search harness tests (bdbnn_tpu/search/ + the `search` CLI).

Three tiers:

- unit: SearchConfig validation/grid expansion, the integrity-digested
  TrialLedger (round-trip, tamper -> ``.old`` fallback, both-torn ->
  refusal), leaderboard ranking determinism over synthetic ledgers,
  and the compare extraction paths (leaderboard artifact + sweep dir,
  clean skips for non-search sources);
- e2e (THE acceptance): a >=3-trial sweep over >=2 binarizer families
  through the REAL CLI completes with a deterministic strict-JSON
  leaderboard, and the SIGTERM-mid-sweep -> exit 75 -> ``--resume``
  variant reaches the SAME ranking/winner WITHOUT re-running completed
  trials (ledger attempts + run-dir counts prove it);
- the sweep's events are consumed by watch/summarize (rendering pins).
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from bdbnn_tpu.configs.config import SearchConfig
from bdbnn_tpu.search.harness import (
    LEADERBOARD_NAME,
    LEDGER_NAME,
    TrialLedger,
    build_leaderboard,
    search_digest,
    sweep_config_hash,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the shared smoke-sweep recipe: tiny synthetic budget, three trials
# over three families — the acceptance floor (>=3 trials, >=2 families)
SWEEP_TRIALS = ["ste@0.05", "proximal@0.05", "stochastic@0.05"]


def _sweep_cfg(out_dir, **kw):
    base = dict(
        out_dir=str(out_dir),
        trials=tuple(SWEEP_TRIALS),
        arch="resnet8_tiny",
        epochs=1,
        batch_size=16,
        print_freq=2,
        synthetic=True,
        synthetic_train_size=64,
        synthetic_val_size=64,
        seed=0,
    )
    base.update(kw)
    return SearchConfig(**base)


def _search_argv(out_dir, resume=False):
    argv = [
        sys.executable, "-m", "bdbnn_tpu.cli", "search",
        "--out-dir", str(out_dir),
        "-a", "resnet8_tiny", "--epochs", "1", "-b", "16", "-p", "2",
        "--synthetic", "--synthetic-train-size", "64",
        "--synthetic-val-size", "64", "--seed", "0",
    ]
    for t in SWEEP_TRIALS:
        argv += ["--trial", t]
    if resume:
        argv.append("--resume")
    return argv


def _env():
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
    )
    return env


class TestSearchConfig:
    def test_grid_expansion_family_major_and_stable(self):
        cfg = SearchConfig(
            out_dir="x", families=("ste", "ede"), lrs=(0.1, 0.05),
            synthetic=True,
        ).validate()
        trials = cfg.expand_trials()
        assert [t[0] for t in trials] == [
            "t000_ste_lr0.1", "t001_ste_lr0.05",
            "t002_ede_lr0.1", "t003_ede_lr0.05",
        ]
        assert trials == cfg.expand_trials()  # deterministic

    def test_explicit_trials_replace_grid(self):
        cfg = SearchConfig(
            out_dir="x", trials=("proximal:delta1=0.25@0.1",),
            synthetic=True,
        ).validate()
        ((tid, spec, lr),) = cfg.expand_trials()
        assert tid == "t000_proximal_lr0.1"
        assert spec == "proximal:delta1=0.25" and lr == 0.1

    def test_unknown_family_rejected_at_config_time(self):
        with pytest.raises(ValueError, match="unknown binarizer family"):
            SearchConfig(
                out_dir="x", families=("nope",), synthetic=True
            ).validate()

    def test_bad_trial_specs_rejected(self):
        with pytest.raises(ValueError, match="FAMILY"):
            SearchConfig(
                out_dir="x", trials=("ste",), synthetic=True
            ).validate()
        with pytest.raises(ValueError, match="not a number"):
            SearchConfig(
                out_dir="x", trials=("ste@fast",), synthetic=True
            ).validate()
        with pytest.raises(ValueError, match="LR must be > 0"):
            SearchConfig(
                out_dir="x", trials=("ste@0",), synthetic=True
            ).validate()

    def test_needs_data_or_synthetic(self):
        with pytest.raises(ValueError, match="synthetic"):
            SearchConfig(out_dir="x").validate()

    def test_resume_flag_does_not_change_sweep_identity(self):
        a = _sweep_cfg("x")
        b = _sweep_cfg("x", resume=True, out="somewhere.json")
        assert sweep_config_hash(a) == sweep_config_hash(b)
        c = _sweep_cfg("x", seed=1)
        assert sweep_config_hash(a) != sweep_config_hash(c)


class TestTrialLedger:
    def _init(self, tmp_path):
        ledger = TrialLedger(str(tmp_path))
        ledger.init_trials(
            (("t000_a", "ste", 0.1), ("t001_b", "ede", 0.1)), "hash1"
        )
        return ledger

    def test_round_trip(self, tmp_path):
        ledger = self._init(tmp_path)
        ledger.mark(
            "t000_a", "done", metrics={"best_top1": 12.5}, attempts=1
        )
        fresh = TrialLedger(str(tmp_path))
        assert fresh.load()
        assert fresh.config_hash == "hash1"
        assert fresh.status("t000_a") == "done"
        assert fresh.entry("t000_a")["metrics"]["best_top1"] == 12.5
        assert fresh.status("t001_b") == "pending"

    def test_tampered_ledger_falls_back_to_old(self, tmp_path):
        ledger = self._init(tmp_path)
        # a second commit displaces the first into .old
        ledger.mark("t000_a", "done", metrics={"best_top1": 10.0})
        path = os.path.join(str(tmp_path), LEDGER_NAME)
        data = json.load(open(path))
        data["trials"]["t000_a"]["metrics"]["best_top1"] = 99.9  # tamper
        json.dump(data, open(path, "w"))
        fresh = TrialLedger(str(tmp_path))
        assert fresh.load()
        # the tampered commit failed verification; .old (the pre-mark
        # state) was restored instead of trusting doctored metrics
        assert fresh.loaded_from == path + ".old"
        assert fresh.status("t000_a") == "pending"

    def test_swapped_entries_fail_verification(self, tmp_path):
        """The trial ID is bound into each entry's digest: exchanging
        two trials' bodies (mis-attributing one recipe's results to
        another) must fail verification, not just body corruption."""
        ledger = self._init(tmp_path)
        ledger.mark("t000_a", "done", metrics={"best_top1": 99.0})
        path = os.path.join(str(tmp_path), LEDGER_NAME)
        data = json.load(open(path))
        a, b = data["trials"]["t000_a"], data["trials"]["t001_b"]
        data["trials"]["t000_a"], data["trials"]["t001_b"] = b, a
        json.dump(data, open(path, "w"))
        fresh = TrialLedger(str(tmp_path))
        assert fresh.load()
        assert fresh.loaded_from == path + ".old"  # swap rejected

    def test_both_torn_refuses(self, tmp_path):
        ledger = self._init(tmp_path)
        ledger.mark("t000_a", "done")
        path = os.path.join(str(tmp_path), LEDGER_NAME)
        open(path, "w").write("{torn")
        open(path + ".old", "w").write("also torn")
        with pytest.raises(RuntimeError, match="integrity"):
            TrialLedger(str(tmp_path)).load()

    def test_stale_running_reconciles(self, tmp_path):
        ledger = self._init(tmp_path)
        # no checkpoint anywhere -> a stale 'running' is a lost attempt
        ledger.mark("t000_a", "running", attempts=1, run_dirs=[])
        fresh = TrialLedger(str(tmp_path))
        fresh.load()
        assert fresh.reconcile_stale() == ["t000_a"]
        assert fresh.status("t000_a") == "pending"
        # with a committed checkpoint in the last run dir -> preempted
        run_dir = tmp_path / "rd"
        (run_dir / "checkpoint").mkdir(parents=True)
        fresh.mark(
            "t001_b", "running", attempts=1, run_dirs=[str(run_dir)]
        )
        again = TrialLedger(str(tmp_path))
        again.load()
        assert again.reconcile_stale() == ["t001_b"]
        assert again.status("t001_b") == "preempted"


class TestLeaderboard:
    def _ledger(self, tmp_path, rows):
        ledger = TrialLedger(str(tmp_path))
        ledger.init_trials(
            tuple((tid, fam, lr) for tid, fam, lr, *_ in rows), "h"
        )
        for tid, _fam, _lr, status, metrics, curve in rows:
            ledger.mark(
                tid, status, metrics=metrics, curve=curve, attempts=1
            )
        return ledger

    def test_ranking_order_and_ties(self, tmp_path):
        rows = [
            ("t000_a", "ste", 0.1, "done",
             {"best_top1": 50.0, "final_top1": 50.0},
             [[10.0, 1.0], [50.0, 2.0]]),
            ("t001_b", "ede", 0.1, "done",
             {"best_top1": 60.0, "final_top1": 55.0},
             [[60.0, 5.0]]),
            ("t002_c", "lab", 0.1, "done",
             {"best_top1": 50.0, "final_top1": 50.0},
             [[50.0, 1.5]]),
            ("t003_d", "approx", 0.1, "failed", None, None),
        ]
        lb = build_leaderboard(
            _sweep_cfg(str(tmp_path)), self._ledger(tmp_path, rows)
        )
        assert [r["trial"] for r in lb["ranking"]] == [
            "t001_b", "t000_a", "t002_c"  # best desc, tie -> trial id
        ]
        assert lb["winner"]["trial"] == "t001_b"
        assert lb["failed"] == 1 and lb["completed"] == 3
        # common level = min over bests = 50; ttca from each curve
        assert lb["common_acc_level"] == 50.0
        assert lb["trials"]["t000_a"]["time_to_common_acc_s"] == 2.0
        assert lb["trials"]["t001_b"]["time_to_common_acc_s"] == 5.0
        assert lb["trials"]["t002_c"]["time_to_common_acc_s"] == 1.5
        # failed trials never rank and never drag the common level
        assert "t003_d" not in [r["trial"] for r in lb["ranking"]]

    def test_resumed_trials_report_null_wall_clock(self, tmp_path):
        """A resumed trial's curve/wall are rebased to the post-resume
        run dir: its time_to_common_acc_s and wall_s must land null
        (unknowable), never a fabricated too-fast figure the compare
        gate would judge."""
        ledger = TrialLedger(str(tmp_path))
        ledger.init_trials(
            (("t000_a", "ste", 0.1), ("t001_b", "ede", 0.1)), "h"
        )
        ledger.mark(
            "t000_a", "done", attempts=1,
            metrics={"best_top1": 50.0, "final_top1": 50.0,
                     "wall_s": 30.0},
            curve=[[50.0, 30.0]],
        )
        ledger.mark(
            "t001_b", "done", attempts=2,  # crossed a preemption
            metrics={"best_top1": 60.0, "final_top1": 60.0,
                     "wall_s": 3.0},  # rebased post-resume figure
            curve=[[60.0, 3.0]],
        )
        lb = build_leaderboard(_sweep_cfg(str(tmp_path)), ledger)
        assert lb["winner"]["trial"] == "t001_b"
        assert lb["trials"]["t001_b"]["resumed"] is True
        assert lb["trials"]["t001_b"]["wall_s"] is None
        assert lb["trials"]["t001_b"]["time_to_common_acc_s"] is None
        assert lb["winner"]["time_to_common_acc_s"] is None
        # the un-resumed trial keeps its honest figures
        assert lb["trials"]["t000_a"]["wall_s"] == 30.0
        assert lb["trials"]["t000_a"]["time_to_common_acc_s"] == 30.0

    def test_no_completed_trials_has_null_winner(self, tmp_path):
        rows = [("t000_a", "ste", 0.1, "failed", None, None)]
        lb = build_leaderboard(
            _sweep_cfg(str(tmp_path)), self._ledger(tmp_path, rows)
        )
        assert lb["winner"] is None
        assert lb["ranking"] == []
        assert lb["common_acc_level"] is None

    def test_leaderboard_is_strict_json_and_deterministic(self, tmp_path):
        rows = [
            ("t000_a", "ste", 0.1, "done",
             {"best_top1": 50.0, "final_top1": float("nan")},
             [[50.0, 2.0]]),
        ]
        cfg = _sweep_cfg(str(tmp_path))
        ledger = self._ledger(tmp_path, rows)
        a = build_leaderboard(cfg, ledger)
        b = build_leaderboard(cfg, ledger)
        blob = json.dumps(a, sort_keys=True)
        assert blob == json.dumps(b, sort_keys=True)

        def no_constants(s):
            raise AssertionError(f"bare {s} token in leaderboard")

        rec = json.loads(blob, parse_constant=no_constants)
        assert rec["ranking"][0]["final_top1"] is None  # NaN -> null


class TestCompareIntegration:
    def _leaderboard(self, tmp_path, best=50.0, ttca=2.0):
        lb = {
            "search_verdict": 1,
            "provenance": {
                "config_hash": "h",
                "recipe": {"arch": "resnet8_tiny", "dataset": "cifar10",
                           "epochs": 1, "batch_size": 16},
            },
            "winner": {"trial": "t000", "family": "ste", "lr": 0.1,
                       "best_top1": best,
                       "time_to_common_acc_s": ttca},
            "ranking": [], "trials": {},
        }
        path = tmp_path / "leaderboard.json"
        path.write_text(json.dumps(lb))
        return str(path)

    def test_leaderboard_artifact_judged(self, tmp_path):
        from bdbnn_tpu.obs.compare import compare_runs, extract_run

        base = self._leaderboard(tmp_path, best=50.0, ttca=2.0)
        rec = extract_run(base)
        assert rec["format"] == "search_leaderboard"
        assert rec["metrics"]["search_best_top1"] == 50.0
        worse_dir = tmp_path / "worse"
        worse_dir.mkdir()
        worse = self._leaderboard(worse_dir, best=40.0, ttca=9.0)
        result = compare_runs([base, worse])
        rows = {
            m["metric"]: m
            for m in result["comparisons"][0]["metrics"]
        }
        assert rows["search_best_top1"]["verdict"] == "regression"
        assert rows["search_time_to_common_acc_s"]["verdict"] == (
            "regression"
        )
        assert result["verdict"] == "regression"

    def test_non_search_sources_skip_cleanly(self, tmp_path):
        """A training-run baseline knows no search metrics: no search
        row appears, in either direction."""
        from bdbnn_tpu.obs.compare import compare_runs

        base = os.path.join(
            REPO, "tests", "fixtures", "compare", "base"
        )
        lb = self._leaderboard(tmp_path, best=50.0)
        result = compare_runs([base, lb], allow_mismatch=True)
        names = {
            m["metric"]
            for m in result["comparisons"][0]["metrics"]
        }
        assert not any(n.startswith("search_") for n in names)

    def test_winnerless_leaderboard_skips(self, tmp_path):
        from bdbnn_tpu.obs.compare import extract_run

        path = tmp_path / "leaderboard.json"
        path.write_text(json.dumps({
            "search_verdict": 1, "winner": None, "ranking": [],
        }))
        rec = extract_run(str(path))
        assert rec["metrics"]["search_best_top1"] is None
        assert rec["metrics"]["search_time_to_common_acc_s"] is None


def _wait_for(predicate, timeout_s, what):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.25)
    raise AssertionError(f"timed out waiting for {what}")


def _ledger_statuses(sweep_dir):
    path = os.path.join(str(sweep_dir), LEDGER_NAME)
    if not os.path.exists(path):
        return {}
    try:
        data = json.load(open(path))
    except ValueError:
        return {}
    return {
        tid: e.get("status")
        for tid, e in (data.get("trials") or {}).items()
    }


@pytest.fixture(scope="class")
def uninterrupted_sweep(tmp_path_factory):
    """ONE clean 3-trial sweep over 3 families through the REAL CLI —
    the baseline every preemption variant's leaderboard is compared
    against, and the subject of the leaderboard-shape pins."""
    out_dir = tmp_path_factory.mktemp("sweep_clean") / "sweep"
    proc = subprocess.run(
        _search_argv(out_dir), env=_env(), cwd=REPO,
        capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr + proc.stdout
    return str(out_dir)


class TestSearchEndToEnd:
    def test_clean_sweep_leaderboard(self, uninterrupted_sweep):
        """The acceptance floor: >=3 trials over >=2 families complete
        with a deterministic strict-JSON leaderboard."""
        lb_path = os.path.join(uninterrupted_sweep, LEADERBOARD_NAME)

        def no_constants(s):
            raise AssertionError(f"bare {s} in leaderboard.json")

        lb = json.loads(open(lb_path).read(), parse_constant=no_constants)
        assert lb["search_verdict"] == 1
        assert lb["trials_total"] == 3 and lb["completed"] == 3
        assert lb["failed"] == 0
        families = {r["family"] for r in lb["ranking"]}
        assert len(families) >= 2
        assert len(lb["ranking"]) == 3
        assert lb["winner"]["trial"] == lb["ranking"][0]["trial"]
        # every trial ran exactly once
        assert all(
            t["attempts"] == 1 and not t["resumed"]
            for t in lb["trials"].values()
        )
        # the winner's run dir is a real run dir the rest of the stack
        # can consume (export the winning recipe, summarize it, ...)
        assert os.path.isdir(lb["winner"]["run_dir"])
        assert os.path.exists(
            os.path.join(lb["winner"]["run_dir"], "manifest.json")
        )

    def test_sigterm_resume_reaches_same_leaderboard(
        self, uninterrupted_sweep, tmp_path
    ):
        """THE resilience acceptance: SIGTERM mid-sweep -> exit 75 with
        in-flight trials checkpointed -> `search --resume` completes ->
        the ranking and winner are IDENTICAL to the uninterrupted
        sweep's, and completed trials were never re-run."""
        out_dir = tmp_path / "sweep"
        proc = subprocess.Popen(
            _search_argv(out_dir), env=_env(), cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        try:
            # let the first trial finish, then preempt the harness
            _wait_for(
                lambda: "done" in _ledger_statuses(out_dir).values(),
                timeout_s=300, what="first trial completion",
            )
            proc.send_signal(signal.SIGTERM)
            out, _ = proc.communicate(timeout=240)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.communicate()
        assert proc.returncode == 75, out
        statuses = _ledger_statuses(out_dir)
        done_first = {t for t, s in statuses.items() if s == "done"}
        assert done_first, statuses
        # nothing may be left 'running'; interrupted trials are either
        # preempted (checkpointed, resumable) or back to pending
        assert all(
            s in ("done", "preempted", "pending")
            for s in statuses.values()
        ), statuses
        assert not os.path.exists(
            os.path.join(str(out_dir), LEADERBOARD_NAME)
        )

        ledger_before = json.load(
            open(os.path.join(str(out_dir), LEDGER_NAME))
        )

        resumed = subprocess.run(
            _search_argv(out_dir, resume=True), env=_env(), cwd=REPO,
            capture_output=True, text=True, timeout=600,
        )
        assert resumed.returncode == 0, resumed.stderr + resumed.stdout

        lb = json.load(
            open(os.path.join(str(out_dir), LEADERBOARD_NAME))
        )
        clean = json.load(
            open(os.path.join(uninterrupted_sweep, LEADERBOARD_NAME))
        )
        # identical leaderboard: the ranking (trial/family/lr/best/
        # final, the deterministic core) and the winner match the
        # uninterrupted sweep's exactly
        assert lb["ranking"] == clean["ranking"]
        assert lb["winner"]["trial"] == clean["winner"]["trial"]
        assert lb["winner"]["best_top1"] == clean["winner"]["best_top1"]
        assert lb["completed"] == 3 and lb["failed"] == 0
        # completed trials were NEVER re-run: one attempt, one run dir,
        # and the ledger entry (metrics + digest) carried verbatim
        ledger_after = json.load(
            open(os.path.join(str(out_dir), LEDGER_NAME))
        )
        for tid in done_first:
            entry = ledger_after["trials"][tid]
            assert entry["attempts"] == 1
            assert len(entry["run_dirs"]) == 1
            assert entry == ledger_before["trials"][tid]
        # and at least one trial crossed the preemption (resumed or
        # re-run from scratch -> attempts 2, or it raced to completion
        # before the signal landed — assert the sweep as a whole saw
        # the preemption in its event trail either way
        events = [
            json.loads(l)
            for l in open(os.path.join(str(out_dir), "events.jsonl"))
            if l.strip()
        ]
        assert any(
            e["kind"] == "search" and e.get("phase") == "preempted"
            for e in events
        )
        assert any(
            e["kind"] == "search" and e.get("phase") == "resume"
            for e in events
        )

    def test_sweep_dir_summarize_and_watch(self, uninterrupted_sweep):
        """The sweep's events are first-class telemetry: summarize
        renders the leaderboard section, watch renders the verdict
        line (in-process — the subprocess smokes live in test_cli)."""
        from bdbnn_tpu.obs.events import read_events
        from bdbnn_tpu.obs.summarize import summarize_run
        from bdbnn_tpu.obs.watch import render_status

        report, summary = summarize_run(uninterrupted_sweep)
        assert summary["search"] is not None
        assert summary["search"]["completed"] == 3
        assert "recipe search: 3 trial(s)" in report
        assert "winner:" in report
        status = render_status(read_events(uninterrupted_sweep))
        assert "search: 3 trial(s)" in status
        assert "VERDICT: 3/3 completed" in status

    def test_resume_with_changed_grid_refused(self, uninterrupted_sweep):
        from bdbnn_tpu.search import run_search

        cfg = _sweep_cfg(
            uninterrupted_sweep, trials=("ste@0.1",), resume=True
        )
        with pytest.raises(RuntimeError, match="DIFFERENT search config"):
            run_search(cfg)

    def test_fresh_dir_with_ledger_needs_resume(self, uninterrupted_sweep):
        from bdbnn_tpu.search import run_search

        with pytest.raises(RuntimeError, match="--resume"):
            run_search(_sweep_cfg(uninterrupted_sweep))

    def test_compare_judges_sweep_against_itself(self, uninterrupted_sweep):
        from bdbnn_tpu.obs.compare import compare_runs

        result = compare_runs([uninterrupted_sweep, uninterrupted_sweep])
        assert result["verdict"] == "pass"
        rows = {
            m["metric"]
            for m in result["comparisons"][0]["metrics"]
        }
        assert "search_best_top1" in rows


class TestWorkerSelfPreemption:
    """A worker preempted on its OWN (node-local reclaim SIGTERMs just
    that PID; the harness keeps running) must be relaunched from its
    checkpoint so the sweep still completes — never left 'preempted'
    forever under an exit-0 leaderboard. Driven deterministically with
    a stubbed subprocess layer: attempt 1 of t000 'exits 75' after
    committing a checkpoint, attempt 2 must carry --resume and
    completes."""

    def test_self_preempted_worker_is_relaunched(
        self, tmp_path, monkeypatch
    ):
        from bdbnn_tpu.search import harness as H

        attempts = {}

        def fake_popen(argv, stdout=None, stderr=None, env=None):
            log_path = argv[argv.index("--log_path") + 1]
            tid = os.path.basename(log_path)
            n = attempts[tid] = attempts.get(tid, 0) + 1
            run_dir = os.path.join(log_path, f"run{n}")
            os.makedirs(run_dir, exist_ok=True)
            t0 = 1000.0
            if tid.startswith("t000") and n == 1:
                assert "--resume" not in argv
                os.makedirs(
                    os.path.join(run_dir, "checkpoint"), exist_ok=True
                )
                with open(
                    os.path.join(run_dir, "events.jsonl"), "w"
                ) as f:
                    f.write(json.dumps(
                        {"t": t0, "kind": "run_start"}
                    ) + "\n")
                rc = 75
            else:
                if tid.startswith("t000") and n == 2:
                    assert "--resume" in argv  # resumed, not restarted
                with open(
                    os.path.join(run_dir, "events.jsonl"), "w"
                ) as f:
                    f.write(json.dumps(
                        {"t": t0, "kind": "run_start"}
                    ) + "\n")
                    f.write(json.dumps(
                        {"t": t0 + 1, "kind": "eval", "epoch": 0,
                         "acc1": 50.0}
                    ) + "\n")
                    f.write(json.dumps(
                        {"t": t0 + 2, "kind": "run_end",
                         "best_acc1": 50.0, "wall_s": 2.0}
                    ) + "\n")
                rc = 0

            class _P:
                returncode = rc

                def poll(self):
                    return rc

                def wait(self, timeout=None):
                    return rc

                def send_signal(self, s):
                    pass

                def kill(self):
                    pass

            return _P()

        monkeypatch.setattr(H.subprocess, "Popen", fake_popen)
        cfg = _sweep_cfg(
            str(tmp_path / "sweep"), trials=("ste@0.1", "ede@0.1")
        )
        result = H.run_search(cfg)
        lb = result["leaderboard"]
        assert lb["completed"] == 2 and lb["failed"] == 0
        assert attempts["t000_ste_lr0.1"] == 2
        t000 = lb["trials"]["t000_ste_lr0.1"]
        assert t000["attempts"] == 2 and t000["resumed"] is True
        # wall-clock facts for the resumed trial are unknowable -> null
        assert t000["wall_s"] is None
        assert t000["time_to_common_acc_s"] is None
        # the untouched trial ran once with honest figures
        assert attempts["t001_ede_lr0.1"] == 1
        assert lb["trials"]["t001_ede_lr0.1"]["wall_s"] == 2.0
        # the trail records the self-preemption + relaunch
        events = [
            json.loads(l)
            for l in open(
                os.path.join(str(tmp_path / "sweep"), "events.jsonl")
            )
            if l.strip()
        ]
        phases = [
            (e.get("phase"), e.get("trial"))
            for e in events if e["kind"] == "trial"
        ]
        assert ("preempted", "t000_ste_lr0.1") in phases
        assert ("resumed", "t000_ste_lr0.1") in phases


class TestSearchDigest:
    def test_digest_shapes(self):
        events = [
            {"kind": "search", "phase": "start", "trials_total": 2,
             "families": ["ste"], "workers": 1},
            {"kind": "trial", "phase": "start", "trial": "t000",
             "family": "ste", "lr": 0.1},
            {"kind": "trial", "phase": "done", "trial": "t000",
             "family": "ste", "lr": 0.1, "best_top1": 50.0},
            {"kind": "trial", "phase": "start", "trial": "t001",
             "family": "ede", "lr": 0.1},
        ]
        d = search_digest(events)
        assert d["start"]["trials_total"] == 2
        assert d["trial_latest"]["t000"]["phase"] == "done"
        assert d["trial_latest"]["t001"]["phase"] == "start"
        assert d["best_done"]["trial"] == "t000"
        assert d["verdict"] is None and d["preempted"] is None
