"""Worker for tests/test_multihost.py — one simulated POD HOST.

Run as ``python multihost_worker.py <proc_id> <num_procs> <port> <dir>``.
Each process owns 4 virtual CPU devices and joins a real
``jax.distributed`` cluster (GRPC coordinator, exactly the multi-host
bring-up a TPU pod uses — reference analogue: NCCL init_process_group,
``train.py:248``). The global mesh is DP x TP2, so with 2 processes the
'model'-sharded kernels span BOTH hosts: every leaf is then only
partially addressable and the collective Orbax checkpoint path is the
only legal one.

Flow: disjoint per-host batches (host_shard_indices) -> global arrays
(shard_batch's multi-process branch) -> 2 jitted DP+TP train steps ->
collective save -> collective restore -> 1 more step. Prints
``LOSS <step> <value>`` lines (the parent asserts they are finite and
bit-identical across processes) and ``MH_WORKER_OK`` at the end.
"""

import os
import sys

proc_id, num_procs, port, workdir = (
    int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3]), sys.argv[4],
)

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=4"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# The CPU PJRT backend compiles multi-process collectives only when a
# cross-host collectives implementation is configured; without this the
# first non-addressable device_put dies with "Multiprocess computations
# aren't implemented on the CPU backend" (its default is a
# single-process stub). TPU/GPU backends ship their own (ICI/NCCL) —
# this knob exists for, and only affects, CPU clusters.
jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}",
    num_processes=num_procs,
    process_id=proc_id,
)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from bdbnn_tpu.data.pipeline import host_shard_indices  # noqa: E402
from bdbnn_tpu.models import conv_weight_paths  # noqa: E402
from bdbnn_tpu.models.resnet import BiResNet  # noqa: E402
from bdbnn_tpu.parallel import (  # noqa: E402
    create_sharded_state,
    jit_train_step,
    make_mesh,
    shard_batch,
)
from bdbnn_tpu.train import (  # noqa: E402
    StepConfig,
    TrainState,
    make_optimizer,
    make_train_step,
)
from bdbnn_tpu.utils.checkpoint import (  # noqa: E402
    load_checkpoint,
    save_checkpoint,
    state_is_distributed,
)

assert jax.process_count() == num_procs, jax.process_count()
assert jax.device_count() == 4 * num_procs

mesh = make_mesh(jax.devices(), model_parallel=2)

model = BiResNet(
    stage_sizes=(1, 1), num_classes=10, width=8,
    stem="cifar", variant="cifar", act="hardtanh",
)
variables = model.init(
    jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3)), train=True
)
paths = conv_weight_paths(variables["params"])
cfg = StepConfig(
    w_kurtosis=True,
    kurt_paths=tuple(paths[1:]),
    kurt_targets=(1.8,) * len(paths[1:]),
    kurtosis_mode="avg",
    w_lambda_kurtosis=1.0,
)
tx = make_optimizer(
    variables["params"], dataset="cifar10", lr=0.05, epochs=3,
    steps_per_epoch=2,
)
state = create_sharded_state(mesh, variables, tx, TrainState)
step = jit_train_step(make_train_step(model, tx, cfg))

# Disjoint per-host slice of a shared deterministic 16-sample epoch —
# the DistributedSampler replacement, exercised across REAL processes.
full_x = np.random.default_rng(0).normal(size=(16, 16, 16, 3)).astype(np.float32)
full_y = np.random.default_rng(1).integers(0, 10, size=(16,))
idx = host_shard_indices(
    16, 0, seed=0, shuffle=True, host_id=proc_id, num_hosts=num_procs
)
gx, gy = shard_batch(mesh, full_x[idx], full_y[idx])

tk = (jnp.float32(1.0), jnp.float32(1.0))
gate = jnp.float32(1.0)
for i in range(2):
    state, metrics = step(state, (gx, gy), tk, gate)
    print(f"LOSS {i} {float(metrics['loss']):.10f}", flush=True)

# TP2 over 2 hosts: kernels sharded over 'model' span both processes
assert state_is_distributed(state), "expected partially-addressable state"
save_checkpoint(
    workdir, state, epoch=0, arch="tiny", best_acc1=0.0, is_best=False
)
restored = load_checkpoint(workdir, state)
assert restored["epoch"] == 1 and restored["arch"] == "tiny"

state2, metrics2 = step(restored["state"], (gx, gy), tk, gate)
print(f"LOSS post-restore {float(metrics2['loss']):.10f}", flush=True)
assert np.isfinite(float(metrics2["loss"]))
print("MH_WORKER_OK", flush=True)
