"""TRUE multi-process multi-host test (SURVEY §5.8).

Everything else in the suite simulates 8 devices in ONE process; this
test spawns two real processes that form a ``jax.distributed`` cluster
over a GRPC coordinator — the same bring-up a TPU pod uses and the
replacement for the reference's NCCL ``init_process_group`` rendezvous
(``train.py:237-314``). It exercises, across actual process boundaries:

- per-host disjoint input sharding + ``shard_batch``'s
  ``make_array_from_process_local_data`` branch,
- a DP x TP2 mesh whose 'model'-sharded kernels SPAN the two hosts
  (leaves not fully addressable by either process),
- the collective Orbax checkpoint save/restore path (barriers, per-host
  shard writes) that single-process tests cannot reach.

The two workers must print bit-identical finite losses: GSPMD executes
one global program, so any divergence means broken input sharding or a
non-collective reduction.
"""

import os
import socket
import subprocess
import sys

import pytest

# conftest skips gloo-marked tests (with a reason) when jaxlib lacks
# multiprocess CPU collectives
pytestmark = pytest.mark.gloo


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_cluster(workdir) -> None:
    """One attempt: spawn the 2-process cluster and assert the
    bit-identical-loss contract. Raises (AssertionError / pytest
    Failed) on any violation so the caller can bound a retry."""
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(worker)))
    port = _free_port()
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), "2", str(port), str(workdir)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
            cwd=repo_root,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=540)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-host worker timed out")
        outs.append((p.returncode, out, err))

    for rc, out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\nstdout:{out}\nstderr:{err[-2000:]}"
        assert "MH_WORKER_OK" in out

    losses = [
        [line for line in out.splitlines() if line.startswith("LOSS")]
        for _, out, _ in outs
    ]
    assert len(losses[0]) == 3
    # one global GSPMD program -> bit-identical metrics on every host
    assert losses[0] == losses[1], f"{losses[0]} != {losses[1]}"


def test_two_process_dp_tp_train_and_collective_checkpoint(tmp_path):
    """Quarantined behind conftest.retry_once_flaky (the ONE bounded
    retry-once policy).

    TRACKING NOTE: PRs 7 and 8 both recorded ONE transient in-suite
    failure of this test on contended boxes (a worker dying or timing
    out during the GRPC coordinator bring-up) that never reproduced in
    isolation or on rerun — the cluster formation races the box's load,
    not our code. A deterministic failure (broken sharding, divergent
    losses) fails BOTH attempts and still fails the suite."""
    from conftest import retry_once_flaky

    retry_once_flaky(
        lambda i: _run_cluster(tmp_path / f"attempt{i + 1}"),
        note=(
            "multihost cluster attempt 1 failed (GRPC coordinator "
            "bring-up transient on contended boxes, PR 7/8 notes)"
        ),
        exceptions=(AssertionError, pytest.fail.Exception),
    )
