"""Capacity & demand observatory tests (bdbnn_tpu/obs/capacity.py +
its serving-stack wiring).

- the demand ledger's identity ``offered == admitted + rejected +
  shed`` under concurrent feeders, plus the per-key/rollup reporting
- the saturation-headroom math's None-propagation discipline (an
  autoscaler must never act on a fabricated estimate)
- the SLO burn-rate plane: per-detector synthetic streams fire exactly
  their own breach (a bulk-class shed storm never torches the premium
  class's budget), warmup -> debounce -> hysteresis via the shared
  DetectorState, and ``peek`` never ticking the machines (a fast
  ``/statsz`` scraper must not accelerate the debounce clock)
- the fleet merge excluding stale hosts (a wedged host's frozen
  numbers never feed the merged view)
- the live ``/statsz`` capacity block over real sockets, and the
  measured-offered-rate accounting fix (serve-mode verdicts record
  the observed arrival rate, never null, never fabricated)
- THE acceptance e2e: a flash crowd against a 2-replica pool fires
  the bulk class's shed burn-rate detector while the premium class's
  budget stays intact, the headroom estimate goes negative during the
  burst, the episode renders in watch/summarize, and ``compare``
  clean-vs-doctored exits 3 on ``serve_burn_rate_max``.
"""

import json
import os
import shutil
import subprocess
import sys
import threading
import time

import pytest

from bdbnn_tpu.obs.capacity import (
    BURN_RATE_CAP,
    CapacityPlane,
    DemandLedger,
    FleetCapacityWindows,
    SLOBudget,
    UtilizationWindows,
    _burn,
    demand_key,
    saturation_headroom,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def tick(self, dt):
        self.t += dt


# ---------------------------------------------------------------------------
# the demand ledger
# ---------------------------------------------------------------------------


class TestDemandLedger:
    def test_identity_holds_under_concurrent_feeders(self):
        """Many threads hammering offered + a disposition on shared
        keys: the per-key identity ``offered == admitted + rejected +
        shed`` must hold exactly at quiescence — the counters are one
        lock, not per-counter races."""
        ledger = DemandLedger(window_s=60.0)
        keys = [("m0", "bulk", 2), ("m0", "premium", 0),
                ("m1", "bulk", 1)]
        per_thread = 200

        def feeder(i):
            model, tenant, p = keys[i % len(keys)]
            for j in range(per_thread):
                ledger.offered(model, tenant, p)
                if j % 3 == 0:
                    ledger.shed(model, tenant, p)
                elif j % 3 == 1:
                    ledger.rejected(model, tenant, p)
                else:
                    ledger.admitted(model, tenant, p)
                    ledger.completed(model, tenant, p)

        threads = [
            threading.Thread(target=feeder, args=(i,)) for i in range(6)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        snap = ledger.snapshot()
        assert snap["identity_ok"] is True
        assert snap["in_flight_decisions"] == 0
        total_offered = sum(
            row["offered"] for row in snap["keys"].values()
        )
        assert total_offered == 6 * per_thread
        for row in snap["keys"].values():
            assert row["identity_delta"] == 0
            assert row["offered"] == (
                row["admitted"] + row["rejected"] + row["shed"]
            )

    def test_in_flight_delta_is_live_gauge(self):
        """`admitted` lands only at the terminal, so the identity
        delta counts requests still queued/computing — then returns to
        zero when they finish."""
        ledger = DemandLedger(window_s=60.0)
        ledger.offered("m", "t", 0)
        ledger.offered("m", "t", 0)
        snap = ledger.snapshot()
        assert snap["in_flight_decisions"] == 2
        assert snap["identity_ok"] is False  # mid-decision, not torn
        ledger.admitted("m", "t", 0)
        ledger.completed("m", "t", 0)
        ledger.admitted("m", "t", 0)
        ledger.failed("m", "t", 0)
        snap = ledger.snapshot()
        assert snap["in_flight_decisions"] == 0
        assert snap["identity_ok"] is True
        row = snap["keys"][demand_key("m", "t", 0)]
        assert row["completed"] == 1 and row["failed"] == 1

    def test_rps_uses_elapsed_span_not_full_window(self):
        """A run younger than the window reports rates over its actual
        age — a 2-second-old run over a 30s window must not dilute
        every rate toward zero."""
        clk = FakeClock()
        ledger = DemandLedger(window_s=30.0, clock=clk)
        for _ in range(20):
            ledger.offered("m", "t", 0)
        clk.tick(2.0)
        snap = ledger.snapshot()
        row = snap["keys"][demand_key("m", "t", 0)]
        assert row["offered_rps"] == pytest.approx(10.0)

    def test_rollups_and_shed_ratio_max(self):
        ledger = DemandLedger(window_s=60.0)
        for _ in range(4):
            ledger.offered("m0", "bulk", 2)
            ledger.shed("m0", "bulk", 2)
        ledger.offered("m0", "premium", 0)
        ledger.admitted("m0", "premium", 0)
        ledger.completed("m0", "premium", 0)
        snap = ledger.snapshot()
        assert snap["by_model"]["m0"]["offered"] == 5
        assert snap["by_tenant"]["bulk"]["shed"] == 4
        assert snap["by_tenant"]["premium"]["shed"] == 0
        # worst per-key shed ratio: bulk's 4/4, not the aggregate 4/5
        assert snap["demand_shed_ratio_max"] == pytest.approx(1.0)

    def test_offered_slope_needs_history(self):
        clk = FakeClock()
        ledger = DemandLedger(window_s=10.0, clock=clk)
        ledger.offered("m", "t", 0)
        # only the newest half has stamps -> no slope yet
        assert ledger.offered_slope_rps_per_s() is None
        clk.tick(6.0)
        for _ in range(30):
            ledger.offered("m", "t", 0)
        # old half: 1 stamp, new half: 30 -> rising demand
        slope = ledger.offered_slope_rps_per_s()
        assert slope is not None and slope > 0


# ---------------------------------------------------------------------------
# utilization windows + headroom math
# ---------------------------------------------------------------------------


class TestUtilizationWindows:
    def test_none_and_nonfinite_skipped_unknown_raises(self):
        u = UtilizationWindows(window=4)
        u.sample(busy_fraction=0.5, occupancy=None,
                 queue_share=float("nan"))
        u.sample(busy_fraction=1.0)
        with pytest.raises(ValueError, match="unknown"):
            u.sample(cpu_temperature=99.0)
        snap = u.snapshot()
        assert snap["busy_fraction"] == {
            "last": 1.0, "mean": 0.75, "n": 2,
        }
        assert snap["occupancy"]["last"] is None
        assert snap["queue_share"]["n"] == 0

    def test_residency_block_reported(self):
        u = UtilizationWindows()
        assert u.snapshot()["residency"] is None
        u.set_residency({"resident_bytes": 1024})
        assert u.snapshot()["residency"] == {"resident_bytes": 1024}


class TestSaturationHeadroom:
    def test_negative_exactly_when_demand_exceeds_capacity(self):
        h = saturation_headroom(
            offered_rps=500.0, completed_rps=200.0, busy_fraction=1.0,
        )
        assert h["capacity_rps_est"] == pytest.approx(200.0)
        assert h["headroom_rps"] == pytest.approx(-300.0)
        assert h["seconds_to_saturation"] is None  # already saturated

    def test_seconds_to_saturation_at_slope(self):
        h = saturation_headroom(
            offered_rps=100.0, completed_rps=100.0, busy_fraction=0.5,
            slope_rps_per_s=10.0,
        )
        assert h["capacity_rps_est"] == pytest.approx(200.0)
        assert h["headroom_rps"] == pytest.approx(100.0)
        assert h["seconds_to_saturation"] == pytest.approx(10.0)

    def test_unmeasurable_inputs_propagate_none(self):
        # busy fraction below the noise floor -> no capacity estimate,
        # no headroom, never a fabricated figure
        h = saturation_headroom(
            offered_rps=100.0, completed_rps=50.0, busy_fraction=0.001,
        )
        assert h["capacity_rps_est"] is None
        assert h["headroom_rps"] is None
        h = saturation_headroom(
            offered_rps=None, completed_rps=50.0, busy_fraction=0.5,
        )
        assert h["capacity_rps_est"] is not None
        assert h["headroom_rps"] is None


class TestBurnMath:
    def test_burn_semantics(self):
        assert _burn(0, 0, 0.01) is None  # empty window: not measured
        assert _burn(0, 100, 0.01) == 0.0
        assert _burn(1, 100, 0.01) == pytest.approx(1.0)
        assert _burn(5, 100, 0.01) == pytest.approx(5.0)
        # zero budget: any badness is the cap, never inf
        assert _burn(1, 100, 0.0) == BURN_RATE_CAP
        assert _burn(0, 100, 0.0) == 0.0
        # cap keeps every figure finite JSON
        assert _burn(100, 100, 1e-9) == BURN_RATE_CAP


# ---------------------------------------------------------------------------
# the SLO budget plane
# ---------------------------------------------------------------------------


def _budget(clk, **kw):
    kw.setdefault("slo_p99_ms", 100.0)
    kw.setdefault("slo_shed_rate", 0.1)
    kw.setdefault("priorities", 3)
    kw.setdefault("fast_window_s", 2.0)
    kw.setdefault("slow_window_s", 6.0)
    return SLOBudget(clock=clk, **kw)


class TestSLOBudget:
    def test_objectives_gate_on_knobs(self):
        clk = FakeClock()
        assert _budget(clk).objectives() == ("latency", "shed")
        assert _budget(clk, slo_shed_rate=0.0).objectives() == (
            "latency",
        )
        assert _budget(
            clk, slo_p99_ms=0.0, slo_shed_rate=0.0
        ).objectives() == ()

    def test_window_validation(self):
        with pytest.raises(ValueError, match="fast_window_s"):
            _budget(FakeClock(), fast_window_s=5.0, slow_window_s=2.0)

    def test_each_detector_fires_exactly_its_own_breach(self):
        """Synthetic per-priority streams: p2 sheds hard, p0 completes
        fast, p1 completes slow. Only p2:shed and p1:latency fire —
        the bulk storm never touches the premium budget, and neither
        breach leaks across objectives."""
        clk = FakeClock()
        budget = _budget(clk)
        fired = []
        for _ in range(8):  # warmup 2 + debounce 2 + slack
            for _ in range(20):
                budget.feed(0, latency_ms=5.0)       # premium: healthy
                budget.feed(1, latency_ms=500.0)     # over the target
                budget.feed(2, shed=True)            # the shed storm
            tick = budget.evaluate()
            fired += [row["detector"] for row in tick["fired"]]
            clk.tick(1.0)
        assert sorted(fired) == ["p1:latency", "p2:shed"]
        snap = budget.snapshot()
        assert snap["breaches"] == 2
        peaks = snap["burn_rate_peaks"]
        assert peaks["p2:shed"] > 1.0
        assert peaks.get("p0:latency", 0.0) <= 1.0
        assert peaks.get("p0:shed", 0.0) == 0.0

    def test_warmup_and_debounce_discipline(self):
        """A persistent breach fires exactly at tick warmup+debounce,
        then latches (no refire while breaching)."""
        clk = FakeClock()
        budget = _budget(clk, warmup=2, debounce=2)
        fire_ticks = []
        for i in range(1, 8):
            budget.feed(2, shed=True)
            tick = budget.evaluate()
            if tick["fired"]:
                fire_ticks.append(i)
            clk.tick(0.5)
        assert fire_ticks == [4]

    def test_peek_never_ticks_the_machines(self):
        """A scraper hammering ``peek`` (the /statsz path) must not
        advance warmup/debounce — only ``evaluate`` is the detector
        clock."""
        clk = FakeClock()
        budget = _budget(clk, warmup=2, debounce=2)
        for _ in range(10):
            budget.feed(2, shed=True)
        for _ in range(50):
            row = budget.peek()["p2:shed"]
            assert row["breach"] is True  # visible immediately...
            assert row["latched"] is False  # ...but never latched
        # the machine still needs its full warmup + debounce of
        # evaluate() ticks before firing
        fires = 0
        for _ in range(4):
            budget.feed(2, shed=True)
            fires += len(budget.evaluate()["fired"])
            clk.tick(0.1)
        assert fires == 1

    def test_recovery_closes_episode_and_rearms(self):
        """Calm fast window -> the latch clears, the episode closes
        with t_end, and a second storm fires a second episode."""
        clk = FakeClock()
        budget = _budget(clk, fast_window_s=1.0, slow_window_s=3.0)
        recovered = []

        def storm(ticks):
            out = []
            for _ in range(ticks):
                for _ in range(10):
                    budget.feed(2, shed=True)
                tick = budget.evaluate()
                out += tick["fired"]
                recovered.extend(tick["recovered"])
                clk.tick(0.5)
            return out

        def calm(ticks):
            for _ in range(ticks):
                for _ in range(10):
                    budget.feed(2, latency_ms=1.0)
                tick = budget.evaluate()
                recovered.extend(tick["recovered"])
                clk.tick(0.5)

        assert len(storm(6)) == 1
        calm(10)  # fast window drains clean -> recovery
        assert [r["detector"] for r in recovered] == ["p2:shed"]
        assert len(storm(8)) == 1  # re-armed: fires again
        snap = budget.snapshot()
        episodes = [
            e for e in snap["episodes"] if e["detector"] == "p2:shed"
        ]
        assert len(episodes) == 2
        assert episodes[0]["t_end"] is not None
        assert episodes[1]["t_end"] is None  # still open
        assert snap["burn_rate_max"] > 1.0


# ---------------------------------------------------------------------------
# the fleet merge
# ---------------------------------------------------------------------------


def _host_block(offered, headroom, burn_fast, shed_ratio=0.0):
    return {
        "demand": {
            "offered_rps": offered,
            "demand_shed_ratio_max": shed_ratio,
        },
        "headroom": {
            "headroom_rps": headroom, "capacity_rps_est": 100.0,
        },
        "slo_budget": {
            "detectors": {
                "p0:latency": {
                    "burn_rate_fast": burn_fast,
                    "burn_rate_slow": burn_fast,
                },
            },
        },
    }


class TestFleetCapacityWindows:
    def test_merge_sums_fresh_and_maxes_burn(self):
        w = FleetCapacityWindows(stale_after=3)
        w.record("h0", _host_block(50.0, 20.0, 0.5, 0.1))
        w.record("h1", _host_block(30.0, -5.0, 4.0, 0.3))
        snap = w.snapshot()
        assert snap["hosts_fresh"] == 2 and snap["hosts_stale"] == 0
        m = snap["merged"]
        assert m["offered_rps"] == pytest.approx(80.0)
        assert m["headroom_rps"] == pytest.approx(15.0)
        assert m["burn_rate_max"] == pytest.approx(4.0)
        assert m["demand_shed_ratio_max"] == pytest.approx(0.3)

    def test_stale_host_excluded_from_merge(self):
        """stale_after consecutive failures freeze a host out of the
        merged view — its LAST numbers are never summed as live."""
        w = FleetCapacityWindows(stale_after=2)
        w.record("h0", _host_block(50.0, 20.0, 0.5))
        w.record("h1", _host_block(500.0, 400.0, 9.0))
        w.record_failure("h1")
        assert w.snapshot()["merged"]["offered_rps"] == 550.0
        w.record_failure("h1")  # streak hits stale_after
        snap = w.snapshot()
        assert snap["hosts_stale"] == 1
        assert snap["hosts"]["h1"]["stale"] is True
        m = snap["merged"]
        assert m["offered_rps"] == pytest.approx(50.0)
        assert m["burn_rate_max"] == pytest.approx(0.5)
        # a good scrape resets the streak -> back in the merge
        w.record("h1", _host_block(10.0, 5.0, 0.1))
        assert w.snapshot()["merged"]["offered_rps"] == 60.0

    def test_payload_without_block_is_a_failure(self):
        """A pre-v8 host whose /statsz has no capacity block goes
        capacity-stale — never a crash, never fabricated zeros."""
        w = FleetCapacityWindows(stale_after=2)
        w.record("h0", None)
        w.record("h0", "not-a-dict")
        snap = w.snapshot()
        assert snap["hosts"]["h0"]["stale"] is True
        assert snap["hosts"]["h0"]["failures"] == 2
        assert snap["merged"]["offered_rps"] is None


# ---------------------------------------------------------------------------
# the live /statsz block + measured offered rate, over real sockets
# ---------------------------------------------------------------------------


class TestLiveStatszCapacity:
    def test_statsz_capacity_block_and_measured_rate(
        self, http_frontend
    ):
        from tests.test_http import _predict, _request

        plane = CapacityPlane(
            slo_p99_ms=1000.0, slo_shed_rate=0.05, priorities=3,
        )
        fe = http_frontend(capacity=plane)
        for i in range(5):
            status, _, _ = _predict(fe, priority=2, tenant="bulk")
            assert status == 200
            time.sleep(0.02)
        status, _, stats = _request(fe, "GET", "/statsz")
        assert status == 200
        cap = stats["capacity"]
        key = demand_key("default", "bulk", 2)
        row = cap["demand"]["keys"][key]
        assert row["offered"] == 5 and row["completed"] == 5
        assert row["identity_delta"] == 0
        assert cap["demand"]["identity_ok"] is True
        # detectors visible (peek), nothing latched by scraping
        det = cap["slo_budget"]["detectors"]
        assert set(det) == {
            f"p{p}:{o}" for p in range(3)
            for o in ("latency", "shed")
        }
        assert all(not r["latched"] for r in det.values())
        assert cap["slo_budget"]["objectives"] == {
            "slo_p99_ms": 1000.0, "slo_shed_rate": 0.05,
        }
        assert "headroom" in cap and "utilization" in cap
        # the measured offered rate: observed arrival stamps, not a
        # config knob — the serve-mode verdict's rate_rps source
        acc = fe.accounting()
        assert acc["measured_rate_rps"] is not None
        assert 0.5 < acc["measured_rate_rps"] < 2000.0

    def test_measured_rate_none_until_two_arrivals(
        self, http_frontend
    ):
        """Fewer than two observed arrivals -> None ("not measured"),
        never a fabricated rate."""
        from tests.test_http import _predict

        fe = http_frontend()
        assert fe.accounting()["measured_rate_rps"] is None
        _predict(fe, priority=0)
        assert fe.accounting()["measured_rate_rps"] is None

    def test_rejects_and_sheds_land_in_ledger(self, http_frontend):
        from tests.test_http import _predict

        fe = http_frontend(quotas={"greedy": (0.000001, 1.0)})
        # burn greedy's single token, then the next is over-quota
        assert _predict(fe, priority=1, tenant="greedy")[0] == 200
        assert _predict(fe, priority=1, tenant="greedy")[0] == 429
        snap = fe.capacity.ledger.snapshot()
        row = snap["keys"][demand_key("default", "greedy", 1)]
        assert row["offered"] == 2
        assert row["rejected"] == 1 and row["admitted"] == 1
        assert row["identity_delta"] == 0


# ---------------------------------------------------------------------------
# THE acceptance e2e: flash crowd against a 2-replica pool
# ---------------------------------------------------------------------------


class TestCapacityAcceptance:
    def test_flash_crowd_burn_breach_headroom_and_compare_gate(
        self, exported_artifact, tmp_path
    ):
        """The acceptance pin, over real sockets and the real AOT
        engines: a flash crowd against a 2-replica pool fires the bulk
        class's shed burn-rate detector during the burst while the
        premium class's budget stays intact; the verdict's capacity
        block carries per-tenant demand and a headroom estimate that
        went negative during the burst; the episode renders in
        watch/summarize; and compare clean-vs-doctored (inflated burn
        rate, flat aggregate p99) exits 3 on serve_burn_rate_max.

        Capacity is shaped with the canary-drill fault-injection hook
        (a per-batch latency inflation): client and server share one
        interpreter here, so client-side pressure alone can never
        out-offer the real engines — the injected service time puts
        true capacity genuinely below the offered rate while leaving
        the premium class's demand comfortably inside it."""
        from bdbnn_tpu.configs.config import ServeHttpConfig
        from bdbnn_tpu.obs.events import read_events, serve_digest
        from bdbnn_tpu.obs.summarize import summarize_run
        from bdbnn_tpu.obs.watch import render_status
        from bdbnn_tpu.serve.http import run_serve_http

        art_dir, _ = exported_artifact
        cfg = ServeHttpConfig(
            artifact=art_dir,
            log_path=str(tmp_path / "serve_http"),
            buckets=(1, 4),
            priorities=3,
            queue_depth=16,
            max_delay_ms=2.0,
            scenario="flash_crowd",
            rate=800.0,
            requests=4000,
            flash_factor=8.0,
            concurrency=48,
            seed=0,
            default_quota="100000:100000",
            stats_interval_s=0.1,
            replicas=2,
            slo_p99_ms=2000.0,    # generous: latency never breaches
            slo_shed_rate=0.005,  # tight shed budget: the crowd torches it
        )
        res = run_serve_http(cfg, degrade={"latency_ms": 6.0})
        v = res["verdict"]
        assert v["serve_verdict"] == 8
        # scenario mode keeps the SCHEDULED rate (the measured-rate
        # fix applies to serve mode only)
        assert v["rate_rps"] == 800.0
        # the burst forced real shedding, but never on priority 0
        assert v["requests_shed"] > 0
        p0 = v["per_priority"]["0"]
        assert p0["shed_queue_full"] == 0 and p0["shed_draining"] == 0

        cap = v["capacity"]
        assert cap is not None
        # per-tenant demand visible in the verdict block
        assert cap["demand"]["by_tenant"]
        assert cap["demand"]["identity_ok"] is True
        assert cap["demand"]["in_flight_decisions"] == 0
        # the bulk class's shed detector fired: burn above threshold,
        # an episode on exactly a low-priority shed detector
        assert cap["burn_rate_max"] is not None
        assert cap["burn_rate_max"] > 1.0
        episodes = cap["slo_budget"]["episodes"]
        assert episodes, "no burn episode recorded"
        assert all(e["objective"] == "shed" for e in episodes)
        assert all(e["priority"] > 0 for e in episodes), (
            "premium budget burned"
        )
        # premium peaks under threshold: budget intact
        peaks = cap["slo_budget"]["burn_rate_peaks"]
        assert peaks.get("p0:shed", 0.0) <= 1.0
        assert peaks.get("p0:latency", 0.0) <= 1.0

        events = read_events(res["run_dir"])
        digest = serve_digest(events)
        breaches = digest["capacity_breaches"]
        assert breaches and all(
            b["priority"] > 0 and b["objective"] == "shed"
            for b in breaches
        )
        # the headroom estimate went negative while the burst was on:
        # negative ticks exist and every one coincides with elevated
        # demand + active shedding (never in the calm phases)
        trail = digest["capacity_stats_trail"]
        headrooms = [
            (e["offered_rps"], (e.get("headroom") or {}))
            for e in trail
        ]
        negative = [
            (off, hr) for off, hr in headrooms
            if hr.get("headroom_rps") is not None
            and hr["headroom_rps"] < 0
        ]
        assert negative, "headroom never went negative during burst"
        measurable_offered = [
            off for off, hr in headrooms
            if hr.get("headroom_rps") is not None
        ]
        # the negative ticks coincide with elevated demand: at least
        # one lands in the top half of the observed offered-rps range
        assert max(off for off, _ in negative) >= (
            0.5 * max(measurable_offered)
        )

        # watch + summarize render the episode
        status = render_status(events, None)
        assert "capacity: burn max" in status
        assert "burn episode: p" in status
        report, summary = summarize_run(res["run_dir"])
        assert summary["serving"]["verdict"]["capacity"] is not None
        assert summary["serving"]["capacity_breaches"] >= 1
        assert "capacity:" in report and "burn episode" in report

        # compare clean-vs-doctored: inflate the burn gate, keep the
        # aggregate p99 flat — exit 3 names serve_burn_rate_max
        clean = tmp_path / "clean_verdict.json"
        doctored = tmp_path / "doctored_verdict.json"
        clean.write_text(json.dumps(v))
        bad = json.loads(json.dumps(v))
        bad["capacity"]["burn_rate_max"] = round(
            v["capacity"]["burn_rate_max"] * 3.0, 4
        )
        doctored.write_text(json.dumps(bad))
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, "-m", "bdbnn_tpu.cli", "compare",
             str(clean), str(doctored), "--json"],
            capture_output=True, text=True, timeout=180, env=env,
            cwd=REPO,
        )
        assert proc.returncode == 3, proc.stderr[-800:]
        result = json.loads(proc.stdout)
        rows = {
            m["metric"]: m
            for m in result["comparisons"][0]["metrics"]
        }
        assert rows["serve_burn_rate_max"]["verdict"] == "regression"
        assert rows["serve_p99_ms"]["verdict"] == "ok"
        # and the identical pair passes clean
        proc = subprocess.run(
            [sys.executable, "-m", "bdbnn_tpu.cli", "compare",
             str(clean), str(clean)],
            capture_output=True, text=True, timeout=180, env=env,
            cwd=REPO,
        )
        assert proc.returncode == 0, proc.stderr[-800:]
