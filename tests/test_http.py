"""Network front end tests (bdbnn_tpu/serve/http.py + admission.py).

Everything here speaks REAL sockets against a live asyncio server —
mostly with a stub runner (no JAX; the engine is injected exactly like
the micro-batcher tests), plus one end-to-end over a real export
artifact pinning the acceptance criterion: a SIGTERM mid-flash-crowd
answers every accepted request before the verdict lands (zero
dropped), with shedding confined to low-priority / over-quota traffic.

- health/readiness gating: /healthz liveness vs /readyz wired to the
  warmup state and the drain latch
- per-tenant admission: token-bucket 429 (over_quota) vs 503
  (draining / queue full) — the shed taxonomy a client retries on
- strict-priority ordering under a full queue: priority 0 overtakes a
  backlog of priority 2, and per-class queue bounds isolate sheds
- the drain contract over a live connection: readyz flips first,
  in-flight requests finish, new requests shed explicitly
- scenario arrival processes: seeded determinism + each scenario's
  shape (burst density, heavy tail, diurnal swing, slow fraction)
- the flash-crowd and slow-client soaks carry the `slow` marker
  (tier-1 budget).
"""

import json
import os
import signal
import socket
import threading
import time

import pytest

from bdbnn_tpu.serve.loadgen import (
    Arrival,
    HttpLoadGenerator,
    build_schedule,
    fairness_ratio,
    http_slo_verdict,
    percentile,
)

# ---------------------------------------------------------------------------
# a minimal raw-socket client (keep the tests byte-honest: no urllib
# connection pooling, no implicit retries)
# ---------------------------------------------------------------------------


def _request(
    fe, method, path, *, headers=None, body=b"", timeout=10.0
):
    with socket.create_connection(
        (fe.host, fe.port), timeout=timeout
    ) as s:
        head = f"{method} {path} HTTP/1.1\r\nhost: t\r\n"
        for k, v in (headers or {}).items():
            head += f"{k}: {v}\r\n"
        head += f"content-length: {len(body)}\r\nconnection: close\r\n\r\n"
        s.sendall(head.encode("latin-1") + body)
        rfile = s.makefile("rb")
        status_line = rfile.readline().decode("latin-1")
        status = int(status_line.split()[1])
        resp_headers = {}
        while True:
            h = rfile.readline()
            if h in (b"\r\n", b"\n", b""):
                break
            name, _, value = h.decode("latin-1").partition(":")
            resp_headers[name.strip().lower()] = value.strip()
        n = int(resp_headers.get("content-length", 0) or 0)
        payload = json.loads(rfile.read(n)) if n else None
        return status, resp_headers, payload


def _predict(fe, *, priority=None, tenant=None, body=b"[1]", **kw):
    headers = {"content-type": "application/json"}
    if priority is not None:
        headers["x-priority"] = str(priority)
    if tenant is not None:
        headers["x-tenant"] = tenant
    return _request(
        fe, "POST", "/v1/predict", headers=headers, body=body, **kw
    )


# ---------------------------------------------------------------------------
# health / readiness gating
# ---------------------------------------------------------------------------


class TestHealthReady:
    def test_readyz_gates_on_warmup_then_drain(self, http_frontend):
        """/healthz is liveness (200 from first socket); /readyz is
        routability: 503 warming until the engine is warm, 200 ready,
        503 draining the instant the drain latch is set."""
        warm = threading.Event()
        fe = http_frontend(ready_fn=warm.is_set)
        status, _, body = _request(fe, "GET", "/healthz")
        assert status == 200 and body["status"] == "ok"
        status, headers, body = _request(fe, "GET", "/readyz")
        assert status == 503 and body["state"] == "warming"
        assert "retry-after" in headers
        warm.set()
        status, _, body = _request(fe, "GET", "/readyz")
        assert status == 200 and body["state"] == "ready"
        fe.drain(timeout=5.0)
        # the listener stays up just long enough to drain; the latch
        # itself is observable synchronously
        assert fe.draining

    def test_statsz_and_404(self, http_frontend):
        fe = http_frontend()
        status, _, body = _request(fe, "GET", "/statsz")
        assert status == 200
        assert body["state"] == "ready"
        assert len(body["batcher"]["per_priority"]) == 3
        status, _, body = _request(fe, "GET", "/nope")
        assert status == 404

    def test_undecodable_body_is_rejected_not_lost(self, http_frontend):
        """A malformed body 400s into its own ledger column — the
        identity completed+shed+failed+rejected == submitted survives
        bad clients instead of leaking a phantom submitted count."""
        fe = http_frontend()
        status, _, body = _predict(fe, priority=0, body=b"{not json")
        assert status == 400 and "undecodable" in body["error"]
        c = fe.accounting()["counts_by_priority"][0]
        assert c["submitted"] == 1 and c["rejected"] == 1
        assert (
            c["completed"] + c["failed"] + c["rejected"]
            + c["shed_draining"] + c["shed_over_quota"]
            + c["shed_queue_full"] + c["shed_unavailable"]
            == c["submitted"]
        )
        tenants = fe.stats()["admission"]["tenants"]
        assert tenants["anon"]["rejected"] == 1

    def test_bad_priority_is_400_not_reclassified(self, http_frontend):
        fe = http_frontend(priorities=2)
        for bad in ("7", "-1", "zero"):
            status, _, body = _predict(fe, priority=bad)
            assert status == 400, bad
            assert "x-priority" in body["error"]
        # absent header lands in the LOWEST class, not 400
        status, _, body = _predict(fe)
        assert status == 200 and body["priority"] == 1


# ---------------------------------------------------------------------------
# per-tenant admission: 429 vs 503
# ---------------------------------------------------------------------------


class TestQuota:
    def test_over_quota_is_429_and_isolated_per_tenant(
        self, http_frontend
    ):
        """A tenant with a 3-request budget gets exactly 3 through and
        429 after; an unthrottled tenant on the SAME server is
        untouched — quota exhaustion is the tenant's fault (429), not
        server overload (503)."""
        fe = http_frontend(quotas={"small": (0.0, 3.0)})
        codes = [
            _predict(fe, tenant="small", priority=0)[0] for _ in range(5)
        ]
        assert codes == [200, 200, 200, 429, 429]
        status, headers, body = _predict(fe, tenant="small", priority=0)
        assert status == 429
        assert body["error"] == "over_quota" and body["tenant"] == "small"
        assert "retry-after" in headers
        # the neighbor is unaffected
        assert _predict(fe, tenant="big", priority=0)[0] == 200
        tenants = fe.stats()["admission"]["tenants"]
        assert tenants["small"]["admitted"] == 3
        assert tenants["small"]["over_quota"] == 3
        assert tenants["big"]["over_quota"] == 0

    def test_bucket_refills_with_injected_clock(self, http_frontend):
        now = [0.0]
        fe = http_frontend(
            quotas={"t": (1.0, 1.0)}, clock=lambda: now[0]
        )
        assert _predict(fe, tenant="t")[0] == 200
        assert _predict(fe, tenant="t")[0] == 429
        now[0] += 2.0  # two seconds of refill at 1 req/s
        assert _predict(fe, tenant="t")[0] == 200


# ---------------------------------------------------------------------------
# strict-priority ordering + per-class bounds under a full queue
# ---------------------------------------------------------------------------


class TestPriorityOrdering:
    def test_priority0_overtakes_full_low_queue(self, http_frontend):
        """With the worker wedged and priority-2's queue FULL, a
        priority-0 request still gets in (its own queue) and executes
        FIRST when the worker resumes; further priority-2 submits shed
        503 queue-full without touching priority 0."""
        release = threading.Event()
        executed = []
        lock = threading.Lock()

        def runner(batch):
            release.wait(10)
            with lock:
                executed.extend(batch)
            return list(batch)

        fe = http_frontend(
            runner=runner, priorities=3, max_batch=1,
            max_delay_ms=0.0, max_queue=2,
        )
        results = {}

        def post(key, priority, payload):
            results[key] = _predict(
                fe, priority=priority,
                body=json.dumps(payload).encode(),
            )

        threads = []

        def spawn(key, priority, payload):
            t = threading.Thread(
                target=post, args=(key, priority, payload), daemon=True
            )
            t.start()
            threads.append(t)
            return t

        # wedge the worker: one in-flight request (popped from the
        # queue into the runner)
        spawn("wedge", 2, "wedge")
        deadline = time.monotonic() + 5
        while not executed and fe.stats()["inflight"] < 1:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        # fill priority-2's 2-slot queue
        spawn("low1", 2, "low1")
        spawn("low2", 2, "low2")
        deadline = time.monotonic() + 5
        while fe.batcher.stats()["per_priority"][2]["queue_depth"] < 2:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        # priority 0 still gets in — separate queue
        spawn("hi", 0, "hi")
        deadline = time.monotonic() + 5
        while fe.batcher.stats()["per_priority"][0]["queue_depth"] < 1:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        # priority-2 overflow sheds 503 queue-full (synchronous)
        status, _, body = _predict(fe, priority=2, body=b'"low3"')
        assert status == 503 and body["error"] == "queue full"
        release.set()
        for t in threads:
            t.join(10)
        assert all(r[0] == 200 for r in results.values()), results
        # the wedged request ran first (it was already in flight); the
        # priority-0 request overtook the two queued priority-2s
        assert executed[0] == "wedge"
        assert executed[1] == "hi"
        assert set(executed[2:]) == {"low1", "low2"}
        per_prio = fe.batcher.stats()["per_priority"]
        assert per_prio[0]["shed"] == 0
        assert per_prio[2]["shed"] == 1


# ---------------------------------------------------------------------------
# drain contract over a live connection
# ---------------------------------------------------------------------------


class TestDrainContract:
    def test_inflight_finishes_new_requests_shed(self, http_frontend):
        """The PR 5 drain contract over sockets: drain flips readyz to
        503 immediately, a request ALREADY accepted completes with 200,
        and a request arriving after the latch sheds 503 draining —
        nothing is dropped, nothing hangs."""
        release = threading.Event()

        def runner(batch):
            release.wait(10)
            return list(batch)

        fe = http_frontend(runner=runner, max_batch=4, max_delay_ms=0.0)
        inflight_result = {}

        def inflight_post():
            inflight_result["r"] = _predict(
                fe, priority=0, body=b'"inflight"', timeout=30
            )

        t = threading.Thread(target=inflight_post, daemon=True)
        t.start()
        deadline = time.monotonic() + 5
        while fe.stats()["inflight"] < 1:
            assert time.monotonic() < deadline
            time.sleep(0.01)

        drained = {}

        def do_drain():
            drained["clean"] = fe.drain(timeout=15.0)

        d = threading.Thread(target=do_drain, daemon=True)
        d.start()
        deadline = time.monotonic() + 5
        while not fe.draining:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        # readyz flipped BEFORE the in-flight request finished
        status, _, body = _request(fe, "GET", "/readyz")
        assert status == 503 and body["state"] == "draining"
        # a new request is shed explicitly, never silently queued
        status, _, body = _predict(fe, priority=0)
        assert status == 503 and body["error"] == "draining"
        release.set()
        t.join(10)
        d.join(15)
        assert drained.get("clean") is True
        assert inflight_result["r"][0] == 200
        acc = fe.accounting()
        counts = acc["counts_by_priority"][0]
        assert counts["completed"] == 1  # the in-flight one, answered
        assert counts["shed_draining"] >= 1


# ---------------------------------------------------------------------------
# scenario arrival processes (no server)
# ---------------------------------------------------------------------------


class TestScenarios:
    def test_deterministic_per_seed(self):
        a = build_schedule("flash_crowd", requests=200, rate=500, seed=7)
        b = build_schedule("flash_crowd", requests=200, rate=500, seed=7)
        c = build_schedule("flash_crowd", requests=200, rate=500, seed=8)
        assert a == b and a != c
        assert all(isinstance(x, Arrival) for x in a)
        assert all(a[i].t <= a[i + 1].t for i in range(len(a) - 1))

    def test_flash_crowd_burst_density(self):
        """The middle-sixth burst window carries a flash_factor-dense
        clump: its arrival rate is several times the baseline's."""
        requests, rate = 2000, 1000.0
        sched = build_schedule(
            "flash_crowd", requests=requests, rate=rate, seed=0,
            flash_factor=10.0,
        )
        duration = requests / rate
        t0, t1 = duration / 3.0, duration / 3.0 + duration / 6.0
        burst = [a.t for a in sched if t0 <= a.t < t1]
        before = sum(1 for a in sched if a.t < t0)
        # measure density over the span the burst actually occupied:
        # the fixed request budget may exhaust before the window ends
        rate_burst = len(burst) / max(burst[-1] - burst[0], 1e-9)
        rate_before = max(before / t0, 1.0)
        assert rate_burst > 4.0 * rate_before

    def test_heavy_tail_is_heavier_than_poisson(self):
        """Lognormal gaps: matched mean, but the max gap dwarfs the
        median by far more than the memoryless process's does."""
        heavy = build_schedule(
            "heavy_tail", requests=2000, rate=1000, seed=0,
            heavy_sigma=1.5,
        )
        poisson = build_schedule(
            "poisson", requests=2000, rate=1000, seed=0
        )

        def gaps(sched):
            ts = [a.t for a in sched]
            return sorted(
                t2 - t1 for t1, t2 in zip(ts, ts[1:])
            )

        hg, pg = gaps(heavy), gaps(poisson)
        ratio_h = hg[-1] / max(percentile(hg, 50.0), 1e-12)
        ratio_p = pg[-1] / max(percentile(pg, 50.0), 1e-12)
        assert ratio_h > 3.0 * ratio_p

    def test_diurnal_swings_between_half_cycles(self):
        sched = build_schedule(
            "diurnal", requests=2000, rate=1000, seed=3, diurnal_amp=0.8,
        )
        duration = 2000 / 1000.0
        first_half = sum(1 for a in sched if a.t % duration < duration / 2)
        second_half = len(sched) - first_half
        # sin > 0 over the first half-cycle: it must carry clearly more
        assert first_half > 1.3 * second_half

    def test_slow_client_fraction_and_exclusivity(self):
        sched = build_schedule(
            "slow_client", requests=1000, rate=500, seed=1,
            slow_fraction=0.25,
        )
        frac = sum(1 for a in sched if a.slow) / len(sched)
        assert 0.15 < frac < 0.35
        for scenario in ("poisson", "flash_crowd"):
            assert not any(
                a.slow
                for a in build_schedule(
                    scenario, requests=100, rate=100, seed=0
                )
            )

    def test_priority_and_tenant_mix(self):
        sched = build_schedule(
            "poisson", requests=3000, rate=1000, seed=0,
            priorities=3, tenants=("a", "b"), tenant_weights=(0.8, 0.2),
        )
        by_p = [0, 0, 0]
        by_t = {"a": 0, "b": 0}
        for arr in sched:
            by_p[arr.priority] += 1
            by_t[arr.tenant] += 1
        # default mix 10/30/60 within sampling noise
        assert 0.05 < by_p[0] / 3000 < 0.15
        assert by_p[2] > by_p[1] > by_p[0]
        assert by_t["a"] > 2.5 * by_t["b"]

    def test_bad_inputs_fail_loudly(self):
        with pytest.raises(ValueError, match="unknown scenario"):
            build_schedule("tsunami", requests=10, rate=10, seed=0)
        with pytest.raises(ValueError, match="priority_weights"):
            build_schedule(
                "poisson", requests=10, rate=10, seed=0,
                priorities=2, priority_weights=(1.0,),
            )


# ---------------------------------------------------------------------------
# verdict v2 assembly (no server)
# ---------------------------------------------------------------------------


class TestVerdictV2:
    def _accounting(self):
        return {
            "wall_s": 2.0,
            "latencies_ms_by_priority": [
                [1.0, 2.0, 3.0], [5.0, 6.0], [],
            ],
            "counts_by_priority": [
                {"submitted": 3, "completed": 3, "failed": 0,
                 "shed_draining": 0, "shed_over_quota": 0,
                 "shed_queue_full": 0},
                {"submitted": 3, "completed": 2, "failed": 0,
                 "shed_draining": 0, "shed_over_quota": 1,
                 "shed_queue_full": 0},
                {"submitted": 4, "completed": 0, "failed": 0,
                 "shed_draining": 1, "shed_over_quota": 0,
                 "shed_queue_full": 3},
            ],
            "requests_seen": 10,
        }

    def _admission(self):
        return {
            "draining": True,
            "default_rate": 100.0,
            "default_burst": 100.0,
            "tenants": {
                "a": {"admitted": 5, "over_quota": 0, "shed": 2,
                      "completed": 3, "failed": 0, "shed_rate": 0.4,
                      "quota_rate": 100.0, "quota_burst": 100.0},
                "b": {"admitted": 4, "over_quota": 1, "shed": 1,
                      "completed": 2, "failed": 0, "shed_rate": 0.4,
                      "quota_rate": 10.0, "quota_burst": 10.0},
            },
        }

    def test_per_priority_blocks_and_strict_json(self):
        v = http_slo_verdict(
            self._accounting(), {"mean_occupancy": 0.5, "batches": 4,
                                 "max_queue_depth_seen": 3,
                                 "max_queue": 8},
            self._admission(),
            scenario="flash_crowd", rate=100.0, seed=0,
            slo_p99_ms=10.0,
        )
        assert v["serve_verdict"] == 8
        assert v["scenario"] == "flash_crowd"
        # aggregate identity
        assert v["requests_submitted"] == 10
        assert v["requests_completed"] == 5
        assert v["requests_shed"] == 5
        p0 = v["per_priority"]["0"]
        assert p0["p99_ms"] == 3.0 and p0["shed"] == 0
        p2 = v["per_priority"]["2"]
        assert p2["p99_ms"] is None  # empty window -> null, no crash
        assert p2["shed_queue_full"] == 3 and p2["shed_rate"] == 1.0
        # per-tenant: submitted = admitted + over_quota
        assert v["per_tenant"]["b"]["submitted"] == 5
        assert v["fairness_ratio"] == pytest.approx(
            (3 / 5) / (2 / 5), abs=1e-4
        )
        assert v["slo"] == {
            "p99_ms_target_priority0": 10.0,
            "p99_ms_priority0": 3.0,
            "met": True,
        }
        # strict RFC 8259 round trip
        line = json.dumps(v, allow_nan=False, sort_keys=True)
        json.loads(
            line, parse_constant=lambda s: pytest.fail(f"bare {s}")
        )

    def test_fairness_ratio_edge_cases(self):
        assert fairness_ratio({}) is None
        assert fairness_ratio(
            {"a": {"submitted": 5, "completed": 5}}
        ) is None  # one tenant: nothing to compare
        assert fairness_ratio({
            "a": {"submitted": 5, "completed": 5},
            "b": {"submitted": 5, "completed": 0},
        }) is None  # starved tenant: not a finite ratio
        assert fairness_ratio({
            "a": {"submitted": 10, "completed": 10},
            "b": {"submitted": 10, "completed": 5},
        }) == 2.0

    def test_percentile_rejects_bad_q(self):
        with pytest.raises(ValueError, match="percentile q"):
            percentile([1.0], 101.0)
        with pytest.raises(ValueError, match="percentile q"):
            percentile([1.0], -0.1)
        assert percentile([], 99.0) is None
        assert percentile([7.0], 0.0) == 7.0
        assert percentile([7.0], 100.0) == 7.0


# ---------------------------------------------------------------------------
# flash crowd against a stub front end (fast): priority isolation
# ---------------------------------------------------------------------------


class TestFlashCrowdStub:
    def test_priority0_protected_sheds_only_low_or_quota(
        self, http_frontend
    ):
        """The acceptance shape at stub scale: a flash crowd overloads
        the server; priority-0 traffic all completes (strict-priority
        dequeue + its own queue) while shedding falls on the low
        classes and the throttled tenant; every request gets a
        response (zero dropped)."""

        def runner(batch):
            time.sleep(0.004)
            return list(batch)

        fe = http_frontend(
            runner=runner, priorities=3, max_batch=4,
            max_delay_ms=1.0, max_queue=4,
            quotas={"greedy": (50.0, 10.0)},
        )
        sched = build_schedule(
            "flash_crowd", requests=400, rate=400, seed=2,
            flash_factor=8.0, tenants=("calm", "greedy"),
        )
        gen = HttpLoadGenerator(
            fe.host, fe.port, sched,
            body_fn=lambda i: json.dumps(i).encode(),
            content_type="application/json", concurrency=16,
        )
        raw = gen.run()
        assert raw["dropped"] == 0
        assert raw["responses"] == raw["submitted"] == 400
        clean = fe.drain(timeout=15.0)
        assert clean
        v = http_slo_verdict(
            fe.accounting(), fe.batcher.stats(),
            fe.admission.stats(), scenario="flash_crowd",
            rate=400.0, seed=2, client=raw,
        )
        # accounting identity server-side
        assert (
            v["requests_completed"] + v["requests_shed"]
            + v["requests_failed"]
            == v["requests_submitted"] == 400
        )
        # the burst forced real shedding...
        assert v["requests_shed"] > 0
        # ...but priority 0 never lost a request to SERVER overload —
        # its only sheds are over-quota 429s (the greedy tenant's own
        # fault), never queue-full/draining 503s
        p0 = v["per_priority"]["0"]
        assert p0["shed_queue_full"] == 0 and p0["shed_draining"] == 0
        assert p0["completed"] == p0["submitted"] - p0["shed_over_quota"]
        shed_by_class = {
            p: blk["shed"] for p, blk in v["per_priority"].items()
        }
        assert sum(shed_by_class.values()) == v["requests_shed"]
        # the overloaded classes DID shed on the queue bound
        assert (
            v["per_priority"]["1"]["shed_queue_full"]
            + v["per_priority"]["2"]["shed_queue_full"]
            > 0
        )
        # the throttled tenant's over-quota rejects are visible per
        # tenant; the calm tenant never hit its bucket
        assert v["per_tenant"]["greedy"]["over_quota"] > 0
        assert v["per_tenant"]["calm"]["over_quota"] == 0
        # strict JSON end to end
        json.dumps(v, allow_nan=False)


# ---------------------------------------------------------------------------
# ServeHttpConfig validation
# ---------------------------------------------------------------------------


class TestServeHttpConfig:
    def test_validate_rejects_bad_knobs(self):
        from bdbnn_tpu.configs.config import ServeHttpConfig

        ok = ServeHttpConfig(artifact="a").validate()
        assert ok.priorities == 3 and ok.scenario == ""
        with pytest.raises(ValueError, match="artifact"):
            ServeHttpConfig(artifact="").validate()
        with pytest.raises(ValueError, match="scenario"):
            ServeHttpConfig(artifact="a", scenario="tsunami").validate()
        with pytest.raises(ValueError, match="priorities"):
            ServeHttpConfig(artifact="a", priorities=0).validate()
        with pytest.raises(ValueError, match="queue-depth"):
            ServeHttpConfig(artifact="a", queue_depth=0).validate()
        with pytest.raises(ValueError, match="TENANT"):
            ServeHttpConfig(
                artifact="a", tenant_quotas=("broken",)
            ).validate()
        # quota VALUES are range-checked at config time too, not at
        # the first request after the run dir already exists
        with pytest.raises(ValueError, match="tenant-quota"):
            ServeHttpConfig(
                artifact="a", tenant_quotas=("t=10:0",)
            ).validate()
        with pytest.raises(ValueError, match="priority-weights"):
            ServeHttpConfig(
                artifact="a", priority_weights=(1.0,)
            ).validate()
        with pytest.raises(ValueError, match="slow-fraction"):
            ServeHttpConfig(artifact="a", slow_fraction=1.5).validate()
        with pytest.raises(ValueError, match="default-quota"):
            ServeHttpConfig(
                artifact="a", default_quota="10:0"
            ).validate()
        # replica-pool / swap orchestration knobs
        with pytest.raises(ValueError, match="replicas"):
            ServeHttpConfig(artifact="a", replicas=0).validate()
        with pytest.raises(ValueError, match="swap-at"):
            ServeHttpConfig(
                artifact="a", scenario="poisson", swap_to="v0002",
                swap_at=1.0,
            ).validate()
        with pytest.raises(ValueError, match="swap-to"):
            ServeHttpConfig(
                artifact="a", scenario="poisson", swap_at=0.5
            ).validate()
        with pytest.raises(ValueError, match="scenario"):
            ServeHttpConfig(
                artifact="a", swap_to="v0002", swap_at=0.5
            ).validate()
        # --swap-to under a scenario with no --swap-at would run the
        # whole bench without ever firing the requested swap and exit
        # 0 with a null swap block — refuse at config time
        with pytest.raises(ValueError, match="swap-at"):
            ServeHttpConfig(
                artifact="a", scenario="poisson", swap_to="v0002"
            ).validate()
        # serve mode (no scenario): --swap-to alone stays legal — the
        # swap is driven externally via POST /admin/swap
        ServeHttpConfig(artifact="a", swap_to="v0002").validate()
        assert ServeHttpConfig(artifact="a").pooled is False
        assert ServeHttpConfig(artifact="a", replicas=2).pooled is True
        assert ServeHttpConfig(artifact="a", registry="r").pooled is True


# ---------------------------------------------------------------------------
# end-to-end over a real export artifact: the acceptance pin
# ---------------------------------------------------------------------------


def _http_cfg(art_dir, tmp_path, **kw):
    from bdbnn_tpu.configs.config import ServeHttpConfig

    base = dict(
        artifact=art_dir,
        log_path=str(tmp_path / "serve_http"),
        buckets=(1, 4),
        priorities=3,
        queue_depth=8,
        max_delay_ms=2.0,
        scenario="flash_crowd",
        rate=150.0,
        requests=120,
        concurrency=8,
        seed=0,
        default_quota="1000:1000",
        stats_interval_s=0.2,
    )
    base.update(kw)
    return ServeHttpConfig(**base)


class TestServeHttpEndToEnd:
    def test_sigterm_mid_flash_crowd_zero_dropped(
        self, exported_artifact, tmp_path
    ):
        """THE acceptance criterion: SIGTERM lands mid-flash-crowd;
        the front end flips readyz, stops admitting, answers every
        accepted request, and the verdict (preempted, drained clean,
        zero client-side dropped) lands last — over real sockets and
        the real AOT engine."""
        from bdbnn_tpu.obs.events import read_events
        from bdbnn_tpu.obs.summarize import summarize_run
        from bdbnn_tpu.obs.watch import render_status
        from bdbnn_tpu.serve.http import run_serve_http

        art_dir, _ = exported_artifact
        cfg = _http_cfg(
            art_dir, tmp_path, requests=10_000, rate=100.0,
        )
        pid = os.getpid()
        killer = threading.Timer(
            2.5, lambda: os.kill(pid, signal.SIGTERM)
        )
        killer.start()
        try:
            res = run_serve_http(cfg)
        finally:
            killer.cancel()
        v = res["verdict"]
        assert v["preempted"] is True
        assert v["drained_clean"] is True
        # zero dropped: every request the client put on the wire got a
        # response — 200 or an explicit shed — across the SIGTERM
        assert v["client"]["dropped"] == 0
        assert v["client"]["responses"] == v["client"]["submitted"]
        # the run was actually cut short, not completed
        assert v["client"]["submitted"] < 10_000
        # server-side ledger identity
        assert (
            v["requests_completed"] + v["requests_shed"]
            + v["requests_failed"]
            == v["requests_submitted"]
        )
        assert v["requests_failed"] == 0
        # run-dir artifacts: manifest + events + verdict, same contract
        # as serve-bench
        with open(os.path.join(res["run_dir"], "verdict.json")) as f:
            assert json.load(f) == v
        events = read_events(res["run_dir"])
        kinds = {e["kind"] for e in events}
        assert {"http", "admission", "serve"} <= kinds
        https = [e for e in events if e["kind"] == "http"]
        phases = [e["phase"] for e in https]
        assert phases[0] == "start" and "ready" in phases
        assert "drain" in phases and phases[-1] == "stop"
        drain_ev = next(e for e in https if e["phase"] == "drain")
        assert drain_ev["signum"] == signal.SIGTERM
        # watch + summarize consume the run dir unchanged
        status = render_status(events, None)
        assert "http:" in status and "SLO:" in status
        report, summary = summarize_run(res["run_dir"])
        assert summary["serving"]["http"]["port"] == res["port"]
        assert summary["serving"]["verdict"]["per_priority"] is not None
        assert "p99" in report

    @pytest.mark.slow
    def test_flash_crowd_soak_priority0_slo(
        self, exported_artifact, tmp_path
    ):
        """The flash-crowd soak at full scale: priority-0 p99 stays
        within the SLO while shedding falls only on low-priority /
        over-quota traffic."""
        from bdbnn_tpu.serve.http import run_serve_http

        art_dir, _ = exported_artifact
        cfg = _http_cfg(
            art_dir, tmp_path, requests=2000, rate=400.0,
            flash_factor=8.0, queue_depth=8, concurrency=24,
            slo_p99_ms=2000.0,
            tenant_quotas=("greedy=100:50",),
            tenants=("calm", "greedy"),
        )
        res = run_serve_http(cfg)
        v = res["verdict"]
        assert v["client"]["dropped"] == 0
        assert v["requests_failed"] == 0
        p0 = v["per_priority"]["0"]
        assert p0["shed_queue_full"] == 0 and p0["shed_draining"] == 0, (
            "server-overload shedding fell on priority 0"
        )
        assert v["slo"]["met"], (
            f"priority-0 p99 {p0['p99_ms']}ms missed the "
            f"{cfg.slo_p99_ms}ms SLO"
        )
        assert v["per_tenant"]["greedy"]["over_quota"] > 0
        assert v["per_tenant"]["calm"]["over_quota"] == 0

    @pytest.mark.slow
    def test_slow_client_soak(self, exported_artifact, tmp_path):
        """Slow writers dribbling bodies must not stall fast clients
        or break the ledger: every request answered, zero dropped."""
        from bdbnn_tpu.serve.http import run_serve_http

        art_dir, _ = exported_artifact
        cfg = _http_cfg(
            art_dir, tmp_path, scenario="slow_client", requests=600,
            rate=150.0, slow_fraction=0.25, concurrency=24,
        )
        res = run_serve_http(cfg)
        v = res["verdict"]
        assert v["client"]["dropped"] == 0
        assert v["client"]["responses"] == v["client"]["submitted"]
        assert (
            v["requests_completed"] + v["requests_shed"]
            == v["requests_submitted"]
        )
        assert v["requests_failed"] == 0
        assert v["drained_clean"] and not v["preempted"]


# ---------------------------------------------------------------------------
# inbound x-rtrace trace-context hardening (PR 16): a non-fleet
# client poking the trace header — malformed, oversized, duplicated —
# must be IGNORED (fresh local trace), never a 500, never a crash
# ---------------------------------------------------------------------------


def _wait_traced(tracer, n=1, timeout=5.0):
    """The local trace finishes AFTER the response flush — poll
    briefly instead of racing the server's last stamp."""
    deadline = time.time() + timeout
    while tracer.finished < n and time.time() < deadline:
        time.sleep(0.005)
    assert tracer.finished == n


class TestTraceContextHardening:
    def _traced_fe(self, http_frontend):
        from bdbnn_tpu.obs.rtrace import RequestTracer

        tracer = RequestTracer(seed=0, sample_every=10**9)
        fe = http_frontend(
            lambda batch: list(batch), tracer=tracer,
        )
        return fe, tracer

    def _predict_with(self, fe, rtrace_value):
        return _request(
            fe, "POST", "/v1/predict",
            headers={"x-priority": "0", "x-tenant": "tenant-a",
                     "x-rtrace": rtrace_value},
            body=b"[1]",
        )

    @pytest.mark.parametrize("bad", [
        "garbage",
        "v=1;id=not-hex;seq=0;p=0",
        "v=9;id=0123456789abcdef;seq=0;p=0",
        "v=1;id=0123456789abcdef;seq=-3;p=0",
        "v=1;id=0123456789abcdef;seq=0;p=0;tn=sp ace",
        "v=1;id=0123456789abcdef;seq=0;p=0;" + "x" * 400,  # oversized
        "\x00\x01\x02binary",
    ])
    def test_malformed_header_means_fresh_local_trace(
        self, http_frontend, bad
    ):
        fe, tracer = self._traced_fe(http_frontend)
        status, resp_headers, payload = self._predict_with(fe, bad)
        # answered normally — and WITHOUT a stage header (that reply
        # is the fleet stitching contract; a garbage context gets a
        # fresh local trace instead, which has nothing to echo)
        assert status == 200, payload
        assert "x-rtrace-stages" not in resp_headers
        _wait_traced(tracer)
        # and the server is still alive for the next client
        status, _, _ = _request(fe, "GET", "/healthz")
        assert status == 200

    def test_duplicate_header_is_ignored(self, http_frontend):
        fe, tracer = self._traced_fe(http_frontend)
        ctx = "v=1;id=0123456789abcdef;seq=0;p=0"
        body = b"[1]"
        # two x-rtrace lines on the wire: which hop minted it? —
        # ambiguous, so the front end must fall back to a local trace
        with socket.create_connection(
            (fe.host, fe.port), timeout=10.0
        ) as s:
            head = (
                "POST /v1/predict HTTP/1.1\r\nhost: t\r\n"
                "x-priority: 0\r\nx-tenant: tenant-a\r\n"
                f"x-rtrace: {ctx}\r\n"
                f"x-rtrace: {ctx}\r\n"
                f"content-length: {len(body)}\r\n"
                "connection: close\r\n\r\n"
            )
            s.sendall(head.encode("latin-1") + body)
            rfile = s.makefile("rb")
            status = int(rfile.readline().split()[1])
            resp_headers = {}
            while True:
                h = rfile.readline()
                if h in (b"\r\n", b"\n", b""):
                    break
                name, _, value = h.decode("latin-1").partition(":")
                resp_headers[name.strip().lower()] = value.strip()
        assert status == 200
        assert "x-rtrace-stages" not in resp_headers
        _wait_traced(tracer)

    def test_valid_context_is_adopted_and_stages_echoed(
        self, http_frontend
    ):
        from bdbnn_tpu.obs.rtrace import parse_stage_header

        fe, tracer = self._traced_fe(http_frontend)
        status, resp_headers, payload = self._predict_with(
            fe, "v=1;id=0123456789abcdef;seq=7;p=0;tn=tenant-a",
        )
        assert status == 200, payload
        parsed = parse_stage_header(resp_headers["x-rtrace-stages"])
        assert parsed is not None
        # the backend continues the SAME trace: the echoed id is the
        # router's, and the header's stage sum equals its own total
        # EXACTLY (the pre-write gap is folded into respond, so the
        # only unattributed time is the final socket write — which
        # lands in the router's network stage by construction)
        assert parsed["id"] == "0123456789abcdef"
        assert sum(parsed["stages"].values()) == pytest.approx(
            parsed["total_ms"], abs=0.005,
        )
        _wait_traced(tracer)
