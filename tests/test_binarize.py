"""Gradient-correctness tests for the binarization custom_vjps.

Mirrors the test strategy SURVEY.md §4 prescribes: STE/EDE gradients vs
the closed-form clipped-identity / polynomial / annealed-tanh estimators.
"""

import jax
import jax.numpy as jnp
import numpy as np

from bdbnn_tpu.nn.binarize import (
    approx_sign,
    binarize_act,
    binarize_weight,
    ede_sign,
    ste_sign,
)

X = jnp.array([-2.5, -1.0, -0.5, -0.0, 0.0, 0.3, 1.0, 1.7])


def test_sign_forward_is_pm1():
    for fn in (ste_sign, approx_sign):
        y = fn(X)
        np.testing.assert_array_equal(
            np.asarray(y), np.array([-1, -1, -1, 1, 1, 1, 1, 1], np.float32)
        )
    y = ede_sign(X, jnp.float32(0.1), jnp.float32(10.0))
    np.testing.assert_array_equal(
        np.asarray(y), np.array([-1, -1, -1, 1, 1, 1, 1, 1], np.float32)
    )


def test_ste_grad_is_clipped_identity():
    g = jax.grad(lambda x: ste_sign(x).sum())(X)
    expect = (np.abs(np.asarray(X)) <= 1.0).astype(np.float32)
    np.testing.assert_allclose(np.asarray(g), expect)


def test_approx_sign_grad_is_birealnet_polynomial():
    g = jax.grad(lambda x: approx_sign(x).sum())(X)
    xa = np.abs(np.asarray(X))
    expect = np.where(xa < 1.0, 2.0 - 2.0 * xa, 0.0).astype(np.float32)
    np.testing.assert_allclose(np.asarray(g), expect, rtol=1e-6)


def test_ede_grad_matches_closed_form():
    for t, k in [(1e-2, 100.0), (0.5, 2.0), (10.0, 1.0)]:
        g = jax.grad(
            lambda x: ede_sign(x, jnp.float32(t), jnp.float32(k)).sum()
        )(X)
        expect = k * t * (1.0 - np.tanh(t * np.asarray(X)) ** 2)
        np.testing.assert_allclose(np.asarray(g), expect, rtol=1e-3, atol=1e-6)


def test_ede_tk_change_does_not_retrace():
    traces = []

    @jax.jit
    def f(x, t, k):
        traces.append(1)
        return ede_sign(x, t, k).sum()

    f(X, jnp.float32(0.1), jnp.float32(10.0))
    f(X, jnp.float32(5.0), jnp.float32(1.0))
    assert len(traces) == 1


def test_binarize_weight_values_and_scale():
    w = jnp.array([[1.0, -2.0], [3.0, -4.0], [-0.5, 0.5]])  # (in=3, out=2)
    b = binarize_weight(w)
    alpha = np.mean(np.abs(np.asarray(w)), axis=0)  # per out-channel
    np.testing.assert_allclose(
        np.asarray(b), np.sign(np.asarray(w) + 1e-30) * alpha, rtol=1e-6
    )


def test_binarize_weight_grad_flows_through_ste_only():
    w = jnp.array([[0.5, -2.0], [0.3, -0.1]])
    g = jax.grad(lambda w: binarize_weight(w).sum())(w)
    # scale detached: grad = alpha * 1{|w|<=1}
    alpha = np.mean(np.abs(np.asarray(w)), axis=0)
    expect = alpha[None, :] * (np.abs(np.asarray(w)) <= 1.0)
    np.testing.assert_allclose(np.asarray(g), expect, rtol=1e-6)


def test_binarize_act_dispatch():
    x = jnp.linspace(-2, 2, 8)
    np.testing.assert_array_equal(
        np.asarray(binarize_act(x)), np.asarray(ste_sign(x))
    )
    np.testing.assert_array_equal(
        np.asarray(binarize_act(x, estimator="approx")),
        np.asarray(approx_sign(x)),
    )
    g = jax.grad(lambda x: binarize_act(x, tk=(0.5, 2.0)).sum())(x)
    expect = 2.0 * 0.5 * (1 - np.tanh(0.5 * np.asarray(x)) ** 2)
    np.testing.assert_allclose(np.asarray(g), expect, rtol=1e-5)


def test_binarization_under_jit_and_vmap():
    f = jax.jit(jax.vmap(lambda x: ste_sign(x) * 2.0))
    x = jnp.ones((4, 8)) * 0.5
    np.testing.assert_allclose(np.asarray(f(x)), 2.0 * np.ones((4, 8)))
