"""Gradient-correctness tests for the binarization custom_vjps and the
binarizer-family registry.

Mirrors the test strategy SURVEY.md §4 prescribes: STE/EDE gradients vs
the closed-form clipped-identity / polynomial / annealed-tanh
estimators, extended per family — proximal tent backward, stochastic
forward expectation, loss-aware alpha — plus the registry pins: the
default family routes through EXACTLY the legacy functions (bitwise),
schedules never retrace, specs validate at parse time.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bdbnn_tpu.nn.binarize import (
    active_family,
    approx_sign,
    binarize_act,
    binarize_weight,
    ede_sign,
    get_active_family,
    make_family,
    parse_binarizer,
    prox_sign,
    resolve_family,
    ste_sign,
    stoch_sign,
)

X = jnp.array([-2.5, -1.0, -0.5, -0.0, 0.0, 0.3, 1.0, 1.7])


def test_sign_forward_is_pm1():
    for fn in (ste_sign, approx_sign):
        y = fn(X)
        np.testing.assert_array_equal(
            np.asarray(y), np.array([-1, -1, -1, 1, 1, 1, 1, 1], np.float32)
        )
    y = ede_sign(X, jnp.float32(0.1), jnp.float32(10.0))
    np.testing.assert_array_equal(
        np.asarray(y), np.array([-1, -1, -1, 1, 1, 1, 1, 1], np.float32)
    )


def test_ste_grad_is_clipped_identity():
    g = jax.grad(lambda x: ste_sign(x).sum())(X)
    expect = (np.abs(np.asarray(X)) <= 1.0).astype(np.float32)
    np.testing.assert_allclose(np.asarray(g), expect)


def test_approx_sign_grad_is_birealnet_polynomial():
    g = jax.grad(lambda x: approx_sign(x).sum())(X)
    xa = np.abs(np.asarray(X))
    expect = np.where(xa < 1.0, 2.0 - 2.0 * xa, 0.0).astype(np.float32)
    np.testing.assert_allclose(np.asarray(g), expect, rtol=1e-6)


def test_ede_grad_matches_closed_form():
    for t, k in [(1e-2, 100.0), (0.5, 2.0), (10.0, 1.0)]:
        g = jax.grad(
            lambda x: ede_sign(x, jnp.float32(t), jnp.float32(k)).sum()
        )(X)
        expect = k * t * (1.0 - np.tanh(t * np.asarray(X)) ** 2)
        np.testing.assert_allclose(np.asarray(g), expect, rtol=1e-3, atol=1e-6)


def test_ede_tk_change_does_not_retrace():
    traces = []

    @jax.jit
    def f(x, t, k):
        traces.append(1)
        return ede_sign(x, t, k).sum()

    f(X, jnp.float32(0.1), jnp.float32(10.0))
    f(X, jnp.float32(5.0), jnp.float32(1.0))
    assert len(traces) == 1


def test_binarize_weight_values_and_scale():
    w = jnp.array([[1.0, -2.0], [3.0, -4.0], [-0.5, 0.5]])  # (in=3, out=2)
    b = binarize_weight(w)
    alpha = np.mean(np.abs(np.asarray(w)), axis=0)  # per out-channel
    np.testing.assert_allclose(
        np.asarray(b), np.sign(np.asarray(w) + 1e-30) * alpha, rtol=1e-6
    )


def test_binarize_weight_grad_flows_through_ste_only():
    w = jnp.array([[0.5, -2.0], [0.3, -0.1]])
    g = jax.grad(lambda w: binarize_weight(w).sum())(w)
    # scale detached: grad = alpha * 1{|w|<=1}
    alpha = np.mean(np.abs(np.asarray(w)), axis=0)
    expect = alpha[None, :] * (np.abs(np.asarray(w)) <= 1.0)
    np.testing.assert_allclose(np.asarray(g), expect, rtol=1e-6)


def test_binarize_act_dispatch():
    x = jnp.linspace(-2, 2, 8)
    np.testing.assert_array_equal(
        np.asarray(binarize_act(x)), np.asarray(ste_sign(x))
    )
    np.testing.assert_array_equal(
        np.asarray(binarize_act(x, estimator="approx")),
        np.asarray(approx_sign(x)),
    )
    g = jax.grad(lambda x: binarize_act(x, tk=(0.5, 2.0)).sum())(x)
    expect = 2.0 * 0.5 * (1 - np.tanh(0.5 * np.asarray(x)) ** 2)
    np.testing.assert_allclose(np.asarray(g), expect, rtol=1e-5)


def test_binarization_under_jit_and_vmap():
    f = jax.jit(jax.vmap(lambda x: ste_sign(x) * 2.0))
    x = jnp.ones((4, 8)) * 0.5
    np.testing.assert_allclose(np.asarray(f(x)), 2.0 * np.ones((4, 8)))


# ---------------------------------------------------------------------------
# Proximal family (arXiv:2402.17710)
# ---------------------------------------------------------------------------


class TestProxSign:
    def test_forward_is_pm1_with_sign0_plus1(self):
        y = prox_sign(X, jnp.float32(0.7))
        np.testing.assert_array_equal(
            np.asarray(y),
            np.array([-1, -1, -1, 1, 1, 1, 1, 1], np.float32),
        )

    def test_backward_is_unit_mass_tent(self):
        """dL/dx = (2/δ)·max(0, 1 − |x|/δ): closed form at several δ,
        and the mass ∫ dx == 2 for every δ (what the clipped-identity
        STE passes over [-1, 1]) — sharpening concentrates, never
        attenuates."""
        for delta in (0.25, 1.0, 2.0):
            g = jax.grad(
                lambda x: prox_sign(x, jnp.float32(delta)).sum()
            )(X)
            xa = np.abs(np.asarray(X))
            expect = (2.0 / delta) * np.clip(1.0 - xa / delta, 0.0, None)
            np.testing.assert_allclose(
                np.asarray(g), expect.astype(np.float32), rtol=1e-5
            )
        # tent mass: base 2δ x height 2/δ / 2 == 2, δ-independent
        xs = np.linspace(-4, 4, 20001, dtype=np.float64)
        dx = xs[1] - xs[0]
        for delta in (0.25, 1.0, 2.0):
            tent = (2.0 / delta) * np.clip(1.0 - np.abs(xs) / delta, 0, None)
            assert float(tent.sum() * dx) == pytest.approx(2.0, rel=1e-3)

    def test_delta_one_equals_bireal_polynomial(self):
        g1 = jax.grad(lambda x: prox_sign(x, jnp.float32(1.0)).sum())(X)
        g2 = jax.grad(lambda x: approx_sign(x).sum())(X)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   rtol=1e-6)

    def test_delta_change_does_not_retrace(self):
        """The schedule no-retrace pin, proximal edition: annealing δ
        across epochs must reuse the one compiled step (the EDE (t, k)
        discipline)."""
        traces = []

        @jax.jit
        def f(x, delta):
            traces.append(1)
            return prox_sign(x, delta).sum()

        f(X, jnp.float32(2.0))
        f(X, jnp.float32(0.5))
        assert len(traces) == 1

    def test_schedule_anneals_log_linearly(self):
        fam = make_family("proximal", {"delta0": 2.0, "delta1": 0.5})
        (d0,) = fam.schedule(0, 4)
        (d4,) = fam.schedule(4, 4)
        assert d0 == pytest.approx(2.0)
        assert d4 == pytest.approx(0.5)
        (dmid,) = fam.schedule(2, 4)
        assert dmid == pytest.approx((2.0 * 0.5) ** 0.5)  # log-linear


# ---------------------------------------------------------------------------
# Stochastic family (BinaryNet, arXiv:1602.02830)
# ---------------------------------------------------------------------------


class TestStochSign:
    def test_deterministic_outside_unit_interval(self):
        """P(+1) = hard-sigmoid: saturated at |x| >= 1, so the sample
        equals the hard sign there for EVERY draw."""
        x = jnp.array([-3.0, -1.0, 1.0, 2.5])
        for i in range(16):
            u = jax.random.uniform(jax.random.PRNGKey(i), x.shape)
            np.testing.assert_array_equal(
                np.asarray(stoch_sign(x, u)),
                np.array([-1, -1, 1, 1], np.float32),
            )

    def test_fixed_key_is_deterministic(self):
        u = jax.random.uniform(jax.random.PRNGKey(7), X.shape)
        a = np.asarray(stoch_sign(X, u))
        b = np.asarray(stoch_sign(X, u))
        np.testing.assert_array_equal(a, b)
        assert set(np.unique(a)) <= {-1.0, 1.0}

    def test_expectation_approx_hard_sign_envelope(self):
        """E[stoch_sign(x)] = 2·σ̂(x) − 1 = clip(x, −1, 1) — equal to
        the hard sign wherever it saturates, the linear envelope
        between."""
        n = 4000
        acc = np.zeros(X.shape, np.float64)
        for i in range(n):
            u = jax.random.uniform(jax.random.PRNGKey(i), X.shape)
            acc += np.asarray(stoch_sign(X, u))
        mean = acc / n
        np.testing.assert_allclose(
            mean, np.clip(np.asarray(X), -1.0, 1.0), atol=0.05
        )

    def test_backward_is_clipped_identity(self):
        u = jax.random.uniform(jax.random.PRNGKey(3), X.shape)
        g = jax.grad(lambda x: stoch_sign(x, u).sum())(X)
        expect = (np.abs(np.asarray(X)) <= 1.0).astype(np.float32)
        np.testing.assert_allclose(np.asarray(g), expect)

    def test_no_rng_falls_back_to_hard_sign(self):
        """Eval/serving convention: without a key the family is the
        deterministic sign (sign(0) := +1 included)."""
        fam = make_family("stochastic")
        np.testing.assert_array_equal(
            np.asarray(fam.binarize_act(X, rng=None)),
            np.asarray(ste_sign(X)),
        )


# ---------------------------------------------------------------------------
# Loss-aware family (arXiv:1611.01600)
# ---------------------------------------------------------------------------


class TestLabFamily:
    def test_alpha_is_curvature_weighted(self):
        """alpha = ||d∘W||₁/||d||₁ with d = |W| -> ΣW²/Σ|W| per output
        channel — upweights large-magnitude weights vs plain mean|W|."""
        w = jnp.array([[1.0, -2.0], [3.0, -4.0], [-0.5, 0.5]])
        fam = make_family("lab")
        a = np.asarray(fam.weight_alpha(w))
        wn = np.asarray(w)
        expect = np.mean(wn * wn, 0) / (np.mean(np.abs(wn), 0) + 1e-12)
        np.testing.assert_allclose(a, expect, rtol=1e-6)
        # strictly >= mean|W| (Cauchy-Schwarz; equality iff uniform |W|)
        assert (a >= np.mean(np.abs(wn), 0) - 1e-6).all()

    def test_weight_grads_keep_ste(self):
        fam = make_family("lab")
        w = jnp.array([[0.5, -2.0], [0.3, -0.1]])

        def f(w):
            return (fam.weight_sign(w)
                    * jax.lax.stop_gradient(fam.weight_alpha(w))).sum()

        g = jax.grad(f)(w)
        wn = np.asarray(w)
        alpha = np.mean(wn * wn, 0) / (np.mean(np.abs(wn), 0) + 1e-12)
        expect = alpha[None, :] * (np.abs(wn) <= 1.0)
        np.testing.assert_allclose(np.asarray(g), expect, rtol=1e-6)


# ---------------------------------------------------------------------------
# Registry: parsing, resolution, legacy-bitwise dispatch
# ---------------------------------------------------------------------------


class TestFamilyRegistry:
    def test_parse_and_canonical_spec(self):
        name, params = parse_binarizer("proximal:delta0=1.5")
        assert name == "proximal"
        assert params == {"delta0": 1.5, "delta1": 0.5}
        fam = make_family(name, params)
        assert fam.spec == "proximal:delta0=1.5"
        assert make_family("ste").spec == "ste"

    def test_unknown_family_and_param_rejected(self):
        with pytest.raises(ValueError, match="unknown binarizer family"):
            parse_binarizer("xnorpp")
        with pytest.raises(ValueError, match="no param"):
            parse_binarizer("proximal:gamma=2")
        with pytest.raises(ValueError, match="not a number"):
            parse_binarizer("proximal:delta0=fast")
        with pytest.raises(ValueError, match="> 0"):
            parse_binarizer("proximal:delta0=-1")
        with pytest.raises(ValueError, match="PARAM=VALUE"):
            parse_binarizer("proximal:delta0")

    def test_legacy_resolution_and_conflict(self):
        assert resolve_family("", ede=False).name == "ste"
        assert resolve_family("", ede=True).name == "ede"
        assert resolve_family("ede", ede=True).name == "ede"
        with pytest.raises(ValueError, match="drop --ede"):
            resolve_family("proximal", ede=True)

    def test_default_families_dispatch_bitwise_to_legacy_fns(self):
        """The refactor contract: the registry entries for the three
        pre-existing estimators ARE the legacy functions — forward and
        backward bitwise, including the (t, k)-pair legacy dispatch of
        the default family."""
        tk = (jnp.float32(0.5), jnp.float32(2.0))
        cases = [
            (make_family("ste"), None, ste_sign(X)),
            (make_family("approx"), None, approx_sign(X)),
            (make_family("ede"), tk, ede_sign(X, *tk)),
            (make_family("ste"), tk, ede_sign(X, *tk)),  # legacy tk path
        ]
        for fam, sched, expect in cases:
            got = fam.binarize_act(X, sched=sched)
            np.testing.assert_array_equal(np.asarray(got),
                                          np.asarray(expect))
        # backward too
        g_fam = jax.grad(
            lambda x: make_family("ede").binarize_act(x, sched=tk).sum()
        )(X)
        g_leg = jax.grad(lambda x: ede_sign(x, *tk).sum())(X)
        np.testing.assert_array_equal(np.asarray(g_fam),
                                      np.asarray(g_leg))

    def test_default_weight_path_bitwise_legacy(self):
        """weight_sign + weight_alpha of the default family reproduce
        the pre-registry inline code (ste_sign + detached mean|W|)
        bitwise, forward and gradient."""
        w = jax.random.normal(jax.random.PRNGKey(0), (3, 3, 4, 8))
        fam = make_family("ste")

        def new_path(w):
            return (
                fam.weight_sign(w)
                * jax.lax.stop_gradient(fam.weight_alpha(w))
            ).sum()

        def legacy_path(w):
            signed = ste_sign(w)
            alpha = jax.lax.stop_gradient(
                jnp.mean(jnp.abs(w), axis=tuple(range(w.ndim - 1)))
            )
            return (signed * alpha).sum()

        np.testing.assert_array_equal(
            np.asarray(jax.jit(new_path)(w)),
            np.asarray(jax.jit(legacy_path)(w)),
        )
        np.testing.assert_array_equal(
            np.asarray(jax.jit(jax.grad(new_path))(w)),
            np.asarray(jax.jit(jax.grad(legacy_path))(w)),
        )

    def test_schedule_families_fall_back_to_ste_on_eval(self):
        """No sched (the eval path) -> plain STE sign for every
        deterministic family: the eval forward is family-invariant
        modulo the weight alpha."""
        for name in ("ede", "proximal"):
            fam = make_family(name)
            np.testing.assert_array_equal(
                np.asarray(fam.binarize_act(X, sched=None)),
                np.asarray(ste_sign(X)),
            )

    def test_active_family_context_restores(self):
        before = get_active_family().name
        with active_family("proximal") as fam:
            assert fam.name == "proximal"
            assert get_active_family().name == "proximal"
        assert get_active_family().name == before
