"""KD losses vs torch oracles reproducing reference ``utils/KD_loss.py``
semantics exactly (incl. the raw-weight log_target KL quirk, SURVEY.md
Appendix B #11)."""

import jax.numpy as jnp
import numpy as np
import torch
import torch.nn.functional as F

from bdbnn_tpu.losses.kd import (
    distribution_loss,
    layer_weight_kl,
    layer_weight_kl_softened,
    loss_kd,
    match_conv_pairs,
    softmax_cross_entropy,
)


def test_softmax_cross_entropy_matches_torch(rng):
    logits = rng.normal(size=(8, 10)).astype(np.float32)
    labels = rng.integers(0, 10, size=(8,))
    want = F.cross_entropy(
        torch.tensor(logits), torch.tensor(labels)
    ).item()
    got = float(
        softmax_cross_entropy(jnp.asarray(logits), jnp.asarray(labels))
    )
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_distribution_loss_matches_reference_formula(rng):
    s = rng.normal(size=(6, 10)).astype(np.float32)
    t = rng.normal(size=(6, 10)).astype(np.float32)
    # reference utils/KD_loss.py:25-37: batch-mean of -p_t . logp_s
    pt = F.softmax(torch.tensor(t), dim=1)
    logps = F.log_softmax(torch.tensor(s), dim=1)
    want = (-(pt * logps).sum(dim=1)).mean().item()
    got = float(distribution_loss(jnp.asarray(s), jnp.asarray(t)))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_layer_weight_kl_matches_torch_kldivloss_log_target(rng):
    shapes = [(3, 3, 4, 8), (1, 1, 8, 8)]
    ws = [rng.normal(size=sh).astype(np.float32) * 0.1 for sh in shapes]
    wt = [rng.normal(size=sh).astype(np.float32) * 0.1 for sh in shapes]
    crit = torch.nn.KLDivLoss(log_target=True)
    want = sum(
        crit(torch.tensor(a), torch.tensor(b)).item()
        for a, b in zip(ws, wt)
    )
    got = float(
        layer_weight_kl(
            [jnp.asarray(a) for a in ws], [jnp.asarray(b) for b in wt]
        )
    )
    np.testing.assert_allclose(got, want, rtol=1e-4)


def test_layer_weight_kl_softened_matches_torch(rng):
    sh = (8, 4, 3, 3)  # torch OIHW layout; loss softmaxes over axis 1
    ws = rng.normal(size=sh).astype(np.float32)
    wt = rng.normal(size=sh).astype(np.float32)
    T = 6.0
    want = (
        F.kl_div(
            F.log_softmax(torch.tensor(ws) / T, dim=1),
            F.softmax(torch.tensor(wt) / T, dim=1),
        )
        * (T * T)
    ).item()
    got = float(
        layer_weight_kl_softened([jnp.asarray(ws)], [jnp.asarray(wt)], T)
    )
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-5)


def test_loss_kd_matches_torch(rng):
    s = rng.normal(size=(5, 10)).astype(np.float32)
    t = rng.normal(size=(5, 10)).astype(np.float32)
    T = 4.0
    want = (
        F.kl_div(
            F.log_softmax(torch.tensor(s) / T, dim=1),
            F.softmax(torch.tensor(t) / T, dim=1),
        )
        * (T * T)
    ).item()
    got = float(loss_kd(jnp.asarray(s), jnp.asarray(t), T))
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-5)


def test_teacher_gets_no_gradient(rng):
    import jax

    s = jnp.asarray(rng.normal(size=(4, 10)).astype(np.float32))
    t = jnp.asarray(rng.normal(size=(4, 10)).astype(np.float32))
    gt = jax.grad(lambda t: distribution_loss(s, t))(t)
    np.testing.assert_array_equal(np.asarray(gt), 0.0)


def test_match_conv_pairs_skips_stem_and_downsample():
    paths = [
        "stem/weight",
        "layer1/block0/conv1/float_weight",
        "layer1/block0/conv2/float_weight",
        "layer2/block0/downsample/weight",
        "layer2/block0/conv1/float_weight",
    ]
    pairs = match_conv_pairs(paths, paths)
    names = [p[0] for p in pairs]
    assert "stem/weight" not in names
    assert not any("downsample" in n for n in names)
    assert len(pairs) == 3


def test_layer_weight_kl_student_gradient_is_constant_drift(rng):
    """The property behind the measured beta/N failure mode
    (ACCURACY_r05_ts.json): d/dw_s of mean(exp(w_t)*(w_t - w_s)) is
    EXACTLY -exp(w_t)/N per element — independent of the student's
    weights. Any beta whose drift rivals the per-weight gradient noise
    floor therefore compounds under Adam instead of averaging out."""
    import jax

    wt = jnp.asarray(rng.normal(size=(3, 3, 4, 8)).astype(np.float32))
    for seed in (0, 1):
        ws = jnp.asarray(
            np.random.default_rng(seed).normal(size=wt.shape).astype(np.float32)
        )
        g = jax.grad(lambda w: layer_weight_kl([w], [wt]))(ws)
        np.testing.assert_allclose(
            np.asarray(g), -np.exp(np.asarray(wt)) / wt.size,
            rtol=1e-5,
        )
    # same-student gradient regardless of ws: constant drift
