"""One simulated POD HOST for the fault-injection matrix
(tests/test_pod_faults.py) — the pattern proven by
tests/multihost_worker.py, pointed at the REAL CLI entry point.

Run as::

    python pod_worker.py <proc_id> <num_procs> <port> <devices> CLI_ARG...

Each process owns ``<devices>`` virtual CPU devices, joins a real
``jax.distributed`` cluster over a GRPC coordinator with gloo CPU
collectives (exactly the multi-host bring-up a TPU pod uses), then
hands control to ``bdbnn_tpu.cli.main`` with the remaining argv — so
the process under test runs the full production path: shared run dir
(process-0 timestamp broadcast), coordinated step-boundary trigger
agreement, collective checkpoint saves, sharded eval. The process
exits with ``cli.main``'s return code, which is how the parent test
asserts that EVERY host — signaled or not — exits 75 (EX_TEMPFAIL)
after a coordinated preemption save.
"""

import os
import re
import sys

proc_id, num_procs, port, devices = (
    int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4]),
)
cli_args = sys.argv[5:]

os.environ["JAX_PLATFORMS"] = "cpu"
# force OUR device count: the parent test session exports =8, but a pod
# host owns only its own slice of the pod's chips
flags = re.sub(
    r"--xla_force_host_platform_device_count=\d+",
    "",
    os.environ.get("XLA_FLAGS", ""),
)
os.environ["XLA_FLAGS"] = (
    flags + f" --xla_force_host_platform_device_count={devices}"
).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# CPU PJRT needs an explicit cross-host collectives impl (gloo); see
# tests/multihost_worker.py for the full story.
jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}",
    num_processes=num_procs,
    process_id=proc_id,
)

from bdbnn_tpu.cli import main  # noqa: E402

print(f"POD_WORKER_READY {proc_id}", flush=True)
rc = main(cli_args)
print(f"POD_WORKER_EXIT {proc_id} {rc}", flush=True)
sys.exit(rc)
