"""Seeded-bad fixture: fires EXACTLY `lock-discipline` (one finding).

A guarded counter read-modify-written off the lock — the shape of the
unguarded-counter races the checker exists for. No jit roots, no event
emits, no serve-metric flattener, so no other checker can fire on this
file (the per-detector discipline tests/test_analysis.py pins).
"""

import threading


class BadCounter:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0  # guarded-by: _lock

    def bump_guarded(self):
        with self._lock:
            self.count += 1

    def bump_racy(self):
        self.count += 1  # BAD: read-modify-write outside the lock
