"""Seeded-bad fixture: fires EXACTLY `jit-purity` (one finding).

A jitted function reaches a host-clock call through a helper — the
closure (not just the root's own body) must catch it. No guarded-by
annotations, no event emits, no serve-metric flattener, so no other
checker can fire on this file.
"""

import time

import jax


def _leaky_helper(x):
    t = time.perf_counter()  # BAD: host clock inside traced code
    return x * t


@jax.jit
def bad_step(x):
    return _leaky_helper(x) + 1
