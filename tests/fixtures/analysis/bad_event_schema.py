"""Seeded-bad fixture: fires EXACTLY `event-schema` (one finding).

Carries its own registry so the checker runs standalone: the one
registered kind ``good`` is documented here and has a call site; the
``rogue`` emit below is unregistered. No locks, no jit, no
serve-metric flattener.
"""

KNOWN_KINDS = frozenset({"good"})


class Emitter:
    def emit(self, kind, **fields):
        return {"kind": kind, **fields}


def run(ev: Emitter):
    ev.emit("good", value=1)
    ev.emit("rogue", value=2)  # BAD: kind not in KNOWN_KINDS
