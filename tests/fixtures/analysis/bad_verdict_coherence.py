"""Seeded-bad fixture: fires EXACTLY `verdict-coherence` (one finding).

A compare-shaped module whose METRIC_SPECS judges a serve metric the
``_serve_metrics`` flattener never produces — the literal-drift class
the checker exists for. No locks, no jit, no event registry.
"""

METRIC_SPECS = (
    ("serve_p99_ms", "lower", "rel"),
    ("serve_ghost_metric", "lower", "rel"),  # BAD: never produced
)


def _serve_metrics(verdict):
    out = {}
    out["serve_p99_ms"] = verdict.get("p99_ms")
    return out
