"""End-to-end ``fit()`` smoke tests (↔ the reference's only validation:
actually running ``train.py``). Tiny synthetic data, 1-2 epochs, on the
8-device CPU mesh — exercises the full orchestration: datasets, mesh,
jitted steps, meters, validation, checkpointing, resume."""

import os

import numpy as np
import pytest

from bdbnn_tpu.configs.config import RunConfig
from bdbnn_tpu.train.loop import fit


def _cfg(tmp_path, **kw):
    base = dict(
        dataset="cifar10",
        synthetic=True,
        synthetic_train_size=256,
        synthetic_val_size=128,
        arch="resnet20",
        epochs=1,
        batch_size=64,
        lr=0.05,
        print_freq=2,
        log_path=str(tmp_path / "log"),
        seed=0,
        workers=2,
    )
    base.update(kw)
    return RunConfig(**base)


class TestFitSmoke:
    # tier-1 budget: resume-from-checkpoint is covered far more
    # strictly by tests/test_faults.py (events, bitwise schedule,
    # params equality); this broad smoke rides the slow tier
    @pytest.mark.slow
    def test_one_epoch_then_resume(self, tmp_path):
        res = fit(_cfg(tmp_path))
        assert np.isfinite(res["best_acc1"])
        assert res["best_acc1"] >= 0.0
        # a checkpoint landed
        runs = list((tmp_path / "log").rglob("checkpoint"))
        assert runs, "no checkpoint written"
        # and resuming from it continues to epoch 2
        res2 = fit(_cfg(tmp_path, epochs=2, resume=str(runs[0].parent)))
        assert np.isfinite(res2["best_acc1"])

    # tier-1 budget (PR 7 rebalance, same rule as above): every piece
    # of this combined smoke has denser tier-1 coverage on its own —
    # remat identity vs the full loss+grads in test_models.TestRemat,
    # EDE + the kurtosis gate inside REAL fits in the test_faults
    # harness (FAULT_BASE runs ede=True, kurtepoch=1), and the
    # kurtosis/EDE numerics in the fast oracle tier — so the broad
    # all-flags-at-once fit rides the slow tier
    @pytest.mark.slow
    def test_kurtosis_ede_remat_run(self, tmp_path):
        # remat=True rides along: the rematerialized blocks must work
        # under the full jitted/donated train step, not just raw grads
        res = fit(
            _cfg(
                tmp_path,
                w_kurtosis=True,
                ede=True,
                diffkurt=False,
                kurtepoch=0,
                remat=True,
            )
        )
        assert np.isfinite(res["best_acc1"])

    @pytest.mark.slow
    def test_ts_smoke_with_escape_hatch(self, tmp_path):
        # slow-tier (PR 8 budget rebalance, PR 6/7 precedent): the
        # 4-term TS loss numerics carry dense oracle coverage in
        # test_kd (fast tier), the mismatched-teacher rejection keeps
        # its own cheap tier-1 pin below, and the TS fit e2e already
        # lives in the slow tier alongside the other TS fits PR 6
        # moved — this 30s broad smoke duplicated that coverage.
        res = fit(
            _cfg(
                tmp_path,
                imagenet_setting_step_2_ts=True,
                arch_teacher="resnet20_float",
                allow_random_teacher=True,
                react=False,
                beta=1.0,
            )
        )
        assert np.isfinite(res["best_acc1"])

    def test_ts_mismatched_teacher_rejected_for_layer_kl(self, tmp_path):
        """Name-matched conv pairs with different shapes (cross-width or
        cross-block-family teachers, e.g. resnet18_float over a resnet20
        student, or the bottleneck resnet50_float teachers) must fail
        LOUDLY at init when the layer KL is active — not crash at trace
        time or silently broadcast a wrong loss."""
        with pytest.raises(ValueError, match="--react"):
            fit(
                _cfg(
                    tmp_path,
                    imagenet_setting_step_2_ts=True,
                    arch_teacher="resnet18_float",
                    allow_random_teacher=True,
                    react=False,
                    beta=1.0,
                )
            )

    # tier-1 budget: the rejected-case twin below pins the
    # validation logic; the full logit-only KD fit rides slow
    @pytest.mark.slow
    def test_ts_mismatched_teacher_ok_for_logit_only_kd(self, tmp_path):
        """The same cross-architecture teacher is fine under --react
        (beta resolves to 0; logit-only KD has no per-layer pairing)."""
        res = fit(
            _cfg(
                tmp_path,
                imagenet_setting_step_2_ts=True,
                arch_teacher="resnet18_float",
                allow_random_teacher=True,
                react=True,
            )
        )
        assert np.isfinite(res["best_acc1"])

    # tier-1 budget: TS distillation e2e is covered by the
    # escape-hatch smoke + the torch-oracle KD loss tests
    @pytest.mark.slow
    def test_vgg_ts_with_float_twin_teacher(self, tmp_path):
        """vgg_small distilled from its FP twin: the full 4-term TS loss
        runs (conv2..conv6 pair shape-matched; stem skipped)."""
        res = fit(
            _cfg(
                tmp_path,
                arch="vgg_small",
                imagenet_setting_step_2_ts=True,
                arch_teacher="vgg_small_float",
                allow_random_teacher=True,
                react=False,
                beta=0.01,
            )
        )
        assert np.isfinite(res["best_acc1"])

    # tier-1 budget: differs from the cifar10 smokes only in the
    # 100-way head + augment constants (unit-covered in test_data)
    @pytest.mark.slow
    def test_cifar100_end_to_end(self, tmp_path):
        """The cifar100 recipe (reference loader.py:31-49: 100-way fc,
        same augment constants) runs end-to-end, not just model init."""
        res = fit(_cfg(tmp_path, dataset="cifar100"))
        assert np.isfinite(res["best_acc1"])
        assert res["best_acc1"] >= 0.0

    def test_evaluate_only_from_trained_fixture(
        self, tiny_trained_run_dir, tmp_path
    ):
        """-e/--evaluate stays covered in tier-1 at one compile's cost:
        restore the session's real trained run, one validation pass,
        {'acc1'} out — the early-return path through the SAME fit()
        startup (shared-stamp, per-process writers, manifest gating)
        the pod rework touched."""
        res = fit(
            _cfg(
                tmp_path,
                evaluate=True,
                resume=tiny_trained_run_dir,
                arch="resnet8_tiny",
                batch_size=16,
                synthetic_val_size=64,
            )
        )
        assert set(res) == {"acc1"} and np.isfinite(res["acc1"])

    # tier-1 budget: two fit() compiles for one early-return
    # branch (covered above via the session fixture); rides slow
    @pytest.mark.slow
    def test_evaluate_only_mode(self, tmp_path):
        """-e/--evaluate (reference train.py:376-379): restore a
        checkpoint, run ONE validation pass, return {'acc1'} without
        training."""
        fit(_cfg(tmp_path))
        runs = list((tmp_path / "log").rglob("checkpoint"))
        assert runs
        res = fit(
            _cfg(tmp_path, evaluate=True, resume=str(runs[0].parent))
        )
        assert set(res) == {"acc1"} and np.isfinite(res["acc1"])

    def test_missing_data_dir_is_hard_error(self, tmp_path):
        cfg = _cfg(tmp_path, synthetic=False, data=str(tmp_path / "nope"))
        with pytest.raises(FileNotFoundError, match="not found"):
            fit(cfg)


class TestRegistryDefaultBitwise:
    """THE binarizer-registry refactor acceptance pin: the default
    family routed through the registry reproduces the PRE-REFACTOR
    path bitwise on a fixed-seed smoke fit — final params and eval
    logits — where 'pre-refactor path' is the legacy inline code
    (``binarize_act(estimator='ste', tk=...)`` dispatch + ``ste_sign``
    weights + detached ``mean|W|`` alpha) monkeypatched over the
    family methods."""

    def _tiny(self, tmp_path, name, **kw):
        return _cfg(
            tmp_path,
            arch="resnet8_tiny",
            synthetic_train_size=64,
            synthetic_val_size=64,
            batch_size=16,
            log_path=str(tmp_path / name),
            **kw,
        )

    def test_default_family_bitwise_equals_pre_refactor_path(
        self, tmp_path, monkeypatch
    ):
        import glob

        import jax
        import jax.numpy as jnp

        from bdbnn_tpu.models import create_model
        from bdbnn_tpu.nn import binarize as B
        from bdbnn_tpu.utils.checkpoint import load_variables

        def run(name):
            fit(self._tiny(tmp_path, name))
            ckpt = glob.glob(
                str(tmp_path / name / "**" / "checkpoint"),
                recursive=True,
            )
            assert ckpt
            return load_variables(ckpt[0])

        registry_vars = run("registry")

        # reconstruct the pre-refactor code path over the SAME fit
        def legacy_act(self, x, sched=None, rng=None):
            return B.binarize_act(x, estimator="ste", tk=sched)

        def legacy_sign(self, w):
            return B.ste_sign(w)

        def legacy_alpha(self, w):
            return jnp.mean(jnp.abs(w), axis=tuple(range(w.ndim - 1)))

        monkeypatch.setattr(
            B.BinarizerFamily, "binarize_act", legacy_act
        )
        monkeypatch.setattr(B.BinarizerFamily, "weight_sign", legacy_sign)
        monkeypatch.setattr(
            B.BinarizerFamily, "weight_alpha", legacy_alpha
        )
        legacy_vars = run("legacy")

        # params bitwise
        flat_r = jax.tree_util.tree_leaves_with_path(
            registry_vars["params"]
        )
        flat_l = jax.tree_util.tree_leaves_with_path(
            legacy_vars["params"]
        )
        assert len(flat_r) == len(flat_l)
        for (pr, lr_), (pl, ll) in zip(flat_r, flat_l):
            assert pr == pl
            np.testing.assert_array_equal(
                np.asarray(lr_), np.asarray(ll), err_msg=str(pr)
            )

        # eval logits bitwise on a fixed batch (monkeypatch still
        # active is fine: both variable sets go through the SAME
        # forward here — the claim under test is parameter equality
        # carrying into identical logits)
        m = create_model("resnet8_tiny", "cifar10")
        x = np.asarray(
            jax.random.normal(jax.random.PRNGKey(0), (8, 32, 32, 3))
        )
        logits_r = m.apply(
            {
                "params": registry_vars["params"],
                "batch_stats": registry_vars["batch_stats"],
            },
            x, train=False,
        )
        logits_l = m.apply(
            {
                "params": legacy_vars["params"],
                "batch_stats": legacy_vars["batch_stats"],
            },
            x, train=False,
        )
        np.testing.assert_array_equal(
            np.asarray(logits_r), np.asarray(logits_l)
        )

    # tier-1 keeps the default-family pin above; the --ede flag vs
    # --binarizer ede equivalence costs two more compiles and rides
    # the slow tier (the resolution logic itself is unit-pinned in
    # test_binarize/test_cli)
    @pytest.mark.slow
    def test_ede_flag_equals_ede_family(self, tmp_path):
        import glob

        import jax

        from bdbnn_tpu.utils.checkpoint import load_variables

        def run(name, **kw):
            fit(self._tiny(tmp_path, name, **kw))
            ckpt = glob.glob(
                str(tmp_path / name / "**" / "checkpoint"),
                recursive=True,
            )
            return load_variables(ckpt[0])

        a = run("flag", ede=True)
        b = run("family", binarizer="ede")
        for la, lb in zip(
            jax.tree_util.tree_leaves(a["params"]),
            jax.tree_util.tree_leaves(b["params"]),
        ):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


class TestDeviceNormalizeFit:
    # tier-1 budget: the uint8 device-normalize path is pinned at
    # unit level (pipelines + step input_norm); the full-fit
    # combination rides the slow tier
    @pytest.mark.slow
    def test_fit_with_device_normalize_and_target_acc(self, tmp_path):
        """End-to-end: uint8 pipelines + on-device normalize + the
        north-star time-to-target clock, through the real CIFAR npz
        data path."""
        rng = np.random.default_rng(0)
        data_dir = tmp_path / "data"
        data_dir.mkdir()
        np.savez(
            data_dir / "data.npz",
            x_train=rng.integers(0, 256, (256, 32, 32, 3), dtype=np.uint8),
            y_train=rng.integers(0, 10, (256,)).astype(np.int64),
            x_test=rng.integers(0, 256, (64, 32, 32, 3), dtype=np.uint8),
            y_test=rng.integers(0, 10, (64,)).astype(np.int64),
        )
        cfg = _cfg(
            tmp_path,
            synthetic=False,
            data=str(data_dir),
            device_normalize=True,
            target_acc=0.1,  # any nonzero accuracy crosses it
            epochs=2,
        )
        res = fit(cfg)
        assert np.isfinite(res["best_acc1"])
        assert "time_to_target_s" in res and res["time_to_target_s"] > 0

    def test_synthetic_rejects_device_normalize(self, tmp_path):
        with pytest.raises(ValueError):
            fit(_cfg(tmp_path, device_normalize=True))
