"""CLI surface tests: the drop-in contract with the reference.

The reference is launched as ``python train.py DATA [flags]`` with the
flag surface of SURVEY.md Appendix A (reference ``train.py:64-171``).
MIGRATION.md promises every reference flag parses here with the same
spelling and default; these tests pin that promise.
"""

import json
import os
import shutil
import subprocess
import sys

import pytest

from bdbnn_tpu.cli import args_to_config, build_parser


def parse(argv):
    return args_to_config(build_parser().parse_args(argv))


class TestReferenceFlagSurface:
    def test_defaults_match_reference(self):
        """Reference training-recipe defaults (train.py:74-170)."""
        cfg = parse(["/data"])
        assert cfg.epochs == 90
        assert cfg.batch_size == 256
        assert cfg.lr == 0.1
        assert cfg.momentum == 0.9
        assert cfg.weight_decay == 1e-4
        assert cfg.w_kurtosis_target == 1.8
        assert cfg.w_lambda_kurtosis == 1.0
        assert cfg.alpha == 0.9
        assert cfg.temperature == 4
        assert cfg.beta == 200
        assert cfg.kurtosis_mode == "avg"
        assert cfg.weight_name == ("all",)

    def test_every_reference_flag_parses(self):
        """One pass over the full Appendix-A surface."""
        cfg = parse(
            [
                "/data", "--dataset", "cifar10", "-a", "resnet20",
                "-j", "8", "--epochs", "120", "--start-epoch", "3",
                "-b", "128", "-lr", "0.01", "--momentum", "0.8",
                "-wd", "5e-4", "-p", "50", "--resume", "ck.pth.tar",
                "--pretrained", "--seed", "7", "--log_path", "mylog",
                "--custom_resnet", "--reset_resume", "--ede",
                "--w-kurtosis", "--w-kurtosis-target", "2.0",
                "--w-lambda-kurtosis", "0.5", "--weight-name", "all",
                "--remove-weight-name", "layer1_0.conv1",
                "--kurtosis-mode", "sum", "--diffkurt", "--kurtepoch", "5",
                "--twoblock", "--imagenet_setting_step_2_ts",
                "-a_teacher", "resnet34_float", "--custom_resnet_teacher",
                "--resume_teacher", "t.pth.tar", "--kd", "--react",
                "--alpha", "0.5", "--temperature", "2", "--beta", "100",
            ]
        )
        assert cfg.arch == "resnet20"
        assert cfg.epochs == 120 and cfg.start_epoch == 3
        assert cfg.kurtepoch == 5 and cfg.diffkurt and cfg.twoblock
        assert cfg.remove_weight_name == ("layer1_0.conv1",)
        assert cfg.react and cfg.imagenet_setting_step_2_ts

    def test_legacy_nccl_flags_parse_and_note(self, capsys):
        """GPU/NCCL-era flags parse, print a note, change nothing."""
        cfg = parse(
            [
                "/data", "--multiprocessing-distributed", "--world-size",
                "4", "--rank", "1", "--dist-url", "tcp://h:1234",
                "--dist-backend", "nccl", "--gpu", "0",
            ]
        )
        err = capsys.readouterr().err
        assert "ignored" in err and "world-size" in err
        # nothing distributed was configured from them
        assert cfg.model_parallel == 1 and not cfg.distributed_init

    @pytest.mark.parametrize(
        "argv",
        [
            # the MIGRATION.md acceptance-config command lines
            ["/d", "--dataset", "cifar10", "-a", "resnet20",
             "--w-kurtosis", "--w-kurtosis-target", "1.8",
             "--w-lambda-kurtosis", "1.0", "--ede"],
            ["/d", "--dataset", "cifar10", "-a", "resnet18",
             "--imagenet_setting_step_2_ts", "--arch_teacher",
             "resnet18_float", "--resume_teacher", "t.pth.tar",
             "--alpha", "0.9", "--temperature", "4", "--beta", "200",
             "--w-kurtosis"],
            ["/d", "--dataset", "imagenet", "-a", "resnet18",
             "--w-kurtosis", "--w-kurtosis-target", "1.8",
             "--w-lambda-kurtosis", "1.0", "--dtype", "bfloat16"],
            ["/d", "--dataset", "imagenet", "-a", "resnet34",
             "--imagenet_setting_step_2_ts", "--react",
             "--arch_teacher", "resnet34_float", "--resume_teacher",
             "t.pth.tar", "--w-kurtosis", "--dtype", "bfloat16"],
            ["/d", "--dataset", "imagenet", "-a", "resnet18",
             "--distributed-init", "--w-kurtosis", "--dtype",
             "bfloat16"],
        ],
    )
    def test_migration_doc_commands_parse(self, argv):
        cfg = parse(argv)
        assert cfg.data == "/d"
        # TS is gated on --imagenet_setting_step_2_ts, exactly as in
        # the reference (train.py:417; its --kd flag is dead there too)
        assert cfg.teacher_student == ("--imagenet_setting_step_2_ts" in argv)


class TestTpuNativeFlags:
    def test_parallelism_and_dtype(self):
        cfg = parse(
            [
                "/data", "--model-parallel", "2", "--distributed-init",
                "--dtype", "bfloat16", "--device-normalize", "--remat",
                "--target-acc", "63.0", "--opt-policy", "adam-linear",
                "--profile-dir", "/tmp/prof",
            ]
        )
        assert cfg.model_parallel == 2 and cfg.distributed_init
        assert cfg.dtype == "bfloat16" and cfg.device_normalize
        assert cfg.remat
        assert cfg.target_acc == 63.0
        assert cfg.opt_policy == "adam-linear"

    def test_bad_dataset_rejected(self):
        with pytest.raises(SystemExit):
            build_parser().parse_args(["/d", "--dataset", "mnist"])

    def test_telemetry_flags(self):
        cfg = parse(["/data", "--no-binarization-probes",
                     "--nonfinite-policy", "warn"])
        assert not cfg.probe_binarization
        assert cfg.nonfinite_policy == "warn"
        # defaults: probes on, fail fast
        cfg = parse(["/data"])
        assert cfg.probe_binarization
        assert cfg.nonfinite_policy == "raise"
        assert cfg.profile_at == ()

    def test_profile_at_flag(self):
        cfg = parse(["/data", "--profile-at", "0:5:3",
                     "--profile-at", "12:40"])
        assert cfg.profile_at == ("0:5:3", "12:40")
        cfg.validate()  # specs parse
        with pytest.raises(ValueError, match="profile-at"):
            parse(["/data", "--profile-at", "nonsense"]).validate()

    def test_health_flags(self):
        # defaults: monitor on, forensics on, bounded
        cfg = parse(["/data"])
        assert cfg.health and cfg.health_forensics
        assert cfg.health_forensics_steps == 4
        assert cfg.health_max_forensics == 2
        assert cfg.health_thresholds == ()
        assert cfg.events_max_mb == 256.0
        cfg = parse([
            "/data", "--no-health-forensics",
            "--health-forensics-steps", "8",
            "--health-max-forensics", "5",
            "--health-threshold", "loss_spike_factor=5",
            "--health-threshold", "flip_collapse_rate=1e-6",
            "--events-max-mb", "64",
        ])
        assert cfg.health and not cfg.health_forensics
        assert cfg.health_forensics_steps == 8
        assert cfg.health_max_forensics == 5
        assert cfg.health_thresholds == (
            "loss_spike_factor=5", "flip_collapse_rate=1e-6",
        )
        assert cfg.events_max_mb == 64.0
        cfg.validate()
        assert not parse(["/data", "--no-health"]).health
        with pytest.raises(ValueError, match="health-threshold"):
            parse(["/data", "--health-threshold", "bogus=1"]).validate()
        with pytest.raises(ValueError, match="events-max-mb"):
            parse(["/data", "--events-max-mb", "-1"]).validate()


class TestBinarizerFlag:
    def test_binarizer_flag_parses_and_canonicalizes(self):
        cfg = parse(["/data", "--binarizer", "proximal:delta1=0.25"])
        assert cfg.binarizer == "proximal:delta1=0.25"
        cfg = cfg.validate()
        assert cfg.binarizer == "proximal:delta1=0.25"
        # legacy mapping canonicalized by validate(): default -> ste,
        # --ede -> ede, and the ede flag follows the family
        assert parse(["/data"]).validate().binarizer == "ste"
        ede_cfg = parse(["/data", "--ede"]).validate()
        assert ede_cfg.binarizer == "ede" and ede_cfg.ede
        fam_cfg = parse(["/data", "--binarizer", "ede"]).validate()
        assert fam_cfg.binarizer == "ede" and fam_cfg.ede

    def test_bad_binarizer_rejected_at_config_time(self):
        with pytest.raises(ValueError, match="unknown binarizer"):
            parse(["/data", "--binarizer", "xnorpp"]).validate()
        with pytest.raises(ValueError, match="no param"):
            parse(["/data", "--binarizer", "ste:gamma=1"]).validate()
        with pytest.raises(ValueError, match="drop --ede"):
            parse(["/data", "--ede", "--binarizer", "lab"]).validate()


class TestSearchCliSmoke:
    """The `search` console entrypoint as a real subprocess: one tiny
    single-trial sweep, then summarize + watch --once consume the
    sweep dir (the multi-trial and preemption e2es live in
    tests/test_search.py)."""

    REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def _run(self, *argv, timeout=300):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env["PYTHONPATH"] = self.REPO + (
            os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH") else ""
        )
        return subprocess.run(
            [sys.executable, "-m", "bdbnn_tpu.cli", *argv],
            capture_output=True, text=True, timeout=timeout, env=env,
            cwd=self.REPO,
        )

    @pytest.fixture(scope="class")
    def tiny_sweep(self, tmp_path_factory):
        out_dir = str(tmp_path_factory.mktemp("cli_sweep") / "sweep")
        proc = self._run(
            "search", "--out-dir", out_dir,
            "--trial", "ste@0.05",
            "-a", "resnet8_tiny", "--epochs", "1", "-b", "16",
            "-p", "2", "--synthetic", "--synthetic-train-size", "64",
            "--synthetic-val-size", "64", "--seed", "0",
        )
        assert proc.returncode == 0, proc.stderr + proc.stdout
        return out_dir, proc

    def test_search_prints_leaderboard(self, tiny_sweep):
        out_dir, proc = tiny_sweep
        lb = json.loads(proc.stdout)
        assert lb["search_verdict"] == 1
        assert lb["completed"] == 1
        assert lb["winner"]["family"] == "ste"
        assert "[search] sweep dir:" in proc.stderr
        assert os.path.exists(os.path.join(out_dir, "leaderboard.json"))
        assert os.path.exists(os.path.join(out_dir, "ledger.json"))

    def test_summarize_renders_sweep(self, tiny_sweep):
        out_dir, _ = tiny_sweep
        proc = self._run("summarize", out_dir)
        assert proc.returncode == 0, proc.stderr[-800:]
        assert "recipe search: 1 trial(s)" in proc.stdout
        assert "winner: t000_ste_lr0.05" in proc.stdout

    def test_watch_once_renders_sweep(self, tiny_sweep):
        out_dir, _ = tiny_sweep
        proc = self._run("watch", out_dir, "--once")
        assert proc.returncode == 0, proc.stderr[-800:]
        assert "search: 1 trial(s)" in proc.stdout
        assert "VERDICT: 1/1 completed" in proc.stdout

    def test_bad_family_fails_at_the_command_line(self, tmp_path):
        proc = self._run(
            "search", "--out-dir", str(tmp_path / "s"),
            "--families", "bogus", "--synthetic", timeout=120,
        )
        assert proc.returncode != 0
        assert "unknown binarizer family" in (proc.stderr + proc.stdout)


class TestSummarizeSubcommand:
    """The console entrypoint for post-hoc reports must not silently
    break: run ``python -m bdbnn_tpu.cli summarize`` as a real
    subprocess against a fixture run dir (built from files alone —
    summarize never needs a live backend)."""

    def _run(self, *argv):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        return subprocess.run(
            [sys.executable, "-m", "bdbnn_tpu.cli", "summarize", *argv],
            capture_output=True, text=True, timeout=180, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )

    def test_summarize_report_and_json(self, fixture_run_dir):
        proc = self._run(fixture_run_dir)
        assert proc.returncode == 0, proc.stderr[-800:]
        assert "== Run summary:" in proc.stdout
        assert "compile" in proc.stdout
        assert "starvation verdict:" in proc.stdout
        assert "layer1_0.conv1" in proc.stdout

        proc = self._run(fixture_run_dir, "--json")
        assert proc.returncode == 0, proc.stderr[-800:]
        summary = json.loads(proc.stdout)
        assert summary["compile_s"] == pytest.approx(5.0)
        assert summary["starvation"]["input_bound"] is True

    def test_summarize_empty_dir_fails(self, tmp_path):
        proc = self._run(str(tmp_path))
        assert proc.returncode != 0

    def test_summarize_renders_attribution(self, fixture_run_dir):
        """The fixture run dir carries a capture window + memory
        events; the CLI report must render the attribution section
        with SEMANTIC category names and the HBM watermark."""
        proc = self._run(fixture_run_dir)
        assert proc.returncode == 0, proc.stderr[-800:]
        assert "device attribution" in proc.stdout
        assert "binary_conv" in proc.stdout
        assert "hbm: peak" in proc.stdout

        proc = self._run(fixture_run_dir, "--json")
        summary = json.loads(proc.stdout)
        cats = summary["attribution"]["categories_ms_per_step"]
        assert cats["binary_conv"] == pytest.approx(4.0)
        assert summary["attribution"]["hbm"]["peak_gib"] == pytest.approx(8.0)


def _append_alert_events(run_dir):
    """Inject one critical alert + the health roll-up into a fixture
    run dir's event stream (what a flip-collapsed run would carry)."""
    with open(os.path.join(run_dir, "events.jsonl"), "a") as f:
        f.write(json.dumps({
            "t": 130.5, "kind": "alert", "detector": "flip_collapse",
            "severity": "critical", "epoch": 2, "step": 3,
            "value": 0.0, "threshold": 1e-5,
            "message": "mean sign-flip rate 0/step < 1e-05",
        }) + "\n")
        f.write(json.dumps({
            "t": 131.0, "kind": "health", "intervals": 9,
            "alerts_total": 1, "alerts_critical": 1,
            "by_detector": {"flip_collapse": 1},
        }) + "\n")


class TestSummarizeStrict:
    """``summarize --strict``: the CI run-health gate. Exit 0 on a
    clean run, exit 3 + a listing on stderr when a run-ending
    (critical) alert fired."""

    def _run(self, *argv):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        return subprocess.run(
            [sys.executable, "-m", "bdbnn_tpu.cli", "summarize", *argv],
            capture_output=True, text=True, timeout=180, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )

    def test_strict_passes_clean_run(self, fixture_run_dir):
        proc = self._run(fixture_run_dir, "--strict")
        assert proc.returncode == 0, proc.stderr[-800:]

    def test_strict_fails_on_critical_alert(self, fixture_run_dir):
        _append_alert_events(fixture_run_dir)
        proc = self._run(fixture_run_dir, "--strict")
        assert proc.returncode == 3
        assert "run-ending alert" in proc.stderr
        assert "flip_collapse" in proc.stderr
        # the report itself renders the health section either way
        assert "health: 1 alert(s)" in proc.stdout
        # without --strict the same run exits 0 (report-only)
        proc = self._run(fixture_run_dir)
        assert proc.returncode == 0
        # and the --json summary carries the machine-readable section
        proc = self._run(fixture_run_dir, "--json")
        summary = json.loads(proc.stdout)
        assert summary["health"]["alerts_critical"] == 1
        assert summary["health"]["by_detector"] == {"flip_collapse": 1}


class TestCompareSubcommand:
    """``python -m bdbnn_tpu.cli compare`` as a real subprocess over
    the checked-in fixture run dirs: deterministic JSON verdict, exit
    3 on regression beyond tolerance, 0 on pass. Reads files only."""

    FIXTURES = os.path.join("tests", "fixtures", "compare")

    def _run(self, *argv):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        return subprocess.run(
            [sys.executable, "-m", "bdbnn_tpu.cli", "compare", *argv],
            capture_output=True, text=True, timeout=180, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )

    def test_regression_verdict_exit_3_and_golden_json(self):
        base = os.path.join(self.FIXTURES, "base")
        cand = os.path.join(self.FIXTURES, "cand")
        proc = self._run(base, cand, "--json")
        assert proc.returncode == 3, proc.stderr[-800:]
        result = json.loads(proc.stdout)
        assert result["verdict"] == "regression"
        # byte-deterministic against the checked-in golden verdict
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with open(os.path.join(repo, self.FIXTURES,
                               "expected_verdict.json")) as f:
            assert result == json.load(f)

    def test_pass_exit_0_and_table(self):
        base = os.path.join(self.FIXTURES, "base")
        proc = self._run(base, base)
        assert proc.returncode == 0, proc.stderr[-800:]
        assert "overall verdict: PASS" in proc.stdout

    def test_regression_table_renders(self):
        proc = self._run(
            os.path.join(self.FIXTURES, "base"),
            os.path.join(self.FIXTURES, "cand"),
        )
        assert proc.returncode == 3
        assert "REGRESSION" in proc.stdout
        assert "best_acc1" in proc.stdout

    def test_needs_two_paths(self):
        proc = self._run(os.path.join(self.FIXTURES, "base"))
        assert proc.returncode == 2  # argparse usage error


class TestServeCliSmoke:
    """The full artifact round trip as real subprocesses: ``export`` a
    session-trained resnet8_tiny run, then ``predict --check`` the
    artifact over the run's own synthetic val split — exit 3 unless the
    reported top-1 EXACTLY matches the exported checkpoint's recorded
    eval accuracy. This is the tier-1 smoke for the serving acceptance
    criterion."""

    def test_export_then_predict_reproduces_recorded_top1(
        self, tiny_trained_run_dir, tmp_path
    ):
        art = str(tmp_path / "artifact")
        # one subprocess driving both subcommands through the real CLI
        # entrypoint (sharing the jax import keeps the smoke inside the
        # tier-1 budget); predict --check itself enforces the exact
        # top-1 reproduction with exit 3 on mismatch
        driver = (
            "import json, sys\n"
            "from bdbnn_tpu.cli import main\n"
            f"rc = main(['export', {tiny_trained_run_dir!r}, '-o', {art!r}])\n"
            "assert rc == 0, rc\n"
            f"rc = main(['predict', {art!r}, '--check'])\n"
            "sys.exit(rc)\n"
        )
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, "-c", driver],
            capture_output=True, text=True, timeout=300, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        assert proc.returncode == 0, (
            proc.stdout[-800:] + proc.stderr[-800:]
        )
        exported = json.loads(
            proc.stdout[: proc.stdout.index("}") + 1]
        )
        assert exported["binarized_convs"] == 5
        assert exported["compression_ratio"] > 1.0
        assert exported["integrity"] == "ok"
        result = json.loads(proc.stdout[proc.stdout.index("}") + 1:])
        assert result["match"] is True
        assert result["top1"] == exported["checkpoint_acc1"]
        assert result["count"] == 64


class TestPerfCliSmoke:
    """The performance observatory's console surface as one real
    subprocess: ``perf``-sweep the session's exported artifact (one
    bucket, dense impl, 2 iters — the smallest honest sweep), then
    ``compare`` the verdict against a doctored copy with one layer 2x
    slower — exit 3, the perf regression gate."""

    def test_perf_then_compare_gate(self, exported_artifact, tmp_path):
        art, _ = exported_artifact
        log = str(tmp_path / "perf_log")
        out = str(tmp_path / "perf_verdict.json")
        cand = str(tmp_path / "doctored.json")
        driver = (
            "import contextlib, io, json, sys\n"
            "from bdbnn_tpu.cli import main\n"
            "buf = io.StringIO()\n"
            "with contextlib.redirect_stdout(buf):\n"
            f"    rc = main(['perf', {art!r}, '--log-path', {log!r},\n"
            "               '--buckets', '1', '--impls', 'dense',\n"
            f"               '--iters', '2', '--out', {out!r}])\n"
            "assert rc == 0, rc\n"
            "v = json.loads(buf.getvalue())\n"
            f"doc = json.load(open({out!r}))\n"
            "key = sorted(doc['perf_layers'])[0]\n"
            "doc['perf_layers'][key] *= 2.0\n"
            f"json.dump(doc, open({cand!r}, 'w'))\n"
            "with contextlib.redirect_stdout(io.StringIO()):\n"
            f"    rc = main(['compare', {out!r}, {cand!r}])\n"
            "assert rc == 3, rc\n"
            "print(json.dumps({'perf_verdict': v['perf_verdict'],\n"
            "                  'best': v['summary']['step_ms_best'],\n"
            "                  'layers': len(v['perf_layers'])}))\n"
            "sys.exit(0)\n"
        )
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, "-c", driver],
            capture_output=True, text=True, timeout=300, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        assert proc.returncode == 0, (
            proc.stdout[-800:] + proc.stderr[-800:]
        )
        result = json.loads(proc.stdout.strip().splitlines()[-1])
        assert result["perf_verdict"] == 1
        assert result["best"] > 0
        assert result["layers"] == 7  # 7 layers x 1 bucket x 1 impl
        # the persisted surface: one ledger line, a populated run dir
        ledger = os.path.join(log, "PERF_LEDGER.jsonl")
        with open(ledger) as f:
            lines = [l for l in f if l.strip()]
        assert len(lines) == 1
        rec = json.loads(lines[0])
        assert rec["arch"] == "resnet8_tiny"
        assert os.path.isfile(
            os.path.join(rec["run_dir"], "BENCH_perf.json")
        )


class TestCheckSubcommand:
    """The static analyzer's console entrypoint as a real subprocess
    (bdbnn_tpu/analysis/ via ``python -m bdbnn_tpu.cli check``): exit 0
    on the clean tree, exit 3 on a doctored temp copy with an injected
    violation, strict-RFC-8259 ``--json`` report."""

    REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def _run(self, *argv):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        return subprocess.run(
            [sys.executable, "-m", "bdbnn_tpu.cli", "check", *argv],
            capture_output=True, text=True, timeout=180, env=env,
            cwd=self.REPO,
        )

    def test_clean_tree_exits_0(self):
        proc = self._run()
        assert proc.returncode == 0, proc.stdout + proc.stderr[-800:]
        assert "CLEAN" in proc.stdout

    def _doctored_root(self, tmp_path):
        """A minimal analyzable copy of the tree: the package, the
        golden compare fixture and the suppression baseline."""
        root = tmp_path / "doctored"
        shutil.copytree(
            os.path.join(self.REPO, "bdbnn_tpu"), root / "bdbnn_tpu"
        )
        golden = os.path.join(
            self.REPO, "tests", "fixtures", "compare",
            "expected_verdict.json",
        )
        dst = root / "tests" / "fixtures" / "compare"
        dst.mkdir(parents=True)
        shutil.copy(golden, dst / "expected_verdict.json")
        shutil.copy(
            os.path.join(self.REPO, "analysis-baseline.txt"),
            root / "analysis-baseline.txt",
        )
        for harness in ("bench.py", "profile_r05.py"):
            # the root-level harnesses are part of the event-schema
            # scan set (bench.py is the only `bench_result` emitter —
            # without it the dead-kind check fires, correctly)
            shutil.copy(
                os.path.join(self.REPO, harness), root / harness
            )
        return root

    def test_injected_violation_exits_3(self, tmp_path):
        root = self._doctored_root(tmp_path)
        target = root / "bdbnn_tpu" / "serve" / "batching.py"
        target.write_text(
            target.read_text()
            + "\n\nclass _DoctoredCounter:\n"
            "    def __init__(self):\n"
            "        import threading\n"
            "        self._lock = threading.Lock()\n"
            "        self.count = 0  # guarded-by: _lock\n\n"
            "    def bump(self):\n"
            "        self.count += 1\n"
        )
        proc = self._run("--root", str(root), "--json")
        assert proc.returncode == 3, proc.stdout + proc.stderr[-800:]
        report = json.loads(
            proc.stdout,
            parse_constant=lambda s: pytest.fail(f"bare {s} token"),
        )
        assert report["verdict"] == "findings"
        fired = {f["checker"] for f in report["findings"]}
        assert fired == {"lock-discipline"}
        assert any(
            "self.count" in f["message"] for f in report["findings"]
        )

    def test_doctored_copy_without_violation_exits_0(self, tmp_path):
        # the doctored-root HARNESS itself must be green, so the
        # injected-violation test fails only for the injection
        root = self._doctored_root(tmp_path)
        proc = self._run("--root", str(root), "--json")
        assert proc.returncode == 0, proc.stdout + proc.stderr[-800:]
        report = json.loads(
            proc.stdout,
            parse_constant=lambda s: pytest.fail(f"bare {s} token"),
        )
        # deterministic strict JSON: a second run is byte-identical
        proc2 = self._run("--root", str(root), "--json")
        assert proc2.stdout == proc.stdout
        assert report["counts"]["suppressed"] == 1  # the baseline entry

    def test_events_into_records_analysis_event(self, tmp_path):
        run_dir = tmp_path / "run"
        proc = self._run("--events-into", str(run_dir))
        assert proc.returncode == 0, proc.stdout + proc.stderr[-800:]
        from bdbnn_tpu.obs.events import read_events

        evs = read_events(str(run_dir), "analysis")
        assert len(evs) == 1
        assert evs[0]["verdict"] == "clean"
        assert evs[0]["findings"] == 0
        # summarize renders the verdict alongside the run
        from bdbnn_tpu.obs.summarize import summarize_run

        report, summary = summarize_run(str(run_dir))
        assert summary["analysis"]["verdict"] == "clean"
        assert "static analysis: CLEAN" in report

    def test_single_checker_filter(self):
        proc = self._run("--checker", "event-schema")
        assert proc.returncode == 0, proc.stdout + proc.stderr[-800:]
        assert "event-schema" in proc.stdout
        assert "lock-discipline" not in proc.stdout


class TestWatchSubcommand:
    """``python -m bdbnn_tpu.cli watch RUN_DIR --once`` — the live-tail
    status view, as a real subprocess against the fixture run dir. Like
    summarize, it reads files only (works on a synced log dir with no
    live process, and must not initialize a JAX backend)."""

    def _run(self, *argv):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        return subprocess.run(
            [sys.executable, "-m", "bdbnn_tpu.cli", "watch", *argv],
            capture_output=True, text=True, timeout=180, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )

    def test_watch_once(self, fixture_run_dir):
        proc = self._run(fixture_run_dir, "--once")
        assert proc.returncode == 0, proc.stderr[-800:]
        out = proc.stdout
        # epoch progress, latest eval, flip drift, completion verdict
        assert "epochs 0->3" in out
        assert "eval:" in out and "best 90.0" in out
        assert "flips:" in out and "settling" in out
        assert "hbm:" in out
        assert "DONE: best acc1 90.0 @ epoch 2" in out

    def test_watch_resolves_log_root(self, fixture_run_dir):
        proc = self._run(os.path.dirname(fixture_run_dir), "--once")
        assert proc.returncode == 0, proc.stderr[-800:]
        assert "DONE" in proc.stdout

    def test_watch_empty_dir_fails(self, tmp_path):
        proc = self._run(str(tmp_path))
        assert proc.returncode != 0
