"""CLI surface tests: the drop-in contract with the reference.

The reference is launched as ``python train.py DATA [flags]`` with the
flag surface of SURVEY.md Appendix A (reference ``train.py:64-171``).
MIGRATION.md promises every reference flag parses here with the same
spelling and default; these tests pin that promise.
"""

import json
import os
import subprocess
import sys

import pytest

from bdbnn_tpu.cli import args_to_config, build_parser


def parse(argv):
    return args_to_config(build_parser().parse_args(argv))


class TestReferenceFlagSurface:
    def test_defaults_match_reference(self):
        """Reference training-recipe defaults (train.py:74-170)."""
        cfg = parse(["/data"])
        assert cfg.epochs == 90
        assert cfg.batch_size == 256
        assert cfg.lr == 0.1
        assert cfg.momentum == 0.9
        assert cfg.weight_decay == 1e-4
        assert cfg.w_kurtosis_target == 1.8
        assert cfg.w_lambda_kurtosis == 1.0
        assert cfg.alpha == 0.9
        assert cfg.temperature == 4
        assert cfg.beta == 200
        assert cfg.kurtosis_mode == "avg"
        assert cfg.weight_name == ("all",)

    def test_every_reference_flag_parses(self):
        """One pass over the full Appendix-A surface."""
        cfg = parse(
            [
                "/data", "--dataset", "cifar10", "-a", "resnet20",
                "-j", "8", "--epochs", "120", "--start-epoch", "3",
                "-b", "128", "-lr", "0.01", "--momentum", "0.8",
                "-wd", "5e-4", "-p", "50", "--resume", "ck.pth.tar",
                "--pretrained", "--seed", "7", "--log_path", "mylog",
                "--custom_resnet", "--reset_resume", "--ede",
                "--w-kurtosis", "--w-kurtosis-target", "2.0",
                "--w-lambda-kurtosis", "0.5", "--weight-name", "all",
                "--remove-weight-name", "layer1_0.conv1",
                "--kurtosis-mode", "sum", "--diffkurt", "--kurtepoch", "5",
                "--twoblock", "--imagenet_setting_step_2_ts",
                "-a_teacher", "resnet34_float", "--custom_resnet_teacher",
                "--resume_teacher", "t.pth.tar", "--kd", "--react",
                "--alpha", "0.5", "--temperature", "2", "--beta", "100",
            ]
        )
        assert cfg.arch == "resnet20"
        assert cfg.epochs == 120 and cfg.start_epoch == 3
        assert cfg.kurtepoch == 5 and cfg.diffkurt and cfg.twoblock
        assert cfg.remove_weight_name == ("layer1_0.conv1",)
        assert cfg.react and cfg.imagenet_setting_step_2_ts

    def test_legacy_nccl_flags_parse_and_note(self, capsys):
        """GPU/NCCL-era flags parse, print a note, change nothing."""
        cfg = parse(
            [
                "/data", "--multiprocessing-distributed", "--world-size",
                "4", "--rank", "1", "--dist-url", "tcp://h:1234",
                "--dist-backend", "nccl", "--gpu", "0",
            ]
        )
        err = capsys.readouterr().err
        assert "ignored" in err and "world-size" in err
        # nothing distributed was configured from them
        assert cfg.model_parallel == 1 and not cfg.distributed_init

    @pytest.mark.parametrize(
        "argv",
        [
            # the MIGRATION.md acceptance-config command lines
            ["/d", "--dataset", "cifar10", "-a", "resnet20",
             "--w-kurtosis", "--w-kurtosis-target", "1.8",
             "--w-lambda-kurtosis", "1.0", "--ede"],
            ["/d", "--dataset", "cifar10", "-a", "resnet18",
             "--imagenet_setting_step_2_ts", "--arch_teacher",
             "resnet18_float", "--resume_teacher", "t.pth.tar",
             "--alpha", "0.9", "--temperature", "4", "--beta", "200",
             "--w-kurtosis"],
            ["/d", "--dataset", "imagenet", "-a", "resnet18",
             "--w-kurtosis", "--w-kurtosis-target", "1.8",
             "--w-lambda-kurtosis", "1.0", "--dtype", "bfloat16"],
            ["/d", "--dataset", "imagenet", "-a", "resnet34",
             "--imagenet_setting_step_2_ts", "--react",
             "--arch_teacher", "resnet34_float", "--resume_teacher",
             "t.pth.tar", "--w-kurtosis", "--dtype", "bfloat16"],
            ["/d", "--dataset", "imagenet", "-a", "resnet18",
             "--distributed-init", "--w-kurtosis", "--dtype",
             "bfloat16"],
        ],
    )
    def test_migration_doc_commands_parse(self, argv):
        cfg = parse(argv)
        assert cfg.data == "/d"
        # TS is gated on --imagenet_setting_step_2_ts, exactly as in
        # the reference (train.py:417; its --kd flag is dead there too)
        assert cfg.teacher_student == ("--imagenet_setting_step_2_ts" in argv)


class TestTpuNativeFlags:
    def test_parallelism_and_dtype(self):
        cfg = parse(
            [
                "/data", "--model-parallel", "2", "--distributed-init",
                "--dtype", "bfloat16", "--device-normalize", "--remat",
                "--target-acc", "63.0", "--opt-policy", "adam-linear",
                "--profile-dir", "/tmp/prof",
            ]
        )
        assert cfg.model_parallel == 2 and cfg.distributed_init
        assert cfg.dtype == "bfloat16" and cfg.device_normalize
        assert cfg.remat
        assert cfg.target_acc == 63.0
        assert cfg.opt_policy == "adam-linear"

    def test_bad_dataset_rejected(self):
        with pytest.raises(SystemExit):
            build_parser().parse_args(["/d", "--dataset", "mnist"])

    def test_telemetry_flags(self):
        cfg = parse(["/data", "--no-binarization-probes",
                     "--nonfinite-policy", "warn"])
        assert not cfg.probe_binarization
        assert cfg.nonfinite_policy == "warn"
        # defaults: probes on, fail fast
        cfg = parse(["/data"])
        assert cfg.probe_binarization
        assert cfg.nonfinite_policy == "raise"
        assert cfg.profile_at == ()

    def test_profile_at_flag(self):
        cfg = parse(["/data", "--profile-at", "0:5:3",
                     "--profile-at", "12:40"])
        assert cfg.profile_at == ("0:5:3", "12:40")
        cfg.validate()  # specs parse
        with pytest.raises(ValueError, match="profile-at"):
            parse(["/data", "--profile-at", "nonsense"]).validate()


class TestSummarizeSubcommand:
    """The console entrypoint for post-hoc reports must not silently
    break: run ``python -m bdbnn_tpu.cli summarize`` as a real
    subprocess against a fixture run dir (built from files alone —
    summarize never needs a live backend)."""

    def _run(self, *argv):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        return subprocess.run(
            [sys.executable, "-m", "bdbnn_tpu.cli", "summarize", *argv],
            capture_output=True, text=True, timeout=180, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )

    def test_summarize_report_and_json(self, fixture_run_dir):
        proc = self._run(fixture_run_dir)
        assert proc.returncode == 0, proc.stderr[-800:]
        assert "== Run summary:" in proc.stdout
        assert "compile" in proc.stdout
        assert "starvation verdict:" in proc.stdout
        assert "layer1_0.conv1" in proc.stdout

        proc = self._run(fixture_run_dir, "--json")
        assert proc.returncode == 0, proc.stderr[-800:]
        summary = json.loads(proc.stdout)
        assert summary["compile_s"] == pytest.approx(5.0)
        assert summary["starvation"]["input_bound"] is True

    def test_summarize_empty_dir_fails(self, tmp_path):
        proc = self._run(str(tmp_path))
        assert proc.returncode != 0

    def test_summarize_renders_attribution(self, fixture_run_dir):
        """The fixture run dir carries a capture window + memory
        events; the CLI report must render the attribution section
        with SEMANTIC category names and the HBM watermark."""
        proc = self._run(fixture_run_dir)
        assert proc.returncode == 0, proc.stderr[-800:]
        assert "device attribution" in proc.stdout
        assert "binary_conv" in proc.stdout
        assert "hbm: peak" in proc.stdout

        proc = self._run(fixture_run_dir, "--json")
        summary = json.loads(proc.stdout)
        cats = summary["attribution"]["categories_ms_per_step"]
        assert cats["binary_conv"] == pytest.approx(4.0)
        assert summary["attribution"]["hbm"]["peak_gib"] == pytest.approx(8.0)


class TestWatchSubcommand:
    """``python -m bdbnn_tpu.cli watch RUN_DIR --once`` — the live-tail
    status view, as a real subprocess against the fixture run dir. Like
    summarize, it reads files only (works on a synced log dir with no
    live process, and must not initialize a JAX backend)."""

    def _run(self, *argv):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        return subprocess.run(
            [sys.executable, "-m", "bdbnn_tpu.cli", "watch", *argv],
            capture_output=True, text=True, timeout=180, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )

    def test_watch_once(self, fixture_run_dir):
        proc = self._run(fixture_run_dir, "--once")
        assert proc.returncode == 0, proc.stderr[-800:]
        out = proc.stdout
        # epoch progress, latest eval, flip drift, completion verdict
        assert "epochs 0->3" in out
        assert "eval:" in out and "best 90.0" in out
        assert "flips:" in out and "settling" in out
        assert "hbm:" in out
        assert "DONE: best acc1 90.0 @ epoch 2" in out

    def test_watch_resolves_log_root(self, fixture_run_dir):
        proc = self._run(os.path.dirname(fixture_run_dir), "--once")
        assert proc.returncode == 0, proc.stderr[-800:]
        assert "DONE" in proc.stdout

    def test_watch_empty_dir_fails(self, tmp_path):
        proc = self._run(str(tmp_path))
        assert proc.returncode != 0
