"""Cross-run regression comparison tests (obs/compare.py + the
`compare` CLI engine): golden-output verdict over two checked-in
fixture run dirs, provenance alignment, tolerance semantics, and the
artifact (ACCURACY_* / BENCH_*) extraction paths."""

import json
import os

import pytest

from bdbnn_tpu.obs.compare import (
    _judge,
    compare_runs,
    extract_run,
    render_comparison,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join("tests", "fixtures", "compare")
BASE = os.path.join(FIXTURES, "base")
CAND = os.path.join(FIXTURES, "cand")


@pytest.fixture
def repo_cwd(monkeypatch):
    """The golden verdict embeds the repo-relative fixture paths the
    CLI would be invoked with."""
    monkeypatch.chdir(REPO)


class TestGoldenVerdict:
    def test_matches_checked_in_golden(self, repo_cwd):
        """THE determinism pin: compare over the two checked-in fixture
        run dirs reproduces the checked-in verdict JSON exactly — no
        clocks, no environment, byte-stable."""
        result = compare_runs([BASE, CAND])
        with open(os.path.join(REPO, FIXTURES, "expected_verdict.json")) as f:
            expected = json.load(f)
        assert result == expected

    def test_regression_verdict_and_metrics(self, repo_cwd):
        result = compare_runs([BASE, CAND])
        assert result["verdict"] == "regression"
        comp = result["comparisons"][0]
        rows = {m["metric"]: m for m in comp["metrics"]}
        # the fixture regresses on every shared axis
        assert rows["best_acc1"]["verdict"] == "regression"
        assert rows["best_acc1"]["delta"] == pytest.approx(-5.0)
        assert rows["time_to_common_acc_s"]["baseline"] == pytest.approx(30.0)
        assert rows["time_to_common_acc_s"]["candidate"] == pytest.approx(60.0)
        assert rows["img_per_s"]["verdict"] == "regression"
        assert rows["hbm_peak_bytes"]["verdict"] == "regression"
        # the candidate's critical flip_collapse alert is a regression
        # against an alert-free baseline
        assert rows["alerts_critical"]["candidate"] == 1
        assert rows["alerts_critical"]["verdict"] == "regression"

    def test_self_compare_passes(self, repo_cwd):
        result = compare_runs([BASE, BASE])
        assert result["verdict"] == "pass"
        assert all(
            m["verdict"] == "ok"
            for c in result["comparisons"]
            for m in c["metrics"]
        )

    def test_render_text(self, repo_cwd):
        text = render_comparison(compare_runs([BASE, CAND]))
        assert "== Run comparison" in text
        assert "REGRESSION" in text
        assert "best_acc1" in text
        assert "overall verdict: REGRESSION" in text

    def test_deterministic_across_invocations(self, repo_cwd):
        a = json.dumps(compare_runs([BASE, CAND]), sort_keys=True)
        b = json.dumps(compare_runs([BASE, CAND]), sort_keys=True)
        assert a == b

    def test_wide_tolerances_mask_regressions(self, repo_cwd):
        result = compare_runs(
            [BASE, CAND], tol_acc_pp=10.0, tol_rel=2.0, tol_hbm=1.0,
        )
        rows = {
            m["metric"]: m
            for m in result["comparisons"][0]["metrics"]
        }
        assert rows["best_acc1"]["verdict"] == "ok"
        assert rows["img_per_s"]["verdict"] == "ok"
        # the new critical alert can never be tolerated away
        assert rows["alerts_critical"]["verdict"] == "regression"
        assert result["verdict"] == "regression"


class TestJudge:
    def test_directions_and_tolerance(self):
        kw = dict(tol_acc_pp=0.5, tol_rel=0.1, tol_hbm=0.05)
        assert _judge("best_acc1", "higher", "acc", 90.0, 89.0, **kw)[
            "verdict"] == "regression"
        assert _judge("best_acc1", "higher", "acc", 90.0, 89.8, **kw)[
            "verdict"] == "ok"
        assert _judge("best_acc1", "higher", "acc", 90.0, 91.0, **kw)[
            "verdict"] == "improvement"
        assert _judge("wall_s", "lower", "rel", 100.0, 109.0, **kw)[
            "verdict"] == "ok"
        assert _judge("wall_s", "lower", "rel", 100.0, 112.0, **kw)[
            "verdict"] == "regression"
        assert _judge("wall_s", "lower", "rel", 100.0, 80.0, **kw)[
            "verdict"] == "improvement"
        # a missing side -> no row at all, never a phantom verdict
        assert _judge("mfu", "higher", "rel", None, 0.4, **kw) is None
        assert _judge("mfu", "higher", "rel", 0.4, None, **kw) is None


class TestAlignment:
    def test_recipe_mismatch_is_incomparable(self, repo_cwd, tmp_path):
        # clone the cand fixture with a different arch
        import shutil

        clone = tmp_path / "cand2"
        shutil.copytree(os.path.join(REPO, CAND), clone)
        man_path = clone / "manifest.json"
        man = json.loads(man_path.read_text())
        man["config"]["arch"] = "resnet18"
        man_path.write_text(json.dumps(man))

        result = compare_runs([BASE, str(clone)])
        assert result["verdict"] == "incomparable"
        comp = result["comparisons"][0]
        assert comp["metrics"] == []  # nothing judged across recipes
        assert any("arch" in m for m in comp["mismatches"])

        forced = compare_runs([BASE, str(clone)], allow_mismatch=True)
        assert forced["verdict"] == "regression"  # judged anyway
        assert forced["comparisons"][0]["mismatches"]

    def test_binarizer_family_is_a_recipe_field(self, repo_cwd, tmp_path):
        """Runs trained under different binarizer families must never
        silently compare as same-recipe (the registry's alignment
        contract); pre-registry manifests (no key -> None) still
        align."""
        import shutil

        clone = tmp_path / "cand_fam"
        shutil.copytree(os.path.join(REPO, CAND), clone)
        man_path = clone / "manifest.json"
        man = json.loads(man_path.read_text())
        man["config"]["binarizer"] = "proximal:delta1=0.25"
        man_path.write_text(json.dumps(man))

        base2 = tmp_path / "base_fam"
        shutil.copytree(os.path.join(REPO, BASE), base2)
        bman_path = base2 / "manifest.json"
        bman = json.loads(bman_path.read_text())
        bman["config"]["binarizer"] = "ste"
        bman_path.write_text(json.dumps(bman))

        result = compare_runs([str(base2), str(clone)])
        assert result["verdict"] == "incomparable"
        assert any(
            "binarizer" in m
            for m in result["comparisons"][0]["mismatches"]
        )
        # one side unknown (the checked-in pre-registry fixture) ->
        # never a mismatch
        legacy = compare_runs([os.path.join(REPO, BASE), str(clone)])
        assert not any(
            "binarizer" in m
            for m in legacy["comparisons"][0]["mismatches"]
        )

    def test_unknown_fields_do_not_mismatch(self, repo_cwd, tmp_path):
        """Artifacts carry partial provenance: a field one side doesn't
        know is not a mismatch."""
        art = tmp_path / "acc.json"
        art.write_text(json.dumps({
            "best_val_top1": 91.0,
            "arch": "resnet20",
            "epochs": 3,  # matches the fixture; dataset/lr/... unknown
        }))
        result = compare_runs([BASE, str(art)])
        assert result["comparisons"][0]["mismatches"] == []
        assert result["verdict"] == "pass"  # 91.0 > 90.0 baseline


class TestArtifactExtraction:
    def test_accuracy_artifact(self, tmp_path):
        art = tmp_path / "ACCURACY_x.json"
        art.write_text(json.dumps({
            "best_val_top1": 94.7,
            "val_top1_curve": [10.0, 50.0, 94.7],
            "time_to_target_s": 2235.9,
            "wall_seconds": 2521.4,
            "arch": "resnet20",
            "epochs": 100,
            "lr": 0.1,
            "batch_size": 128,
            "dtype": "float32",
            "ede": True,
        }))
        rec = extract_run(str(art))
        assert rec["format"] == "accuracy_artifact"
        assert rec["metrics"]["best_acc1"] == pytest.approx(94.7)
        assert rec["metrics"]["final_acc1"] == pytest.approx(94.7)
        assert rec["metrics"]["time_to_target_s"] == pytest.approx(2235.9)
        assert rec["metrics"]["wall_s"] == pytest.approx(2521.4)
        assert rec["provenance"]["recipe"]["arch"] == "resnet20"

    def test_bench_artifact(self, tmp_path):
        art = tmp_path / "BENCH_x.json"
        art.write_text(json.dumps({
            "n": 5,
            "parsed": {
                "metric": "train_step_images_per_sec_per_chip",
                "value": 6265.0,
                "device_ms_per_step": 16.99,
                "device_mfu": 0.383,
                "device_kind": "TPU v5 lite",
                "dtype": "bfloat16",
            },
        }))
        rec = extract_run(str(art))
        assert rec["format"] == "bench_artifact"
        assert rec["metrics"]["img_per_s"] == pytest.approx(6265.0)
        assert rec["metrics"]["jit_step_ms"] == pytest.approx(16.99)
        assert rec["metrics"]["mfu"] == pytest.approx(0.383)
        assert rec["provenance"]["device_kind"] == "TPU v5 lite"

    def test_bench_vs_bench_step_ms_regression(self, tmp_path):
        def bench(path, ms, mfu):
            path.write_text(json.dumps({
                "parsed": {
                    "metric": "m", "value": 1000.0 * 17.0 / ms,
                    "device_ms_per_step": ms, "device_mfu": mfu,
                    "device_kind": "TPU v5 lite", "dtype": "bfloat16",
                },
            }))

        bench(tmp_path / "a.json", 17.0, 0.38)
        bench(tmp_path / "b.json", 22.0, 0.29)  # ~29% slower
        result = compare_runs(
            [str(tmp_path / "a.json"), str(tmp_path / "b.json")]
        )
        rows = {
            m["metric"]: m
            for m in result["comparisons"][0]["metrics"]
        }
        assert rows["jit_step_ms"]["verdict"] == "regression"
        assert rows["mfu"]["verdict"] == "regression"
        assert result["verdict"] == "regression"

    def test_zero_shared_metrics_is_not_a_pass(self, tmp_path):
        """A CI gate must not report green for a comparison that
        compared nothing: an accuracy artifact vs a bench artifact
        share no metric, so the verdict is incomparable (exit 2), not
        pass (exit 0)."""
        acc = tmp_path / "acc.json"
        acc.write_text(json.dumps({"best_val_top1": 90.0}))
        bench = tmp_path / "bench.json"
        bench.write_text(json.dumps({
            "parsed": {"metric": "m", "value": 100.0,
                       "device_ms_per_step": 17.0},
        }))
        result = compare_runs([str(acc), str(bench)])
        assert result["comparisons"][0]["verdict"] == "no_shared_metrics"
        assert result["verdict"] == "incomparable"

    def test_unrecognized_artifact_rejected(self, tmp_path):
        bad = tmp_path / "x.json"
        bad.write_text(json.dumps({"hello": 1}))
        with pytest.raises(ValueError, match="not a recognized artifact"):
            extract_run(str(bad))

    def test_missing_source_rejected(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            extract_run(str(tmp_path / "nope"))

    def test_needs_two_sources(self):
        with pytest.raises(ValueError, match="baseline"):
            compare_runs(["one"])


class TestFleetAttributionVersionSkew:
    """The three v7 fleet-attribution metrics must skip cleanly when
    either side predates the fleet tracing plane (pinned per the
    satellite): a v6 verdict carries no fleet_attribution block, so
    the metrics land None on that side -> no row, never a phantom
    verdict or a crash."""

    V6 = {
        "serve_verdict": 6,
        "p99_ms": 12.0, "throughput_rps": 90.0, "shed_rate": 0.0,
        "provenance": {"recipe": {"arch": "resnet8_tiny",
                                  "dataset": "cifar10"}},
    }

    def test_v6_verdict_extracts_none_for_fleet_trace_metrics(self):
        from bdbnn_tpu.obs.compare import _serve_metrics

        m = _serve_metrics(dict(self.V6))
        assert m["serve_fleet_p99_network_ms"] is None
        assert m["serve_fleet_retry_hop_share"] is None
        assert m["serve_fleet_stage_spread_max"] is None

    def test_v6_vs_v7_skips_both_directions(self, tmp_path):
        v7 = dict(self.V6)
        v7["serve_verdict"] = 7
        v7["fleet_attribution"] = {
            "stages": {"network": {"p99_ms": 3.5, "n": 50}},
            "retry_hop_share": 0.0,
            "host_stage_spread_max": 1.2,
        }
        a = tmp_path / "v6.json"
        b = tmp_path / "v7.json"
        a.write_text(json.dumps(self.V6))
        b.write_text(json.dumps(v7))
        for pair in ([str(a), str(b)], [str(b), str(a)]):
            result = compare_runs(pair)
            judged = {
                m["metric"]
                for m in result["comparisons"][0]["metrics"]
            }
            assert "serve_fleet_p99_network_ms" not in judged
            assert "serve_fleet_retry_hop_share" not in judged
            assert "serve_fleet_stage_spread_max" not in judged
            assert result["verdict"] == "pass"

    def test_v7_both_sides_judges_fleet_trace_metrics(self, tmp_path):
        def v7(network_p99, share):
            v = dict(self.V6)
            v["serve_verdict"] = 7
            v["fleet_attribution"] = {
                "stages": {"network": {"p99_ms": network_p99,
                                       "n": 50}},
                "retry_hop_share": share,
                "host_stage_spread_max": 1.0,
            }
            return v

        a = tmp_path / "clean.json"
        b = tmp_path / "wedged.json"
        a.write_text(json.dumps(v7(3.0, 0.0)))
        b.write_text(json.dumps(v7(3.1, 0.25)))
        # a zero-baseline share leaves zero relative headroom: any
        # retry-hop time in the candidate regresses regardless of how
        # wide --tol-rel is opened (the acceptance compare gate)
        result = compare_runs([str(a), str(b)], tol_rel=5.0)
        rows = {
            m["metric"]: m
            for m in result["comparisons"][0]["metrics"]
        }
        assert rows["serve_fleet_retry_hop_share"]["verdict"] == (
            "regression"
        )
        assert rows["serve_fleet_p99_network_ms"]["verdict"] == "ok"
        assert result["verdict"] == "regression"


class TestCapacityVersionSkew:
    """The three v8 capacity gates must skip cleanly when either side
    predates the capacity observatory (pinned per the satellite): a
    v7 verdict carries no capacity block, so the metrics land None on
    that side -> no row, never a phantom verdict or a crash."""

    V7 = {
        "serve_verdict": 7,
        "p99_ms": 12.0, "throughput_rps": 90.0, "shed_rate": 0.0,
        "provenance": {"recipe": {"arch": "resnet8_tiny",
                                  "dataset": "cifar10"}},
    }

    @staticmethod
    def _v8(burn, headroom, shed_ratio):
        v = dict(TestCapacityVersionSkew.V7)
        v["serve_verdict"] = 8
        v["capacity"] = {
            "demand": {"offered_rps": 100.0},
            "slo_budget": {"episodes": []},
            "burn_rate_max": burn,
            "headroom_rps": headroom,
            "demand_shed_ratio_max": shed_ratio,
        }
        return v

    def test_v7_verdict_extracts_none_for_capacity_metrics(self):
        from bdbnn_tpu.obs.compare import _serve_metrics

        m = _serve_metrics(dict(self.V7))
        assert m["serve_burn_rate_max"] is None
        assert m["serve_headroom_rps"] is None
        assert m["serve_demand_shed_ratio_max"] is None

    def test_v7_vs_v8_skips_both_directions(self, tmp_path):
        a = tmp_path / "v7.json"
        b = tmp_path / "v8.json"
        a.write_text(json.dumps(self.V7))
        b.write_text(json.dumps(self._v8(0.4, 120.0, 0.01)))
        for pair in ([str(a), str(b)], [str(b), str(a)]):
            result = compare_runs(pair)
            judged = {
                m["metric"]
                for m in result["comparisons"][0]["metrics"]
            }
            assert "serve_burn_rate_max" not in judged
            assert "serve_headroom_rps" not in judged
            assert "serve_demand_shed_ratio_max" not in judged
            assert result["verdict"] == "pass"

    def test_v8_both_sides_judges_capacity_gates(self, tmp_path):
        a = tmp_path / "clean.json"
        b = tmp_path / "burning.json"
        a.write_text(json.dumps(self._v8(0.5, 120.0, 0.01)))
        # candidate: budget burning 3x harder, less headroom, worse
        # shed ratio — all three gates regress; headroom judges as
        # "higher is better" so the shrink is the regression
        b.write_text(json.dumps(self._v8(1.5, 40.0, 0.05)))
        result = compare_runs([str(a), str(b)])
        rows = {
            m["metric"]: m
            for m in result["comparisons"][0]["metrics"]
        }
        assert rows["serve_burn_rate_max"]["verdict"] == "regression"
        assert rows["serve_headroom_rps"]["verdict"] == "regression"
        assert rows["serve_demand_shed_ratio_max"]["verdict"] == (
            "regression"
        )
        assert rows["serve_p99_ms"]["verdict"] == "ok"
        assert result["verdict"] == "regression"
