"""Resharded-restore worker (tests/test_faults.py::TestReshardedResume).

Run as::

    python reshard_worker.py <devices> <victim_run_dir> [CLI_ARG...]

A fresh single-process interpreter pinned to ``<devices>`` virtual CPU
devices — a DIFFERENT topology than the 8-device session that wrote the
checkpoint. Two phases:

1. **Bitwise restore check**: build the training state template on the
   new mesh (same arch/optimizer as the fault harness), run the real
   ``load_checkpoint`` against it, and compare every params/batch_stats
   leaf against the template-free host read (``load_variables`` — the
   ground truth for what was saved). Prints ``RESHARD_PARAMS_BITWISE_OK``
   only if every leaf matches exactly: the elastic restore must change
   placement, never values.
2. **Resume to completion** (when CLI args follow): hand control to
   ``bdbnn_tpu.cli.main`` so the resumed training runs end-to-end on
   the smaller topology; the parent asserts the run's ``restore`` event
   lineage and final metrics.
"""

import os
import re
import sys

devices, victim = int(sys.argv[1]), sys.argv[2]
cli_args = sys.argv[3:]

os.environ["JAX_PLATFORMS"] = "cpu"
flags = re.sub(
    r"--xla_force_host_platform_device_count=\d+",
    "",
    os.environ.get("XLA_FLAGS", ""),
)
os.environ["XLA_FLAGS"] = (
    flags + f" --xla_force_host_platform_device_count={devices}"
).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from bdbnn_tpu.models import create_model  # noqa: E402
from bdbnn_tpu.parallel import create_sharded_state, make_mesh  # noqa: E402
from bdbnn_tpu.train import TrainState, make_optimizer  # noqa: E402
from bdbnn_tpu.utils.checkpoint import (  # noqa: E402
    CKPT_NAME,
    load_checkpoint,
    load_variables,
)

assert jax.device_count() == devices, jax.device_count()

# the fault-harness recipe (conftest.FAULT_BASE): the template only
# needs matching STRUCTURE + the new mesh's shardings
model = create_model("resnet8_tiny", "cifar10")
variables = model.init(
    jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), train=True
)
tx = make_optimizer(
    variables["params"], dataset="cifar10", lr=0.05, epochs=2,
    steps_per_epoch=4,
)
mesh = make_mesh()
state = create_sharded_state(mesh, variables, tx, TrainState)

restored = load_checkpoint(victim, state)
# ground truth must read the SAME chain load_checkpoint restores
# (<victim>/checkpoint) — load_variables(run_dir) would prefer
# model_best/, which diverges if the victim crossed an epoch boundary
# (and saved a best copy) before the preemption landed
ground = load_variables(os.path.join(victim, CKPT_NAME))

for name, got_tree, want_tree in (
    ("params", restored["state"].params, ground["params"]),
    ("batch_stats", restored["state"].batch_stats, ground["batch_stats"]),
):
    got = jax.tree_util.tree_leaves(jax.device_get(got_tree))
    want = jax.tree_util.tree_leaves(want_tree)
    assert len(got) == len(want), (name, len(got), len(want))
    for g, w in zip(got, want):
        assert np.array_equal(np.asarray(g), np.asarray(w)), (
            f"{name} leaf differs after reshard onto {devices} devices"
        )
print("RESHARD_PARAMS_BITWISE_OK", flush=True)
print(
    "RESHARD_CURSOR",
    restored["epoch"],
    restored["step_in_epoch"],
    (restored.get("topology") or {}).get("devices"),
    flush=True,
)

if cli_args:
    from bdbnn_tpu.cli import main

    rc = main(cli_args)
    print(f"RESHARD_RESUME_EXIT {rc}", flush=True)
    sys.exit(rc)
