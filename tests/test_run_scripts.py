"""Artifact-runner helpers (run_kd): stale-run isolation — a rerun in
the same workdir must read ONLY the latest timestamped run (the round-5
code review caught curves merging across a crashed run and its rerun)."""

import json
import os

import pytest

import run_kd


@pytest.mark.fast
class TestLatestRunSelection:
    def _mk_run(self, root, stamp, tag_value, with_best=True):
        d = root / "1.8" / stamp
        d.mkdir(parents=True)
        with open(d / "scalars.jsonl", "w") as f:
            f.write(json.dumps(
                {"tag": "Val Acc1", "value": tag_value, "step": 0}
            ) + "\n")
        if with_best:
            (d / "model_best").mkdir()
        return d

    def test_read_curves_uses_latest_only(self, tmp_path):
        self._mk_run(tmp_path, "2026-07-30_10-00-00", 11.0)
        self._mk_run(tmp_path, "2026-07-30_12-00-00", 99.0)
        curves = run_kd._read_curves(str(tmp_path), ("Val Acc1",))
        assert curves["Val Acc1"] == [99.0]

    def test_read_curves_empty_workdir(self, tmp_path):
        assert run_kd._read_curves(str(tmp_path), ("Val Acc1",)) == {}

    def test_find_run_dir_prefers_latest(self, tmp_path):
        old = self._mk_run(tmp_path, "2026-07-30_10-00-00", 1.0)
        new = self._mk_run(tmp_path, "2026-07-30_12-00-00", 2.0)
        assert run_kd._find_run_dir(str(tmp_path)) == str(new)
        assert run_kd._find_run_dir(str(tmp_path)) != str(old)

    def test_find_run_dir_raises_without_checkpoint(self, tmp_path):
        # runs exist but none ever checkpointed
        self._mk_run(tmp_path, "2026-07-30_10-00-00", 1.0, with_best=False)
        with pytest.raises(FileNotFoundError):
            run_kd._find_run_dir(str(tmp_path))
