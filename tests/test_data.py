"""Data-pipeline tests: augment semantics, shard disjointness,
determinism — covering what the reference's loader got wrong
(shuffled test sets, broken DistributedSampler; SURVEY.md Appendix B
#5/#6)."""

import numpy as np
import pytest

from bdbnn_tpu.data import (
    CIFAR_MEAN,
    CIFAR_STD,
    Pipeline,
    host_shard_indices,
    normalize,
    synthetic_dataset,
)
from bdbnn_tpu.data.pipeline import random_crop_pad, random_hflip


def test_normalize_matches_totensor_normalize(rng):
    u8 = rng.integers(0, 256, size=(4, 32, 32, 3), dtype=np.uint8)
    out = normalize(u8, CIFAR_MEAN, CIFAR_STD)
    expect = (u8.astype(np.float32) / 255.0 - CIFAR_MEAN) / CIFAR_STD
    np.testing.assert_allclose(out, expect, rtol=1e-6)


def test_random_crop_preserves_shape_and_content_domain(rng):
    u8 = rng.integers(1, 256, size=(8, 32, 32, 3), dtype=np.uint8)
    out = random_crop_pad(u8, np.random.default_rng(0), pad=4)
    assert out.shape == u8.shape
    # every output pixel is either zero padding or from the source image
    assert set(np.unique(out)) <= set(np.unique(u8)) | {0}


def test_hflip_flips_half_on_average():
    u8 = np.arange(16 * 32 * 32 * 3, dtype=np.uint8).reshape(16, 32, 32, 3)
    out = random_hflip(u8, np.random.default_rng(0))
    flipped = sum(
        not np.array_equal(a, b) for a, b in zip(out, u8)
    )
    assert 0 < flipped < 16


class TestHostSharding:
    def test_disjoint_and_complete(self):
        n, hosts = 1000, 4
        shards = [
            host_shard_indices(n, epoch=3, seed=7, host_id=h, num_hosts=hosts)
            for h in range(hosts)
        ]
        all_idx = np.concatenate(shards)
        assert len(all_idx) == n
        assert len(np.unique(all_idx)) == n  # disjoint + complete

    def test_deterministic_across_hosts(self):
        a = host_shard_indices(100, epoch=1, seed=3, host_id=0, num_hosts=2)
        b = host_shard_indices(100, epoch=1, seed=3, host_id=0, num_hosts=2)
        np.testing.assert_array_equal(a, b)

    def test_epoch_changes_order(self):
        a = host_shard_indices(100, epoch=0, seed=3)
        b = host_shard_indices(100, epoch=1, seed=3)
        assert not np.array_equal(a, b)

    def test_eval_not_shuffled(self):
        # Appendix B #6 fix: deterministic eval order
        a = host_shard_indices(50, epoch=9, shuffle=False)
        np.testing.assert_array_equal(a, np.arange(50))


class TestPipeline:
    def test_train_epoch_batches(self):
        ds = synthetic_dataset(130, 32, 10, seed=0)
        p = Pipeline(ds, batch_size=32, train=True, seed=0, prefetch=0)
        batches = list(p.epoch(0))
        assert len(batches) == 4 == p.steps_per_epoch()  # drop remainder
        x, y = batches[0]
        assert x.shape == (32, 32, 32, 3) and x.dtype == np.float32
        assert y.shape == (32,)

    def test_eval_keeps_remainder_and_order(self):
        ds = synthetic_dataset(70, 32, 10, seed=0)
        p = Pipeline(ds, batch_size=32, train=False, prefetch=0)
        batches = list(p.epoch(0))
        assert [len(b[1]) for b in batches] == [32, 32, 6]
        ys = np.concatenate([b[1] for b in batches])
        np.testing.assert_array_equal(ys, ds.labels)

    def test_prefetch_matches_sync(self):
        ds = synthetic_dataset(96, 32, 10, seed=1)
        sync = list(Pipeline(ds, 32, train=True, seed=5, prefetch=0).epoch(2))
        pre = list(Pipeline(ds, 32, train=True, seed=5, prefetch=3).epoch(2))
        assert len(sync) == len(pre)
        for (xa, ya), (xb, yb) in zip(sync, pre):
            np.testing.assert_array_equal(xa, xb)
            np.testing.assert_array_equal(ya, yb)

    def test_two_hosts_see_disjoint_labels_union_all(self):
        ds = synthetic_dataset(64, 8, 10, seed=2)
        # tag labels = example index to track identity
        ds.labels = np.arange(64)
        got = []
        for h in range(2):
            p = Pipeline(
                ds, 16, train=True, seed=0, host_id=h, num_hosts=2, prefetch=0
            )
            for _, y in p.epoch(0):
                got.append(y)
        allseen = np.concatenate(got)
        assert len(allseen) == 64
        assert len(np.unique(allseen)) == 64


@pytest.fixture(scope="module")
def jpeg_folder(tmp_path_factory):
    """2 classes x 12 JPEGs, 64x80 — shared by every ImageFolder
    pipeline test class."""
    from PIL import Image

    from bdbnn_tpu.data import ImageFolder

    root = tmp_path_factory.mktemp("imgs")
    rng = np.random.default_rng(0)
    for cls in ("a", "b"):
        d = root / "train" / cls
        d.mkdir(parents=True)
        for i in range(12):
            arr = rng.integers(0, 255, size=(64, 80, 3), dtype=np.uint8)
            Image.fromarray(arr).save(d / f"{i:03d}.jpg")
    return ImageFolder(str(root / "train"))


class TestMPImageFolderPipeline:
    """The pod-grade multiprocess ImageNet feed (VERDICT r3 #4):
    worker-count-invariant determinism + parity of the shard/batch
    contract with the thread fallback."""

    @pytest.mark.slow
    def test_deterministic_across_worker_counts(self, jpeg_folder):
        # slow-tier (PR 8 budget rebalance): worker count is
        # structurally irrelevant since PR 6's per-sample keyed augment
        # RNG (splitmix64 by GLOBAL dataset index) — the invariant is
        # pinned cheaper by the union-of-shards == single-host tests
        # and the bitwise resumed-tail tests; this 21s sweep re-proved
        # it across three worker counts.
        from bdbnn_tpu.data import MPImageFolderPipeline

        def batches(workers):
            pipe = MPImageFolderPipeline(
                jpeg_folder, 8, train=True, image_size=32, seed=3,
                num_workers=workers,
            )
            return list(pipe.epoch(0))

        b1, b4 = batches(1), batches(4)
        assert len(b1) == len(b4) == 3  # 24 images / batch 8
        for (x1, y1), (x4, y4) in zip(b1, b4):
            np.testing.assert_array_equal(y1, y4)
            np.testing.assert_array_equal(x1, x4)
        assert b1[0][0].shape == (8, 32, 32, 3)
        assert b1[0][0].dtype == np.float32

    def test_eval_ordered_unaugmented_and_remainder(self, jpeg_folder):
        from bdbnn_tpu.data import MPImageFolderPipeline

        pipe = MPImageFolderPipeline(
            jpeg_folder, 10, train=False, image_size=32, num_workers=2,
        )
        got = list(pipe.epoch(0))
        # eval keeps the remainder: 24 -> 10 + 10 + 4
        assert [len(y) for _, y in got] == [10, 10, 4]
        labels = np.concatenate([y for _, y in got])
        np.testing.assert_array_equal(
            labels, [s[1] for s in jpeg_folder.samples]
        )
        # deterministic: second epoch identical
        again = list(pipe.epoch(0))
        for (x1, _), (x2, _) in zip(got, again):
            np.testing.assert_array_equal(x1, x2)

    def test_host_sharding_disjoint(self, jpeg_folder):
        from bdbnn_tpu.data import MPImageFolderPipeline

        def epoch_sample_counts(host_id):
            pipe = MPImageFolderPipeline(
                jpeg_folder, 4, train=True, image_size=32, seed=1,
                host_id=host_id, num_hosts=2, num_workers=2,
            )
            return sum(len(y) for _, y in pipe.epoch(0))

        assert epoch_sample_counts(0) + epoch_sample_counts(1) == 24


class TestTFDataImageFolderPipeline:
    """The tf.data input engine (the BASELINE.json-named pod path):
    same shard/batch/determinism contract as the mp pipeline, decode +
    augment in TF's C++ threadpool."""

    # collection-cheap check (find_spec, not a real TF import — the
    # heavyweight import-proving tfdata_available() would load TF during
    # pytest collection for every run, including the fast tier)
    pytestmark = pytest.mark.skipif(
        __import__("importlib.util", fromlist=["find_spec"]).find_spec(
            "tensorflow"
        )
        is None,
        reason="tensorflow not installed",
    )

    def test_train_shapes_dtype_and_determinism(self, jpeg_folder):
        from bdbnn_tpu.data import TFDataImageFolderPipeline

        pipe = TFDataImageFolderPipeline(
            jpeg_folder, 8, train=True, image_size=32, seed=3
        )
        got = list(pipe.epoch(0))
        assert len(got) == 3  # 24 images / batch 8, drop remainder
        x, y = got[0]
        assert x.shape == (8, 32, 32, 3) and x.dtype == np.float32
        assert y.dtype == np.int64
        # normalized: values live in roughly (x-mean)/std range, and the
        # batch is not constant
        assert x.std() > 0.1 and abs(float(x.mean())) < 3.0
        # bit-identical re-run (stateless augment ops keyed on
        # (seed, epoch, index) — AUTOTUNE decisions cannot change data)
        again = list(pipe.epoch(0))
        for (x1, y1), (x2, y2) in zip(got, again):
            np.testing.assert_array_equal(x1, x2)
            np.testing.assert_array_equal(y1, y2)
        # different epoch reshuffles + re-augments
        other = list(pipe.epoch(1))
        assert any(
            not np.array_equal(a[1], b[1]) or not np.array_equal(a[0], b[0])
            for a, b in zip(got, other)
        )

    def test_eval_ordered_remainder_and_u8(self, jpeg_folder):
        from bdbnn_tpu.data import TFDataImageFolderPipeline

        pipe = TFDataImageFolderPipeline(
            jpeg_folder, 10, train=False, image_size=32,
            device_normalize=True,
        )
        got = list(pipe.epoch(0))
        assert [len(y) for _, y in got] == [10, 10, 4]
        assert got[0][0].dtype == np.uint8
        labels = np.concatenate([y for _, y in got])
        np.testing.assert_array_equal(
            labels, [s[1] for s in jpeg_folder.samples]
        )

    def test_eval_matches_pil_reference_pipeline(self, jpeg_folder):
        """The eval transform (Resize(short=256)+CenterCrop) must agree
        with the PIL path within resampling tolerance — both claim
        torchvision semantics."""
        from bdbnn_tpu.data import (
            ImageFolderPipeline,
            TFDataImageFolderPipeline,
        )

        tf_pipe = TFDataImageFolderPipeline(
            jpeg_folder, 24, train=False, image_size=224,
            device_normalize=True,
        )
        pil_pipe = ImageFolderPipeline(
            jpeg_folder, 24, train=False, image_size=224,
            device_normalize=True,
        )
        (xt, _), = list(tf_pipe.epoch(0))
        (xp, _), = list(pil_pipe.epoch(0))
        # same geometry; with antialias=True on the tf resizes
        # (ADVICE r4 — PIL antialiases, tf by default does not) the two
        # kernels agree to within a few of 255 levels (measured: mean
        # |diff| ~1.1, max 5 on this fixture) — tight enough that a
        # systematic resize-protocol deviation fails the suite
        assert xt.shape == xp.shape
        diff = np.abs(xt.astype(np.int32) - xp.astype(np.int32))
        assert float(np.mean(diff)) < 2.0
        assert float(np.mean(diff < 8)) > 0.995

    def test_host_sharding_disjoint(self, jpeg_folder):
        from bdbnn_tpu.data import TFDataImageFolderPipeline

        def count(host_id):
            pipe = TFDataImageFolderPipeline(
                jpeg_folder, 4, train=True, image_size=32, seed=1,
                host_id=host_id, num_hosts=2,
            )
            return sum(len(y) for _, y in pipe.epoch(0))

        assert count(0) + count(1) == 24


class TestCifarPickleBranch:
    """The real ``cifar-10-batches-py`` loader branch (VERDICT r4 #6):
    a byte-layout fixture synthesized exactly like the distribution
    pickles (3072-byte CHW uint8 rows, ``b"data"``/``b"labels"`` keys,
    bytes-keyed dicts) so a data-bearing machine runs BASELINE config 1
    unmodified — previously only the npz fallback was tested."""

    @pytest.fixture(scope="class")
    def cifar_pickle_root(self, tmp_path_factory):
        import pickle

        root = tmp_path_factory.mktemp("cifar10")
        base = root / "cifar-10-batches-py"
        base.mkdir()
        rng = np.random.default_rng(7)

        def write(name, n, label_offset):
            # distribution layout: row = R-plane ++ G-plane ++ B-plane
            imgs = rng.integers(0, 256, size=(n, 3, 32, 32), dtype=np.uint8)
            labels = [(label_offset + i) % 10 for i in range(n)]
            d = {
                b"data": imgs.reshape(n, 3072),
                b"labels": labels,
                b"batch_label": name.encode(),
                b"filenames": [f"{i}.png".encode() for i in range(n)],
            }
            with open(base / name, "wb") as f:
                pickle.dump(d, f)
            return imgs, np.asarray(labels)

        train = [write(f"data_batch_{i}", 8, i) for i in range(1, 6)]
        test = write("test_batch", 6, 3)
        return root, train, test

    def test_train_split_concatenates_all_batches(self, cifar_pickle_root):
        from bdbnn_tpu.data import load_cifar10

        root, train, _ = cifar_pickle_root
        ds = load_cifar10(str(root), "train")
        assert len(ds) == 40
        want_imgs = np.concatenate([t[0] for t in train])  # NCHW
        want_labels = np.concatenate([t[1] for t in train])
        # loader must emit NHWC uint8
        assert ds.images.shape == (40, 32, 32, 3)
        assert ds.images.dtype == np.uint8
        np.testing.assert_array_equal(
            ds.images, want_imgs.transpose(0, 2, 3, 1)
        )
        np.testing.assert_array_equal(ds.labels, want_labels)
        assert ds.labels.dtype == np.int64

    def test_test_split_reads_test_batch(self, cifar_pickle_root):
        from bdbnn_tpu.data import load_cifar10

        root, _, (imgs, labels) = cifar_pickle_root
        ds = load_cifar10(str(root), "test")
        assert len(ds) == 6
        np.testing.assert_array_equal(
            ds.images, imgs.transpose(0, 2, 3, 1)
        )
        np.testing.assert_array_equal(ds.labels, labels)

    def test_channel_plane_decode_is_exact(self, cifar_pickle_root):
        """One hand-built row: R plane all 10s, G all 20s, B all 30s —
        the decoded HWC pixel must be exactly (10, 20, 30)."""
        import pickle

        from bdbnn_tpu.data import load_cifar10

        root, *_ = cifar_pickle_root
        solo = root.parent / "cifar_solo"
        (solo / "cifar-10-batches-py").mkdir(parents=True)
        row = np.concatenate(
            [np.full(1024, v, np.uint8) for v in (10, 20, 30)]
        )
        d = {b"data": row[None, :], b"labels": [4]}
        for name in [f"data_batch_{i}" for i in range(1, 6)] + ["test_batch"]:
            with open(solo / "cifar-10-batches-py" / name, "wb") as f:
                pickle.dump(d, f)
        ds = load_cifar10(str(solo), "test")
        np.testing.assert_array_equal(ds.images[0, 0, 0], [10, 20, 30])
        np.testing.assert_array_equal(
            ds.images[0], np.stack([np.full((32, 32), v, np.uint8)
                                    for v in (10, 20, 30)], axis=-1)
        )
        assert ds.labels[0] == 4


class TestResumableIterators:
    """Mid-epoch resume cursors (preemption tolerance): ``epoch(e,
    start_step=k)`` must yield EXACTLY the batches an uninterrupted
    ``epoch(e)`` yields from batch k on — including augmentation draws,
    which are derived per batch/sample, never from a sequential stream
    a skip would desynchronize."""

    def test_pipeline_tail_is_bitwise_identical(self):
        ds = synthetic_dataset(96, 8, 4, seed=1)
        pipe = Pipeline(ds, 16, train=True, seed=5, prefetch=0)
        full = list(pipe.epoch(2))
        assert len(full) == 6
        for k in (1, 3, 5):
            tail = list(pipe.epoch(2, start_step=k))
            assert len(tail) == len(full) - k
            for (xf, yf), (xt, yt) in zip(full[k:], tail):
                np.testing.assert_array_equal(xf, xt)
                np.testing.assert_array_equal(yf, yt)

    def test_pipeline_tail_identical_with_prefetch_thread(self):
        ds = synthetic_dataset(64, 8, 4, seed=1)
        full = list(Pipeline(ds, 16, train=True, seed=5, prefetch=0).epoch(0))
        tail = list(
            Pipeline(ds, 16, train=True, seed=5, prefetch=3).epoch(
                0, start_step=2
            )
        )
        for (xf, yf), (xt, yt) in zip(full[2:], tail):
            np.testing.assert_array_equal(xf, xt)
            np.testing.assert_array_equal(yf, yt)

    def test_imagefolder_tail_is_bitwise_identical(self, jpeg_folder):
        from bdbnn_tpu.data import ImageFolderPipeline

        pipe = ImageFolderPipeline(
            jpeg_folder, 8, train=True, image_size=32, seed=3,
            num_threads=2,
        )
        full = list(pipe.epoch(1))
        tail = list(pipe.epoch(1, start_step=1))
        assert len(tail) == len(full) - 1
        for (xf, yf), (xt, yt) in zip(full[1:], tail):
            np.testing.assert_array_equal(xf, xt)
            np.testing.assert_array_equal(yf, yt)

    def test_mp_imagefolder_tail_is_bitwise_identical(self, jpeg_folder):
        from bdbnn_tpu.data import MPImageFolderPipeline

        pipe = MPImageFolderPipeline(
            jpeg_folder, 8, train=True, image_size=32, seed=3,
            num_workers=2,
        )
        try:
            full = list(pipe.epoch(0))
            tail = list(pipe.epoch(0, start_step=2))
        finally:
            pipe.close()
        for (xf, yf), (xt, yt) in zip(full[2:], tail):
            np.testing.assert_array_equal(xf, xt)
            np.testing.assert_array_equal(yf, yt)


class TestTopologyInvariantStream:
    """Elastic resume needs the TRAIN stream to be a pure function of
    (seed, epoch, global sample index) — never of host count or stream
    position. Then any (host_id, num_hosts) sharding of the same global
    permutation yields, per global step, the SAME multiset of
    (augmented sample, label): a checkpoint from an M-host run resumes
    onto M' hosts and feeds bit-identical augmented pixels."""

    @staticmethod
    def _rows(x, y):
        """Order-independent batch fingerprint: one bytes key per
        (augmented sample, label) pair, sorted."""
        return sorted(
            xi.tobytes() + int(yi).to_bytes(8, "little")
            for xi, yi in zip(np.asarray(x), np.asarray(y))
        )

    def test_pipeline_union_matches_single_host_batches(self):
        ds = synthetic_dataset(64, 8, 4, seed=1)
        solo = list(Pipeline(ds, 16, train=True, seed=7, prefetch=0).epoch(3))
        duo = [
            list(
                Pipeline(
                    ds, 8, train=True, seed=7, prefetch=0,
                    host_id=h, num_hosts=2,
                ).epoch(3)
            )
            for h in (0, 1)
        ]
        assert len(solo) == len(duo[0]) == len(duo[1]) == 4
        for k, (x, y) in enumerate(solo):
            union_x = np.concatenate([duo[0][k][0], duo[1][k][0]])
            union_y = np.concatenate([duo[0][k][1], duo[1][k][1]])
            # same global batch content, augmentation draws included
            assert self._rows(x, y) == self._rows(union_x, union_y)

    def test_imagefolder_union_matches_single_host_batches(self, jpeg_folder):
        from bdbnn_tpu.data import ImageFolderPipeline

        mk = lambda bs, h, n: ImageFolderPipeline(
            jpeg_folder, bs, train=True, image_size=32, seed=9,
            num_threads=2, host_id=h, num_hosts=n,
        )
        solo = list(mk(8, 0, 1).epoch(2))
        duo = [list(mk(4, h, 2).epoch(2)) for h in (0, 1)]
        steps = min(len(solo), len(duo[0]), len(duo[1]))
        assert steps >= 2
        for k in range(steps):
            union_x = np.concatenate([duo[0][k][0], duo[1][k][0]])
            union_y = np.concatenate([duo[0][k][1], duo[1][k][1]])
            assert self._rows(*solo[k]) == self._rows(union_x, union_y)

    def test_keyed_augment_is_per_sample_deterministic(self):
        from bdbnn_tpu.data import keyed_crop_flip, sample_augment_keys

        rng = np.random.default_rng(0)
        imgs = rng.integers(0, 256, size=(6, 32, 32, 3), dtype=np.uint8)
        keys = sample_augment_keys(5, 2, np.arange(10, 16))
        out = keyed_crop_flip(imgs, keys)
        # the draw for a sample depends only on ITS key: augmenting a
        # permuted batch permutes the outputs exactly
        perm = np.array([3, 1, 5, 0, 2, 4])
        out_perm = keyed_crop_flip(imgs[perm], keys[perm])
        np.testing.assert_array_equal(out[perm], out_perm)
        # ...and a different epoch produces different draws
        keys2 = sample_augment_keys(5, 3, np.arange(10, 16))
        assert (keys != keys2).all()


class TestGracefulDataDegradation:
    """One corrupt image must cost one substituted sample + one
    recorded ``data_error`` — not the run (ImageFolderPipeline._load_one
    retry -> deterministic-neighbor substitute)."""

    @pytest.fixture
    def folder_with_corruption(self, tmp_path):
        from PIL import Image

        from bdbnn_tpu.data import ImageFolder

        rng = np.random.default_rng(0)
        d = tmp_path / "train" / "a"
        d.mkdir(parents=True)
        for i in range(8):
            arr = rng.integers(0, 255, size=(48, 48, 3), dtype=np.uint8)
            Image.fromarray(arr).save(d / f"{i:03d}.jpg")
        # truncate one file mid-stream (the classic bitrot/partial-copy
        # failure PIL raises OSError on)
        victim = d / "003.jpg"
        data = victim.read_bytes()
        victim.write_bytes(data[: len(data) // 2])
        return ImageFolder(str(tmp_path / "train"))

    def test_corrupt_sample_is_substituted_and_reported(
        self, folder_with_corruption
    ):
        from bdbnn_tpu.data import ImageFolderPipeline

        pipe = ImageFolderPipeline(
            folder_with_corruption, 4, train=False, image_size=32,
            num_threads=2,
        )
        seen = []
        pipe.on_data_error = seen.append
        batches = list(pipe.epoch(0))
        # the epoch completes at full size despite the corrupt file
        assert sum(len(y) for _, y in batches) == 8
        assert len(seen) == 1
        err = seen[0]
        assert err["index"] == 3
        assert err["substitute"] == 4  # deterministic neighbor
        assert err["path"].endswith("003.jpg")
        assert "Error" in err["error"] or "error" in err["error"].lower()

    def test_mp_pipeline_substitutes_and_reports(
        self, folder_with_corruption
    ):
        """The pod-grade multiprocess backend keeps the same contract:
        the substitution happens in the worker process and the error
        travels back over the result pipe to on_data_error."""
        from bdbnn_tpu.data import MPImageFolderPipeline

        pipe = MPImageFolderPipeline(
            folder_with_corruption, 4, train=False, image_size=32,
            num_workers=2,
        )
        seen = []
        pipe.on_data_error = seen.append
        try:
            batches = list(pipe.epoch(0))
        finally:
            pipe.close()
        assert sum(len(y) for _, y in batches) == 8
        assert len(seen) == 1
        assert seen[0]["index"] == 3 and seen[0]["substitute"] == 4
        assert seen[0]["path"].endswith("003.jpg")

    def test_corrupt_sample_substitution_is_deterministic(
        self, folder_with_corruption
    ):
        from bdbnn_tpu.data import ImageFolderPipeline

        pipe = ImageFolderPipeline(
            folder_with_corruption, 4, train=True, image_size=32,
            num_threads=2,
        )
        a = list(pipe.epoch(0))
        b = list(pipe.epoch(0))
        for (xa, ya), (xb, yb) in zip(a, b):
            np.testing.assert_array_equal(xa, xb)
            np.testing.assert_array_equal(ya, yb)

    def test_all_corrupt_raises(self, tmp_path):
        from PIL import Image

        from bdbnn_tpu.data import ImageFolder, ImageFolderPipeline

        d = tmp_path / "train" / "a"
        d.mkdir(parents=True)
        arr = np.zeros((32, 32, 3), np.uint8)
        for i in range(2):
            Image.fromarray(arr).save(d / f"{i}.jpg")
        for p in d.iterdir():
            p.write_bytes(b"not an image at all")
        pipe = ImageFolderPipeline(
            ImageFolder(str(tmp_path / "train")), 2, train=False,
            image_size=32, num_threads=1,
        )
        with pytest.raises(Exception):
            list(pipe.epoch(0))
