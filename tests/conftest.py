"""Test harness: simulate an 8-device TPU-like mesh on CPU.

The reference validated distributed behavior only by running on the
authors' GPU cluster (SURVEY.md §4); here every distributed code path is
exercised on a virtual 8-device CPU mesh via
``--xla_force_host_platform_device_count`` — the JAX-native analogue of a
gloo/mock-NCCL DDP test.

Must run before the first ``import jax`` anywhere in the test session.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The env var alone is NOT enough in environments where a PJRT plugin's
# sitecustomize has already called jax.config.update("jax_platforms", ...)
# at interpreter start (config updates override the env var). Re-pin to
# CPU here, before any backend is initialized, so jax.devices() never
# dials a remote TPU from a unit test.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# The `fast` tier (`pytest -m fast`, <60s): pure-numerics oracle tests
# (binarization custom_vjps, kurtosis/KD losses, optimizer + EDE-schedule
# torch parity) plus the no-jax CLI flag-surface tests. The full suite
# stays the default.
_FAST_MODULES = {"test_binarize", "test_kurtosis", "test_kd", "test_cli"}
_FAST_CLASSES = {"TestOptimizerParity", "TestEDESchedule"}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if (
            item.module.__name__ in _FAST_MODULES
            or (item.cls is not None and item.cls.__name__ in _FAST_CLASSES)
        ):
            item.add_marker(pytest.mark.fast)


@pytest.fixture
def rng():
    return np.random.default_rng(0)
