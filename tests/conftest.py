"""Test harness: simulate an 8-device TPU-like mesh on CPU.

The reference validated distributed behavior only by running on the
authors' GPU cluster (SURVEY.md §4); here every distributed code path is
exercised on a virtual 8-device CPU mesh via
``--xla_force_host_platform_device_count`` — the JAX-native analogue of a
gloo/mock-NCCL DDP test.

Must run before the first ``import jax`` anywhere in the test session.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The env var alone is NOT enough in environments where a PJRT plugin's
# sitecustomize has already called jax.config.update("jax_platforms", ...)
# at interpreter start (config updates override the env var). Re-pin to
# CPU here, before any backend is initialized, so jax.devices() never
# dials a remote TPU from a unit test.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# The `fast` tier (`pytest -m fast`, <60s): pure-numerics oracle tests
# (binarization custom_vjps, kurtosis/KD losses, optimizer + EDE-schedule
# torch parity) plus the no-jax CLI flag-surface tests. The full suite
# stays the default.
_FAST_MODULES = {"test_binarize", "test_kurtosis", "test_kd", "test_cli"}
_FAST_CLASSES = {"TestOptimizerParity", "TestEDESchedule"}
# in fast modules but not fast: real subprocesses that import jax
_NOT_FAST_CLASSES = {
    "TestSummarizeSubcommand",
    "TestWatchSubcommand",
    "TestSummarizeStrict",
    "TestCompareSubcommand",
    "TestServeCliSmoke",
    "TestPerfCliSmoke",
}


def gloo_cpu_collectives_available() -> bool:
    """True when this jaxlib ships the gloo CPU collectives the
    simulated-pod subprocess tests configure
    (``jax_cpu_collectives_implementation=gloo``). Without it a
    multi-process CPU cluster cannot compile cross-host collectives and
    every gloo worker dies at its first non-addressable device_put —
    better to skip those tests WITH A REASON than to fail or silently
    pass."""
    try:
        from jax._src.lib import xla_extension as xe
    except Exception:
        try:
            import jaxlib.xla_extension as xe
        except Exception:
            return False
    return hasattr(xe, "make_gloo_tcp_collectives")


_GLOO_SKIP = pytest.mark.skip(
    reason="platform lacks gloo multiprocess CPU collectives (jaxlib "
    "built without make_gloo_tcp_collectives) — the simulated-pod "
    "subprocess tests cannot form a CPU cluster here"
)


def pytest_collection_modifyitems(config, items):
    gloo_ok = gloo_cpu_collectives_available()
    for item in items:
        # gloo-marked tests (test_multihost, the pod fault matrix) need
        # multiprocess CPU collectives; skip CLEANLY where absent
        if not gloo_ok and item.get_closest_marker("gloo") is not None:
            item.add_marker(_GLOO_SKIP)
        if item.cls is not None and item.cls.__name__ in _NOT_FAST_CLASSES:
            continue
        if (
            item.module.__name__ in _FAST_MODULES
            or (item.cls is not None and item.cls.__name__ in _FAST_CLASSES)
        ):
            item.add_marker(pytest.mark.fast)


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def retry_once_flaky(attempt, *, note, exceptions=(AssertionError,)):
    """THE quarantine policy for known timing-sensitive transients —
    one place, one contract (PR 11; unifies the copies that had grown
    in test_multihost, the test_pod_faults cluster-formation fixture
    and the paced scaling sweep).

    ``attempt(i)`` runs one attempt (``i`` = 0 or 1, so callers can
    vary workdirs per attempt) and raises one of ``exceptions`` on
    failure. Policy: the FIRST failure is surfaced as a warning
    carrying the caller's tracking ``note`` (a recurring flake stays
    visible in -W summaries instead of vanishing), then exactly ONE
    retry runs. A deterministic failure fails BOTH attempts and still
    fails the suite — the retry masks box contention, never a real
    regression. Do not wrap a test in this without a tracking note
    naming the documented transient it quarantines."""
    import warnings

    try:
        return attempt(0)
    except exceptions as first:
        warnings.warn(
            f"{note} — known transient, retrying once: {first}"
        )
        return attempt(1)


# ---------------------------------------------------------------------------
# Tier-1 wall-budget guard: the ROADMAP command runs the not-slow tier
# under `timeout -k 10 870`; drifting past that used to be discovered
# only as a mid-run SIGKILL with zero diagnostics. This guard FAILS the
# suite (with a rebalance hint) as soon as a green not-slow run exceeds
# the soft budget below, so budget drift is a red test with a message,
# never a timeout autopsy. Scoped to `-m 'not slow'` invocations only —
# full/slow runs and small -k selections are not the tier-1 shape.
# ---------------------------------------------------------------------------

TIER1_WALL_BUDGET_S = float(os.environ.get("TIER1_WALL_BUDGET_S", "850"))


def pytest_sessionstart(session):
    import time

    session.config._tier1_wall_t0 = time.monotonic()


def pytest_sessionfinish(session, exitstatus):
    import time

    t0 = getattr(session.config, "_tier1_wall_t0", None)
    if t0 is None:
        return
    try:
        markexpr = session.config.getoption("markexpr") or ""
    except Exception:
        return
    if "not slow" not in markexpr:
        return
    elapsed = time.monotonic() - t0
    if elapsed <= TIER1_WALL_BUDGET_S:
        return
    msg = (
        f"\nTIER-1 WALL BUDGET EXCEEDED: {elapsed:.0f}s > "
        f"{TIER1_WALL_BUDGET_S:.0f}s soft budget (hard timeout 870s).\n"
        "Rebalance before the driver starts SIGKILLing mid-run: move "
        "the broadest e2e smokes whose logic has denser unit/fault "
        "coverage to the `slow` tier (PR 6/7/8 precedent — fit() "
        "smokes, soak tests, heavy per-arch matrix tails), or raise "
        "TIER1_WALL_BUDGET_S explicitly if the box is known-slow."
    )
    tr = session.config.pluginmanager.get_plugin("terminalreporter")
    if tr is not None:
        tr.write_line(msg, red=True)
    else:
        print(msg)
    if session.exitstatus == 0:
        session.exitstatus = 1


# ---------------------------------------------------------------------------
# Simulated-device subprocess harness: one place that knows how to pin a
# FRESH python process to its own --xla_force_host_platform_device_count
# (the tests/pod_worker.py env recipe), shared by the reshard tests
# (test_faults.py — restore onto 4/2 devices) and the replica-pool CLI
# e2e (test_pool.py — serve-bench --replicas on a clean 8-device mesh).
# Subprocess isolation matters: the parent session's jax backend is
# already initialized at 8 devices and cannot be re-pinned in-process.
# ---------------------------------------------------------------------------

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(TESTS_DIR)


@pytest.fixture(scope="session")
def sim_device_subprocess():
    """Session-scoped runner for device-count-pinned subprocesses:
    ``run(argv, devices=8, timeout=540) -> CompletedProcess``. The env
    strips the parent's XLA_FLAGS (workers that pin their own count do
    so themselves — pod_worker.py / reshard_worker.py), forces the
    requested count otherwise, pins JAX_PLATFORMS=cpu, and puts the
    repo root on PYTHONPATH with cwd at the repo root."""
    import re as _re
    import subprocess
    import sys as _sys

    def run(argv, *, devices=8, timeout=540, pin_env=True):
        env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
        env["JAX_PLATFORMS"] = "cpu"
        if pin_env:
            flags = _re.sub(
                r"--xla_force_host_platform_device_count=\d+", "",
                os.environ.get("XLA_FLAGS", ""),
            )
            env["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count={devices}"
            ).strip()
        env["PYTHONPATH"] = (
            REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
        )
        return subprocess.run(
            [_sys.executable, *argv],
            capture_output=True, text=True, env=env, cwd=REPO_ROOT,
            timeout=timeout,
        )

    return run


# ---------------------------------------------------------------------------
# Network front-end harness (tests/test_http.py): a session-scoped
# free-port allocator (two fixtures in one session never race for the
# same port) and a server-lifecycle factory that guarantees every
# started front end is drained at teardown, pass or fail.
# ---------------------------------------------------------------------------


@pytest.fixture(scope="session")
def port_allocator():
    """Session-scoped free-port allocator, multi-process hardened:
    **bind-and-hold handoff** instead of probe-then-release. ``alloc()``
    binds port 0 and KEEPS the socket bound — while held, the kernel
    cannot re-issue that port to any other port-0 bind on the box
    (the race the old probe hit once fleet tests started handing ports
    to host SUBPROCESSES whose bind happens seconds after the probe).
    The holder is closed at handoff time: ``alloc(hold=True)`` returns
    the port still held and the caller releases it with
    ``alloc.release(port)`` immediately before binding; the default
    ``hold=False`` releases on return (the in-process consumers bind
    within microseconds). An explicit bind of a held port by an
    unrelated process remains possible in the tiny release→bind
    window — cluster-formation callers additionally wrap in
    ``retry_once_flaky``."""
    import socket as _socket

    handed = set()
    held = {}

    def release(port: int) -> int:
        s = held.pop(port, None)
        if s is not None:
            s.close()
        return port

    def alloc(hold: bool = False) -> int:
        while True:
            s = _socket.socket()
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
            if port in handed:
                s.close()
                continue
            handed.add(port)
            held[port] = s
            if not hold:
                release(port)
            return port

    alloc.release = release
    yield alloc
    for port in list(held):
        release(port)


@pytest.fixture
def free_port(port_allocator):
    return port_allocator()


@pytest.fixture
def http_frontend(port_allocator):
    """Factory for stub-runner HTTP front ends (no JAX): returns
    ``make(runner=..., **kw) -> HttpFrontEnd`` already started on a
    fresh port; every started server is drained at teardown even when
    the test failed mid-request."""
    from bdbnn_tpu.serve.admission import AdmissionController
    from bdbnn_tpu.serve.batching import MicroBatcher
    from bdbnn_tpu.serve.http import HttpFrontEnd

    started = []

    def make(
        runner=None,
        *,
        priorities=3,
        max_batch=8,
        max_queue=16,
        max_delay_ms=2.0,
        default_rate=1e9,
        default_burst=1e9,
        quotas=None,
        clock=None,
        ready_fn=None,
        **front_kw,
    ):
        if runner is None:
            runner = lambda batch: list(batch)
        batcher = MicroBatcher(
            runner,
            max_batch=max_batch,
            max_queue=max_queue,
            max_delay_ms=max_delay_ms,
            priorities=priorities,
        )
        admission_kw = dict(
            default_rate=default_rate,
            default_burst=default_burst,
            quotas=quotas or {},
        )
        if clock is not None:
            admission_kw["clock"] = clock
        admission = AdmissionController(**admission_kw)
        # bind-and-hold handoff: the allocator keeps the port's socket
        # bound until immediately before the front end binds it
        port = port_allocator(hold=True)
        fe = HttpFrontEnd(
            batcher,
            admission,
            ready_fn=ready_fn or (lambda: True),
            port=port,
            **front_kw,
        )
        port_allocator.release(port)
        fe.start()
        started.append(fe)
        return fe

    yield make
    for fe in started:
        fe.drain(timeout=10.0)


def write_synthetic_trace(path, n_steps=5):
    """A hand-built ``*.trace.json.gz`` in the Chrome-trace shape the
    jax profiler emits on TPU: a device process with named threads —
    "XLA Modules" (module-level jit_train_step events), "XLA Ops" (op
    events whose ``tf_op`` metadata carries named-scope paths + one
    unnamed HLO fusion), plus the aux umbrella lines a real trace
    carries ("TensorFlow Name Scope" spans named after the scopes
    themselves, the "Steps" line) which re-describe the same time and
    must NOT be counted — and a host track with data_wait/dispatch
    TraceAnnotations and runtime noise. Durations are microseconds.
    Per-step ms the parser must recover: binarize 1.0, binary_conv
    4.0, bn_act 1.5, kurtosis_loss 2.0, optimizer 0.5, unattributed
    1.0; step total 10.0; host data_wait 3.0 + dispatch 0.25."""
    import gzip
    import json
    import os

    events = [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 2, "name": "process_name",
         "args": {"name": "/host:CPU python"}},
        {"ph": "M", "pid": 1, "tid": 1, "name": "thread_name",
         "args": {"name": "XLA Modules"}},
        {"ph": "M", "pid": 1, "tid": 2, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        {"ph": "M", "pid": 1, "tid": 3, "name": "thread_name",
         "args": {"name": "TensorFlow Name Scope"}},
        {"ph": "M", "pid": 1, "tid": 4, "name": "thread_name",
         "args": {"name": "Steps"}},
    ]
    t = 0
    for step in range(n_steps):
        events.append({"ph": "X", "pid": 1, "tid": 1, "ts": t,
                       "dur": 10_000, "name": f"jit_train_step.{step}",
                       "args": {}})
        # aux umbrella lines: scope-named spans + the step marker —
        # the same device time AGAIN; counting them would double every
        # scoped category and add a phantom step of "unattributed"
        events.append({"ph": "X", "pid": 1, "tid": 3, "ts": t,
                       "dur": 1_000, "name": "binarize", "args": {}})
        events.append({"ph": "X", "pid": 1, "tid": 3, "ts": t + 1_000,
                       "dur": 2_000, "name": "kurtosis_loss", "args": {}})
        events.append({"ph": "X", "pid": 1, "tid": 4, "ts": t,
                       "dur": 10_000, "name": str(step), "args": {}})
        for dur_us, name, tf_op in (
            (1_000, "fusion.1",
             "jit(train_step)/binarize/sign"),
            (4_000, "convolution.2",
             "jit(train_step)/binary_conv/conv_general_dilated"),
            (1_500, "fusion.3",
             "jit(train_step)/bn_act/batch_norm"),
            (2_000, "reduce.4",
             "jit(train_step)/kurtosis_loss/reduce_sum"),
            (500, "fusion.5",
             "jit(train_step)/optimizer/add"),
            # an unnamed HLO op: no scope on its metadata path
            (1_000, "fusion.77", None),
        ):
            args = {"hlo_op": name}
            if tf_op:
                args["tf_op"] = tf_op
            events.append({"ph": "X", "pid": 1, "tid": 2, "ts": t,
                           "dur": dur_us, "name": name, "args": args})
        # host track: the loop's TraceAnnotations + runtime noise that
        # must NOT be attributed anywhere
        events.append({"ph": "X", "pid": 2, "tid": 9, "ts": t,
                       "dur": 3_000, "name": "data_wait", "args": {}})
        events.append({"ph": "X", "pid": 2, "tid": 9, "ts": t + 3_000,
                       "dur": 250, "name": "dispatch", "args": {}})
        events.append({"ph": "X", "pid": 2, "tid": 9, "ts": t,
                       "dur": 11_000, "name": "PjitFunction(train_step)",
                       "args": {}})
        t += 12_000
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with gzip.open(path, "wt") as f:
        json.dump({"traceEvents": events}, f)
    return path


def _write_fixture_run_dir(path):
    """A hand-built telemetry run dir (manifest + scalars + events)
    matching the schemas fit() writes — used by the summarize tests in
    test_obs.py and the CLI subprocess smoke in test_cli.py. Built from
    files alone on purpose: `summarize` must work on a run dir with no
    live process behind it."""
    import json
    import os

    os.makedirs(path, exist_ok=True)
    manifest = {
        "schema": 1,
        "created": "2026-08-01T00:00:00",
        "created_unix": 1785542400.0,
        "config_hash": "deadbeef00112233",
        "config": {"arch": "resnet20", "epochs": 3},
        "jax_version": "0.4.37",
        "jaxlib_version": "0.4.36",
        "backend": "tpu",
        "device_kind": "TPU v5e",
        "device_count": 8,
        "local_device_count": 8,
        "process_index": 0,
        "process_count": 1,
        "python": "3.11.0",
        "hostname": "fixture",
        "argv": ["cli"],
    }
    with open(os.path.join(path, "manifest.json"), "w") as f:
        json.dump(manifest, f)
    scalars = []
    for epoch in range(3):
        scalars += [
            {"tag": "Train Loss", "value": 2.0 - 0.5 * epoch, "step": epoch},
            {"tag": "Train loss_ce", "value": 1.9 - 0.5 * epoch, "step": epoch},
            {"tag": "Train loss_kurt", "value": 0.1, "step": epoch},
            {"tag": "Train grad_norm", "value": 2.0 / (1 + epoch), "step": epoch},
            {"tag": "Val Acc1", "value": 30.0 * (1 + epoch), "step": epoch},
            {"tag": "Probe flip layer1_0.conv1", "value": 1e-3 / (1 + epoch),
             "step": epoch},
            {"tag": "Probe kurt layer1_0.conv1", "value": 2.5 - 0.2 * epoch,
             "step": epoch},
        ]
    with open(os.path.join(path, "scalars.jsonl"), "w") as f:
        for s in scalars:
            f.write(json.dumps(s) + "\n")
    events = [
        {"t": 100.0, "kind": "run_start", "config_hash": "deadbeef00112233",
         "start_epoch": 0, "epochs": 3, "steps_per_epoch": 4,
         "probed_layers": ["layer1_0.conv1"]},
        {"t": 105.0, "kind": "compile", "seconds": 5.0},
    ]
    t = 105.0
    for epoch in range(3):
        for step in (0, 2, 3):
            t += 2.0
            events.append({
                "t": t, "kind": "train_interval", "epoch": epoch,
                "step": step, "steps": 2 if step == 2 else 1,
                "loss": 2.0 - 0.5 * epoch, "top1": 25.0, "img_per_s": 100.0,
                "grad_norm": 2.0 / (1 + epoch),
                "data_wait_s": 1.0, "dispatch_s": 0.5, "drain_s": 0.5,
                "interval_s": 2.0, "data_wait_share": 0.5,
                "flip_rate": {"layer1_0.conv1": 1e-3 / (1 + epoch)},
                "kurtosis": {"layer1_0.conv1": 2.5 - 0.2 * epoch},
            })
        t += 1.0
        events.append({"t": t, "kind": "epoch", "epoch": epoch,
                       "loss": 2.0 - 0.5 * epoch, "top1": 25.0,
                       "img_per_s_chip": 12.5, "wall_s": 7.0})
        t += 1.0
        events.append({"t": t, "kind": "eval", "epoch": epoch,
                       "acc1": 30.0 * (1 + epoch), "acc5": 80.0,
                       "loss": 1.5 - 0.4 * epoch})
    # a --profile-at capture window + HBM watermarks, backing the
    # summarize attribution section (trace file under <run>/profile)
    trace_dir = os.path.join(path, "profile")
    write_synthetic_trace(
        os.path.join(trace_dir, "fixture.trace.json.gz"), n_steps=5
    )
    # flops chosen so MFU vs the v5e 197 TFLOP/s peak over the 10
    # ms/step trace total is exactly 0.5
    events.append({"t": t + 0.5, "kind": "profile", "epoch": 2,
                   "start_step": 1, "steps": 5, "trace_dir": trace_dir,
                   "flops_per_step": 0.985e12})
    events.append({"t": 104.0, "kind": "memory", "phase": "post_compile",
                   "available": True,
                   "devices": [{"device": "0", "bytes_in_use": 2 * 2**30,
                                "peak_bytes_in_use": 6 * 2**30,
                                "bytes_limit": 16 * 2**30}],
                   "peak_bytes": 6 * 2**30, "limit_bytes": 16 * 2**30})
    events.append({"t": t + 0.6, "kind": "memory", "phase": "epoch",
                   "epoch": 2, "available": True, "devices": [],
                   "peak_bytes": 8 * 2**30, "limit_bytes": 16 * 2**30})
    events.append({"t": t + 1.0, "kind": "run_end", "best_acc1": 90.0,
                   "best_epoch": 2, "wall_s": t - 99.0})
    with open(os.path.join(path, "events.jsonl"), "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    return path


@pytest.fixture
def fixture_run_dir(tmp_path):
    """A synthetic run dir with one hooked layer, 3 epochs of scalars,
    and a full event timeline whose phase timing reads input-bound
    (data-wait share 0.5)."""
    return _write_fixture_run_dir(str(tmp_path / "run"))


# ---------------------------------------------------------------------------
# Fault-injection harness shared config (tests/test_faults.py +
# tests/test_pod_faults.py): ONE uninterrupted baseline fit per session,
# compared against every kill/resume/reshard result in both modules.
# ---------------------------------------------------------------------------

FAULT_EPOCHS = 2
FAULT_STEPS_PER_EPOCH = 4  # 128 synthetic examples / global batch 32

FAULT_BASE = dict(
    dataset="cifar10",
    synthetic=True,
    synthetic_train_size=128,
    synthetic_val_size=64,
    arch="resnet8_tiny",
    epochs=FAULT_EPOCHS,
    batch_size=32,
    lr=0.05,
    print_freq=1,
    seed=0,
    workers=2,
    # nontrivial schedule state at the resume point: EDE anneal on, and
    # the kurtosis gate flips open at epoch 1 — exactly the scalars a
    # wrong fast-forward would corrupt
    ede=True,
    kurtepoch=1,
    save_every_steps=2,
)


def fault_cfg(log_path, **kw):
    from bdbnn_tpu.configs.config import RunConfig

    return RunConfig(**{**FAULT_BASE, "log_path": str(log_path), **kw})


def fault_cli_args(log_path, **overrides):
    """The CLI surface of ``FAULT_BASE`` (subprocess + in-process
    main). ``overrides`` replace/add flag values by dest name."""
    base = {
        "--synthetic-train-size": "128",
        "--synthetic-val-size": "64",
        "-a": "resnet8_tiny",
        "--epochs": str(FAULT_EPOCHS),
        "-b": "32",
        "-lr": "0.05",
        "-p": "1",
        "--seed": "0",
        "-j": "2",
        "--kurtepoch": "1",
        "--save-every-steps": "2",
        "--log_path": str(log_path),
    }
    base.update(overrides)
    args = ["--synthetic", "--ede"]
    for flag, val in base.items():
        if val is None:
            continue
        args += [flag, val]
    return args


@pytest.fixture(scope="session")
def fault_baseline(tmp_path_factory):
    """ONE uninterrupted run at the fault-harness config; every
    kill/resume/reshard result (in-process, subprocess, or pod) is
    compared against it."""
    from bdbnn_tpu.train.loop import fit
    from bdbnn_tpu.utils.checkpoint import CKPT_NAME, load_variables

    import glob as _glob
    import os as _os

    root = tmp_path_factory.mktemp("fault_baseline")
    res = fit(fault_cfg(root))
    hits = _glob.glob(
        _os.path.join(str(root), "**", "events.jsonl"), recursive=True
    )
    run_dir = _os.path.dirname(sorted(hits)[-1])
    return {
        "res": res,
        "run_dir": run_dir,
        "params": load_variables(_os.path.join(run_dir, CKPT_NAME)),
    }


@pytest.fixture(scope="session")
def tiny_trained_run_dir(tmp_path_factory):
    """A REAL (smoke-scale) training run dir, produced once per session
    by an in-process fit() on resnet8_tiny + synthetic CIFAR: manifest,
    events (incl. eval accuracies), scalars, and a committed checkpoint
    + model_best. The serving tests export from it and check the
    artifact reproduces its recorded eval top-1; the CLI smoke drives
    export -> predict over it as real subprocesses."""
    from bdbnn_tpu.configs.config import RunConfig
    from bdbnn_tpu.obs.summarize import resolve_run_dir
    from bdbnn_tpu.train.loop import fit

    root = tmp_path_factory.mktemp("tiny_train")
    cfg = RunConfig(
        dataset="cifar10",
        arch="resnet8_tiny",
        synthetic=True,
        synthetic_train_size=64,
        synthetic_val_size=64,
        batch_size=16,
        epochs=1,
        lr=0.05,
        print_freq=2,
        log_path=str(root),
        seed=0,
    )
    fit(cfg)
    return resolve_run_dir(str(root))


@pytest.fixture(scope="session")
def exported_artifact(tiny_trained_run_dir, tmp_path_factory):
    """ONE export artifact per session over the real trained fixture
    run — shared by the serve-bench tests (test_serve.py) and the HTTP
    front-end e2e (test_http.py). Returns (artifact_dir, artifact)."""
    from bdbnn_tpu.serve.export import export_artifact

    out = str(tmp_path_factory.mktemp("artifact") / "art")
    artifact = export_artifact(tiny_trained_run_dir, out)
    return out, artifact
