"""Test harness: simulate an 8-device TPU-like mesh on CPU.

The reference validated distributed behavior only by running on the
authors' GPU cluster (SURVEY.md §4); here every distributed code path is
exercised on a virtual 8-device CPU mesh via
``--xla_force_host_platform_device_count`` — the JAX-native analogue of a
gloo/mock-NCCL DDP test.

Must run before the first ``import jax`` anywhere in the test session.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The env var alone is NOT enough in environments where a PJRT plugin's
# sitecustomize has already called jax.config.update("jax_platforms", ...)
# at interpreter start (config updates override the env var). Re-pin to
# CPU here, before any backend is initialized, so jax.devices() never
# dials a remote TPU from a unit test.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# The `fast` tier (`pytest -m fast`, <60s): pure-numerics oracle tests
# (binarization custom_vjps, kurtosis/KD losses, optimizer + EDE-schedule
# torch parity) plus the no-jax CLI flag-surface tests. The full suite
# stays the default.
_FAST_MODULES = {"test_binarize", "test_kurtosis", "test_kd", "test_cli"}
_FAST_CLASSES = {"TestOptimizerParity", "TestEDESchedule"}
# in fast modules but not fast: real subprocesses that import jax
_NOT_FAST_CLASSES = {"TestSummarizeSubcommand"}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.cls is not None and item.cls.__name__ in _NOT_FAST_CLASSES:
            continue
        if (
            item.module.__name__ in _FAST_MODULES
            or (item.cls is not None and item.cls.__name__ in _FAST_CLASSES)
        ):
            item.add_marker(pytest.mark.fast)


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def _write_fixture_run_dir(path):
    """A hand-built telemetry run dir (manifest + scalars + events)
    matching the schemas fit() writes — used by the summarize tests in
    test_obs.py and the CLI subprocess smoke in test_cli.py. Built from
    files alone on purpose: `summarize` must work on a run dir with no
    live process behind it."""
    import json
    import os

    os.makedirs(path, exist_ok=True)
    manifest = {
        "schema": 1,
        "created": "2026-08-01T00:00:00",
        "created_unix": 1785542400.0,
        "config_hash": "deadbeef00112233",
        "config": {"arch": "resnet20", "epochs": 3},
        "jax_version": "0.4.37",
        "jaxlib_version": "0.4.36",
        "backend": "cpu",
        "device_kind": "cpu",
        "device_count": 8,
        "local_device_count": 8,
        "process_index": 0,
        "process_count": 1,
        "python": "3.11.0",
        "hostname": "fixture",
        "argv": ["cli"],
    }
    with open(os.path.join(path, "manifest.json"), "w") as f:
        json.dump(manifest, f)
    scalars = []
    for epoch in range(3):
        scalars += [
            {"tag": "Train Loss", "value": 2.0 - 0.5 * epoch, "step": epoch},
            {"tag": "Train loss_ce", "value": 1.9 - 0.5 * epoch, "step": epoch},
            {"tag": "Train loss_kurt", "value": 0.1, "step": epoch},
            {"tag": "Train grad_norm", "value": 2.0 / (1 + epoch), "step": epoch},
            {"tag": "Val Acc1", "value": 30.0 * (1 + epoch), "step": epoch},
            {"tag": "Probe flip layer1_0.conv1", "value": 1e-3 / (1 + epoch),
             "step": epoch},
            {"tag": "Probe kurt layer1_0.conv1", "value": 2.5 - 0.2 * epoch,
             "step": epoch},
        ]
    with open(os.path.join(path, "scalars.jsonl"), "w") as f:
        for s in scalars:
            f.write(json.dumps(s) + "\n")
    events = [
        {"t": 100.0, "kind": "run_start", "config_hash": "deadbeef00112233",
         "start_epoch": 0, "epochs": 3, "steps_per_epoch": 4,
         "probed_layers": ["layer1_0.conv1"]},
        {"t": 105.0, "kind": "compile", "seconds": 5.0},
    ]
    t = 105.0
    for epoch in range(3):
        for step in (0, 2, 3):
            t += 2.0
            events.append({
                "t": t, "kind": "train_interval", "epoch": epoch,
                "step": step, "steps": 2 if step == 2 else 1,
                "loss": 2.0 - 0.5 * epoch, "top1": 25.0, "img_per_s": 100.0,
                "grad_norm": 2.0 / (1 + epoch),
                "data_wait_s": 1.0, "dispatch_s": 0.5, "drain_s": 0.5,
                "interval_s": 2.0, "data_wait_share": 0.5,
                "flip_rate": {"layer1_0.conv1": 1e-3 / (1 + epoch)},
                "kurtosis": {"layer1_0.conv1": 2.5 - 0.2 * epoch},
            })
        t += 1.0
        events.append({"t": t, "kind": "epoch", "epoch": epoch,
                       "loss": 2.0 - 0.5 * epoch, "top1": 25.0,
                       "img_per_s_chip": 12.5, "wall_s": 7.0})
        t += 1.0
        events.append({"t": t, "kind": "eval", "epoch": epoch,
                       "acc1": 30.0 * (1 + epoch), "acc5": 80.0,
                       "loss": 1.5 - 0.4 * epoch})
    events.append({"t": t + 1.0, "kind": "run_end", "best_acc1": 90.0,
                   "best_epoch": 2, "wall_s": t - 99.0})
    with open(os.path.join(path, "events.jsonl"), "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    return path


@pytest.fixture
def fixture_run_dir(tmp_path):
    """A synthetic run dir with one hooked layer, 3 epochs of scalars,
    and a full event timeline whose phase timing reads input-bound
    (data-wait share 0.5)."""
    return _write_fixture_run_dir(str(tmp_path / "run"))
