"""On-chip profile capture → profiles/r05/PROFILE_r05.json (VERDICT r4
next-round #2: show the convert/reduce breakdown shift from the fused
single-pass kurtosis moments + native maxpool padding, target ≥50%
device MFU or a written analysis of the residual).

Reuses bench.py's compiled flagship step (BASELINE config 3 workload:
binary ResNet-18 react @ 224², bf16, batch 128, fwd+bwd+Adam+19-layer
kurtosis) and its fenced measurement. Trace parsing lives in the
shared :mod:`bdbnn_tpu.obs.trace` module (this script's one-off
``_trace_breakdown`` was promoted there): the legacy raw-HLO grouping
keeps the output directly comparable with profiles/r04/PROFILE_r04.json,
and the semantic span attribution (binarize / binary_conv / bn_act /
kurtosis_loss / optimizer / ...) rides along under
``device_attribution_ms_per_step``.

Run on the real chip (dies fast if the tunnel is down):
    python profile_r05.py [--batch 128] [--iters 20]
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import shutil
import sys

import bench
from bdbnn_tpu.obs.trace import attribute_trace, hlo_breakdown


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--out-dir", default="profiles/r05")
    args = ap.parse_args()

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    dev = jax.devices()[0]
    print(f"[profile] device: {dev.device_kind} ({dev.platform})",
          file=sys.stderr)

    compiled, state, batch_xy, tk, gate, flops = bench._compile_step(
        "bfloat16", args.batch
    )
    host_rate, state = bench._measure_compiled(
        compiled, state, batch_xy, tk, gate, args.batch, args.iters
    )

    trace_dir = os.path.join(args.out_dir, "trace")
    dev_ms, trace_path, state = bench._profile_device_ms(
        compiled, state, batch_xy, tk, gate, args.batch, trace_dir
    )
    peak = bench.BF16_PEAK_TFLOPS.get(dev.device_kind)
    if trace_path:
        breakdown, step_total_ms = hlo_breakdown(
            trace_path, bench.PROFILE_TRACE_STEPS
        )
        attribution = attribute_trace(
            trace_path,
            bench.PROFILE_TRACE_STEPS,
            flops_per_step=flops or None,
            peak_tflops=peak,
        )
    else:
        breakdown, step_total_ms, attribution = {}, None, None

    dev_rate = args.batch / (dev_ms / 1e3) if dev_ms else None
    out = {
        "what": (
            "jax.profiler trace of 5 steps of the flagship bench "
            "workload after the r5 perf changes (fused single-pass "
            "kurtosis raw moments; native reduce_window maxpool "
            "padding): full BD-BNN train step (fwd + bwd + Adam + "
            "19-layer kurtosis), binary ResNet-18 react @ 224x224, "
            f"bf16, batch {args.batch}, conv_impl=dot"
        ),
        "captured": datetime.datetime.now(datetime.timezone.utc).strftime(
            "%Y-%m-%dT%H:%MZ"
        )
        + f" on {dev.device_kind} ({dev.platform})",
        "device_kind": dev.device_kind,
        "bf16_peak_tflops": peak,
        "trace_file": os.path.basename(trace_path) if trace_path else None,
        "flops_per_step_xla_cost_analysis": flops,
        "gflops_per_image": round(flops / args.batch / 1e9, 2) if flops else None,
        "device_ms_per_step_median": round(dev_ms, 2) if dev_ms else None,
        "device_images_per_sec": round(dev_rate) if dev_rate else None,
        "device_mfu": (
            round(flops / (dev_ms / 1e3) / (peak * 1e12), 3)
            if dev_ms and flops and peak
            else None
        ),
        "host_fenced_median_img_per_sec": round(host_rate),
        "host_fenced_ms_per_step": round(args.batch / host_rate * 1e3, 2),
        "host_fenced_mfu": (
            round(flops * host_rate / args.batch / (peak * 1e12), 3)
            if flops and peak
            else None
        ),
        "device_time_breakdown_ms_per_step": breakdown,
        "device_attribution_ms_per_step": (
            attribution["categories_ms_per_step"] if attribution else None
        ),
        "device_attribution_mfu": (
            attribution["mfu"] if attribution else None
        ),
        "device_track_total_ms_per_step": (
            round(step_total_ms, 2) if step_total_ms else None
        ),
        "r04_comparison": {
            "source": "profiles/r04/PROFILE_r04.json",
            "device_ms_per_step_median": 16.99,
            "device_mfu": 0.383,
            "convert_reduce_fusion_ms": 5.44,
            "pad_plus_select_and_scatter_ms": 1.76,
        },
    }
    os.makedirs(args.out_dir, exist_ok=True)
    if trace_path:
        shutil.copy(
            trace_path, os.path.join(args.out_dir, "train_step_trace.json.gz")
        )
    out_path = os.path.join(args.out_dir, "PROFILE_r05.json")
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    print(f"[profile] wrote {out_path}", file=sys.stderr)


if __name__ == "__main__":
    main()
