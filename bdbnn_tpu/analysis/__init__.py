"""Project-native static analysis: the ``check`` CLI's engine.

Generic linters know nothing about this repo's load-bearing
invariants: that 54 lock sites in ``serve/pool.py`` guard specific
attributes, that the jitted forward must stay trace-pure so packed
1-bit inference stays bitwise-exact, that every ``EventWriter.emit``
kind is registered, or that ``compare``'s serve-metric namespace must
agree with the verdict producers and the golden fixture. Each of those
contracts was previously enforced by reviewer vigilance — and each has
a PR where vigilance failed (the restart-clobbers-SHIFTING race, the
shed-reason misattribution, verdict-key drift). This package enforces
them mechanically, as a tier-1 gate:

- :mod:`~bdbnn_tpu.analysis.core` — the shared framework: Finding
  records (``file:line:checker-id:message``), AST/file discovery, the
  suppression baseline (sorted, deduplicated, every entry justified —
  a stale suppression is itself a finding), and the deterministic
  strict-JSON report.
- :mod:`~bdbnn_tpu.analysis.lockcheck` — ``lock-discipline``:
  ``# guarded-by: <lock>`` annotated attributes must only be written /
  read-modify-written / mutated under ``with self.<lock>``.
- :mod:`~bdbnn_tpu.analysis.jitpure` — ``jit-purity``: functions
  reachable from jit/AOT call sites must not call host-sync or
  nondeterminism primitives.
- :mod:`~bdbnn_tpu.analysis.eventschema` — ``event-schema``: the
  ``tests/test_events_schema.py`` AST scan promoted into the package.
- :mod:`~bdbnn_tpu.analysis.verdictcheck` — ``verdict-coherence``:
  ``obs/compare.py``'s serve-metric flattener vs METRIC_SPECS vs the
  golden fixture vs the verdict-producing sites.

Stdlib-only (the obs rule): running the analyzer never initializes a
JAX backend, so it is cheap enough to run on every CI pass and from
``python -m bdbnn_tpu.cli check`` on a laptop.
"""

from bdbnn_tpu.analysis.core import (
    BASELINE_NAME,
    CHECKER_IDS,
    Finding,
    load_baseline,
    render_report,
    run_check,
)

__all__ = [
    "BASELINE_NAME",
    "CHECKER_IDS",
    "Finding",
    "load_baseline",
    "render_report",
    "run_check",
]
