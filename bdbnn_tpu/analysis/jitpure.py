"""``jit-purity``: jitted code must stay trace-pure and deterministic.

The packed-inference exactness contract — bitwise-equal 1-bit forward,
the zero-tolerance shadow logit-drift probe, the canary drift gate —
all assume that what ``jax.jit`` traced is a pure function of its
inputs. A host-sync or nondeterminism primitive inside a traced
function breaks that silently: ``time.*`` / ``random.*`` /
``np.random.*`` calls bake one trace-time value into the compiled
program (or retrace), ``.item()`` / ``device_get`` force a host sync
mid-step, and ``print``/``logging`` fire at trace time only — the
classic "my debug print ran once" confusion.

The checker builds a conservative name-based call graph over the **jit
domain** (``nn/``, ``models/``, ``losses/``, ``train/step.py``,
``serve/engine.py``, ``obs/probes.py``, ``parallel/mesh.py``):

- **roots** — arguments of ``jax.jit(...)`` / ``pjit(...)`` calls and
  ``@jit``-style decorators anywhere in the scan set (``jit(f)`` marks
  ``f``; ``jit(make_step(...))`` marks the factory ``make_step``,
  whose body contains the traced closure), the ``__call__``/``setup``
  methods of flax ``nn.Module`` classes (always traced), and —
  higher-order wrappers — when a function jits one of its OWN
  parameters (``jit_train_step(step_fn)``), every call to that wrapper
  marks its argument as a root.
- **closure** — from each root, every call by name that resolves to a
  function defined in the jit domain is reachable (over-approximate on
  purpose: a false edge costs a spurious look, a missed edge costs a
  missed host sync).
- **ban list** — inside reachable functions: ``time.*``,
  ``random.*``, ``np.random.*`` / ``numpy.random.*``, ``.item()``,
  ``jax.device_get`` / ``device_get``, ``print`` and ``logging.*``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from bdbnn_tpu.analysis.core import Finding, relpath

CHECKER_ID = "jit-purity"

# default jit domain, relative to the repo root (prefix match)
JIT_DOMAIN = (
    "bdbnn_tpu/nn/", "bdbnn_tpu/models/", "bdbnn_tpu/losses/",
    "bdbnn_tpu/train/step.py", "bdbnn_tpu/serve/engine.py",
    "bdbnn_tpu/obs/probes.py", "bdbnn_tpu/parallel/mesh.py",
)

_JIT_NAMES = {"jit", "pjit"}


def _is_jit_func(func: ast.expr) -> bool:
    """``jit`` / ``jax.jit`` / ``jax.experimental.pjit.pjit`` ..."""
    if isinstance(func, ast.Name):
        return func.id in _JIT_NAMES
    if isinstance(func, ast.Attribute):
        return func.attr in _JIT_NAMES
    return False


def _called_name(func: ast.expr) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _root_from_arg(arg: ast.expr) -> Optional[str]:
    """The function name a jit argument marks reachable."""
    if isinstance(arg, ast.Name):
        return arg.id
    if isinstance(arg, ast.Attribute):
        return arg.attr  # jax.jit(self._apply) -> "_apply"
    if isinstance(arg, ast.Call):
        return _called_name(arg.func)  # jit(make_step(...)) -> factory
    return None


def _banned(node: ast.Call) -> Optional[str]:
    """The ban-list label for a call, or None."""
    func = node.func
    if isinstance(func, ast.Name):
        if func.id == "print":
            return "print()"
        if func.id == "device_get":
            return "device_get()"
        return None
    if not isinstance(func, ast.Attribute):
        return None
    if func.attr == "item" and not node.args and not node.keywords:
        return ".item() host sync"
    base = func.value
    if isinstance(base, ast.Name):
        if base.id == "time":
            return f"time.{func.attr}()"
        if base.id == "random":
            return f"random.{func.attr}()"
        if base.id == "logging":
            return f"logging.{func.attr}()"
        if base.id == "jax" and func.attr == "device_get":
            return "jax.device_get()"
    if (
        isinstance(base, ast.Attribute)
        and base.attr == "random"
        and isinstance(base.value, ast.Name)
        and base.value.id in ("np", "numpy")
    ):
        return f"{base.value.id}.random.{func.attr}()"
    return None


class _Module:
    def __init__(self, rel: str, tree: ast.Module):
        self.rel = rel
        # name -> function nodes (module-level defs AND methods; name
        # collisions keep every candidate — over-approximation)
        self.functions: Dict[str, List[ast.AST]] = {}


def _is_flax_module(cls: ast.ClassDef) -> bool:
    for base in cls.bases:
        name = base.attr if isinstance(base, ast.Attribute) else (
            base.id if isinstance(base, ast.Name) else ""
        )
        if "Module" in name:
            return True
    return False


def analyze_jit_purity(
    root: str,
    files: List[str],
    *,
    domain: Tuple[str, ...] = JIT_DOMAIN,
) -> Tuple[List[Finding], Set[str], Set[str]]:
    """``(findings, roots, reachable)`` — the full analysis. The roots
    and reachable sets are exposed so the tier-1 floor test can pin
    that the checker actually traversed the jit domain (a refactor
    that silently empties the root set must fail loudly, not pass
    vacuously)."""
    findings: List[Finding] = []
    index: Dict[str, List[Tuple[_Module, ast.AST]]] = {}
    roots: Set[str] = set()
    # wrapper name -> positional index of the parameter it jits
    wrappers: Dict[str, int] = {}

    rel_of = {p: relpath(p, root) for p in files}
    # fixture-corpus mode: a scan set with no package files (the
    # seeded-bad snippets under tests/fixtures/analysis/) is ALL domain
    any_pkg = any(r.startswith("bdbnn_tpu/") for r in rel_of.values())
    if any_pkg:
        in_domain = {
            p for p in files
            if any(
                rel_of[p] == d or rel_of[p].startswith(d)
                for d in domain
            )
        }
    else:
        in_domain = set(files)

    parsed: Dict[str, ast.Module] = {}
    for path in files:
        try:
            with open(path) as f:
                src = f.read()
        except OSError:
            continue
        if "jit" not in src and path not in in_domain:
            continue
        try:
            parsed[path] = ast.parse(src, filename=path)
        except SyntaxError:
            continue  # lock checker reports unparseable files

    # pass 1: function index over the jit domain + flax-module roots
    for path, tree in parsed.items():
        if path not in in_domain:
            continue
        mod = _Module(rel_of[path], tree)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                mod.functions.setdefault(node.name, []).append(node)
            elif isinstance(node, ast.ClassDef) and _is_flax_module(node):
                for item in node.body:
                    if isinstance(
                        item, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ) and item.name in ("__call__", "setup"):
                        roots.add(item.name)
        for name, nodes in mod.functions.items():
            index.setdefault(name, []).extend(
                (mod, n) for n in nodes
            )

    # pass 2: jit roots + higher-order jit wrappers, over EVERY file
    for path, tree in parsed.items():
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    d = dec.func if isinstance(dec, ast.Call) else dec
                    if _is_jit_func(d):
                        roots.add(node.name)
                    elif (
                        isinstance(dec, ast.Call)
                        and _called_name(dec.func) == "partial"
                        and dec.args
                        and _is_jit_func(dec.args[0])
                    ):
                        roots.add(node.name)
                # a function that jits one of its own parameters is a
                # jit WRAPPER: calls to it mark their argument
                params = [a.arg for a in node.args.args]
                for sub in ast.walk(node):
                    if (
                        isinstance(sub, ast.Call)
                        and _is_jit_func(sub.func)
                        and sub.args
                        and isinstance(sub.args[0], ast.Name)
                        and sub.args[0].id in params
                    ):
                        wrappers[node.name] = params.index(sub.args[0].id)
            elif isinstance(node, ast.Call) and _is_jit_func(node.func):
                if node.args:
                    name = _root_from_arg(node.args[0])
                    if name:
                        roots.add(name)
    for path, tree in parsed.items():
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = _called_name(node.func)
            if name in wrappers and len(node.args) > wrappers[name]:
                arg_root = _root_from_arg(node.args[wrappers[name]])
                if arg_root:
                    roots.add(arg_root)

    # pass 3: closure over the name-based call graph
    reachable: Set[str] = set()
    frontier = [r for r in roots if r in index]
    while frontier:
        name = frontier.pop()
        if name in reachable:
            continue
        reachable.add(name)
        for _mod, fn in index[name]:
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Call):
                    callee = _called_name(sub.func)
                    if callee and callee in index and (
                        callee not in reachable
                    ):
                        frontier.append(callee)

    # pass 4: ban list inside every reachable function
    for name in sorted(reachable):
        for mod, fn in index[name]:
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Call):
                    label = _banned(sub)
                    if label:
                        findings.append(Finding(
                            mod.rel, sub.lineno, CHECKER_ID,
                            f"{label} inside jit-reachable "
                            f"function {name!r} — host sync / "
                            "nondeterminism in traced code",
                        ))
    return sorted(set(findings)), roots, reachable


def check_jit_purity(
    root: str,
    files: List[str],
    *,
    domain: Tuple[str, ...] = JIT_DOMAIN,
) -> List[Finding]:
    findings, _roots, _reachable = analyze_jit_purity(
        root, files, domain=domain
    )
    return findings


__all__ = [
    "CHECKER_ID", "JIT_DOMAIN", "analyze_jit_purity", "check_jit_purity",
]
