"""``verdict-coherence``: compare's metric namespaces cannot drift.

The literal-drift class PR 9 fixed ad hoc: ``obs/compare.py`` judges
each verdict family through string keys that must agree across FOUR
places — the ``METRIC_SPECS`` judgment table, the per-family
flattener (``_serve_metrics`` for serving SLO verdicts,
``_perf_metrics`` for roofline perf verdicts) that produces those
keys from a verdict, the verdict-PRODUCING sites that emit the
source fields the flattener reads, and the checked-in golden fixture
(``tests/fixtures/compare/expected_verdict.json``) that pins the
metric skeleton. A key renamed in any one of them silently turns a
CI gate into a no-op (the metric lands ``None`` on both sides and
``_judge`` skips it). For every ``(flattener, prefix, producers)``
row in ``FLATTENERS`` this checker cross-references all four:

1. every ``<prefix>*`` metric in ``METRIC_SPECS`` is produced by the
   flattener;
2. every key the flattener produces is judged in ``METRIC_SPECS``;
3. every produced ``<prefix>*`` key appears in the golden fixture's
   metric skeleton (when the fixture exists under the root);
4. every top-level verdict field the flattener reads
   (``verdict.get("...")``) appears as a string literal in at least
   one of that family's verdict-producing sites (when those files
   exist under the root).

All static: the flattener's produced-key set is recovered from its
AST — constant subscripts, the ``(field, name)`` table loops
(``_SERVE_METRIC_FIELDS`` / ``_PERF_METRIC_FIELDS``), and the
``f"serve_p99_ms_p{p}"`` per-priority loop over
``range(_SERVE_PRIORITY_CLASSES)`` are all evaluated from literals.
"""

from __future__ import annotations

import ast
import json
import os
from typing import Any, Dict, List, Optional, Set, Tuple

from bdbnn_tpu.analysis.core import Finding, relpath

CHECKER_ID = "verdict-coherence"

FLATTENER = "_serve_metrics"
SPECS_NAME = "METRIC_SPECS"
GOLDEN_FIXTURE = "tests/fixtures/compare/expected_verdict.json"
PRODUCER_FILES = (
    "bdbnn_tpu/serve/loadgen.py",
    "bdbnn_tpu/serve/http.py",
    # the fleet router's verdict assembly: the v6 fleet block and the
    # v7 fleet_attribution block (whose serve_fleet_* gates
    # _serve_metrics reads) are produced here
    "bdbnn_tpu/serve/fleet.py",
    # the capacity observatory: the v8 capacity block's flat gates
    # (burn_rate_max / headroom_rps / demand_shed_ratio_max read by
    # _serve_metrics) are assembled here
    "bdbnn_tpu/obs/capacity.py",
)

# every judged verdict family: (flattener function in compare.py,
# METRIC_SPECS key prefix owned by that family, producer files whose
# literals must cover every verdict field the flattener reads)
FLATTENERS = (
    (FLATTENER, "serve_", PRODUCER_FILES),
    ("_perf_metrics", "perf_", ("bdbnn_tpu/obs/roofline.py",)),
)


def _module_literal(tree: ast.Module, name: str) -> Optional[Any]:
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == name
            for t in node.targets
        ):
            try:
                return ast.literal_eval(node.value)
            except (ValueError, SyntaxError):
                return None
        if (
            isinstance(node, ast.AnnAssign)
            and isinstance(node.target, ast.Name)
            and node.target.id == name
            and node.value is not None
        ):
            try:
                return ast.literal_eval(node.value)
            except (ValueError, SyntaxError):
                return None
    return None


def _expand_joined(
    key: ast.JoinedStr, fn: ast.FunctionDef, tree: ast.Module
) -> List[str]:
    """``out[f"serve_p99_ms_p{p}"]`` inside ``for p in range(CONST)``:
    expand the pattern over the loop range. Unexpandable patterns
    return [] (and sub-check 2 will surface the mismatch loudly via
    the METRIC_SPECS side)."""
    if len(key.values) != 2:
        return []
    prefix, var = key.values
    if not (
        isinstance(prefix, ast.Constant)
        and isinstance(prefix.value, str)
        and isinstance(var, ast.FormattedValue)
        and isinstance(var.value, ast.Name)
    ):
        return []
    loop_var = var.value.id
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.For)
            and isinstance(node.target, ast.Name)
            and node.target.id == loop_var
            and isinstance(node.iter, ast.Call)
            and isinstance(node.iter.func, ast.Name)
            and node.iter.func.id == "range"
            and len(node.iter.args) == 1
        ):
            bound_node = node.iter.args[0]
            bound: Optional[int] = None
            if isinstance(bound_node, ast.Constant):
                bound = bound_node.value
            elif isinstance(bound_node, ast.Name):
                val = _module_literal(tree, bound_node.id)
                bound = val if isinstance(val, int) else None
            if isinstance(bound, int):
                return [f"{prefix.value}{i}" for i in range(bound)]
    return []


def _produced_keys(
    fn: ast.FunctionDef, tree: ast.Module
) -> Tuple[Set[str], Set[str]]:
    """``(produced keys, table source fields)``: every key
    ``_serve_metrics`` assigns into its ``out`` dict, plus the verdict
    fields read through the ``(field, name)`` table loop (whose
    ``verdict.get(field)`` is variable, not a literal)."""
    keys: Set[str] = set()
    table_fields: Set[str] = set()
    table_loops: Dict[str, str] = {}  # loop key var -> table name
    for node in ast.walk(fn):
        # for field, name in _SERVE_METRIC_FIELDS: out[name] = ...
        if (
            isinstance(node, ast.For)
            and isinstance(node.target, ast.Tuple)
            and len(node.target.elts) == 2
            and all(isinstance(e, ast.Name) for e in node.target.elts)
            and isinstance(node.iter, ast.Name)
        ):
            table_loops[node.target.elts[1].id] = node.iter.id
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if not (
                isinstance(t, ast.Subscript)
                and isinstance(t.value, ast.Name)
                and t.value.id == "out"
            ):
                continue
            key = t.slice
            if isinstance(key, ast.Constant) and isinstance(
                key.value, str
            ):
                keys.add(key.value)
            elif isinstance(key, ast.JoinedStr):
                keys.update(_expand_joined(key, fn, tree))
            elif isinstance(key, ast.Name) and key.id in table_loops:
                table = _module_literal(tree, table_loops[key.id])
                if isinstance(table, (tuple, list)):
                    for row in table:
                        if (
                            isinstance(row, (tuple, list))
                            and len(row) == 2
                        ):
                            table_fields.add(str(row[0]))
                            keys.add(str(row[1]))
    return keys, table_fields


def _source_fields(fn: ast.FunctionDef) -> Set[str]:
    """Top-level verdict fields the flattener reads:
    ``verdict.get("...")`` literals."""
    fields: Set[str] = set()
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "get"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "verdict"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            fields.add(node.args[0].value)
    return fields


def _json_keys(obj: Any, out: Set[str]) -> None:
    if isinstance(obj, dict):
        for k, v in obj.items():
            out.add(str(k))
            _json_keys(v, out)
    elif isinstance(obj, list):
        for v in obj:
            _json_keys(v, out)


def _string_literals(tree: ast.Module) -> Set[str]:
    return {
        n.value
        for n in ast.walk(tree)
        if isinstance(n, ast.Constant) and isinstance(n.value, str)
    }


def check_verdict_coherence(
    root: str, files: List[str]
) -> List[Finding]:
    findings: List[Finding] = []
    for path in files:
        try:
            with open(path) as f:
                src = f.read()
        except OSError:
            continue
        if SPECS_NAME not in src or not any(
            name in src for name, _, _ in FLATTENERS
        ):
            continue
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError:
            continue  # reported by lock-discipline
        specs = _module_literal(tree, SPECS_NAME)
        if not isinstance(specs, (tuple, list)):
            continue
        rel = relpath(path, root)
        golden = os.path.join(root, GOLDEN_FIXTURE)
        golden_keys: Set[str] = set()
        golden_ok = os.path.isfile(golden)
        if golden_ok:
            try:
                with open(golden) as f:
                    doc = json.load(f)
                _json_keys(doc, golden_keys)
            except (OSError, ValueError):
                golden_ok = False
                findings.append(Finding(
                    GOLDEN_FIXTURE, 1, CHECKER_ID,
                    "golden fixture is unreadable / not valid JSON",
                ))
        for flattener, prefix, producer_files in FLATTENERS:
            fn = next(
                (
                    n for n in tree.body
                    if isinstance(n, ast.FunctionDef)
                    and n.name == flattener
                ),
                None,
            )
            if fn is None:
                continue
            judged = {
                str(row[0])
                for row in specs
                if isinstance(row, (tuple, list)) and row
                and str(row[0]).startswith(prefix)
            }
            produced, table_fields = _produced_keys(fn, tree)
            produced_own = {k for k in produced if k.startswith(prefix)}
            for name in sorted(judged - produced_own):
                findings.append(Finding(
                    rel, fn.lineno, CHECKER_ID,
                    f"{SPECS_NAME} judges {name!r} but {flattener} "
                    "never produces it (the gate silently skips)",
                ))
            for name in sorted(produced_own - judged):
                findings.append(Finding(
                    rel, fn.lineno, CHECKER_ID,
                    f"{flattener} produces {name!r} but {SPECS_NAME} "
                    "never judges it (unjudged verdict metric)",
                ))
            # golden-fixture skeleton (when checked in under this root)
            if golden_ok and golden_keys:
                for name in sorted(judged & produced_own):
                    if name not in golden_keys:
                        findings.append(Finding(
                            GOLDEN_FIXTURE, 1, CHECKER_ID,
                            f"metric {name!r} missing from the "
                            "golden verdict fixture's metric skeleton",
                        ))
            # verdict-producing sites carry every source field literal
            producers: List[Tuple[str, Set[str]]] = []
            for prod_rel in producer_files:
                p = os.path.join(root, prod_rel)
                if not os.path.isfile(p):
                    continue
                try:
                    with open(p) as f:
                        ptree = ast.parse(f.read(), filename=p)
                except (OSError, SyntaxError):
                    continue
                producers.append((prod_rel, _string_literals(ptree)))
            if producers:
                all_literals: Set[str] = set()
                for _, lits in producers:
                    all_literals |= lits
                for field in sorted(_source_fields(fn) | table_fields):
                    if field not in all_literals:
                        findings.append(Finding(
                            rel, fn.lineno, CHECKER_ID,
                            f"{flattener} reads verdict field "
                            f"{field!r} but no verdict-producing site "
                            f"({', '.join(p for p, _ in producers)}) "
                            "mentions that literal",
                        ))
    return sorted(findings)


__all__ = [
    "CHECKER_ID",
    "FLATTENERS",
    "GOLDEN_FIXTURE",
    "PRODUCER_FILES",
    "check_verdict_coherence",
]
