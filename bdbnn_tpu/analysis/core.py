"""Checker framework: findings, file discovery, baseline, report.

A **checker** is a function ``check(root, files) -> List[Finding]``
registered in :data:`CHECKERS`. ``root`` is the repo root the paths
are rendered relative to; ``files`` is the explicit ``.py`` scan set
(absolute paths). Checkers are pure AST/file analysis — no imports of
the analyzed code, no JAX — so they run identically on the live tree,
on a doctored temp copy (the CLI smoke test) and on the seeded-bad
fixture corpus under ``tests/fixtures/analysis/``.

A **finding** renders as ``file:line:checker-id:message`` — one line,
stable and diffable. The **suppression baseline**
(``analysis-baseline.txt`` at the repo root) holds records of findings
that are understood and accepted; the framework enforces the
baseline's own hygiene (checker id ``baseline``):

- every entry must be justified — immediately preceded by at least one
  ``# why: ...`` comment line;
- entries must be sorted and deduplicated;
- a **stale** entry (no current finding matches it) is itself a
  finding: suppressions must be garbage-collected with the code they
  excuse.

Suppression matching is on ``(file, checker-id, message)`` — the line
number in the record is **advisory** (it documents where the finding
sat when baselined): an edit above the site shifts every finding's
line, and a baseline that breaks on unrelated-line churn would be
resynced by hand on almost every PR. One entry consumes AT MOST ONE
matching finding (the one closest to the advisory line): the baseline
excuses one understood occurrence, so a brand-NEW site producing the
same message stays open and fails the gate.

The report dict is deterministic (no clocks, no absolute paths) and
strict-JSON after ``obs.events.jsonsafe`` — the same discipline every
other machine-readable artifact in this repo follows.
"""

from __future__ import annotations

import dataclasses
import glob
import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

BASELINE_NAME = "analysis-baseline.txt"

# checker id every baseline-hygiene finding carries
BASELINE_CHECKER = "baseline"


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One defect record: ``file:line:checker-id:message``. ``file``
    is repo-root-relative, posix-style, so records are stable across
    machines and usable as baseline entries verbatim."""

    file: str
    line: int
    checker: str
    message: str

    @property
    def record(self) -> str:
        return f"{self.file}:{self.line}:{self.checker}:{self.message}"

    @property
    def match_key(self) -> Tuple[str, str, str]:
        """Suppression identity: the line number is advisory."""
        return (self.file, self.checker, self.message)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "file": self.file,
            "line": self.line,
            "checker": self.checker,
            "message": self.message,
            "record": self.record,
        }


def relpath(path: str, root: str) -> str:
    return os.path.relpath(os.path.abspath(path), os.path.abspath(root)
                           ).replace(os.sep, "/")


def discover_files(root: str) -> List[str]:
    """The default scan set: every ``.py`` under the package plus the
    root-level harnesses that share the event channel (the
    ``tests/test_events_schema.py`` precedent)."""
    out = sorted(
        glob.glob(
            os.path.join(root, "bdbnn_tpu", "**", "*.py"), recursive=True
        )
    )
    for extra in ("bench.py", "profile_r05.py"):
        p = os.path.join(root, extra)
        if os.path.isfile(p):
            out.append(p)
    return out


# -- baseline ----------------------------------------------------------------


def load_baseline(path: str) -> Tuple[List[Dict[str, Any]], List[Finding]]:
    """Parse a suppression baseline. Returns ``(entries, problems)``:
    ``entries`` are ``{record, line, justified}`` dicts; ``problems``
    are baseline-hygiene findings (unjustified / duplicate / unsorted
    entries). Staleness is judged by the caller, which knows the
    current finding set. A missing file is an empty baseline."""
    entries: List[Dict[str, Any]] = []
    problems: List[Finding] = []
    if not os.path.isfile(path):
        return entries, problems
    name = os.path.basename(path)
    pending_why = False
    with open(path) as f:
        for lineno, raw in enumerate(f, start=1):
            line = raw.strip()
            if not line:
                pending_why = False
                continue
            if line.startswith("#"):
                if line[1:].strip().lower().startswith("why:"):
                    pending_why = True
                continue
            entries.append(
                {"record": line, "line": lineno, "justified": pending_why}
            )
            pending_why = False
    def natural_key(record: str):
        # the analyzer's own report order: (file, NUMERIC line, rest) —
        # so records pasted from `check` output in order are sorted
        parts = record.split(":", 2)
        if len(parts) == 3 and parts[1].isdigit():
            return (parts[0], int(parts[1]), parts[2])
        return (record, 0, "")

    def dedup_key(record: str):
        # two entries differing only in the advisory line number are
        # the same suppression
        parts = record.split(":", 3)
        return (parts[0], parts[2], parts[3]) if len(parts) == 4 else record

    seen = set()
    prev = None
    for e in entries:
        if not e["justified"]:
            problems.append(Finding(
                name, e["line"], BASELINE_CHECKER,
                "suppression has no '# why:' justification comment "
                f"({e['record']})",
            ))
        if dedup_key(e["record"]) in seen:
            problems.append(Finding(
                name, e["line"], BASELINE_CHECKER,
                f"duplicate suppression ({e['record']})",
            ))
        seen.add(dedup_key(e["record"]))
        if prev is not None and natural_key(e["record"]) < natural_key(
            prev
        ):
            problems.append(Finding(
                name, e["line"], BASELINE_CHECKER,
                f"baseline not sorted ({e['record']} after {prev})",
            ))
        prev = e["record"]
    return entries, problems


# -- registry / driver -------------------------------------------------------


def _checkers() -> Dict[str, Callable[[str, List[str]], List[Finding]]]:
    # local imports: each checker module imports this one for Finding
    from bdbnn_tpu.analysis.eventschema import check_event_schema
    from bdbnn_tpu.analysis.jitpure import check_jit_purity
    from bdbnn_tpu.analysis.lockcheck import check_lock_discipline
    from bdbnn_tpu.analysis.verdictcheck import check_verdict_coherence

    return {
        "lock-discipline": check_lock_discipline,
        "jit-purity": check_jit_purity,
        "event-schema": check_event_schema,
        "verdict-coherence": check_verdict_coherence,
    }


# derived from the registry, never hand-maintained: a checker added
# to _checkers() is automatically runnable from run_check's default
# selection and the CLI's --checker choices
CHECKER_IDS: Tuple[str, ...] = tuple(_checkers())


def run_check(
    root: str,
    *,
    checkers: Optional[Sequence[str]] = None,
    files: Optional[Sequence[str]] = None,
    baseline_path: Optional[str] = None,
) -> Dict[str, Any]:
    """Run the selected checkers over ``files`` (default: the
    discovered package set under ``root``) and fold in the baseline.
    Returns the deterministic report dict; ``verdict`` is ``"clean"``
    exactly when there are no unsuppressed findings (the CLI maps
    anything else to exit 3)."""
    registry = _checkers()
    selected = list(checkers) if checkers else list(CHECKER_IDS)
    unknown = [c for c in selected if c not in registry]
    if unknown:
        raise ValueError(
            f"unknown checker(s) {unknown}; known: {sorted(registry)}"
        )
    scan = list(files) if files is not None else discover_files(root)

    all_findings: List[Finding] = []
    for cid in selected:
        all_findings.extend(registry[cid](root, scan))
    all_findings.sort()

    if baseline_path is None:
        baseline_path = os.path.join(root, BASELINE_NAME)
    entries, problems = load_baseline(baseline_path)
    def entry_parts(record: str):
        """((file, checker, message), advisory line) — None key for a
        record too malformed to split."""
        parts = record.split(":", 3)
        if len(parts) == 4:
            advisory = int(parts[1]) if parts[1].isdigit() else 0
            return (parts[0], parts[2], parts[3]), advisory
        return None, 0

    # one entry consumes AT MOST ONE matching finding — the one whose
    # line sits closest to the entry's advisory line. Line churn above
    # a baselined site keeps matching (the line is advisory), but a
    # brand-NEW site with the same message stays OPEN: the baseline
    # excuses one understood occurrence, never a class of them.
    by_key: Dict[Tuple[str, str, str], List[Finding]] = {}
    for f in all_findings:
        by_key.setdefault(f.match_key, []).append(f)
    consumed: set = set()
    known_checkers = set(registry)
    for e in entries:
        key, advisory = entry_parts(e["record"])
        entry_checker = key[1] if key else ""
        if entry_checker == BASELINE_CHECKER:
            # hygiene findings bypass the suppression set by design —
            # an entry naming the baseline checker suppresses nothing
            # and would otherwise linger as inert dead weight
            problems.append(Finding(
                os.path.basename(baseline_path), e["line"],
                BASELINE_CHECKER,
                "baseline-hygiene findings cannot be suppressed "
                f"({e['record']})",
            ))
            continue
        if key is None or entry_checker not in known_checkers:
            # a typo'd / malformed record can never match a finding —
            # it must not become a permanently inert suppression
            problems.append(Finding(
                os.path.basename(baseline_path), e["line"],
                BASELINE_CHECKER,
                f"suppression names unknown checker id "
                f"{entry_checker!r} ({e['record']})",
            ))
            continue
        # an entry belonging to a KNOWN checker that did not run this
        # pass (--checker filter) is out of scope — neither live nor
        # stale
        if entry_checker not in selected:
            continue
        candidates = [
            f for f in by_key.get(key, ())
            if id(f) not in consumed
        ]
        if not candidates:
            problems.append(Finding(
                os.path.basename(baseline_path), e["line"],
                BASELINE_CHECKER,
                f"stale suppression (no current finding matches "
                f"{e['record']}; the line is advisory — file, checker "
                "and message must match)",
            ))
            continue
        best = min(candidates, key=lambda f: (abs(f.line - advisory),
                                              f.line))
        consumed.add(id(best))
    suppressed = [f for f in all_findings if id(f) in consumed]
    open_findings = sorted(
        [f for f in all_findings if id(f) not in consumed]
        + problems
    )

    return {
        "root": ".",  # deterministic: never an absolute path
        "checkers": selected,
        "files_scanned": len(scan),
        "findings": [f.to_dict() for f in open_findings],
        "suppressed": [f.to_dict() for f in suppressed],
        "counts": {
            "findings": len(open_findings),
            "suppressed": len(suppressed),
            "by_checker": {
                cid: sum(1 for f in open_findings if f.checker == cid)
                for cid in sorted(set(
                    [f.checker for f in open_findings] + selected
                ))
            },
        },
        "verdict": "clean" if not open_findings else "findings",
    }


def render_report(report: Dict[str, Any]) -> str:
    """The human-readable rendering of one :func:`run_check` report."""
    lines = [
        "== Static analysis "
        f"({', '.join(report['checkers'])}; "
        f"{report['files_scanned']} files)"
    ]
    for f in report["findings"]:
        lines.append(f"  {f['record']}")
    if report["suppressed"]:
        lines.append(
            f"  ({report['counts']['suppressed']} finding(s) suppressed "
            f"by {BASELINE_NAME})"
        )
    lines.append(
        f"verdict: {report['verdict'].upper()} "
        f"({report['counts']['findings']} open finding(s))"
    )
    return "\n".join(lines)


__all__ = [
    "BASELINE_CHECKER",
    "BASELINE_NAME",
    "CHECKER_IDS",
    "Finding",
    "discover_files",
    "load_baseline",
    "relpath",
    "render_report",
    "run_check",
]
