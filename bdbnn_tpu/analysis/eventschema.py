"""``event-schema``: the events channel's registry cannot drift.

The AST found-set scan born in ``tests/test_events_schema.py``,
promoted into the package (the test is now a thin wrapper over this
module) and extended. Three invariants:

1. **Registered kinds** — every ``<obj>.emit(...)`` / ``<obj>._emit(
   ...)`` call site passing a LITERAL string kind must pass one
   registered in ``KNOWN_KINDS``. The ``_emit`` attribute names the
   telemetry-relay wrappers (serve/pool.py, serve/canary.py) that
   forward ``(kind, **fields)`` to an injected ``on_event`` hook —
   their literal kinds must register exactly like direct emits, or the
   canary/shadow channel could drift unregistered.
2. **Documented kinds** — every ``KNOWN_KINDS`` entry must be
   documented as ``\\`\\`kind\\`\\``` in the registry module's
   docstring (obs/events.py's kind-by-kind table), so the registry and
   the docs cannot drift.
3. **Live kinds** — every ``KNOWN_KINDS`` entry must have at least one
   emit call site in the scan set: a kind nobody emits is dead
   registry weight (usually a renamed literal the registry kept).

The registry is located **statically**: the scanned file that assigns
``KNOWN_KINDS`` a set/frozenset literal is the registry module (the
real tree's ``bdbnn_tpu/obs/events.py``; a fixture snippet can carry
its own). No import of the analyzed code happens.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from bdbnn_tpu.analysis.core import Finding, relpath

CHECKER_ID = "event-schema"

_EMIT_ATTRS = ("emit", "_emit")


def emit_call_kinds(tree: ast.Module) -> List[Tuple[int, str]]:
    """(lineno, kind) for every emit/_emit call passing a literal
    string first argument. Non-literal first args are not the event
    channel (ProgressLog.emit's step index; **info-style relays are
    covered at the site that adds the literal kind)."""
    out = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _EMIT_ATTRS
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            out.append((node.lineno, node.args[0].value))
    return out


def find_registry(
    parsed: Dict[str, ast.Module]
) -> Optional[Tuple[str, Set[str], str, int]]:
    """Locate the KNOWN_KINDS registry in the scan set: returns
    ``(path, kinds, module_docstring, lineno)`` or None."""
    for path, tree in sorted(parsed.items()):
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign):
                continue
            if not any(
                isinstance(t, ast.Name) and t.id == "KNOWN_KINDS"
                for t in node.targets
            ):
                continue
            value = node.value
            if isinstance(value, ast.Call) and value.args:
                value = value.args[0]  # frozenset({...})
            try:
                kinds = ast.literal_eval(value)
            except (ValueError, SyntaxError):
                continue
            if isinstance(kinds, (set, frozenset, list, tuple)):
                return (
                    path,
                    {str(k) for k in kinds},
                    ast.get_docstring(tree) or "",
                    node.lineno,
                )
    return None


def scan_events(
    root: str, files: List[str]
) -> Tuple[List[Finding], Set[str]]:
    """The full scan: returns ``(findings, found_kinds)`` so the
    thin-wrapper test can also assert its historical found-set floor."""
    findings: List[Finding] = []
    parsed: Dict[str, ast.Module] = {}
    for path in files:
        try:
            with open(path) as f:
                src = f.read()
        except OSError:
            continue
        try:
            parsed[path] = ast.parse(src, filename=path)
        except SyntaxError:
            continue  # reported by lock-discipline
    registry = find_registry(parsed)
    found: Set[str] = set()
    if registry is None:
        return findings, found
    reg_path, kinds, doc, reg_lineno = registry
    for path, tree in sorted(parsed.items()):
        rel = relpath(path, root)
        for lineno, kind in emit_call_kinds(tree):
            found.add(kind)
            if kind not in kinds:
                findings.append(Finding(
                    rel, lineno, CHECKER_ID,
                    f"emit({kind!r}) uses a kind not registered in "
                    "KNOWN_KINDS",
                ))
    reg_rel = relpath(reg_path, root)
    for kind in sorted(kinds):
        if f"``{kind}``" not in doc:
            findings.append(Finding(
                reg_rel, reg_lineno, CHECKER_ID,
                f"registered kind {kind!r} is not documented "
                "(``kind``) in the registry module docstring",
            ))
        if kind not in found:
            findings.append(Finding(
                reg_rel, reg_lineno, CHECKER_ID,
                f"registered kind {kind!r} has no emit call site in "
                "the scan set (dead registry entry?)",
            ))
    return sorted(findings), found


def check_event_schema(root: str, files: List[str]) -> List[Finding]:
    findings, _found = scan_events(root, files)
    return findings


__all__ = [
    "CHECKER_ID",
    "check_event_schema",
    "emit_call_kinds",
    "find_registry",
    "scan_events",
]
