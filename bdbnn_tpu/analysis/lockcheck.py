"""``lock-discipline``: guarded attributes mutate only under their lock.

The static twin of the races PRs 8/9/11 caught by hand (the
restart-clobbers-SHIFTING clobber, the registry lock stolen
mid-critical-section, counter read-modify-writes off the lock). The
checker is **annotation-driven**: a threaded class declares which
attributes a lock guards, and the checker flags every write,
read-modify-write or container mutation of a guarded attribute that is
not enclosed in a ``with self.<lock>`` block.

Annotation spec (comments, so zero runtime cost — full table in
docs/design.md §15):

- ``self.shed = 0  # guarded-by: _lock`` — trailing an attribute
  assignment: that attribute is guarded by ``self._lock``.
- ``# guarded-by: _lock: shed, completed, batches`` — a standalone
  comment anywhere in the class body: bulk declaration.
- ``def _pop_highest(self):  # requires-lock: _lock`` — trailing a
  ``def``: the method REQUIRES its caller to hold the lock. Inside it
  the lock counts as held; every call site outside a ``with`` of that
  lock is flagged — the "escape via helper method" class of race.

Semantics the checker understands:

- ``self._cv = threading.Condition(self._lock)`` aliases the condition
  to its lock: holding either is holding the lock.
- ``__init__`` is exempt (construction is single-threaded; no worker
  exists yet).
- Cross-object accesses (``r.state = STOPPED`` from the pool over a
  Replica) are checked against every annotated class in the same
  file: the access must sit under ``with r.<lock>`` for a lock that
  guards that attribute.
- Nested functions and lambdas get a FRESH held-lock context: a
  closure defined inside a ``with`` block runs later, without it.
- Plain reads are deliberately NOT flagged: advisory reads
  (``r.state == READY`` in the dispatcher) are racy-by-design and
  documented at their sites; the damage class is lost updates and torn
  read-modify-writes, which all require a write.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from typing import Dict, List, Optional, Set, Tuple

from bdbnn_tpu.analysis.core import Finding, relpath

CHECKER_ID = "lock-discipline"

_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_]\w*)\s*(?::\s*(.+))?")
_REQUIRES_RE = re.compile(r"#\s*requires-lock:\s*([A-Za-z_]\w*)")

# container methods that mutate their receiver: calling one on a
# guarded attribute is a mutation of that attribute
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "discard", "remove", "pop", "popleft", "popitem", "clear", "update",
    "setdefault", "sort", "reverse", "rotate",
})

# free functions that mutate their FIRST argument in place
_MUTATING_FREE = frozenset({"heappush", "heappop", "heapify",
                            "heappushpop", "heapreplace"})


def _attr_of_line(code: str) -> Optional[str]:
    """The ``self.<attr>`` a trailing guarded-by comment annotates."""
    m = re.search(r"self\.([A-Za-z_]\w*)", code)
    return m.group(1) if m else None


class _ClassSpec:
    """One annotated class: {attr: lock}, {method: required lock},
    {condition alias: lock}."""

    def __init__(self, name: str):
        self.name = name
        self.guards: Dict[str, str] = {}
        self.requires: Dict[str, str] = {}
        self.aliases: Dict[str, str] = {}

    def canon(self, lock: str) -> str:
        return self.aliases.get(lock, lock)


def _comments(source: str) -> List[Tuple[int, int, str]]:
    """(lineno, col, text) for every REAL comment token — docstrings
    and string literals quoting an annotation example must not
    register guards, so the raw lines are never regex-scanned."""
    out = []
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out.append((tok.start[0], tok.start[1], tok.string))
    except (tokenize.TokenError, IndentationError):
        pass  # ast.parse succeeded, so this should not happen
    return out


def _collect_specs(
    tree: ast.Module, source: str, path: str
) -> Tuple[Dict[str, _ClassSpec], List[Finding]]:
    """Parse annotations + Condition aliases into per-class specs.
    An annotation that binds to NOTHING (trailing guarded-by with no
    ``self.<attr>`` on the line, any form outside a class body, a
    requires-lock comment off a def signature) is itself a finding —
    silence would mean an attribute the author believes protected is
    entirely unchecked."""
    problems: List[Finding] = []
    lines = source.splitlines()
    classes = [
        node for node in ast.walk(tree) if isinstance(node, ast.ClassDef)
    ]

    def owner_of(lineno: int) -> Optional[ast.ClassDef]:
        best = None
        for c in classes:
            if c.lineno <= lineno <= (c.end_lineno or c.lineno):
                if best is None or c.lineno > best.lineno:
                    best = c  # innermost
        return best

    specs: Dict[str, _ClassSpec] = {}

    def spec_for(cls: ast.ClassDef) -> _ClassSpec:
        return specs.setdefault(cls.name, _ClassSpec(cls.name))

    for lineno, col, text in _comments(source):
        m = _GUARDED_RE.search(text)
        if m:
            cls = owner_of(lineno)
            if cls is None:
                problems.append(Finding(
                    path, lineno, CHECKER_ID,
                    "guarded-by annotation outside any class body "
                    "binds to nothing",
                ))
            else:
                spec = spec_for(cls)
                lock, bulk = m.group(1), m.group(2)
                if bulk:
                    for attr in re.split(r"[,\s]+", bulk.strip()):
                        if attr:
                            spec.guards[attr] = lock
                else:
                    attr = _attr_of_line(lines[lineno - 1][:col])
                    if attr:
                        spec.guards[attr] = lock
                    else:
                        problems.append(Finding(
                            path, lineno, CHECKER_ID,
                            "trailing guarded-by annotation with no "
                            "'self.<attr>' on its line binds to "
                            "nothing (use the bulk form for "
                            "multi-line assignments)",
                        ))
        m = _REQUIRES_RE.search(text)
        if m:
            cls = owner_of(lineno)
            bound = False
            if cls is not None:
                # the comment must sit on a def's signature lines
                for node in ast.walk(cls):
                    if isinstance(
                        node, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ) and node.lineno <= lineno < node.body[0].lineno:
                        spec_for(cls).requires[node.name] = m.group(1)
                        bound = True
                        break
            if not bound:
                problems.append(Finding(
                    path, lineno, CHECKER_ID,
                    "requires-lock annotation not on a method's def "
                    "signature line binds to nothing",
                ))

    # Condition aliases: self.X = threading.Condition(self.Y)
    for cls in classes:
        if cls.name not in specs:
            continue
        spec = specs[cls.name]
        for node in ast.walk(cls):
            if not (isinstance(node, ast.Assign) and node.targets):
                continue
            t = node.targets[0]
            v = node.value
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
                and isinstance(v, ast.Call)
                and isinstance(v.func, ast.Attribute)
                and v.func.attr == "Condition"
                and v.args
                and isinstance(v.args[0], ast.Attribute)
                and isinstance(v.args[0].value, ast.Name)
                and v.args[0].value.id == "self"
            ):
                spec.aliases[t.attr] = v.args[0].attr
    return specs, problems


def _receiver(node: ast.expr) -> Optional[str]:
    """``self`` / a bare local name receiver of an attribute access."""
    if isinstance(node, ast.Name):
        return node.id
    return None


class _MethodChecker(ast.NodeVisitor):
    """Walk one method body tracking held (receiver, lock) pairs."""

    def __init__(
        self,
        *,
        path: str,
        cls: _ClassSpec,
        all_specs: Dict[str, _ClassSpec],
        method: ast.AST,
        held: Set[Tuple[str, str]],
        findings: List[Finding],
    ):
        self.path = path
        self.cls = cls
        self.all_specs = all_specs
        self.method = method
        self.held = set(held)
        self.findings = findings

    # -- lock context --------------------------------------------------

    def _lock_of_withitem(
        self, item: ast.withitem
    ) -> Optional[Tuple[str, str]]:
        ctx = item.context_expr
        # with self._lock: / with r._lock:  (also .acquire-style calls
        # are not with-items; Condition objects alias to their lock)
        if isinstance(ctx, ast.Attribute):
            recv = _receiver(ctx.value)
            if recv is not None:
                return recv, ctx.attr
        return None

    def visit_With(self, node: ast.With) -> None:
        added = []
        for item in node.items:
            got = self._lock_of_withitem(item)
            if got is not None:
                recv, lock = got
                spec = self.cls if recv == "self" else None
                names = {lock}
                if spec is not None:
                    names.add(spec.canon(lock))
                else:
                    for s in self.all_specs.values():
                        names.add(s.canon(lock))
                for n in names:
                    pair = (recv, n)
                    if pair not in self.held:
                        self.held.add(pair)
                        added.append(pair)
        for stmt in node.body:
            self.visit(stmt)
        for pair in added:
            self.held.discard(pair)

    # nested scopes run later, without the enclosing lock
    def _fresh_scope(self, node: ast.AST) -> None:
        sub = _MethodChecker(
            path=self.path, cls=self.cls, all_specs=self.all_specs,
            method=node, held=set(), findings=self.findings,
        )
        for child in ast.iter_child_nodes(node):
            sub.visit(child)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._fresh_scope(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._fresh_scope(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._fresh_scope(node)

    # -- guarded-access core -------------------------------------------

    def _guard_for(
        self, recv: str, attr: str
    ) -> Optional[List[Tuple[str, str]]]:
        """Acceptable (receiver, lock) pairs for this access, or None
        when the attribute is not guarded for this receiver."""
        if recv == "self":
            lock = self.cls.guards.get(attr)
            if lock is None:
                return None
            return [("self", self.cls.canon(lock))]
        pairs = []
        for spec in self.all_specs.values():
            lock = spec.guards.get(attr)
            if lock is not None:
                pairs.append((recv, spec.canon(lock)))
        return pairs or None

    def _check_access(
        self, node: ast.Attribute, what: str
    ) -> None:
        recv = _receiver(node.value)
        if recv is None:
            return
        pairs = self._guard_for(recv, node.attr)
        if pairs is None:
            return
        if any(p in self.held for p in pairs):
            return
        lock = pairs[0][1]
        self.findings.append(Finding(
            self.path, node.lineno, CHECKER_ID,
            f"{what} of guarded attribute {recv}.{node.attr} outside "
            f"'with {recv}.{lock}'",
        ))

    def _target_attr(self, t: ast.expr) -> Optional[ast.Attribute]:
        """The Attribute an assignment target mutates: ``x.a = ...``,
        ``x.a[k] = ...`` and ``x.a[k][j] = ...`` all mutate ``x.a``."""
        return self._mutated_attr(t)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            targets = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
            for tt in targets:
                attr = self._target_attr(tt)
                if attr is not None:
                    self._check_access(attr, "write")
        self.visit(node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        attr = self._target_attr(node.target)
        if attr is not None:
            self._check_access(attr, "write")
        if node.value is not None:
            self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        attr = self._target_attr(node.target)
        if attr is not None:
            self._check_access(attr, "read-modify-write")
        self.visit(node.value)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            attr = self._target_attr(t)
            if attr is not None:
                self._check_access(attr, "delete")

    def _mutated_attr(self, node: ast.expr) -> Optional[ast.Attribute]:
        """The guarded attribute a mutation reaches: ``self._q`` (a
        direct Attribute) or ``self._qs[p]`` / ``self._counts[t][k]``
        (any depth of Subscripts off the Attribute — mutating a nested
        element mutates the guarded container)."""
        while isinstance(node, ast.Subscript):
            node = node.value
        if isinstance(node, ast.Attribute):
            return node
        return None

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Attribute):
            # mutation through a container method: self._q.append(x)
            # and self._qs[p].append(x) (subscripted element of a
            # guarded container)
            if func.attr in _MUTATORS:
                attr = self._mutated_attr(func.value)
                if attr is not None:
                    self._check_access(attr, f"{func.attr}() mutation")
            # escape via a helper that requires the lock:
            # self._pop_highest() outside 'with self._lock'
            recv = _receiver(func.value)
            if recv is not None:
                self._check_requires(recv, func.attr, node.lineno)
        # mutation through a free function: heapq.heappush(self._tail[p],
        # ...) mutates its first argument in place
        fname = (
            func.attr if isinstance(func, ast.Attribute)
            else func.id if isinstance(func, ast.Name) else None
        )
        if fname in _MUTATING_FREE and node.args:
            attr = self._mutated_attr(node.args[0])
            if attr is not None:
                self._check_access(attr, f"{fname}() mutation")
        self.generic_visit(node)

    def _check_requires(
        self, recv: str, method: str, lineno: int
    ) -> None:
        """Like :meth:`_guard_for`: collect EVERY candidate lock a
        same-named method may require across the file's classes and
        accept any held one — first-spec-wins would false-positive a
        call holding the correct lock when two classes share a method
        name with different locks."""
        specs = (
            [self.cls] if recv == "self" else list(self.all_specs.values())
        )
        locks = [
            spec.canon(spec.requires[method])
            for spec in specs
            if method in spec.requires
        ]
        if not locks:
            return
        if any((recv, lock) in self.held for lock in locks):
            return
        self.findings.append(Finding(
            self.path, lineno, CHECKER_ID,
            f"call to {recv}.{method}() which requires "
            f"{locks[0]}, outside 'with {recv}.{locks[0]}'",
        ))


def _check_function(
    path: str,
    node: ast.AST,
    spec: _ClassSpec,
    all_specs: Dict[str, _ClassSpec],
    findings: List[Finding],
) -> None:
    held: Set[Tuple[str, str]] = set()
    req = spec.requires.get(getattr(node, "name", ""))
    if req is not None:
        held.add(("self", spec.canon(req)))
    checker = _MethodChecker(
        path=path, cls=spec, all_specs=all_specs, method=node,
        held=held, findings=findings,
    )
    for child in node.body:
        checker.visit(child)


def _check_class(
    path: str,
    cls_node: ast.ClassDef,
    spec: _ClassSpec,
    all_specs: Dict[str, _ClassSpec],
    findings: List[Finding],
) -> None:
    for node in cls_node.body:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name == "__init__":
            continue  # construction is single-threaded by contract
        _check_function(path, node, spec, all_specs, findings)


def check_lock_discipline(root: str, files: List[str]) -> List[Finding]:
    """Run the lock-discipline checker over every annotated class in
    ``files``. Files with no ``guarded-by`` annotations cost one regex
    scan and are skipped."""
    findings: List[Finding] = []
    for path in files:
        try:
            with open(path) as f:
                source = f.read()
        except OSError:
            continue
        # EVERY file is parsed, annotated or not: lock-discipline is
        # the one checker that reports unparseable files (the others
        # skip SyntaxError citing this), and a syntax error anywhere
        # would otherwise make the whole analyzer silently vacuous for
        # that file
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            findings.append(Finding(
                relpath(path, root), e.lineno or 0, CHECKER_ID,
                f"unparseable file: {e.msg}",
            ))
            continue
        if "guarded-by:" not in source and "requires-lock:" not in source:
            # fast path: no annotation marker of either kind anywhere
            continue
        rel = relpath(path, root)
        specs, problems = _collect_specs(tree, source, rel)
        findings.extend(problems)
        if not specs:
            continue
        # EVERY class and module-level function in an annotated file is
        # walked: cross-object accesses (a pool mutating r.restarts)
        # live outside the class that declared the guard
        empty = _ClassSpec("")
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                _check_class(
                    rel, node, specs.get(node.name, empty), specs,
                    findings,
                )
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _check_function(rel, node, empty, specs, findings)
    return sorted(findings)


__all__ = ["CHECKER_ID", "check_lock_discipline"]
