from bdbnn_tpu.losses import kd, kurtosis
from bdbnn_tpu.losses.kd import (
    distribution_loss,
    layer_weight_kl,
    layer_weight_kl_softened,
    loss_kd,
    match_conv_pairs,
    softmax_cross_entropy,
)

# NB: the bare kurtosis() function is deliberately NOT re-exported here —
# it would shadow the `bdbnn_tpu.losses.kurtosis` submodule attribute.
# Use `kurtosis.kurtosis` or import it from the submodule directly.
from bdbnn_tpu.losses.kurtosis import (
    kurtosis_loss,
    kurtosis_regularization,
    l2_regularization,
    resolve_targets,
    weight_to_pm1_regularization,
)

__all__ = [
    "kd",
    "kurtosis",
    "kurtosis_loss",
    "kurtosis_regularization",
    "l2_regularization",
    "resolve_targets",
    "weight_to_pm1_regularization",
    "distribution_loss",
    "layer_weight_kl",
    "layer_weight_kl_softened",
    "loss_kd",
    "match_conv_pairs",
    "softmax_cross_entropy",
]
