"""Kurtosis ("bimodal") weight regularization — the core BD-BNN idea.

Pure jit-fusable functions over weight pytrees, replacing the
reference's per-batch Python object reconstruction (reference
``train.py:461-484``; ``kurtosis.py:5-39``) which is free here at trace
time.

Numerics parity notes (SURVEY.md Appendix B #10, #12):

- the reference computes std with **Bessel's correction** (torch.std,
  n-1 denominator, ``kurtosis.py:25``) — ``jnp.std`` defaults to ddof=0,
  so this module uses ddof=1 explicitly;
- the reference's per-tensor ``k_mode`` avg/max/sum are degenerate
  (applied to an already-scalar kurtosis, ``kurtosis.py:31-39``); only
  the cross-layer reduction (``train.py:505-511``) is meaningful, and
  that is what ``kurtosis_regularization`` implements.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

Array = jax.Array

# Hard-coded per-layer target tables for ``--diffkurt`` (19 binarized convs
# of the ResNet-18-shaped flagship). Reference: train.py:467-475 (plain
# loop: imagenet / cifar) and train.py:586-589 (teacher-student loop).
DIFFKURT_TARGETS_IMAGENET: tuple = (
    1.8, 1.4, 1.4, 1.4,
    1.4, 1.2, 1.4, 1.2, 1.2,
    1.4, 1.4, 1.4, 1.2, 1.2,
    1.2, 1.2, 1.4, 1.0, 1.0,
)
DIFFKURT_TARGETS_CIFAR: tuple = (
    1.4, 1.4, 1.4, 1.4,
    1.4, 1.4, 1.4, 1.4, 1.4,
    1.4, 1.4, 1.4, 1.4, 1.4,
    1.8, 1.8, 1.8, 1.8, 2.2,
)
DIFFKURT_TARGETS_TS: tuple = (
    1.8, 1.8, 1.8, 1.8,
    1.8, 1.8, 1.4, 1.8, 1.8,
    1.8, 1.4, 1.4, 1.4, 1.4,
    1.8, 1.2, 1.4, 1.2, 1.2,
)


def kurtosis(w: Array) -> Array:
    """kurt(W) = mean(((W - mean) / std)^4) with Bessel-corrected std.

    Two-pass CENTERED moments: pass 1 is the mean (one read), pass 2
    computes Σd² and Σd⁴ of d = w − μ in one fused loop (second read),
    then

        s²  = Σd²/(n−1)                    (Bessel, ddof=1)
        kurt = (Σd⁴/n) / s⁴

    The naive mean/std/z⁴ chain cost 3–4 reads of each latent tensor
    and dominated device step time (32% "convert_reduce_fusion",
    profiles/r04/PROFILE_r04.json; VERDICT r4 next-round #2). A pure
    single-pass raw-moment form (Σw..Σw⁴) would be one read cheaper
    still, but catastrophically cancels once |μ|/σ ≳ 40 in f32
    (measured: kurt −131 vs true 3.05 at μ=−8, σ=0.05) — the centered
    form is exact for any offset and keeps the fused-single-reduction
    structure where it matters (tests/test_kurtosis.py pins both the
    torch oracle and the offset robustness).
    """
    w = w.reshape(-1).astype(jnp.float32)
    n = w.size
    d = w - jnp.mean(w)
    d2 = d * d
    s2 = jnp.sum(d2)
    s4 = jnp.sum(d2 * d2)
    var = s2 / (n - 1)
    return (s4 / n) / (var * var)


def kurtosis_loss(w: Array, target) -> Array:
    """(kurt(W) - target)^2 for a single weight tensor."""
    return (kurtosis(w) - jnp.asarray(target, jnp.float32)) ** 2


def kurtosis_regularization(
    weights: Sequence[Array],
    targets: Sequence[float],
    mode: str = "avg",
) -> Array:
    """Cross-layer reduction of per-layer kurtosis losses.

    ``mode`` ∈ {sum, avg, max} ↔ ``--kurtosis-mode`` reduced exactly as
    reference ``train.py:505-511``.
    """
    if len(weights) != len(targets):
        raise ValueError(
            f"{len(weights)} weight tensors but {len(targets)} targets"
        )
    # "kurtosis_loss" named scope: the regularizer's ops (and their
    # gradients, which inherit the scope path) attribute as one device
    # trace category (obs/trace.py DEVICE_SPANS)
    with jax.named_scope("kurtosis_loss"):
        losses = jnp.stack(
            [kurtosis_loss(w, t) for w, t in zip(weights, targets)]
        )
        if mode == "sum":
            return jnp.sum(losses)
        if mode == "avg":
            return jnp.mean(losses)
        if mode == "max":
            return jnp.max(losses)
    raise ValueError(f"unknown kurtosis mode: {mode!r}")


def l2_regularization(weights: Sequence[Array]) -> Array:
    """Sum of squared weights (reference ``RidgeRegularization``,
    ``kurtosis.py:42-53``; built but never added to the loss there —
    here it is wired behind ``w_l2_reg``, fixing Appendix B #2)."""
    return sum(jnp.sum(w**2) for w in weights)


def weight_to_pm1_regularization(weights: Sequence[Array]) -> Array:
    """‖|W| − 1‖₂ summed over tensors: pulls latent weights toward ±1
    (reference ``WeightRegularization``, ``kurtosis.py:56-70``)."""
    return sum(
        jnp.sqrt(jnp.sum((jnp.abs(w) - 1.0) ** 2)) for w in weights
    )


def resolve_targets(
    num_layers: int,
    *,
    scalar_target: float = 1.8,
    diffkurt: bool = False,
    dataset: str = "cifar10",
    teacher_student: bool = False,
) -> tuple:
    """Per-layer target vector replicating reference target selection
    (``train.py:465-477`` and ``train.py:585-591``)."""
    if not diffkurt:
        return (float(scalar_target),) * num_layers
    if teacher_student:
        table = DIFFKURT_TARGETS_TS
    elif dataset == "imagenet":
        table = DIFFKURT_TARGETS_IMAGENET
    else:
        table = DIFFKURT_TARGETS_CIFAR
    if num_layers != len(table):
        raise ValueError(
            f"--diffkurt tables are defined for {len(table)} hooked layers; "
            f"model hooks {num_layers}. Pass explicit targets instead."
        )
    return table
