"""Teacher–student knowledge-distillation losses.

Functional equivalents of the reference's ``utils/KD_loss.py``. The
reference rescans all teacher×student module pairs every batch
(O(L²) ``named_modules`` loops, ``utils/KD_loss.py:59-66``); here pair
matching happens once at init (:func:`match_conv_pairs`) and the losses
are pure functions of weight lists, fused into the jitted step.

Numerics parity (deliberate, see SURVEY.md Appendix B #11): the layer
KL is torch's ``KLDivLoss(log_target=True)`` applied to **raw weights**
with the default 'mean' (elementwise-mean) reduction — mathematically
loose (weights are not log-probabilities) but it is the shipped
behavior: loss = mean(exp(w_t) * (w_t - w_s)).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array


def softmax_cross_entropy(logits: Array, labels: Array) -> Array:
    """Mean CE over the batch with integer labels (↔ nn.CrossEntropyLoss,
    reference ``train.py:318``)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    return jnp.mean(nll)


def distribution_loss(stud_logits: Array, teacher_logits: Array) -> Array:
    """Logit distillation: batch-mean of −softmax(teacher)·log_softmax(stud)
    (reference ``DistributionLoss``, ``utils/KD_loss.py:10-43``).

    The teacher side is stop_gradient'ed, replacing the reference's
    runtime ``requires_grad`` assertion (``utils/KD_loss.py:22-23``).
    """
    with jax.named_scope("kd_logit_loss"):
        teacher_logits = jax.lax.stop_gradient(teacher_logits)
        logp_s = jax.nn.log_softmax(stud_logits, axis=1)
        p_t = jax.nn.softmax(teacher_logits, axis=1)
        return jnp.mean(-jnp.sum(p_t * logp_s, axis=1))


def _kl_div_log_target_mean(input_: Array, log_target: Array) -> Array:
    """torch.nn.KLDivLoss(log_target=True, reduction='mean'):
    elementwise mean of exp(target) * (target - input)."""
    return jnp.mean(jnp.exp(log_target) * (log_target - input_))


def layer_weight_kl(
    stud_weights: Sequence[Array],
    teacher_weights: Sequence[Array],
) -> Array:
    """Per-layer weight "KL" summed over matched conv pairs (reference
    ``DistributionLoss_layer``, ``utils/KD_loss.py:46-67``): for each
    pair, KLDivLoss(log_target=True) on the raw weight tensors, with
    student as input and teacher as (log-)target."""
    with jax.named_scope("kd_weight_loss"):
        total = jnp.float32(0.0)
        for ws, wt in zip(stud_weights, teacher_weights, strict=True):
            wt = jax.lax.stop_gradient(wt)
            total = total + _kl_div_log_target_mean(ws, wt)
        return total


def layer_weight_kl_softened(
    stud_weights: Sequence[Array],
    teacher_weights: Sequence[Array],
    temperature: float = 6.0,
) -> Array:
    """Temperature-softened per-layer weight KL over axis 1 (reference
    ``DistributionLoss_layer_cifar_act``, ``utils/KD_loss.py:69-87``):
    Σ_pairs elementwise-mean KL(softmax(w_t/T, axis=1) ‖ softmax(w_s/T,
    axis=1)) · T²."""
    T = temperature
    total = jnp.float32(0.0)
    for ws, wt in zip(stud_weights, teacher_weights, strict=True):
        wt = jax.lax.stop_gradient(wt)
        logp_s = jax.nn.log_softmax(ws / T, axis=1)
        p_t = jax.nn.softmax(wt / T, axis=1)
        # torch F.kl_div default 'mean' = elementwise mean of
        # p_t * log p_t - p_t * logp_s, with the 0·log 0 = 0 convention
        # (xlogy) so an underflowed teacher probability yields 0, not NaN.
        kl = jnp.mean(jax.scipy.special.xlogy(p_t, p_t) - p_t * logp_s)
        total = total + kl * (T * T)
    return total


def loss_kd(stud_logits: Array, teacher_logits: Array, temperature: float = 6.0) -> Array:
    """Hinton logit KD with T² scaling and torch's elementwise-mean
    reduction (reference ``loss_kd``, ``utils/KD_loss.py:90-100``)."""
    T = temperature
    teacher_logits = jax.lax.stop_gradient(teacher_logits)
    logp_s = jax.nn.log_softmax(stud_logits / T, axis=1)
    p_t = jax.nn.softmax(teacher_logits / T, axis=1)
    # elementwise mean; xlogy keeps 0·log 0 = 0 for saturated teacher rows
    kl = jnp.mean(jax.scipy.special.xlogy(p_t, p_t) - p_t * logp_s)
    return kl * (T * T)


def match_conv_pairs(
    stud_paths: Sequence[str],
    teacher_paths: Sequence[str],
    *,
    skip_stem: bool = True,
    skip_downsample: bool = True,
) -> List[Tuple[str, str]]:
    """One-time pairing of student/teacher conv weights for the layer KL.

    Replaces the reference's per-batch O(L²) name-matched scan
    (``utils/KD_loss.py:59-66``): name-equal conv pairs, skipping the
    stem conv ('module.conv1' there; index 0 here) and any 'downsample'
    path. Paths are the frameworks' ordered conv weight names
    (see ``bdbnn_tpu.models.registry.conv_weight_paths``).

    Parity note: the defaults reproduce ``DistributionLoss_layer`` (the
    TS-loop loss). The softened CIFAR variant
    (``DistributionLoss_layer_cifar_act``, ``utils/KD_loss.py:81-86``)
    skips only the stem and DOES include downsample convs — pair for it
    with ``skip_downsample=False``.
    """
    teacher_set = set(teacher_paths)
    pairs = []
    for i, p in enumerate(stud_paths):
        if skip_stem and i == 0:
            continue
        if skip_downsample and "downsample" in p:
            continue
        if p in teacher_set:
            pairs.append((p, p))
    return pairs
