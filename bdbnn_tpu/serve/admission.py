"""Per-tenant admission control: token-bucket quotas + shed taxonomy.

The micro-batcher's queue bound protects the SERVER (bounded memory,
explicit shedding under aggregate overload) but says nothing about
WHO gets the capacity: one tenant replaying a firehose starves every
other tenant long before the aggregate bound trips. This module adds
the per-tenant layer the HTTP front end (serve/http.py) consults
BEFORE a request may touch the batcher:

- :class:`TokenBucket` — the classic rate limiter: ``burst`` tokens of
  headroom refilled at ``rate`` tokens/second; one token per admitted
  request. The clock is injectable so tests are deterministic.
- :class:`AdmissionController` — one bucket per tenant (created
  lazily from the default quota; explicit per-tenant overrides), a
  latched drain flag, and per-tenant accounting. ``admit(tenant)``
  returns one of three decisions the front end maps onto distinct
  status codes:

  ============  ======  ====================================================
  decision      HTTP    meaning
  ============  ======  ====================================================
  ``admit``     —       hand the request to the batcher
  ``over_quota``  429   THIS tenant exhausted its own budget (retry later;
                        other tenants are unaffected)
  ``draining``  503     the SERVER is going away (SIGTERM latched) —
                        retry against another replica
  ============  ======  ====================================================

  Queue-full sheds from the batcher are a third, distinct cause the
  front end also maps to 503 (server overload, not tenant fault) and
  records here per tenant via :meth:`record_shed` — so the SLO
  verdict can show exactly which tenants lost what to which cause.

Stdlib-only, no locks beyond one mutex: decisions are a dict lookup +
float math, cheap enough for the request path.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

ADMIT = "admit"
OVER_QUOTA = "over_quota"
DRAINING = "draining"

DEFAULT_TENANT = "anon"


class TokenBucket:
    """``burst`` tokens of headroom, refilled at ``rate``/s, one token
    per :meth:`try_take`. ``rate=0`` means a fixed budget of ``burst``
    requests and no refill (useful in tests and hard caps)."""

    def __init__(
        self,
        rate: float,
        burst: float,
        *,
        clock: Callable[[], float] = time.monotonic,
    ):
        if rate < 0 or burst <= 0:
            raise ValueError(
                f"need rate >= 0 and burst > 0, got rate={rate} burst={burst}"
            )
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self._clock = clock
        self._t_last = clock()

    def try_take(self, n: float = 1.0) -> bool:
        now = self._clock()
        self.tokens = min(
            self.burst, self.tokens + (now - self._t_last) * self.rate
        )
        self._t_last = now
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False

    def headroom(self) -> float:
        """Current fill fraction (0..1) WITHOUT taking a token: the
        refresh math of :meth:`try_take` applied read-only, so the
        capacity plane (obs/capacity.py) can sample quota headroom
        between requests without charging anyone."""
        now = self._clock()
        return (
            min(self.burst, self.tokens + (now - self._t_last) * self.rate)
            / self.burst
        )


def parse_quota(spec: str) -> Tuple[float, float]:
    """``"RATE"`` or ``"RATE:BURST"`` -> (rate, burst); burst defaults
    to max(rate, 1) so a bare rate behaves like a 1-second window."""
    rate_s, _, burst_s = str(spec).partition(":")
    rate = float(rate_s)
    burst = float(burst_s) if burst_s else max(rate, 1.0)
    return rate, burst


def parse_tenant_quotas(
    specs: Iterable[str],
) -> Dict[str, Tuple[float, float]]:
    """Repeatable CLI form ``TENANT=RATE[:BURST]`` -> {tenant: (rate,
    burst)}; malformed specs fail at config time, not mid-request."""
    out: Dict[str, Tuple[float, float]] = {}
    for spec in specs:
        tenant, sep, quota = str(spec).partition("=")
        if not sep or not tenant:
            raise ValueError(
                f"tenant quota must be TENANT=RATE[:BURST], got {spec!r}"
            )
        out[tenant] = parse_quota(quota)
    return out


class AdmissionController:
    """Per-tenant token buckets behind one latched drain flag.

    ``quotas`` maps tenant -> (rate, burst) overrides; unknown tenants
    lazily get a bucket at the default quota (every tenant is limited,
    not just the named ones). ``clock`` is injected into every bucket,
    so a test can step time deterministically.
    """

    def __init__(
        self,
        *,
        default_rate: float = 100.0,
        default_burst: float = 100.0,
        quotas: Optional[Dict[str, Tuple[float, float]]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.default_rate = float(default_rate)
        self.default_burst = float(default_burst)
        self._quotas = dict(quotas or {})
        for tenant, (rate, burst) in self._quotas.items():
            if rate < 0 or burst <= 0:
                raise ValueError(
                    f"tenant {tenant!r}: need rate >= 0 and burst > 0, "
                    f"got {rate}:{burst}"
                )
        self._clock = clock
        self._buckets: Dict[str, TokenBucket] = {}  # guarded-by: _lock
        self._draining = threading.Event()
        self._lock = threading.Lock()
        # per-tenant accounting: every decision and every downstream
        # disposition the front end reports back lands here, so the
        # verdict's per-tenant table comes from ONE place
        self._counts: Dict[str, Dict[str, int]] = {}  # guarded-by: _lock

    def _tenant_counts(self, tenant: str) -> Dict[str, int]:  # requires-lock: _lock
        return self._counts.setdefault(
            tenant,
            {"admitted": 0, "over_quota": 0, "shed": 0, "completed": 0,
             "failed": 0, "rejected": 0},
        )

    def quota_for(self, tenant: str) -> Tuple[float, float]:
        return self._quotas.get(
            tenant, (self.default_rate, self.default_burst)
        )

    # -- request path --------------------------------------------------

    def admit(self, tenant: str, trace=None) -> str:
        """One decision per request: ``draining`` | ``over_quota`` |
        ``admit`` (in that precedence — a draining server must not
        charge tenants tokens for requests it will not serve).
        ``trace`` (optional, obs/rtrace.py) gets its ``admit`` span
        stamped here — the quota decision's cost belongs to the layer
        that owns it, the same owning-site rule as the training side's
        ``jax.named_scope`` spans."""
        with self._lock:
            if self._draining.is_set():
                decision = DRAINING
            else:
                bucket = self._buckets.get(tenant)
                if bucket is None:
                    rate, burst = self.quota_for(tenant)
                    bucket = TokenBucket(rate, burst, clock=self._clock)
                    self._buckets[tenant] = bucket
                counts = self._tenant_counts(tenant)
                if not bucket.try_take():
                    counts["over_quota"] += 1
                    decision = OVER_QUOTA
                else:
                    counts["admitted"] += 1
                    decision = ADMIT
        if trace is not None:
            trace.stamp("admit")
        return decision

    def record_shed(self, tenant: str) -> None:
        """An ADMITTED request the batcher then shed (queue full or a
        racing drain) — server overload charged to the server, but
        visible per tenant."""
        with self._lock:
            self._tenant_counts(tenant)["shed"] += 1

    def record_completed(self, tenant: str) -> None:
        with self._lock:
            self._tenant_counts(tenant)["completed"] += 1

    def record_failed(self, tenant: str) -> None:
        """Accepted but the engine errored — NOT shedding (an operator
        must never read a broken artifact as overload)."""
        with self._lock:
            self._tenant_counts(tenant)["failed"] += 1

    def record_rejected(self, tenant: str) -> None:
        """Admitted but the BODY was malformed (400) — the tenant's
        own bad request, distinct from shedding and from engine
        failure in the ledger."""
        with self._lock:
            self._tenant_counts(tenant)["rejected"] += 1

    def token_headroom(self) -> Optional[float]:
        """Mean quota-headroom fraction across the tenants seen so far
        (1.0 = every bucket full, 0.0 = every tenant exhausted) — the
        admission gauge the capacity plane's UtilizationWindows
        samples. None before any tenant has been admitted: no buckets
        is "nothing to measure", not "full headroom"."""
        with self._lock:
            fracs = [b.headroom() for b in self._buckets.values()]
        if not fracs:
            return None
        return round(sum(fracs) / len(fracs), 4)

    # -- lifecycle / reporting -----------------------------------------

    def drain(self) -> None:
        """Latch: every subsequent admit() returns ``draining``."""
        self._draining.set()

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            per_tenant = {}
            for tenant in sorted(self._counts):
                c = dict(self._counts[tenant])
                seen = (
                    c["admitted"] + c["over_quota"]
                )
                c["shed_rate"] = round(
                    (c["over_quota"] + c["shed"]) / seen, 6
                ) if seen else 0.0
                rate, burst = self.quota_for(tenant)
                c["quota_rate"] = rate
                c["quota_burst"] = burst
                per_tenant[tenant] = c
            return {
                "draining": self._draining.is_set(),
                "default_rate": self.default_rate,
                "default_burst": self.default_burst,
                "tenants": per_tenant,
            }


__all__ = [
    "ADMIT",
    "DEFAULT_TENANT",
    "DRAINING",
    "OVER_QUOTA",
    "AdmissionController",
    "TokenBucket",
    "parse_quota",
    "parse_tenant_quotas",
]
